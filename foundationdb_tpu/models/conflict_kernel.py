"""The jitted MVCC conflict-resolution kernel.

This is the TPU-native replacement for the reference resolver's skiplist
engine (fdbserver/SkipList.cpp + ConflictSet.h: ConflictBatch::addTransaction /
detectConflicts / combineWriteConflictRanges). Same observable semantics,
completely different shape:

- The write history is a *step function over the keyspace*: sorted boundary
  keys ``K[C, W]`` with per-segment last-write version ``V[C]``. This is
  exact, not approximate, because the reference hands out ONE commit version
  per resolve batch (masterserver → CommitProxy getVersion), so every write
  of a batch lands at the same version.
- A batch resolve is one ``jit``ted call of dense ops: binary-search every
  read endpoint into K, sparse-table range-max for "newest write version
  overlapping this read", a rank-space pairwise overlap matrix for intra-batch
  read-vs-earlier-write conflicts, and a wave-relaxation loop (matvec rounds)
  that reproduces the reference's sequential acceptance order without a
  sequential scan.
- Accepted writes are painted into the step function with a sort-merge +
  coverage prefix-sum, then boundaries made redundant (equal adjacent
  versions, expired segments) are compacted out — the analogue of the
  reference skiplist's insert + version-window GC.

Everything is static-shape; hosts pad batches (see conflict_set.TPUConflictSet).
Versions on device are int32, relative to a host-held base (the MVCC window
is ~5-7M versions, far inside int32; the host rebases periodically).
"""

from __future__ import annotations

import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from foundationdb_tpu.core.keypack import INT32_MAX
from foundationdb_tpu.core.types import (
    WAVE_LEVEL_CYCLE as LEVEL_CYCLE,
    WAVE_LEVEL_NONE as LEVEL_NONE,
    env_choice as _env_choice,
)
from foundationdb_tpu.ops.bitset import (
    or_matvec_u32,
    pack_bits_u32,
    unpack_bits_u32,
)
from foundationdb_tpu.ops.lex import (
    lex_lt,
    lex_max,
    lex_min,
    searchsorted_words,
    searchsorted_words_2sided_fp,
    searchsorted_words_fp,
    sort_keys_with_payload,
    sort_ranks_with_payload,
)
from foundationdb_tpu.ops.rmq import (
    block_table,
    range_max,
    range_max_blocked,
    sparse_table,
)

NEG_VERSION = -(2**31) + 1


# History RMQ implementation: "sparse" (default) | "blocked". Read once at
# import — flipping it mid-process would silently split jit caches.
_RMQ_DESIGN = _env_choice("FDB_TPU_RMQ", "sparse", ("sparse", "blocked"))

# Within-block acceptance design: "wave" (default — data-dependent matvec
# relaxation rounds) | "seq" (a fixed G-step sequential fori_loop over the
# block tile). The wave wins when conflict chains are shallow (few rounds,
# each an MXU matvec); mako-shaped 95%-conflict Zipf batches drive deep
# chains where the wave's round count approaches G anyway with two [G, G]
# matvecs per round — there the bounded trivial-step scan may win
# (VERDICT r3 item 4). Same import-once rule as the RMQ flag; the
# heal-window auto-bench ranks both at full-kernel level.
_ACCEPT_DESIGN = _env_choice("FDB_TPU_ACCEPT", "wave", ("wave", "seq"))

# History design: "window" (default — two-level base+delta: the base
# sparse table is built once per merge epoch, per-batch work touches only
# the small delta) | "batch" (r4 behavior: one flat step function whose
# sparse table is rebuilt EVERY batch — the O(C·log C)/batch hot-path
# cost VERDICT r4 item 2 ordered out). Import-once rule as above; the
# heal-window auto-bench ranks both (BENCH_r05_batchhist A/B).
_HIST_DESIGN = _env_choice("FDB_TPU_HISTORY", "window", ("window", "batch"))

# Packed-kernel design: "1" (default) | "0" (the r5 unpacked kernel, kept
# as the A/B baseline — scripts/kernel_ab.sh). Three stacked HBM-diet
# reductions, byte-identical verdicts (oracle-tested):
#   1. rank-space history probes — the host packer dedups+sorts the
#      batch's endpoint keys ONCE per dispatch (PackedBatch.dict_keys);
#      the [C, W] history is probed once per UNIQUE key with a first-word
#      fingerprint fast path (ops/lex.searchsorted_words_fp), so the
#      common probe step touches 4 bytes instead of 4·W, and the device
#      endpoint-rank sort disappears entirely (ranks arrive precomputed).
#   2. rank-carried paint — the paint pass sorts int32 ranks (1 word)
#      instead of [n2, W] keys and gathers boundary keys back from the
#      dictionary (the step-function analogue of Redwood's page prefix
#      compression: the shared key bytes live once, in the dictionary).
#   3. bit-packed conflict masks — the [G, B] overlap rows, the [G, G]
#      wave tiles, and the per-txn loser-range report become uint32
#      bitsets (ops/bitset): 8x fewer bytes than bool, 16x fewer than
#      the bf16 MXU tiles, on the acceptance loop's hottest operands.
# Same import-once rule as the flags above.
_PACKED = _env_choice("FDB_TPU_PACKED", "1", ("0", "1")) != "0"

# Wave-commit mode: "0" (default — sequential-order acceptance, conflicts
# abort) | "1" (reorder-don't-abort: the same conflict graph schedules
# txns into dependency-ordered commit waves; only true cycles abort —
# see _wave_commit_accept). Selects the ENGINE DEFAULT only: both modes'
# entry points are separate jitted programs, so hosts can construct
# engines of either mode in one process (TPUConflictSet(wave_commit=...)).
_WAVE_COMMIT = _env_choice("FDB_TPU_WAVE_COMMIT", "0", ("0", "1")) == "1"

# Device-resident dictionary mode: "1" (default) | "0" (the per-dispatch
# repack baseline — scripts/resident_ab.sh A/Bs the two). Under resident
# mode the endpoint-key dictionary AND the MVCC history PERSIST in device
# memory across dispatches: the host ships only the DELTA of
# never-before-seen endpoint keys per dispatch (merged on-device by
# _dict_insert, with a rank-rebase that shifts existing history ranks
# past the inserted positions), and the history itself lives in RANK
# SPACE — width-1 int32 rank rows instead of [C, W] key rows — so every
# history probe, paint sort, and merge streams 1/W of the key bytes and
# the full dictionary never crosses PCIe after the first repack.
# Requires the packed kernel (rank-space batches); under FDB_TPU_PACKED=0
# the flag is inert. Same import-once rule as the flags above.
_RESIDENT = (_env_choice("FDB_TPU_RESIDENT", "1", ("0", "1")) == "1") and _PACKED

# Speculative pipelined resolve: "0" (default — windows resolve strictly
# in order, the A/B baseline) | "1" (window N+1 dispatches against window
# N's PENDING write sets: N's accepted-so-far writes are painted as if
# committed while N's verdicts are still in flight / unconfirmed by the
# upper layer; a host-side reconcile ring confirms or repairs when the
# verdicts land — see conflict_set.TPUConflictSet.spec_dispatch_window).
# Requires the packed kernel (the dependency probe runs over the batch
# dictionary); inert under FDB_TPU_PACKED=0, mirroring _RESIDENT's
# gating. Same import-once rule as the flags above.
_SPEC_RESOLVE = (
    _env_choice("FDB_TPU_SPEC_RESOLVE", "0", ("0", "1")) == "1"
) and _PACKED

# Verdict encoding (core.types.Verdict values, as device int8).
V_COMMITTED = 0
V_CONFLICT = 1
V_TOO_OLD = 2


class ConflictState(NamedTuple):
    """Device-resident write history (the step function)."""

    keys: jax.Array  # int32 [C, W] sorted; keys[0] = packed b""; tail = +inf
    versions: jax.Array  # int32 [C]; versions[i] covers [keys[i], keys[i+1]); tail NEG
    n_used: jax.Array  # int32 scalar — live boundary count
    oldest: jax.Array  # int32 scalar — oldest resolvable (relative) version
    overflow: jax.Array  # bool scalar — capacity exceeded; host must react


class BatchTensors(NamedTuple):
    """One padded resolver batch (host-packed, see conflict_set.BatchPacker)."""

    read_begin: jax.Array  # int32 [B, R, W]
    read_end: jax.Array  # int32 [B, R, W]
    read_mask: jax.Array  # bool [B, R]
    write_begin: jax.Array  # int32 [B, Q, W]
    write_end: jax.Array  # int32 [B, Q, W]
    write_mask: jax.Array  # bool [B, Q]
    read_version: jax.Array  # int32 [B] (relative)
    txn_mask: jax.Array  # bool [B]


class PackedBatch(NamedTuple):
    """One padded resolver batch in RANK SPACE (FDB_TPU_PACKED=1).

    The host packer dedups+sorts all of the batch's endpoint keys once per
    dispatch (conflict_set.TPUConflictSet._pack_dict): ``dict_keys`` holds
    the sorted unique keys padded with +inf rows (the LAST row is always
    +inf — paint parks masked slots there), and every range endpoint is an
    int32 rank into it. Ranks are order-isomorphic to byte order with
    identical tie structure (equal keys share a rank), so emptiness and
    overlap tests are scalar int32 compares, the history is probed once
    per unique key instead of once per endpoint slot, and the paint pass
    sorts 1-word ranks instead of W-word keys."""

    dict_keys: jax.Array  # int32 [N + 1, W] sorted unique, +inf padded
    read_begin: jax.Array  # int32 [B, R] ranks into dict_keys
    read_end: jax.Array  # int32 [B, R]
    read_mask: jax.Array  # bool [B, R]
    write_begin: jax.Array  # int32 [B, Q]
    write_end: jax.Array  # int32 [B, Q]
    write_mask: jax.Array  # bool [B, Q]
    read_version: jax.Array  # int32 [B] (relative)
    txn_mask: jax.Array  # bool [B]


def init_state(capacity: int, width: int, min_key) -> ConflictState:
    """min_key: the codec's packed b"" (KeyCodec.min_key) — boundary 0."""
    keys = jnp.full((capacity, width), INT32_MAX, dtype=jnp.int32)
    keys = keys.at[0].set(jnp.asarray(min_key, dtype=jnp.int32))
    versions = jnp.full((capacity,), NEG_VERSION, dtype=jnp.int32)
    return ConflictState(
        keys=keys,
        versions=versions,
        n_used=jnp.int32(1),
        oldest=jnp.int32(0),
        overflow=jnp.zeros((), jnp.bool_),
    )


# ---------------------------------------------------------------------------
# Phase 1: history conflicts (reads vs committed writes of earlier batches)
# ---------------------------------------------------------------------------


def _history_conflict_ranges(
    state: ConflictState, batch: BatchTensors
) -> jax.Array:
    """bool [B, R]: read range slot overlaps a historical write newer than
    rv — the per-range form the conflicting-keys report path needs (which
    read ranges LOST, reference: conflictingKRIndices)."""
    b, r, w = batch.read_begin.shape
    rb = batch.read_begin.reshape(b * r, w)
    re_ = batch.read_end.reshape(b * r, w)
    # Segments [lo, hi) intersect [rb, re): lo = segment containing rb,
    # hi = first segment starting at/after re.
    lo = searchsorted_words(state.keys, rb, side="right") - 1
    hi = searchsorted_words(state.keys, re_, side="left")
    # RMQ design: sparse table by default. The blocked two-level
    # alternative wins its ISOLATED build+query A/B 3.5x on CPU-XLA but
    # regressed the FULL kernel 27% there (fusion effects) — production
    # stays on the sparse table; FDB_TPU_RMQ=blocked flips it so the
    # auto-bench can rank both at full-kernel level on the real chip.
    if _RMQ_DESIGN == "blocked":
        bt = block_table(state.versions, NEG_VERSION)
        newest = range_max_blocked(
            bt, jnp.maximum(lo, 0), hi, NEG_VERSION
        ).reshape(b, r)
    else:
        st = sparse_table(state.versions)
        newest = range_max(
            st, jnp.maximum(lo, 0), hi, NEG_VERSION
        ).reshape(b, r)
    nonempty = lex_lt(batch.read_begin, batch.read_end)
    live = batch.read_mask & nonempty
    return live & (newest > batch.read_version[:, None])


def _history_conflicts(state: ConflictState, batch: BatchTensors) -> jax.Array:
    """bool [B]: some read range overlaps a historical write newer than rv."""
    return jnp.any(_history_conflict_ranges(state, batch), axis=1)


def _read_vs_accepted_writes(
    rb: jax.Array,
    re_: jax.Array,
    read_live: jax.Array,
    wb: jax.Array,
    we: jax.Array,
    write_live: jax.Array,
    accepted: jax.Array,
) -> jax.Array:
    """bool [B, R]: read range slot overlaps SOME accepted txn's write
    range (rank space). The intra-batch half of the loser-range report:
    all of a batch's accepted writes land at the same commit version, so
    a rejected txn repairing at that version must re-read every one of
    its ranges an accepted peer wrote — earlier OR later in batch order
    (the report is for re-reading a snapshot, not for blame assignment).
    A txn's own writes never qualify (it was rejected, so it is not in
    `accepted`)."""
    b, q = wb.shape
    aw = (write_live & accepted[:, None]).reshape(b * q)
    wbf = wb.reshape(b * q)
    wef = we.reshape(b * q)
    hit = (
        (rb[:, :, None] < wef[None, None, :])
        & (wbf[None, None, :] < re_[:, :, None])
        & aw[None, None, :]
    )
    return read_live & jnp.any(hit, axis=2)


# ---------------------------------------------------------------------------
# Phase 2: intra-batch conflict graph + wave acceptance
# ---------------------------------------------------------------------------


def _endpoint_ranks(batch: BatchTensors) -> tuple[jax.Array, ...]:
    """Map all batch endpoints into a shared dense rank space.

    Strict byte order is preserved among the batch's own endpoints (ranks via
    searchsorted-left into the sorted endpoint multiset), so interval overlap
    tests downstream are scalar int32 compares — no word axis.
    """
    b, r, w = batch.read_begin.shape
    q = batch.write_begin.shape[1]
    flat = jnp.concatenate(
        [
            batch.read_begin.reshape(b * r, w),
            batch.read_end.reshape(b * r, w),
            batch.write_begin.reshape(b * q, w),
            batch.write_end.reshape(b * q, w),
        ]
    )
    (sorted_keys,) = sort_keys_with_payload(flat)
    ranks = searchsorted_words(sorted_keys, flat, side="left")
    n_r = b * r
    n_q = b * q
    rb = ranks[:n_r].reshape(b, r)
    re_ = ranks[n_r : 2 * n_r].reshape(b, r)
    wb = ranks[2 * n_r : 2 * n_r + n_q].reshape(b, q)
    we = ranks[2 * n_r + n_q :].reshape(b, q)
    return rb, re_, wb, we


# Above this many (read-slot × write-slot) pairs the unrolled overlap form
# is replaced by one vectorized 4D reduce (compile time / program size
# cap). 128 keeps tpcc's 12x8 on the unrolled path: inside the block
# scan each term is a fused [G, B] compare with no 4D intermediate,
# while the vectorized form materializes [G, R, B, Q] per block.
_OVERLAP_UNROLL_LIMIT = 128


def _overlap_rows(
    rows_rb: jax.Array,
    rows_re: jax.Array,
    rows_live: jax.Array,
    wb: jax.Array,
    we: jax.Array,
    write_live: jax.Array,
) -> jax.Array:
    """M rows [N, B] for a slice of reader txns vs ALL writer txns.

    rows_*: [N, R] rank-space read intervals; wb/we/write_live: [B, Q].
    One fused [N, B] elementwise term per (read-slot, write-slot) pair —
    no 4D intermediate, no serialized map: XLA fuses the R·Q compares into
    a single memory-bound pass over the output matrix.

    Program size grows as R·Q under the unrolled form, so large range
    limits (e.g. tpcc's 12×8) switch to a single vectorized 4D reduce:
    one [N, R, B, Q] compare + any-reduce, constant program size at the
    cost of a fusible 4D intermediate."""
    n, r = rows_rb.shape
    b, q = wb.shape
    if r * q > _OVERLAP_UNROLL_LIMIT:
        t = (rows_rb[:, :, None, None] < we[None, None, :, :]) & (
            wb[None, None, :, :] < rows_re[:, :, None, None]
        )
        live = rows_live[:, :, None, None] & write_live[None, None, :, :]
        return jnp.any(t & live, axis=(1, 3))
    m = jnp.zeros((n, b), jnp.bool_)
    for i in range(r):
        rbi = rows_rb[:, i, None]
        rei = rows_re[:, i, None]
        livei = rows_live[:, i, None]
        for j in range(q):
            t = (rbi < we[None, :, j]) & (wb[None, :, j] < rei)
            m = m | (t & livei & write_live[None, :, j])
    return m


def endpoint_ranks_live(batch: BatchTensors) -> tuple[jax.Array, ...]:
    """(rb, re, read_live, wb, we, write_live): endpoint ranks plus the
    liveness masks (slot populated AND range non-empty in rank space) —
    the shared precursor of every acceptance path."""
    rb, re_, wb, we = _endpoint_ranks(batch)
    read_live = batch.read_mask & (rb < re_)  # [B, R]
    write_live = batch.write_mask & (wb < we)  # [B, Q]
    return rb, re_, read_live, wb, we, write_live


def _pairwise_overlap(batch: BatchTensors) -> jax.Array:
    """M[i, j] (bool [B, B]): some read range of txn i overlaps some write
    range of txn j."""
    return _overlap_rows(*endpoint_ranks_live(batch))


# Block size for the block-sequential acceptance scan. Within a block the
# wave relaxation runs on a [G, G] tile (0.5 MB at G=512 — VMEM-resident);
# cross-block influence is a single [G, B] matvec per block. This bounds
# the data-dependent round count by G per block AND shrinks each round's
# traffic from [B, B] (134 MB at B=8192) to [G, G], which matters on
# high-conflict workloads (mako Zipf-0.99, 95% conflicts) where acceptance
# chains are deep and the full-matrix wave paid 268 MB per round.
_ACCEPT_BLOCK = 512


def _block_scan_accept(base, xs_rows, make_rows):
    """Shared block-scan body for both acceptance entry points.

    Exact sequential-order acceptance (equivalent to _wave_accept and to
    the reference's sequential ConflictBatch order): process blocks of G
    txns in order (lax.scan); a block's candidates are first demoted by
    accepted writers in EARLIER blocks (one [G, B] @ [B] matvec against
    the accepted-so-far vector — later blocks contribute zeros), then the
    within-block order is resolved by the [G, G] wave. All predecessors
    of a block outside it are fully determined when the block runs, so
    the result is exact.

    xs_rows: pytree whose leaves have leading axis nblk; make_rows maps
    one slice of it to that block's [G, B] overlap rows.

    Packed-mask form (FDB_TPU_PACKED=1, block size a multiple of 32): the
    [G, B] rows are uint32-packed the moment they are built and never
    touched as bool again — the cross-block demotion matvec becomes a
    bitwise AND + any-reduce against the packed accepted vector (1/8 the
    row bytes, no bool→bf16 conversion, no MXU round trip), the accepted
    carry itself is a [B/32] bitset, and the within-block tile handed to
    the wave/seq accept is the packed [G, G/32] diagonal slice.
    """
    b = base.shape[0]
    g = min(_ACCEPT_BLOCK, b)
    nblk = b // g
    packed = _PACKED and g % 32 == 0
    seq = _ACCEPT_DESIGN == "seq"

    def body(acc, xs):
        rows_x, base_k, k = xs
        rows_k = make_rows(rows_x)  # [G, B]
        if packed:
            rp = pack_bits_u32(rows_k)  # [G, B/32]
            prior_hit = or_matvec_u32(rp, acc)
            sub = jax.lax.dynamic_slice(
                rp, (jnp.int32(0), k * (g // 32)), (g, g // 32)
            )
            accept_fn = _seq_accept_packed if seq else _wave_accept_packed
            acc_k = accept_fn(base_k & ~prior_hit, sub)
            acc = jax.lax.dynamic_update_slice(
                acc, pack_bits_u32(acc_k), (k * (g // 32),)
            )
        else:
            prior_hit = (
                jax.lax.dot(
                    rows_k.astype(jnp.bfloat16),
                    acc.astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32,
                )
                > 0.0
            )
            sub = jax.lax.dynamic_slice(rows_k, (jnp.int32(0), k * g), (g, g))
            accept_fn = _seq_accept if seq else _wave_accept
            acc_k = accept_fn(base_k & ~prior_hit, sub)
            acc = jax.lax.dynamic_update_slice(acc, acc_k, (k * g,))
        return acc, None

    acc, _ = jax.lax.scan(
        body,
        jnp.zeros((b // 32,), jnp.uint32) if packed else jnp.zeros_like(base),
        (
            xs_rows,
            base.reshape(nblk, g),
            jnp.arange(nblk, dtype=jnp.int32),
        ),
    )
    return unpack_bits_u32(acc, b) if packed else acc


def _block_accept(base: jax.Array, m: jax.Array) -> jax.Array:
    """Block-scan acceptance over a materialized [B, B] overlap matrix."""
    b = base.shape[0]
    g = min(_ACCEPT_BLOCK, b)
    if b % g:
        return _wave_accept(base, m)
    return _block_scan_accept(
        base, m.reshape(b // g, g, b), lambda rows_k: rows_k
    )


def _block_accept_fused(
    base: jax.Array,
    rb: jax.Array,
    re_: jax.Array,
    read_live: jax.Array,
    wb: jax.Array,
    we: jax.Array,
    write_live: jax.Array,
) -> jax.Array:
    """_block_accept with the overlap rows computed in-scan from rank
    intervals: the [B, B] matrix is never materialized — each block builds
    its own [G, B] slice from the [B, R]/[B, Q] rank vectors (a few KB),
    saving the ~200 MB/batch of matrix write+read at B=8192."""
    b = base.shape[0]
    g = min(_ACCEPT_BLOCK, b)
    if b % g:
        m = _overlap_rows(rb, re_, read_live, wb, we, write_live)
        return _wave_accept(base, m)
    nblk = b // g
    r = rb.shape[1]
    return _block_scan_accept(
        base,
        (
            rb.reshape(nblk, g, r),
            re_.reshape(nblk, g, r),
            read_live.reshape(nblk, g, r),
        ),
        lambda x: _overlap_rows(x[0], x[1], x[2], wb, we, write_live),
    )


def _seq_accept(base: jax.Array, m: jax.Array) -> jax.Array:
    """Exact sequential acceptance as a fixed G-step fori_loop.

    The literal transcription of the reference's per-txn order
    (ConflictBatch processes transactions strictly in sequence): step i
    accepts txn i iff base[i] and no already-accepted predecessor's writes
    overlap its reads. Each step is a [G] AND + any-reduce + one-element
    update — trivial VPU work, no matvec, no data-dependent trip count.
    Worst case and best case cost the same G steps, which beats the wave
    exactly when conflict chains are deep enough that its data-dependent
    round count (2 [G, G] matvecs per round) approaches G."""
    g = base.shape[0]
    tri = jnp.tril(jnp.ones((g, g), jnp.bool_), k=-1)
    p = m & tri

    def body(i, acc):
        hit = jnp.any(p[i] & acc)
        return acc.at[i].set(base[i] & ~hit)

    return jax.lax.fori_loop(0, g, body, jnp.zeros_like(base))


def _wave_accept(base: jax.Array, m: jax.Array) -> jax.Array:
    """Reproduce sequential in-order acceptance with O(depth) matvec rounds.

    base[i]: txn i would commit absent intra-batch conflicts. Edge j→i exists
    when j < i and M[i, j] (j's writes overlap i's reads). Sequential rule:
    accept i iff base[i] and no ACCEPTED j<i with an edge. Rounds: a txn is
    rejected as soon as an accepted conflicting predecessor is known; it is
    accepted once all its predecessors are determined and none of the
    accepted ones conflict. Each round determines at least the lowest
    undetermined txn, and in practice conflict chains are shallow (hot-key
    workloads determine in 2-3 rounds).
    """
    b = base.shape[0]
    tri = jnp.tril(jnp.ones((b, b), jnp.bool_), k=-1)
    # bf16 edges: the matvec rides the MXU; accumulation is forced to f32 so
    # row sums up to B stay exact (we only test > 0 anyway).
    p = (m & tri).astype(jnp.bfloat16)  # [B, B]

    def mv(vec):
        return (
            jax.lax.dot(p, vec.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32)
            > 0.0
        )

    def cond(carry):
        det, _, i = carry
        # Formal bound: each round determines at least the lowest
        # undetermined txn (all its predecessors are determined), so B
        # rounds always suffice — the cap makes the worst case explicit.
        return ~jnp.all(det) & (i < b)

    def step(carry):
        det, acc, i = carry
        hit_acc = mv(acc)
        pending = mv(~det)
        newly_rej = ~det & hit_acc
        newly_acc = ~det & base & ~hit_acc & ~pending
        det = det | newly_rej | newly_acc | (~det & ~base)
        acc = acc | newly_acc
        return det, acc, i + 1

    det0 = ~base  # non-candidates are determined (not accepted) immediately
    acc0 = jnp.zeros_like(base)
    _, acc, _ = jax.lax.while_loop(cond, step, (det0, acc0, jnp.int32(0)))
    return acc


def _wave_accept_packed(base: jax.Array, p: jax.Array) -> jax.Array:
    """_wave_accept over a uint32-packed [G, G/32] predecessor bitset.

    Same relaxation rounds and round count; each round's two matvecs are
    bitwise AND + any-reduce against the packed tile — 1/16 the operand
    bytes of the bf16 MXU tile (bit vs 2-byte lane) and no bool↔bf16
    conversions. ``p`` is the raw packed block tile; the strict-lower
    triangle mask is applied here (packed, so it too is 1/8 the bytes)."""
    g = base.shape[0]
    p = p & pack_bits_u32(jnp.tril(jnp.ones((g, g), jnp.bool_), k=-1))

    def mv(vec):
        return or_matvec_u32(p, pack_bits_u32(vec))

    def cond(carry):
        det, _, i = carry
        return ~jnp.all(det) & (i < g)

    def step(carry):
        det, acc, i = carry
        hit_acc = mv(acc)
        pending = mv(~det)
        newly_rej = ~det & hit_acc
        newly_acc = ~det & base & ~hit_acc & ~pending
        det = det | newly_rej | newly_acc | (~det & ~base)
        acc = acc | newly_acc
        return det, acc, i + 1

    det0 = ~base
    acc0 = jnp.zeros_like(base)
    _, acc, _ = jax.lax.while_loop(cond, step, (det0, acc0, jnp.int32(0)))
    return acc


def _seq_accept_packed(base: jax.Array, p: jax.Array) -> jax.Array:
    """_seq_accept over the packed [G, G/32] bitset: step i ANDs its
    predecessor row against the packed accepted set and sets one bit. No
    triangle mask is needed — bits j >= i are still zero in the accepted
    set when step i runs, exactly the sequential invariant."""
    g = base.shape[0]

    def body(i, accp):
        hit = jnp.any((p[i] & accp) != 0)
        bit = (base[i] & ~hit).astype(jnp.uint32) << (i & 31).astype(
            jnp.uint32
        )
        word = i >> 5
        return accp.at[word].set(accp[word] | bit)

    accp = jax.lax.fori_loop(0, g, body, jnp.zeros((g // 32,), jnp.uint32))
    return unpack_bits_u32(accp, g)


# ---------------------------------------------------------------------------
# Phase 2b: wave commit (FDB_TPU_WAVE_COMMIT=1) — reorder, don't abort
# ---------------------------------------------------------------------------
#
# Sequential acceptance treats batch order as serialization order and
# aborts every txn whose reads overlap an accepted EARLIER txn's writes —
# throwing away the conflict graph it just materialized. Wave commit
# spends it instead (FAFO, arXiv:2507.10757): the constraint "i must
# serialize BEFORE j" exists exactly when reads(i) ∩ writes(j) ≠ ∅ (i
# must not observe j's write), which is the untriangled overlap matrix.
# Topologically leveling that digraph yields commit WAVES: wave 0 txns
# see only pre-batch state, wave k txns serialize after waves < k, and
# every write-after-read chain commits in dependency order instead of
# losing all but its luckiest link. Only txns on TRUE CYCLES (mutual
# read-write entanglement — e.g. two RMWs of one key) are unschedulable;
# they abort, one exactly-on-a-cycle victim at a time, and the repair
# subsystem mops them up.
#
# Serializability: the realized order is (wave, batch index). A committed
# txn j's reads overlap no historical write past its read version (the
# history gate is unchanged) and no committed peer write EXCEPT those of
# txns at strictly LATER waves — which serialize after j, so j's
# pre-batch snapshot is exactly what the order prescribes. All writes
# still land at the batch commit version: visible read versions are
# always batch versions (GRV hands out committed batch versions, never
# intra-batch points), so a single-version paint is byte-equivalent for
# every future conflict test while the proxy applies same-version
# mutations in wave order.

#: Wave-level encoding (int32 [B], alongside the verdicts):
#:   >= 0  committed at this wave (serialization order = (level, index))
#:   -1    not committed for non-cycle reasons (history conflict,
#:         TOO_OLD, masked slot)
#:   -2    aborted on a true cycle (the repair engine's residue)
#: Canonical values live in core.types (imported at the top) so the
#: oracle and the runtime share them without importing device code.


def _pred_matrix_packed(base, rb, re_, read_live, wb, we, write_live):
    """uint32 [BP, BP/32] packed predecessor bitsets over rank intervals:
    bit i of row j ⇔ reads(i) ∩ writes(j) ≠ ∅ (txn i must serialize
    before txn j), diagonal cleared, restricted to candidate txns.

    Built [G, B]-blockwise with the same _overlap_rows primitive as the
    acceptance scan (writes of the block's txns as rows, everyone's reads
    as columns — overlap is symmetric, so the transpose falls out of the
    argument order) and packed the moment each block materializes. Inputs
    are padded to a multiple of 32 (BP) by the caller."""
    bp = base.shape[0]
    g = min(_ACCEPT_BLOCK, bp)
    q = wb.shape[1]
    if bp % g == 0 and bp > g:
        nblk = bp // g
        p = jax.lax.map(
            lambda x: pack_bits_u32(
                _overlap_rows(x[0], x[1], x[2], rb, re_, read_live)
            ),
            (
                wb.reshape(nblk, g, q),
                we.reshape(nblk, g, q),
                write_live.reshape(nblk, g, q),
            ),
        ).reshape(bp, bp // 32)
    else:
        p = pack_bits_u32(
            _overlap_rows(wb, we, write_live, rb, re_, read_live)
        )
    idx = jnp.arange(bp, dtype=jnp.int32)
    diag = jnp.where(
        (idx[:, None] >> 5) == jnp.arange(bp // 32, dtype=jnp.int32)[None, :],
        (jnp.uint32(1) << (idx & 31).astype(jnp.uint32))[:, None],
        jnp.uint32(0),
    )
    return p & ~diag & pack_bits_u32(base)[None, :]


def _min_pred(p, undetp, j):
    """Lowest-index undetermined predecessor of txn j (packed row scan).
    Only called on stuck txns, whose undetermined predecessor set is
    non-empty by construction."""
    row = p[j] & undetp
    w = jnp.argmax(row != 0).astype(jnp.int32)
    lanes = jnp.arange(32, dtype=jnp.uint32)
    bit = jnp.argmax(((row[w] >> lanes) & 1) != 0).astype(jnp.int32)
    return w * 32 + bit


def _cycle_victim(p, undet, undetp):
    """Deterministic exactly-on-a-cycle victim of a stalled schedule.

    At a stall every undetermined txn has an undetermined predecessor, so
    the min-predecessor walk is total on the stuck set and — being a
    deterministic functional graph — terminates on exactly one cycle.
    Walk BP steps from the lowest stuck txn (guaranteed to have entered
    the cycle: entry distance < |stuck| <= BP), then walk BP more
    tracking the minimum index visited — at least one full loop of the
    cycle, so the result is the cycle's minimum-index member regardless
    of where the first walk landed. The host oracle replays the identical
    rule with n steps; both step counts exceed every entry distance and
    cycle length, so the victims agree byte-for-byte."""
    bp = undet.shape[0]
    j0 = jnp.argmax(undet).astype(jnp.int32)
    j = jax.lax.fori_loop(0, bp, lambda _, j: _min_pred(p, undetp, j), j0)

    def track(_, carry):
        j, m = carry
        j = _min_pred(p, undetp, j)
        return j, jnp.minimum(m, j)

    _, victim = jax.lax.fori_loop(0, bp, track, (j, j))
    return victim


def wave_pred_matrix(
    base: jax.Array, ranks: tuple[jax.Array, ...]
) -> jax.Array:
    """uint32 [BP, BP/32] packed predecessor bitsets over (possibly
    shard-clipped) rank intervals, padded to BP = ceil32(B). The
    shard-exchange operand: shards partition the keyspace, so the OR of
    per-shard clipped matrices IS the global matrix (an edge's overlap
    region lands in exactly the shards that witness it) — the mesh
    engine all_gathers and OR-reduces these, and the role-level
    resolve_edges payload carries them to the commit proxy."""
    rb, re_, read_live, wb, we, write_live = ranks
    b = base.shape[0]
    bp = ((b + 31) // 32) * 32
    if bp != b:
        pad = bp - b
        base = jnp.pad(base, (0, pad))
        rb = jnp.pad(rb, ((0, pad), (0, 0)))
        re_ = jnp.pad(re_, ((0, pad), (0, 0)))
        read_live = jnp.pad(read_live, ((0, pad), (0, 0)))
        wb = jnp.pad(wb, ((0, pad), (0, 0)))
        we = jnp.pad(we, ((0, pad), (0, 0)))
        write_live = jnp.pad(write_live, ((0, pad), (0, 0)))
    return _pred_matrix_packed(base, rb, re_, read_live, wb, we, write_live)


def wave_occupied_tiles(p: jax.Array) -> jax.Array:
    """int32 scalar: non-zero 32x32-bit tiles of a packed predecessor
    matrix (32 rows x 1 uint32 word). The realized-graph density signal
    behind the mesh exchange-cost model: a tile-scoped exchange ships
    only occupied tiles, so its bytes scale with the conflict graph the
    workload actually produced, not with BP² (bench.py roofline
    ``exchange_bytes_per_batch``)."""
    bp, w = p.shape
    t = p.reshape(bp // 32, 32, w)
    return jnp.sum(jnp.any(t != 0, axis=1).astype(jnp.int32))


def _wave_level_packed(base: jax.Array, p: jax.Array) -> jax.Array:
    """level int32 [BP] from a packed predecessor matrix: the wave-commit
    fixed point. ``base`` is the padded candidate mask; ``p`` the packed
    [BP, BP/32] graph (global or single-shard — the rule is graph-
    agnostic).

    Fixed point over the packed predecessor bitsets (same operand shape
    and AND/any-reduce rounds as _wave_accept_packed): each iteration
    either levels every txn with no undetermined predecessor into the
    next wave, or — when the remaining subgraph has no source, i.e. every
    stuck txn sits on or behind a cycle — aborts the one _cycle_victim
    and continues, so txns merely DOWNSTREAM of a cycle are re-examined
    once the cycle is broken and still commit. Every iteration determines
    at least one txn, bounding the loop by the candidate count (the
    saturation cap makes the worst case explicit, exactly like the wave
    accept's round cap). Deterministic in the graph alone, so every mesh
    shard running it on the same OR-reduced matrix reports the identical
    schedule (core/wavemesh.level_wave_graph is the host replay)."""
    bp = base.shape[0]
    idx = jnp.arange(bp, dtype=jnp.int32)

    def cond(carry):
        undet, _level, _wave, it = carry
        return jnp.any(undet) & (it < bp + 1)

    def step(carry):
        undet, level, wave, it = carry
        undetp = pack_bits_u32(undet)
        blocked = or_matvec_u32(p, undetp)
        ready = undet & ~blocked
        has_ready = jnp.any(ready)
        victim = jax.lax.cond(
            has_ready,
            lambda: jnp.int32(bp),  # out-of-range: no abort this round
            lambda: _cycle_victim(p, undet, undetp),
        )
        vmask = idx == victim
        level = jnp.where(
            has_ready & ready,
            wave,
            jnp.where(vmask, jnp.int32(LEVEL_CYCLE), level),
        )
        undet = undet & ~jnp.where(has_ready, ready, vmask)
        return undet, level, wave + has_ready.astype(jnp.int32), it + 1

    _, level, _, _ = jax.lax.while_loop(
        cond,
        step,
        (
            base,
            jnp.full((bp,), LEVEL_NONE, jnp.int32),
            jnp.int32(0),
            jnp.int32(0),
        ),
    )
    return level


def wave_level_from_graph(
    cand: jax.Array, p: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """(accepted bool [B], level int32 [B]) from a GLOBAL predecessor
    matrix + global candidate mask. Columns are re-masked to candidates
    here: a shard's clipped matrix carries edges from txns that are
    candidates in its local view but history-gated on another shard, and
    those edges must not constrain the schedule."""
    b = cand.shape[0]
    bp = p.shape[0]
    candp = jnp.pad(cand, (0, bp - b)) if bp != b else cand
    p = p & pack_bits_u32(candp)[None, :]
    level = _wave_level_packed(candp, p)[:b]
    return level >= 0, level


def _wave_commit_accept(
    base: jax.Array, ranks: tuple[jax.Array, ...]
) -> tuple[jax.Array, jax.Array]:
    """(accepted bool [B], level int32 [B]): schedule candidate txns into
    dependency-ordered commit waves; abort only true-cycle members. The
    single-shard composition of wave_pred_matrix + _wave_level_packed."""
    b = base.shape[0]
    p = wave_pred_matrix(base, ranks)
    bp = p.shape[0]
    basep = jnp.pad(base, (0, bp - b)) if bp != b else base
    level = _wave_level_packed(basep, p)[:b]
    return level >= 0, level


# ---------------------------------------------------------------------------
# Phase 3: paint accepted writes into the step function + compact
# ---------------------------------------------------------------------------


def _paint_and_compact(
    state: ConflictState,
    batch: BatchTensors,
    accepted: jax.Array,
    commit_version: jax.Array,
    new_oldest: jax.Array,
) -> ConflictState:
    """Fold accepted writes into the step function WITHOUT re-sorting the
    whole history. The history keys are already sorted, so only the batch's
    2·B·Q new endpoints are sorted ([2BQ, W], tiny next to [C+2BQ, W]); the
    two sorted sequences are then interleaved by rank arithmetic (the
    merge-path construction: each element's output slot is its own index
    plus its cross-rank in the other sequence, history winning ties), and
    the surviving boundaries are compacted to the front by gathering the
    j-th kept entry (binary search into the keep prefix-sum). Everything is
    sorts-of-small + gathers: no full-history sort (the first version of
    this kernel re-sorted all of C per batch) and no large scatters (XLA
    TPU scatters serialize; gathers tile onto the VPU)."""
    c, w = state.keys.shape
    b, q, _ = batch.write_begin.shape
    e2 = b * q
    n2 = 2 * e2
    n = c + n2

    valid = (
        accepted[:, None]
        & batch.write_mask
        & lex_lt(batch.write_begin, batch.write_end)
    )  # [B, Q]
    inf_row = jnp.full((w,), INT32_MAX, jnp.int32)
    wb = jnp.where(valid[..., None], batch.write_begin, inf_row).reshape(e2, w)
    we = jnp.where(valid[..., None], batch.write_end, inf_row).reshape(e2, w)

    # New endpoints with their coverage delta and their segment's pre-paint
    # version (the version a split boundary must inherit).
    new_keys = jnp.concatenate([wb, we])  # [n2, W]
    new_delta = jnp.concatenate(
        [valid.reshape(e2).astype(jnp.int32), -valid.reshape(e2).astype(jnp.int32)]
    )
    # ONE history search serves both uses below: cross_rank on the raw
    # endpoints gives seg (containing segment), and — carried through the
    # sort as a payload — its sorted permutation IS the cross-rank of the
    # sorted endpoints (searchsorted of a permuted set permutes the same
    # way), which the merge-path needs for pos_n.
    cross_rank = searchsorted_words(state.keys, new_keys, side="right")
    seg = cross_rank - 1
    new_oldv = state.versions[jnp.maximum(seg, 0)]

    snew, sdelta_new, soldv_new, scross = sort_keys_with_payload(
        new_keys, new_delta, new_oldv, cross_rank
    )
    return _paint_tail(
        state, snew, sdelta_new, soldv_new, scross, commit_version, new_oldest
    )


def _paint_tail(
    state: ConflictState,
    snew: jax.Array,
    sdelta_new: jax.Array,
    soldv_new: jax.Array,
    scross: jax.Array,
    commit_version: jax.Array,
    new_oldest: jax.Array,
) -> ConflictState:
    """Shared merge-path + coverage + compact tail of the paint pass.

    Inputs are the SORTED new endpoints (snew [n2, W] keys, coverage
    deltas, pre-paint segment versions, cross-ranks into the history) —
    produced by the W-word key sort on the unpacked path and by the
    1-word rank sort + dictionary gather on the packed path."""
    c, w = state.keys.shape
    n2 = snew.shape[0]
    n = c + n2

    # Merge-path, scatter-free (TPU scatters serialize badly; gathers tile).
    # pos_n[j] = output slot of sorted-new[j] = j + its cross-rank in the
    # history ('right' side puts history entries before equal new entries —
    # a collision-free permutation of [0, n) even with duplicate keys).
    # Each output slot then derives its source by rank arithmetic: slot i
    # holds new[k] iff pos_n[k] == i, else history[i - #new_slots_before_i].
    pos_n = jnp.arange(n2, dtype=jnp.int32) + scross
    idx = jnp.arange(n, dtype=jnp.int32)
    cnt_le = jnp.searchsorted(pos_n, idx, side="right").astype(jnp.int32)
    k_new = jnp.maximum(cnt_le - 1, 0)
    from_new = (cnt_le > 0) & (pos_n[k_new] == idx)
    hist_idx = jnp.clip(idx - cnt_le, 0, c - 1)  # exact for non-new slots

    skeys = jnp.where(from_new[:, None], snew[k_new], state.keys[hist_idx])
    sdelta = jnp.where(from_new, sdelta_new[k_new], 0)
    soldv = jnp.where(from_new, soldv_new[k_new], state.versions[hist_idx])

    covered = jnp.cumsum(sdelta) > 0
    is_inf = jnp.all(skeys == INT32_MAX, axis=-1)
    newv = jnp.where(covered, commit_version, soldv)
    # GC: segments at/below the window floor can never conflict again.
    newv = jnp.where((newv <= new_oldest) | is_inf, NEG_VERSION, newv)

    fkeys, fv, n_used, overflow = _dedup_compact(skeys, newv, c, state.overflow)
    return ConflictState(
        keys=fkeys,
        versions=fv,
        n_used=n_used,
        oldest=new_oldest,
        overflow=overflow,
    )


def _dedup_compact(skeys, newv, c_out, prior_overflow):
    """Shared compaction tail of every step-function rewrite (paint and
    the window-history merge): dedup equal keys, drop boundaries that no
    longer change the step function, compact survivors to the front.

    skeys [n, W] sorted (ties allowed), newv [n] already GC'd (expired and
    padding rows hold the sentinel). Returns (keys, versions, n_used,
    overflow) at capacity c_out."""
    n, w = skeys.shape
    is_inf = jnp.all(skeys == INT32_MAX, axis=-1)
    # Dedup equal keys: keep the LAST occurrence (it carries the full
    # coverage sum and the consistent old version).
    neq_next = jnp.any(skeys[:-1] != skeys[1:], axis=-1)
    keep1 = jnp.concatenate([neq_next, jnp.ones((1,), jnp.bool_)])
    # Drop boundaries whose version equals the previous KEPT boundary's —
    # they no longer change the step function (this is what erases interior
    # boundaries of freshly painted ranges and expired segments).
    idx = jnp.arange(n, dtype=jnp.int32)
    kept_idx = jnp.where(keep1, idx, -1)
    prev_kept = jnp.concatenate(
        [jnp.full((1,), -1, jnp.int32), jax.lax.cummax(kept_idx, axis=0)[:-1]]
    )
    prev_v = jnp.where(prev_kept >= 0, newv[jnp.maximum(prev_kept, 0)], NEG_VERSION - 1)
    keep = keep1 & (newv != prev_v) & ~is_inf

    # The keyspace minimum must always remain a boundary. Force its run's
    # LAST row (the keep-last dedup representative): forcing the first
    # would duplicate the boundary whenever a batch paints endpoints
    # equal to the minimum (e.g. shard-clamped delta-0 entries at lo).
    first_live = jnp.argmax(~is_inf)  # index of smallest real key (= min key)
    is_min = jnp.all(skeys == skeys[first_live], axis=-1) & ~is_inf
    min_last = n - 1 - jnp.argmax(is_min[::-1])
    keep = keep.at[min_last].set(True)

    # Compact survivors to the front, gather-style: output slot j pulls the
    # (j+1)-th kept entry (binary search into the keep prefix-sum) — the
    # scatter-free dual of a prefix-sum scatter compaction.
    keep_cum = jnp.cumsum(keep.astype(jnp.int32))  # [n], non-decreasing
    n_used = keep_cum[-1]
    out_j = jnp.arange(c_out, dtype=jnp.int32)
    src = jnp.searchsorted(keep_cum, out_j + 1, side="left").astype(jnp.int32)
    src = jnp.clip(src, 0, n - 1)
    live_out = out_j < n_used
    fkeys = jnp.where(
        live_out[:, None], skeys[src], jnp.full((w,), INT32_MAX, jnp.int32)
    )
    fv = jnp.where(live_out, newv[src], NEG_VERSION)
    overflow = prior_overflow | (n_used > c_out)
    return fkeys, fv, jnp.minimum(n_used, c_out), overflow


def clip_batch(batch: BatchTensors, lo: jax.Array, hi: jax.Array) -> BatchTensors:
    """Restrict every range to the keyspace shard [lo, hi).

    The device-side analogue of the reference CommitProxy's per-resolver
    conflict-range split (CommitProxyServer.actor.cpp: ranges are routed to
    resolvers by keyRange shard). Ranges outside the shard become empty and
    drop out of their masks; read_version/txn_mask are untouched (TOO_OLD is
    judged on the unclipped batch so all shards agree).
    """
    rb = lex_max(batch.read_begin, lo)
    re_ = lex_min(batch.read_end, hi)
    wb = lex_max(batch.write_begin, lo)
    we = lex_min(batch.write_end, hi)
    return batch._replace(
        read_begin=rb,
        read_end=re_,
        read_mask=batch.read_mask & lex_lt(rb, re_),
        write_begin=wb,
        write_end=we,
        write_mask=batch.write_mask & lex_lt(wb, we),
    )


# ---------------------------------------------------------------------------
# Entry: full resolve step
# ---------------------------------------------------------------------------


def too_old_mask(
    state: ConflictState, batch: BatchTensors, new_oldest: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """(floor, too_old[B]). The window floor advances BEFORE resolution
    (reference: Resolver sets ConflictSet::oldestVersion from the request,
    then detects conflicts) and never regresses — a caller passing a
    regressed new_oldest must not reopen a window whose writes were GC'd.
    Write-only transactions are never too old."""
    has_reads = jnp.any(
        batch.read_mask & lex_lt(batch.read_begin, batch.read_end), axis=1
    )
    floor = jnp.maximum(state.oldest, new_oldest)
    too_old = batch.txn_mask & has_reads & (batch.read_version < floor)
    return floor, too_old


def assemble_verdicts(
    too_old: jax.Array, txn_mask: jax.Array, accepted: jax.Array
) -> jax.Array:
    return jnp.where(
        too_old,
        jnp.int8(V_TOO_OLD),
        jnp.where(txn_mask & ~accepted, jnp.int8(V_CONFLICT), jnp.int8(V_COMMITTED)),
    )


def loser_range_mask(
    hist_mask: jax.Array,
    ranks: tuple[jax.Array, ...],
    accepted: jax.Array,
    verdicts: jax.Array,
) -> jax.Array:
    """bool [B, R]: which read range slots of each CONFLICT txn lost —
    history conflicts exactly, plus overlaps with accepted peers' writes
    (whose mutations land at this batch's commit version). Surfaced to the
    host so the resolver's conflicting-keys report (and the client repair
    engine behind it) re-reads only these, not the whole read set."""
    rb, re_, read_live, wb, we, write_live = ranks
    intra = _read_vs_accepted_writes(
        rb, re_, read_live, wb, we, write_live, accepted
    )
    return (hist_mask | intra) & (verdicts == V_CONFLICT)[:, None]


def _accept_or_schedule(base, ranks, wave: bool):
    """Shared acceptance dispatch: sequential-order block scan (wave=False)
    or the wave-commit schedule (wave=True — levels ride along)."""
    if wave:
        return _wave_commit_accept(base, ranks)
    return _block_accept_fused(base, *ranks), None


def resolve_batch(
    state: ConflictState,
    batch: BatchTensors,
    commit_version: jax.Array,
    new_oldest: jax.Array,
    report: bool = False,
    wave: bool = False,
):
    """Resolve one batch and fold its accepted writes into the history.

    Returns (verdicts int8 [B], new_state) — with `report` (a static
    Python flag; each value compiles its own program), (verdicts,
    loser_mask bool [B, R], new_state). Mirrors the reference call
    sequence ConflictBatch::detectConflicts → combineWriteConflictRanges →
    SkipList::addConflictRanges, as one compiled program.

    `wave` (static) switches intra-batch acceptance to the wave-commit
    schedule and inserts the int32 [B] wave levels right after the
    verdicts in every return shape.
    """
    floor, too_old = too_old_mask(state, batch, new_oldest)
    hist_mask = _history_conflict_ranges(state, batch)
    hist_conflict = jnp.any(hist_mask, axis=1)
    base = batch.txn_mask & ~too_old & ~hist_conflict
    ranks = endpoint_ranks_live(batch)
    accepted, levels = _accept_or_schedule(base, ranks, wave)
    verdicts = assemble_verdicts(too_old, batch.txn_mask, accepted)
    new_state = _paint_and_compact(state, batch, accepted, commit_version, floor)
    out = (verdicts, levels) if wave else (verdicts,)
    if report:
        losers = loser_range_mask(hist_mask, ranks, accepted, verdicts)
        return (*out, losers, new_state)
    return (*out, new_state)


def rebase(state: ConflictState, delta: jax.Array) -> ConflictState:
    """Shift all relative versions down by delta (host rebases its offset).

    Versions below delta are expired by construction (host only rebases to
    the window floor) — clamp them to the sentinel instead of underflowing;
    this also makes a saturated delta (huge version jump) behave correctly.
    """
    v = jnp.where(state.versions < delta, NEG_VERSION, state.versions - delta)
    return state._replace(
        versions=v, oldest=jnp.maximum(state.oldest - delta, 0)
    )


def resolve_many(
    state: ConflictState,
    batches: BatchTensors,  # leading scan axis [k, ...] on every leaf
    commit_versions: jax.Array,  # int32 [k], strictly increasing
    new_oldests: jax.Array,  # int32 [k], non-decreasing
    wave: bool = False,
):
    """Resolve k batches in ONE compiled program (device-side lax.scan).

    Semantically identical to k sequential resolve_batch calls; exists
    because per-dispatch host→device latency (66 ms through a tunneled
    PJRT backend) would otherwise dominate the ~4 ms of real per-batch
    compute. The reference amortizes the same way at a different layer:
    CommitProxy batches many client commits per ResolveTransactionBatch
    RPC (CommitProxyServer.actor.cpp). With `wave` (static) the int32
    [k, B] wave levels are returned after the verdicts.
    """

    def body(st, xs):
        batch, cv, old = xs
        out = resolve_batch(st, batch, cv, old, wave=wave)
        return out[-1], out[:-1]

    state, stacked = jax.lax.scan(
        body, state, (batches, commit_versions, new_oldests)
    )
    return (*stacked, state)


# ---------------------------------------------------------------------------
# Window history (default, FDB_TPU_HISTORY=window): two-level base + delta
# ---------------------------------------------------------------------------
#
# VERDICT r4 item 2: the flat design above rebuilds sparse_table(versions)
# — O(C·log C) HBM traffic at C=262k — inside EVERY resolve_batch of the
# resolve_many scan. The two-level design amortizes it:
#
# - `base`: the bulk history, FROZEN between merges, with its sparse table
#   carried alongside (built once per merge, not per batch).
# - `delta`: a small step function (capacity Cd ~ one batch's worst-case
#   paint) holding only the writes since the last merge. Per-batch work —
#   the delta RMQ build and the paint — touches Cd elements, not C.
# - History query = max(base range-max via the PREBUILT table, delta
#   range-max via a per-batch table over Cd).
# - When the next batch's worst-case paint wouldn't fit the delta, the
#   delta is folded into the base (pointwise-max merge of two step
#   functions over their union boundary set — one O(C+Cd) pass) and the
#   base table rebuilt, all inside the same compiled program (lax.cond).
#
# Freezing base between merges is sound: base versions only become STALE
# (≤ the advancing floor), and the conflict test `newest > read_version`
# with read_version ≥ floor (non-TOO_OLD txns) is unaffected by stale
# segments; expired segments are GC'd at the next merge.


class HistState(NamedTuple):
    """Two-level device history: frozen base + its RMQ table + live delta."""

    base: ConflictState
    base_st: jax.Array  # sparse table over base.versions [L, C]
    delta: ConflictState  # capacity Cd; oldest = the LIVE window floor


def init_hist(capacity: int, width: int, min_key,
              delta_capacity: int) -> HistState:
    base = init_state(capacity, width, min_key)
    return HistState(
        base=base,
        base_st=sparse_table(base.versions),
        delta=init_state(delta_capacity, width, min_key),
    )


def _reset_delta(delta: ConflictState, floor: jax.Array) -> ConflictState:
    """Empty delta after a merge; keys[0] (the keyspace minimum boundary)
    is invariant under paint, so reuse it. Overflow stays sticky (host
    clears after reacting)."""
    keys = jnp.full_like(delta.keys, INT32_MAX).at[0].set(delta.keys[0])
    return ConflictState(
        keys=keys,
        versions=jnp.full_like(delta.versions, NEG_VERSION),
        n_used=jnp.int32(1),
        oldest=floor,
        overflow=delta.overflow,
    )


def _merge_delta(base: ConflictState, delta: ConflictState,
                 floor: jax.Array) -> ConflictState:
    """Fold the delta into the base: pointwise max of the two step
    functions over the union boundary set, then GC (≤ floor) + compact.
    Max is exact because delta writes postdate every base write they
    cover. Same merge-path construction as _paint_and_compact — all
    sorts-of-small + gathers, no scatters."""
    c, w = base.keys.shape
    cd = delta.keys.shape[0]
    n = c + cd
    # The packed design's fingerprint search also serves the merge (both
    # operands are step-function key arrays); unpacked keeps the r5
    # full-width search so the A/B baseline is untouched.
    _ss = searchsorted_words_fp if _PACKED else searchsorted_words
    cross_d = _ss(base.keys, delta.keys, side="right")  # [Cd]
    seg_b_for_d = jnp.maximum(cross_d - 1, 0)
    cross_b = _ss(delta.keys, base.keys, side="right")  # [C]
    seg_d_for_b = jnp.maximum(cross_b - 1, 0)

    # Merge-path: delta entry j lands at slot j + its cross-rank ('right'
    # puts base entries before equal delta entries → keep-last dedup keeps
    # the delta occurrence; both carry the same max so either is correct).
    pos_d = jnp.arange(cd, dtype=jnp.int32) + cross_d
    idx = jnp.arange(n, dtype=jnp.int32)
    cnt_le = jnp.searchsorted(pos_d, idx, side="right").astype(jnp.int32)
    k_d = jnp.maximum(cnt_le - 1, 0)
    from_d = (cnt_le > 0) & (pos_d[k_d] == idx)
    b_idx = jnp.clip(idx - cnt_le, 0, c - 1)

    skeys = jnp.where(from_d[:, None], delta.keys[k_d], base.keys[b_idx])
    vb = jnp.where(from_d, base.versions[seg_b_for_d[k_d]],
                   base.versions[b_idx])
    vd = jnp.where(from_d, delta.versions[k_d],
                   delta.versions[seg_d_for_b[b_idx]])
    v = jnp.maximum(vb, vd)
    is_inf = jnp.all(skeys == INT32_MAX, axis=-1)
    v = jnp.where((v <= floor) | is_inf, NEG_VERSION, v)

    fkeys, fv, n_used, overflow = _dedup_compact(
        skeys, v, c, base.overflow | delta.overflow
    )
    return ConflictState(
        keys=fkeys, versions=fv, n_used=n_used, oldest=floor,
        overflow=overflow,
    )


def _maybe_merge(hist: HistState, demand: jax.Array,
                 floor: jax.Array) -> HistState:
    """Fold delta into base when `demand` more boundary slots wouldn't
    fit, OR when enough base segments have expired that the merge's GC
    reclaims meaningful capacity (the frozen base never GCs on its own —
    without this, headroom would stay pinned after the MVCC floor slides
    past old history, starving the resolver fail-safe's release check).
    The sparse-table rebuild rides inside the taken branch only."""
    base, base_st, delta = hist
    cd = delta.keys.shape[0]
    c = base.keys.shape[0]

    reclaimable = jnp.sum(
        ((base.versions <= floor) & (base.versions > NEG_VERSION))
        .astype(jnp.int32)
    )

    def do_merge(h):
        b, _st, d = h
        nb = _merge_delta(b, d, floor)
        return HistState(nb, sparse_table(nb.versions), _reset_delta(d, floor))

    need = (delta.n_used + demand > cd) | (reclaimable >= max(c // 8, 1))
    return jax.lax.cond(need, do_merge, lambda h: h, hist)


def _history_conflict_ranges_hist(base: ConflictState, base_st: jax.Array,
                                  delta: ConflictState,
                                  batch: BatchTensors) -> jax.Array:
    """bool [B, R]: _history_conflict_ranges against base (prebuilt table)
    + delta (small per-batch table)."""
    b, r, w = batch.read_begin.shape
    rb = batch.read_begin.reshape(b * r, w)
    re_ = batch.read_end.reshape(b * r, w)
    lo = searchsorted_words(base.keys, rb, side="right") - 1
    hi = searchsorted_words(base.keys, re_, side="left")
    newest_b = range_max(base_st, jnp.maximum(lo, 0), hi, NEG_VERSION)
    lo_d = searchsorted_words(delta.keys, rb, side="right") - 1
    hi_d = searchsorted_words(delta.keys, re_, side="left")
    if _RMQ_DESIGN == "blocked":
        dt = block_table(delta.versions, NEG_VERSION)
        newest_d = range_max_blocked(dt, jnp.maximum(lo_d, 0), hi_d,
                                     NEG_VERSION)
    else:
        dt = sparse_table(delta.versions)
        newest_d = range_max(dt, jnp.maximum(lo_d, 0), hi_d, NEG_VERSION)
    newest = jnp.maximum(newest_b, newest_d).reshape(b, r)
    nonempty = lex_lt(batch.read_begin, batch.read_end)
    live = batch.read_mask & nonempty
    return live & (newest > batch.read_version[:, None])


def _history_conflicts_hist(base: ConflictState, base_st: jax.Array,
                            delta: ConflictState,
                            batch: BatchTensors) -> jax.Array:
    """bool [B]: any-reduce of _history_conflict_ranges_hist."""
    return jnp.any(
        _history_conflict_ranges_hist(base, base_st, delta, batch), axis=1
    )


def resolve_batch_hist(
    hist: HistState,
    batch: BatchTensors,
    commit_version: jax.Array,
    new_oldest: jax.Array,
    report: bool = False,
    wave: bool = False,
):
    """resolve_batch over the two-level history. Identical verdicts to
    resolve_batch (oracle-tested); only the history data structure
    differs. `report` (static) additionally returns the loser-range mask
    bool [B, R] (see loser_range_mask); `wave` (static) inserts the wave
    levels after the verdicts."""
    floor, too_old = too_old_mask(hist.delta, batch, new_oldest)
    demand = 2 * jnp.sum(
        (batch.write_mask & lex_lt(batch.write_begin, batch.write_end))
        .astype(jnp.int32)
    )
    hist = _maybe_merge(hist, demand, floor)
    base_h, base_st, delta = hist
    hist_mask = _history_conflict_ranges_hist(base_h, base_st, delta, batch)
    hist_conflict = jnp.any(hist_mask, axis=1)
    ok = batch.txn_mask & ~too_old & ~hist_conflict
    ranks = endpoint_ranks_live(batch)
    accepted, levels = _accept_or_schedule(ok, ranks, wave)
    verdicts = assemble_verdicts(too_old, batch.txn_mask, accepted)
    delta = _paint_and_compact(delta, batch, accepted, commit_version, floor)
    new_hist = HistState(base_h, base_st, delta)
    out = (verdicts, levels) if wave else (verdicts,)
    if report:
        losers = loser_range_mask(hist_mask, ranks, accepted, verdicts)
        return (*out, losers, new_hist)
    return (*out, new_hist)


def resolve_many_hist(
    hist: HistState,
    batches: BatchTensors,
    commit_versions: jax.Array,
    new_oldests: jax.Array,
    wave: bool = False,
):
    def body(h, xs):
        batch, cv, old = xs
        out = resolve_batch_hist(h, batch, cv, old, wave=wave)
        return out[-1], out[:-1]

    hist, stacked = jax.lax.scan(
        body, hist, (batches, commit_versions, new_oldests)
    )
    return (*stacked, hist)


def advance_hist(hist: HistState, commit_version: jax.Array,
                 new_oldest: jax.Array) -> HistState:
    """GC-only step for the hist engine: advance the floor AND force a
    merge so expired base segments compact out — this is what lets the
    resolver fail-safe drain (headroom must recover as the window slides;
    the lazy base would otherwise hold expired segments until the next
    organic merge)."""
    floor = jnp.maximum(hist.delta.oldest, new_oldest)
    nb = _merge_delta(hist.base, hist.delta, floor)
    return HistState(nb, sparse_table(nb.versions),
                     _reset_delta(hist.delta, floor))


# ---------------------------------------------------------------------------
# Packed kernel (FDB_TPU_PACKED=1): rank-space probes over the host-deduped
# key dictionary, fingerprint history search, bit-packed masks. Byte-
# identical verdicts to the unpacked entry points (oracle-tested); only
# the data movement differs.
# ---------------------------------------------------------------------------


def too_old_mask_packed(
    state: ConflictState, pb: PackedBatch, new_oldest: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """too_old_mask in rank space (emptiness is a scalar int32 compare)."""
    has_reads = jnp.any(pb.read_mask & (pb.read_begin < pb.read_end), axis=1)
    floor = jnp.maximum(state.oldest, new_oldest)
    too_old = pb.txn_mask & has_reads & (pb.read_version < floor)
    return floor, too_old


def endpoint_ranks_live_packed(pb: PackedBatch) -> tuple[jax.Array, ...]:
    """endpoint_ranks_live without the device sort: the host packer
    already emitted rank-space intervals (order-isomorphic with exact tie
    structure), so this is just the liveness mask computation."""
    read_live = pb.read_mask & (pb.read_begin < pb.read_end)
    write_live = pb.write_mask & (pb.write_begin < pb.write_end)
    return (pb.read_begin, pb.read_end, read_live,
            pb.write_begin, pb.write_end, write_live)


def _dict_history_search(
    state_keys: jax.Array, dict_keys: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """(rs, ls) int32 [N+1]: ONE column-cascade fingerprint search of
    every UNIQUE batch key into the history yields both searchsorted
    sides; per-slot probes then gather by rank. rs ('right') - 1 is the
    containing segment for a range begin; ls ('left') is the first
    segment at/after a range end; rs is also exactly the paint pass's
    cross-rank."""
    ls, rs = searchsorted_words_2sided_fp(state_keys, dict_keys)
    return rs, ls


def _history_conflict_ranges_packed(
    state: ConflictState, pb: PackedBatch,
    rs: jax.Array | None = None, ls: jax.Array | None = None,
) -> jax.Array:
    """_history_conflict_ranges over the dictionary: the [C, W] history is
    probed once per unique key (4-byte fingerprint steps, full-width
    compares only on first-word ties); read slots gather their bounds by
    rank."""
    b, r = pb.read_begin.shape
    if rs is None:
        rs, ls = _dict_history_search(state.keys, pb.dict_keys)
    lo = rs[pb.read_begin.reshape(-1)] - 1
    hi = ls[pb.read_end.reshape(-1)]
    if _RMQ_DESIGN == "blocked":
        bt = block_table(state.versions, NEG_VERSION)
        newest = range_max_blocked(
            bt, jnp.maximum(lo, 0), hi, NEG_VERSION
        ).reshape(b, r)
    else:
        st = sparse_table(state.versions)
        newest = range_max(
            st, jnp.maximum(lo, 0), hi, NEG_VERSION
        ).reshape(b, r)
    live = pb.read_mask & (pb.read_begin < pb.read_end)
    return live & (newest > pb.read_version[:, None])


def _history_conflicts_packed(state: ConflictState, pb: PackedBatch) -> jax.Array:
    return jnp.any(_history_conflict_ranges_packed(state, pb), axis=1)


def _paint_and_compact_packed(
    state: ConflictState,
    pb: PackedBatch,
    accepted: jax.Array,
    commit_version: jax.Array,
    new_oldest: jax.Array,
    rs: jax.Array | None = None,
) -> ConflictState:
    """_paint_and_compact with rank-carried endpoints: sorts 1-word int32
    ranks (plus one index payload) instead of [n2, W] keys, gathers the
    boundary keys back from the dictionary, and reuses the history search
    already done per unique key (rs) as the merge-path cross-rank."""
    b, q = pb.write_begin.shape
    e2 = b * q
    n_dict = pb.dict_keys.shape[0]

    valid = (
        accepted[:, None] & pb.write_mask & (pb.write_begin < pb.write_end)
    )  # [B, Q]
    inf_rank = jnp.int32(n_dict - 1)  # last dictionary row is always +inf
    wr = jnp.where(valid, pb.write_begin, inf_rank).reshape(e2)
    er = jnp.where(valid, pb.write_end, inf_rank).reshape(e2)
    new_ranks = jnp.concatenate([wr, er])  # [n2]
    new_delta = jnp.concatenate(
        [valid.reshape(e2).astype(jnp.int32), -valid.reshape(e2).astype(jnp.int32)]
    )
    if rs is None:
        rs = searchsorted_words_fp(state.keys, pb.dict_keys, side="right")
    cross_rank = rs[new_ranks]
    seg = cross_rank - 1
    new_oldv = state.versions[jnp.maximum(seg, 0)]

    # Rank order IS key order with identical ties, so the stable 1-word
    # sort yields the same permutation as sort_keys_with_payload; the
    # other columns ride as one gathered index payload.
    idx = jnp.arange(2 * e2, dtype=jnp.int32)
    sranks, sidx = sort_ranks_with_payload(new_ranks, idx)
    return _paint_tail(
        state,
        pb.dict_keys[sranks],
        new_delta[sidx],
        new_oldv[sidx],
        cross_rank[sidx],
        commit_version,
        new_oldest,
    )


def pack_loser_mask(losers: jax.Array) -> jax.Array:
    """bool [B, R] -> uint32 [B] bitset (bit c = coalesced read slot c
    lost) when R <= 32 — an 8x cut of the report path's device→host
    transfer; wider R (no production config) stays bool."""
    b, r = losers.shape
    if r > 32:
        return losers
    lanes = jnp.arange(r, dtype=jnp.uint32)
    return (losers.astype(jnp.uint32) << lanes[None, :]).sum(
        axis=1, dtype=jnp.uint32
    )


def resolve_batch_packed(
    state: ConflictState,
    pb: PackedBatch,
    commit_version: jax.Array,
    new_oldest: jax.Array,
    report: bool = False,
    wave: bool = False,
):
    """resolve_batch over a PackedBatch — identical verdicts, rank-space
    data movement. With `report`, the loser mask returns uint32-packed;
    with `wave`, the wave levels ride after the verdicts."""
    floor, too_old = too_old_mask_packed(state, pb, new_oldest)
    rs, ls = _dict_history_search(state.keys, pb.dict_keys)
    hist_mask = _history_conflict_ranges_packed(state, pb, rs, ls)
    hist_conflict = jnp.any(hist_mask, axis=1)
    base = pb.txn_mask & ~too_old & ~hist_conflict
    ranks = endpoint_ranks_live_packed(pb)
    accepted, levels = _accept_or_schedule(base, ranks, wave)
    verdicts = assemble_verdicts(too_old, pb.txn_mask, accepted)
    new_state = _paint_and_compact_packed(
        state, pb, accepted, commit_version, floor, rs
    )
    out = (verdicts, levels) if wave else (verdicts,)
    if report:
        losers = loser_range_mask(hist_mask, ranks, accepted, verdicts)
        return (*out, pack_loser_mask(losers), new_state)
    return (*out, new_state)


def resolve_many_packed(
    state: ConflictState,
    pbs: PackedBatch,  # leading scan axis [k, ...] on every leaf
    commit_versions: jax.Array,
    new_oldests: jax.Array,
    wave: bool = False,
):
    def body(st, xs):
        pb, cv, old = xs
        out = resolve_batch_packed(st, pb, cv, old, wave=wave)
        return out[-1], out[:-1]

    state, stacked = jax.lax.scan(
        body, state, (pbs, commit_versions, new_oldests)
    )
    return (*stacked, state)


def _history_conflict_ranges_hist_packed(
    base: ConflictState, base_st: jax.Array, delta: ConflictState,
    pb: PackedBatch,
    rs_b: jax.Array, ls_b: jax.Array, rs_d: jax.Array, ls_d: jax.Array,
) -> jax.Array:
    """_history_conflict_ranges_hist over the dictionary: base and delta
    are each fingerprint-searched once per unique key."""
    b, r = pb.read_begin.shape
    rbf = pb.read_begin.reshape(-1)
    ref = pb.read_end.reshape(-1)
    newest_b = range_max(
        base_st, jnp.maximum(rs_b[rbf] - 1, 0), ls_b[ref], NEG_VERSION
    )
    lo_d = jnp.maximum(rs_d[rbf] - 1, 0)
    hi_d = ls_d[ref]
    if _RMQ_DESIGN == "blocked":
        dt = block_table(delta.versions, NEG_VERSION)
        newest_d = range_max_blocked(dt, lo_d, hi_d, NEG_VERSION)
    else:
        dt = sparse_table(delta.versions)
        newest_d = range_max(dt, lo_d, hi_d, NEG_VERSION)
    newest = jnp.maximum(newest_b, newest_d).reshape(b, r)
    live = pb.read_mask & (pb.read_begin < pb.read_end)
    return live & (newest > pb.read_version[:, None])


def _history_conflicts_hist_packed(hist: HistState, pb: PackedBatch) -> jax.Array:
    rs_b, ls_b = _dict_history_search(hist.base.keys, pb.dict_keys)
    rs_d, ls_d = _dict_history_search(hist.delta.keys, pb.dict_keys)
    return jnp.any(
        _history_conflict_ranges_hist_packed(
            hist.base, hist.base_st, hist.delta, pb, rs_b, ls_b, rs_d, ls_d
        ),
        axis=1,
    )


def resolve_batch_hist_packed(
    hist: HistState,
    pb: PackedBatch,
    commit_version: jax.Array,
    new_oldest: jax.Array,
    report: bool = False,
    wave: bool = False,
):
    """resolve_batch_hist over a PackedBatch. The delta's right-side
    dictionary search doubles as the paint pass's cross-rank (both run
    against the post-merge delta)."""
    floor, too_old = too_old_mask_packed(hist.delta, pb, new_oldest)
    demand = 2 * jnp.sum(
        (pb.write_mask & (pb.write_begin < pb.write_end)).astype(jnp.int32)
    )
    hist = _maybe_merge(hist, demand, floor)
    base_h, base_st, delta = hist
    rs_b, ls_b = _dict_history_search(base_h.keys, pb.dict_keys)
    rs_d, ls_d = _dict_history_search(delta.keys, pb.dict_keys)
    hist_mask = _history_conflict_ranges_hist_packed(
        base_h, base_st, delta, pb, rs_b, ls_b, rs_d, ls_d
    )
    hist_conflict = jnp.any(hist_mask, axis=1)
    ok = pb.txn_mask & ~too_old & ~hist_conflict
    ranks = endpoint_ranks_live_packed(pb)
    accepted, levels = _accept_or_schedule(ok, ranks, wave)
    verdicts = assemble_verdicts(too_old, pb.txn_mask, accepted)
    delta = _paint_and_compact_packed(
        delta, pb, accepted, commit_version, floor, rs_d
    )
    new_hist = HistState(base_h, base_st, delta)
    out = (verdicts, levels) if wave else (verdicts,)
    if report:
        losers = loser_range_mask(hist_mask, ranks, accepted, verdicts)
        return (*out, pack_loser_mask(losers), new_hist)
    return (*out, new_hist)


def resolve_many_hist_packed(
    hist: HistState,
    pbs: PackedBatch,
    commit_versions: jax.Array,
    new_oldests: jax.Array,
    wave: bool = False,
):
    def body(h, xs):
        pb, cv, old = xs
        out = resolve_batch_hist_packed(h, pb, cv, old, wave=wave)
        return out[-1], out[:-1]

    hist, stacked = jax.lax.scan(
        body, hist, (pbs, commit_versions, new_oldests)
    )
    return (*stacked, hist)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_packed_jit(state, pb, commit_version, new_oldest):
    return resolve_batch_packed(state, pb, commit_version, new_oldest)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_report_packed_jit(state, pb, commit_version, new_oldest):
    return resolve_batch_packed(state, pb, commit_version, new_oldest,
                                report=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_many_packed_jit(state, pbs, commit_versions, new_oldests):
    return resolve_many_packed(state, pbs, commit_versions, new_oldests)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_hist_packed_jit(hist, pb, commit_version, new_oldest):
    return resolve_batch_hist_packed(hist, pb, commit_version, new_oldest)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_report_hist_packed_jit(hist, pb, commit_version, new_oldest):
    return resolve_batch_hist_packed(hist, pb, commit_version, new_oldest,
                                     report=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_many_hist_packed_jit(hist, pbs, commit_versions, new_oldests):
    return resolve_many_hist_packed(hist, pbs, commit_versions, new_oldests)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_hist_jit(hist, batch, commit_version, new_oldest):
    return resolve_batch_hist(hist, batch, commit_version, new_oldest)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_report_hist_jit(hist, batch, commit_version, new_oldest):
    return resolve_batch_hist(hist, batch, commit_version, new_oldest,
                              report=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_report_jit(state, batch, commit_version, new_oldest):
    return resolve_batch(state, batch, commit_version, new_oldest,
                         report=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_many_hist_jit(hist, batches, commit_versions, new_oldests):
    return resolve_many_hist(hist, batches, commit_versions, new_oldests)


@functools.partial(jax.jit, donate_argnums=(0,))
def _advance_hist_jit(hist, commit_version, new_oldest):
    return (
        jnp.zeros((1,), jnp.int8),
        advance_hist(hist, commit_version, new_oldest),
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def _rebase_hist_jit(hist, delta_v):
    base = rebase(hist.base, delta_v)
    return HistState(base, sparse_table(base.versions),
                     rebase(hist.delta, delta_v))


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_jit(state, batch, commit_version, new_oldest):
    return resolve_batch(state, batch, commit_version, new_oldest)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_many_jit(state, batches, commit_versions, new_oldests):
    return resolve_many(state, batches, commit_versions, new_oldests)


@functools.partial(jax.jit, donate_argnums=(0,))
def _rebase_jit(state, delta):
    return rebase(state, delta)


# -- wave-commit entry points (FDB_TPU_WAVE_COMMIT=1 engines) ---------------
# Same four engine configurations as above; every return shape gains the
# int32 [B] (or [k, B]) wave levels right after the verdicts.


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_wave_jit(state, batch, commit_version, new_oldest):
    return resolve_batch(state, batch, commit_version, new_oldest, wave=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_report_wave_jit(state, batch, commit_version, new_oldest):
    return resolve_batch(state, batch, commit_version, new_oldest,
                         report=True, wave=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_many_wave_jit(state, batches, commit_versions, new_oldests):
    return resolve_many(state, batches, commit_versions, new_oldests,
                        wave=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_hist_wave_jit(hist, batch, commit_version, new_oldest):
    return resolve_batch_hist(hist, batch, commit_version, new_oldest,
                              wave=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_report_hist_wave_jit(hist, batch, commit_version, new_oldest):
    return resolve_batch_hist(hist, batch, commit_version, new_oldest,
                              report=True, wave=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_many_hist_wave_jit(hist, batches, commit_versions, new_oldests):
    return resolve_many_hist(hist, batches, commit_versions, new_oldests,
                             wave=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_packed_wave_jit(state, pb, commit_version, new_oldest):
    return resolve_batch_packed(state, pb, commit_version, new_oldest,
                                wave=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_report_packed_wave_jit(state, pb, commit_version, new_oldest):
    return resolve_batch_packed(state, pb, commit_version, new_oldest,
                                report=True, wave=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_many_packed_wave_jit(state, pbs, commit_versions, new_oldests):
    return resolve_many_packed(state, pbs, commit_versions, new_oldests,
                               wave=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_hist_packed_wave_jit(hist, pb, commit_version, new_oldest):
    return resolve_batch_hist_packed(hist, pb, commit_version, new_oldest,
                                     wave=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_report_hist_packed_wave_jit(hist, pb, commit_version,
                                         new_oldest):
    return resolve_batch_hist_packed(hist, pb, commit_version, new_oldest,
                                     report=True, wave=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_many_hist_packed_wave_jit(hist, pbs, commit_versions,
                                       new_oldests):
    return resolve_many_hist_packed(hist, pbs, commit_versions, new_oldests,
                                    wave=True)


# ---------------------------------------------------------------------------
# Resident kernel (FDB_TPU_RESIDENT=1, requires FDB_TPU_PACKED=1): the
# endpoint-key dictionary and the MVCC history persist in device memory
# across dispatches. The history is stored in RANK SPACE — a width-1
# ConflictState/HistState whose "key" rows are int32 ranks into the
# resident dictionary (INT32_MAX = the +inf sentinel, exactly the role the
# all-inf row plays at full width) — so ALL of the step-function machinery
# above (_paint_tail, _dedup_compact, _merge_delta, _maybe_merge, rebase,
# advance_hist) is reused verbatim at W=1, and per-dispatch device work
# never touches a full-width key except the (usually tiny) delta merge.
# ---------------------------------------------------------------------------


class RankBatch(NamedTuple):
    """One padded resolver batch in RESIDENT rank space: every endpoint is
    an int32 rank into the resident dictionary (host-computed against the
    post-merge mirror — see conflict_set._ResidentMirror), INT32_MAX for
    masked/padding slots. Field names match PackedBatch minus dict_keys so
    too_old_mask_packed / endpoint_ranks_live_packed apply unchanged.

    ``paint_src`` is the HOST-precomputed stable argsort of the write
    endpoints [wb..., we...] — the resident paint's sort permutation. It
    cannot depend on device-side acceptance because rejected writes ride
    the merge as delta-0 boundaries (version-preserving no-ops the
    compaction provably erases), so the device paint is pure gathers: the
    27-MB-modeled per-batch sort network disappears. Rank clipping (the
    mesh shard clamp) is monotone, so the same permutation stays sorted
    for every shard's clipped view."""

    read_begin: jax.Array  # int32 [B, R] resident ranks
    read_end: jax.Array  # int32 [B, R]
    read_mask: jax.Array  # bool [B, R]
    write_begin: jax.Array  # int32 [B, Q]
    write_end: jax.Array  # int32 [B, Q]
    write_mask: jax.Array  # bool [B, Q]
    read_version: jax.Array  # int32 [B] (relative)
    txn_mask: jax.Array  # bool [B]
    paint_src: jax.Array  # int32 [2·B·Q] stable argsort of write endpoints


class ResidentBatch(NamedTuple):
    """A RankBatch plus its dictionary DELTA: the sorted never-before-seen
    endpoint keys of this dispatch, +inf padded to the engine's static
    delta width. On the window path the ranks carry a leading [k] scan
    axis while the delta does NOT — one merge serves the whole window."""

    delta_keys: jax.Array  # int32 [M, W] sorted new keys, +inf padded
    ranks: RankBatch


class ResState(NamedTuple):
    """Device-resident dictionary + rank-space history (+ shard bounds).

    ``shard_lo``/``shard_hi`` are the mesh engine's per-shard keyspace
    bounds AS RANKS (hi = INT32_MAX for the last shard's +inf) — kept in
    device state, not per-batch arguments, because a dictionary insert
    shifts them exactly like it shifts history ranks. Single-chip engines
    carry the degenerate [1] bounds (0, INT32_MAX) and never read them."""

    dict_keys: jax.Array  # int32 [D + 1, W] sorted resident keys, +inf padded
    n_keys: jax.Array  # int32 — live resident key count
    hist: ConflictState | HistState  # width-1 rank-space history
    shard_lo: jax.Array  # int32 [S] rank bounds (mesh); [1] dummy otherwise
    shard_hi: jax.Array


_RANK_MIN = np.zeros(1, np.int32)  # width-1 "min key": rank 0 (the min key)


def init_res(
    dict_rows, dict_capacity: int, capacity: int,
    delta_capacity: int | None = None,
    shard_lo=None, shard_hi=None,
) -> ResState:
    """dict_rows: host-built initial dictionary [n0, W] (sorted; row 0 is
    the packed b""). delta_capacity selects the two-level window history
    (None = flat). shard_lo/hi: initial rank bounds ([1] defaults)."""
    n0, w = dict_rows.shape
    dict_keys = jnp.full((dict_capacity + 1, w), INT32_MAX, jnp.int32)
    dict_keys = dict_keys.at[:n0].set(jnp.asarray(dict_rows, jnp.int32))
    if delta_capacity is None:
        hist: ConflictState | HistState = init_state(capacity, 1, _RANK_MIN)
    else:
        hist = init_hist(capacity, 1, _RANK_MIN, delta_capacity)
    if shard_lo is None:
        shard_lo = np.zeros(1, np.int32)
        shard_hi = np.full(1, INT32_MAX, np.int32)
    return ResState(
        dict_keys=dict_keys,
        n_keys=jnp.int32(n0),
        hist=hist,
        shard_lo=jnp.asarray(shard_lo, jnp.int32),
        shard_hi=jnp.asarray(shard_hi, jnp.int32),
    )


def _dict_insert(dict_keys, n_keys, delta_keys):
    """Merge M sorted-unique NEW keys into the resident dictionary.

    Returns (new_dict_keys, new_n_keys, shift) where shift[r] = how many
    inserted keys precede old rank r — the rank-rebase table: an existing
    rank r becomes r + shift[r]. Same scatter-free merge-path construction
    as _paint_tail; the host guarantees fit (n_keys + m <= capacity), and
    real delta rows are disjoint from resident keys by construction."""
    d1, w = dict_keys.shape
    m_cap = delta_keys.shape[0]
    # 'left' of dict rows into the delta: for a real dict key, the count
    # of real delta keys strictly below it (delta +inf padding never
    # counts); for dict +inf padding rows, exactly m — both correct.
    shift = searchsorted_words_fp(delta_keys, dict_keys, side="left")
    # 'right' of delta rows into the dict: real delta keys (distinct from
    # every resident key) count the resident keys below; delta +inf rows
    # count ALL d1 rows, pushing their merge position past the output
    # window so only real rows ever land.
    cross = searchsorted_words_fp(dict_keys, delta_keys, side="right")
    pos_d = jnp.arange(m_cap, dtype=jnp.int32) + cross
    idx = jnp.arange(d1, dtype=jnp.int32)
    cnt_le = jnp.searchsorted(pos_d, idx, side="right").astype(jnp.int32)
    k_new = jnp.maximum(cnt_le - 1, 0)
    from_new = (cnt_le > 0) & (pos_d[k_new] == idx)
    old_idx = jnp.clip(idx - cnt_le, 0, d1 - 1)
    out = jnp.where(from_new[:, None], delta_keys[k_new], dict_keys[old_idx])
    m = jnp.sum(
        (~jnp.all(delta_keys == INT32_MAX, axis=-1)).astype(jnp.int32)
    )
    return out, n_keys + m, shift


def _shift_rank_rows(keys: jax.Array, shift: jax.Array) -> jax.Array:
    """Rank-rebase a width-1 history key array ([..., C, 1]): each live
    rank r becomes r + shift[r]; the INT32_MAX sentinel is invariant."""
    r = keys[..., 0]
    d1 = shift.shape[0]
    shifted = r + shift[jnp.clip(r, 0, d1 - 1)]
    return jnp.where(r == INT32_MAX, r, shifted)[..., None]


def _shift_rank_vec(v: jax.Array, shift: jax.Array) -> jax.Array:
    """Rank-rebase a bare rank vector (shard bounds)."""
    d1 = shift.shape[0]
    shifted = v + shift[jnp.clip(v, 0, d1 - 1)]
    return jnp.where(v == INT32_MAX, v, shifted)


def _shift_hist(hist, shift):
    if isinstance(hist, HistState):
        return HistState(
            hist.base._replace(keys=_shift_rank_rows(hist.base.keys, shift)),
            hist.base_st,  # versions untouched — the RMQ table survives
            hist.delta._replace(keys=_shift_rank_rows(hist.delta.keys, shift)),
        )
    return hist._replace(keys=_shift_rank_rows(hist.keys, shift))


def apply_delta(res: ResState, delta_keys: jax.Array) -> ResState:
    """Fold this dispatch's key delta into the resident state: insert the
    new keys into the dictionary and rank-rebase the history + shard
    bounds past the inserted positions. The empty-delta steady state (high
    hit rate) skips the whole merge via lax.cond."""
    any_new = jnp.any(~jnp.all(delta_keys == INT32_MAX, axis=-1))

    def do(res):
        nd, nn, shift = _dict_insert(res.dict_keys, res.n_keys, delta_keys)
        return ResState(
            dict_keys=nd,
            n_keys=nn,
            hist=_shift_hist(res.hist, shift),
            shard_lo=_shift_rank_vec(res.shard_lo, shift),
            shard_hi=_shift_rank_vec(res.shard_hi, shift),
        )

    return jax.lax.cond(any_new, do, lambda r: r, res)


def _dict_evict(dict_keys, n_keys, evict_ranks):
    """Remove E sorted-unique resident ranks from the dictionary — the
    exact inverse of _dict_insert (the tiered engine's DEMOTION delta).

    evict_ranks: int32 [E] strictly increasing ranks, INT32_MAX padded.
    Returns (new_dict_keys, new_n_keys, shift) where shift[r] <= 0 is the
    rank-rebase table for SURVIVING ranks (r becomes r + shift[r]). The
    host guarantees no evicted rank is referenced by device history or
    shard bounds (exact-liveness selection), so the off-by-one a demoted
    rank itself would take through the table is never observed. Same
    scatter-free merge-path construction as _dict_insert: kept row j
    reads source j + t where t = |{i : e_i - i <= j}| (e_i - i is
    nondecreasing for strictly increasing e_i)."""
    d1, _w = dict_keys.shape
    e_cap = evict_ranks.shape[0]
    real = evict_ranks != INT32_MAX
    n_ev = jnp.sum(real.astype(jnp.int32))
    i = jnp.arange(e_cap, dtype=jnp.int32)
    adj = jnp.where(real, evict_ranks - i, INT32_MAX)
    j = jnp.arange(d1, dtype=jnp.int32)
    t = jnp.searchsorted(adj, j, side="right").astype(jnp.int32)
    out = dict_keys[jnp.clip(j + t, 0, d1 - 1)]
    new_n = n_keys - n_ev
    out = jnp.where((j < new_n)[:, None], out, INT32_MAX)
    # Surviving rank r has no evicted rank equal to it, so the <= count
    # IS the strictly-below count — negate it for the shared shifters.
    shift = -jnp.searchsorted(evict_ranks, j, side="right").astype(jnp.int32)
    return out, new_n, shift


def apply_evict(res: ResState, evict_ranks: jax.Array) -> ResState:
    """Fold a demotion delta into the resident state: remove the evicted
    ranks from the dictionary and rank-rebase the history + shard bounds
    DOWN past the removed positions — the mirror image of apply_delta.
    The empty-delta case (no victims survived selection) skips the
    compaction via lax.cond, like apply_delta's steady state."""
    any_ev = jnp.any(evict_ranks != INT32_MAX)

    def do(res):
        nd, nn, shift = _dict_evict(res.dict_keys, res.n_keys, evict_ranks)
        return ResState(
            dict_keys=nd,
            n_keys=nn,
            hist=_shift_hist(res.hist, shift),
            shard_lo=_shift_rank_vec(res.shard_lo, shift),
            shard_hi=_shift_rank_vec(res.shard_hi, shift),
        )

    return jax.lax.cond(any_ev, do, lambda r: r, res)


def apply_dict_remap(res: ResState, new_dict, new_n, remap) -> ResState:
    """Full-repack tail: swap in the host-rebuilt dictionary and remap
    every device-held rank through ``remap`` (old rank -> new rank; exact
    for every LIVE history rank — the host includes all live keys in the
    new dictionary, see conflict_set._execute_repack)."""

    def rr(keys):
        r = keys[..., 0]
        m = remap[jnp.clip(r, 0, remap.shape[0] - 1)]
        return jnp.where(r == INT32_MAX, r, m)[..., None]

    hist = res.hist
    if isinstance(hist, HistState):
        hist = HistState(
            hist.base._replace(keys=rr(hist.base.keys)),
            hist.base_st,
            hist.delta._replace(keys=rr(hist.delta.keys)),
        )
    else:
        hist = hist._replace(keys=rr(hist.keys))
    rv = lambda v: jnp.where(  # noqa: E731 — tiny local lambda
        v == INT32_MAX, v, remap[jnp.clip(v, 0, remap.shape[0] - 1)]
    )
    return ResState(
        dict_keys=jnp.asarray(new_dict, jnp.int32),
        n_keys=jnp.asarray(new_n, jnp.int32),
        hist=hist,
        shard_lo=rv(res.shard_lo),
        shard_hi=rv(res.shard_hi),
    )


def clip_ranks(rbk: RankBatch, lo, hi) -> RankBatch:
    """clip_batch in rank space: restrict every range to the shard's rank
    interval [lo, hi). Scalar int32 compares — out-of-shard ranges fall
    out of their masks via rb' >= re'. Both endpoints take the SAME
    two-sided clamp: one monotone map over all endpoints, so the host's
    paint permutation (RankBatch.paint_src, computed on unclipped ranks)
    stays sorted for the clipped view — a one-sided max/min pair would
    order a beyond-shard begin after a clamped +inf end and corrupt the
    gather-only paint."""
    clamp = lambda v: jnp.clip(v, lo, hi)  # noqa: E731
    rb = clamp(rbk.read_begin)
    re_ = clamp(rbk.read_end)
    wb = clamp(rbk.write_begin)
    we = clamp(rbk.write_end)
    return rbk._replace(
        read_begin=rb, read_end=re_, read_mask=rbk.read_mask & (rb < re_),
        write_begin=wb, write_end=we, write_mask=rbk.write_mask & (wb < we),
    )


def _rank_probe(keys: jax.Array, q: jax.Array, side: str) -> jax.Array:
    """searchsorted of bare int32 ranks into a width-1 history key array —
    the resident probe: one binary search of 4-byte gathers, no
    fingerprint cascade needed (ranks ARE the fingerprint)."""
    return searchsorted_words(keys, q[..., None], side=side)


def _history_conflict_ranges_res(state: ConflictState, rbk: RankBatch) -> jax.Array:
    """_history_conflict_ranges over the rank-space history: per-slot
    probes (the host already deduped the rank space; a probe step gathers
    4 bytes, so per-slot beats the probe-per-unique-key indirection)."""
    b, r = rbk.read_begin.shape
    lo = _rank_probe(state.keys, rbk.read_begin.reshape(-1), "right") - 1
    hi = _rank_probe(state.keys, rbk.read_end.reshape(-1), "left")
    if _RMQ_DESIGN == "blocked":
        bt = block_table(state.versions, NEG_VERSION)
        newest = range_max_blocked(
            bt, jnp.maximum(lo, 0), hi, NEG_VERSION
        ).reshape(b, r)
    else:
        st = sparse_table(state.versions)
        newest = range_max(
            st, jnp.maximum(lo, 0), hi, NEG_VERSION
        ).reshape(b, r)
    live = rbk.read_mask & (rbk.read_begin < rbk.read_end)
    return live & (newest > rbk.read_version[:, None])


def _history_conflicts_res(state: ConflictState, rbk: RankBatch) -> jax.Array:
    return jnp.any(_history_conflict_ranges_res(state, rbk), axis=1)


def _history_conflict_ranges_hist_res(
    base: ConflictState, base_st: jax.Array, delta: ConflictState,
    rbk: RankBatch,
) -> jax.Array:
    b, r = rbk.read_begin.shape
    qb = rbk.read_begin.reshape(-1)
    qe = rbk.read_end.reshape(-1)
    newest_b = range_max(
        base_st,
        jnp.maximum(_rank_probe(base.keys, qb, "right") - 1, 0),
        _rank_probe(base.keys, qe, "left"),
        NEG_VERSION,
    )
    lo_d = jnp.maximum(_rank_probe(delta.keys, qb, "right") - 1, 0)
    hi_d = _rank_probe(delta.keys, qe, "left")
    if _RMQ_DESIGN == "blocked":
        dt = block_table(delta.versions, NEG_VERSION)
        newest_d = range_max_blocked(dt, lo_d, hi_d, NEG_VERSION)
    else:
        dt = sparse_table(delta.versions)
        newest_d = range_max(dt, lo_d, hi_d, NEG_VERSION)
    newest = jnp.maximum(newest_b, newest_d).reshape(b, r)
    live = rbk.read_mask & (rbk.read_begin < rbk.read_end)
    return live & (newest > rbk.read_version[:, None])


def _history_conflicts_hist_res(hist: HistState, rbk: RankBatch) -> jax.Array:
    return jnp.any(
        _history_conflict_ranges_hist_res(
            hist.base, hist.base_st, hist.delta, rbk
        ),
        axis=1,
    )


def _paint_and_compact_res(
    state: ConflictState,
    rbk: RankBatch,
    accepted: jax.Array,
    commit_version: jax.Array,
    new_oldest: jax.Array,
) -> ConflictState:
    """_paint_and_compact in rank space, WITHOUT the device endpoint sort.

    The host ships the stable argsort of the write endpoints
    (rbk.paint_src) — legal because the permutation must not depend on
    device-side acceptance: a rejected (or shard-clipped-empty) write's
    endpoints enter the merge with coverage delta 0 and their containing
    segment's version, i.e. boundaries that do not change the step
    function, which _dedup_compact erases exactly like the old +inf
    parking did. The paint is therefore pure gathers over rank rows; full
    keys never materialize again until a repack."""
    b, q = rbk.write_begin.shape
    e2 = b * q
    valid = (
        accepted[:, None] & rbk.write_mask & (rbk.write_begin < rbk.write_end)
    )
    wr = rbk.write_begin.reshape(e2)
    er = rbk.write_end.reshape(e2)
    new_ranks = jnp.concatenate([wr, er])
    new_delta = jnp.concatenate(
        [valid.reshape(e2).astype(jnp.int32), -valid.reshape(e2).astype(jnp.int32)]
    )
    cross_rank = _rank_probe(state.keys, new_ranks, "right")
    seg = cross_rank - 1
    new_oldv = state.versions[jnp.maximum(seg, 0)]
    sidx = rbk.paint_src
    return _paint_tail(
        state,
        new_ranks[sidx][:, None],
        new_delta[sidx],
        new_oldv[sidx],
        cross_rank[sidx],
        commit_version,
        new_oldest,
    )


def _resolve_core_res(hist, rbk: RankBatch, commit_version, new_oldest,
                      report: bool = False, wave: bool = False):
    """Shared resident resolve body over either history design. Returns
    (verdicts[, levels][, losers], new_hist)."""
    two_level = isinstance(hist, HistState)
    if two_level:
        floor, too_old = too_old_mask_packed(hist.delta, rbk, new_oldest)
        demand = 2 * jnp.sum(
            (rbk.write_mask & (rbk.write_begin < rbk.write_end)).astype(
                jnp.int32
            )
        )
        hist = _maybe_merge(hist, demand, floor)
        base_h, base_st, delta = hist
        hist_mask = _history_conflict_ranges_hist_res(
            base_h, base_st, delta, rbk
        )
    else:
        floor, too_old = too_old_mask_packed(hist, rbk, new_oldest)
        hist_mask = _history_conflict_ranges_res(hist, rbk)
    hist_conflict = jnp.any(hist_mask, axis=1)
    base = rbk.txn_mask & ~too_old & ~hist_conflict
    ranks = endpoint_ranks_live_packed(rbk)
    accepted, levels = _accept_or_schedule(base, ranks, wave)
    verdicts = assemble_verdicts(too_old, rbk.txn_mask, accepted)
    if two_level:
        delta = _paint_and_compact_res(
            delta, rbk, accepted, commit_version, floor
        )
        new_hist: ConflictState | HistState = HistState(base_h, base_st, delta)
    else:
        new_hist = _paint_and_compact_res(
            hist, rbk, accepted, commit_version, floor
        )
    out = (verdicts, levels) if wave else (verdicts,)
    if report:
        losers = loser_range_mask(hist_mask, ranks, accepted, verdicts)
        return (*out, pack_loser_mask(losers), new_hist)
    return (*out, new_hist)


def resolve_batch_res(res: ResState, rb: ResidentBatch, commit_version,
                      new_oldest, report: bool = False, wave: bool = False):
    """resolve_batch over the resident state: delta merge + rank rebase,
    then the rank-space resolve core. Identical verdicts to the packed
    per-dispatch-dictionary path (oracle- and A/B-parity tested)."""
    res = apply_delta(res, rb.delta_keys)
    out = _resolve_core_res(res.hist, rb.ranks, commit_version, new_oldest,
                            report=report, wave=wave)
    return (*out[:-1], res._replace(hist=out[-1]))


def resolve_many_res(res: ResState, rb: ResidentBatch, commit_versions,
                     new_oldests, wave: bool = False):
    """Window path: ONE delta merge + rank rebase for the whole window
    (the delta carries no scan axis), then a pure rank-space scan with no
    per-step dictionary work at all."""
    res = apply_delta(res, rb.delta_keys)

    def body(h, xs):
        rbk, cv, old = xs
        out = _resolve_core_res(h, rbk, cv, old, wave=wave)
        return out[-1], out[:-1]

    hist, stacked = jax.lax.scan(
        body, res.hist, (rb.ranks, commit_versions, new_oldests)
    )
    return (*stacked, res._replace(hist=hist))


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_res_jit(res, rb, commit_version, new_oldest):
    return resolve_batch_res(res, rb, commit_version, new_oldest)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_report_res_jit(res, rb, commit_version, new_oldest):
    return resolve_batch_res(res, rb, commit_version, new_oldest, report=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_many_res_jit(res, rb, commit_versions, new_oldests):
    return resolve_many_res(res, rb, commit_versions, new_oldests)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_res_wave_jit(res, rb, commit_version, new_oldest):
    return resolve_batch_res(res, rb, commit_version, new_oldest, wave=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_report_res_wave_jit(res, rb, commit_version, new_oldest):
    return resolve_batch_res(res, rb, commit_version, new_oldest,
                             report=True, wave=True)


@functools.partial(jax.jit, donate_argnums=(0,))
def _resolve_many_res_wave_jit(res, rb, commit_versions, new_oldests):
    return resolve_many_res(res, rb, commit_versions, new_oldests, wave=True)


# The hist/flat distinction is carried by the ResState PYTREE (res.hist is
# a HistState or a ConflictState), so the _hist entry names alias the same
# functions — jit specializes per pytree structure. The aliases keep the
# engine's suffix-composition naming total.
_resolve_hist_res_jit = _resolve_res_jit
_resolve_report_hist_res_jit = _resolve_report_res_jit
_resolve_many_hist_res_jit = _resolve_many_res_jit
_resolve_hist_res_wave_jit = _resolve_res_wave_jit
_resolve_report_hist_res_wave_jit = _resolve_report_res_wave_jit
_resolve_many_hist_res_wave_jit = _resolve_many_res_wave_jit


@functools.partial(jax.jit, donate_argnums=(0,))
def _rebase_res_jit(res, delta_v):
    hist = res.hist
    if isinstance(hist, HistState):
        base = rebase(hist.base, delta_v)
        # base versions shifted — the prebuilt RMQ table must follow.
        hist = HistState(base, sparse_table(base.versions),
                         rebase(hist.delta, delta_v))
    else:
        hist = rebase(hist, delta_v)
    return res._replace(hist=hist)


@functools.partial(jax.jit, donate_argnums=(0,))
def _advance_hist_res_jit(res, commit_version, new_oldest):
    return (
        jnp.zeros((1,), jnp.int8),
        res._replace(hist=advance_hist(res.hist, commit_version, new_oldest)),
    )


@functools.partial(jax.jit, donate_argnums=(0,))
def _repack_res_jit(res, new_dict, new_n, remap):
    return apply_dict_remap(res, new_dict, new_n, remap)


@functools.partial(jax.jit, donate_argnums=(0,))
def _evict_res_jit(res, evict_ranks):
    """Demotion delta for the tiered dictionary: drop cold ranks from the
    hot tier and rebase ranks down. Elementwise over history rows like
    _rebase_res_jit / _repack_res_jit, so the mesh engine runs it on the
    per-device state under jit unchanged (dict replicated, hist sharded)."""
    return apply_evict(res, evict_ranks)


# ---------------------------------------------------------------------------
# Two-phase wave entry points (role-level global wave commit): a sharded
# resolver deployment splits one resolve into EDGES (history gate + this
# shard's clipped predecessor bitsets; no paint) and APPLY (level the
# OR-reduced GLOBAL graph + paint the globally accepted writes). The
# commit proxy is the reduction point between the phases
# (core/wavemesh.combine_edges); every shard levels the identical graph,
# so every shard reports the identical (wave, index) schedule. The mesh
# ShardedConflictSet performs the same exchange as an on-device
# all_gather inside one program and never needs these.
# ---------------------------------------------------------------------------


def wave_edges_batch(state: ConflictState, batch: BatchTensors, new_oldest):
    """(too_old [B], hist_conflict [B], pred uint32 [BP, BP/32]): the
    phase-1 body — gate verdicts for THIS shard's clipped view plus its
    clipped predecessor matrix. Reads the history, never paints it."""
    _floor, too_old = too_old_mask(state, batch, new_oldest)
    hist_conflict = _history_conflicts(state, batch)
    base = batch.txn_mask & ~too_old & ~hist_conflict
    p = wave_pred_matrix(base, endpoint_ranks_live(batch))
    return too_old, hist_conflict, p


def wave_edges_batch_hist(hist: HistState, batch: BatchTensors, new_oldest):
    """wave_edges_batch over the two-level history. No merge here — the
    probe against base+delta is merge-invariant (pointwise max), and the
    capacity merge runs in the apply phase, just before the paint that
    needs the room."""
    _floor, too_old = too_old_mask(hist.delta, batch, new_oldest)
    hist_conflict = _history_conflicts_hist(
        hist.base, hist.base_st, hist.delta, batch
    )
    base = batch.txn_mask & ~too_old & ~hist_conflict
    p = wave_pred_matrix(base, endpoint_ranks_live(batch))
    return too_old, hist_conflict, p


def wave_edges_batch_packed(state: ConflictState, pb: PackedBatch, new_oldest):
    _floor, too_old = too_old_mask_packed(state, pb, new_oldest)
    hist_conflict = _history_conflicts_packed(state, pb)
    base = pb.txn_mask & ~too_old & ~hist_conflict
    p = wave_pred_matrix(base, endpoint_ranks_live_packed(pb))
    return too_old, hist_conflict, p


def wave_edges_batch_hist_packed(hist: HistState, pb: PackedBatch, new_oldest):
    _floor, too_old = too_old_mask_packed(hist.delta, pb, new_oldest)
    hist_conflict = _history_conflicts_hist_packed(hist, pb)
    base = pb.txn_mask & ~too_old & ~hist_conflict
    p = wave_pred_matrix(base, endpoint_ranks_live_packed(pb))
    return too_old, hist_conflict, p


def wave_edges_res(res: ResState, rb: ResidentBatch, new_oldest):
    """Resident phase-1: the dictionary delta merges HERE (the host
    packed ranks against the post-merge mirror), so the returned state
    carries the merged dictionary and the apply phase must not re-merge.
    History is still unpainted."""
    res = apply_delta(res, rb.delta_keys)
    hist = res.hist
    if isinstance(hist, HistState):
        _floor, too_old = too_old_mask_packed(hist.delta, rb.ranks, new_oldest)
        hist_conflict = _history_conflicts_hist_res(hist, rb.ranks)
    else:
        _floor, too_old = too_old_mask_packed(hist, rb.ranks, new_oldest)
        hist_conflict = _history_conflicts_res(hist, rb.ranks)
    base = rb.ranks.txn_mask & ~too_old & ~hist_conflict
    p = wave_pred_matrix(base, endpoint_ranks_live_packed(rb.ranks))
    return too_old, hist_conflict, p, res


def wave_apply_batch(
    state: ConflictState, batch: BatchTensors, cand, p, commit_version,
    new_oldest,
):
    """(levels int32 [B], new_state): level the GLOBAL graph, paint the
    globally accepted writes. ``cand``/``p`` are the combined candidate
    mask and OR-reduced predecessor matrix — identical on every shard,
    so the returned schedule is identical on every shard."""
    floor = jnp.maximum(state.oldest, new_oldest)
    accepted, levels = wave_level_from_graph(cand, p)
    new_state = _paint_and_compact(state, batch, accepted, commit_version,
                                   floor)
    return levels, new_state


def wave_apply_batch_hist(
    hist: HistState, batch: BatchTensors, cand, p, commit_version, new_oldest,
):
    floor = jnp.maximum(hist.delta.oldest, new_oldest)
    demand = 2 * jnp.sum(
        (batch.write_mask & lex_lt(batch.write_begin, batch.write_end))
        .astype(jnp.int32)
    )
    hist = _maybe_merge(hist, demand, floor)
    base_h, base_st, delta = hist
    accepted, levels = wave_level_from_graph(cand, p)
    delta = _paint_and_compact(delta, batch, accepted, commit_version, floor)
    return levels, HistState(base_h, base_st, delta)


def wave_apply_batch_packed(
    state: ConflictState, pb: PackedBatch, cand, p, commit_version,
    new_oldest,
):
    floor = jnp.maximum(state.oldest, new_oldest)
    accepted, levels = wave_level_from_graph(cand, p)
    new_state = _paint_and_compact_packed(
        state, pb, accepted, commit_version, floor
    )
    return levels, new_state


def wave_apply_batch_hist_packed(
    hist: HistState, pb: PackedBatch, cand, p, commit_version, new_oldest,
):
    floor = jnp.maximum(hist.delta.oldest, new_oldest)
    demand = 2 * jnp.sum(
        (pb.write_mask & (pb.write_begin < pb.write_end)).astype(jnp.int32)
    )
    hist = _maybe_merge(hist, demand, floor)
    base_h, base_st, delta = hist
    accepted, levels = wave_level_from_graph(cand, p)
    delta = _paint_and_compact_packed(
        delta, pb, accepted, commit_version, floor
    )
    return levels, HistState(base_h, base_st, delta)


def wave_apply_res(
    res: ResState, rbk: RankBatch, cand, p, commit_version, new_oldest,
):
    """Resident apply: the dictionary already merged in wave_edges_res,
    so this is pure rank-space level + paint."""
    hist = res.hist
    accepted, levels = wave_level_from_graph(cand, p)
    if isinstance(hist, HistState):
        floor = jnp.maximum(hist.delta.oldest, new_oldest)
        demand = 2 * jnp.sum(
            (rbk.write_mask & (rbk.write_begin < rbk.write_end)).astype(
                jnp.int32
            )
        )
        hist = _maybe_merge(hist, demand, floor)
        base_h, base_st, delta = hist
        delta = _paint_and_compact_res(
            delta, rbk, accepted, commit_version, floor
        )
        new_hist: ConflictState | HistState = HistState(base_h, base_st, delta)
    else:
        floor = jnp.maximum(hist.oldest, new_oldest)
        new_hist = _paint_and_compact_res(
            hist, rbk, accepted, commit_version, floor
        )
    return levels, res._replace(hist=new_hist)


# Edge entries are NOT donated (the apply phase reuses the same state);
# the resident edge entry IS donated (the delta merge replaces the
# state, returned alongside). Apply entries donate like every resolve.
_wave_edges_jit = jax.jit(wave_edges_batch)
_wave_edges_hist_jit = jax.jit(wave_edges_batch_hist)
_wave_edges_packed_jit = jax.jit(wave_edges_batch_packed)
_wave_edges_hist_packed_jit = jax.jit(wave_edges_batch_hist_packed)
_wave_edges_res_jit = jax.jit(wave_edges_res, donate_argnums=(0,))
_wave_edges_hist_res_jit = _wave_edges_res_jit

_wave_apply_jit = jax.jit(wave_apply_batch, donate_argnums=(0,))
_wave_apply_hist_jit = jax.jit(wave_apply_batch_hist, donate_argnums=(0,))
_wave_apply_packed_jit = jax.jit(wave_apply_batch_packed, donate_argnums=(0,))
_wave_apply_hist_packed_jit = jax.jit(
    wave_apply_batch_hist_packed, donate_argnums=(0,)
)
_wave_apply_res_jit = jax.jit(wave_apply_res, donate_argnums=(0,))
_wave_apply_hist_res_jit = _wave_apply_res_jit


# ---------------------------------------------------------------------------
# Per-phase entry points (bench --profile): each phase compiled alone so the
# host can time it with block_until_ready and attribute the batch cost.
# ---------------------------------------------------------------------------


@jax.jit
def _phase_history_jit(state, batch):
    return _history_conflicts(state, batch)


@jax.jit
def _phase_ranks_jit(batch):
    return endpoint_ranks_live(batch)


@jax.jit
def _phase_accept_jit(base, rb, re_, read_live, wb, we, write_live):
    return _block_accept_fused(base, rb, re_, read_live, wb, we, write_live)


@jax.jit  # state NOT donated: profiling replays phases on the same state
def _phase_paint_jit(state, batch, accepted, commit_version, new_oldest):
    return _paint_and_compact(state, batch, accepted, commit_version, new_oldest)


@jax.jit
def _phase_history_hist_jit(hist, batch):
    return _history_conflicts_hist(hist.base, hist.base_st, hist.delta, batch)


@jax.jit
def _phase_paint_hist_jit(hist, batch, accepted, commit_version, new_oldest):
    return _paint_and_compact(hist.delta, batch, accepted, commit_version,
                              new_oldest)


@jax.jit
def _phase_merge_hist_jit(hist, new_oldest):
    """The amortized cost: one delta→base fold + base table rebuild."""
    nb = _merge_delta(hist.base, hist.delta, new_oldest)
    return nb, sparse_table(nb.versions)


@jax.jit
def _phase_history_packed_jit(state, pb):
    return _history_conflicts_packed(state, pb)


@jax.jit
def _phase_ranks_packed_jit(pb):
    """Near-zero by design: the endpoint sort moved into the host packer
    (the deduped dictionary) — timed anyway so the phase breakdown stays
    shape-compatible across the A/B."""
    return endpoint_ranks_live_packed(pb)


@jax.jit
def _phase_history_hist_packed_jit(hist, pb):
    return _history_conflicts_hist_packed(hist, pb)


@jax.jit  # state NOT donated: profiling replays phases on the same state
def _phase_paint_packed_jit(state, pb, accepted, commit_version, new_oldest):
    return _paint_and_compact_packed(state, pb, accepted, commit_version,
                                     new_oldest)


@jax.jit
def _phase_paint_hist_packed_jit(hist, pb, accepted, commit_version,
                                 new_oldest):
    return _paint_and_compact_packed(hist.delta, pb, accepted,
                                     commit_version, new_oldest)


@jax.jit
def _phase_dict_insert_res_jit(res, delta_keys):
    """The resident path's DEVICE-MERGE component (the on-device half of
    what the per-dispatch repack used to do monolithically): one delta
    insert + rank rebase. Its host counterpart — the mirror delta
    extraction — is timed host-side by the profiler as host_pack."""
    return apply_delta(res, delta_keys)


@jax.jit
def _phase_history_res_jit(res, rbk):
    hist = res.hist
    if isinstance(hist, HistState):
        return _history_conflicts_hist_res(hist, rbk)
    return _history_conflicts_res(hist, rbk)


@jax.jit  # state NOT donated: profiling replays phases on the same state
def _phase_paint_res_jit(res, rbk, accepted, commit_version, new_oldest):
    hist = res.hist
    st = hist.delta if isinstance(hist, HistState) else hist
    return _paint_and_compact_res(st, rbk, accepted, commit_version,
                                  new_oldest)


@jax.jit
def _phase_merge_hist_res_jit(res, new_oldest):
    """The amortized two-level fold, rank-space edition."""
    hist = res.hist
    nb = _merge_delta(hist.base, hist.delta, new_oldest)
    return nb, sparse_table(nb.versions)


# ---------------------------------------------------------------------------
# Speculative pipelined resolve (FDB_TPU_SPEC_RESOLVE=1): the host
# dispatches window N+1 against window N's OPTIMISTICALLY painted state
# (the resolve programs above paint accepted-so-far writes in the same
# program that decides them) while N's verdicts are still in flight —
# i.e. unconfirmed by the upper layer (tlog durability, wave apply,
# ratekeeper). The kernel side of the reconcile is three programs:
#
# - _snapshot_jit: fresh device buffers for the pre-window state, taken
#   right before a speculative dispatch. The resolve entry points donate
#   their state argument (argnum 0), so the ACTIVE state never
#   double-buffers; the snapshot is the explicit, depth-bounded HBM cost
#   of speculation (one state copy per in-flight window), and rolling
#   back a mis-speculated window is a host pointer swap.
# - paint-only entry points (_paint{,_many}{_hist}{_packed|_res}_jit):
#   re-advance a rolled-back state with a FORCED accept mask (the
#   speculative accepts ∩ the upper layer's confirmation) — the same
#   merge/GC/paint pipeline as the resolve bodies, minus the verdict
#   decision the upper layer already overrode.
# - the verdict-dependency mask (_spec_mark_rejected / _spec_dep_*): did
#   ANY read of a younger in-flight window overlap a write the older
#   window's confirmation rejected? Rejected writes are painted into a
#   small scratch step function at +inf version; a younger window whose
#   probe comes back clean provably kept its speculative verdicts (its
#   reads never saw a rejected boundary; its floor and intra-window graph
#   are unchanged), so reconcile re-paints it instead of re-resolving.
#   A dirty (or scratch-overflowed) probe sends the whole window through
#   the repair path: re-resolve against the corrected history — only
#   genuinely-conflicted txns flip.
# ---------------------------------------------------------------------------


@jax.jit
def _snapshot_jit(tree):
    """Device copy of an arbitrary state pytree (NOT donated — the live
    state keeps executing; see the speculation ring in conflict_set)."""
    return jax.tree_util.tree_map(jnp.copy, tree)


def paint_batch_packed(state: ConflictState, pb: PackedBatch, accepted,
                       commit_version, new_oldest) -> ConflictState:
    """Paint-only advance: apply a host-forced accept mask to the flat
    packed history — resolve_batch_packed minus the verdict decision."""
    floor = jnp.maximum(state.oldest, new_oldest)
    return _paint_and_compact_packed(state, pb, accepted, commit_version,
                                     floor)


def paint_many_packed(state, pbs, accepted, commit_versions, new_oldests):
    def body(st, xs):
        pb, acc, cv, old = xs
        return paint_batch_packed(st, pb, acc, cv, old), None

    state, _ = jax.lax.scan(
        body, state, (pbs, accepted, commit_versions, new_oldests)
    )
    return state


def paint_batch_hist_packed(hist: HistState, pb: PackedBatch, accepted,
                            commit_version, new_oldest) -> HistState:
    """Two-level edition: same demand-driven merge as the resolve body (a
    forced paint must respect delta capacity exactly like a decided one)."""
    floor, _ = too_old_mask_packed(hist.delta, pb, new_oldest)
    demand = 2 * jnp.sum(
        (pb.write_mask & (pb.write_begin < pb.write_end)).astype(jnp.int32)
    )
    hist = _maybe_merge(hist, demand, floor)
    base_h, base_st, delta = hist
    delta = _paint_and_compact_packed(delta, pb, accepted, commit_version,
                                      floor)
    return HistState(base_h, base_st, delta)


def paint_many_hist_packed(hist, pbs, accepted, commit_versions, new_oldests):
    def body(h, xs):
        pb, acc, cv, old = xs
        return paint_batch_hist_packed(h, pb, acc, cv, old), None

    hist, _ = jax.lax.scan(
        body, hist, (pbs, accepted, commit_versions, new_oldests)
    )
    return hist


def _paint_core_res(hist, rbk: RankBatch, accepted, commit_version,
                    new_oldest):
    if isinstance(hist, HistState):
        floor, _ = too_old_mask_packed(hist.delta, rbk, new_oldest)
        demand = 2 * jnp.sum(
            (rbk.write_mask & (rbk.write_begin < rbk.write_end)).astype(
                jnp.int32
            )
        )
        hist = _maybe_merge(hist, demand, floor)
        base_h, base_st, delta = hist
        delta = _paint_and_compact_res(delta, rbk, accepted, commit_version,
                                       floor)
        return HistState(base_h, base_st, delta)
    floor = jnp.maximum(hist.oldest, new_oldest)
    return _paint_and_compact_res(hist, rbk, accepted, commit_version, floor)


def paint_batch_res(res: ResState, rb: ResidentBatch, accepted,
                    commit_version, new_oldest) -> ResState:
    """Resident edition: the dictionary delta re-applies exactly as the
    resolve body would (a rolled-back snapshot predates this window's
    insert, so the replayed merge reproduces the original rank space)."""
    res = apply_delta(res, rb.delta_keys)
    return res._replace(
        hist=_paint_core_res(res.hist, rb.ranks, accepted, commit_version,
                             new_oldest)
    )


def paint_many_res(res, rb, accepted, commit_versions, new_oldests):
    res = apply_delta(res, rb.delta_keys)

    def body(h, xs):
        rbk, acc, cv, old = xs
        return _paint_core_res(h, rbk, acc, cv, old), None

    hist, _ = jax.lax.scan(
        body, res.hist, (rb.ranks, accepted, commit_versions, new_oldests)
    )
    return res._replace(hist=hist)


@functools.partial(jax.jit, donate_argnums=(0,))
def _paint_packed_jit(state, pb, accepted, commit_version, new_oldest):
    return paint_batch_packed(state, pb, accepted, commit_version, new_oldest)


@functools.partial(jax.jit, donate_argnums=(0,))
def _paint_many_packed_jit(state, pbs, accepted, commit_versions,
                           new_oldests):
    return paint_many_packed(state, pbs, accepted, commit_versions,
                             new_oldests)


@functools.partial(jax.jit, donate_argnums=(0,))
def _paint_hist_packed_jit(hist, pb, accepted, commit_version, new_oldest):
    return paint_batch_hist_packed(hist, pb, accepted, commit_version,
                                   new_oldest)


@functools.partial(jax.jit, donate_argnums=(0,))
def _paint_many_hist_packed_jit(hist, pbs, accepted, commit_versions,
                                new_oldests):
    return paint_many_hist_packed(hist, pbs, accepted, commit_versions,
                                  new_oldests)


@functools.partial(jax.jit, donate_argnums=(0,))
def _paint_res_jit(res, rb, accepted, commit_version, new_oldest):
    return paint_batch_res(res, rb, accepted, commit_version, new_oldest)


@functools.partial(jax.jit, donate_argnums=(0,))
def _paint_many_res_jit(res, rb, accepted, commit_versions, new_oldests):
    return paint_many_res(res, rb, accepted, commit_versions, new_oldests)


# Hist/flat distinction rides the ResState pytree (see the resident alias
# block above) — same totality trick for the paint entry names.
_paint_hist_res_jit = _paint_res_jit
_paint_many_hist_res_jit = _paint_many_res_jit


# -- verdict-dependency mask -------------------------------------------------
# Scratch = a small flat ConflictState holding ONLY the rejected writes of
# the reconciling window, painted at +inf version so any overlapping read
# trips the probe regardless of its read version. Works for flat AND
# two-level non-resident engines (the scratch is its own flat state; only
# the batches' dictionaries are probed). Resident engines skip the probe
# (their ranks live in per-window coordinate systems) and repair
# pessimistically — see conflict_set._spec_dep_windows.

_SPEC_DEP_VERSION = INT32_MAX - 1


def _spec_mark_rejected(scratch: ConflictState, pbs: PackedBatch,
                        rejected) -> ConflictState:
    def body(st, xs):
        pb, rej = xs
        st = _paint_and_compact_packed(
            st, pb, rej, jnp.int32(_SPEC_DEP_VERSION), jnp.int32(0)
        )
        return st, None

    scratch, _ = jax.lax.scan(body, scratch, (pbs, rejected))
    return scratch


def _spec_dep_window(scratch: ConflictState, pbs: PackedBatch):
    def body(acc, pb):
        return acc | jnp.any(_history_conflict_ranges_packed(scratch, pb)), None

    dep, _ = jax.lax.scan(body, jnp.bool_(False), pbs)
    return dep | scratch.overflow


@functools.partial(jax.jit, donate_argnums=(0,))
def _spec_mark_rejected_jit(scratch, pbs, rejected):
    return _spec_mark_rejected(scratch, pbs, rejected)


@jax.jit  # scratch NOT donated: one marked scratch probes every younger window
def _spec_dep_window_jit(scratch, pbs):
    return _spec_dep_window(scratch, pbs)
