"""Host-side ConflictSet API over the jitted kernel.

This is the seam the reference exposes as ``newConflictSet()`` /
``ConflictBatch`` (fdbserver/ConflictSet.h): the runtime's Resolver role
(runtime/resolver.py) talks to this class and never sees device tensors.
Responsibilities here: pad/pack byte-range batches into static-shape tensors,
chunk oversized batches (sub-batches at the same commit version are exactly
equivalent — earlier chunks' writes are painted at cv before later chunks
resolve, which reproduces in-batch ordering), coalesce per-txn conflict
ranges beyond the padded width (conservative covering ranges: false
conflicts possible, missed conflicts impossible), and manage the
absolute↔relative version mapping with periodic device rebase.
"""

from __future__ import annotations

import numpy as np

from foundationdb_tpu.core.keypack import INT32_MAX, KeyCodec
from foundationdb_tpu.core.types import KeyRange, TxnConflictInfo, Verdict
from foundationdb_tpu.models import conflict_kernel as ck

DEFAULT_WINDOW_VERSIONS = 5_000_000  # ~5s at 1M versions/sec, reference MVCC window
_REBASE_THRESHOLD = 1 << 30


class TPUConflictSet:
    """Drop-in conflict engine: resolve(txns, commit_version) → verdicts."""

    def __init__(
        self,
        capacity: int = 1 << 16,
        batch_size: int = 512,
        max_read_ranges: int = 8,
        max_write_ranges: int = 8,
        max_key_bytes: int = 32,
        window_versions: int = DEFAULT_WINDOW_VERSIONS,
    ):
        self.codec = KeyCodec(max_key_bytes)
        self.capacity = capacity
        self.batch_size = batch_size
        self.max_read_ranges = max_read_ranges
        self.max_write_ranges = max_write_ranges
        self.window_versions = window_versions
        self.base_version: int | None = None
        self.oldest_version: int = 0  # absolute; advances monotonically
        self._last_commit: int = 0
        self._init_engine()

    def _init_engine(self) -> None:
        """Build device state + entry points. Subclasses (the mesh-sharded
        engine) override this; all host-side logic is shared."""
        self.state = ck.init_state(self.capacity, self.codec.width, self.codec.min_key)
        self._resolve_fn = ck._resolve_jit
        self._rebase_fn = ck._rebase_jit

    # -- public API ---------------------------------------------------------

    def resolve(
        self,
        txns: list[TxnConflictInfo],
        commit_version: int,
        oldest_version: int | None = None,
    ) -> list[Verdict]:
        if commit_version <= self._last_commit:
            raise ValueError(
                f"commit versions must advance: {commit_version} <= {self._last_commit}"
            )
        if self.base_version is None:
            self.base_version = max(0, commit_version - self.window_versions)
        if oldest_version is not None:
            self.oldest_version = max(self.oldest_version, oldest_version)
        self.oldest_version = max(
            self.oldest_version, commit_version - self.window_versions
        )
        self._maybe_rebase(commit_version)
        self._last_commit = commit_version

        out: list[Verdict] = []
        for i in range(0, len(txns), self.batch_size):
            out.extend(self._resolve_chunk(txns[i : i + self.batch_size], commit_version))
        return out

    @property
    def overflowed(self) -> bool:
        return bool(np.asarray(self.state.overflow).any())

    # -- internals ----------------------------------------------------------

    def _rel(self, v: int) -> int:
        assert self.base_version is not None
        rel = v - self.base_version
        if rel < 0:
            raise ValueError(f"version {v} below base {self.base_version}")
        return rel

    def _rel_read(self, v: int) -> int:
        """Read versions may legitimately predate the base (ancient readers):
        clamp to -1, which is strictly below every window floor → TOO_OLD for
        readers, irrelevant for blind writers."""
        assert self.base_version is not None
        return max(-1, v - self.base_version)

    def _maybe_rebase(self, commit_version: int) -> None:
        assert self.base_version is not None
        if commit_version - self.base_version < _REBASE_THRESHOLD:
            return
        delta = self.oldest_version - self.base_version
        if delta <= 0:
            return
        # Device versions < delta are all expired; the kernel clamps them to
        # the sentinel, so saturating the device delta at int32 max is exact
        # even for astronomically large jumps.
        self.state = self._rebase_fn(self.state, np.int32(min(delta, 2**31 - 1)))
        self.base_version += delta

    def _resolve_chunk(
        self, txns: list[TxnConflictInfo], commit_version: int
    ) -> list[Verdict]:
        batch = self._pack(txns)
        cv = np.int32(self._rel(commit_version))
        oldest = np.int32(self._rel(self.oldest_version))
        verdicts, self.state = self._resolve_fn(self.state, batch, cv, oldest)
        v = np.asarray(verdicts)[: len(txns)]
        return [Verdict(int(x)) for x in v]

    def _pack(self, txns: list[TxnConflictInfo]) -> ck.BatchTensors:
        b = self.batch_size
        r, q = self.max_read_ranges, self.max_write_ranges
        w = self.codec.width

        read_begin = np.full((b, r, w), INT32_MAX, np.int32)
        read_end = np.full((b, r, w), INT32_MAX, np.int32)
        read_mask = np.zeros((b, r), bool)
        write_begin = np.full((b, q, w), INT32_MAX, np.int32)
        write_end = np.full((b, q, w), INT32_MAX, np.int32)
        write_mask = np.zeros((b, q), bool)
        read_version = np.zeros((b,), np.int32)
        txn_mask = np.zeros((b,), bool)

        # One vectorized pack per endpoint kind across the whole batch (the
        # per-txn Python work is just index bookkeeping).
        r_rows, r_cols, r_pairs = [], [], []
        w_rows, w_cols, w_pairs = [], [], []
        for i, t in enumerate(txns):
            txn_mask[i] = True
            read_version[i] = self._rel_read(t.read_version)
            for c, x in enumerate(_coalesce(t.read_ranges, r)):
                r_rows.append(i)
                r_cols.append(c)
                r_pairs.append((x.begin, x.end))
            for c, x in enumerate(_coalesce(t.write_ranges, q)):
                w_rows.append(i)
                w_cols.append(c)
                w_pairs.append((x.begin, x.end))
        if r_pairs:
            rb, re_ = self.codec.pack_ranges(r_pairs)
            read_begin[r_rows, r_cols] = rb
            read_end[r_rows, r_cols] = re_
            read_mask[r_rows, r_cols] = True
        if w_pairs:
            wb, we = self.codec.pack_ranges(w_pairs)
            write_begin[w_rows, w_cols] = wb
            write_end[w_rows, w_cols] = we
            write_mask[w_rows, w_cols] = True

        return ck.BatchTensors(
            read_begin=read_begin,
            read_end=read_end,
            read_mask=read_mask,
            write_begin=write_begin,
            write_end=write_end,
            write_mask=write_mask,
            read_version=read_version,
            txn_mask=txn_mask,
        )


def _coalesce(ranges: list[KeyRange], limit: int) -> list[KeyRange]:
    """At most `limit` ranges covering the input (conservative widening).

    Sorts by begin and covers even-sized groups — the analogue of the
    reference's combineWriteConflictRanges merging adjacent/overlapping
    ranges, extended to force a static width.
    """
    live = [x for x in ranges if not x.empty]
    if len(live) <= limit:
        return live
    live.sort(key=lambda x: x.begin)
    out = []
    step = -(-len(live) // limit)
    for i in range(0, len(live), step):
        grp = live[i : i + step]
        out.append(KeyRange(grp[0].begin, max(g.end for g in grp)))
    return out
