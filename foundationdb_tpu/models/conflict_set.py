"""Host-side ConflictSet API over the jitted kernel.

This is the seam the reference exposes as ``newConflictSet()`` /
``ConflictBatch`` (fdbserver/ConflictSet.h): the runtime's Resolver role
(runtime/resolver.py) talks to this class and never sees device tensors.
Responsibilities here: pad/pack byte-range batches into static-shape tensors,
chunk oversized batches (sub-batches at the same commit version are exactly
equivalent — earlier chunks' writes are painted at cv before later chunks
resolve, which reproduces in-batch ordering), coalesce per-txn conflict
ranges beyond the padded width (conservative covering ranges: false
conflicts possible, missed conflicts impossible), and manage the
absolute↔relative version mapping with periodic device rebase.
"""

from __future__ import annotations

import ctypes
import os
import struct
import threading
from time import perf_counter as _perf_counter
from collections import deque
from typing import Callable, NamedTuple

import numpy as np

from foundationdb_tpu.core.keypack import INT32_MAX, KeyCodec, row_sort_keys
from foundationdb_tpu.core.types import KeyRange, TxnConflictInfo, Verdict
from foundationdb_tpu.models import conflict_kernel as ck

DEFAULT_WINDOW_VERSIONS = 5_000_000  # ~5s at 1M versions/sec, reference MVCC window
_REBASE_THRESHOLD = 1 << 30


# ---------------------------------------------------------------------------
# Resident-dictionary host mirror (FDB_TPU_RESIDENT=1)
# ---------------------------------------------------------------------------
#
# The host keeps a sorted mirror of the device-resident endpoint-key
# dictionary so per-dispatch rank computation is a membership lookup plus
# arithmetic instead of the full np.unique dedup+sort _pack_dict pays, and
# only the never-before-seen keys (the DELTA) ever cross PCIe. Keys are
# compared as uint64 column pairs (the packed int32 words re-biased and
# packed big-endian two-per-word), so every comparison in the vectorized
# binary search below is a native numpy op — no structured-dtype memcmp
# dispatch on the hot path.


def _rows_to_u64(rows: np.ndarray) -> np.ndarray:
    """[n, W] packed int32 key rows -> [n, ceil(W/2)] uint64 columns whose
    lexicographic order (and equality) equals key order. The sign bias is
    one uint32 XOR (re-biasing to unsigned), then word pairs combine."""
    n, w = rows.shape
    u = np.ascontiguousarray(rows).view(np.uint32) ^ np.uint32(0x80000000)
    if w % 2:
        u = np.concatenate([u, np.zeros((n, 1), np.uint32)], axis=1)
    return (u[:, 0::2].astype(np.uint64) << np.uint64(32)) | u[:, 1::2]


def _u64_lt(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Lexicographic a < b over trailing uint64 columns (vectorized)."""
    out = np.zeros(a.shape[:-1], bool)
    eq = np.ones(a.shape[:-1], bool)
    for j in range(a.shape[-1]):
        out |= eq & (a[..., j] < b[..., j])
        eq &= a[..., j] == b[..., j]
    return out


def _u64_searchsorted(sorted2d: np.ndarray, q: np.ndarray,
                      side: str = "left") -> np.ndarray:
    """Multi-column searchsorted over the uint64 mirror columns.

    Two-level: one NATIVE np.searchsorted per side on column 0 (the first
    8 key bytes — this is the C-speed heavy lifting), then a short
    vectorized binary search on the remaining columns INSIDE each
    equal-column-0 run. Runs are tiny in practice (a key and its
    point-range end share the first 8 bytes), so the refinement costs a
    couple of light passes; the worst case degrades to the plain
    vectorized search."""
    d = sorted2d.shape[0]
    n = q.shape[0]
    if d == 0:
        return np.zeros(n, np.int64)
    col0 = sorted2d[:, 0]
    if sorted2d.shape[1] == 1:
        return np.searchsorted(col0, q[:, 0], side=side).astype(np.int64)
    lo = np.searchsorted(col0, q[:, 0], side="left").astype(np.int64)
    hi = np.searchsorted(col0, q[:, 0], side="right").astype(np.int64)
    rest = sorted2d[:, 1:]
    qrest = q[:, 1:]
    max_run = int((hi - lo).max(initial=0))
    for _ in range(int(max_run + 1).bit_length()):
        act = lo < hi
        if not act.any():
            break
        mid = (lo + hi) >> 1
        rows = rest[np.minimum(mid, d - 1)]
        go = (_u64_lt(rows, qrest) if side == "left"
              else ~_u64_lt(qrest, rows))
        lo = np.where(act & go, mid + 1, lo)
        hi = np.where(act & ~go, mid, hi)
    return lo


def _u64_unique_sorted(u: np.ndarray, rows: np.ndarray):
    """Sort+dedup a small u64 key set, carrying the int32 rows along."""
    order = np.lexsort(tuple(u[:, j] for j in reversed(range(u.shape[1]))))
    us = u[order]
    keep = np.ones(len(us), bool)
    if len(us) > 1:
        keep[1:] = (us[1:] != us[:-1]).any(axis=1)
    return us[keep], rows[order][keep]


def pack_rank_dictionary(flat: np.ndarray, pad_rows: int | None = None):
    """THE shared pack/dictionary entry point: dedup+sort a flat [n, W]
    packed-key stack into a sorted-unique dictionary plus int32 ranks.

    Both the resolver's batch pack (:meth:`TPUConflictSet._pack_dict`) and
    the read plane (:mod:`foundationdb_tpu.reads`) rewrite their key sets
    through this one definition, so rank semantics (equal keys share a
    rank; ranks are exact order isomorphisms) cannot drift between roles.

    Returns ``(dict_keys, ranks)`` where ``dict_keys`` is ``[pad_rows, W]``
    (default ``n + 1``) with every row past the unique keys +inf
    (``INT32_MAX`` — kernels park masked slots there), and ``ranks`` is the
    int32 rank of each input row in the sorted dictionary."""
    n, w = flat.shape
    if pad_rows is None:
        pad_rows = n + 1
    _, first, inverse = np.unique(
        row_sort_keys(flat), return_index=True, return_inverse=True
    )
    if len(first) >= pad_rows:
        raise ValueError(
            f"{len(first)} unique keys need >= {len(first) + 1} dictionary "
            f"rows (one +inf pad), got pad_rows={pad_rows}"
        )
    dict_keys = np.full((pad_rows, w), INT32_MAX, np.int32)
    dict_keys[: len(first)] = flat[first]
    return dict_keys, inverse.astype(np.int32)


class _RepackPlan(NamedTuple):
    """A pack that overflowed the resident dictionary, deferred to the
    dispatch thread (the repack needs EXACT device liveness — a sync the
    packing thread must not perform while windows are in flight). The
    mirror gate is held until dispatch executes the plan; the single pack
    worker therefore stalls the pipeline for exactly one repack."""

    bt: object  # the raw BatchTensors (key space)
    qu: np.ndarray  # [n, U] endpoint u64 keys, flat pack order
    is_pad: np.ndarray  # [n] all-inf rows (masked slots / +inf ends)
    new_u64: np.ndarray  # sorted-unique never-seen keys
    new_rows: np.ndarray  # their int32 rows
    dims: tuple  # (lead, b, r, q, w)
    cv: int


class _DemotePlan(NamedTuple):
    """A pack whose merged key count crossed the tiered engine's hot-tier
    watermark, deferred to the dispatch thread exactly like _RepackPlan:
    victim selection needs EXACT device liveness (a sync the packing
    thread must not perform while windows are in flight). The mirror gate
    is held until dispatch demotes and re-packs; unlike a repack, the
    device traffic is a tiny int32 rank vector, not the whole dictionary."""

    bt: object  # the raw BatchTensors (key space)
    qu: np.ndarray  # [n, U] endpoint u64 keys, flat pack order
    is_pad: np.ndarray  # [n] all-inf rows (masked slots / +inf ends)
    new_u64: np.ndarray  # sorted-unique delta keys (misses + promotions)
    new_rows: np.ndarray  # their int32 rows
    dims: tuple  # (lead, b, r, q, w)
    cv: int


_HASH_C1 = np.uint64(0x9E3779B97F4A7C15)
_HASH_C2 = np.uint64(0xFF51AFD7ED558CCD)


class _ResidentMirror:
    """Host mirror of the device-resident dictionary.

    Two coupled views: a SORTED view (u64/rows/last_used/pinned — the
    rank space the device shares) and a stable ID space probed through a
    vectorized open-addressing hash table (tab: slot -> id, linear
    probing, load factor <= 1/4). Per-dispatch membership + rank is a few
    vectorized gathers — measured ~3.5x faster than even a native
    searchsorted over the endpoint set, which is what buys the host-pack
    cut the resident design is for. Ids are append-only between resets
    (full repack / reshard rebuilds everything); ``rank_of_id`` re-scatters
    on every insert so id -> current rank stays exact as inserts shift
    the rank space."""

    def __init__(self, rows: np.ndarray, capacity: int, delta_slots: int,
                 frag_threshold: float, tiered: bool = False):
        self.capacity = int(capacity)
        self.delta_slots = int(delta_slots)
        self.frag_threshold = float(frag_threshold)
        # Tiered mode (FDB_TPU_DICT_HOT_CAPACITY): the ID space is
        # promoted from "mirror" to authoritative COLD STORE. Ids of
        # demoted keys keep their tab entries, u64 rows and last-used
        # versions; only the sorted (rank-space) view shrinks. probe()
        # therefore still finds cold keys — the pack path routes those
        # hits through the normal never-seen-key delta (a PROMOTION).
        self.tiered = bool(tiered)
        self._n_ids = 0
        rows = np.asarray(rows, np.int32).copy()
        u64 = _rows_to_u64(rows)
        t = 16
        while t < 4 * self.capacity:
            t <<= 1
        self._mask = np.int64(t - 1)
        self.tab = np.full(t, -1, np.int64)
        self.u64_by_id = np.zeros((self.capacity + 1, u64.shape[1]),
                                  np.uint64)
        self.rank_of_id = np.zeros(self.capacity + 1, np.int64)
        # last-used versions live in ID space (scatter-only on the hot
        # path); used_sorted() materializes the rank-space view on the
        # rare repack/reshard paths that need it.
        self.last_used_by_id = np.zeros(self.capacity + 1, np.int64)
        self.hot_by_id = np.zeros(self.capacity + 1, bool)
        self.reset(u64, rows, np.zeros(len(rows), np.int64),
                   np.ones(len(rows), bool))
        self.lock = threading.RLock()
        # Deferred-repack handshake: cleared when a pack emits a
        # _RepackPlan, set again once the dispatch thread executes it —
        # the next pack blocks at entry so its deltas are computed against
        # the post-repack mirror.
        self.gate = threading.Event()
        self.gate.set()
        self.stats = {
            "dispatches": 0,
            "endpoints": 0,
            "endpoint_hits": 0,
            "unique_keys": 0,
            "delta_new_keys": 0,
            "evictions": 0,
            "full_repacks": 0,
            "repack_stalls": 0,
            # Tiered-dictionary economics (zero when tiering is off):
            "demotions": 0,        # keys moved hot -> cold via _dict_evict
            "promotions": 0,       # cold keys re-entered through the delta
            "demotion_stalls": 0,  # packs deferred behind a _DemotePlan
            "demotion_bytes": 0,   # device bytes shipped by evict deltas
            "demotion_events": 0,  # _demote_now calls that evicted > 0
        }

    @property
    def n(self) -> int:
        return len(self.u64)

    @property
    def cold_n(self) -> int:
        """Keys resident only in the host cold tier (0 when untired —
        every id is then in the sorted hot view)."""
        return self._n_ids - self.n

    def _hash(self, u64: np.ndarray) -> np.ndarray:
        h = u64[:, 0] * _HASH_C1
        for j in range(1, u64.shape[1]):
            h = (h ^ u64[:, j]) * _HASH_C2
        return ((h ^ (h >> np.uint64(33))) & np.uint64(self._mask)).astype(
            np.int64
        )

    def reset(self, u64, rows, last_used, pinned) -> None:
        """Rebuild the sorted view from a fresh sorted key set (repack and
        reshard path; the delta path uses incremental insert_new).

        Untired: the ID space rebuilds too (ids == sorted positions).
        Tiered: the ID space is the cold store and SURVIVES — existing
        keys keep their stable ids, keys leaving the hot view demote
        instead of vanishing, genuinely new keys allocate fresh ids — so
        a full repack or scoped reshard never forgets the cold tier."""
        n = len(u64)
        if self.tiered and self._n_ids:
            ids = self.probe(u64)
            alloc = np.flatnonzero(ids < 0)
            self._ensure_ids(self._n_ids + len(alloc))
            fresh = self._n_ids + np.arange(len(alloc), dtype=np.int64)
            ids[alloc] = fresh
            self.u64_by_id[fresh] = u64[alloc]
            self._n_ids += len(alloc)
            self.u64, self.rows = u64, rows
            self.pinned = pinned
            self.hot_by_id[: self._n_ids] = False
            self.hot_by_id[ids] = True
            self.last_used_by_id[ids] = last_used
            self.id_at = ids
            self.rank_of_id[ids] = np.arange(n)
            self._tab_insert(fresh)
            return
        self.u64, self.rows = u64, rows
        self.pinned = pinned
        self._n_ids = n
        self.u64_by_id[:n] = u64
        self.last_used_by_id[:n] = last_used  # ids == sorted pos at reset
        self.id_at = np.arange(n, dtype=np.int64)  # sorted pos -> id
        self.rank_of_id[:n] = np.arange(n)
        self.hot_by_id[:] = False
        self.hot_by_id[:n] = True
        self.tab[:] = -1
        self._tab_insert(np.arange(n, dtype=np.int64))

    def _ensure_ids(self, need: int) -> None:
        """Grow the ID-space arrays (and rehash the probe table when its
        <=1/4 load bound would break) so the cold tier scales with the key
        UNIVERSE while the sorted hot view stays at hot capacity."""
        cur = len(self.u64_by_id)
        if need > cur:
            new = cur
            while new < need:
                new <<= 1
            grow = new - cur
            self.u64_by_id = np.concatenate(
                [self.u64_by_id,
                 np.zeros((grow, self.u64_by_id.shape[1]), np.uint64)]
            )
            self.rank_of_id = np.concatenate(
                [self.rank_of_id, np.zeros(grow, np.int64)]
            )
            self.last_used_by_id = np.concatenate(
                [self.last_used_by_id, np.zeros(grow, np.int64)]
            )
            self.hot_by_id = np.concatenate(
                [self.hot_by_id, np.zeros(grow, bool)]
            )
        if need * 4 > self._mask + 1:
            t = int(self._mask + 1)
            while need * 4 > t:
                t <<= 1
            self._mask = np.int64(t - 1)
            self.tab = np.full(t, -1, np.int64)
            self._tab_insert(np.arange(self._n_ids, dtype=np.int64))

    def demote(self, ranks: np.ndarray) -> np.ndarray:
        """Drop sorted-view rows at the given rank positions (the host
        half of the _dict_evict delta). Their ids stay in the cold store —
        tab entry, u64 row and last-used version intact — so a later
        probe() still finds them and promotion re-enters them through the
        normal delta with the SAME stable id. Returns the demoted ids."""
        ids = self.id_at[ranks]
        self.u64 = np.delete(self.u64, ranks, axis=0)
        self.rows = np.delete(self.rows, ranks, axis=0)
        self.pinned = np.delete(self.pinned, ranks)
        self.id_at = np.delete(self.id_at, ranks)
        self.rank_of_id[self.id_at] = np.arange(len(self.id_at))
        self.hot_by_id[ids] = False
        self.stats["demotions"] += len(ids)
        return ids

    def probe(self, qu: np.ndarray, active: "np.ndarray | None" = None):
        """ids int64 [n] (-1 = absent) for each query key row."""
        n = len(qu)
        ids = np.full(n, -1, np.int64)
        # Guard on the ID space, not the sorted view: under tiering the
        # hot view can be empty while cold ids remain probe-able (untired
        # the two counts are always equal).
        if n == 0 or self._n_ids == 0:
            return ids
        idxs = (np.flatnonzero(active) if active is not None
                else np.arange(n, dtype=np.int64))
        h = self._hash(qu[idxs])
        q = qu[idxs]
        step = np.int64(0)
        while len(idxs):
            slot = (h + step) & self._mask
            cand = self.tab[slot]
            hit = cand >= 0
            match = np.zeros(len(idxs), bool)
            if hit.any():
                rows = self.u64_by_id[cand[hit]]
                qh = q[hit]
                eq = rows[:, 0] == qh[:, 0]
                for j in range(1, rows.shape[1]):
                    eq &= rows[:, j] == qh[:, j]
                match[hit] = eq
            ids[idxs[match]] = cand[match]
            # Empty slot = definitive miss (no deletes outside reset).
            cont = hit & ~match
            idxs, h, q = idxs[cont], h[cont], q[cont]
            step += 1
            if step > self._mask:  # full-table bound (unreachable: load<=1/4)
                break
        return ids

    def touch(self, ids: np.ndarray, cv: int) -> None:
        if ids.size:
            self.last_used_by_id[ids] = cv

    def used_sorted(self) -> np.ndarray:
        """Rank-space view of the last-used versions (repack/reshard)."""
        return self.last_used_by_id[self.id_at]

    def insert_new(self, new_u64, new_rows, cv: int,
                   ids: "np.ndarray | None" = None) -> np.ndarray:
        """Incremental sorted insert of delta keys; returns their ids.

        ``ids`` (tiered promotion path): per-row existing cold id, or -1
        for a genuinely new key. Cold keys re-enter the sorted view with
        their stable id (tab/u64/last-used rows already present); only
        the -1 rows allocate. Untired callers omit it — every delta key
        is then never-seen and allocates append-only, exactly as before."""
        m = len(new_u64)
        ins = _u64_searchsorted(self.u64, new_u64, "left")
        self.u64 = np.insert(self.u64, ins, new_u64, axis=0)
        self.rows = np.insert(self.rows, ins, new_rows, axis=0)
        self.pinned = np.insert(self.pinned, ins, False)
        if ids is None:
            new_ids = self._n_ids + np.arange(m, dtype=np.int64)
            self.u64_by_id[new_ids] = new_u64
            self.last_used_by_id[new_ids] = cv
            self._n_ids += m
            self.id_at = np.insert(self.id_at, ins, new_ids)
            self.rank_of_id[self.id_at] = np.arange(len(self.id_at))
            self.hot_by_id[new_ids] = True
            self._tab_insert(new_ids)
            return new_ids
        alloc = np.flatnonzero(ids < 0)
        self._ensure_ids(self._n_ids + len(alloc))
        new_ids = np.asarray(ids, np.int64).copy()
        fresh = self._n_ids + np.arange(len(alloc), dtype=np.int64)
        new_ids[alloc] = fresh
        self.u64_by_id[fresh] = new_u64[alloc]
        self.last_used_by_id[new_ids] = cv
        self._n_ids += len(alloc)
        self.id_at = np.insert(self.id_at, ins, new_ids)
        self.rank_of_id[self.id_at] = np.arange(len(self.id_at))
        self.hot_by_id[new_ids] = True
        self.stats["promotions"] += m - len(alloc)
        self._tab_insert(fresh)
        return new_ids

    def _tab_insert(self, ids: np.ndarray) -> None:
        """Vectorized linear-probing insert: same-batch slot races resolve
        by scatter-then-gather-back (losers advance with the occupied)."""
        if not len(ids):
            return
        h = self._hash(self.u64_by_id[ids])
        idxs = np.arange(len(ids), dtype=np.int64)
        step = np.int64(0)
        while len(idxs):
            slot = (h[idxs] + step) & self._mask
            empty = np.flatnonzero(self.tab[slot] < 0)
            if len(empty):
                self.tab[slot[empty]] = ids[idxs[empty]]
                won = self.tab[slot[empty]] == ids[idxs[empty]]
                done = np.zeros(len(idxs), bool)
                done[empty[won]] = True
                idxs = idxs[~done]
            step += 1
            if step > self._mask:
                raise RuntimeError("resident hash table full")

    def frag_due(self, floor_version: int) -> bool:
        """Opportunistic-repack trigger: the dictionary is mostly full AND
        mostly stale (keys unused since the MVCC floor) — reclaim early
        instead of stalling the pipeline on a forced overflow repack.
        Tiered engines reclaim stale keys through DEMOTION deltas instead
        (stale == the demotion victim set), so the trigger is off there:
        a stale-but-device-live key can't be reclaimed by a repack either,
        and firing on it would repack repeatedly for zero freed rows."""
        if self.tiered:
            return False
        if self.n <= self.capacity // 2:
            return False
        stale = int(
            (self.last_used_by_id[: self._n_ids] < floor_version).sum()
        )
        return stale > self.frag_threshold * self.n


class PreparedWindow(NamedTuple):
    """A host-packed dispatch window awaiting device dispatch.

    The pack half (``pack_wire_window``) is pure host work — the C wire
    pass, padding, and (under FDB_TPU_PACKED) the ``_pack_dict``
    dedup+sort — so a scheduler can run it on a worker thread for window
    N+1 while the device still executes window N (sched/packing.py). The
    dispatch half (``dispatch_window``) threads device state and must run
    on the dispatching thread, in commit-version order."""

    batch: object  # device-format batch tensors, k-leading axis
    cvs_rel: np.ndarray
    olds_rel: np.ndarray
    count: int
    rebase_delta: int  # deferred device rebase; applied before dispatch


class _SpecPending(NamedTuple):
    """One speculatively dispatched window awaiting reconcile.

    ``snapshot`` is a fresh device copy of the engine state taken RIGHT
    BEFORE this window's dispatch (the resolve entry points donate their
    state argument, so the live state never double-buffers — the
    snapshot is the explicit, spec_depth-bounded HBM cost of
    speculation). Rolling a mis-speculated window back is a host pointer
    swap to this snapshot followed by a paint-only re-advance with the
    confirmed accept mask."""

    seq: int
    snapshot: object  # device state BEFORE dispatch (rollback target)
    batch: object  # device-format batch (PackedBatch / ResidentBatch)
    cvs_rel: np.ndarray
    olds_rel: np.ndarray
    count: int
    verdicts: object  # device verdicts int8 [k, B] (still in flight)
    levels: object  # device wave levels int32 [k, B] or None


class TPUConflictSet:
    """Drop-in conflict engine: resolve(txns, commit_version) → verdicts."""

    def __init__(
        self,
        capacity: int = 1 << 16,
        batch_size: int = 512,
        max_read_ranges: int = 8,
        max_write_ranges: int = 8,
        max_key_bytes: int = 32,
        window_versions: int = DEFAULT_WINDOW_VERSIONS,
        delta_capacity: int | None = None,
        wave_commit: bool | None = None,
        resident: bool | None = None,
        dict_capacity: int | None = None,
        dict_delta_slots: int | None = None,
        dict_hot_capacity: int | None = None,
        dict_demote_batch: int | None = None,
        spec_resolve: bool | None = None,
        spec_depth: int = 2,
    ):
        self.codec = KeyCodec(max_key_bytes)
        # Resident-dictionary mode (FDB_TPU_RESIDENT default; requires the
        # packed kernel): the endpoint dictionary and rank-space history
        # persist on device across dispatches; the host ships key DELTAS.
        # Per-engine override (like wave_commit) so a process can A/B both
        # modes; forced off when the packed kernel is off.
        self.resident = (
            ck._RESIDENT if resident is None else bool(resident)
        ) and ck._PACKED
        # Speculative pipelined resolve (FDB_TPU_SPEC_RESOLVE default;
        # requires the packed kernel — the reconcile dependency probe runs
        # over the batch dictionary): dispatches run against the
        # OPTIMISTICALLY advanced state while earlier windows' verdicts are
        # still unconfirmed by the upper layer; a bounded reconcile ring
        # (spec_depth in-flight windows, one device-state snapshot each)
        # confirms or rolls back + repairs. Same per-engine override shape
        # as resident/wave_commit; inert under FDB_TPU_PACKED=0.
        self.spec = (
            ck._SPEC_RESOLVE if spec_resolve is None else bool(spec_resolve)
        ) and ck._PACKED
        self.spec_depth = max(1, int(spec_depth))
        self._spec_ring: deque[_SpecPending] = deque()
        self._spec_seq = 0
        self._spec_done: dict[int, tuple] = {}
        self._spec_stats = {
            "spec_dispatched": 0,  # windows dispatched speculatively
            "spec_confirmed": 0,   # reconciled with zero rollback
            "spec_repaired": 0,    # reconciled through rollback + repair
            "spec_flipped": 0,     # younger-window verdicts changed by repair
            "chain_rolls": 0,      # optimistic chain rolled to reconciled state
        }
        # Upper-layer confirmation hook: called at reconcile time as
        # hook(seq, verdicts[k, count]) -> bool[k, count] confirmation mask
        # (False = this txn's speculative outcome is revoked — tlog
        # failure, ratekeeper revoke, chaos injection) or None = confirm
        # all. Default None = every window confirms (the production fast
        # path; revocation is the exception speculation bets against).
        self.spec_confirm_hook: Callable | None = None
        self._nat_win: bool | None = None  # lazy kp_pack_window gate
        self.dict_capacity = int(
            dict_capacity
            or int(os.environ.get("FDB_TPU_DICT_CAPACITY", "0"))
            or max(2 * capacity,
                   capacity + 4 * batch_size * (max_read_ranges
                                                + max_write_ranges))
        )
        self.dict_delta_slots = int(
            dict_delta_slots
            or int(os.environ.get("FDB_TPU_DICT_DELTA", "0"))
            or min(max(self.dict_capacity // 2, 1),
                   max(1024, 2 * batch_size * (max_read_ranges
                                               + max_write_ranges)))
        )
        self._dict_frag = float(os.environ.get("FDB_TPU_DICT_FRAG", "0.75"))
        # Two-tier dictionary (FDB_TPU_DICT_HOT_CAPACITY > 0, resident
        # engines only): the device dictionary becomes the HOT tier at
        # this capacity and the mirror's ID space the authoritative host
        # COLD store. Crossing the hot watermark demotes rank-contiguous
        # victim batches through _dict_evict (the inverse of the insert
        # delta) instead of full-repacking, so capacity follows the hot
        # set, not the key universe. 0/None = untired (bit-identical to
        # the pre-tiering engine).
        hot = int(
            dict_hot_capacity
            if dict_hot_capacity is not None
            else int(os.environ.get("FDB_TPU_DICT_HOT_CAPACITY", "0") or 0)
        )
        self.tiered = bool(hot > 0) and self.resident
        if self.tiered:
            self.dict_capacity = hot
            self.dict_delta_slots = min(
                self.dict_delta_slots, max(1, hot // 2)
            )
            # Static evict-delta width (jit shape): one batch per
            # _evict_res_jit call, looped when the victim set is larger.
            self._demote_slots = int(
                dict_demote_batch
                or int(os.environ.get("FDB_TPU_DICT_DEMOTE_BATCH", "0") or 0)
                or self.dict_delta_slots
            )
            # Demotion fires when the post-merge key count would leave
            # less than one delta's headroom in the hot tier.
            self._demote_watermark = max(
                1, self.dict_capacity - self.dict_delta_slots
            )
        else:
            self._demote_slots = 0
            self._demote_watermark = 0
        # Wave-commit mode (reorder-don't-abort; conflict_kernel phase 2b):
        # None = the FDB_TPU_WAVE_COMMIT env default. Both modes' entry
        # points are distinct compiled programs, so engines of either mode
        # coexist in one process (the import-once rule only pins the env
        # DEFAULT). NOTE: a wave engine reorders txns against the FULL
        # conflict graph of its window. Single-resolver roles see it
        # whole; the mesh ShardedConflictSet OR-reduces per-shard clipped
        # graphs on-device; role-level multi-resolver deployments run the
        # two-phase global protocol (resolve_edges/resolve_apply below —
        # the commit proxy OR-reduces the shards' edge bitsets and every
        # shard levels the identical global graph).
        self.wave_commit = ck._WAVE_COMMIT if wave_commit is None else bool(
            wave_commit
        )
        self.capacity = capacity
        self.batch_size = batch_size
        self.max_read_ranges = max_read_ranges
        self.max_write_ranges = max_write_ranges
        self.window_versions = window_versions
        # Window-history delta sizing: must absorb one batch's worst-case
        # paint (the in-jit merge empties it just-in-time before a batch
        # that wouldn't fit).
        self.delta_capacity = delta_capacity or min(
            capacity, 2 * batch_size * max_write_ranges + 2
        )
        self.base_version: int | None = None
        self.oldest_version: int = 0  # absolute; advances monotonically
        self._last_commit: int = 0
        # Exact conflicting read ranges of the LAST resolve() call, by txn
        # index — populated only when some txn asked
        # (report_conflicting_keys) so the hot path pays nothing. Same
        # surface as the oracle's (reference: conflictingKRIndices); the
        # runtime Resolver reads it for the repair subsystem's reports.
        self.last_conflicting: dict[int, list[KeyRange]] = {}
        # Wave levels of the LAST resolve() call, by txn index (wave
        # engines only; None otherwise): >= 0 committed at that wave,
        # conflict_kernel.LEVEL_CYCLE aborted on a true cycle,
        # LEVEL_NONE every other non-commit. Chunked resolves offset
        # later chunks' waves past earlier ones (chunks serialize in
        # order), so the list is one coherent schedule for the call.
        self.last_wave: list[int] | None = None
        # Exact reordered count of the last resolve (wave engines only):
        # txns committed past their chunk's FIRST wave — the published
        # cross-chunk offsets deliberately excluded (see _collect_waves).
        self.last_reordered: int | None = None
        # Window-path analogue (dispatch_window collectors): int32
        # [k, count] levels, one independent wave schedule per scanned
        # batch (batches already serialize by commit version).
        self.last_wave_window: np.ndarray | None = None
        self._empty_dev_batch = None  # advance()'s constant batch, packed lazily
        # Admission subsystem (attach_admission_filter): a RecentWritesFilter
        # fed from each dispatch's ACCEPTED write sets using the endpoint
        # u64 columns the resident pack already computed — no re-hash, no
        # extra host→device key bytes (the filter's jax banks persist on
        # device; the update operand is the write-fingerprint row the
        # dispatch shipped anyway).
        self.admission_filter = None
        self._adm_stash = None  # (write fps [b, q], valid [b, q]) per pack
        # Role-level global wave protocol (core/wavemesh): resolve_edges
        # stashes the packed chunks here until resolve_apply consumes the
        # combined global graph. None between windows; the mesh-sharded
        # subclass leaves the entry points unset (it exchanges in-jit).
        self._wave_pending = None
        self._wave_edges_fn = None
        self._wave_apply_fn = None
        self._init_engine()

    def attach_admission_filter(self, f) -> None:
        """Attach a RecentWritesFilter to the resident engine: every
        resolve feeds the accepted write-set fingerprints (resident mode
        only — the fingerprints ARE the mirror's u64 key columns)."""
        if not self.resident:
            raise ValueError(
                "admission filter attaches to the resident engine only "
                "(FDB_TPU_RESIDENT=1 / resident=True)")
        self.admission_filter = f

    def _init_engine(self) -> None:
        """Build device state + entry points. Subclasses (the mesh-sharded
        engine) override this; all host-side logic is shared. Under
        FDB_TPU_PACKED (default) the packer additionally emits the batch's
        deduped key dictionary (_pack_dict) and the device runs the
        rank-space kernel entry points; under FDB_TPU_RESIDENT (default)
        the dictionary instead PERSISTS on device and the packer emits
        rank batches + key deltas against the host mirror."""
        hist = ck._HIST_DESIGN == "window"
        self._mirror: _ResidentMirror | None = None
        self._dev_batch_deferred = None  # window-path packer (may defer repack)
        if self.resident:
            self._mirror = _ResidentMirror(
                self.codec.min_key[None, :], self.dict_capacity,
                self.dict_delta_slots, self._dict_frag,
                tiered=self.tiered,
            )
            self.state = ck.init_res(
                self._mirror.rows, self.dict_capacity, self.capacity,
                self.delta_capacity if hist else None,
            )
            self._dev_batch = lambda bt: self._pack_resident(bt)
            self._dev_batch_deferred = lambda bt: self._pack_resident(
                bt, defer_repack=True
            )
            self._rebase_fn = ck._rebase_res_jit
            self._repack_fn = ck._repack_res_jit
            self._evict_fn = ck._evict_res_jit
        else:
            self._dev_batch = self._pack_dict if ck._PACKED else (lambda bt: bt)
            self._dev_batch_deferred = self._dev_batch
            if hist:
                self.state = ck.init_hist(
                    self.capacity, self.codec.width, self.codec.min_key,
                    self.delta_capacity,
                )
                self._rebase_fn = ck._rebase_hist_jit
            else:
                self.state = ck.init_state(
                    self.capacity, self.codec.width, self.codec.min_key
                )
                self._rebase_fn = ck._rebase_jit
        # Entry points follow one naming convention —
        # _resolve{,_report,_many}{_hist}{_packed|_res}{_wave}_jit — so the
        # (history, packed/resident, wave) design point composes the names
        # instead of a hand-written table a mis-paired branch could
        # silently skew.
        fmt = "_res" if self.resident else ("_packed" if ck._PACKED else "")
        suffix = (("_hist" if hist else "") + fmt
                  + ("_wave" if self.wave_commit else "") + "_jit")
        self._resolve_fn = getattr(ck, "_resolve" + suffix)
        self._resolve_report_fn = getattr(ck, "_resolve_report" + suffix)
        self._resolve_many_fn = getattr(ck, "_resolve_many" + suffix)
        if self.wave_commit:
            # Two-phase entry points for the role-level global wave
            # protocol (resolve_edges/resolve_apply) — same suffix
            # composition as above.
            two = ("_hist" if hist else "") + fmt + "_jit"
            self._wave_edges_fn = getattr(ck, "_wave_edges" + two)
            self._wave_apply_fn = getattr(ck, "_wave_apply" + two)
        if self.spec:
            # Paint-only re-advance entry points for the reconcile path
            # (no _wave variant: a forced accept mask has no levels to
            # compute — wave engines paint with levels >= 0).
            pfx = ("_hist" if hist else "") + fmt + "_jit"
            self._paint_many_fn = getattr(ck, "_paint_many" + pfx)

    def _pack_dict(self, bt: ck.BatchTensors) -> ck.PackedBatch:
        """Dedup+sort ALL batch endpoint keys once per dispatch (host
        numpy — a memcmp sort over the biased byte view) and rewrite the
        batch in rank space: the kernel receives the sorted unique key
        dictionary plus int32 ranks per endpoint slot. The dictionary's
        static size is the endpoint count + 1, with the last row always
        +inf (paint parks masked slots there); ranks are exact order
        isomorphisms (equal keys share a rank)."""
        rb = np.asarray(bt.read_begin)
        if rb.ndim == 4:  # [k, B, R, W] window path: pack per scan step
            parts = [
                self._pack_dict(
                    ck.BatchTensors(*(np.asarray(x)[i] for x in bt))
                )
                for i in range(rb.shape[0])
            ]
            return ck.PackedBatch(*(np.stack(x) for x in zip(*parts)))
        b, r, w = rb.shape
        q = bt.write_begin.shape[1]
        flat = np.concatenate([
            rb.reshape(-1, w),
            np.asarray(bt.read_end).reshape(-1, w),
            np.asarray(bt.write_begin).reshape(-1, w),
            np.asarray(bt.write_end).reshape(-1, w),
        ])
        dict_keys, inv = pack_rank_dictionary(flat)
        n_r, n_q = b * r, b * q
        return ck.PackedBatch(
            dict_keys=dict_keys,
            read_begin=inv[:n_r].reshape(b, r),
            read_end=inv[n_r : 2 * n_r].reshape(b, r),
            read_mask=np.asarray(bt.read_mask),
            write_begin=inv[2 * n_r : 2 * n_r + n_q].reshape(b, q),
            write_end=inv[2 * n_r + n_q :].reshape(b, q),
            write_mask=np.asarray(bt.write_mask),
            read_version=np.asarray(bt.read_version),
            txn_mask=np.asarray(bt.txn_mask),
        )

    # -- resident-dictionary packing (FDB_TPU_RESIDENT=1) --------------------

    def _flat_endpoints(self, bt: ck.BatchTensors):
        """All endpoint key rows of a (possibly [k]-leading) batch, flat in
        (read_begin, read_end, write_begin, write_end) section order."""
        rb = np.asarray(bt.read_begin)
        lead = rb.shape[:-3]
        b, r, w = rb.shape[-3:]
        q = np.asarray(bt.write_begin).shape[-2]
        flat = np.concatenate([
            rb.reshape(-1, w),
            np.asarray(bt.read_end).reshape(-1, w),
            np.asarray(bt.write_begin).reshape(-1, w),
            np.asarray(bt.write_end).reshape(-1, w),
        ])
        return flat, (lead, b, r, q, w)

    def _ranks_to_batch(self, bt: ck.BatchTensors, ranks: np.ndarray,
                        dims, delta_rows: np.ndarray) -> ck.ResidentBatch:
        """Reassemble flat endpoint ranks + a key delta into the device
        ResidentBatch (delta padded to the engine's static slot count)."""
        lead, b, r, q, w = dims
        nl = int(np.prod(lead)) if lead else 1
        n_r, n_q = nl * b * r, nl * b * q
        delta = np.full((self.dict_delta_slots, w), INT32_MAX, np.int32)
        delta[: len(delta_rows)] = delta_rows
        wb = ranks[2 * n_r : 2 * n_r + n_q].reshape(*lead, b, q)
        we = ranks[2 * n_r + n_q :].reshape(*lead, b, q)
        # The paint permutation, precomputed here (kernel RankBatch
        # docstring: rejected writes merge as delta-0 no-ops, so the sort
        # order is acceptance-independent and the device paint is pure
        # gathers). Introsort, per scan step: order within equal-rank
        # ties is irrelevant (the coverage cumsum at a tie run's last row
        # is order-independent and keep-last dedup erases the rest), so
        # the stable kind's extra pass buys nothing.
        paint = np.concatenate(
            [wb.reshape(*lead, b * q), we.reshape(*lead, b * q)], axis=-1
        )
        paint_src = np.argsort(paint, axis=-1).astype(np.int32)
        return ck.ResidentBatch(
            delta_keys=delta,
            ranks=ck.RankBatch(
                read_begin=ranks[:n_r].reshape(*lead, b, r),
                read_end=ranks[n_r : 2 * n_r].reshape(*lead, b, r),
                read_mask=np.asarray(bt.read_mask),
                write_begin=wb,
                write_end=we,
                write_mask=np.asarray(bt.write_mask),
                read_version=np.asarray(bt.read_version),
                txn_mask=np.asarray(bt.txn_mask),
                paint_src=paint_src,
            ),
        )

    def _note_write_fps(self, qu: np.ndarray, is_pad: np.ndarray,
                        dims) -> None:
        """Stash the pack's write-begin fingerprints for the admission
        filter feed (_collect records the ACCEPTED rows once verdicts
        land). The fingerprint IS admission.filter.u64_cols_fingerprint
        over the endpoint u64 columns — one shared definition, because
        the no-re-hash feed contract depends on record and probe staying
        bit-identical — so the feed costs a vectorized mix over rows
        already computed, never a key re-hash. Window-path packs ([k]-leading) skip the stash: the
        runtime role feed goes through Resolver.admission_filter there."""
        if self.admission_filter is None:
            return
        lead, b, r, q, _w = dims
        if lead:
            self._adm_stash = None
            return
        from foundationdb_tpu.admission.filter import u64_cols_fingerprint

        n_r, n_q = b * r, b * q
        sect = slice(2 * n_r, 2 * n_r + n_q)
        fps = u64_cols_fingerprint(qu[sect])
        self._adm_stash = (fps.reshape(b, q), (~is_pad[sect]).reshape(b, q))

    def _pack_resident(self, bt: ck.BatchTensors, defer_repack: bool = False):
        """Rank-space pack against the resident mirror: classify every
        endpoint as hit (already resident) or miss, emit the sorted-unique
        miss set as the dispatch's dictionary DELTA, and rewrite endpoints
        as ranks into the POST-merge dictionary — pure host arithmetic,
        no np.unique over the full endpoint set and no dictionary ship.

        Overflow (delta too large / dictionary full) or fragmentation
        forces a FULL REPACK, which needs exact device liveness: inline on
        the dispatching thread, or — on the threaded window path
        (``defer_repack``) — deferred to dispatch_window via _RepackPlan
        with the mirror gate held so later packs wait for the new mirror."""
        mir = self._mirror
        mir.gate.wait()
        flat, dims = self._flat_endpoints(bt)
        qu = _rows_to_u64(flat)
        # All-inf pad rows map bijectively to one u64 row — comparing the
        # (half-width) u64 columns beats a W-word reduce on the hot path.
        pad = _rows_to_u64(np.full((1, dims[-1]), INT32_MAX, np.int32))[0]
        is_pad = qu[:, 0] == pad[0]
        for j in range(1, qu.shape[1]):
            is_pad &= qu[:, j] == pad[j]
        ids = mir.probe(qu, ~is_pad)
        found = ids >= 0
        if self.tiered:
            # Cold-tier hits (probe found a demoted id) re-enter through
            # the SAME never-seen-key delta: a promotion is just a delta
            # row whose id already exists. Only hot hits skip the delta.
            hot_hit = np.zeros(len(ids), bool)
            f = np.flatnonzero(found)
            hot_hit[f] = mir.hot_by_id[ids[f]]
            miss = ~hot_hit & ~is_pad
        else:
            hot_hit = found
            miss = ~found & ~is_pad
        mi = np.flatnonzero(miss)
        if mi.size:
            new_u64, new_rows = _u64_unique_sorted(qu[mi], flat[mi])
        else:
            new_u64 = np.zeros((0, qu.shape[1]), np.uint64)
            new_rows = np.zeros((0, dims[-1]), np.int32)
        m = len(new_u64)
        cv = self._last_commit
        need_repack = (
            m > self.dict_delta_slots
            or (not self.tiered and mir.n + m > mir.capacity)
            or mir.frag_due(self.oldest_version)
        )
        if need_repack:
            if defer_repack:
                mir.gate.clear()
                mir.stats["repack_stalls"] += 1
                return _RepackPlan(bt, qu, is_pad, new_u64, new_rows, dims, cv)
            return self._repack_and_rank(
                _RepackPlan(bt, qu, is_pad, new_u64, new_rows, dims, cv)
            )
        if self.tiered and mir.n + m > self._demote_watermark:
            if defer_repack:
                # Same deferral contract as _RepackPlan: victim selection
                # needs the exact-liveness device sync, so the packing
                # thread hands the window to dispatch with the gate held.
                mir.gate.clear()
                mir.stats["demotion_stalls"] += 1
                return _DemotePlan(bt, qu, is_pad, new_u64, new_rows, dims, cv)
            self._demote_now(m, protect=(qu, is_pad))
            if mir.n + m > mir.capacity:
                # Demotion could not free enough room (victims all
                # pinned, device-live or recent): the honest full-repack
                # fallback — the thrash pathology obs/doctor flags.
                return self._repack_and_rank(
                    _RepackPlan(bt, qu, is_pad, new_u64, new_rows, dims, cv)
                )
        with mir.lock:
            mir.touch(ids[hot_hit], cv)
            if m:
                pos = _u64_searchsorted(new_u64, qu[mi], "left")
                if self.tiered:
                    # Every miss is in the new set: its index there maps
                    # it to its existing cold id (promotion) or -1 (new).
                    row_ids = np.full(m, -1, np.int64)
                    row_ids[pos] = ids[mi]
                    new_ids = mir.insert_new(new_u64, new_rows, cv,
                                             ids=row_ids)
                else:
                    # Every miss is in the new set: its index there is its
                    # id.
                    new_ids = mir.insert_new(new_u64, new_rows, cv)
                ids[mi] = new_ids[pos]
            # Post-merge rank = current sorted position of the id.
            ranks = mir.rank_of_id[np.maximum(ids, 0)].astype(np.int32)
            ranks[is_pad | (ids < 0)] = INT32_MAX
            st = mir.stats
            st["dispatches"] += 1
            st["endpoints"] += int((~is_pad).sum())
            st["endpoint_hits"] += int(hot_hit.sum())
            fid = ids[hot_hit]
            uniq_found = (
                int(np.bincount(fid, minlength=1).astype(bool).sum())
                if fid.size else 0
            )
            st["unique_keys"] += m + uniq_found
            st["delta_new_keys"] += m
        self._note_write_fps(qu, is_pad, dims)
        return self._ranks_to_batch(bt, ranks, dims, new_rows)

    def _device_live_ranks(self) -> np.ndarray:
        """Exact dictionary liveness: every rank the device history still
        references (device sync — the repack-only cost). Sorted unique."""
        hist = self.state.hist
        if isinstance(hist, ck.HistState):
            arrays = [hist.base.keys, hist.delta.keys]
        else:
            arrays = [hist.keys]
        ranks = np.concatenate(
            [np.asarray(a)[..., 0].reshape(-1) for a in arrays]
        )
        live = np.unique(ranks[ranks != INT32_MAX])
        return live[(live >= 0) & (live < self._mirror.n)]

    def _repack_and_rank(self, plan: _RepackPlan) -> ck.ResidentBatch:
        """Full dictionary repack: rebuild the dictionary from {live
        history ranks} ∪ {pinned} ∪ {this dispatch's keys} ∪ the most
        recently used survivors (oldest-last-used evicted first), ship it
        whole, and remap every device-held rank. The rare fallback the
        per-delta path buys its way out of; also the cold-start path."""
        mir = self._mirror
        with mir.lock:
            try:
                live = self._device_live_ranks()
                keep = np.zeros(mir.n, bool)
                keep[live] = True
                keep |= mir.pinned
                pos = _u64_searchsorted(mir.u64, plan.qu, "left")
                cand = np.minimum(pos, max(mir.n - 1, 0))
                found = (
                    (pos < mir.n)
                    & (mir.u64[cand] == plan.qu).all(axis=1)
                    & ~plan.is_pad
                )
                keep[pos[found]] = True  # this dispatch's keys stay
                mir.touch(mir.id_at[pos[found]], plan.cv)
                m = len(plan.new_u64)
                must = int(keep.sum())
                if must + m + 1 > mir.capacity + 1:
                    raise ValueError(
                        f"resident dictionary cannot fit {must} live/pinned"
                        f" + {m} new keys in capacity {mir.capacity};"
                        " raise dict_capacity / FDB_TPU_DICT_CAPACITY or"
                        " run with FDB_TPU_RESIDENT=0"
                    )
                # Fill remaining room newest-first, leaving delta headroom.
                used_sorted = mir.used_sorted()
                target = max(mir.capacity - self.dict_delta_slots - m, must)
                room = target - must
                cand_idx = np.flatnonzero(~keep)
                if room > 0 and cand_idx.size:
                    by_age = cand_idx[
                        np.argsort(used_sorted[cand_idx], kind="stable")
                    ]
                    keep[by_age[max(0, by_age.size - room):]] = True
                evicted = mir.n - int(keep.sum())

                kept_u64 = mir.u64[keep]
                kept_rows = mir.rows[keep]
                kept_used = used_sorted[keep]
                kept_pin = mir.pinned[keep]
                ins = _u64_searchsorted(kept_u64, plan.new_u64, "left")
                fin_u64 = np.insert(kept_u64, ins, plan.new_u64, axis=0)
                fin_rows = np.insert(kept_rows, ins, plan.new_rows, axis=0)
                fin_used = np.insert(kept_used, ins, plan.cv)
                fin_pin = np.insert(kept_pin, ins, False)
                n_new = len(fin_u64)

                # remap: exact new rank for every kept old rank; dropped
                # ranks get their insertion point (provably dead — never
                # gathered by the device).
                remap = np.zeros(mir.capacity + 1, np.int32)
                remap[: mir.n] = _u64_searchsorted(
                    fin_u64, mir.u64, "left"
                ).astype(np.int32)
                dict_dev = np.full(
                    (mir.capacity + 1, fin_rows.shape[1]), INT32_MAX, np.int32
                )
                dict_dev[:n_new] = fin_rows
                self.state = self._repack_fn(
                    self.state, dict_dev, np.int32(n_new), remap
                )
                mir.reset(fin_u64, fin_rows, fin_used, fin_pin)
                st = mir.stats
                st["full_repacks"] += 1
                st["evictions"] += evicted
                st["dispatches"] += 1
                st["endpoints"] += int((~plan.is_pad).sum())
                st["endpoint_hits"] += int(found.sum())
                st["unique_keys"] += m + int(np.unique(pos[found]).size)
                st["delta_new_keys"] += m

                # Ranks against the rebuilt mirror; the delta already rode
                # in with the repack, so the device delta is empty.
                ranks = _u64_searchsorted(fin_u64, plan.qu, "left").astype(
                    np.int32
                )
                ranks[plan.is_pad] = INT32_MAX
            finally:
                mir.gate.set()
        self._note_write_fps(plan.qu, plan.is_pad, plan.dims)
        return self._ranks_to_batch(
            plan.bt, ranks, plan.dims,
            np.zeros((0, plan.dims[-1]), np.int32),
        )

    def _demote_now(self, incoming: int, protect=None) -> int:
        """Demote cold hot-tier keys to the host cold store (dispatch
        thread only — selection needs the exact-liveness device sync).

        Victim policy, in exclusion order: pinned min/bound keys never
        move; ranks the device history still references (exact
        _device_live_ranks) stay — evicting one would skew every younger
        rank through the shift table; keys used inside the in-flight MVCC
        window (last_used >= oldest_version) stay; the current dispatch's
        keys (``protect`` = its probed u64 set) stay; and when an
        admission filter is attached, keys its recency banks report
        maybe-written since the floor stay. Survivors demote
        oldest-last-used first, shipped as static-width _evict_res_jit
        rank deltas (a few KiB) — never a full repack. Returns the count
        actually demoted (0 = nothing safely evictable)."""
        mir = self._mirror
        with mir.lock:
            used = mir.used_sorted()
            cand = ~mir.pinned & (used < self.oldest_version)
            cand[self._device_live_ranks()] = False
            if protect is not None:
                qu, is_pad = protect
                pids = mir.probe(qu, ~is_pad)
                pf = pids[pids >= 0]
                hot = pf[mir.hot_by_id[pf]]
                cand[mir.rank_of_id[hot]] = False
            if self.admission_filter is not None:
                idx = np.flatnonzero(cand)
                if idx.size:
                    from foundationdb_tpu.admission.filter import (
                        u64_cols_fingerprint,
                    )
                    recent = np.asarray(
                        self.admission_filter.probe_u64(
                            u64_cols_fingerprint(mir.u64[idx]),
                            self.oldest_version,
                        )
                    )
                    cand[idx[recent]] = False
            idx = np.flatnonzero(cand)
            if not idx.size:
                return 0
            # Free past the watermark plus half a batch of hysteresis so
            # the next few windows' deltas fit without demoting again.
            over = mir.n + incoming - self._demote_watermark
            want = min(idx.size,
                       max(over, 0) + max(1, self._demote_slots // 2))
            victims = idx[np.argsort(used[idx], kind="stable")[:want]]
            order = np.sort(victims)
            done = 0
            while done < len(order):
                # Chunks ascend, so every previously evicted rank sits
                # below this chunk: the device-rank adjustment is exactly
                # the count already gone.
                chunk = order[done : done + self._demote_slots] - done
                ev = np.full(self._demote_slots, INT32_MAX, np.int32)
                ev[: len(chunk)] = chunk.astype(np.int32)
                self.state = self._evict_fn(self.state, ev)
                mir.stats["demotion_bytes"] += 4 * self._demote_slots
                done += len(chunk)
            mir.demote(order)
            mir.stats["demotion_events"] += 1
            return len(order)

    def _demote_and_rank(self, plan: _DemotePlan) -> ck.ResidentBatch:
        """Execute a deferred demotion on the dispatch thread (every
        earlier window has dispatched, so liveness is exact — the same
        ordering argument as the deferred _RepackPlan), reopen the gate,
        then re-pack the stalled window inline: the inline path
        re-derives hits/promotions against the post-demotion mirror and
        itself escalates to a full repack if demotion could not free
        enough room."""
        try:
            self._demote_now(len(plan.new_u64),
                             protect=(plan.qu, plan.is_pad))
        finally:
            self._mirror.gate.set()
        return self._pack_resident(plan.bt)

    @property
    def dict_stats(self) -> dict | None:
        """Dictionary-economics counters (None unless resident): unique
        keys/dispatch, delta hit rate, evictions, forced full repacks."""
        if self._mirror is None:
            return None
        s = dict(self._mirror.stats)
        d = max(1, s["dispatches"])
        e = max(1, s["endpoints"])
        s.update(
            resident_keys=self._mirror.n,
            dict_capacity=self._mirror.capacity,
            delta_slots=self.dict_delta_slots,
            unique_keys_per_dispatch=round(s["unique_keys"] / d, 1),
            delta_hit_rate=round(s["endpoint_hits"] / e, 4),
            # Tier economics (inert zeros when tiering is off):
            tiered=self.tiered,
            dict_hot_occupancy=round(
                self._mirror.n / max(1, self._mirror.capacity), 4
            ),
            cold_tier_keys=self._mirror.cold_n,
            demotion_bytes_per_dispatch=round(s["demotion_bytes"] / d, 1),
            # What ONE full repack ships host->device (the packed dict
            # rows + the rank-shift table) — the per-event counterfactual
            # the demotion delta replaces. The A/B multiplies this by
            # demotion_events to price the no-evict design.
            full_repack_ship_bytes=(self._mirror.capacity + 1) * 4
            * (self._mirror.rows.shape[1] + 1),
        )
        return s

    # -- public API ---------------------------------------------------------

    def resolve(
        self,
        txns: list[TxnConflictInfo],
        commit_version: int,
        oldest_version: int | None = None,
    ) -> list[Verdict]:
        return self.resolve_async(txns, commit_version, oldest_version)()

    def resolve_async(
        self,
        txns: list[TxnConflictInfo],
        commit_version: int,
        oldest_version: int | None = None,
    ) -> Callable[[], list[Verdict]]:
        """Dispatch every chunk to the device immediately and return a
        collector. The caller (resolver role, bench) packs/dispatches the
        NEXT batch while the device still computes this one — materializing
        verdicts (the device→host sync) is deferred to the collector.

        When some txn set report_conflicting_keys (and the engine compiled
        a report entry point), the kernel's loser-range mask rides along
        and the collector populates ``last_conflicting`` — exact
        conflicting read ranges per txn index, the same surface the oracle
        provides."""
        can_report = getattr(self, "_resolve_report_fn", None) is not None
        self._spec_drain_serial()
        self._begin_resolve(commit_version, oldest_version)
        cv = np.int32(self._rel(commit_version))
        oldest = np.int32(self._rel(self.oldest_version))
        pending: list[tuple] = []
        for i in range(0, len(txns), self.batch_size):
            chunk = txns[i : i + self.batch_size]
            # Per CHUNK: only chunks that actually contain a reporting txn
            # pay the report program + host-side range bookkeeping.
            if can_report and any(t.report_conflicting_keys for t in chunk):
                batch, reads = self._pack(chunk, collect_reads=True)
                # Pack BEFORE reading self.state: a resident-dictionary
                # repack inside the packer replaces (and donates) it.
                dev = self._dev_batch(batch)
                out = self._resolve_report_fn(self.state, dev, cv, oldest)
                verdicts, levels, losers, self.state = (
                    out if self.wave_commit else (out[0], None, *out[1:])
                )
                flags = [t.report_conflicting_keys for t in chunk]
                pending.append(
                    (verdicts, len(chunk), losers, reads, flags, levels,
                     self._take_adm(commit_version))
                )
            else:
                batch = self._pack(chunk)
                dev = self._dev_batch(batch)  # may repack: order matters
                out = self._resolve_fn(self.state, dev, cv, oldest)
                verdicts, levels, self.state = (
                    out if self.wave_commit else (out[0], None, out[1])
                )
                pending.append((verdicts, len(chunk), None, None, None,
                                levels, self._take_adm(commit_version)))
        return lambda: self._collect(pending)

    def _take_adm(self, commit_version: int):
        """Claim the last pack's admission write-fingerprint stash, BOUND
        to its resolve's commit version (None when no filter is attached /
        window-path pack). The version rides in the pending tuple — NOT
        instance state — because deferred collectors pipeline: a later
        dispatch must not relabel an earlier dispatch's write versions."""
        stash, self._adm_stash = self._adm_stash, None
        return None if stash is None else (stash, commit_version)

    def resolve_wire(
        self,
        wire: bytes | np.ndarray,
        commit_version: int,
        oldest_version: int | None = None,
        count: int | None = None,
    ) -> list[Verdict]:
        return self.resolve_wire_async(wire, commit_version, oldest_version, count)()

    def resolve_wire_async(
        self,
        wire: bytes | np.ndarray,
        commit_version: int,
        oldest_version: int | None = None,
        count: int | None = None,
        as_array: bool = False,
    ) -> Callable[[], list[Verdict]]:
        """The production hot path: a flat serialized resolver batch (see
        native/keypack.cpp for the wire format — the analogue of the
        reference's ResolveTransactionBatchRequest bytes) is packed into
        device tensors by one C pass, never touching per-txn Python objects."""
        buf = np.frombuffer(wire, dtype=np.uint8) if isinstance(wire, (bytes, bytearray)) else wire
        lib = _keypack_lib()
        # Structurally validate the WHOLE buffer before any dispatch: a chunk
        # failing mid-stream would leave earlier chunks' writes painted into
        # device history with no verdicts delivered (phantom conflicts
        # forever). kp_count_txns walks every record's bounds in one C pass.
        counted = int(lib.kp_count_txns(_u8(buf), buf.size, 0))
        if counted < 0 or (count is not None and count > counted):
            raise ValueError("malformed resolver wire batch")
        if count is None:
            count = counted
        self._spec_drain_serial()
        self._begin_resolve(commit_version, oldest_version)
        cv = np.int32(self._rel(commit_version))
        oldest = np.int32(self._rel(self.oldest_version))
        pending: list[tuple] = []
        offset, remaining = 0, count
        while remaining > 0:
            n = min(remaining, self.batch_size)
            batch, offset = self._pack_wire(buf, offset, n)
            dev = self._dev_batch(batch)  # may repack: order matters
            out = self._resolve_fn(self.state, dev, cv, oldest)
            verdicts, levels, self.state = (
                out if self.wave_commit else (out[0], None, out[1])
            )
            pending.append((verdicts, n, None, None, None, levels,
                            self._take_adm(commit_version)))
            remaining -= n
        if as_array:

            def collect_array():
                self._collect_waves(pending)
                self._feed_admission(pending)
                return np.concatenate(
                    [np.asarray(v)[:n] for v, n, *_rest in pending]
                )

            return collect_array
        return lambda: self._collect(pending)

    def resolve_wire_window(
        self,
        wire: bytes | np.ndarray,
        commit_versions,
        count: int,
    ) -> np.ndarray:
        return self.resolve_wire_window_async(wire, commit_versions, count)()

    def resolve_wire_window_async(
        self,
        wire: bytes | np.ndarray,
        commit_versions,
        count: int,
    ) -> Callable[[], np.ndarray]:
        """Resolve a WINDOW of k consecutive batches in one device dispatch.

        ``wire`` holds k·count txns; txns [i·count, (i+1)·count) resolve at
        ``commit_versions[i]`` (strictly increasing). One lax.scan program
        (conflict_kernel.resolve_many) replaces k dispatches — the host-side
        analogue of the reference proxy batching many commits per resolver
        RPC, here amortizing per-dispatch latency instead of network round
        trips. Returns a collector yielding verdicts int8 [k, count].

        Callers should keep k fixed across calls (each distinct k compiles
        its own program). The pack/dispatch halves are separately callable
        (``pack_wire_window`` / ``dispatch_window``) so a scheduler can
        double-buffer host packing against device execution.
        """
        return self.dispatch_window(
            self.pack_wire_window(wire, commit_versions, count)
        )

    def pack_wire_window(
        self,
        wire: bytes | np.ndarray,
        commit_versions,
        count: int,
    ) -> PreparedWindow:
        """Host half of the window path: validate, advance version
        bookkeeping, and pack wire bytes into device-format tensors. Pure
        host work (the device rebase, if one fell due, is DEFERRED into the
        PreparedWindow), so it may run on a packing thread concurrently
        with ``dispatch_window`` of the PREVIOUS window — never concurrently
        with another pack (packs are commit-version ordered)."""
        buf = (
            np.frombuffer(wire, dtype=np.uint8)
            if isinstance(wire, (bytes, bytearray))
            else wire
        )
        k = len(commit_versions)
        if count > self.batch_size:
            raise ValueError("window path resolves one kernel batch per version")
        lib = _keypack_lib()
        counted = int(lib.kp_count_txns(_u8(buf), buf.size, 0))
        if counted < k * count:
            raise ValueError("malformed resolver wire batch")

        # A raise below must leave the host bookkeeping untouched: with a
        # deferred rebase, base_version would otherwise run ahead of the
        # never-rebased device state and silently skew every later
        # window's relative versions. Restoring the snapshot makes a
        # failed pack fully transactional (host-only — thread-safe on the
        # packing thread).
        snap = (self.base_version, self.oldest_version, self._last_commit)
        try:
            rebase_delta = 0
            oldest_abs = np.empty(k, np.int64)
            for i, cv in enumerate(commit_versions):
                rebase_delta += self._begin_resolve(
                    int(cv), None, defer_rebase=True
                )
                oldest_abs[i] = self.oldest_version
            # base_version is final after all _begin_resolve rebases —
            # convert now. A rebase mid-window can lift base above floors
            # snapshotted earlier; clamp those to 0 (everything below base
            # is already expired on device, so a zero floor is exact — the
            # kernel takes max(state.oldest, new_oldest), never regresses).
            cvs_rel = np.asarray(
                [self._rel(int(cv)) for cv in commit_versions], np.int32
            )
            olds_rel = np.asarray(
                [max(0, int(v) - self.base_version) for v in oldest_abs],
                np.int32,
            )

            if self._native_window_pack:
                # Fused C pass: wire walk + padding + the per-batch
                # dictionary dedup/sort/rank emission that _pack_dict pays
                # in numpy — the host half of the speculative pipeline,
                # sized so packing N+2 never stalls the device on N+1.
                dev_batch = self._pack_window_native(buf, k, count)
            else:
                batches = self._empty_batch(k)
                offset = 0
                for i in range(k):
                    offset = lib.kp_pack_batch(
                        _u8(buf), buf.size, offset, count,
                        self.batch_size, self.max_read_ranges,
                        self.max_write_ranges,
                        self.codec.n_words, self.base_version,
                        _i32(batches.read_begin[i]), _i32(batches.read_end[i]),
                        _u8(batches.read_mask[i]),
                        _i32(batches.write_begin[i]), _i32(batches.write_end[i]),
                        _u8(batches.write_mask[i]),
                        _i32(batches.read_version[i]), _u8(batches.txn_mask[i]),
                    )
                    if offset < 0:
                        raise ValueError("malformed resolver wire batch")
                # The deferred-repack packer variant: a resident-dictionary
                # overflow on the packing thread becomes a _RepackPlan
                # executed by dispatch_window (which may sync device
                # state), not an inline repack here.
                dev_batch = self._dev_batch_deferred(batches)
        except BaseException:
            self.base_version, self.oldest_version, self._last_commit = snap
            raise
        return PreparedWindow(
            batch=dev_batch,
            cvs_rel=cvs_rel,
            olds_rel=olds_rel,
            count=count,
            rebase_delta=rebase_delta,
        )

    @property
    def _native_window_pack(self) -> bool:
        """Use the fused native window packer (kp_pack_window)? Gated to
        the speculative non-resident packed path — the arm whose pipeline
        the fused pack exists to feed (the resident path already replaced
        _pack_dict with the mirror; serial stays the honest A/B baseline).
        FDB_TPU_NATIVE_WINDOW_PACK=0 forces the numpy packer for parity
        tests; a stale prebuilt .so without the symbol degrades silently."""
        if self._nat_win is None:
            self._nat_win = (
                self.spec
                and not self.resident
                and os.environ.get("FDB_TPU_NATIVE_WINDOW_PACK", "1") != "0"
                and hasattr(_keypack_lib(), "kp_pack_window")
            )
        return self._nat_win

    def _pack_window_native(self, buf: np.ndarray, k: int,
                            count: int) -> ck.PackedBatch:
        """One kp_pack_window call → the window's PackedBatch (rank layout
        bit-identical to _pack_dict over kp_pack_batch output)."""
        lib = _keypack_lib()
        b, r, q = self.batch_size, self.max_read_ranges, self.max_write_ranges
        w = self.codec.width
        n = 2 * b * (r + q)
        bt = self._empty_batch(k)
        dict_keys = np.full((k, n + 1, w), INT32_MAX, np.int32)
        rb_rank = np.empty((k, b, r), np.int32)
        re_rank = np.empty((k, b, r), np.int32)
        wb_rank = np.empty((k, b, q), np.int32)
        we_rank = np.empty((k, b, q), np.int32)
        off = lib.kp_pack_window(
            _u8(buf), buf.size, 0, k, count, b, r, q,
            self.codec.n_words, self.base_version,
            _i32(bt.read_begin), _i32(bt.read_end), _u8(bt.read_mask),
            _i32(bt.write_begin), _i32(bt.write_end), _u8(bt.write_mask),
            _i32(bt.read_version), _u8(bt.txn_mask),
            _i32(dict_keys), _i32(rb_rank), _i32(re_rank),
            _i32(wb_rank), _i32(we_rank),
        )
        if off < 0:
            raise ValueError("malformed resolver wire batch")
        return ck.PackedBatch(
            dict_keys=dict_keys,
            read_begin=rb_rank,
            read_end=re_rank,
            read_mask=bt.read_mask,
            write_begin=wb_rank,
            write_end=we_rank,
            write_mask=bt.write_mask,
            read_version=bt.read_version,
            txn_mask=bt.txn_mask,
        )

    def dispatch_window(self, prepared: PreparedWindow) -> Callable[[], np.ndarray]:
        """Device half of the window path: thread state through the scan
        program. Must run on the dispatching thread, in the same order the
        windows were packed.

        Speculative engines route through the reconcile ring: the dispatch
        happens immediately against the optimistically advanced state, and
        the returned collector reconciles (in FIFO order) before
        materializing verdicts — callers like the bench loop and
        PipelinedWindowRunner see the same collector contract either way."""
        if self.spec:
            seq = self.spec_dispatch_window(prepared)

            def collect_spec() -> np.ndarray:
                while seq not in self._spec_done:
                    self.reconcile_window()
                verdicts, levels = self._spec_done.pop(seq)
                if self.wave_commit:
                    self.last_wave_window = levels
                return verdicts

            return collect_spec
        if prepared.rebase_delta:
            self.state = self._rebase_fn(
                self.state, np.int32(min(prepared.rebase_delta, 2**31 - 1))
            )
        batch = prepared.batch
        if isinstance(batch, _RepackPlan):
            # Deferred resident repack: runs here because every earlier
            # window has dispatched, so the device liveness sync is exact
            # and the rank remap lands between window N-1 and N — the same
            # position it holds in the mirror's history.
            batch = self._repack_and_rank(batch)
        elif isinstance(batch, _DemotePlan):
            # Deferred tiered demotion: same exactness argument, but the
            # device traffic is an evict rank vector, not a dictionary.
            batch = self._demote_and_rank(batch)
        out = self._resolve_many_fn(
            self.state, batch, prepared.cvs_rel, prepared.olds_rel
        )
        verdicts, levels, self.state = (
            out if self.wave_commit else (out[0], None, out[1])
        )
        if not self.wave_commit:
            return lambda: np.asarray(verdicts)[:, : prepared.count]

        def collect():
            # Waves are PER BATCH on the window path (batches already
            # serialize by commit version); publish int32 [k, count].
            self.last_wave_window = np.asarray(levels)[:, : prepared.count]
            return np.asarray(verdicts)[:, : prepared.count]

        return collect

    # -- speculative pipelined resolve (FDB_TPU_SPEC_RESOLVE=1) ---------------
    #
    # The resolve programs above paint accepted writes in the SAME device
    # program that decides them, so by the time window N's verdicts are
    # materialized on the host — let alone confirmed durable by the upper
    # layer (tlog push, ratekeeper) — the device state has already
    # advanced optimistically. Serial mode serializes anyway: it waits
    # for N's collector before dispatching N+1. Speculative mode
    # dispatches N+1 immediately and keeps a bounded FIFO ring of
    # unconfirmed windows; when N's confirmation lands (or the ring
    # fills), reconcile either confirms (the overwhelmingly common case —
    # drop N's snapshot, done) or rolls the state back to N's snapshot,
    # re-paints N with only the confirmed accepts, and repairs every
    # younger in-flight window against the corrected history. A
    # dependency probe (reads of the younger window vs N's rejected
    # writes, probed through the packed batch dictionary) distinguishes
    # windows whose verdicts provably survived (paint-only re-advance)
    # from windows that must re-resolve (the repair path — only
    # genuinely-conflicted txns flip). Serializability is therefore
    # preserved by construction; the A/B harness additionally replays
    # both arms through a fresh serial engine and compares verdict bytes.

    def spec_dispatch_window(self, prepared: PreparedWindow) -> int:
        """Dispatch a packed window speculatively; returns its reconcile
        sequence id. Must run on the dispatching thread, in pack order
        (same contract as dispatch_window)."""
        if not self.spec:
            raise ValueError("speculative resolve is off for this engine "
                             "(FDB_TPU_SPEC_RESOLVE=1 / spec_resolve=True)")
        while len(self._spec_ring) >= self.spec_depth:
            self.reconcile_window()
        if prepared.rebase_delta:
            # Pending snapshots are in pre-rebase version coordinates —
            # a rebase under them would corrupt every rollback target.
            # Rebases are ~once per 2^30 versions; draining first is free.
            self.reconcile_all()
            self.state = self._rebase_fn(
                self.state, np.int32(min(prepared.rebase_delta, 2**31 - 1))
            )
        batch = prepared.batch
        if isinstance(batch, _RepackPlan):
            # A resident-dictionary repack rebuilds the rank space from
            # exact device liveness — not a rollback-able operation, and
            # the liveness sync must not see unconfirmed writes. Drain.
            self.reconcile_all()
            batch = self._repack_and_rank(batch)
        elif isinstance(batch, _DemotePlan):
            # Demotion shares the repack's constraints: the liveness sync
            # must not see unconfirmed speculative paints, and evicting a
            # rank is not rollback-able (snapshots hold pre-evict ranks).
            self.reconcile_all()
            batch = self._demote_and_rank(batch)
        snap = ck._snapshot_jit(self.state)
        out = self._resolve_many_fn(
            self.state, batch, prepared.cvs_rel, prepared.olds_rel
        )
        verdicts, levels, self.state = (
            out if self.wave_commit else (out[0], None, out[1])
        )
        seq = self._spec_seq
        self._spec_seq += 1
        self._spec_ring.append(_SpecPending(
            seq=seq, snapshot=snap, batch=batch,
            cvs_rel=prepared.cvs_rel, olds_rel=prepared.olds_rel,
            count=prepared.count, verdicts=verdicts, levels=levels,
        ))
        self._spec_stats["spec_dispatched"] += 1
        return seq

    def _spec_accept_mask(self, batch, verdicts, levels) -> np.ndarray:
        """bool [k, B]: which txns this dispatch ACCEPTED (i.e. painted).
        Wave engines: committed at some wave (levels >= 0 — padding is
        excluded by construction). Plain engines: verdict COMMITTED ∧
        txn_mask (padded slots get verdict 0 from assemble_verdicts and
        MUST be masked out)."""
        if levels is not None:
            return np.asarray(levels) >= 0
        txn_mask = (batch.ranks.txn_mask if isinstance(batch, ck.ResidentBatch)
                    else batch.txn_mask)
        return (np.asarray(verdicts) == 0) & np.asarray(txn_mask)

    def reconcile_window(self, confirmed: np.ndarray | None = None) -> np.ndarray:
        """Reconcile the OLDEST in-flight window against its upper-layer
        confirmation; returns its verdicts int8 [k, count] (also stashed
        for the window's dispatch collector).

        ``confirmed`` is a bool [k, count] mask (False = the upper layer
        revoked this txn's speculative outcome); None consults
        ``spec_confirm_hook``, and a None hook confirms everything. The
        window's own verdicts are returned UNCHANGED — an upper-layer
        revocation is an upper-layer abort, not a resolver verdict; what
        reconcile repairs is the HISTORY (revoked writes un-painted) and
        every younger window that speculated on it."""
        p = self._spec_ring.popleft()
        verdicts_np = np.asarray(p.verdicts)[:, : p.count]
        levels_np = (None if p.levels is None
                     else np.asarray(p.levels)[:, : p.count])
        spec_acc = self._spec_accept_mask(p.batch, p.verdicts, p.levels)
        k, b = spec_acc.shape
        if confirmed is None and self.spec_confirm_hook is not None:
            confirmed = self.spec_confirm_hook(p.seq, verdicts_np)
        if confirmed is None:
            rejected = np.zeros((k, b), bool)
        else:
            conf = np.zeros((k, b), bool)
            conf[:, : p.count] = np.asarray(confirmed, bool)[:, : p.count]
            rejected = spec_acc & ~conf
        if not rejected.any():
            self._spec_stats["spec_confirmed"] += 1
            self._spec_done[p.seq] = (verdicts_np, levels_np)
            return verdicts_np  # snapshot drops here — state already right

        # -- mis-speculation: rollback + repair --------------------------
        self._spec_stats["spec_repaired"] += 1
        self._spec_stats["chain_rolls"] += 1
        # 1) Roll the live state back to before this window (pointer swap
        #    to the snapshot; it becomes the live state and is donated by
        #    the paint below, so no extra buffer lingers).
        self.state = p.snapshot
        # 2) Re-advance with ONLY the confirmed accepts: a paint-only pass
        #    with a host-forced mask — the same merge/GC/paint pipeline,
        #    minus the verdict decision the upper layer overrode.
        self.state = self._paint_many_fn(
            self.state, p.batch, spec_acc & ~rejected,
            p.cvs_rel, p.olds_rel,
        )
        # 3) Repair every younger in-flight window against the corrected
        #    history, in dispatch order. The dependency probe says which
        #    ones provably kept their verdicts (reads never touched a
        #    rejected write → paint-only re-advance) and which must
        #    re-resolve (the repair path; only genuinely-conflicted txns
        #    flip).
        younger = list(self._spec_ring)
        self._spec_ring.clear()
        deps = self._spec_dep_windows(p.batch, rejected, younger)
        for y, dep in zip(younger, deps):
            snap = ck._snapshot_jit(self.state)
            if dep:
                out = self._resolve_many_fn(
                    self.state, y.batch, y.cvs_rel, y.olds_rel
                )
                nv, nl, self.state = (
                    out if self.wave_commit else (out[0], None, out[1])
                )
                old_acc = self._spec_accept_mask(y.batch, y.verdicts, y.levels)
                new_acc = self._spec_accept_mask(y.batch, nv, nl)
                self._spec_stats["spec_flipped"] += int(
                    (old_acc != new_acc)[:, : y.count].sum()
                )
                y = y._replace(snapshot=snap, verdicts=nv, levels=nl)
            else:
                acc = self._spec_accept_mask(y.batch, y.verdicts, y.levels)
                self.state = self._paint_many_fn(
                    self.state, y.batch, acc, y.cvs_rel, y.olds_rel
                )
                y = y._replace(snapshot=snap)
            self._spec_ring.append(y)
        self._spec_done[p.seq] = (verdicts_np, levels_np)
        return verdicts_np

    def _spec_dep_windows(self, batch, rejected: np.ndarray,
                          younger: list[_SpecPending]) -> list[bool]:
        """Per younger window: did ANY of its reads overlap a write the
        reconciling window's confirmation rejected? Rejected writes are
        painted into a small scratch step function at +inf version, then
        each younger window's batch dictionary probes it — a clean probe
        proves the window's verdicts survived (its floor and intra-window
        graph are unchanged, and no read saw a rejected boundary).
        Resident engines skip the probe (batch ranks live in per-window
        coordinate systems the scratch can't share) and repair
        pessimistically — still exact, just never paint-only."""
        if not younger:
            return []
        if self.resident:
            return [True] * len(younger)
        k, b = rejected.shape
        cap = min(self.capacity, 2 * k * b * self.max_write_ranges + 2)
        scratch = ck.init_state(cap, self.codec.width, self.codec.min_key)
        scratch = ck._spec_mark_rejected_jit(scratch, batch, rejected)
        return [
            bool(np.asarray(ck._spec_dep_window_jit(scratch, y.batch)))
            for y in younger
        ]

    def reconcile_all(self) -> None:
        """Drain the in-flight ring (confirmations via spec_confirm_hook).
        Serial entry points and non-rollback-able device ops (rebase,
        resident repack) call this before touching state."""
        while self._spec_ring:
            self.reconcile_window()

    def _spec_drain_serial(self) -> None:
        """Guard for serial-path entry points on a speculative engine:
        in-flight windows must confirm/repair before state is read or
        advanced outside the ring."""
        if self._spec_ring:
            self.reconcile_all()

    def spec_metrics(self) -> dict:
        """Counters for the obs plane (resolver.get_metrics mirrors these;
        ratekeeper clamps speculation depth on the repair rate)."""
        out = dict(self._spec_stats)
        out["spec_depth"] = len(self._spec_ring)
        return out

    def spec_resolve_async(self, txns, commit_version: int,
                           oldest_version: int | None = None):
        """Object-path speculative dispatch (the resolver role's seam):
        one chunk lifted to a k=1 window through the same ring. Returns a
        collector yielding list[Verdict], or None when this batch can't
        speculate (oversized → chunking serializes anyway; a reporting txn
        needs the report program) — the caller falls back to the serial
        path after reconcile_all().

        Admission-filter feeding is skipped under speculation (the filter
        is advisory recency state; feeding optimistic accepts could
        poison it on revocation)."""
        if (not self.spec or len(txns) > self.batch_size
                or any(t.report_conflicting_keys for t in txns)):
            return None
        while len(self._spec_ring) >= self.spec_depth:
            self.reconcile_window()
        delta = self._begin_resolve(commit_version, oldest_version,
                                    defer_rebase=True)
        if delta:
            self.reconcile_all()
            self.state = self._rebase_fn(
                self.state, np.int32(min(delta, 2**31 - 1))
            )
        cv_rel = np.asarray([self._rel(commit_version)], np.int32)
        old_rel = np.asarray([self._rel(self.oldest_version)], np.int32)
        batch = self._pack(txns)
        self._adm_stash = None
        dev = self._dev_batch_deferred(batch)
        if isinstance(dev, _RepackPlan):
            self.reconcile_all()
            dev = self._repack_and_rank(dev)
        elif isinstance(dev, _DemotePlan):
            self.reconcile_all()
            dev = self._demote_and_rank(dev)
        if isinstance(dev, ck.ResidentBatch):
            # k=1 lift: the scan axis goes on the ranks; the key delta is
            # per-window (merged once) exactly as the window packer emits.
            dev = dev._replace(ranks=type(dev.ranks)(
                *(np.asarray(f)[None] for f in dev.ranks)
            ))
        else:
            dev = type(dev)(*(np.asarray(f)[None] for f in dev))
        snap = ck._snapshot_jit(self.state)
        out = self._resolve_many_fn(self.state, dev, cv_rel, old_rel)
        verdicts, levels, self.state = (
            out if self.wave_commit else (out[0], None, out[1])
        )
        seq = self._spec_seq
        self._spec_seq += 1
        self._spec_ring.append(_SpecPending(
            seq=seq, snapshot=snap, batch=dev, cvs_rel=cv_rel,
            olds_rel=old_rel, count=len(txns), verdicts=verdicts,
            levels=levels,
        ))
        self._spec_stats["spec_dispatched"] += 1

        def collect() -> list[Verdict]:
            while seq not in self._spec_done:
                self.reconcile_window()
            v, lv = self._spec_done.pop(seq)
            if self.wave_commit and lv is not None:
                row = lv[0]
                self.last_wave = [int(x) for x in row]
                self.last_reordered = int((row > 0).sum())
            return [Verdict(int(x)) for x in v[0]]

        return collect

    # -- role-level global wave protocol (core/wavemesh) ----------------------

    @property
    def wave_global_capable(self) -> bool:
        """Does this engine implement the two-phase global wave protocol
        (resolve_edges/resolve_apply)? True for single-chip wave-commit
        engines; the mesh-sharded subclass exchanges edges on-device
        inside one program and is a self-contained single resolver from
        the role's perspective (it reports False — a deployment sharding
        ABOVE a mesh engine would need edges of edges)."""
        return self.wave_commit and self._wave_edges_fn is not None

    def resolve_edges(
        self,
        txns: list[TxnConflictInfo],
        commit_version: int,
        oldest_version: int | None = None,
    ):
        """Phase 1 of the global wave protocol: gate this shard's CLIPPED
        view of the window (TOO_OLD + history conflicts) and build its
        clipped predecessor bitsets, WITHOUT painting. The packed device
        batches stay stashed until resolve_apply consumes the combined
        graph — one pack serves both phases. Returns a wavemesh.WaveEdges
        payload (per-chunk packed uint32 matrices) for the commit proxy's
        OR-reduce."""
        from foundationdb_tpu.core.wavemesh import WaveEdges

        if not self.wave_global_capable:
            raise ValueError(
                "resolve_edges requires a wave-commit engine with the "
                "two-phase entry points (wave_commit=True)"
            )
        if self._wave_pending is not None:
            raise ValueError(
                "resolve_edges with an apply outstanding: the previous "
                "window's resolve_apply must land first (version chain)"
            )
        if len(txns) > self.batch_size:
            # The protocol exchanges ONE schedule domain per window. The
            # single-engine path chunks oversized windows and serializes
            # them THROUGH the history (chunk k+1's gate sees chunk k's
            # paints — cross-chunk read-write pairs abort); a one-shot
            # edge exchange gates every chunk against the pre-window
            # history and would silently commit those pairs. Callers
            # (the commit proxy) must keep wave batches within one
            # engine chunk.
            raise ValueError(
                f"global wave window of {len(txns)} txns exceeds the "
                f"engine chunk ({self.batch_size}): one exchange carries "
                "one schedule domain"
            )
        self._spec_drain_serial()
        self._begin_resolve(commit_version, oldest_version)
        cv = np.int32(self._rel(commit_version))
        oldest = np.int32(self._rel(self.oldest_version))
        # The guard above pins the one-window-one-chunk invariant, so the
        # payload is exactly one chunk (or none for an empty window).
        n = len(txns)
        if not n:
            self._wave_pending = ([], commit_version)
            return WaveEdges(
                count=0, too_old=np.zeros(0, bool),
                hist_conflict=np.zeros(0, bool), chunks=[],
            )
        batch = self._pack(txns)
        dev = self._dev_batch(batch)
        if self.resident:
            too_old, hist_c, p, self.state = self._wave_edges_fn(
                self.state, dev, oldest
            )
        else:
            too_old, hist_c, p = self._wave_edges_fn(self.state, dev, oldest)
        self._wave_pending = (
            [(dev, n, cv, oldest, self._take_adm(commit_version))],
            commit_version,
        )
        return WaveEdges(
            count=n,
            too_old=np.asarray(too_old)[:n],
            hist_conflict=np.asarray(hist_c)[:n],
            chunks=[(n, np.asarray(p))],
        )

    def resolve_abandon(self) -> None:
        """Drop a pending resolve_edges without painting (another shard's
        capacity fail-safe rejected the whole window). Nothing reached
        device history in phase 1, so dropping the stash IS the
        paint-nothing fail-safe contract; version bookkeeping stays
        advanced (harmless — the device floor catches up on the next
        dispatch's max())."""
        self._wave_pending = None

    def resolve_apply(self, graph) -> list[Verdict]:
        """Phase 2: level the combined GLOBAL graph on-device (identical
        inputs on every shard → identical schedule on every shard), paint
        this shard's accepted writes, and publish last_wave /
        last_reordered exactly like a single-shard wave resolve. The
        conflicting-keys report degrades to the resolver-side
        conservative superset on this path (last_conflicting stays
        empty)."""
        if self._wave_pending is None:
            raise ValueError("resolve_apply without a pending resolve_edges")
        pend, commit_version = self._wave_pending
        self._wave_pending = None
        if len(graph.chunks) != len(pend):
            raise ValueError(
                f"global graph has {len(graph.chunks)} chunks; this shard "
                f"packed {len(pend)}"
            )
        gi = 0
        level_parts: list[np.ndarray] = []
        feed: list[tuple] = []
        for (dev, n, cv, oldest, adm), (nc, pred) in zip(pend, graph.chunks):
            if nc != n:
                raise ValueError(
                    f"global graph chunk of {nc} txns vs local pack of {n}"
                )
            cand = np.zeros(self.batch_size, bool)
            cand[:n] = graph.cand[gi : gi + n]
            rbk = dev.ranks if self.resident else dev
            levels, self.state = self._wave_apply_fn(
                self.state, rbk, cand, np.ascontiguousarray(pred, np.uint32),
                cv, oldest,
            )
            lv = np.asarray(levels)[:n]
            level_parts.append(lv)
            if adm is not None:
                feed.append((lv, adm))
            gi += n
        # Stitch the coherent window schedule (same chunk-offset rule as
        # _collect_waves) + the attribution counters.
        waves: list[int] = []
        offset = 0
        reordered = 0
        for lv in level_parts:
            reordered += int((lv > 0).sum())
            waves.extend(int(x) + offset if x >= 0 else int(x) for x in lv)
            if len(lv) and int(lv.max()) >= 0:
                offset += int(lv.max()) + 1
        self.last_wave = waves
        self.last_reordered = reordered
        self.last_conflicting = {}
        # Admission feed (engine-attached filters): accepted writes at
        # this window's commit version, judged on the GLOBAL schedule.
        if self.admission_filter is not None:
            for lv, ((fps, valid), adm_cv) in feed:
                sel = valid[: len(lv)] & (lv >= 0)[:, None]
                if sel.any():
                    self.admission_filter.record_u64(
                        fps[: len(lv)][sel], adm_cv
                    )
                else:
                    self.admission_filter.advance(adm_cv)
        from foundationdb_tpu.core.wavemesh import verdicts_from_schedule

        return verdicts_from_schedule(graph, waves)

    def _collect_waves(self, pending: list[tuple]) -> None:
        """Publish ``last_wave`` from the pending chunks' level tensors.

        Chunks of one resolve call serialize in submission order (earlier
        chunks' writes are painted before later chunks resolve), so chunk
        i+1's wave 0 serializes after ALL of chunk i's waves: offset each
        chunk's committed levels past the previous chunk's maximum to make
        the list one coherent schedule for the whole call."""
        if not self.wave_commit:
            return
        waves: list[int] = []
        offset = 0
        reordered = 0
        for verdicts, n, _losers, _reads, _flags, levels, _adm in pending:
            lv = np.asarray(levels)[:n]
            # Reordered = committed past its CHUNK's first wave (raw
            # level > 0). The chunk offsets below exist only to make the
            # published schedule coherent across chunks — a later chunk's
            # wave-0 txn committed in plain arrival order and must not
            # count as reordered.
            reordered += int((lv > 0).sum())
            waves.extend(int(x) + offset if x >= 0 else int(x) for x in lv)
            if n and int(lv.max()) >= 0:
                offset += int(lv.max()) + 1
        self.last_wave = waves
        self.last_reordered = reordered

    def _feed_admission(self, pending: list[tuple]) -> None:
        """Record ACCEPTED write fingerprints into the attached admission
        filter at this resolve's commit version (no-op when detached).
        Runs at collect time — verdicts are already materialized, so the
        mask costs one vectorized compare per chunk."""
        if self.admission_filter is None:
            return
        for verdicts, n, _l, _r, _f, _lv, adm in pending:
            if adm is None:
                continue
            (fps, valid), cv = adm
            v = np.asarray(verdicts)[:n]
            sel = valid[:n] & (v == Verdict.COMMITTED)[:, None]
            if sel.any():
                self.admission_filter.record_u64(fps[:n][sel], cv)
            else:
                self.admission_filter.advance(cv)

    def _collect(self, pending: list[tuple]) -> list[Verdict]:
        out: list[Verdict] = []
        self.last_conflicting = {}
        self._collect_waves(pending)
        self._feed_admission(pending)
        gi = 0
        for verdicts, n, losers, reads, flags, _levels, _adm in pending:
            v = np.asarray(verdicts)[:n]
            if losers is not None:
                m = np.asarray(losers)[:n]
                if m.dtype != np.bool_:
                    # uint32 bitset rows (packed kernel): bit c = coalesced
                    # read slot c lost — unpack to the bool [n, R] layout.
                    m = (
                        (m[:, None]
                         >> np.arange(self.max_read_ranges, dtype=np.uint32))
                        & 1
                    ).astype(bool)
                for j in range(n):
                    if v[j] == Verdict.CONFLICT and flags[j]:
                        cols = [
                            reads[j][c]
                            for c in np.nonzero(m[j])[0]
                            if c < len(reads[j])
                        ]
                        # Mask column c maps to the txn's c-th COALESCED
                        # read range (the conservative covering ranges
                        # _pack submitted) — a loser report may therefore
                        # be slightly wider than the raw read set, never
                        # narrower. Empty mask (shouldn't happen for a
                        # real conflict) degrades to the full read set.
                        self.last_conflicting[gi + j] = cols or list(reads[j])
            out.extend(Verdict(int(x)) for x in v)
            gi += n
        return out

    def _begin_resolve(
        self,
        commit_version: int,
        oldest_version: int | None,
        defer_rebase: bool = False,
    ) -> int:
        """Advance host-side version bookkeeping for one dispatch. Returns
        the version delta of a rebase that fell due: 0 normally, applied to
        device state immediately — unless ``defer_rebase``, in which case
        the caller must apply it before the next device op (the packing
        thread may not touch device state)."""
        if commit_version <= self._last_commit:
            raise ValueError(
                f"commit versions must advance: {commit_version} <= {self._last_commit}"
            )
        if self.base_version is None:
            self.base_version = max(0, commit_version - self.window_versions)
        if oldest_version is not None:
            self.oldest_version = max(self.oldest_version, oldest_version)
        self.oldest_version = max(
            self.oldest_version, commit_version - self.window_versions
        )
        delta = self._maybe_rebase(commit_version, defer=defer_rebase)
        self._last_commit = commit_version
        return delta

    @property
    def _hist_core(self):
        """The history state proper (unwraps the resident ResState)."""
        st = self.state
        return st.hist if isinstance(st, ck.ResState) else st

    @property
    def _is_hist(self) -> bool:
        return isinstance(self._hist_core, ck.HistState)

    @property
    def overflowed(self) -> bool:
        st = self._hist_core
        if self._is_hist:
            return bool(
                np.asarray(st.base.overflow).any()
                or np.asarray(st.delta.overflow).any()
            )
        return bool(np.asarray(st.overflow).any())

    def headroom(self) -> int:
        """Free boundary slots in the tightest shard (device sync).

        The host-side back-pressure signal: a painted write range adds at
        most 2 boundaries, so a batch of n txns can grow the history by at
        most ``2 * n * max_write_ranges`` slots — if headroom is below that,
        resolving the batch could overflow (truncate history → missed
        conflicts). The runtime Resolver checks this before every batch and
        fail-safes instead (see runtime/resolver.py). The reference's
        SkipList never loses history inside the MVCC window; this check is
        how the fixed-capacity engine earns the same guarantee.

        Window-history engine: a merge keeps at most base+delta live
        boundaries, and the just-in-time merge empties the delta before a
        batch that wouldn't fit — so admission needs room in the merged
        base AND a delta that can absorb one whole batch.
        """
        st = self._hist_core
        if self._is_hist:
            used = int(np.asarray(st.base.n_used).max()) + int(
                np.asarray(st.delta.n_used).max()
            )
            return min(self.capacity - used, self.delta_capacity)
        used = int(np.asarray(st.n_used).max())
        return self.capacity - used

    def worst_case_growth(self, n_txns: int) -> int:
        """Upper bound on boundary-slot growth from resolving n_txns."""
        return 2 * n_txns * self.max_write_ranges

    def clear_overflow(self) -> None:
        """Reset the sticky device overflow flag (after the host has
        reacted — see Resolver's unsafe-window handling)."""
        hc = self._hist_core
        if self._is_hist:
            base, st, delta = hc
            new = ck.HistState(
                base._replace(overflow=base.overflow & False),
                st,
                delta._replace(overflow=delta.overflow & False),
            )
        else:
            new = hc._replace(overflow=hc.overflow & False)
        if isinstance(self.state, ck.ResState):
            self.state = self.state._replace(hist=new)
        else:
            self.state = new

    def advance(self, commit_version: int, oldest_version: int | None = None) -> None:
        """GC-only dispatch: move the version chain and MVCC floor forward
        without painting any writes. Expired segments compact out, so
        headroom recovers as the window slides — this is what lets the
        Resolver's fail-safe mode drain and exit. The window-history
        engine forces a merge here (the lazy base would otherwise hold
        expired segments until the next organic merge)."""
        self._spec_drain_serial()
        self._begin_resolve(commit_version, oldest_version)
        if self.admission_filter is not None:
            self.admission_filter.advance(commit_version)  # age the banks
        cv = np.int32(self._rel(commit_version))
        oldest = np.int32(self._rel(self.oldest_version))
        if self._is_hist:
            fn = (ck._advance_hist_res_jit
                  if isinstance(self.state, ck.ResState)
                  else ck._advance_hist_jit)
            _, self.state = fn(self.state, cv, oldest)
            return
        if self._empty_dev_batch is None:
            # The packed dictionary build is real host work (np.unique over
            # all endpoint rows) and advance()'s all-masked batch is a
            # constant — pack it once. The batch argument is never donated.
            self._empty_dev_batch = self._dev_batch(self._empty_batch())
        self.state = self._resolve_fn(
            self.state, self._empty_dev_batch, cv, oldest
        )[-1]

    # -- internals ----------------------------------------------------------

    def _rel(self, v: int) -> int:
        assert self.base_version is not None
        rel = v - self.base_version
        if rel < 0:
            raise ValueError(f"version {v} below base {self.base_version}")
        return rel

    def _rel_read(self, v: int) -> int:
        """Read versions may legitimately predate the base (ancient readers):
        clamp to -1, which is strictly below every window floor → TOO_OLD for
        readers, irrelevant for blind writers."""
        assert self.base_version is not None
        return max(-1, v - self.base_version)

    def _maybe_rebase(self, commit_version: int, defer: bool = False) -> int:
        assert self.base_version is not None
        if commit_version - self.base_version < _REBASE_THRESHOLD:
            return 0
        delta = self.oldest_version - self.base_version
        if delta <= 0:
            return 0
        # Device versions < delta are all expired; the kernel clamps them to
        # the sentinel, so saturating the device delta at int32 max is exact
        # even for astronomically large jumps.
        if not defer:
            self.state = self._rebase_fn(self.state, np.int32(min(delta, 2**31 - 1)))
        self.base_version += delta
        return delta

    def _empty_batch(self, k: int | None = None) -> ck.BatchTensors:
        """Padded all-masked-out batch tensors (shared by both packers so
        the wire and object paths can never diverge on layout). k adds a
        leading window axis for the scan path."""
        lead = () if k is None else (k,)
        b = self.batch_size
        r, q = self.max_read_ranges, self.max_write_ranges
        w = self.codec.width
        return ck.BatchTensors(
            read_begin=np.full((*lead, b, r, w), INT32_MAX, np.int32),
            read_end=np.full((*lead, b, r, w), INT32_MAX, np.int32),
            read_mask=np.zeros((*lead, b, r), bool),
            write_begin=np.full((*lead, b, q, w), INT32_MAX, np.int32),
            write_end=np.full((*lead, b, q, w), INT32_MAX, np.int32),
            write_mask=np.zeros((*lead, b, q), bool),
            read_version=np.zeros((*lead, b), np.int32),
            txn_mask=np.zeros((*lead, b), bool),
        )

    def _pack_wire(
        self, buf: np.ndarray, offset: int, count: int
    ) -> tuple[ck.BatchTensors, int]:
        """One C pass: wire bytes [offset..] → padded batch tensors."""
        bt = self._empty_batch()
        lib = _keypack_lib()
        new_off = lib.kp_pack_batch(
            _u8(buf), buf.size, offset, count,
            self.batch_size, self.max_read_ranges, self.max_write_ranges,
            self.codec.n_words, self.base_version,
            _i32(bt.read_begin), _i32(bt.read_end), _u8(bt.read_mask),
            _i32(bt.write_begin), _i32(bt.write_end), _u8(bt.write_mask),
            _i32(bt.read_version), _u8(bt.txn_mask),
        )
        if new_off < 0:
            raise ValueError("malformed resolver wire batch")
        return bt, int(new_off)

    def _pack(self, txns: list[TxnConflictInfo], collect_reads: bool = False):
        # Host-pack stage stamp (obs subsystem): wall seconds of the last
        # host-side pack, read by the resolver's span sink right after a
        # resolve — a stored float, never entering kernel state.
        _t_pack0 = _perf_counter()
        bt = self._empty_batch()
        read_begin, read_end, read_mask = bt.read_begin, bt.read_end, bt.read_mask
        write_begin, write_end, write_mask = bt.write_begin, bt.write_end, bt.write_mask
        read_version, txn_mask = bt.read_version, bt.txn_mask
        r, q = self.max_read_ranges, self.max_write_ranges

        # One vectorized pack per endpoint kind across the whole batch (the
        # per-txn Python work is just index bookkeeping).
        r_rows, r_cols, r_pairs = [], [], []
        w_rows, w_cols, w_pairs = [], [], []
        reads_per_txn: list[list[KeyRange]] = []
        for i, t in enumerate(txns):
            txn_mask[i] = True
            read_version[i] = self._rel_read(t.read_version)
            creads = _coalesce(t.read_ranges, r)
            if collect_reads:
                # Kept in slot order: the report path maps the kernel's
                # loser-mask columns back to these ranges.
                reads_per_txn.append(creads)
            for c, x in enumerate(creads):
                r_rows.append(i)
                r_cols.append(c)
                r_pairs.append((x.begin, x.end))
            for c, x in enumerate(_coalesce(t.write_ranges, q)):
                w_rows.append(i)
                w_cols.append(c)
                w_pairs.append((x.begin, x.end))
        if r_pairs:
            rb, re_ = self.codec.pack_ranges(r_pairs)
            read_begin[r_rows, r_cols] = rb
            read_end[r_rows, r_cols] = re_
            read_mask[r_rows, r_cols] = True
        if w_pairs:
            wb, we = self.codec.pack_ranges(w_pairs)
            write_begin[w_rows, w_cols] = wb
            write_end[w_rows, w_cols] = we
            write_mask[w_rows, w_cols] = True

        # ACCUMULATE across chunks (a capacity-chunked resolve packs once
        # per chunk; the reader — the resolver's span sink — clears the
        # stamp to None per dispatched batch, so the sum is per-batch).
        self.last_host_pack_s = (
            (getattr(self, "last_host_pack_s", None) or 0.0)
            + (_perf_counter() - _t_pack0))
        if collect_reads:
            return bt, reads_per_txn
        return bt


def encode_resolve_batch(txns: list[TxnConflictInfo]) -> bytes:
    """Serialize txns to the resolver wire format (native/keypack.cpp).

    The sim runtime and tests use this to exercise the production path; a
    real deployment's proxies would emit these bytes directly as their RPC
    payload (the analogue of serializing ResolveTransactionBatchRequest)."""
    out = bytearray()
    for t in txns:
        reads = list(t.read_ranges)
        writes = list(t.write_ranges)
        out += struct.pack("<qii", t.read_version, len(reads), len(writes))
        for rng in reads + writes:
            out += struct.pack("<ii", len(rng.begin), len(rng.end))
            out += rng.begin
            out += rng.end
    return bytes(out)


_KP_LIB = None


def _keypack_lib():
    global _KP_LIB
    if _KP_LIB is None:
        from foundationdb_tpu.native import load_library

        lib = load_library("keypack")
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64 = ctypes.c_int64
        lib.kp_pack_batch.restype = i64
        lib.kp_pack_batch.argtypes = [
            u8p, i64, i64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, i64,
            i32p, i32p, u8p, i32p, i32p, u8p, i32p, u8p,
        ]
        lib.kp_count_txns.restype = i64
        lib.kp_count_txns.argtypes = [u8p, i64, i64]
        if hasattr(lib, "kp_pack_window"):  # absent only in a stale .so
            lib.kp_pack_window.restype = i64
            lib.kp_pack_window.argtypes = [
                u8p, i64, i64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_int, ctypes.c_int, i64,
                i32p, i32p, u8p, i32p, i32p, u8p, i32p, u8p,
                i32p, i32p, i32p, i32p, i32p,
            ]
        _KP_LIB = lib
    return _KP_LIB


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _coalesce(ranges: list[KeyRange], limit: int) -> list[KeyRange]:
    """At most `limit` ranges covering the input (conservative widening).

    Sorts by begin and covers even-sized groups — the analogue of the
    reference's combineWriteConflictRanges merging adjacent/overlapping
    ranges, extended to force a static width.
    """
    live = [x for x in ranges if not x.empty]
    if len(live) <= limit:
        return live
    live.sort(key=lambda x: x.begin)
    out = []
    step = -(-len(live) // limit)
    for i in range(0, len(live), step):
        grp = live[i : i + step]
        out.append(KeyRange(grp[0].begin, max(g.end for g in grp)))
    return out
