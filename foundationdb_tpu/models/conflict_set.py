"""Host-side ConflictSet API over the jitted kernel.

This is the seam the reference exposes as ``newConflictSet()`` /
``ConflictBatch`` (fdbserver/ConflictSet.h): the runtime's Resolver role
(runtime/resolver.py) talks to this class and never sees device tensors.
Responsibilities here: pad/pack byte-range batches into static-shape tensors,
chunk oversized batches (sub-batches at the same commit version are exactly
equivalent — earlier chunks' writes are painted at cv before later chunks
resolve, which reproduces in-batch ordering), coalesce per-txn conflict
ranges beyond the padded width (conservative covering ranges: false
conflicts possible, missed conflicts impossible), and manage the
absolute↔relative version mapping with periodic device rebase.
"""

from __future__ import annotations

import ctypes
import struct
from typing import Callable, NamedTuple

import numpy as np

from foundationdb_tpu.core.keypack import INT32_MAX, KeyCodec, row_sort_keys
from foundationdb_tpu.core.types import KeyRange, TxnConflictInfo, Verdict
from foundationdb_tpu.models import conflict_kernel as ck

DEFAULT_WINDOW_VERSIONS = 5_000_000  # ~5s at 1M versions/sec, reference MVCC window
_REBASE_THRESHOLD = 1 << 30


class PreparedWindow(NamedTuple):
    """A host-packed dispatch window awaiting device dispatch.

    The pack half (``pack_wire_window``) is pure host work — the C wire
    pass, padding, and (under FDB_TPU_PACKED) the ``_pack_dict``
    dedup+sort — so a scheduler can run it on a worker thread for window
    N+1 while the device still executes window N (sched/packing.py). The
    dispatch half (``dispatch_window``) threads device state and must run
    on the dispatching thread, in commit-version order."""

    batch: object  # device-format batch tensors, k-leading axis
    cvs_rel: np.ndarray
    olds_rel: np.ndarray
    count: int
    rebase_delta: int  # deferred device rebase; applied before dispatch


class TPUConflictSet:
    """Drop-in conflict engine: resolve(txns, commit_version) → verdicts."""

    def __init__(
        self,
        capacity: int = 1 << 16,
        batch_size: int = 512,
        max_read_ranges: int = 8,
        max_write_ranges: int = 8,
        max_key_bytes: int = 32,
        window_versions: int = DEFAULT_WINDOW_VERSIONS,
        delta_capacity: int | None = None,
        wave_commit: bool | None = None,
    ):
        self.codec = KeyCodec(max_key_bytes)
        # Wave-commit mode (reorder-don't-abort; conflict_kernel phase 2b):
        # None = the FDB_TPU_WAVE_COMMIT env default. Both modes' entry
        # points are distinct compiled programs, so engines of either mode
        # coexist in one process (the import-once rule only pins the env
        # DEFAULT). NOTE: a wave engine reorders txns within its own view,
        # so it must see every conflict range of its batches — one engine
        # per resolver role, and never more than one wave resolver per
        # keyspace (the mesh ShardedConflictSet shards internally and
        # stays exact; role-level multi-resolver deployments must keep
        # wave commit off — see sim/cluster.new_conflict_set).
        self.wave_commit = ck._WAVE_COMMIT if wave_commit is None else bool(
            wave_commit
        )
        self.capacity = capacity
        self.batch_size = batch_size
        self.max_read_ranges = max_read_ranges
        self.max_write_ranges = max_write_ranges
        self.window_versions = window_versions
        # Window-history delta sizing: must absorb one batch's worst-case
        # paint (the in-jit merge empties it just-in-time before a batch
        # that wouldn't fit).
        self.delta_capacity = delta_capacity or min(
            capacity, 2 * batch_size * max_write_ranges + 2
        )
        self.base_version: int | None = None
        self.oldest_version: int = 0  # absolute; advances monotonically
        self._last_commit: int = 0
        # Exact conflicting read ranges of the LAST resolve() call, by txn
        # index — populated only when some txn asked
        # (report_conflicting_keys) so the hot path pays nothing. Same
        # surface as the oracle's (reference: conflictingKRIndices); the
        # runtime Resolver reads it for the repair subsystem's reports.
        self.last_conflicting: dict[int, list[KeyRange]] = {}
        # Wave levels of the LAST resolve() call, by txn index (wave
        # engines only; None otherwise): >= 0 committed at that wave,
        # conflict_kernel.LEVEL_CYCLE aborted on a true cycle,
        # LEVEL_NONE every other non-commit. Chunked resolves offset
        # later chunks' waves past earlier ones (chunks serialize in
        # order), so the list is one coherent schedule for the call.
        self.last_wave: list[int] | None = None
        # Exact reordered count of the last resolve (wave engines only):
        # txns committed past their chunk's FIRST wave — the published
        # cross-chunk offsets deliberately excluded (see _collect_waves).
        self.last_reordered: int | None = None
        # Window-path analogue (dispatch_window collectors): int32
        # [k, count] levels, one independent wave schedule per scanned
        # batch (batches already serialize by commit version).
        self.last_wave_window: np.ndarray | None = None
        self._empty_dev_batch = None  # advance()'s constant batch, packed lazily
        self._init_engine()

    def _init_engine(self) -> None:
        """Build device state + entry points. Subclasses (the mesh-sharded
        engine) override this; all host-side logic is shared. Under
        FDB_TPU_PACKED (default) the packer additionally emits the batch's
        deduped key dictionary (_pack_dict) and the device runs the
        rank-space kernel entry points."""
        self._dev_batch = self._pack_dict if ck._PACKED else (lambda bt: bt)
        hist = ck._HIST_DESIGN == "window"
        if hist:
            self.state = ck.init_hist(
                self.capacity, self.codec.width, self.codec.min_key,
                self.delta_capacity,
            )
            self._rebase_fn = ck._rebase_hist_jit
        else:
            self.state = ck.init_state(
                self.capacity, self.codec.width, self.codec.min_key
            )
            self._rebase_fn = ck._rebase_jit
        # Entry points follow one naming convention —
        # _resolve{,_report,_many}{_hist}{_packed}{_wave}_jit — so the
        # (history, packed, wave) design point composes the names instead
        # of a hand-written 12-way table a mis-paired branch could
        # silently skew.
        suffix = (("_hist" if hist else "")
                  + ("_packed" if ck._PACKED else "")
                  + ("_wave" if self.wave_commit else "") + "_jit")
        self._resolve_fn = getattr(ck, "_resolve" + suffix)
        self._resolve_report_fn = getattr(ck, "_resolve_report" + suffix)
        self._resolve_many_fn = getattr(ck, "_resolve_many" + suffix)

    def _pack_dict(self, bt: ck.BatchTensors) -> ck.PackedBatch:
        """Dedup+sort ALL batch endpoint keys once per dispatch (host
        numpy — a memcmp sort over the biased byte view) and rewrite the
        batch in rank space: the kernel receives the sorted unique key
        dictionary plus int32 ranks per endpoint slot. The dictionary's
        static size is the endpoint count + 1, with the last row always
        +inf (paint parks masked slots there); ranks are exact order
        isomorphisms (equal keys share a rank)."""
        rb = np.asarray(bt.read_begin)
        if rb.ndim == 4:  # [k, B, R, W] window path: pack per scan step
            parts = [
                self._pack_dict(
                    ck.BatchTensors(*(np.asarray(x)[i] for x in bt))
                )
                for i in range(rb.shape[0])
            ]
            return ck.PackedBatch(*(np.stack(x) for x in zip(*parts)))
        b, r, w = rb.shape
        q = bt.write_begin.shape[1]
        flat = np.concatenate([
            rb.reshape(-1, w),
            np.asarray(bt.read_end).reshape(-1, w),
            np.asarray(bt.write_begin).reshape(-1, w),
            np.asarray(bt.write_end).reshape(-1, w),
        ])
        _, first, inverse = np.unique(
            row_sort_keys(flat), return_index=True, return_inverse=True
        )
        n = flat.shape[0]
        dict_keys = np.full((n + 1, w), INT32_MAX, np.int32)
        dict_keys[: len(first)] = flat[first]
        inv = inverse.astype(np.int32)
        n_r, n_q = b * r, b * q
        return ck.PackedBatch(
            dict_keys=dict_keys,
            read_begin=inv[:n_r].reshape(b, r),
            read_end=inv[n_r : 2 * n_r].reshape(b, r),
            read_mask=np.asarray(bt.read_mask),
            write_begin=inv[2 * n_r : 2 * n_r + n_q].reshape(b, q),
            write_end=inv[2 * n_r + n_q :].reshape(b, q),
            write_mask=np.asarray(bt.write_mask),
            read_version=np.asarray(bt.read_version),
            txn_mask=np.asarray(bt.txn_mask),
        )

    # -- public API ---------------------------------------------------------

    def resolve(
        self,
        txns: list[TxnConflictInfo],
        commit_version: int,
        oldest_version: int | None = None,
    ) -> list[Verdict]:
        return self.resolve_async(txns, commit_version, oldest_version)()

    def resolve_async(
        self,
        txns: list[TxnConflictInfo],
        commit_version: int,
        oldest_version: int | None = None,
    ) -> Callable[[], list[Verdict]]:
        """Dispatch every chunk to the device immediately and return a
        collector. The caller (resolver role, bench) packs/dispatches the
        NEXT batch while the device still computes this one — materializing
        verdicts (the device→host sync) is deferred to the collector.

        When some txn set report_conflicting_keys (and the engine compiled
        a report entry point), the kernel's loser-range mask rides along
        and the collector populates ``last_conflicting`` — exact
        conflicting read ranges per txn index, the same surface the oracle
        provides."""
        can_report = getattr(self, "_resolve_report_fn", None) is not None
        self._begin_resolve(commit_version, oldest_version)
        cv = np.int32(self._rel(commit_version))
        oldest = np.int32(self._rel(self.oldest_version))
        pending: list[tuple] = []
        for i in range(0, len(txns), self.batch_size):
            chunk = txns[i : i + self.batch_size]
            # Per CHUNK: only chunks that actually contain a reporting txn
            # pay the report program + host-side range bookkeeping.
            if can_report and any(t.report_conflicting_keys for t in chunk):
                batch, reads = self._pack(chunk, collect_reads=True)
                out = self._resolve_report_fn(
                    self.state, self._dev_batch(batch), cv, oldest
                )
                verdicts, levels, losers, self.state = (
                    out if self.wave_commit else (out[0], None, *out[1:])
                )
                flags = [t.report_conflicting_keys for t in chunk]
                pending.append(
                    (verdicts, len(chunk), losers, reads, flags, levels)
                )
            else:
                batch = self._pack(chunk)
                out = self._resolve_fn(
                    self.state, self._dev_batch(batch), cv, oldest
                )
                verdicts, levels, self.state = (
                    out if self.wave_commit else (out[0], None, out[1])
                )
                pending.append((verdicts, len(chunk), None, None, None, levels))
        return lambda: self._collect(pending)

    def resolve_wire(
        self,
        wire: bytes | np.ndarray,
        commit_version: int,
        oldest_version: int | None = None,
        count: int | None = None,
    ) -> list[Verdict]:
        return self.resolve_wire_async(wire, commit_version, oldest_version, count)()

    def resolve_wire_async(
        self,
        wire: bytes | np.ndarray,
        commit_version: int,
        oldest_version: int | None = None,
        count: int | None = None,
        as_array: bool = False,
    ) -> Callable[[], list[Verdict]]:
        """The production hot path: a flat serialized resolver batch (see
        native/keypack.cpp for the wire format — the analogue of the
        reference's ResolveTransactionBatchRequest bytes) is packed into
        device tensors by one C pass, never touching per-txn Python objects."""
        buf = np.frombuffer(wire, dtype=np.uint8) if isinstance(wire, (bytes, bytearray)) else wire
        lib = _keypack_lib()
        # Structurally validate the WHOLE buffer before any dispatch: a chunk
        # failing mid-stream would leave earlier chunks' writes painted into
        # device history with no verdicts delivered (phantom conflicts
        # forever). kp_count_txns walks every record's bounds in one C pass.
        counted = int(lib.kp_count_txns(_u8(buf), buf.size, 0))
        if counted < 0 or (count is not None and count > counted):
            raise ValueError("malformed resolver wire batch")
        if count is None:
            count = counted
        self._begin_resolve(commit_version, oldest_version)
        cv = np.int32(self._rel(commit_version))
        oldest = np.int32(self._rel(self.oldest_version))
        pending: list[tuple] = []
        offset, remaining = 0, count
        while remaining > 0:
            n = min(remaining, self.batch_size)
            batch, offset = self._pack_wire(buf, offset, n)
            out = self._resolve_fn(
                self.state, self._dev_batch(batch), cv, oldest
            )
            verdicts, levels, self.state = (
                out if self.wave_commit else (out[0], None, out[1])
            )
            pending.append((verdicts, n, None, None, None, levels))
            remaining -= n
        if as_array:

            def collect_array():
                self._collect_waves(pending)
                return np.concatenate(
                    [np.asarray(v)[:n] for v, n, *_rest in pending]
                )

            return collect_array
        return lambda: self._collect(pending)

    def resolve_wire_window(
        self,
        wire: bytes | np.ndarray,
        commit_versions,
        count: int,
    ) -> np.ndarray:
        return self.resolve_wire_window_async(wire, commit_versions, count)()

    def resolve_wire_window_async(
        self,
        wire: bytes | np.ndarray,
        commit_versions,
        count: int,
    ) -> Callable[[], np.ndarray]:
        """Resolve a WINDOW of k consecutive batches in one device dispatch.

        ``wire`` holds k·count txns; txns [i·count, (i+1)·count) resolve at
        ``commit_versions[i]`` (strictly increasing). One lax.scan program
        (conflict_kernel.resolve_many) replaces k dispatches — the host-side
        analogue of the reference proxy batching many commits per resolver
        RPC, here amortizing per-dispatch latency instead of network round
        trips. Returns a collector yielding verdicts int8 [k, count].

        Callers should keep k fixed across calls (each distinct k compiles
        its own program). The pack/dispatch halves are separately callable
        (``pack_wire_window`` / ``dispatch_window``) so a scheduler can
        double-buffer host packing against device execution.
        """
        return self.dispatch_window(
            self.pack_wire_window(wire, commit_versions, count)
        )

    def pack_wire_window(
        self,
        wire: bytes | np.ndarray,
        commit_versions,
        count: int,
    ) -> PreparedWindow:
        """Host half of the window path: validate, advance version
        bookkeeping, and pack wire bytes into device-format tensors. Pure
        host work (the device rebase, if one fell due, is DEFERRED into the
        PreparedWindow), so it may run on a packing thread concurrently
        with ``dispatch_window`` of the PREVIOUS window — never concurrently
        with another pack (packs are commit-version ordered)."""
        buf = (
            np.frombuffer(wire, dtype=np.uint8)
            if isinstance(wire, (bytes, bytearray))
            else wire
        )
        k = len(commit_versions)
        if count > self.batch_size:
            raise ValueError("window path resolves one kernel batch per version")
        lib = _keypack_lib()
        counted = int(lib.kp_count_txns(_u8(buf), buf.size, 0))
        if counted < k * count:
            raise ValueError("malformed resolver wire batch")

        # A raise below must leave the host bookkeeping untouched: with a
        # deferred rebase, base_version would otherwise run ahead of the
        # never-rebased device state and silently skew every later
        # window's relative versions. Restoring the snapshot makes a
        # failed pack fully transactional (host-only — thread-safe on the
        # packing thread).
        snap = (self.base_version, self.oldest_version, self._last_commit)
        try:
            rebase_delta = 0
            oldest_abs = np.empty(k, np.int64)
            for i, cv in enumerate(commit_versions):
                rebase_delta += self._begin_resolve(
                    int(cv), None, defer_rebase=True
                )
                oldest_abs[i] = self.oldest_version
            # base_version is final after all _begin_resolve rebases —
            # convert now. A rebase mid-window can lift base above floors
            # snapshotted earlier; clamp those to 0 (everything below base
            # is already expired on device, so a zero floor is exact — the
            # kernel takes max(state.oldest, new_oldest), never regresses).
            cvs_rel = np.asarray(
                [self._rel(int(cv)) for cv in commit_versions], np.int32
            )
            olds_rel = np.asarray(
                [max(0, int(v) - self.base_version) for v in oldest_abs],
                np.int32,
            )

            batches = self._empty_batch(k)
            offset = 0
            for i in range(k):
                offset = lib.kp_pack_batch(
                    _u8(buf), buf.size, offset, count,
                    self.batch_size, self.max_read_ranges,
                    self.max_write_ranges,
                    self.codec.n_words, self.base_version,
                    _i32(batches.read_begin[i]), _i32(batches.read_end[i]),
                    _u8(batches.read_mask[i]),
                    _i32(batches.write_begin[i]), _i32(batches.write_end[i]),
                    _u8(batches.write_mask[i]),
                    _i32(batches.read_version[i]), _u8(batches.txn_mask[i]),
                )
                if offset < 0:
                    raise ValueError("malformed resolver wire batch")
        except BaseException:
            self.base_version, self.oldest_version, self._last_commit = snap
            raise
        return PreparedWindow(
            batch=self._dev_batch(batches),
            cvs_rel=cvs_rel,
            olds_rel=olds_rel,
            count=count,
            rebase_delta=rebase_delta,
        )

    def dispatch_window(self, prepared: PreparedWindow) -> Callable[[], np.ndarray]:
        """Device half of the window path: thread state through the scan
        program. Must run on the dispatching thread, in the same order the
        windows were packed."""
        if prepared.rebase_delta:
            self.state = self._rebase_fn(
                self.state, np.int32(min(prepared.rebase_delta, 2**31 - 1))
            )
        out = self._resolve_many_fn(
            self.state, prepared.batch, prepared.cvs_rel, prepared.olds_rel
        )
        verdicts, levels, self.state = (
            out if self.wave_commit else (out[0], None, out[1])
        )
        if not self.wave_commit:
            return lambda: np.asarray(verdicts)[:, : prepared.count]

        def collect():
            # Waves are PER BATCH on the window path (batches already
            # serialize by commit version); publish int32 [k, count].
            self.last_wave_window = np.asarray(levels)[:, : prepared.count]
            return np.asarray(verdicts)[:, : prepared.count]

        return collect

    def _collect_waves(self, pending: list[tuple]) -> None:
        """Publish ``last_wave`` from the pending chunks' level tensors.

        Chunks of one resolve call serialize in submission order (earlier
        chunks' writes are painted before later chunks resolve), so chunk
        i+1's wave 0 serializes after ALL of chunk i's waves: offset each
        chunk's committed levels past the previous chunk's maximum to make
        the list one coherent schedule for the whole call."""
        if not self.wave_commit:
            return
        waves: list[int] = []
        offset = 0
        reordered = 0
        for verdicts, n, _losers, _reads, _flags, levels in pending:
            lv = np.asarray(levels)[:n]
            # Reordered = committed past its CHUNK's first wave (raw
            # level > 0). The chunk offsets below exist only to make the
            # published schedule coherent across chunks — a later chunk's
            # wave-0 txn committed in plain arrival order and must not
            # count as reordered.
            reordered += int((lv > 0).sum())
            waves.extend(int(x) + offset if x >= 0 else int(x) for x in lv)
            if n and int(lv.max()) >= 0:
                offset += int(lv.max()) + 1
        self.last_wave = waves
        self.last_reordered = reordered

    def _collect(self, pending: list[tuple]) -> list[Verdict]:
        out: list[Verdict] = []
        self.last_conflicting = {}
        self._collect_waves(pending)
        gi = 0
        for verdicts, n, losers, reads, flags, _levels in pending:
            v = np.asarray(verdicts)[:n]
            if losers is not None:
                m = np.asarray(losers)[:n]
                if m.dtype != np.bool_:
                    # uint32 bitset rows (packed kernel): bit c = coalesced
                    # read slot c lost — unpack to the bool [n, R] layout.
                    m = (
                        (m[:, None]
                         >> np.arange(self.max_read_ranges, dtype=np.uint32))
                        & 1
                    ).astype(bool)
                for j in range(n):
                    if v[j] == Verdict.CONFLICT and flags[j]:
                        cols = [
                            reads[j][c]
                            for c in np.nonzero(m[j])[0]
                            if c < len(reads[j])
                        ]
                        # Mask column c maps to the txn's c-th COALESCED
                        # read range (the conservative covering ranges
                        # _pack submitted) — a loser report may therefore
                        # be slightly wider than the raw read set, never
                        # narrower. Empty mask (shouldn't happen for a
                        # real conflict) degrades to the full read set.
                        self.last_conflicting[gi + j] = cols or list(reads[j])
            out.extend(Verdict(int(x)) for x in v)
            gi += n
        return out

    def _begin_resolve(
        self,
        commit_version: int,
        oldest_version: int | None,
        defer_rebase: bool = False,
    ) -> int:
        """Advance host-side version bookkeeping for one dispatch. Returns
        the version delta of a rebase that fell due: 0 normally, applied to
        device state immediately — unless ``defer_rebase``, in which case
        the caller must apply it before the next device op (the packing
        thread may not touch device state)."""
        if commit_version <= self._last_commit:
            raise ValueError(
                f"commit versions must advance: {commit_version} <= {self._last_commit}"
            )
        if self.base_version is None:
            self.base_version = max(0, commit_version - self.window_versions)
        if oldest_version is not None:
            self.oldest_version = max(self.oldest_version, oldest_version)
        self.oldest_version = max(
            self.oldest_version, commit_version - self.window_versions
        )
        delta = self._maybe_rebase(commit_version, defer=defer_rebase)
        self._last_commit = commit_version
        return delta

    @property
    def _is_hist(self) -> bool:
        return isinstance(self.state, ck.HistState)

    @property
    def overflowed(self) -> bool:
        if self._is_hist:
            return bool(
                np.asarray(self.state.base.overflow).any()
                or np.asarray(self.state.delta.overflow).any()
            )
        return bool(np.asarray(self.state.overflow).any())

    def headroom(self) -> int:
        """Free boundary slots in the tightest shard (device sync).

        The host-side back-pressure signal: a painted write range adds at
        most 2 boundaries, so a batch of n txns can grow the history by at
        most ``2 * n * max_write_ranges`` slots — if headroom is below that,
        resolving the batch could overflow (truncate history → missed
        conflicts). The runtime Resolver checks this before every batch and
        fail-safes instead (see runtime/resolver.py). The reference's
        SkipList never loses history inside the MVCC window; this check is
        how the fixed-capacity engine earns the same guarantee.

        Window-history engine: a merge keeps at most base+delta live
        boundaries, and the just-in-time merge empties the delta before a
        batch that wouldn't fit — so admission needs room in the merged
        base AND a delta that can absorb one whole batch.
        """
        if self._is_hist:
            used = int(np.asarray(self.state.base.n_used).max()) + int(
                np.asarray(self.state.delta.n_used).max()
            )
            return min(self.capacity - used, self.delta_capacity)
        used = int(np.asarray(self.state.n_used).max())
        return self.capacity - used

    def worst_case_growth(self, n_txns: int) -> int:
        """Upper bound on boundary-slot growth from resolving n_txns."""
        return 2 * n_txns * self.max_write_ranges

    def clear_overflow(self) -> None:
        """Reset the sticky device overflow flag (after the host has
        reacted — see Resolver's unsafe-window handling)."""
        if self._is_hist:
            base, st, delta = self.state
            self.state = ck.HistState(
                base._replace(overflow=base.overflow & False),
                st,
                delta._replace(overflow=delta.overflow & False),
            )
            return
        self.state = self.state._replace(overflow=self.state.overflow & False)

    def advance(self, commit_version: int, oldest_version: int | None = None) -> None:
        """GC-only dispatch: move the version chain and MVCC floor forward
        without painting any writes. Expired segments compact out, so
        headroom recovers as the window slides — this is what lets the
        Resolver's fail-safe mode drain and exit. The window-history
        engine forces a merge here (the lazy base would otherwise hold
        expired segments until the next organic merge)."""
        self._begin_resolve(commit_version, oldest_version)
        cv = np.int32(self._rel(commit_version))
        oldest = np.int32(self._rel(self.oldest_version))
        if self._is_hist:
            _, self.state = ck._advance_hist_jit(self.state, cv, oldest)
            return
        if self._empty_dev_batch is None:
            # The packed dictionary build is real host work (np.unique over
            # all endpoint rows) and advance()'s all-masked batch is a
            # constant — pack it once. The batch argument is never donated.
            self._empty_dev_batch = self._dev_batch(self._empty_batch())
        self.state = self._resolve_fn(
            self.state, self._empty_dev_batch, cv, oldest
        )[-1]

    # -- internals ----------------------------------------------------------

    def _rel(self, v: int) -> int:
        assert self.base_version is not None
        rel = v - self.base_version
        if rel < 0:
            raise ValueError(f"version {v} below base {self.base_version}")
        return rel

    def _rel_read(self, v: int) -> int:
        """Read versions may legitimately predate the base (ancient readers):
        clamp to -1, which is strictly below every window floor → TOO_OLD for
        readers, irrelevant for blind writers."""
        assert self.base_version is not None
        return max(-1, v - self.base_version)

    def _maybe_rebase(self, commit_version: int, defer: bool = False) -> int:
        assert self.base_version is not None
        if commit_version - self.base_version < _REBASE_THRESHOLD:
            return 0
        delta = self.oldest_version - self.base_version
        if delta <= 0:
            return 0
        # Device versions < delta are all expired; the kernel clamps them to
        # the sentinel, so saturating the device delta at int32 max is exact
        # even for astronomically large jumps.
        if not defer:
            self.state = self._rebase_fn(self.state, np.int32(min(delta, 2**31 - 1)))
        self.base_version += delta
        return delta

    def _empty_batch(self, k: int | None = None) -> ck.BatchTensors:
        """Padded all-masked-out batch tensors (shared by both packers so
        the wire and object paths can never diverge on layout). k adds a
        leading window axis for the scan path."""
        lead = () if k is None else (k,)
        b = self.batch_size
        r, q = self.max_read_ranges, self.max_write_ranges
        w = self.codec.width
        return ck.BatchTensors(
            read_begin=np.full((*lead, b, r, w), INT32_MAX, np.int32),
            read_end=np.full((*lead, b, r, w), INT32_MAX, np.int32),
            read_mask=np.zeros((*lead, b, r), bool),
            write_begin=np.full((*lead, b, q, w), INT32_MAX, np.int32),
            write_end=np.full((*lead, b, q, w), INT32_MAX, np.int32),
            write_mask=np.zeros((*lead, b, q), bool),
            read_version=np.zeros((*lead, b), np.int32),
            txn_mask=np.zeros((*lead, b), bool),
        )

    def _pack_wire(
        self, buf: np.ndarray, offset: int, count: int
    ) -> tuple[ck.BatchTensors, int]:
        """One C pass: wire bytes [offset..] → padded batch tensors."""
        bt = self._empty_batch()
        lib = _keypack_lib()
        new_off = lib.kp_pack_batch(
            _u8(buf), buf.size, offset, count,
            self.batch_size, self.max_read_ranges, self.max_write_ranges,
            self.codec.n_words, self.base_version,
            _i32(bt.read_begin), _i32(bt.read_end), _u8(bt.read_mask),
            _i32(bt.write_begin), _i32(bt.write_end), _u8(bt.write_mask),
            _i32(bt.read_version), _u8(bt.txn_mask),
        )
        if new_off < 0:
            raise ValueError("malformed resolver wire batch")
        return bt, int(new_off)

    def _pack(self, txns: list[TxnConflictInfo], collect_reads: bool = False):
        bt = self._empty_batch()
        read_begin, read_end, read_mask = bt.read_begin, bt.read_end, bt.read_mask
        write_begin, write_end, write_mask = bt.write_begin, bt.write_end, bt.write_mask
        read_version, txn_mask = bt.read_version, bt.txn_mask
        r, q = self.max_read_ranges, self.max_write_ranges

        # One vectorized pack per endpoint kind across the whole batch (the
        # per-txn Python work is just index bookkeeping).
        r_rows, r_cols, r_pairs = [], [], []
        w_rows, w_cols, w_pairs = [], [], []
        reads_per_txn: list[list[KeyRange]] = []
        for i, t in enumerate(txns):
            txn_mask[i] = True
            read_version[i] = self._rel_read(t.read_version)
            creads = _coalesce(t.read_ranges, r)
            if collect_reads:
                # Kept in slot order: the report path maps the kernel's
                # loser-mask columns back to these ranges.
                reads_per_txn.append(creads)
            for c, x in enumerate(creads):
                r_rows.append(i)
                r_cols.append(c)
                r_pairs.append((x.begin, x.end))
            for c, x in enumerate(_coalesce(t.write_ranges, q)):
                w_rows.append(i)
                w_cols.append(c)
                w_pairs.append((x.begin, x.end))
        if r_pairs:
            rb, re_ = self.codec.pack_ranges(r_pairs)
            read_begin[r_rows, r_cols] = rb
            read_end[r_rows, r_cols] = re_
            read_mask[r_rows, r_cols] = True
        if w_pairs:
            wb, we = self.codec.pack_ranges(w_pairs)
            write_begin[w_rows, w_cols] = wb
            write_end[w_rows, w_cols] = we
            write_mask[w_rows, w_cols] = True

        if collect_reads:
            return bt, reads_per_txn
        return bt


def encode_resolve_batch(txns: list[TxnConflictInfo]) -> bytes:
    """Serialize txns to the resolver wire format (native/keypack.cpp).

    The sim runtime and tests use this to exercise the production path; a
    real deployment's proxies would emit these bytes directly as their RPC
    payload (the analogue of serializing ResolveTransactionBatchRequest)."""
    out = bytearray()
    for t in txns:
        reads = list(t.read_ranges)
        writes = list(t.write_ranges)
        out += struct.pack("<qii", t.read_version, len(reads), len(writes))
        for rng in reads + writes:
            out += struct.pack("<ii", len(rng.begin), len(rng.end))
            out += rng.begin
            out += rng.end
    return bytes(out)


_KP_LIB = None


def _keypack_lib():
    global _KP_LIB
    if _KP_LIB is None:
        from foundationdb_tpu.native import load_library

        lib = load_library("keypack")
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64 = ctypes.c_int64
        lib.kp_pack_batch.restype = i64
        lib.kp_pack_batch.argtypes = [
            u8p, i64, i64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, i64,
            i32p, i32p, u8p, i32p, i32p, u8p, i32p, u8p,
        ]
        lib.kp_count_txns.restype = i64
        lib.kp_count_txns.argtypes = [u8p, i64, i64]
        _KP_LIB = lib
    return _KP_LIB


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _i32(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _coalesce(ranges: list[KeyRange], limit: int) -> list[KeyRange]:
    """At most `limit` ranges covering the input (conservative widening).

    Sorts by begin and covers even-sized groups — the analogue of the
    reference's combineWriteConflictRanges merging adjacent/overlapping
    ranges, extended to force a static width.
    """
    live = [x for x in ranges if not x.empty]
    if len(live) <= limit:
        return live
    live.sort(key=lambda x: x.begin)
    out = []
    step = -(-len(live) // limit)
    for i in range(0, len(live), step):
        grp = live[i : i + step]
        out.append(KeyRange(grp[0].begin, max(g.end for g in grp)))
    return out
