"""Read-your-writes transaction layer.

Reference: fdbclient/ReadYourWrites.actor.cpp — the default client surface.
Reads observe the transaction's own uncommitted writes overlaid on the
snapshot: sets and clears resolve locally without touching storage (and
without adding read conflict ranges, like the reference's known-value
fast path); atomic ops on unknown base values read through, then fold the
pending operations on top.

Overlay model: per-key entries updated in program order — an entry is
either ("value", v) when the outcome is locally known, or ("ops", [...])
when atomic ops await the base value — plus the union of cleared ranges
to suppress snapshot rows with no later entry.
"""

from __future__ import annotations

from foundationdb_tpu.client.transaction import Database, KeySelector, Transaction
from foundationdb_tpu.core.errors import FdbError
from foundationdb_tpu.core.mutations import MutationType, apply_atomic
from foundationdb_tpu.core.types import KeyRange


def _unreadable() -> FdbError:
    # Reference: accessed_unreadable (1036) — reading a versionstamped value.
    return FdbError("read of versionstamped value", code=1036)


class RYWTransaction(Transaction):
    def _reset(self) -> None:
        super()._reset()
        self._overlay: dict[bytes, tuple[str, object]] = {}
        self._clears: list[KeyRange] = []

    def set_option(self, name: str, value=None) -> None:
        # Reference option 51: reads see only the snapshot, never this
        # transaction's own writes (apps use it to audit pre-txn state
        # and to skip the overlay bookkeeping). Like the reference, it
        # must be set before the transaction reads or writes.
        if name == "read_your_writes_disable":
            if self._overlay or self.mutations or self._read_version is not None:
                raise FdbError(
                    "read_your_writes_disable must be set before any "
                    "read or write", code=2006)
            self.ryw_disabled = True
            return
        super().set_option(name, value)

    # -- write path: maintain the overlay -------------------------------------

    def set(self, key: bytes, value: bytes) -> None:
        super().set(key, value)
        self._overlay[key] = ("value", value)

    def clear(self, key: bytes) -> None:
        super().clear(key)
        self._overlay[key] = ("value", None)

    def clear_range(self, begin: bytes, end: bytes) -> None:
        super().clear_range(begin, end)
        r = KeyRange(begin, end)
        if r.empty:
            return
        for k in [k for k in self._overlay if r.contains(k)]:
            self._overlay[k] = ("value", None)
        self._clears.append(r)

    def atomic_op(self, op: MutationType, key: bytes, param: bytes) -> None:
        super().atomic_op(op, key, param)
        if op in (MutationType.SET_VERSIONSTAMPED_KEY, MutationType.SET_VERSIONSTAMPED_VALUE):
            # Final key/value unknown until commit; RYW marks it unreadable
            # (the reference raises accessed_unreadable on such reads — we
            # surface the stamped value as unknowable the same way).
            if op == MutationType.SET_VERSIONSTAMPED_VALUE:
                self._overlay[key] = ("unreadable", None)
            return
        kind, cur = self._overlay.get(key, (None, None))
        if kind == "value":
            self._overlay[key] = ("value", apply_atomic(op, cur, param))
        elif kind == "ops":
            cur.append((op, param))
        elif self._covered_by_clear(key):
            self._overlay[key] = ("value", apply_atomic(op, None, param))
        else:
            self._overlay[key] = ("ops", [(op, param)])

    def _covered_by_clear(self, key: bytes) -> bool:
        return any(r.contains(key) for r in self._clears)

    # -- read path: overlay over snapshot --------------------------------------

    async def get(self, key: bytes, snapshot: bool = False) -> bytes | None:
        if getattr(self, "ryw_disabled", False):
            return await super().get(key, snapshot)
        kind, entry = self._overlay.get(key, (None, None))
        if kind == "value":
            return entry  # known locally: no storage read, no conflict range
        if kind == "unreadable":
            raise _unreadable()
        if self._covered_by_clear(key):
            # Locally known None (own clear_range): no read, no conflict.
            return None
        base = await super().get(key, snapshot)
        if kind == "ops":
            for op, param in entry:
                base = apply_atomic(op, base, param)
            if not snapshot:
                # Safe to serve from the fast path later: the serializable
                # read conflict range was just added by super().get. A
                # snapshot fold must NOT be cached — a later serializable
                # get() still owes its conflict range.
                self._overlay[key] = ("value", base)
        return base

    async def get_multi(self, keys, snapshot: bool = False) -> list:
        """Batched get with the same overlay-over-snapshot semantics as
        get(): locally-known keys resolve without a storage read (and
        without a conflict range); only the remainder rides the batched
        fetch."""
        if getattr(self, "ryw_disabled", False):
            return await super().get_multi(keys, snapshot)
        keys = list(keys)
        out: list = [None] * len(keys)
        # Unique key -> every position wanting it: a duplicated key must
        # fetch once and fan the SAME resolved value out to all positions
        # (per-position folding would rewrite an "ops" overlay to "value"
        # on the first occurrence and hand later occurrences the raw
        # storage base — two different values in one result).
        need: dict[bytes, list[int]] = {}
        for j, key in enumerate(keys):
            kind, entry = self._overlay.get(key, (None, None))
            if kind == "value":
                out[j] = entry
            elif kind == "unreadable":
                raise _unreadable()
            elif self._covered_by_clear(key):
                out[j] = None
            else:
                need.setdefault(key, []).append(j)
        if need:
            uniq = list(need)
            bases = await super().get_multi(uniq, snapshot)
            for key, base in zip(uniq, bases):
                kind, entry = self._overlay.get(key, (None, None))
                if kind == "ops":
                    for op, param in entry:
                        base = apply_atomic(op, base, param)
                    if not snapshot:
                        self._overlay[key] = ("value", base)
                for j in need[key]:
                    out[j] = base
        return out

    def _merge(
        self, base: dict[bytes, bytes], lo: bytes, hi: bytes, reverse: bool
    ) -> list[tuple[bytes, bytes]]:
        """Overlay-merge base rows over the fully-scanned span [lo, hi)."""
        merged: dict[bytes, bytes] = {
            k: v for k, v in base.items() if not self._covered_by_clear(k)
        }
        for k, (kind, entry) in self._overlay.items():
            if not (lo <= k < hi):
                continue
            if kind == "unreadable":
                raise _unreadable()
            if kind == "value":
                if entry is None:
                    merged.pop(k, None)
                else:
                    merged[k] = entry
            elif kind == "ops":
                v = merged.get(k)
                for op, param in entry:
                    v = apply_atomic(op, v, param)
                if v is None:
                    merged.pop(k, None)
                else:
                    merged[k] = v
        return sorted(merged.items(), reverse=reverse)

    async def get_range(
        self,
        begin: bytes,
        end: bytes,
        limit: int = 0,
        reverse: bool = False,
        snapshot: bool = False,
    ) -> list[tuple[bytes, bytes]]:
        if getattr(self, "ryw_disabled", False):
            return await super().get_range(begin, end, limit, reverse, snapshot)
        if limit <= 0:
            base = dict(
                await super().get_range(begin, end, 0, reverse, snapshot)
            )
            return self._merge(base, begin, end, reverse)
        # Limited scan: page through the snapshot until the merged view is
        # full. Rows may be eaten by our clears or deleted/inserted by the
        # overlay, so the merge only counts rows inside the span scanned so
        # far — a key past the scan horizon can never precede them.
        page = max(64, 2 * limit)
        base: dict[bytes, bytes] = {}
        cursor_b, cursor_e = begin, end
        while True:
            rows = await super().get_range(
                cursor_b, cursor_e, limit=page, reverse=reverse, snapshot=snapshot
            )
            base.update(rows)
            exhausted = len(rows) < page
            if exhausted:
                lo, hi = begin, end
            elif reverse:
                lo, hi = rows[-1][0], end
                cursor_e = rows[-1][0]
            else:
                lo, hi = begin, rows[-1][0] + b"\x00"
                cursor_b = rows[-1][0] + b"\x00"
            merged = self._merge(base, lo, hi, reverse)
            if exhausted or len(merged) >= limit:
                return merged[:limit]

    async def get_key(self, sel: KeySelector, snapshot: bool = False) -> bytes:
        # Resolve against the merged view: scan a window around the anchor.
        # (The reference resolves selectors inside the RYW view the same way;
        # we reuse the merged get_range since our selector offsets are small.)
        from foundationdb_tpu.runtime.shardmap import MAX_KEY

        # User-keyspace confinement in BOTH directions without system
        # access (see Transaction.get_key): system keys are neither
        # returned nor read. A prefix-scoped authz token further clamps
        # the scan to its covering span (Transaction._token_span) — the
        # keyspace-edge scan would be denied at storage.
        space_end = self._keyspace_end()
        space_begin = b""
        span = self._token_span()
        if span is not None:
            space_begin = max(space_begin, span[0])
            space_end = min(space_end, span[1])
        if sel.offset >= 1:
            begin = min(sel.key + b"\x00" if sel.or_equal else sel.key,
                        space_end)
            begin = max(begin, space_begin)
            rows = await self.get_range(
                begin, space_end, limit=sel.offset, snapshot=snapshot
            )
            return (rows[sel.offset - 1][0]
                    if len(rows) >= sel.offset else MAX_KEY)
        back = 1 - sel.offset
        end = min(sel.key + b"\x00" if sel.or_equal else sel.key, space_end)
        end = max(end, space_begin)
        rows = await self.get_range(space_begin, end, limit=back,
                                    reverse=True, snapshot=snapshot)
        return rows[back - 1][0] if len(rows) >= back else b""


def open_database(cluster) -> Database:
    """Build a client Database for a SimCluster (the `fdb.open()` analogue)."""
    db = Database(
        cluster.loop,
        cluster.grv_proxy_eps,
        cluster.commit_proxy_eps,
        cluster.storage_map.clone(),  # own copy: goes stale, refreshed on
        cluster.storage_eps,          # wrong_shard_server (location cache)
        controller_ep=getattr(cluster, "controller_ep", None),
        coordinator_eps=getattr(cluster, "coordinator_eps", None),
    )
    db.transaction_class = RYWTransaction  # RYW is the default surface
    db.cluster = cluster  # \xff\xff/status/json reads route through it
    return db
