"""ctypes surface over the native networked C client (native/netclient.cpp).

The C library is the deliverable — a C program links it and talks to the
cluster over TCP with no Python anywhere (the parity target is the
reference's bindings/c/fdb_c.cpp network client). This wrapper exists so
Python tests (and Python users who want the C data path) can drive it.
"""

from __future__ import annotations

import ctypes

import numpy as np

from foundationdb_tpu.core.errors import FdbError
from foundationdb_tpu.core.mutations import Mutation
from foundationdb_tpu.core.types import KeyRange

_LIB = None


def _lib():
    global _LIB
    if _LIB is None:
        from foundationdb_tpu.native import load_library

        lib = load_library("netclient")
        lib.fnet_connect.restype = ctypes.c_void_p
        lib.fnet_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.fnet_connect_tls.restype = ctypes.c_void_p
        lib.fnet_connect_tls.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ]
        lib.fnet_close.argtypes = [ctypes.c_void_p]
        lib.fnet_get_read_version.restype = ctypes.c_int64
        lib.fnet_get_read_version.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.fnet_commit.restype = ctypes.c_int64
        lib.fnet_commit.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int32, i32p, u8p, i64p, u8p, i64p,
            ctypes.c_int32, u8p, i64p, u8p, i64p,
            ctypes.c_int32, u8p, i64p, u8p, i64p,
        ]
        lib.fnet_commit_send.restype = ctypes.c_uint64
        lib.fnet_commit_send.argtypes = lib.fnet_commit.argtypes
        lib.fnet_commit_wait.restype = ctypes.c_int64
        lib.fnet_commit_wait.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.fnet_get.restype = ctypes.c_int32
        lib.fnet_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, u8p, ctypes.c_int64,
            ctypes.c_int64, u8p, ctypes.c_int64, i64p,
        ]
        lib.fnet_get_range.restype = ctypes.c_int32
        lib.fnet_get_range.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, u8p, ctypes.c_int64,
            u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, u8p, ctypes.c_int64, i64p,
        ]
        _LIB = lib
    return _LIB


def _flat(blobs: list[bytes]):
    """(data u8[], offsets i64[n+1]) ctypes views for a list of byte strings."""
    offs = np.zeros(len(blobs) + 1, np.int64)
    for i, b in enumerate(blobs):
        offs[i + 1] = offs[i] + len(b)
    data = np.frombuffer(b"".join(blobs), np.uint8) if blobs else np.zeros(1, np.uint8)
    data = np.ascontiguousarray(data)
    return (
        data, offs,
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )


class NetClient:
    """One TCP connection to a cluster transport; blocking calls."""

    def __init__(self, host: str, port: int,
                 grv_service: bytes = b"grv_proxy",
                 proxy_service: bytes = b"commit_proxy",
                 storage_service: bytes = b"storage0",
                 tls: dict | None = None):
        """`tls`: {"cert": path, "key": path, "ca": path} — mutual TLS
        against a TLS-enabled cluster (the spec's `tls` section; the C
        side dlopens the system OpenSSL 3 runtime)."""
        if tls:
            self._h = _lib().fnet_connect_tls(
                host.encode(), port,
                str(tls["cert"]).encode(), str(tls["key"]).encode(),
                str(tls["ca"]).encode(),
            )
        else:
            self._h = _lib().fnet_connect(host.encode(), port)
        if not self._h:
            raise ConnectionError(
                f"cannot connect to {host}:{port}"
                + (" (TLS handshake failed)" if tls else ""))
        self.grv_service = grv_service
        self.proxy_service = proxy_service
        self.storage_service = storage_service

    def close(self) -> None:
        if self._h:
            _lib().fnet_close(self._h)
            self._h = None

    def get_read_version(self) -> int:
        v = _lib().fnet_get_read_version(self._h, self.grv_service)
        if v < 0:
            raise FdbError(f"get_read_version failed", code=int(-v))
        return int(v)

    def _commit_args(self, read_version, mutations, read_ranges, write_ranges):
        mtypes = np.asarray([int(m.type) for m in mutations], np.int32)
        if mtypes.size == 0:
            mtypes = np.zeros(1, np.int32)
        p1 = _flat([m.param1 for m in mutations])
        p2 = _flat([m.param2 for m in mutations])
        rb = _flat([r.begin for r in read_ranges])
        re_ = _flat([r.end for r in read_ranges])
        wb = _flat([r.begin for r in write_ranges])
        we = _flat([r.end for r in write_ranges])
        # Keep the arrays alive through the C call.
        keepalive = (mtypes, p1, p2, rb, re_, wb, we)
        args = (
            self._h, self.proxy_service, read_version,
            len(mutations),
            mtypes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            p1[2], p1[3], p2[2], p2[3],
            len(read_ranges), rb[2], rb[3], re_[2], re_[3],
            len(write_ranges), wb[2], wb[3], we[2], we[3],
        )
        return args, keepalive

    def commit(self, read_version: int, mutations: list[Mutation],
               read_ranges: list[KeyRange] = (),
               write_ranges: list[KeyRange] = ()) -> int:
        args, _keep = self._commit_args(
            read_version, mutations, read_ranges, write_ranges
        )
        v = _lib().fnet_commit(*args)
        if v < 0:
            raise FdbError("commit failed", code=int(-v))
        return int(v)

    def commit_send(self, read_version: int, mutations: list[Mutation],
                    read_ranges: list[KeyRange] = (),
                    write_ranges: list[KeyRange] = ()) -> int:
        """Pipelined commit: send and return a request id without waiting.
        Any number may be outstanding on this connection; collect each
        with commit_wait (any order)."""
        args, _keep = self._commit_args(
            read_version, mutations, read_ranges, write_ranges
        )
        req = _lib().fnet_commit_send(*args)
        if req == 0:
            raise FdbError("commit send failed", code=1100)
        return int(req)

    def commit_wait(self, req_id: int) -> int:
        v = _lib().fnet_commit_wait(self._h, req_id)
        if v < 0:
            raise FdbError("commit failed", code=int(-v))
        return int(v)

    def get(self, key: bytes, version: int) -> bytes | None:
        cap = 1 << 20
        for _attempt in range(2):
            buf = np.zeros(cap, np.uint8)
            out_len = ctypes.c_int64(0)
            kbuf = np.frombuffer(key, np.uint8) if key else np.zeros(1, np.uint8)
            rc = _lib().fnet_get(
                self._h, self.storage_service,
                kbuf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                len(key), version,
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                buf.size, ctypes.byref(out_len),
            )
            if rc == 1:
                return None
            if rc == 0:
                return bytes(buf[: out_len.value])
            if rc == -1500 and cap < out_len.value <= (64 << 20):
                cap = int(out_len.value)  # C layer reported the needed size
                continue
            raise FdbError("get failed", code=int(-rc))
        raise FdbError("get failed after resize", code=1500)

    def get_range(self, begin: bytes, end: bytes, version: int,
                  limit: int = 10_000,
                  reverse: bool = False) -> list[tuple[bytes, bytes]]:
        """Rows in [begin, end) at `version` through the C wire client
        (server side: the proxy ReadRouter fans out across shards)."""
        u8 = ctypes.POINTER(ctypes.c_uint8)
        cap = 1 << 20
        for _attempt in range(2):
            buf = np.zeros(cap, np.uint8)
            used = ctypes.c_int64(0)
            bb = np.frombuffer(begin, np.uint8) if begin else np.zeros(1, np.uint8)
            eb = np.frombuffer(end, np.uint8) if end else np.zeros(1, np.uint8)
            rc = _lib().fnet_get_range(
                self._h, self.storage_service,
                bb.ctypes.data_as(u8), len(begin),
                eb.ctypes.data_as(u8), len(end),
                version, limit, 1 if reverse else 0,
                buf.ctypes.data_as(u8), buf.size, ctypes.byref(used),
            )
            if rc >= 0:
                rows, pos, raw = [], 0, bytes(buf[: used.value])
                for _ in range(rc):
                    klen = int.from_bytes(raw[pos:pos + 4], "little")
                    k = raw[pos + 4:pos + 4 + klen]
                    pos += 4 + klen
                    vlen = int.from_bytes(raw[pos:pos + 4], "little")
                    v = raw[pos + 4:pos + 4 + vlen]
                    pos += 4 + vlen
                    rows.append((k, v))
                return rows
            if rc == -1500 and cap < used.value <= (64 << 20):
                cap = int(used.value)
                continue
            raise FdbError("get_range failed", code=int(-rc))
        raise FdbError("get_range failed after resize", code=1500)
