"""Client library: Database / Transaction — grv, reads, commit, retry loop.

Reference: fdbclient/NativeAPI.actor.cpp. A Transaction lazily acquires a
read version from a GRV proxy, routes reads to storage servers by shard,
accumulates mutations and conflict ranges, and commits through a commit
proxy. ``Database.run`` is the canonical retry loop (reference: the
``on_error`` contract every binding implements): retryable errors reset
the transaction and back off; everything else propagates.

Key selectors resolve the way the reference's getKey does: walk |offset|
keys forward/back from the anchor via shard-routed range reads.
"""

from __future__ import annotations

from dataclasses import dataclass

from foundationdb_tpu.core.errors import (
    AdmissionPreAborted,
    CommitUnknownResult,
    FdbError,
    ProcessKilled,
    UsedDuringCommit,
)
from foundationdb_tpu.runtime.flow import BrokenPromise
from foundationdb_tpu.core.mutations import (
    ATOMIC_OPS,
    Mutation,
    MutationType,
    make_versionstamp,
)
from foundationdb_tpu.core.types import (
    KeyRange,
    MAX_KEY_SIZE,
    MAX_TRANSACTION_SIZE,
    MAX_VALUE_SIZE,
    single_key_range,
)

SPECIAL_KEY_PREFIX = b"\xff\xff"
STATUS_JSON_KEY = b"\xff\xff/status/json"
CONFLICTING_KEYS_PREFIX = b"\xff\xff/transaction/conflicting_keys/"
WORKER_INTERFACES_PREFIX = b"\xff\xff/worker_interfaces/"
from foundationdb_tpu.core.errors import (
    FutureVersion,
    KeyOutsideLegalRange,
    KeyTooLarge,
    NotCommitted,
    TransactionTimedOut,
    TransactionTooLarge,
    ValueTooLarge,
    WrongShardServer,
)
from foundationdb_tpu.obs.span import span_sink
from foundationdb_tpu.runtime.commit_proxy import CommitRequest
from foundationdb_tpu.runtime.shardmap import MAX_KEY, KeyShardMap


async def run_transaction_loop(tr, fn, max_retries: int = 50):
    """THE canonical retry loop (reference: the on_error contract every
    binding implements) — one definition shared by Database.run and
    Tenant.run so their semantics can never diverge."""
    for _ in range(max_retries):
        try:
            result = await fn(tr)
            await tr.commit()
            return result
        except FdbError as e:
            await tr.on_error(e)  # raises if not retryable
    raise FdbError("retry limit reached", code=1021)


@dataclass(frozen=True)
class KeySelector:
    """Reference: fdbclient KeySelectorRef. Resolves to the key `offset`
    positions after (before, if negative) the anchor: the last key < `key`
    (or ≤ `key` when or_equal)."""

    key: bytes
    or_equal: bool
    offset: int

    @classmethod
    def last_less_than(cls, key: bytes) -> "KeySelector":
        return cls(key, False, 0)

    @classmethod
    def last_less_or_equal(cls, key: bytes) -> "KeySelector":
        return cls(key, True, 0)

    @classmethod
    def first_greater_than(cls, key: bytes) -> "KeySelector":
        return cls(key, True, 1)

    @classmethod
    def first_greater_or_equal(cls, key: bytes) -> "KeySelector":
        return cls(key, False, 1)

    def __add__(self, n: int) -> "KeySelector":
        return KeySelector(self.key, self.or_equal, self.offset + n)

    def __sub__(self, n: int) -> "KeySelector":
        return KeySelector(self.key, self.or_equal, self.offset - n)


class Database:
    """Handle to the cluster: GRV proxies, commit proxies, storage routing."""

    def __init__(
        self,
        loop,
        grv_proxy_eps: list,
        commit_proxy_eps: list,
        storage_map: KeyShardMap,
        storage_eps: list,
        controller_ep=None,
        coordinator_eps: list | None = None,
    ):
        self.loop = loop
        self.grv_proxies = grv_proxy_eps
        self.commit_proxies = commit_proxy_eps
        self.storage_map = storage_map
        self.storage_eps = storage_eps
        self.controller = controller_ep
        self.coordinator_eps = list(coordinator_eps or [])
        self.cluster = None  # open_database attaches; special-key reads use it
        self.epoch = 1
        # Round-robin start is randomized per client: with a fixed start
        # every fresh client hammers the same proxy first — and on a
        # multi-region cluster eps[1] can be a standby-region proxy that
        # serves nothing, making a non-retrying caller fail
        # deterministically (deployed multi-region test find). Uses the
        # loop's seeded rng: deterministic under simulation.
        self._rr = loop.rng.randrange(1 << 16) if hasattr(loop, "rng") else 0
        self.transaction_class = Transaction  # ryw.open_database swaps in RYW
        # Failure monitoring (reference: the client's FailureMonitor):
        # storage endpoints that just failed are tried LAST for a TTL, so
        # one dead replica costs one detection delay total — not one per
        # read against its team.
        self._ep_failed_at: dict[int, float] = {}
        # Same for proxies, keyed by endpoint address (see _pick).
        self._proxy_failed_at: dict = {}

    async def refresh_client_info(self) -> None:
        """Re-fetch proxy endpoints from the cluster controller — how clients
        ride through recovery (reference: clients monitor ClientDBInfo and
        swap proxy connections when the epoch changes)."""
        if self.controller is None and not self.coordinator_eps:
            return
        try:
            info = await self.controller.get_client_info()
        except Exception:
            # Controller unreachable — maybe killed and re-elected: ask the
            # coordinators who leads now (reference: clients re-resolve the
            # controller through the cluster file's coordinators).
            await self._relocate_controller()
            try:
                info = await self.controller.get_client_info()
            except Exception:
                return  # still down: keep stale info, retry later
        self.epoch = info.epoch
        # Mid-recovery the controller can publish an empty generation;
        # keep the stale endpoints (they fail retryably) rather than
        # adopting a list the client cannot route through at all.
        if info.grv_proxy_eps:
            self.grv_proxies = list(info.grv_proxy_eps)
        if info.commit_proxy_eps:
            self.commit_proxies = list(info.commit_proxy_eps)

    async def _relocate_controller(self) -> None:
        for ep in self.coordinator_eps:
            try:
                val = await ep.get_leader()
            except Exception:
                continue
            if val and val.get("controller_ep") is not None:
                self.controller = val["controller_ep"]
                return

    def refresh_shard_map(self) -> None:
        """Invalidate the location cache after wrong_shard_server (reference:
        NativeAPI's invalidateCache + re-read of \\xff/keyServers)."""
        if self.cluster is not None:
            self.storage_map = self.cluster.storage_map.clone()

    MAX_SHARD_RETRIES = 5
    FAILED_EP_TTL = 4.0  # how long a failed replica is deprioritized
    PROXY_FAILED_TTL = 5.0  # how long a failed proxy endpoint sits out

    def _order_team(self, team):
        """Team members with recently-failed replicas demoted to the end
        (reference: FailureMonitor-aware load balancing)."""
        now = self.loop.now

        def bad(tag):
            return now - self._ep_failed_at.get(tag, -1e9) < self.FAILED_EP_TTL

        return sorted(team, key=bad)

    async def read_key(self, key: bytes, version: int,
                       token: str | None = None):
        """Point read with replica failover + shard-map refresh: try every
        team member (dead replicas skipped), refresh the map and re-route on
        wrong_shard_server (data distribution moved the shard)."""
        for _ in range(self.MAX_SHARD_RETRIES):
            team = self.storage_map.team_for_key(key)
            wrong_shard = False
            last_future = None
            for tag in self._order_team(team):
                try:
                    return await self.storage_eps[tag].get(
                        key, version, token=token)
                except BrokenPromise:
                    self._ep_failed_at[tag] = self.loop.now
                    continue  # dead/partitioned replica: try the next
                except FutureVersion as e:
                    # Replica behind the read version (pull lag, or a
                    # partitioned region's fenced replica that can NEVER
                    # reach a successor-generation version): demote it
                    # and try a caught-up team member before giving up.
                    self._ep_failed_at[tag] = self.loop.now
                    last_future = e
                    continue
                except WrongShardServer:
                    wrong_shard = True
                    break
            if last_future is not None and not wrong_shard:
                raise last_future
            self.refresh_shard_map()
            if not wrong_shard:
                # Whole team unreachable: brief pause, maybe a recovery or
                # move lands; retried reads are idempotent.
                await self.loop.sleep(0.05)
        raise ProcessKilled(f"no reachable storage replica for {key[:16]!r}")

    async def read_keys(self, keys: list[bytes], version: int,
                        token: str | None = None) -> list:
        """Batched point reads: keys group per owning team and each group
        rides ONE get_multi RPC (the storage side answers the whole group
        from one coalesced probe — reads/). Failover discipline matches
        read_key: team members in failure-demoted order, shard-map refresh
        and re-group on wrong_shard_server. Results are positional."""
        keys = list(keys)
        out: list = [None] * len(keys)
        remaining = list(range(len(keys)))
        for _ in range(self.MAX_SHARD_RETRIES):
            groups: dict[tuple, list[int]] = {}
            for i in remaining:
                team = tuple(self.storage_map.team_for_key(keys[i]))
                groups.setdefault(team, []).append(i)
            retry: list[int] = []
            future_idxs: list[int] = []
            last_future = None
            unreachable = False
            for team, idxs in groups.items():
                sub = [keys[i] for i in idxs]
                try:
                    vals = await self.first_of_team(
                        list(team),
                        lambda tag, sub=sub: self.storage_eps[tag].get_multi(
                            sub, version, token=token),
                    )
                    for i, v in zip(idxs, vals):
                        out[i] = v
                except WrongShardServer:
                    retry.extend(idxs)
                except FutureVersion as e:
                    last_future = e
                    future_idxs.extend(idxs)
                except ProcessKilled:
                    unreachable = True
                    retry.extend(idxs)
            if last_future is not None and not retry:
                # No group needs a re-route: whole-team lag is terminal
                # here, exactly as in read_key.
                raise last_future
            # Lagging-team keys ride the retry loop with the re-routed
            # groups (the map refresh may land them on a caught-up team);
            # they must NEVER fall out of `remaining` as a spurious None.
            retry.extend(future_idxs)
            if not retry:
                return out
            remaining = retry
            self.refresh_shard_map()
            if unreachable:
                await self.loop.sleep(0.05)  # whole team down: brief pause
        raise ProcessKilled("no reachable storage replica for batched read")

    async def watch_key(self, key: bytes, value, token: str | None = None):
        """Arm a watch on the key's current owner. wrong_shard_server —
        at arm time (stale map) or later when the armed shard moves away
        (storage cancel_range fails the watch) — propagates to the watch
        future as a retryable error: the CALLER re-arms, re-reading the
        value first, which is the reference contract. A transparent
        re-arm loop here would leave the future silently parked across
        moves and could not distinguish the two cases anyway."""
        tag = self.storage_map.tag_for_key(key)
        try:
            return await self.storage_eps[tag].watch(key, value, token=token)
        except WrongShardServer:
            self.refresh_shard_map()  # next arm lands on the new owner
            raise

    async def read_range(
        self, begin: bytes, end: bytes, version: int,
        limit: int, reverse: bool, token: str | None = None,
    ) -> list[tuple[bytes, bytes]]:
        """Range read across shards with the same failover/refresh loop."""
        out: list[tuple[bytes, bytes]] = []
        cursor_begin, cursor_end = begin, end
        for _ in range(self.MAX_SHARD_RETRIES):
            try:
                parts = self.storage_map.split_range_teams(
                    KeyRange(cursor_begin, cursor_end)
                )
                if reverse:
                    parts = parts[::-1]
                for r, team in parts:
                    if len(out) >= limit:
                        return out
                    got = await self._read_part(
                        r, team, version, limit - len(out), reverse, token)
                    out.extend(got)
                    # Progress cursor so a later wrong-shard retry does not
                    # re-read (and double-count) finished parts.
                    if reverse:
                        cursor_end = r.begin
                    else:
                        cursor_begin = r.end
                return out
            except WrongShardServer:
                self.refresh_shard_map()
        raise ProcessKilled("shard map kept changing under range read")

    async def first_of_team(self, team, make_call):
        """Try `await make_call(tag)` on every team member in
        failure-demoted order — THE team-failover policy (one definition,
        shared by range reads and locality's shard_stats): dead
        (BrokenPromise) and lagging/fenced (FutureVersion) replicas are
        demoted and the next member tried; a member that no longer serves
        the shard (WrongShardServer) is noted but the rest still get
        their shot. Raise preference: wrong-shard (caller refreshes the
        map) > future-version (caller retries) > no-reachable-replica.
        (Point reads keep their own loop: they STOP at the first
        wrong-shard answer — same team, same stale map — instead of
        trying the remaining members.)"""
        last_wrong: Exception | None = None
        last_future: Exception | None = None
        for tag in self._order_team(team):
            try:
                return await make_call(tag)
            except BrokenPromise:
                self._ep_failed_at[tag] = self.loop.now
                continue
            except FutureVersion as e:
                self._ep_failed_at[tag] = self.loop.now
                last_future = e
                continue
            except WrongShardServer as e:
                last_wrong = e
                continue
        if last_wrong is not None:
            raise last_wrong
        if last_future is not None:
            raise last_future
        raise ProcessKilled("no reachable storage replica in team")

    async def _read_part(
        self, r: KeyRange, team, version: int, limit: int, reverse: bool,
        token: str | None = None,
    ) -> list[tuple[bytes, bytes]]:
        return await self.first_of_team(
            team,
            lambda tag: self.storage_eps[tag].get_range(
                r.begin, r.end, version, limit=limit, reverse=reverse,
                token=token,
            ),
        )

    def _pick(self, eps: list):
        """Round-robin over proxy endpoints, skipping recently-failed ones.

        The demotion matters beyond plain failover: a retry loop calls
        _pick twice per attempt (GRV then commit), so with a bare rotation
        over 2 proxies the parity locks — GRV lands on the healthy proxy
        every attempt and commit on the broken one, forever (deployed
        multi-region find: the standby region's proxy is up but serves
        nothing). Failed endpoints sit out PROXY_FAILED_TTL seconds."""
        if not eps:
            # No known endpoints (fresh client against a recovering
            # cluster): retryable — on_error refreshes the client info.
            raise ProcessKilled("no known proxy endpoints")
        self._rr += 1
        now = self.loop.now
        n = len(eps)
        for j in range(n):
            ep = eps[(self._rr + j) % n]
            if (now - self._proxy_failed_at.get(self._ep_addr(ep), -1e9)
                    >= self.PROXY_FAILED_TTL):
                return ep
        return eps[self._rr % n]  # everything demoted: plain rotation

    @staticmethod
    def _ep_addr(ep):
        """Stable identity for a proxy endpoint (its peer address /
        process): grv and commit endpoint objects for the same process
        must share one demotion entry, and refreshed endpoint lists must
        keep it. NOTE: both transports' endpoint classes synthesize RPC
        stubs via __getattr__ for non-underscore names — only their REAL
        attributes (`_addr`; sim `process`) are safe to probe."""
        addr = ep.__dict__.get("_addr")  # deployed RemoteEndpoint
        if addr is not None:
            return addr
        proc = ep.__dict__.get("process")  # sim Endpoint
        if proc is not None:
            return proc
        return id(ep)

    def note_proxy_failed(self, ep) -> None:
        self._proxy_failed_at[self._ep_addr(ep)] = self.loop.now

    def transaction(self) -> "Transaction":
        return self.transaction_class(self)

    async def run(self, fn, max_retries: int = 50):
        """Run `await fn(tr)` + commit with the standard retry loop."""
        return await run_transaction_loop(self.transaction(), fn, max_retries)


class Transaction:
    """Raw (non-RYW) transaction: reads see the snapshot only; your own
    writes become visible after commit. client/ryw.py layers read-your-writes
    on top (and is what Database.run hands out in practice via layers)."""

    MAX_BACKOFF = 1.0
    # Admission pre-abort pacing (the repair engine's score-scaled
    # jittered formula, starting far below the blind ladder): delay =
    # min(cap, base · odds · 2^streak) · jitter(0.5..1.5), where streak
    # counts CONSECUTIVE pre-aborts of this transaction — first retries
    # are near-immediate (the pre-abort cost the cluster almost nothing),
    # but a txn losing over and over escalates toward the cap so hot-key
    # storms cannot starve a client into its retry limit.
    PREABORT_BACKOFF_BASE = 0.0005
    PREABORT_BACKOFF_CAP = 0.1

    def __init__(self, db: Database):
        self.db = db
        self._backoff = 0.01
        # Options survive resets, like reference options on a retry loop.
        self.report_conflicting_keys = False  # fdb option 712
        self.tags: set[str] = set()  # fdb option TAG (ratekeeper throttling)
        self.timeout_ms: int | None = None  # option 500
        self.retry_limit: int | None = None  # option 501
        self.size_limit: int | None = None  # option 503
        self.access_system_keys = False  # option 301
        self.lock_aware = False  # option 306: commit despite database lock
        self.authorization_token: str | None = None  # option 2000
        # Admission lane (reference: PRIORITY_SYSTEM_IMMEDIATE option 200 /
        # PRIORITY_BATCH option 201): shapes both the GRV lane and the
        # commit proxy's batch formation (sched/lanes.py).
        self.priority = "default"
        # Admission-control opt-out (admission subsystem): fail with
        # AdmissionShaped (retryable) instead of riding the serializing
        # shaped lane — for latency-sensitive clients that prefer an
        # immediate error to a queue position.
        self.admission_no_shape = False
        self._retries = 0  # attempts consumed by on_error (for retry_limit)
        self._preabort_streak = 0  # consecutive pre-aborts (pacing)
        # Commit-path tracing (obs subsystem): None = sampling undecided,
        # False = not sampled, TraceContext = sampled. Decided once per
        # transaction LIFETIME (at the first GRV) so a retried txn keeps
        # its trace id; per-attempt stamps live in _obs_grv (reset-able).
        self._obs = None
        self._obs_grv: "tuple[float, float] | None" = None
        self._reset()

    def set_option(self, name: str, value=None) -> None:
        """Transaction options (reference: fdb_transaction_set_option);
        only the ones this client implements."""
        if name == "report_conflicting_keys":
            self.report_conflicting_keys = True
        elif name == "tag":
            if not value:
                raise FdbError("tag option requires a value", code=2006)
            self.tags.add(value)
        elif name == "timeout":
            ms = int(value)
            # Reference option 500: value 0 clears a previously-set timeout.
            self.timeout_ms = ms if ms > 0 else None
            if self.timeout_ms is not None:
                self._deadline = self._start + self.timeout_ms / 1000.0
        elif name == "retry_limit":
            self.retry_limit = int(value)
        elif name == "size_limit":
            limit = int(value)
            if not 32 <= limit <= MAX_TRANSACTION_SIZE:
                # Rejected option must be a no-op.
                raise FdbError(
                    f"size_limit {value} outside [32, "
                    f"{MAX_TRANSACTION_SIZE}]", code=2006)
            self.size_limit = limit
        elif name == "access_system_keys":
            self.access_system_keys = True
        elif name == "lock_aware":
            self.lock_aware = True
        elif name == "priority_system_immediate":
            self.priority = "system"
        elif name == "priority_batch":
            self.priority = "batch"
        elif name == "admission_no_shape":
            self.admission_no_shape = True
        elif name == "authorization_token":
            if not value:
                raise FdbError("authorization_token requires a value",
                               code=2006)
            self.authorization_token = (
                value.decode() if isinstance(value, bytes) else str(value))
        else:
            raise FdbError(f"unknown transaction option {name!r}", code=2006)

    def _check_timeout(self) -> None:
        if self.timeout_ms is not None and self.db.loop.now > self._deadline:
            raise TransactionTimedOut(
                f"transaction exceeded {self.timeout_ms}ms")

    def _reset(self) -> None:
        # Timeout measures from creation/reset, like the reference (the
        # option itself survives resets; the clock restarts per attempt).
        self._start = self.db.loop.now
        if self.timeout_ms is not None:
            self._deadline = self._start + self.timeout_ms / 1000.0
        self._read_version: int | None = None
        self.mutations: list[Mutation] = []
        self.read_ranges: list[KeyRange] = []
        self.write_ranges: list[KeyRange] = []
        self._committed: tuple[int, int] | None = None  # (version, batch_order)
        self._pending_watches: list[tuple[bytes, bytes | None]] = []
        self._watch_futures: list = []
        self._conflicting_ranges: list[tuple[bytes, bytes]] = []
        self._obs_grv = None  # per-attempt GRV stamp (obs subsystem)

    # -- versions -------------------------------------------------------------

    async def get_read_version(self) -> int:
        self._check_timeout()
        if self._read_version is None:
            if self._obs is None:
                # Sampling decision (obs subsystem): once per txn, at the
                # first GRV — counter-based, so it never perturbs the
                # loop's seeded RNG stream. None (no sink / not sampled)
                # collapses to False: decided, unsampled.
                sink = span_sink(self.db.loop)
                self._obs = (sink.sample() if sink is not None
                             else None) or False
            t_grv = self.db.loop.now if self._obs else 0.0
            ep = self.db._pick(self.db.grv_proxies)
            try:
                self._read_version = await ep.get_read_version(
                    # Lane pass-through: system traffic must reach the GRV
                    # proxy AS system — it bypasses ratekeeper admission
                    # there (campaign find: mapping system onto the default
                    # lane let resolver-queue backpressure starve system
                    # txns behind the very storm they outrank).
                    self.priority,
                    sorted(self.tags) if self.tags else None,
                )
            except BrokenPromise as e:
                # Dead/retired GRV proxy: retryable — on_error refreshes the
                # proxy list from the controller before the next attempt.
                self.db.note_proxy_failed(ep)
                raise ProcessKilled(str(e)) from e
            except ProcessKilled as e:
                if "unconfirmed" in str(e) and str(e).startswith("grv epoch"):
                    # The proxy's epoch-liveness confirm failed (its tlog
                    # set is locked/fenced/unreachable): it can mint no
                    # read versions until stand-down — demote it so the
                    # retry rotates to a confirmable proxy immediately.
                    self.db.note_proxy_failed(ep)
                raise
            except FdbError as e:
                if e.code == 1500 and str(e).startswith("no service"):
                    # Proxy process up but serving no recruited role yet
                    # (standby-region proxy, or mid-recruitment): same
                    # recovery path as a dead proxy — demote + retry
                    # rotates to a recruited one.
                    self.db.note_proxy_failed(ep)
                    raise ProcessKilled(str(e)) from e
                raise
            if self._obs:
                # grv_wait stage: request -> grant, queue/deferral incl.
                self._obs_grv = (t_grv, self.db.loop.now - t_grv)
        return self._read_version

    def set_read_version(self, version: int) -> None:
        self._read_version = version

    @property
    def committed_version(self) -> int:
        if self._committed is None:
            raise FdbError("transaction not committed", code=2021)
        return self._committed[0]

    def get_versionstamp(self) -> bytes:
        """The 10-byte stamp this txn's versionstamped ops used (valid after
        commit; reference: Transaction::getVersionstamp)."""
        v, order = self._committed if self._committed else (None, None)
        if v is None:
            raise FdbError("transaction not committed", code=2021)
        return make_versionstamp(v, order)

    # -- reads ----------------------------------------------------------------

    async def get(self, key: bytes, snapshot: bool = False) -> bytes | None:
        self._check_timeout()
        if key.startswith(SPECIAL_KEY_PREFIX):
            return await self._get_special(key)
        _check_key(key)
        version = await self.get_read_version()
        value = await self._fetch_key(key, version)
        if not snapshot:
            self.read_ranges.append(single_key_range(key))
        return value

    async def get_multi(self, keys, snapshot: bool = False) -> list:
        """Batched point reads: one round trip per owning team instead of
        one per key (Database.read_keys → storage get_multi → the
        coalesced probe). Positional results; conflict-range accounting
        identical to the same sequence of get() calls."""
        self._check_timeout()
        keys = list(keys)
        if any(k.startswith(SPECIAL_KEY_PREFIX) for k in keys):
            # Special keys are client-synthesized — no batched path.
            return [await self.get(k, snapshot) for k in keys]
        for key in keys:
            _check_key(key)
        if not keys:
            return []
        version = await self.get_read_version()
        values = await self._fetch_keys(keys, version)
        if not snapshot:
            for key in keys:
                self.read_ranges.append(single_key_range(key))
        return values

    # Storage-fetch seams: the repair engine's transaction subclass
    # (repair/engine.py RepairableTransaction) overrides these to serve
    # replayed reads from its recorded cache — conflict-range accounting
    # above stays identical either way.

    async def _fetch_key(self, key: bytes, version: int) -> bytes | None:
        return await self.db.read_key(key, version,
                                      token=self.authorization_token)

    async def _fetch_keys(self, keys: list[bytes], version: int) -> list:
        # A subclass that re-points the single-key seam (repair's replayed
        # reads) keeps batched reads consistent automatically: route
        # through ITS _fetch_key rather than bypassing the override.
        if type(self)._fetch_key is not Transaction._fetch_key:
            return [await self._fetch_key(k, version) for k in keys]
        return await self.db.read_keys(keys, version,
                                       token=self.authorization_token)

    async def _fetch_range(
        self, begin: bytes, end: bytes, version: int, limit: int,
        reverse: bool,
    ) -> list[tuple[bytes, bytes]]:
        return await self.db.read_range(begin, end, version, limit, reverse,
                                        token=self.authorization_token)

    async def _get_special(self, key: bytes) -> bytes | None:
        """The special key space (reference: SpecialKeySpace — synthetic
        reads served by the client, no conflict ranges). Only the status
        document is populated, like the reference's most-used entry."""
        if key == STATUS_JSON_KEY and self.db.cluster is not None:
            import json

            from foundationdb_tpu.runtime.status import fetch_status

            doc = await fetch_status(self.db.cluster)
            return json.dumps(doc).encode()
        if key.startswith(CONFLICTING_KEYS_PREFIX):
            for k, v in self._conflicting_rows():
                if k == key:
                    return v
            return None
        if key.startswith(WORKER_INTERFACES_PREFIX):
            for k, v in self._worker_interface_rows():
                if k == key:
                    return v
            return None
        return None

    def _worker_interface_rows(self) -> list[tuple[bytes, bytes]]:
        """\xff\xff/worker_interfaces/<process> rows (reference: the
        module fdbcli uses for process discovery/kill): one row per live
        generation process plus persistent storages, valued with a small
        JSON of role info."""
        import json

        cluster = self.db.cluster
        if cluster is None:
            return []
        rows: list[tuple[bytes, bytes]] = []
        dead = cluster.loop.dead_processes
        gen = cluster.controller.generation
        procs: dict[str, str] = {p: "generation" for p in gen.heartbeat_eps}
        for p in cluster.storage_procs():
            # Real process names — region-prefixed on multi-region
            # clusters, where a bare "storage0" would advertise a row
            # that names nothing (kills through it no-op, dead-filter
            # never matches).
            procs.setdefault(p, "storage")
        for p in sorted(procs):
            if p in dead:
                continue
            rows.append((
                WORKER_INTERFACES_PREFIX + p.encode(),
                json.dumps({"process": p, "class": procs[p],
                            "epoch": gen.epoch}).encode(),
            ))
        return rows

    def _conflicting_rows(self) -> list[tuple[bytes, bytes]]:
        """\\xff\\xff/transaction/conflicting_keys/ rows from the last
        failed commit attempt: merged conflicting ranges as boundary
        markers — range begins valued \\x01, range ends \\x00 (the
        reference's exact format)."""
        merged: list[tuple[bytes, bytes]] = []
        for b, e in sorted(self._conflicting_ranges):
            if merged and b <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(merged[-1][1], e))
            else:
                merged.append((b, e))
        rows: list[tuple[bytes, bytes]] = []
        for b, e in merged:
            rows.append((CONFLICTING_KEYS_PREFIX + b, b"\x01"))
            rows.append((CONFLICTING_KEYS_PREFIX + e, b"\x00"))
        return rows

    async def get_range(
        self,
        begin: bytes,
        end: bytes,
        limit: int = 0,
        reverse: bool = False,
        snapshot: bool = False,
    ) -> list[tuple[bytes, bytes]]:
        """Rows in [begin, end); limit 0 = unlimited. The read conflict range
        covers only what the result depends on: up to the last key returned
        when the limit truncates the scan (reference: getRange conflict-range
        trimming in NativeAPI)."""
        self._check_timeout()
        if begin.startswith(SPECIAL_KEY_PREFIX):
            synthetic = self._conflicting_rows() + self._worker_interface_rows()
            rows = sorted(
                (k, v) for k, v in synthetic if begin <= k < end
            )
            if reverse:
                rows.reverse()
            return rows[:limit] if limit > 0 else rows
        version = await self.get_read_version()
        cap = limit if limit > 0 else 1 << 30
        rows = await self._fetch_range(begin, end, version, cap, reverse)
        rows = rows[:cap]
        if not snapshot:
            if limit > 0 and len(rows) == cap and rows:
                if reverse:
                    conflict = KeyRange(rows[-1][0], end)
                else:
                    conflict = KeyRange(begin, rows[-1][0] + b"\x00")
            else:
                conflict = KeyRange(begin, end)
            if not conflict.empty:
                self.read_ranges.append(conflict)
        return rows

    def _keyspace_end(self) -> bytes:
        """Exclusive end of the keyspace this transaction may resolve
        selectors in: the user keyspace unless access_system_keys."""
        return MAX_KEY if self.access_system_keys else b"\xff"

    def _token_span(self) -> tuple[bytes, bytes] | None:
        """Covering span [lo, hi) of the transaction token's prefixes —
        selector scans clamp to it, or a prefix-scoped token could never
        resolve selectors (the scan-to-the-keyspace-edge read is denied
        at storage; review finding). The token payload is readable
        without the key (signatures protect integrity, not secrecy).
        Multi-prefix tokens get their covering span; scans crossing the
        GAPS between prefixes are still denied server-side — use one
        token per tenant (TenantTransaction clamps exactly)."""
        if not self.authorization_token:
            return None
        try:
            import base64 as _b64
            import json as _json

            payload = self.authorization_token.split(".", 1)[0]
            doc = _json.loads(_b64.urlsafe_b64decode(
                payload + "=" * (-len(payload) % 4)))
            prefixes = [bytes.fromhex(p) for p in doc["prefixes"]]
        except Exception:
            return None  # malformed: let the server be the judge
        if not prefixes or b"" in prefixes:
            return None  # whole-user-keyspace grant: no clamp needed
        from foundationdb_tpu.core.types import strinc

        return min(prefixes), max(strinc(p) for p in prefixes)

    async def get_key(self, sel: KeySelector, snapshot: bool = False) -> bytes:
        """Resolve a key selector (reference: Transaction::getKey). Returns
        b"" when the selector runs off the front, MAX_KEY off the back.

        Without access_system_keys, resolution is confined to the user
        keyspace [b"", b"\\xff"): BOTH scan directions stop at b"\\xff", so
        system keys (e.g. the TimeKeeper's \\xff\\x02/ samples) can neither
        be returned nor be included in the recorded read-conflict range —
        otherwise every 10s system commit would spuriously conflict-abort
        transactions whose selectors ran off the end of user data
        (reference: getKey clamps non-system transactions to maxKey).
        With a prefix-scoped authz token, resolution is further confined
        to the token's covering span (scans outside it are denied at
        storage anyway)."""
        self._check_timeout()
        version = await self.get_read_version()
        anchor = sel.key
        space_end = self._keyspace_end()
        space_begin = b""
        span = self._token_span()
        if span is not None:
            space_begin = max(space_begin, span[0])
            space_end = min(space_end, span[1])
        # Position 0 is "last key ≤/< anchor"; walk |offset| from there.
        if sel.offset >= 1:
            # forward: the offset-th key in order from (anchor, or_equal ? > : ≥)
            begin = min(anchor + b"\x00" if sel.or_equal else anchor, space_end)
            begin = max(begin, space_begin)
            rows = await self._scan_keys(begin, space_end, sel.offset, False, version)
            result = rows[sel.offset - 1] if len(rows) >= sel.offset else MAX_KEY
        else:
            back = 1 - sel.offset  # how many keys back from the anchor
            end = min(anchor + b"\x00" if sel.or_equal else anchor, space_end)
            end = max(end, space_begin)
            rows = await self._scan_keys(space_begin, end, back, True, version)
            result = rows[back - 1] if len(rows) >= back else b""
        if not snapshot:
            # Result depends on the span between anchor and resolved key,
            # clipped to the space actually scanned.
            lo, hi = sorted((min(anchor, space_end), min(result, space_end)))
            self.read_ranges.append(KeyRange(lo, hi + b"\x00"))
        return result

    async def _scan_keys(
        self, begin: bytes, end: bytes, limit: int, reverse: bool, version: int
    ) -> list[bytes]:
        rows = await self.db.read_range(begin, end, version, limit, reverse,
                                        token=self.authorization_token)
        return [k for k, _v in rows[:limit]]

    async def watch(self, key: bytes) -> "object":
        """Register a watch armed at commit (reference: watches are part of
        the commit). Returns a Future resolving when the key's value changes
        from what this txn observed."""
        value = await self.get(key, snapshot=True)
        from foundationdb_tpu.runtime.flow import Future

        slot = Future()
        self._pending_watches.append((key, value))
        self._watch_futures.append(slot)
        return slot

    # -- writes ---------------------------------------------------------------

    def set(self, key: bytes, value: bytes) -> None:
        _check_writable_key(key, self.access_system_keys)
        _check_value(value)
        self.mutations.append(Mutation(MutationType.SET_VALUE, key, value))
        self.write_ranges.append(single_key_range(key))

    def clear(self, key: bytes) -> None:
        _check_writable_key(key, self.access_system_keys)
        self.mutations.append(Mutation(MutationType.CLEAR_RANGE, key, key + b"\x00"))
        self.write_ranges.append(single_key_range(key))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        r = KeyRange(begin, end)
        if r.empty:
            return
        _check_writable_key(begin, self.access_system_keys)
        end_cap = b"\xff\xff" if self.access_system_keys else b"\xff"
        if end > end_cap:
            raise KeyOutsideLegalRange(
                f"clear_range end {end[:16]!r} beyond {end_cap!r}")
        self.mutations.append(Mutation(MutationType.CLEAR_RANGE, begin, end))
        self.write_ranges.append(r)

    def atomic_op(self, op: MutationType, key: bytes, param: bytes) -> None:
        if op not in ATOMIC_OPS and op not in (
            MutationType.SET_VERSIONSTAMPED_KEY,
            MutationType.SET_VERSIONSTAMPED_VALUE,
        ):
            raise ValueError(f"not an atomic op: {op!r}")
        _check_writable_key(key, self.access_system_keys)
        self.mutations.append(Mutation(op, key, param))
        if op == MutationType.SET_VERSIONSTAMPED_KEY:
            # The final key is unknown until commit: conflict over every key
            # the stamp substitution could produce (prefix below the offset,
            # then any stamp + suffix).
            import struct

            (off,) = struct.unpack("<I", key[-4:])
            prefix = key[:-4][:off]
            self.write_ranges.append(KeyRange(prefix, prefix + b"\xff" * 11))
        else:
            self.write_ranges.append(single_key_range(key))

    def add_read_conflict_range(self, begin: bytes, end: bytes) -> None:
        self.read_ranges.append(KeyRange(begin, end))

    def add_write_conflict_range(self, begin: bytes, end: bytes) -> None:
        self.write_ranges.append(KeyRange(begin, end))

    # -- commit ---------------------------------------------------------------

    @property
    def is_read_only(self) -> bool:
        return not self.mutations and not self.write_ranges

    def get_approximate_size(self) -> int:
        """Commit-size estimate of the accumulated mutations + conflict
        ranges (reference: Transaction::getApproximateSize; same number
        the size_limit/transaction_too_large check uses)."""
        return sum(
            len(m.param1) + len(m.param2) + 24 for m in self.mutations
        ) + sum(
            len(r.begin) + len(r.end) + 16
            for r in self.read_ranges + self.write_ranges
        )

    async def commit(self) -> int:
        if self._committed is not None:
            raise UsedDuringCommit("commit() called twice")
        self._check_timeout()
        version = await self.get_read_version()
        if self.is_read_only:
            self._committed = (version, 0)
            self._arm_watches()  # read-only txns still arm watches at commit
            return version
        size = self.get_approximate_size()
        cap = min(self.size_limit or MAX_TRANSACTION_SIZE, MAX_TRANSACTION_SIZE)
        if size > cap:
            raise TransactionTooLarge(f"{size} > {cap}")
        req = CommitRequest(
            read_version=version,
            mutations=list(self.mutations),
            read_ranges=list(self.read_ranges),
            write_ranges=list(self.write_ranges),
            report_conflicting_keys=self.report_conflicting_keys,
            lock_aware=self.lock_aware,
            token=self.authorization_token,
            priority=self.priority,
            admission_no_shape=self.admission_no_shape,
            admission_attempts=self._preabort_streak,
            # Sampled txns carry their trace id so the proxy stamps
            # stage spans onto the reply (obs subsystem).
            trace=self._obs.tid if self._obs else None,
        )
        commit_ep = self.db._pick(self.db.commit_proxies)
        t_commit = self.db.loop.now if self._obs else 0.0
        try:
            res = await commit_ep.commit(req)
        except NotCommitted as e:
            # Stash the resolver's conflicting ranges for this attempt:
            # readable via \xff\xff/transaction/conflicting_keys/ until
            # the next reset (reference: SpecialKeySpace module backed by
            # the commit reply's conflictingKRIndices). The failed batch's
            # commit version + hot-range odds stay on the exception —
            # that's what the repair engine consumes (repair/engine.py).
            self._conflicting_ranges = list(e.conflicting_ranges or [])
            raise
        except BrokenPromise as e:
            # Proxy died mid-commit: the batch may or may not have reached
            # the tlogs — exactly commit_unknown_result.
            self.db.note_proxy_failed(commit_ep)
            raise CommitUnknownResult(str(e)) from e
        except FdbError as e:
            if e.code == 1500 and str(e).startswith("no service"):
                # Unrecruited proxy (standby region / mid-recruitment):
                # the commit never entered a batch, so this is a KNOWN
                # non-commit — plain retryable, not unknown-result.
                self.db.note_proxy_failed(commit_ep)
                raise ProcessKilled(str(e)) from e
            raise
        self._committed = (res.version, res.batch_order)
        if self._obs:
            try:
                self._obs_record_commit(getattr(res, "spans", None),
                                        t_commit, self.db.loop.now)
            except Exception:
                # Tracing bookkeeping must never fail a transaction that
                # IS durably committed (a malformed spans tuple from a
                # buggy/older proxy would otherwise raise out of commit()
                # and skip arming the watches below).
                pass
        self._arm_watches()
        return res.version

    def _obs_record_commit(self, proxy_spans, t0: float, t1: float) -> None:
        """Assemble this sampled txn's exact commit-path breakdown from
        the client-measured GRV/commit envelopes plus the proxy's
        piggybacked stage spans, and record it (span tree + per-stage
        histograms + the arithmetic residue as `unattributed`). e2e is
        the COMMIT PATH only — grv_wait + the commit round trip — so the
        identity e2e == sum(stages) + unattributed is exact and app
        think-time between reads never pollutes it.

        A sampled commit answered WITHOUT spans (the proxy process runs
        untraced — e.g. servers started without FDB_TPU_OBS=1, or an
        older peer) still records: grv_wait plus the whole commit round
        trip as `unattributed`, so the report says loudly that the
        server side is dark instead of silently showing nothing."""
        sink = span_sink(self.db.loop)
        if sink is None:
            return
        commit_dur = t1 - t0
        e2e = commit_dur
        stages: list[tuple[str, float, float]] = []
        if self._obs_grv is not None:
            g0, g_dur = self._obs_grv
            stages.append(("grv_wait", g0, g_dur))
            e2e += g_dur
        if proxy_spans:
            proxy_total = 0.0
            for name, start, dur in proxy_spans:
                if name == "proxy_total":
                    proxy_total = dur
                else:
                    stages.append((name, start, dur))
            # The transport residue: commit round trip minus the proxy's
            # envelope (request + reply legs, client/proxy queueing
            # outside the stamped stages). Clamped at 0 against
            # cross-process clock skew; the exact residue still lands in
            # `unattributed`.
            stages.append(("reply", t0, max(0.0, commit_dur - proxy_total)))
        sink.record_txn(self._obs.tid, e2e, stages)

    def _arm_watches(self) -> None:
        for (key, value), slot in zip(self._pending_watches, self._watch_futures):
            # Database.watch_key re-routes on wrong_shard_server (the
            # shard may have moved between read and commit) — the seed
            # armed directly on the possibly-stale location.
            fut = self.db.loop.spawn(
                self.db.watch_key(key, value,
                                  token=self.authorization_token),
                name="watch_arm",
            )
            fut.add_done_callback(
                lambda f, s=slot: s._finish(f._state, f._value)
            )
        self._pending_watches, self._watch_futures = [], []

    async def on_error(self, e: FdbError) -> None:
        """Reset + backoff for retryable errors; re-raise otherwise."""
        # This attempt's un-armed watches can never fire (reference fails
        # them with transaction_cancelled).
        for slot in self._watch_futures:
            slot._finish("error", FdbError("transaction reset", code=1025))
        self._pending_watches, self._watch_futures = [], []
        if not isinstance(e, FdbError) or not e.retryable:
            raise e
        self._retries += 1
        if self.retry_limit is not None and self._retries > self.retry_limit:
            raise e  # option 501: give up after N retries (reference)
        if isinstance(e, AdmissionPreAborted):
            # Admission pre-abort: a PROVEN loss detected before dispatch.
            # The blind exponential ladder is the wrong pacing here — the
            # proxy attached its hot-range odds, so apply the repair
            # subsystem's score-scaled jittered backoff instead and do
            # NOT consume the ladder (the next real conflict still starts
            # from the small backoff). This is what turns the abort storm
            # into a paced queue instead of a sleep pile-up; the streak
            # escalation bounds how long a persistent loser spins.
            self._reset()
            odds = max((s for _b, _e2, s in (e.hot_ranges or [])),
                       default=0.0)
            delay = min(self.PREABORT_BACKOFF_CAP,
                        self.PREABORT_BACKOFF_BASE * max(odds, 1.0)
                        * (1 << min(self._preabort_streak, 16)))
            self._preabort_streak += 1
            await self.db.loop.sleep(
                delay * (0.5 + self.db.loop.rng.random()))
            return
        self._preabort_streak = 0
        backoff = self._backoff
        self._backoff = min(self.MAX_BACKOFF, self._backoff * 2)
        self._reset()
        await self.db.loop.sleep(backoff * (0.5 + self.db.loop.rng.random()))
        # Only errors that can signal a generation change warrant a trip to
        # the controller — plain conflict retries must stay proxy-local.
        if isinstance(e, (CommitUnknownResult, ProcessKilled)):
            await self.db.refresh_client_info()


def _check_key(key: bytes) -> None:
    if len(key) > MAX_KEY_SIZE:
        raise KeyTooLarge(f"{len(key)} > {MAX_KEY_SIZE}")


def _check_writable_key(key: bytes, allow_system: bool = False) -> None:
    """Writes to the system keyspace (keys starting with 0xff) are illegal
    unless the transaction set the access_system_keys option (reference:
    error 2004 key_outside_legal_range on such mutations). The
    double-0xff special-key space is never directly writable."""
    _check_key(key)
    if key.startswith(SPECIAL_KEY_PREFIX):
        raise KeyOutsideLegalRange(f"write to special key {key[:16]!r}")
    if key.startswith(b"\xff") and not allow_system:
        raise KeyOutsideLegalRange(f"write to system key {key[:16]!r}")


def _check_value(value: bytes) -> None:
    if len(value) > MAX_VALUE_SIZE:
        raise ValueTooLarge(f"{len(value)} > {MAX_VALUE_SIZE}")
