"""Embedded database: ctypes binding over the native C client API.

Reference: bindings/python/fdb on top of bindings/c/fdb_c.cpp. The native
side (native/fdb_tpu_c.cpp) is a complete in-process MVCC transactional
engine with the fdb_c surface shape; this wrapper gives it the familiar
Python face — ``EmbeddedDatabase`` / ``EmbeddedTransaction`` with
get/get_range/set/clear/atomic ops, snapshot reads, and the standard
``run`` retry loop — raising the SAME error classes (core/errors.py) as
the distributed client, so layer code (tuple/subspace) runs on either.

Synchronous by design: the embedded engine has no network, so there is
nothing to await (the reference's C API is callback-async because it talks
to a cluster; embedded use collapses that)."""

from __future__ import annotations

import ctypes

from foundationdb_tpu.core.errors import (
    CommitUnknownResult,
    FdbError,
    InvertedRange,
    KeyTooLarge,
    NotCommitted,
    TransactionTooOld,
    UsedDuringCommit,
    ValueTooLarge,
)
from foundationdb_tpu.core.mutations import MutationType
from foundationdb_tpu.native import load_library

_ERRORS: dict[int, type[FdbError]] = {
    1007: TransactionTooOld,
    1020: NotCommitted,
    1021: CommitUnknownResult,
    2017: UsedDuringCommit,
    2102: KeyTooLarge,
    2103: ValueTooLarge,
    2005: InvertedRange,
}


def _lib() -> ctypes.CDLL:
    lib = load_library("fdb_tpu_c")
    if getattr(lib, "_fdb_tpu_configured", False):
        return lib
    u8p, i32p, i64p = (
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int64),
    )
    vp, vpp = ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p)
    sigs = {
        "fdb_tpu_create_database": ([], vp),
        "fdb_tpu_destroy_database": ([vp], None),
        "fdb_tpu_database_get_version": ([vp], ctypes.c_int64),
        "fdb_tpu_database_set_window": ([vp, ctypes.c_int64], None),
        "fdb_tpu_database_debug_entries": ([vp], ctypes.c_int64),
        "fdb_tpu_database_create_transaction": ([vp], vp),
        "fdb_tpu_transaction_destroy": ([vp], None),
        "fdb_tpu_transaction_reset": ([vp], None),
        "fdb_tpu_transaction_get_read_version": ([vp], ctypes.c_int64),
        "fdb_tpu_transaction_set_read_version": ([vp, ctypes.c_int64], None),
        "fdb_tpu_transaction_get": (
            [vp, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
             ctypes.POINTER(vp), i32p, i32p], ctypes.c_int),
        "fdb_tpu_transaction_get_range": (
            [vp, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
             ctypes.c_int, ctypes.c_int, ctypes.c_int, vpp, i32p, i32p],
            ctypes.c_int),
        "fdb_tpu_range_kv": (
            [vp, ctypes.c_int, ctypes.POINTER(vp), i32p, ctypes.POINTER(vp),
             i32p], None),
        "fdb_tpu_transaction_set": (
            [vp, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int],
            ctypes.c_int),
        "fdb_tpu_transaction_clear": ([vp, ctypes.c_char_p, ctypes.c_int], ctypes.c_int),
        "fdb_tpu_transaction_clear_range": (
            [vp, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int],
            ctypes.c_int),
        "fdb_tpu_transaction_atomic_op": (
            [vp, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
             ctypes.c_int], ctypes.c_int),
        "fdb_tpu_transaction_add_conflict_range": (
            [vp, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
             ctypes.c_int], ctypes.c_int),
        "fdb_tpu_transaction_commit": ([vp, i64p], ctypes.c_int),
        "fdb_tpu_transaction_get_committed_version": ([vp], ctypes.c_int64),
        "fdb_tpu_get_error": ([ctypes.c_int], ctypes.c_char_p),
        "fdb_tpu_error_predicate": ([ctypes.c_int, ctypes.c_int], ctypes.c_int),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype
    lib._fdb_tpu_configured = True
    return lib


def _check(code: int) -> None:
    if code:
        msg = _lib().fdb_tpu_get_error(code).decode()
        raise _ERRORS.get(code, FdbError)(msg, code=None if code in _ERRORS else code)


class EmbeddedTransaction:
    def __init__(self, db: "EmbeddedDatabase"):
        self._lib = db._lib
        self._tr = self._lib.fdb_tpu_database_create_transaction(db._handle())
        self._closed = False

    def _h(self):
        """Live native handle; a closed transaction raises instead of
        passing a freed pointer into C (use-after-free crash)."""
        if self._closed:
            raise FdbError("transaction used after close", code=2017)
        return self._tr

    # -- versions ----------------------------------------------------------

    def get_read_version(self) -> int:
        return self._lib.fdb_tpu_transaction_get_read_version(self._h())

    def set_read_version(self, v: int) -> None:
        self._lib.fdb_tpu_transaction_set_read_version(self._h(), v)

    @property
    def committed_version(self) -> int:
        v = self._lib.fdb_tpu_transaction_get_committed_version(self._h())
        if v < 0:
            raise FdbError("transaction not committed", code=2021)
        return v

    # -- reads -------------------------------------------------------------

    def get(self, key: bytes, snapshot: bool = False) -> bytes | None:
        out_val = ctypes.c_void_p()
        out_len, present = ctypes.c_int(), ctypes.c_int()
        _check(self._lib.fdb_tpu_transaction_get(
            self._h(), key, len(key), int(snapshot),
            ctypes.byref(out_val), ctypes.byref(out_len), ctypes.byref(present)))
        if not present.value:
            return None
        return ctypes.string_at(out_val, out_len.value)

    def get_range(self, begin: bytes, end: bytes, limit: int = 0,
                  reverse: bool = False, snapshot: bool = False
                  ) -> list[tuple[bytes, bytes]]:
        handle = ctypes.c_void_p()
        count, more = ctypes.c_int(), ctypes.c_int()
        _check(self._lib.fdb_tpu_transaction_get_range(
            self._h(), begin, len(begin), end, len(end), limit, int(reverse),
            int(snapshot), ctypes.byref(handle), ctypes.byref(count),
            ctypes.byref(more)))
        out = []
        k, v = ctypes.c_void_p(), ctypes.c_void_p()
        klen, vlen = ctypes.c_int(), ctypes.c_int()
        for i in range(count.value):
            self._lib.fdb_tpu_range_kv(
                handle, i, ctypes.byref(k), ctypes.byref(klen),
                ctypes.byref(v), ctypes.byref(vlen))
            out.append((ctypes.string_at(k, klen.value), ctypes.string_at(v, vlen.value)))
        return out

    # -- writes ------------------------------------------------------------

    def set(self, key: bytes, value: bytes) -> None:
        _check(self._lib.fdb_tpu_transaction_set(self._h(), key, len(key), value, len(value)))

    def clear(self, key: bytes) -> None:
        _check(self._lib.fdb_tpu_transaction_clear(self._h(), key, len(key)))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        _check(self._lib.fdb_tpu_transaction_clear_range(
            self._h(), begin, len(begin), end, len(end)))

    def atomic_op(self, op: MutationType, key: bytes, param: bytes) -> None:
        _check(self._lib.fdb_tpu_transaction_atomic_op(
            self._h(), key, len(key), param, len(param), int(op)))

    def add_read_conflict_range(self, begin: bytes, end: bytes) -> None:
        _check(self._lib.fdb_tpu_transaction_add_conflict_range(
            self._h(), begin, len(begin), end, len(end), 0))

    def add_write_conflict_range(self, begin: bytes, end: bytes) -> None:
        _check(self._lib.fdb_tpu_transaction_add_conflict_range(
            self._h(), begin, len(begin), end, len(end), 1))

    # -- commit / lifecycle --------------------------------------------------

    def commit(self) -> int:
        out = ctypes.c_int64()
        _check(self._lib.fdb_tpu_transaction_commit(self._h(), ctypes.byref(out)))
        return out.value

    def reset(self) -> None:
        self._lib.fdb_tpu_transaction_reset(self._h())

    def close(self) -> None:
        if not self._closed:
            self._lib.fdb_tpu_transaction_destroy(self._tr)
            self._closed = True

    def __del__(self):  # pragma: no cover - GC safety net
        try:
            self.close()
        except Exception:
            pass


class EmbeddedDatabase:
    """fdb.open()-shaped handle over the native engine."""

    def __init__(self):
        self._lib = _lib()
        self._db = self._lib.fdb_tpu_create_database()

    def _handle(self):
        if self._db is None:
            raise FdbError("database used after close", code=2017)
        return self._db

    def transaction(self) -> EmbeddedTransaction:
        return EmbeddedTransaction(self)

    @property
    def version(self) -> int:
        return self._lib.fdb_tpu_database_get_version(self._handle())

    def run(self, fn, max_retries: int = 50):
        """The standard retry loop (reference: every binding's
        @transactional): retryable errors reset + retry."""
        tr = self.transaction()
        try:
            for _ in range(max_retries):
                try:
                    result = fn(tr)
                    tr.commit()
                    return result
                except FdbError as e:
                    # One source of truth for retryability: the shared error
                    # model (core/errors.py), same as the distributed client.
                    if not e.retryable:
                        raise
                    tr.reset()
            raise FdbError("retry limit reached", code=1021)
        finally:
            tr.close()

    def close(self) -> None:
        if self._db is not None:
            self._lib.fdb_tpu_destroy_database(self._db)
            self._db = None

    def __del__(self):  # pragma: no cover - GC safety net
        try:
            self.close()
        except Exception:
            pass
