"""Tenants: named, prefix-isolated keyspaces.

Reference: fdbclient/Tenant.cpp + TenantManagement.actor.cpp — a tenant
is a name mapped (via system keyspace metadata) to a short unique key
prefix; transactions opened through a Tenant see only their own keyspace,
with every key transparently prefixed on the way in and stripped on the
way out. Same design here:

- metadata: ``\\xff/tenant/map/<name>`` → 8-byte prefix, allocated from
  ``\\xff/tenant/idCounter`` (monotone counter — prefixes are never
  reused, so late writes from a deleted tenant's stale client cannot
  land in a successor's keyspace).
- ``create_tenant`` / ``delete_tenant`` (must be empty, like the
  reference) / ``list_tenants`` are ordinary transactions with
  access_system_keys.
- ``Tenant(db, name)`` hands out TenantTransactions: RYW transactions
  whose public surface maps keys through the tenant prefix. Conflict
  ranges, RYW overlay, atomic ops, watches and retry all inherit — the
  prefix mapping happens strictly at the API boundary.
"""

from __future__ import annotations

import struct

from foundationdb_tpu.client.ryw import RYWTransaction
from foundationdb_tpu.client.transaction import KeySelector, run_transaction_loop
from foundationdb_tpu.core.errors import FdbError
from foundationdb_tpu.core.mutations import MutationType
from foundationdb_tpu.core.types import strinc

from foundationdb_tpu.core.types import TENANT_MAP_PREFIX  # canonical home

TENANT_ID_COUNTER = b"\xff/tenant/idCounter"
# Tenant data lives under this byte BY CONVENTION, like the reference's
# optional tenant mode: plain-database clients are not fenced off from it
# (a raw client CAN read or clobber \x1e-prefixed rows, exactly as a raw
# fdb client can when the cluster does not require tenants). Cluster-wide
# enforcement (reference: tenant_mode=required) is not implemented.
DATA_PREFIX = b"\x1e"


class TenantError(FdbError):
    code = 2130  # tenant_name_required..tenant family; closest public code

    def __init__(self, message: str, code: int | None = None):
        super().__init__(message, code)


class TenantNotFound(TenantError):
    def __init__(self, name: bytes):
        super().__init__(f"tenant {name!r} not found", code=2131)


class TenantExists(TenantError):
    def __init__(self, name: bytes):
        super().__init__(f"tenant {name!r} already exists", code=2132)


class TenantNotEmpty(TenantError):
    def __init__(self, name: bytes):
        super().__init__(f"tenant {name!r} is not empty", code=2133)


def _check_name(name: bytes) -> None:
    if not name or name.startswith(b"\xff"):
        raise TenantError(f"illegal tenant name {name!r}", code=2134)


async def create_tenant(db, name: bytes, token: str | None = None) -> bytes:
    """Create `name`; returns its data prefix (reference:
    TenantAPI::createTenant). On an authz-enabled cluster `token` must
    carry the system grant (runtime/authz mint_token system=True) — the
    tenant map lives in \\xff and system writes are token-gated there."""
    _check_name(name)

    async def body(tr):
        tr.set_option("access_system_keys")
        if token:
            tr.set_option("authorization_token", token)
        if await tr.get(TENANT_MAP_PREFIX + name) is not None:
            raise TenantExists(name)
        raw = await tr.get(TENANT_ID_COUNTER)
        next_id = (struct.unpack(">Q", raw)[0] + 1) if raw else 1
        tr.set(TENANT_ID_COUNTER, struct.pack(">Q", next_id))
        prefix = DATA_PREFIX + struct.pack(">Q", next_id)
        tr.set(TENANT_MAP_PREFIX + name, prefix)
        return prefix

    return await db.run(body)


async def delete_tenant(db, name: bytes, token: str | None = None) -> None:
    """Delete `name`; fails unless its keyspace is empty (reference
    semantics — data must be cleared first). `token` as create_tenant."""

    async def body(tr):
        tr.set_option("access_system_keys")
        if token:
            tr.set_option("authorization_token", token)
        prefix = await tr.get(TENANT_MAP_PREFIX + name)
        if prefix is None:
            raise TenantNotFound(name)
        rows = await tr.get_range(prefix, strinc(prefix), limit=1)
        if rows:
            raise TenantNotEmpty(name)
        tr.clear(TENANT_MAP_PREFIX + name)

    await db.run(body)


async def list_tenants(db, token: str | None = None) -> list[bytes]:
    """`token`: any valid token on a read-authz cluster (the tenant map
    admits every tokened reader — runtime/authz.TENANT_MAP_RANGE)."""
    async def body(tr):
        tr.set_option("access_system_keys")
        if token:
            tr.set_option("authorization_token", token)
        rows = await tr.get_range(
            TENANT_MAP_PREFIX, TENANT_MAP_PREFIX + b"\xff"
        )
        return [k[len(TENANT_MAP_PREFIX):] for k, _v in rows]

    return await db.run(body)


class Tenant:
    """Handle to one tenant's keyspace (reference: fdb_database_open_tenant).

    The prefix is resolved lazily on first use and cached (reference
    clients cache the tenant map entry the same way)."""

    def __init__(self, db, name: bytes, token: str | None = None):
        """`token`: the tenant's authz token — on a read-authz cluster the
        lazy prefix resolution reads the tenant map at storage, which
        admits any VALID token (runtime/authz.TENANT_MAP_RANGE)."""
        _check_name(name)
        self.db = db
        self.name = name
        self.token = token
        self._prefix: bytes | None = None

    async def _resolve(self) -> bytes:
        if self._prefix is None:
            # Through the retry loop: a raw read here would surface
            # transient errors (killed proxy, recovery in flight) as
            # tenant failures — found by the buggify campaign.
            async def body(tr):
                tr.set_option("access_system_keys")
                if self.token:
                    tr.set_option("authorization_token", self.token)
                return await tr.get(TENANT_MAP_PREFIX + self.name)

            prefix = await self.db.run(body)
            if prefix is None:
                raise TenantNotFound(self.name)
            self._prefix = prefix
        return self._prefix

    def transaction(self) -> "TenantTransaction":
        return TenantTransaction(self)

    async def run(self, fn, max_retries: int = 50):
        """The canonical retry loop, tenant-scoped. Resolves the prefix
        up front so write-only bodies work (no dummy read needed)."""
        await self._resolve()
        return await run_transaction_loop(self.transaction(), fn, max_retries)


class TenantTransaction(RYWTransaction):
    """RYW transaction confined to one tenant's prefix.

    Every public key crossing the API is mapped through the prefix; keys
    coming back out are stripped. The underlying machinery (conflict
    ranges, overlay, commit, retry) operates on the real (prefixed) keys
    and is inherited unchanged."""

    def __init__(self, tenant: Tenant):
        super().__init__(tenant.db)
        self._tenant = tenant

    async def _p(self, key: bytes) -> bytes:
        if not isinstance(key, bytes):
            raise TypeError(f"key must be bytes, got {type(key).__name__}")
        return await self._tenant._resolve() + key

    def _strip(self, key: bytes) -> bytes:
        return key[len(self._tenant._prefix):]

    # -- reads ---------------------------------------------------------------

    async def get(self, key: bytes, snapshot: bool = False):
        return await super().get(await self._p(key), snapshot=snapshot)

    async def get_range(self, begin: bytes, end: bytes, limit: int = 0,
                        reverse: bool = False, snapshot: bool = False):
        rows = await super().get_range(
            await self._p(begin), await self._p(end),
            limit=limit, reverse=reverse, snapshot=snapshot,
        )
        return [(self._strip(k), v) for k, v in rows]

    async def get_key(self, sel: KeySelector, snapshot: bool = False) -> bytes:
        """Selector walk over RAW (prefixed) ranges, scan bounds pinned to
        the tenant's span — resolution is confined to the tenant by
        construction (reference: tenant transactions clamp to the tenant
        range). Calls the BASE get_range explicitly: the inherited
        get_key would dispatch to our overriding get_range and
        double-prefix."""
        prefix = await self._tenant._resolve()
        raw_range = RYWTransaction.get_range
        anchor = prefix + sel.key
        span_end = strinc(prefix)  # covers EVERY tenant key incl. >= \xff
        if sel.offset >= 1:
            begin = anchor + b"\x00" if sel.or_equal else anchor
            rows = await raw_range(
                self, max(begin, prefix), span_end,
                limit=sel.offset, snapshot=snapshot,
            )
            if len(rows) < sel.offset:
                return b"\xff"  # off the tenant's end
            return self._strip(rows[sel.offset - 1][0])
        back = 1 - sel.offset
        end = anchor + b"\x00" if sel.or_equal else anchor
        rows = await raw_range(
            self, prefix, max(min(end, span_end), prefix),
            limit=back, reverse=True, snapshot=snapshot,
        )
        if len(rows) < back:
            return b""  # off the tenant's front
        return self._strip(rows[back - 1][0])

    async def watch(self, key: bytes):
        # Baseline read via the BASE get (the inherited watch would
        # dispatch back to our overriding get and double-prefix).
        from foundationdb_tpu.runtime.flow import Future

        real = await self._p(key)
        value = await RYWTransaction.get(self, real, snapshot=True)
        slot = Future()
        self._pending_watches.append((real, value))
        self._watch_futures.append(slot)
        return slot

    # -- writes --------------------------------------------------------------
    # Mutations are synchronous in the base API, so the prefix must be
    # resolved beforehand: Tenant.run resolves it before the retry loop;
    # a hand-built transaction must read (or await tenant._resolve())
    # before writing.

    def _pp(self, key: bytes) -> bytes:
        if self._tenant._prefix is None:
            raise TenantError(
                "tenant prefix not resolved — use Tenant.run (resolves it "
                "up front), or read/await tenant._resolve() first",
                code=2135,
            )
        if not isinstance(key, bytes):
            raise TypeError(f"key must be bytes, got {type(key).__name__}")
        return self._tenant._prefix + key

    def set(self, key: bytes, value: bytes) -> None:
        super().set(self._pp(key), value)

    def clear(self, key: bytes) -> None:
        super().clear(self._pp(key))

    def clear_range(self, begin: bytes, end: bytes) -> None:
        super().clear_range(self._pp(begin), self._pp(end))

    def atomic_op(self, op: MutationType, key: bytes, param: bytes) -> None:
        super().atomic_op(op, self._pp(key), param)

    def add_read_conflict_range(self, begin: bytes, end: bytes) -> None:
        super().add_read_conflict_range(self._pp(begin), self._pp(end))

    def add_write_conflict_range(self, begin: bytes, end: bytes) -> None:
        super().add_write_conflict_range(self._pp(begin), self._pp(end))
