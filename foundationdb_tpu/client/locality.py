"""Locality API — shard boundaries and key→server placement.

Reference: fdbclient's locality surface (bindings expose it as
``fdb.locality``): ``get_boundary_keys`` walks the ``\\xff/keyServers/``
map to list shard boundaries, ``get_addresses_for_key`` returns the
storage servers owning a key. Here the same answers come from the
client's shard map (refreshed from the controller, the way the reference
reads keyServers through the proxies), so callers can partition scans by
real shard boundaries and route work near data.
"""

from __future__ import annotations

from foundationdb_tpu.core.types import KeyRange


async def get_boundary_keys(db, begin: bytes, end: bytes) -> list[bytes]:
    """Shard boundary keys in [begin, end), ascending. The first boundary
    at or after `begin` starts the list (reference semantics: the split
    points of the key range, suitable for parallelising a scan)."""
    await db.refresh_client_info()
    bounds: list[bytes] = []
    for sub, _tag in db.storage_map.split_range(KeyRange(begin, end)):
        bounds.append(sub.begin)
    return [b for b in bounds if begin <= b < end]


async def get_addresses_for_key(tr, key: bytes) -> list[str]:
    """Process names of the storage team serving `key` (reference:
    Transaction::getAddressesForKey; process identity stands in for
    ip:port in the sim, and IS ip:port under the TCP runtime)."""
    db = tr.db
    await db.refresh_client_info()
    team = db.storage_map.team_for_key(key)
    out = []
    for tag in team:
        ep = db.storage_eps[tag]
        # Sim endpoints carry a `process` name; TCP RemoteEndpoints carry
        # `_addr` (their __getattr__ manufactures RPC stubs, so a plain
        # getattr for "process" would return a callable, not a name).
        addr = getattr(ep, "_addr", None)
        if addr is not None:
            out.append(f"{addr[0]}:{addr[1]}")
        else:
            proc = ep.__dict__.get("process")
            out.append(proc if isinstance(proc, str) else f"storage{tag}")
    return out


async def get_estimated_range_size_bytes(tr, begin: bytes, end: bytes) -> int:
    """Estimated bytes stored in [begin, end) (reference:
    Transaction::getEstimatedRangeSizeBytes, backed by StorageMetrics).
    Sums each covered shard's byte stats, with the same replica failover
    the read path uses (Database.first_of_team): a dead or lagging/fenced
    replica is demoted and the next team member answers, instead of the
    whole estimate failing on the primary tag alone (ADVICE.md r5)."""
    db = tr.db
    await db.refresh_client_info()
    # Estimate at the transaction's read version: shard_stats waits for
    # the storage apply loop (known-committed fence) to reach it, so the
    # caller's own committed writes are counted.
    version = await tr.get_read_version()
    token = getattr(tr, "authorization_token", None)
    total = 0
    for sub, team in db.storage_map.split_range_teams(KeyRange(begin, end)):
        stats = await db.first_of_team(
            team,
            lambda tag, sub=sub: db.storage_eps[tag].shard_stats(
                sub.begin, sub.end, version, token=token),
        )
        total += int(stats.get("bytes", 0))
    return total
