"""Hysteresis scale policy: deterministic decisions from scrape signals.

The policy is a pure consumer of the standard metrics-scrape contract
(obs/registry.py aggregated view) — the same dict the flight recorder
snapshots into the ring, so every threshold the policy acts on is
replayable from the ring after the fact (the doctor's `scale_relief`
attribution depends on exactly this).

Signals (see ``read_signals``):

====================  ==========================================  =========
signal                aggregated-scrape key                       scales
====================  ==========================================  =========
resolver_queue        ratekeeper.worst_resolver_queue             resolver
resolver_occupancy    ratekeeper.resolver_dispatch_occupancy      resolver
limiting_reason_code  ratekeeper.limiting_reason_code             resolver
grv_queue_per_proxy   grv_proxy.queued + grv_proxy.batch_queued   proxy
admission_saturation  ratekeeper.admission_saturation             proxy
====================  ==========================================  =========

Queue depth and dispatch occupancy are complementary resolver signals:
the commit pipeline self-clocks (a proxy holds few batches in flight),
so a saturated resolver shows a SHALLOW queue at high occupancy — depth
alone would sleep through exactly the overload that scaling fixes.
Occupancy is also the signal that provably responds to recruitment: a
resolver's dispatch work is proportional to the key-range fragments it
owns, so adding a resolver splits the load where depth may not move.

Hysteresis discipline (mirrors SloTracker's anomaly discipline —
warm-up + consecutive-window confirmation, never single-sample edges):

- **separated thresholds**: the scale-up trigger sits well above the
  scale-down trigger (e.g. resolver queue >= 16 up, <= 2 down), so a
  signal hovering between them drives NO decisions at all;
- **consecutive-window confirmation**: a direction must hold for
  ``confirm_up`` (resp. ``confirm_down``) consecutive observe() windows
  before it can fire — one spiky scrape is not a capacity change, and
  scale-down demands a LONGER streak than scale-up (shedding capacity
  is the riskier direction);
- **cooldown windows**: after any applied decision for a role, further
  decisions for that role are suppressed for ``cooldown_up_s`` /
  ``cooldown_down_s`` — an oscillating load whose period sits inside
  the cooldown provably cannot thrash the fleet (the AB's oscillation
  gate pins the resulting bound on scale-event count);
- **down only when calm everywhere**: scale-down candidates are
  suppressed outright while ANY scale-up pressure exists — mixed
  pressure means the system is NOT overprovisioned.

Every suppression is counted (``suppressed_confirm`` /
``suppressed_cooldown`` / ``suppressed_bounds``) and exported as
``autoscale_*`` counters so a quiet fleet is distinguishable from an
unarmed one.
"""

from __future__ import annotations

from dataclasses import dataclass

from foundationdb_tpu.runtime.ratekeeper import LIMIT_REASONS

#: roles the policy may scale (chain roles with a recruit path).
ROLES = ("proxy", "resolver")

_CODE_RESOLVER_QUEUE = LIMIT_REASONS.index("resolver_queue")
_CODE_ADMISSION = LIMIT_REASONS.index("admission_filter")


@dataclass(frozen=True)
class ScaleDecision:
    """One confirmed, cooldown-cleared, bounds-checked fleet change.

    ``metric``/``clear_below`` name the aggregated-scrape key the
    decision fired on and the value below which the triggering signal
    counts as CLEARED — the relief contract the flight-recorder
    annotation carries and the doctor re-checks from ring snapshots.
    Slack-triggered scale-downs carry ``clear_below=None``: there is no
    limiting signal left to clear, drain-complete is the relief.
    """

    role: str  # "proxy" | "resolver"
    direction: str  # "up" | "down"
    from_n: int
    to_n: int
    signal: str
    value: float
    metric: str
    clear_below: "float | None"
    clear_above: bool  # True: relief is the metric RISING past clear_below
    t_detect: float  # first window of the confirming streak


def read_signals(agg: dict, fleet: dict) -> dict:
    """Policy inputs from one aggregated scrape (missing keys read as
    quiet — a partial scrape must never manufacture pressure)."""
    n_proxies = max(1, int(fleet.get("proxy", 1)))
    queued = (float(agg.get("grv_proxy.queued", 0.0) or 0.0)
              + float(agg.get("grv_proxy.batch_queued", 0.0) or 0.0))
    return {
        "resolver_queue": float(
            agg.get("ratekeeper.worst_resolver_queue", 0.0) or 0.0),
        "resolver_occupancy": float(
            agg.get("ratekeeper.resolver_dispatch_occupancy", 0.0) or 0.0),
        "limiting_reason_code": int(
            agg.get("ratekeeper.limiting_reason_code", 0) or 0),
        "grv_queue_per_proxy": queued / n_proxies,
        "admission_saturation": float(
            agg.get("ratekeeper.admission_saturation", 0.0) or 0.0),
    }


class AutoscalePolicy:
    """Deterministic hysteresis policy (module docstring). Stateful
    across ``observe()`` calls (streaks + cooldown stamps) but pure of
    any cluster handle — the same policy object drives the sim and
    deployed control loops."""

    def __init__(self, *,
                 min_fleet: "dict | None" = None,
                 max_fleet: "dict | None" = None,
                 confirm_up: int = 2,
                 confirm_down: int = 6,
                 cooldown_up_s: float = 4.0,
                 cooldown_down_s: float = 12.0,
                 resolver_q_up: float = 16.0,
                 resolver_q_down: float = 2.0,
                 resolver_occ_up: float = 0.85,
                 resolver_occ_clear: float = 0.80,
                 resolver_occ_down: float = 0.30,
                 proxy_q_up: float = 64.0,
                 proxy_q_down: float = 2.0,
                 admission_sat_up: float = 0.75) -> None:
        assert confirm_down >= confirm_up >= 1
        assert cooldown_down_s >= cooldown_up_s >= 0.0
        assert resolver_q_up > resolver_q_down >= 0.0
        assert resolver_occ_up >= resolver_occ_clear > resolver_occ_down >= 0.0
        assert proxy_q_up > proxy_q_down >= 0.0
        self.min_fleet = dict(min_fleet or {r: 1 for r in ROLES})
        self.max_fleet = dict(max_fleet or {r: 4 for r in ROLES})
        self.confirm_up = int(confirm_up)
        self.confirm_down = int(confirm_down)
        self.cooldown_up_s = float(cooldown_up_s)
        self.cooldown_down_s = float(cooldown_down_s)
        self.resolver_q_up = float(resolver_q_up)
        self.resolver_q_down = float(resolver_q_down)
        self.resolver_occ_up = float(resolver_occ_up)
        self.resolver_occ_clear = float(resolver_occ_clear)
        self.resolver_occ_down = float(resolver_occ_down)
        self.proxy_q_up = float(proxy_q_up)
        self.proxy_q_down = float(proxy_q_down)
        self.admission_sat_up = float(admission_sat_up)
        self._streak: dict[tuple, int] = {}
        self._streak_t0: dict[tuple, float] = {}
        self._last_scale: dict[str, float] = {}
        self.windows_observed = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.suppressed_confirm = 0
        self.suppressed_cooldown = 0
        self.suppressed_bounds = 0

    # -- streak bookkeeping ------------------------------------------------

    def _press(self, key: tuple, t: float, pressed: bool) -> int:
        if not pressed:
            self._streak[key] = 0
            return 0
        if self._streak.get(key, 0) == 0:
            self._streak_t0[key] = t
        self._streak[key] = self._streak.get(key, 0) + 1
        return self._streak[key]

    def _cooldown_ok(self, role: str, t: float, direction: str) -> bool:
        last = self._last_scale.get(role)
        if last is None:
            return True
        window = (self.cooldown_up_s if direction == "up"
                  else self.cooldown_down_s)
        return (t - last) >= window

    # -- the decision ------------------------------------------------------

    def observe(self, t: float, agg: dict,
                fleet: dict) -> "ScaleDecision | None":
        """One control window: feed the scrape, get at most ONE decision
        (the control loop applies it and re-observes — capacity moves
        one step per window by construction)."""
        sig = read_signals(agg, fleet)
        self.windows_observed += 1
        rq, gq = sig["resolver_queue"], sig["grv_queue_per_proxy"]
        occ = sig["resolver_occupancy"]
        sat, code = sig["admission_saturation"], sig["limiting_reason_code"]
        res_q_up = rq >= self.resolver_q_up or code == _CODE_RESOLVER_QUEUE
        res_up = res_q_up or occ >= self.resolver_occ_up
        prox_up = (gq >= self.proxy_q_up or sat >= self.admission_sat_up
                   or code == _CODE_ADMISSION)
        res_down = (not res_up and rq <= self.resolver_q_down
                    and occ <= self.resolver_occ_down)
        prox_down = (not prox_up and gq <= self.proxy_q_down
                     and sat < self.admission_sat_up / 2)
        any_up = res_up or prox_up
        # Priority: resolver pressure outranks proxy pressure (it sits
        # deeper in the pipeline — a starved resolver backs commits up
        # into every proxy), ups outrank downs, downs need global calm.
        # Queue depth outranks occupancy within the resolver signal: an
        # actually-deep queue is the stronger evidence.
        candidates = (
            ("resolver", "up", res_up,
             *(("resolver_queue", rq,
                "ratekeeper.worst_resolver_queue",
                self.resolver_q_down, False) if res_q_up else
               ("resolver_occupancy", occ,
                "ratekeeper.resolver_dispatch_occupancy",
                self.resolver_occ_clear, False))),
            ("proxy", "up", prox_up,
             "admission_saturation" if sat >= self.admission_sat_up
             else "grv_queue", sat if sat >= self.admission_sat_up else gq,
             "grv_proxy.queued", None, False),
            ("resolver", "down", res_down and not any_up,
             "resolver_queue_slack", rq, "", None, False),
            ("proxy", "down", prox_down and not any_up,
             "grv_queue_slack", gq, "", None, False),
        )
        decision = None
        for role, direction, pressed, signal, value, metric, clear, \
                above in candidates:
            streak = self._press((role, direction), t, pressed)
            if not pressed or decision is not None:
                continue
            need = (self.confirm_up if direction == "up"
                    else self.confirm_down)
            if streak < need:
                self.suppressed_confirm += 1
                continue
            if not self._cooldown_ok(role, t, direction):
                self.suppressed_cooldown += 1
                continue
            from_n = int(fleet[role])
            to_n = from_n + (1 if direction == "up" else -1)
            if not (self.min_fleet[role] <= to_n <= self.max_fleet[role]):
                self.suppressed_bounds += 1
                continue
            clear_below = clear
            if role == "proxy" and direction == "up":
                # Aggregated GRV queue is summed across instances: the
                # calm threshold scales with the NEW fleet size.
                clear_below = self.proxy_q_down * to_n
            decision = ScaleDecision(
                role=role, direction=direction, from_n=from_n, to_n=to_n,
                signal=signal, value=float(value),
                metric=metric or "", clear_below=clear_below,
                clear_above=above,
                t_detect=self._streak_t0.get((role, direction), t),
            )
        if decision is not None:
            self._last_scale[decision.role] = t
            for d in ("up", "down"):
                self._streak[(decision.role, d)] = 0
            if decision.direction == "up":
                self.scale_ups += 1
            else:
                self.scale_downs += 1
        return decision

    def metrics(self) -> dict:
        """The documented ``autoscale_*`` counter set (AUTOSCALE_
        DOCUMENTED_COUNTERS in obs/registry.py — events_total is added
        by the control loop that owns the event list)."""
        return {
            "autoscale_windows_observed": self.windows_observed,
            "autoscale_scale_ups": self.scale_ups,
            "autoscale_scale_downs": self.scale_downs,
            "autoscale_suppressed_confirm": self.suppressed_confirm,
            "autoscale_suppressed_cooldown": self.suppressed_cooldown,
            "autoscale_suppressed_bounds": self.suppressed_bounds,
        }
