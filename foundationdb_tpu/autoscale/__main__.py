"""CLI: one-JSON-line selfcheck (default) or the full gated AB.

    env JAX_PLATFORMS=cpu python -m foundationdb_tpu.autoscale
    env JAX_PLATFORMS=cpu python -m foundationdb_tpu.autoscale --ab

Selfcheck exits non-zero when a gate fails; ``--ab`` always exits 0
with the verdict in the record's ``valid``/``gates`` fields (the
openloop precedent: rc is reserved for harness errors, so a watch
stage can still commit an honest failing record).
"""

from __future__ import annotations

import argparse
import json
import sys


def main() -> int:
    ap = argparse.ArgumentParser(prog="python -m foundationdb_tpu.autoscale")
    ap.add_argument("--ab", action="store_true",
                    help="run the full autoscale-vs-fixed AB + "
                         "oscillation gate (AUTOSCALE_AB.json record)")
    ap.add_argument("--seed", type=int, default=20260807)
    ap.add_argument("--fast", action="store_true",
                    help="shorter schedules (CI-sized)")
    args = ap.parse_args()

    from foundationdb_tpu.autoscale.ab import run_autoscale_ab, selfcheck

    if args.ab:
        rec = run_autoscale_ab(seed=args.seed, fast=args.fast)
        print(json.dumps(rec))
        return 0
    rec = selfcheck(seed=args.seed)
    print(json.dumps(rec))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
