"""Autoscale A/B + selfcheck: sim-twin closed-loop scaling, gated exactly.

``run_autoscale_ab`` produces the committed ``AUTOSCALE_AB.json`` record
(scripts/autoscale_ab.sh; tpuwatch stage ``ab_autoscale``): the SAME
seed and open-loop "dur:rate" schedule driven against two arms —

- **autoscale**: the closed-loop controller armed (controller.py), the
  hysteresis policy recruiting/retiring resolvers and proxies live
  through scale-via-recovery, every decision annotated on the flight
  ring;
- **fixed**: the identical cluster with the fleet frozen at the seed
  topology.

plus an **oscillating** run (autoscaler armed, load period sitting
INSIDE the policy cooldown) proving the hysteresis gates: the scale-
event count must stay within the computed bound — an oscillation-
follower would produce one event per period.

Gates (chaos style — exact, never liveness-only):

- zero acked-commit loss across every recruit/retire transition, and
  exactly-once unknown-result resolution (the chaos ledger's counter +
  marker identity, read back at one snapshot after quiesce);
- per scale event: time-to-relief with the staged detect/recruit/relief
  breakdown recorded;
- every scale event attributed by the doctor (``scale_relief``) to its
  triggering signal class from ring snapshots alone;
- the oscillating run within the hysteresis bound.

Honesty flags ride the record: ``valid`` (all gates), ``cpu_fallback``
(this is the CPU sim twin — no device claim), ``p99_quotable``. The
throughput *ratio* between arms is reported but NOT gated: sim virtual
time on a single-core host says nothing about multi-core scaling (the
OPENLOOP_AB precedent — see ROADMAP).
"""

from __future__ import annotations

import os
import tempfile

from foundationdb_tpu.autoscale.controller import Autoscaler, arm as arm_autoscaler
from foundationdb_tpu.autoscale.policy import AutoscalePolicy
from foundationdb_tpu.core.errors import (
    CommitUnknownResult,
    FdbError,
    NotCommitted,
    ProcessKilled,
)
from foundationdb_tpu.loadgen.arrivals import parse_profile, trace_schedule
from foundationdb_tpu.loadgen.chaos import (
    OP_TIMEOUT_S,
    AckedLedger,
    _bounded,
    _OpTimeout,
)

#: per-arrival total retry budget (sim seconds) before abandonment.
TXN_BUDGET_S = 20.0

#: resolver dispatch knobs that make queue depth (and the ratekeeper's
#: resolver_queue backpressure) observable in virtual time — the bench
#: OVERLOAD_SPEC values (loadgen/bench.py).
OVERLOAD_KNOBS = {"resolver_budget_s": 0.05,
                  "resolver_dispatch_cost_s": 0.05}


def _spread(k: int) -> bytes:
    """One raw leading byte spreading keys across the WHOLE keyspace so
    resolver/storage shard maps see balanced ranges (every printable
    prefix would pile onto the first shard of a uniform split)."""
    return bytes([(k * 83) % 250])


def _ctr_key(i: int, n_ctrs: int) -> bytes:
    return _spread(i * 97) + b"ctr/%02d" % i


# -- the exactly-once ledger workload (shared with tests/test_autoscale) ------


async def ledger_txn(loop, db, ledger: AckedLedger, lat: list, k: int,
                     n_ctrs: int, t_sched: float,
                     budget_s: float = TXN_BUDGET_S) -> None:
    """One arrival: atomically increment a counter + write a per-arrival
    marker + unique key (the chaos exactly-once oracle), with the chaos
    retry discipline — known non-commits retry, unknown outcomes stop
    and are resolved at read-back. Latency is CO-correct: measured from
    the SCHEDULED arrival, not the (possibly backlogged) spawn."""
    ctr_key = _ctr_key(k % n_ctrs, n_ctrs)
    marker = _spread(k) + b"m/%06d" % k
    ukey = _spread(k + 1) + b"u/%06d" % k
    val = b"v%06d" % k
    deadline = loop.now + budget_s
    backoff = 0.02
    while True:
        tr = db.transaction()
        commit_in_flight = False
        try:
            cur = await _bounded(loop, tr.get(ctr_key), OP_TIMEOUT_S,
                                 f"autoscale.get{k}")
            tr.set(ctr_key, b"%d" % (int(cur or b"0") + 1))
            tr.set(marker, b"1")
            tr.set(ukey, val)
            commit_in_flight = True
            await _bounded(loop, tr.commit(), OP_TIMEOUT_S,
                           f"autoscale.commit{k}")
            ledger.ack(ukey, val, marker)
            lat.append(loop.now - t_sched)
            return
        except _OpTimeout:
            # A recruit/retire recovery can drop an in-flight promise on
            # the floor (the chaos find): a hung COMMIT is may-be-
            # committed; a hung read provably committed nothing — retry.
            ledger.op_timeouts += 1
            if commit_in_flight:
                ledger.note_unknown(ukey, val, marker)
                return
        except CommitUnknownResult:
            ledger.note_unknown(ukey, val, marker)
            return
        except NotCommitted:
            ledger.conflict_retries += 1
        except FdbError as e:
            if not e.retryable:
                ledger.nonretryable.append(f"{type(e).__name__}: {e}")
                return
            if isinstance(e, ProcessKilled):
                try:  # re-discover the new generation's proxies
                    await db.refresh_client_info()
                except Exception:
                    pass
        if loop.now > deadline:
            ledger.abandoned += 1
            return
        backoff = min(0.5, backoff * 1.6)
        await loop.sleep(backoff * (0.5 + loop.rng.random()))


async def drive_ledger(loop, db, ledger: AckedLedger, schedule, lat: list,
                       n_ctrs: int = 32, max_inflight: int = 1024,
                       drain_s: float = 10.0) -> None:
    """Open-loop driver over an arrivals schedule (loadgen/arrivals.py):
    arrivals are offered on time regardless of completions; past
    max_inflight they are shed (counted, never silently dropped). The
    accounting identity is asserted at the end."""
    t0 = loop.now
    live: set = set()
    for k, off in enumerate(schedule):
        dt = t0 + float(off) - loop.now
        if dt > 0:
            await loop.sleep(dt)
        ledger.offered += 1
        if len(live) >= max_inflight:
            ledger.shed += 1
            continue
        task = loop.spawn(
            ledger_txn(loop, db, ledger, lat, k, n_ctrs, t0 + float(off)),
            name=f"autoscale.txn{k}")
        live.add(task)
        task.add_done_callback(lambda f, t=task: live.discard(t))
    deadline = loop.now + drain_s
    while live and loop.now < deadline:
        await loop.sleep(0.1)
    leftovers = list(live)
    for task in leftovers:
        task.cancel()
    settle = loop.now + 5.0
    while any(not t.done() for t in leftovers) and loop.now < settle:
        await loop.sleep(0.05)
    ledger.abandoned += sum(1 for t in leftovers if t.is_error())
    assert (len(ledger.acked) + len(ledger.unknown) + ledger.shed
            + ledger.abandoned + len(ledger.nonretryable)
            == ledger.offered), "autoscale ledger accounting broke"


async def verify_ledger(loop, db, ledger: AckedLedger) -> dict:
    """Read everything back at ONE snapshot and compute the exactly-once
    identity (chaos semantics): every acked key present, sum(counters)
    == markers present, every unknown resolved committed XOR absent."""
    deadline = loop.now + 60.0
    while True:
        tr = db.transaction()
        try:
            rows = await tr.get_range(b"\x00", b"\xfb", snapshot=True)
            break
        except FdbError as e:
            if loop.now > deadline:
                raise
            if isinstance(e, ProcessKilled):
                try:  # endpoints may be a generation stale post-scale
                    await db.refresh_client_info()
                except Exception:
                    pass
            await loop.sleep(0.5)
    got = dict(rows)
    lost = sorted(k.hex() for k, v in ledger.acked.items()
                  if got.get(k) != v)
    unknown_committed = sum(
        1 for k, v in ledger.unknown.items() if got.get(k) == v)
    unknown_absent = sum(1 for k in ledger.unknown if k not in got)
    unknown_mangled = (len(ledger.unknown) - unknown_committed
                       - unknown_absent)
    markers_present = sum(1 for k in got if k[1:].startswith(b"m/"))
    ctr_sum = sum(int(v) for k, v in got.items()
                  if k[1:].startswith(b"ctr/"))
    acked_marker_missing = [m.hex() for m in ledger.acked_markers
                            if m not in got]
    return {
        "offered": ledger.offered,
        "acked": len(ledger.acked),
        "unknown": len(ledger.unknown),
        "unknown_committed": unknown_committed,
        "unknown_absent": unknown_absent,
        "unknown_mangled": unknown_mangled,
        "shed": ledger.shed,
        "abandoned": ledger.abandoned,
        "conflict_retries": ledger.conflict_retries,
        "acked_lost_count": len(lost),
        "acked_lost": lost[:10],
        "counter_sum": ctr_sum,
        "markers_present": markers_present,
        "acked_marker_missing": acked_marker_missing[:10],
        "exactly_once_ok": (ctr_sum == markers_present
                            and not acked_marker_missing
                            and unknown_mangled == 0),
        "zero_acked_loss": not lost,
        "nonretryable_errors": ledger.nonretryable[:10],
    }


def _p99_ms(lat: list) -> "float | None":
    if not lat:
        return None
    s = sorted(lat)
    return round(s[min(len(s) - 1, int(0.99 * len(s)))] * 1000.0, 3)


# -- one arm ------------------------------------------------------------------


def run_arm(seed: int, profile: str, *, autoscale: bool, workdir: str,
            name: str, policy_kw: "dict | None" = None,
            n_proxies: int = 1, n_resolvers: int = 1,
            n_ctrs: int = 32, drain_s: float = 10.0,
            settle_s: float = 6.0) -> dict:
    """One seeded sim run of the schedule against one arm. Returns the
    arm record: ledger verification, goodput/p99, the applied scale
    events with staged timings, and the doctor's ring-side attribution
    of every event (autoscale arms)."""
    from foundationdb_tpu.client.ryw import open_database
    from foundationdb_tpu.obs.doctor import scale_relief
    from foundationdb_tpu.obs.recorder import FlightRecorder
    from foundationdb_tpu.obs.registry import (
        AUTOSCALE_DOCUMENTED_COUNTERS,
        scrape_sim,
    )
    from foundationdb_tpu.sim.cluster import SimCluster

    ring = os.path.join(workdir, f"ring_{name}.jsonl")
    if os.path.exists(ring):
        os.unlink(ring)
    c = SimCluster(seed=seed, n_proxies=n_proxies, n_resolvers=n_resolvers,
                   n_tlogs=2, n_storages=2, ratekeeper=True,
                   recorder_path=ring, recorder_interval_s=1.0,
                   **OVERLOAD_KNOBS)
    db = open_database(c)
    scaler: "Autoscaler | None" = None
    if autoscale:
        scaler = arm_autoscaler(c, policy=AutoscalePolicy(**(policy_kw or {})))
    ledger = AckedLedger()
    lat: list[float] = []
    segments = parse_profile(profile)
    schedule = trace_schedule(segments, seed=seed)
    duration = sum(d for d, _r in segments)

    async def main() -> dict:
        await drive_ledger(c.loop, db, ledger, schedule, lat,
                           n_ctrs=n_ctrs, drain_s=drain_s)
        ctrl = c.controller
        deadline = c.loop.now + 60.0
        while ctrl._recovering and c.loop.now < deadline:
            await c.loop.sleep(0.2)
        # Post-drain settle: the autoscaler's relief watcher needs a few
        # calm scrapes to stamp relief on the last event.
        await c.loop.sleep(settle_s)
        out = await verify_ledger(c.loop, db, ledger)
        reg = await scrape_sim(c)
        extra = AUTOSCALE_DOCUMENTED_COUNTERS if autoscale else ()
        out["scrape"] = {
            "audit_problems": reg.audit()[:10],
            "missing_documented": reg.missing_documented(extra=extra),
        }
        out["final_epoch"] = ctrl.generation.epoch
        return out

    verify = c.loop.run(main(), timeout=900)
    wall = duration + drain_s + settle_s
    rec = {
        "name": name,
        "autoscale": autoscale,
        "profile": profile,
        "duration_s": duration,
        "fleet_initial": {"proxy": n_proxies, "resolver": n_resolvers},
        "fleet_final": {"proxy": c.n_proxies, "resolver": c.n_resolvers},
        "goodput_tps": round(len(ledger.acked) / wall, 2),
        "p99_ms": _p99_ms(lat),
        "p99_quotable": len(lat) >= 20,
        "ledger": verify,
        "ring_path": ring,
    }
    if scaler is not None:
        rec["scale_events"] = scaler.events
        rec["counters"] = scaler.metrics()
        records = FlightRecorder.load(ring)
        attributed = scale_relief(records)
        rec["doctor_scale_events"] = attributed
        rec["events_attributed"] = (
            attributed is not None
            and len(attributed) == len(scaler.events)
            and all(a["attributed"] for a in attributed))
    if c.flight_recorder is not None:
        c.flight_recorder.close()
    return rec


def hysteresis_bound(policy_kw: dict, duration_s: float,
                     poll_s: float = Autoscaler.POLL_S) -> int:
    """Worst-case scale-event count the hysteresis gates permit over
    ``duration_s``: one initial adaptation per direction, plus one full
    up+down cycle per cooldown+confirmation period — an oscillation-
    follower (one event per load period) sits far above this."""
    p = AutoscalePolicy(**policy_kw)
    cycle_s = (p.cooldown_up_s + p.cooldown_down_s
               + p.confirm_down * poll_s)
    return 1 + 2 * int(duration_s // cycle_s)


# -- the record ---------------------------------------------------------------


def run_autoscale_ab(seed: int = 20260807, fast: bool = False,
                     workdir: "str | None" = None) -> dict:
    workdir = workdir or tempfile.mkdtemp(prefix="autoscale_ab_")
    # Base sits under the single-resolver dispatch capacity at the
    # OVERLOAD_KNOBS; the crowd saturates it (windowed occupancy ~1.0)
    # and piles on conflict-retry amplification, which is where a fixed
    # fleet degrades in this sim — its adaptive batching absorbs raw
    # throughput elastically, so overload shows up as TAIL LATENCY, not
    # lost admission. The fast profile uses a gentler crowd that still
    # trips the scale-up signal (selfcheck-sized).
    crowd = 28.0 if fast else 80.0
    base = 8.0
    flash = (f"4:{base:g},8:{crowd:g},10:{base:g}" if fast
             else f"6:{base:g},12:{crowd:g},16:{base:g}")
    osc_period_on, osc_period_off = 2.0, 2.0
    osc_reps = 6 if fast else 8
    osc = ",".join(f"{osc_period_on:g}:{crowd:g},{osc_period_off:g}:{base:g}"
                   for _ in range(osc_reps))
    osc_duration = osc_reps * (osc_period_on + osc_period_off)
    policy_kw = {"max_fleet": {"proxy": 3, "resolver": 3}}

    arms = {
        "autoscale": run_arm(seed, flash, autoscale=True, workdir=workdir,
                             name="autoscale", policy_kw=policy_kw),
        "fixed": run_arm(seed, flash, autoscale=False, workdir=workdir,
                         name="fixed"),
    }
    oscillation_arm = run_arm(seed + 1, osc, autoscale=True,
                              workdir=workdir, name="oscillation",
                              policy_kw=policy_kw)
    # The bound covers the WHOLE observed window — the oscillating
    # schedule plus the drain/settle tail the autoscaler keeps running
    # through (a tail scale-down is still a scale event).
    bound = hysteresis_bound(policy_kw, osc_duration + 10.0 + 6.0)
    osc_events = len(oscillation_arm.get("scale_events") or [])
    auto = arms["autoscale"]
    events = auto.get("scale_events") or []

    gates = {
        "zero_acked_loss": all(
            a["ledger"]["zero_acked_loss"]
            for a in (*arms.values(), oscillation_arm)),
        "exactly_once": all(
            a["ledger"]["exactly_once_ok"]
            for a in (*arms.values(), oscillation_arm)),
        "scaled_up": any(e["direction"] == "up" and e["recruited"]
                         for e in events),
        "relief_recorded": bool(events) and all(
            e["time_to_relief"] is not None for e in events),
        "events_attributed": bool(auto.get("events_attributed"))
        and (osc_events == 0 or oscillation_arm.get("events_attributed")),
        "hysteresis_within_bound": osc_events <= bound,
        "scrape_clean": all(
            not a["ledger"]["scrape"]["audit_problems"]
            and not a["ledger"]["scrape"]["missing_documented"]
            for a in (*arms.values(), oscillation_arm)),
    }
    cores = (len(os.sched_getaffinity(0))
             if hasattr(os, "sched_getaffinity") else (os.cpu_count() or 1))
    return {
        "metric": "autoscale_ab",
        "seed": seed,
        "fast": fast,
        "schedule": {"flash_crowd": flash, "oscillating": osc,
                     "oscillation_period_s": osc_period_on + osc_period_off},
        "arms": arms,
        "oscillation": {
            "arm": oscillation_arm,
            "events_total": osc_events,
            "bound": bound,
            "within_bound": osc_events <= bound,
        },
        "scale_events": events,
        "gates": gates,
        "valid": all(gates.values()),
        "cpu_fallback": True,  # CPU sim twin: no device claim anywhere
        "p99_quotable": all(a["p99_quotable"] for a in arms.values()),
        "goodput_ratio": (
            round(auto["goodput_tps"] / arms["fixed"]["goodput_tps"], 3)
            if arms["fixed"]["goodput_tps"] else None),
        "p99_ratio": (
            round(auto["p99_ms"] / arms["fixed"]["p99_ms"], 3)
            if auto["p99_ms"] and arms["fixed"]["p99_ms"] else None),
        "single_core_caveat": (
            "goodput_ratio is reported, not gated: sim virtual time on "
            f"{cores} host cores says nothing about multi-core scaling "
            "(OPENLOOP_AB precedent; ROADMAP follow-up)"),
        "host": {"cores": cores},
        "workdir": workdir,
        "replay": ("env JAX_PLATFORMS=cpu python -m foundationdb_tpu."
                   f"autoscale --ab --seed {seed}"
                   + (" --fast" if fast else "")),
    }


def selfcheck(seed: int = 20260807) -> dict:
    """One-JSON-line selfcheck (tpuwatch-style): a fast flash-crowd run
    with the autoscaler armed must scale up, lose nothing, resolve every
    unknown exactly once, and have every event doctor-attributed."""
    workdir = tempfile.mkdtemp(prefix="autoscale_self_")
    a = run_arm(seed, "3:8,8:28,6:8", autoscale=True, workdir=workdir,
                name="selfcheck",
                policy_kw={"max_fleet": {"proxy": 3, "resolver": 3}})
    events = a.get("scale_events") or []
    problems: list[str] = []
    if not any(e["direction"] == "up" and e["recruited"] for e in events):
        problems.append("no scale-up recruited under the flash crowd")
    if not a["ledger"]["zero_acked_loss"]:
        problems.append(
            f"acked-commit loss: {a['ledger']['acked_lost_count']}")
    if not a["ledger"]["exactly_once_ok"]:
        problems.append("exactly-once identity violated")
    if events and not a.get("events_attributed"):
        problems.append("doctor could not attribute every scale event")
    if a["ledger"]["scrape"]["missing_documented"]:
        problems.append(
            f"documented counters missing: "
            f"{a['ledger']['scrape']['missing_documented']}")
    if a["ledger"]["scrape"]["audit_problems"]:
        problems.append(
            f"scrape audit: {a['ledger']['scrape']['audit_problems']}")
    return {
        "metric": "autoscale_selfcheck",
        "ok": not problems,
        "problems": problems[:10],
        "seed": seed,
        "events": [{k: e[k] for k in ("name", "role", "from_n", "to_n",
                                      "signal", "detect_s", "recruit_s",
                                      "relief_s", "time_to_relief",
                                      "relieved")}
                   for e in events],
        "fleet_final": a["fleet_final"],
        "acked": a["ledger"]["acked"],
        "unknown": a["ledger"]["unknown"],
        "replay": ("env JAX_PLATFORMS=cpu python -m foundationdb_tpu."
                   f"autoscale --seed {seed}"),
    }
