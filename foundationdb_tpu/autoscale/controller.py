"""Closed-loop elastic autoscaler: scrape → policy → recruit/retire.

``Autoscaler`` is the sim control loop: it scrapes the cluster through
the standard contract (obs/registry.scrape_sim — the identical
aggregated view the flight recorder rings), feeds the hysteresis policy
(policy.py), and applies confirmed decisions by mutating the cluster's
fleet targets (``SimCluster.n_proxies`` / ``n_resolvers``) and driving
a generation change through ``ClusterController.request_recovery`` —
scale-via-recovery, the same recruit path every failure heal takes, so
a resolver count change IS a scoped mesh reshard (the new generation
re-derives the resolver map) and proxy retirement naturally resets the
ratekeeper leases (each generation gets a fresh ratekeeper sharing the
same quota dict).

Every applied decision lands on the flight-recorder timeline as a
first-class ``AutoscaleRecruit``/``AutoscaleRetire`` annotation
(cls="autoscale") carrying the triggering signal, the fleet transition,
and the relief contract (`metric` + `clear_below`) the doctor's
``scale_relief`` attribution re-checks from ring snapshots. Each event
records the staged time-to-relief breakdown the AB gates on:

- ``detect_s``  — first over-threshold window → confirmed decision
  (the policy's consecutive-window confirmation cost);
- ``recruit_s`` — decision → generation change complete (epoch bumped,
  controller idle);
- ``relief_s``  — recruit complete → triggering signal reads clear in
  the scrape for ``RELIEF_CONFIRM`` consecutive windows (a freshly
  recruited generation starts with empty queues, so one quiet scrape
  right after the recovery proves nothing).

The deployed twin is ``deployed_scale``: against real processes the
fleet target moves via the PR 13 supervisor's ``configure`` RPC — the
controller recruits the role onto a spec process (spawn → recruit RPC →
ratekeeper lease share appears on the new proxy's first get_rates
poll), and retirement drains through ``Worker.stand_down`` /
``recruit_proxy``, which now release the outgoing GRV proxy's budget
lease explicitly (``Ratekeeper.release_lease``) instead of waiting out
the live-poller TTL.
"""

from __future__ import annotations

from foundationdb_tpu.autoscale.policy import AutoscalePolicy, ScaleDecision

#: consecutive cleared scrapes before a scale event counts as relieved.
RELIEF_CONFIRM = 2

#: generation-change wait bound per applied decision (sim seconds).
RECRUIT_DEADLINE_S = 60.0


class Autoscaler:
    """Sim-side closed loop. Construct with a running ``SimCluster``
    (attaches itself as ``cluster.autoscaler`` so scrape_sim exports the
    ``autoscale.*`` counters), then spawn ``run()`` on the cluster loop:

        scaler = Autoscaler(cluster)
        cluster.loop.spawn(scaler.run(), process="autoscaler",
                           name="autoscale.run")
    """

    POLL_S = 0.5

    def __init__(self, cluster, policy: "AutoscalePolicy | None" = None,
                 poll_s: "float | None" = None) -> None:
        self.cluster = cluster
        self.loop = cluster.loop
        self.policy = policy or AutoscalePolicy()
        self.poll_s = float(poll_s or self.POLL_S)
        self.events: list[dict] = []  # applied decisions, staged timings
        self._pending_relief: list[dict] = []
        self._relief_streak: dict[int, int] = {}  # id(event) -> streak
        cluster.autoscaler = self

    # -- scrape-contract surface ------------------------------------------

    def fleet(self) -> dict:
        return {"proxy": self.cluster.n_proxies,
                "resolver": self.cluster.n_resolvers}

    def metrics(self) -> dict:
        m = self.policy.metrics()
        m["autoscale_events_total"] = len(self.events)
        return m

    def _annotate(self, name: str, **details) -> None:
        rec = getattr(self.cluster, "flight_recorder", None)
        if rec is not None:
            rec.annotate(name, "autoscale", severity="warn", **details)

    # -- the loop ----------------------------------------------------------

    async def run(self) -> None:
        from foundationdb_tpu.obs.registry import scrape_sim

        while True:
            await self.loop.sleep(self.poll_s)
            ctrl = getattr(self.cluster, "controller", None)
            if ctrl is None or ctrl._recovering:
                continue  # never stack decisions on an in-flight recovery
            reg = await scrape_sim(self.cluster)
            agg = reg.aggregated()
            t = self.loop.now
            self._check_relief(t, agg)
            decision = self.policy.observe(t, agg, self.fleet())
            if decision is not None:
                await self._apply(decision, t)

    async def _apply(self, d: ScaleDecision, t_decide: float) -> None:
        ctrl = self.cluster.controller
        epoch0 = ctrl.generation.epoch
        if d.role == "proxy":
            self.cluster.n_proxies = d.to_n
        else:
            self.cluster.n_resolvers = d.to_n
        name = "AutoscaleRecruit" if d.direction == "up" else "AutoscaleRetire"
        self._annotate(
            name, role=d.role, from_n=d.from_n, to_n=d.to_n,
            signal=d.signal, value=round(d.value, 4),
            metric=d.metric or None, clear_below=d.clear_below,
            clear_above=d.clear_above,
        )
        await ctrl.request_recovery(
            epoch0, f"autoscale {d.direction}: {d.role} {d.from_n}->"
                    f"{d.to_n} on {d.signal}={d.value:.1f}")
        deadline = self.loop.now + RECRUIT_DEADLINE_S
        while ((ctrl.generation.epoch <= epoch0 or ctrl._recovering)
               and self.loop.now < deadline):
            await self.loop.sleep(0.1)
        t_done = self.loop.now
        ev = {
            "name": name,
            "role": d.role,
            "direction": d.direction,
            "from_n": d.from_n,
            "to_n": d.to_n,
            "signal": d.signal,
            "value": round(d.value, 4),
            "metric": d.metric or None,
            "clear_below": d.clear_below,
            "clear_above": d.clear_above,
            "epoch": ctrl.generation.epoch,
            "recruited": ctrl.generation.epoch > epoch0,
            "t_detect": round(d.t_detect, 3),
            "t_decide": round(t_decide, 3),
            "t_recruit_done": round(t_done, 3),
            "detect_s": round(t_decide - d.t_detect, 3),
            "recruit_s": round(t_done - t_decide, 3),
            "relief_s": None,
            "time_to_relief": None,
            "relieved": False if d.clear_below is not None else None,
        }
        self.events.append(ev)
        if d.clear_below is not None:
            self._pending_relief.append(ev)
        else:
            # Slack-triggered scale-down: no limiting signal to clear —
            # drain-complete (the generation change) IS the relief.
            ev["relief_s"] = 0.0
            ev["time_to_relief"] = round(t_done - d.t_detect, 3)

    def _check_relief(self, t: float, agg: dict) -> None:
        still: list[dict] = []
        for ev in self._pending_relief:
            v = agg.get(ev["metric"])
            cleared = (
                t >= ev["t_recruit_done"] + self.poll_s
                and v is not None
                and ((float(v) > ev["clear_below"]) if ev["clear_above"]
                     else (float(v) < ev["clear_below"]))
            )
            key = id(ev)
            streak = self._relief_streak.get(key, 0) + 1 if cleared else 0
            self._relief_streak[key] = streak
            if streak < RELIEF_CONFIRM:
                still.append(ev)
                continue
            del self._relief_streak[key]
            ev["relieved"] = True
            ev["relief_s"] = round(t - ev["t_recruit_done"], 3)
            ev["time_to_relief"] = round(t - ev["t_detect"], 3)
            self._annotate(
                "AutoscaleRelief", role=ev["role"], signal=ev["signal"],
                value=float(v), event_t=ev["t_decide"],
                relief_s=ev["relief_s"],
            )
        self._pending_relief = still


def arm(cluster, policy: "AutoscalePolicy | None" = None,
        poll_s: "float | None" = None) -> Autoscaler:
    """Attach an autoscaler to a SimCluster and spawn its control loop
    on a dedicated sim process (like the flight recorder: chaos against
    cluster roles must never take the control plane down with them)."""
    scaler = Autoscaler(cluster, policy=policy, poll_s=poll_s)
    cluster.loop.spawn(scaler.run(),
                       process=cluster.process_prefix + "autoscaler",
                       name="autoscale.run")
    return scaler


async def deployed_scale(controller_ep, role: str, to_n: int) -> dict:
    """Deployed actuator: move the fleet target for a chain role on a
    managed real-process cluster (loadgen/deploy.py supervisor). The
    controller's ``configure`` persists the desired count and drives the
    generation change that recruits/retires the role processes; retired
    GRV proxies release their ratekeeper lease explicitly on the way
    out (Worker._release_grv_lease), and resolver count changes reshard
    the mesh for the new generation."""
    if role not in ("proxy", "resolver", "tlog"):
        raise ValueError(f"cannot autoscale role {role!r}")
    return await controller_ep.configure({role: int(to_n)})
