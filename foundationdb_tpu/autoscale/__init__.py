"""Closed-loop elastic autoscaler: SLO-driven recruit/retire with
reshard-on-scale and hysteresis gates.

The subsystem watches the cluster through the standard metrics-scrape
contract (obs/registry) — ratekeeper limiting reason, resolver queue
depth, admission saturation, per-proxy GRV queue — and recruits or
retires commit proxies and resolvers live:

- **policy.py** — deterministic hysteresis policy: separated up/down
  thresholds, consecutive-window confirmation, per-role cooldowns,
  down-only-when-calm. Oscillating load with a period inside the
  cooldown provably cannot thrash (the AB pins the bound).
- **controller.py** — the control loops: the sim ``Autoscaler`` applies
  decisions via scale-via-recovery (resolver scale = scoped mesh
  reshard; proxy retire = ratekeeper lease reset), stamping every
  decision on the flight-recorder timeline with staged
  detect/recruit/relief timings; ``deployed_scale`` moves real-process
  fleets through the supervisor's ``configure`` RPC.
- **ab.py** — the gated A/B (``AUTOSCALE_AB.json``): zero acked-commit
  loss + exactly-once across every scale transition, per-event
  time-to-relief, doctor attribution, hysteresis bound.

``python -m foundationdb_tpu.autoscale`` runs the one-line selfcheck;
``--ab`` emits the full AB record.
"""

from foundationdb_tpu.autoscale.controller import (
    Autoscaler,
    arm,
    deployed_scale,
)
from foundationdb_tpu.autoscale.policy import (
    AutoscalePolicy,
    ScaleDecision,
    read_signals,
)

__all__ = [
    "Autoscaler",
    "AutoscalePolicy",
    "ScaleDecision",
    "arm",
    "deployed_scale",
    "read_signals",
]
