"""Admission-time early conflict detection (ROADMAP tentpole, ISSUE 9).

At Zipf-contention load the cluster pays full resolve + repair cost for
transactions that are provably doomed on arrival. This subsystem detects
them AT ADMISSION — a device-residentable recent-writes fingerprint
filter (filter.py) probed at GRV grant and commit-proxy batch formation
(policy.py) — and SHAPES the outcome instead of letting the abort storm
run: likely losers are co-scheduled into one serializing dispatch window
(wave commit reorders them instead of aborting), proven losers are
pre-aborted with the repair subsystem's score-scaled jittered backoff,
and filter saturation feeds the ratekeeper next to resolver_queue.

Knobs (README "Admission control"): FDB_TPU_ADMISSION (default 0),
FDB_TPU_ADMISSION_SHAPE_RISK, FDB_TPU_ADMISSION_PREABORT,
FDB_TPU_ADMISSION_BITS_LOG2 / _BANKS / _WINDOW.
"""

from foundationdb_tpu.admission.filter import (
    RecentWritesFilter,
    fingerprints,
    key_fingerprint,
    u64_cols_fingerprint,
)
from foundationdb_tpu.admission.policy import (
    AdmissionDecision,
    AdmissionPolicy,
    admission_env_default,
)

__all__ = [
    "AdmissionDecision",
    "AdmissionPolicy",
    "RecentWritesFilter",
    "admission_env_default",
    "fingerprints",
    "key_fingerprint",
    "u64_cols_fingerprint",
]
