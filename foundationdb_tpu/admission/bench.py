"""Admission A/B goodput harness: FDB_TPU_ADMISSION off vs on, same seed.

The acceptance harness of the admission subsystem (ISSUE 9): the SAME
Zipf-0.99 read-modify-write contention stream (sim/workloads.
ZipfRepairWorkload) runs on fresh deterministic sim clusters with
admission OFF and ON — same seed per pair, so the arms differ only in
the admission subsystem — under both canonical client loops:

- ``naive`` (Database.run full-restart retry): the abort-storm
  deployment shape the subsystem targets; this is the HEADLINE pair.
  Multiple seeds are run and the gate is the MEAN goodput ratio (the
  naive ladder's realization variance is the dominant noise source;
  per-seed ratios ride along, and every pair must individually favor
  admission-on for the record to be valid).
- ``repair`` (run_repairable partial re-execution): recorded alongside
  at the wave-commit A/B's proven scale — admission must COMPOSE with
  repair, not cannibalize it (pre-aborted txns degrade to the canonical
  conflict path past the streak ceiling, so the repair engine still gets
  its loser reports).

Serializability is enforced, not assumed, on BOTH sides of every pair:
the clusters resolve with the replay-checked brute-force oracle
(engine "oracle-replay" — every commit set is validated by sequential
replay, byte-for-byte) and the workload's RMW-sum invariant fails the
run if any committed increment was lost or duplicated. Shaping never
changes verdicts (only scheduling), so every non-shaped AND shaped txn
alike is oracle-verified through the same resolve path.

Attribution is exact per arm: CONFLICT verdicts (resolver counters),
shaped / pre-aborted / false-positive counts (admission policy counters
— ``shaped_committed`` is a shaped txn the engine then committed, the
measured false-positive), and the preabort honesty invariant
(``preaborted == len(preabort_log)``: every pre-abort carries its
confirming committed-write evidence).

Driven by ``python bench.py --admission-ab`` (scripts/admission_ab.sh →
ADMISSION_AB.json). Pure simulation: no TPU, no JAX device work.
"""

from __future__ import annotations

import hashlib


def _state_checksum(c, db) -> str:
    """FNV-style digest of the final key space — the byte-exact end state
    the oracle-replayed commit set produced (recorded per arm)."""

    async def dump(tr):
        return await tr.get_range(b"", b"\xff", limit=1_000_000)

    rows = c.loop.run(db.run(dump), timeout=300)
    h = hashlib.sha256()
    for k, v in rows:
        h.update(k)
        h.update(b"\x00")
        h.update(v)
        h.update(b"\x01")
    return h.hexdigest()[:16]


def _one(seed: int, repair: bool, admission: bool, n_keys: int,
         n_txns: int, n_clients: int, timeout: float) -> dict:
    from foundationdb_tpu.client.ryw import open_database
    from foundationdb_tpu.sim.cluster import SimCluster
    from foundationdb_tpu.sim.workloads import ZipfRepairWorkload, run_workload

    c = SimCluster(seed=seed, engine="oracle-replay", admission=admission)
    db = open_database(c)
    w = ZipfRepairWorkload(seed=seed, n_keys=n_keys, n_txns=n_txns,
                           n_clients=n_clients, repair=repair)
    metrics = c.loop.run(run_workload(c, db, w), timeout=timeout)
    entry = {
        "goodput_txns_per_sec": metrics.extra.get("goodput"),
        "elapsed_virtual_s": round(metrics.extra.get("elapsed", 0.0), 3),
        "committed": metrics.ops,
        "serializable": True,  # run_workload raised otherwise (replay oracle
        # + RMW-sum conservation: every committed increment byte-accounted)
        "conflicts": sum(r.txns_conflicted for r in c.resolvers),
        "state_checksum": _state_checksum(c, db),
    }
    if repair:
        entry["repair"] = metrics.extra.get("repair")
    else:
        entry["full_restarts"] = metrics.txns_retried
    if admission:
        pols = [p.admission for p in c.commit_proxies if p.admission]
        counters: dict = {}
        for pol in pols:
            for k, v in pol.counters.items():
                counters[k] = counters.get(k, 0) + v
        entry["admission"] = counters
        # Preabort honesty (the exact-attribution contract): every
        # pre-abort logged its confirming committed-write evidence, up to
        # the forensics log's cap (counters keep counting past it — a
        # capped log on a big run is not missing evidence).
        entry["preabort_evidence_complete"] = all(
            len(pol.preabort_log)
            == min(pol.counters["preaborted"], pol.PREABORT_LOG_CAP)
            for pol in pols
        )
        entry["filter"] = pols[0].filter.metrics() if pols else None
    return entry


def run_admission_ab(
    naive_seeds: tuple = (20260803, 20260804, 99),
    naive_cfg: dict | None = None,
    repair_seeds: tuple = (20260803, 20260804),
    repair_cfg: dict | None = None,
    min_ratio: float = 1.2,
    timeout: float = 6000.0,
) -> dict:
    naive_cfg = naive_cfg or {"n_keys": 10, "n_txns": 600, "n_clients": 24}
    repair_cfg = repair_cfg or {"n_keys": 12, "n_txns": 360, "n_clients": 24}
    result: dict = {
        "metric": "admission_ab",
        "flag": "FDB_TPU_ADMISSION",
        "unit": "committed txns / virtual s",
        "workload": {"theta": 0.99, "naive": dict(naive_cfg),
                     "repair": dict(repair_cfg)},
        "serializability": (
            "replay-checked oracle engine on BOTH sides of every pair "
            "(sim/oracle.ReplayCheckedOracle: every commit set validated "
            "by inline sequential replay, byte-for-byte) + RMW-sum "
            "conservation checked after each run"
        ),
        "min_ratio": min_ratio,
    }
    ok = True
    ratios = []
    pairs = []
    for seed in naive_seeds:
        off = _one(seed, False, False, timeout=timeout, **naive_cfg)
        on = _one(seed, False, True, timeout=timeout, **naive_cfg)
        denom = off["goodput_txns_per_sec"] or 1e-9
        ratio = round((on["goodput_txns_per_sec"] or 0.0) / denom, 3)
        ratios.append(ratio)
        ok = ok and ratio > 1.0 and on.get("preabort_evidence_complete", False)
        pairs.append({"seed": seed, "off": off, "on": on, "ratio": ratio})
    result["naive_pairs"] = pairs
    mean = round(sum(ratios) / max(1, len(ratios)), 3)
    result["value"] = mean
    result["naive_ratio_mean"] = mean
    result["naive_ratios"] = ratios
    ok = ok and mean >= min_ratio

    rpairs = []
    for seed in repair_seeds:
        try:
            off = _one(seed, True, False, timeout=timeout, **repair_cfg)
            on = _one(seed, True, True, timeout=timeout, **repair_cfg)
        except Exception as e:  # noqa: BLE001 — the repair loop's known
            # retry-limit wall at unlucky seeds predates this subsystem;
            # a failed secondary pair is recorded, never hidden, and
            # fails the record (gate on reproducible pairs only).
            rpairs.append({"seed": seed, "error": str(e)[:200]})
            ok = False
            continue
        denom = off["goodput_txns_per_sec"] or 1e-9
        ratio = round((on["goodput_txns_per_sec"] or 0.0) / denom, 3)
        ok = ok and ratio > 1.0
        rpairs.append({"seed": seed, "off": off, "on": on, "ratio": ratio})
    result["repair_pairs"] = rpairs
    result["repair_ratios"] = [p.get("ratio") for p in rpairs]

    # Honesty flags (bench record conventions; see scripts/wave_ab.sh):
    # CPU-only BY DESIGN — cpu_fallback marks an unintended fallback from
    # a claimed TPU run, which this is not; virtual-time goodput has no
    # wall-clock latency distribution, so no p99 is quotable.
    result["cpu_fallback"] = False
    result["p99_quotable"] = False
    result["p99_note"] = "virtual-time sim goodput; no wall-clock latencies"
    result["valid"] = ok
    return result
