"""Recent-writes fingerprint filter: the admission subsystem's memory.

A compact banked Bloom-style filter over uint64 key fingerprints — the
same u64 column encoding the resident dictionary's host mirror uses
(models/conflict_set._rows_to_u64), so the device-resident integration
(TPUConflictSet.attach_admission_filter) feeds it straight from the
endpoint u64 columns each dispatch already computed: no re-hash, no
re-pack, and with the jax backend the bit banks PERSIST in device memory
across dispatches — the update ships only the write-set fingerprints that
ride along with the dispatch anyway.

Aging is by VERSION WINDOW, not decay: the filter holds ``banks`` bit
banks, each covering a slice of the MVCC window (``window_versions /
banks`` commit versions). Writes are recorded into the current bank; when
the version stream advances past the bank's slice the oldest bank is
cleared and becomes current. A probe for a transaction at read version
``rv`` consults only banks whose recorded-version range can exceed
``rv`` — a hit means "some write newer than your snapshot probably
touched this key", which is exactly the admission-time likely-loser
signal (arXiv:2301.06181's wasted-work detection, moved before dispatch).

Two truth tiers, deliberately separate:

- The BLOOM banks answer fast and may false-positive — they drive
  SHAPING (advisory: a shaped txn still resolves normally, so a false
  positive costs one co-scheduling delay, never a wrong verdict) and the
  saturation signal the ratekeeper consumes.
- The EXACT SHADOW (``exact_shadow=True``) keeps per-bank dicts of real
  key bytes → last write version. PRE-ABORTS are only ever issued from a
  shadow confirmation (a recorded write at version > rv overlapping the
  txn's read set), so every pre-aborted transaction is a true conflict
  loser by construction — the honesty contract
  tests/test_admission.py asserts against the resolve oracle.

The resolver is the authoritative feeder (every accepted write set passes
through it); commit proxies ALSO self-feed from their own batches'
accepted writes (zero lag for single-proxy clusters) and pull cross-proxy
deltas from the resolvers (``Resolver.admission_delta``). Double-feeding
is harmless by design: recording (key, version) twice is idempotent for
both tiers.
"""

from __future__ import annotations

import os

import numpy as np

from foundationdb_tpu.runtime.sequencer import MVCC_WINDOW_VERSIONS

_HASH_C1 = np.uint64(0x9E3779B97F4A7C15)
_HASH_C2 = np.uint64(0xFF51AFD7ED558CCD)

#: Bounded delta log: a consumer further behind than this re-syncs from
#: the recent tail only (conservative: it misses OLDER entries, so it can
#: only under-detect, never wrongly pre-abort — exactness lives in the
#: shadow CONFIRMATION, not in feed completeness).
DELTA_LOG_CAP = 4096


def _env_int(name: str, default: int) -> int:
    """Loud env parsing (the repo's kernel-flag convention: an unusable
    value RAISES with what is accepted — a silent default would run the
    cluster with unintended filter geometry and report nothing)."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not a valid setting; expected an integer"
        ) from None


_FNV_OFFSET = np.uint64(0xCBF29CE484222325)
_FNV_PRIME = np.uint64(0x100000001B3)


def fingerprints(keys: list[bytes]) -> np.ndarray:
    """uint64 fingerprints of raw keys (FNV-1a + a splitmix finisher so
    Bloom index derivation sees well-mixed high bits). Vectorized ACROSS
    keys — one numpy pass per byte column, masked by key length, so the
    resolver/proxy feed and probe paths pay array ops, not a Python loop
    per byte (uint64 arithmetic wraps mod 2^64, exactly FNV's ring)."""
    n = len(keys)
    if not n:
        return np.zeros(0, np.uint64)
    lens = np.fromiter((len(k) for k in keys), np.int64, count=n)
    width = int(lens.max(initial=0))
    buf = np.zeros((n, max(width, 1)), np.uint8)
    for i, k in enumerate(keys):
        buf[i, : len(k)] = np.frombuffer(k, np.uint8)
    h = np.full(n, _FNV_OFFSET, np.uint64)
    for j in range(width):
        h = np.where(j < lens, (h ^ buf[:, j]) * _FNV_PRIME, h)
    h = h * _HASH_C1
    return h ^ (h >> np.uint64(33))


def key_fingerprint(key: bytes) -> np.uint64:
    return fingerprints([key])[0]


def u64_cols_fingerprint(cols: np.ndarray) -> np.ndarray:
    """Fingerprint [n, C] uint64 key columns (the resident mirror's
    encoding) into [n] uint64 — the same multiplicative mix the mirror's
    hash table uses, so the device path never touches key bytes."""
    cols = np.asarray(cols, np.uint64)
    h = cols[:, 0] * _HASH_C1
    for j in range(1, cols.shape[1]):
        h = (h ^ cols[:, j]) * _HASH_C2
    return h ^ (h >> np.uint64(33))


class _NumpyBanks:
    """Host backend: bool bit banks in numpy."""

    def __init__(self, banks: int, nbits: int):
        self.bits = np.zeros((banks, nbits), bool)

    def set(self, bank: int, idx: np.ndarray) -> None:
        self.bits[bank, idx] = True

    def clear(self, bank: int) -> None:
        self.bits[bank] = False

    def any_all_hashes(self, idx: np.ndarray, bank_mask: np.ndarray) -> np.ndarray:
        """[n, k] slot indices → [n] hit (all k bits set in SOME unmasked
        bank)."""
        hits = self.bits[:, idx].all(axis=2)  # [banks, n]
        return (hits & bank_mask[:, None]).any(axis=0)

    def fill(self, bank: int) -> float:
        return float(self.bits[bank].mean())

    def fill_max(self) -> float:
        return float(self.bits.mean(axis=1).max())


class _JaxBanks:
    """Device backend: the banks live as a jax device array across calls
    (device-resident state), with jitted scatter/gather entry points.

    Operand row counts are PADDED to powers of two with a valid mask —
    jax.jit specializes per shape, and the accepted-write count varies
    every dispatch, so unpadded operands would retrace + recompile on
    the hot resolve path (log₂ bucket count bounds the program count,
    the same discipline as the kernel's quantized window depths)."""

    def __init__(self, banks: int, nbits: int):
        import jax
        import jax.numpy as jnp

        self._jnp = jnp
        self.bits = jnp.zeros((banks, nbits), bool)

        @jax.jit
        def _set(bits, bank, idx, valid):
            # Scatter-max of booleans: padded (valid=False) rows write
            # False, which can never clear an existing bit.
            return bits.at[bank, idx].max(valid)

        @jax.jit
        def _clear(bits, bank):
            return bits.at[bank].set(False)

        @jax.jit
        def _probe(bits, idx, bank_mask):
            hits = bits[:, idx].all(axis=2)
            return (hits & bank_mask[:, None]).any(axis=0)

        self._set_fn, self._clear_fn, self._probe_fn = _set, _clear, _probe

    @staticmethod
    def _pow2(n: int) -> int:
        return 1 << max(n - 1, 0).bit_length()

    def set(self, bank: int, idx: np.ndarray) -> None:
        m = len(idx)
        size = self._pow2(m)
        pad = np.zeros(size, np.int64)
        pad[:m] = idx
        valid = np.zeros(size, bool)
        valid[:m] = True
        self.bits = self._set_fn(self.bits, bank, pad, valid)

    def clear(self, bank: int) -> None:
        self.bits = self._clear_fn(self.bits, bank)

    def any_all_hashes(self, idx: np.ndarray, bank_mask: np.ndarray) -> np.ndarray:
        n = idx.shape[0]
        size = self._pow2(n)
        pad = np.zeros((size, idx.shape[1]), np.int64)
        pad[:n] = idx
        return np.asarray(self._probe_fn(self.bits, pad, bank_mask))[:n]

    def fill(self, bank: int) -> float:
        return float(self._jnp.mean(self.bits[bank]))

    def fill_max(self) -> float:
        return float(self._jnp.max(self._jnp.mean(self.bits, axis=1)))


class RecentWritesFilter:
    """Banked recent-writes filter with version-window aging.

    ``backend``: "numpy" (host; the runtime roles' default — deterministic
    and dependency-free) or "jax" (device-resident banks + jitted
    update/probe; what TPUConflictSet attaches). Both backends are
    bit-identical in behavior — tests/test_admission.py asserts parity.
    """

    def __init__(
        self,
        bits_log2: int | None = None,
        banks: int | None = None,
        hashes: int = 2,
        window_versions: int | None = None,
        exact_shadow: bool = True,
        backend: str = "numpy",
    ):
        self.nbits = 1 << (bits_log2
                           or _env_int("FDB_TPU_ADMISSION_BITS_LOG2", 16))
        self.banks = banks or _env_int("FDB_TPU_ADMISSION_BANKS", 4)
        self.hashes = max(1, hashes)
        self.window_versions = (window_versions
                                or _env_int("FDB_TPU_ADMISSION_WINDOW",
                                            MVCC_WINDOW_VERSIONS))
        self.slice_versions = max(1, self.window_versions // self.banks)
        self.backend = backend
        self._bits = (_JaxBanks if backend == "jax"
                      else _NumpyBanks)(self.banks, self.nbits)
        self._cur = 0
        # Per-bank recorded-version bounds: [min, max] per bank, -1 = empty.
        self.bank_min = np.full(self.banks, -1, np.int64)
        self.bank_max = np.full(self.banks, -1, np.int64)
        self._cur_from = -1  # version at which the current bank opened
        # Exact shadow: per-bank dict of key bytes -> newest write version.
        self.exact_shadow = exact_shadow
        self._shadow: list[dict[bytes, int]] = [dict() for _ in range(self.banks)]
        # Delta log for cross-role feeding: (key, version) ring + seq.
        self._delta_log: list[tuple[bytes, int]] = []
        self.delta_seq = 0
        self.recorded = 0
        self.rotations = 0

    # -- aging ---------------------------------------------------------------

    def _rotate_to(self, version: int) -> None:
        if self._cur_from < 0:
            self._cur_from = version
            return
        while version - self._cur_from >= self.slice_versions:
            self._cur_from += self.slice_versions
            self._cur = (self._cur + 1) % self.banks
            self._bits.clear(self._cur)
            self.bank_min[self._cur] = -1
            self.bank_max[self._cur] = -1
            self._shadow[self._cur] = {}
            self.rotations += 1

    def advance(self, version: int) -> None:
        """Age banks forward without recording (GC-only dispatches)."""
        self._rotate_to(version)

    # -- recording -----------------------------------------------------------

    def _idx(self, fps: np.ndarray) -> np.ndarray:
        """[n] fingerprints → [n, hashes] slot indices (h1 + i·h2 style)."""
        fps = np.asarray(fps, np.uint64)
        h2 = (fps >> np.uint64(32)) | np.uint64(1)
        mult = np.arange(self.hashes, dtype=np.uint64)
        return ((fps[:, None] + mult[None, :] * h2[:, None])
                % np.uint64(self.nbits)).astype(np.int64)

    def record_u64(self, fps: np.ndarray, version: int) -> None:
        """Record write fingerprints at a commit version (Bloom tier only
        — the device path, where key bytes never exist host-side)."""
        fps = np.asarray(fps, np.uint64).reshape(-1)
        self._rotate_to(version)
        if not fps.size:
            return
        b = self._cur
        self._bits.set(b, self._idx(fps).reshape(-1))
        self.bank_min[b] = version if self.bank_min[b] < 0 else min(
            int(self.bank_min[b]), version)
        self.bank_max[b] = max(int(self.bank_max[b]), version)
        self.recorded += int(fps.size)

    def record(self, keys: list[bytes], version: int,
               log_delta: bool = True) -> None:
        """Record raw write keys at a commit version (both tiers + the
        delta log feeding downstream filters; ``log_delta=False`` for
        entries REPLAYED from a peer's delta — a consumer-side filter
        serves no deltas of its own, so re-logging them is pure churn)."""
        if not keys:
            self._rotate_to(version)
            return
        self.record_u64(fingerprints(keys), version)
        if self.exact_shadow:
            shadow = self._shadow[self._cur]
            for k in keys:
                prev = shadow.get(k)
                if prev is None or prev < version:
                    shadow[k] = version
        if not log_delta:
            return
        for k in keys:
            self._delta_log.append((bytes(k), version))
        self.delta_seq += len(keys)
        if len(self._delta_log) > DELTA_LOG_CAP:
            del self._delta_log[: len(self._delta_log) - DELTA_LOG_CAP]

    # -- cross-role delta feed ------------------------------------------------

    def delta_since(self, since_seq: int) -> tuple[int, list[tuple[bytes, int]]]:
        """Entries recorded after ``since_seq`` (bounded by the log cap —
        a laggard consumer misses only OLDER entries; see module note)."""
        behind = self.delta_seq - since_seq
        if behind <= 0:
            return self.delta_seq, []
        return self.delta_seq, list(self._delta_log[-min(behind,
                                                         len(self._delta_log)):])

    def apply_delta(self, entries: list[tuple[bytes, int]]) -> None:
        """Merge a peer's delta (idempotent: double-feeding is harmless).
        Entries arrive in feed order (version runs are contiguous), so
        each same-version run records in ONE vectorized call, and none of
        it re-enters this filter's own delta log."""
        i, n = 0, len(entries)
        while i < n:
            version = int(entries[i][1])
            j = i
            while j < n and int(entries[j][1]) == version:
                j += 1
            self.record([bytes(k) for k, _v in entries[i:j]], version,
                        log_delta=False)
            i = j

    # -- probing -------------------------------------------------------------

    def _bank_mask(self, read_version: int) -> np.ndarray:
        """Banks that can hold a write NEWER than the read version."""
        return self.bank_max > read_version

    def probe_u64(self, fps: np.ndarray, read_version: int) -> np.ndarray:
        """[n] fingerprints → [n] bool likely-newer-write hits."""
        fps = np.asarray(fps, np.uint64).reshape(-1)
        if not fps.size:
            return np.zeros(0, bool)
        mask = self._bank_mask(read_version)
        if not mask.any():
            return np.zeros(len(fps), bool)
        return self._bits.any_all_hashes(self._idx(fps), mask)

    def probe_keys(self, keys: list[bytes], read_version: int) -> np.ndarray:
        if not keys:
            return np.zeros(0, bool)
        return self.probe_u64(fingerprints(keys), read_version)

    def probe_exact(self, key: bytes, read_version: int) -> int | None:
        """Exact tier: the newest RECORDED write version for ``key`` that
        is strictly newer than ``read_version`` (None = no confirmation).
        Only meaningful with exact_shadow; this is the ONLY evidence a
        pre-abort may be issued on."""
        best = None
        for shadow in self._shadow:
            v = shadow.get(key)
            if v is not None and v > read_version and (best is None or v > best):
                best = v
        return best

    # -- signals -------------------------------------------------------------

    def saturation(self) -> float:
        """Worst fill fraction over ALL banks — the admission signal the
        ratekeeper reads next to resolver_queue (a saturated bank means
        the write rate is outrunning what the filter can discriminate:
        probes degrade toward all-hit, i.e. shape-everything). Max over
        banks, not the current bank: probes consult the OLDER banks too,
        so a freshly-rotated (empty) current bank must not blind the
        SAT_BLIND guard while saturated elder banks still answer."""
        return self._bits.fill_max()

    def metrics(self) -> dict:
        return {
            "backend": self.backend,
            "bits": self.nbits,
            "banks": self.banks,
            "recorded": self.recorded,
            "rotations": self.rotations,
            "saturation": round(self.saturation(), 4),
            "delta_seq": self.delta_seq,
            "shadow_entries": sum(len(s) for s in self._shadow),
        }
