"""Admission policy: turn recent-writes evidence into admit/shape/preabort.

Probed at two admission points (the subsystem's whole reason to exist —
detect doomed transactions BEFORE they burn a resolve dispatch and a
client backoff ladder):

- Commit-proxy batch formation (``CommitProxy.run``): every request's
  read set is probed against the proxy's RecentWritesFilter.

  * Exact-shadow confirmation of a newer overlapping write → the txn is a
    PROVEN loser (the recorded write is committed, inside the MVCC
    window, and newer than the txn's snapshot — resolving it can only
    return CONFLICT). It is pre-aborted on the spot with
    ``AdmissionPreAborted`` carrying the hot-range odds, and the client
    retries after the existing score-scaled jittered backoff (the repair
    subsystem's formula) instead of riding the resolve pipeline and the
    blind exponential ladder. This is what converts an abort storm into
    a paced queue.
  * Bloom-tier hit without exact confirmation → LIKELY loser: routed to
    the proxy's serializing shaped lane, where contenders are
    deliberately co-scheduled into ONE dispatch window (same commit
    version) so a wave-commit resolver reorders the survivable chains
    instead of aborting them, and the rest lose at most one window.
    Shaping is advisory — a false positive costs one co-scheduling
    delay, never a wrong verdict — and is ACCOUNTED: shaped txns that
    then commit are the measured false positives
    (``shaped_committed``, judged against the resolve engine's verdict).

- GRV grant (``GrvProxy``): no read set exists yet, so the GRV gate uses
  the cluster-wide signal instead — filter saturation (via the
  ratekeeper's rates poll) defers default/batch read-version grants when
  the filter says the write rate has outrun its discrimination.

System-priority traffic is NEVER shaped or pre-aborted (the lane
contract: recovery and system-keyspace txns outrank the storm); the
campaign gate asserts the counter stays zero.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from foundationdb_tpu.admission.filter import RecentWritesFilter


def admission_env_default() -> bool:
    """FDB_TPU_ADMISSION env default (validated through the kernel
    flags' shared env_choice — unknown values raise with the accepted
    list instead of silently picking a mode)."""
    from foundationdb_tpu.core.types import env_choice

    return env_choice("FDB_TPU_ADMISSION", "0", ("0", "1")) == "1"


def _env_float(name: str, default: float) -> float:
    """Loud env parsing (kernel-flag convention — see filter._env_int)."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not a valid setting; expected a number"
        ) from None


@dataclass(frozen=True)
class AdmissionDecision:
    action: str  # "admit" | "shape" | "preabort"
    risk: float  # fraction of probed read keys hitting the Bloom tier
    confirm_version: int | None = None  # exact-shadow proof (preabort only)
    wide: bool = False  # shape came from the wide-range sketch path


class AdmissionPolicy:
    #: Bloom-tier hit fraction at/above which a txn is shaped. One hot
    #: read among three (the Zipf RMW shape) must clear it: 1/3 ≥ 0.3.
    SHAPE_RISK = 0.3
    #: Filter saturation above which probes are no longer discriminating:
    #: shaping pauses (everything would shape) and the saturation signal
    #: alone carries the backpressure (ratekeeper + GRV deferral).
    SAT_BLIND = 0.98
    #: Widest read range still probed per-key: a point read's range is
    #: key..key+\\x00 (len+1); anything wider can't be enumerated into
    #: fingerprints and falls back to the hot-range sketch for shaping.
    POINT_SLOP = 2
    #: Hot-range sketch score at/above which a wide-range read shapes.
    SKETCH_SHAPE_SCORE = 8.0
    #: Consecutive pre-aborts (client-reported attempts) at/above which a
    #: txn is admitted REGARDLESS: the canonical conflict path (loser
    #: report → repair engine / retry ladder) takes over, so admission
    #: can never starve a persistent loser.
    PREABORT_CEILING = 3
    #: Evidence-log bound (forensics): counters keep counting past it.
    PREABORT_LOG_CAP = 4096
    #: Consecutive clean admits that end an "engaged" episode: the
    #: engage/release annotations (obs flight recorder) follow episodes,
    #: not per-txn decisions — without hysteresis a workload shaping one
    #: txn in fifty would flap an annotation per batch.
    RELEASE_CLEAN = 64

    def __init__(
        self,
        filter: RecentWritesFilter | None = None,
        hot_ranges=None,
        enabled: bool | None = None,
        shape_risk: float | None = None,
        preabort: bool | None = None,
    ):
        self.enabled = admission_env_default() if enabled is None else bool(
            enabled)
        self.filter = filter or RecentWritesFilter()
        self.hot_ranges = hot_ranges  # HotRangeSketch (may be None)
        self.shape_risk = (shape_risk if shape_risk is not None
                          else _env_float("FDB_TPU_ADMISSION_SHAPE_RISK",
                                          self.SHAPE_RISK))
        if preabort is None:
            from foundationdb_tpu.core.types import env_choice

            preabort = env_choice(
                "FDB_TPU_ADMISSION_PREABORT", "1", ("0", "1")) == "1"
        self.preabort_enabled = bool(preabort)
        self.counters = {
            "probes": 0,
            "admitted": 0,
            "shaped": 0,
            "preaborted": 0,
            "shaped_committed": 0,  # false positives, vs the engine verdict
            "shaped_conflicted": 0,  # true positives the filter caught
            "shaped_too_old": 0,  # expired snapshots (prove nothing)
            "system_bypass": 0,
            "system_shaped": 0,  # MUST stay 0 (campaign gate)
            "no_shape_rejects": 0,  # admission_no_shape option fired
            "wide_range_shaped": 0,  # sketch-driven (not per-key) shapes
            "saturation_blind": 0,  # probes skipped: filter saturated
            "preabort_ceiling": 0,  # admitted past the streak ceiling
            # Engage/release EPISODES (see RELEASE_CLEAN): the filter is
            # "engaged" from its first shape/pre-abort until RELEASE_CLEAN
            # consecutive clean admits. The flight recorder turns deltas
            # of these into admission_filter timeline annotations.
            "engage_events": 0,
            "release_events": 0,
        }
        self.engaged = False
        self._clean_streak = 0
        # Pre-abort evidence log for the honesty tests: every entry is the
        # (key, confirming write version, txn read version) triple that
        # justified a pre-abort; tests replay it against the oracle's
        # write history. Bounded at PREABORT_LOG_CAP (forensics, not
        # accounting — evidence checks must compare against the cap).
        self.preabort_log: list[tuple[bytes, int, int]] = []

    # -- engage/release episode (obs annotation surface) ----------------------

    def _note_intervention(self) -> None:
        """A shape or pre-abort happened: the episode engages (or stays
        engaged) and the clean streak resets."""
        self._clean_streak = 0
        if not self.engaged:
            self.engaged = True
            self.counters["engage_events"] += 1

    def _note_clean(self) -> None:
        """A clean admit: RELEASE_CLEAN of these in a row end the episode."""
        if not self.engaged:
            return
        self._clean_streak += 1
        if self._clean_streak >= self.RELEASE_CLEAN:
            self.engaged = False
            self._clean_streak = 0
            self.counters["release_events"] += 1

    # -- the decision ---------------------------------------------------------

    def _point_key(self, r) -> bytes | None:
        """The key of a point-like read range, None if too wide to probe."""
        begin, end = bytes(r.begin), bytes(r.end)
        if len(end) <= len(begin) + self.POINT_SLOP and end[: len(begin)] == begin:
            return begin
        return None

    def decide(self, read_ranges, read_version: int,
               priority: str = "default",
               attempts: int = 0) -> AdmissionDecision:
        if not self.enabled:
            return AdmissionDecision("admit", 0.0)
        if attempts >= self.PREABORT_CEILING:
            self.counters["preabort_ceiling"] += 1
            return AdmissionDecision("admit", 0.0)
        if priority == "system":
            # SYSTEM_IMMEDIATE bypasses admission wholesale (lane
            # contract); counted so the campaign gate can prove both that
            # system traffic flowed AND that none of it was shaped.
            self.counters["system_bypass"] += 1
            return AdmissionDecision("admit", 0.0)
        reads = [r for r in read_ranges if not r.empty]
        if not reads:
            # Blind writes conflict with nothing — always admit.
            self.counters["admitted"] += 1
            self._note_clean()
            return AdmissionDecision("admit", 0.0)
        self.counters["probes"] += 1
        keys, wide = [], []
        for r in reads:
            k = self._point_key(r)
            (keys if k is not None else wide).append(k if k is not None else r)
        # Exact tier first: one confirmed newer write = proven loser.
        if self.preabort_enabled:
            for k in keys:
                v = self.filter.probe_exact(k, read_version)
                if v is not None:
                    self.counters["preaborted"] += 1
                    if len(self.preabort_log) < self.PREABORT_LOG_CAP:
                        self.preabort_log.append((k, v, read_version))
                    self._note_intervention()
                    return AdmissionDecision("preabort", 1.0,
                                             confirm_version=v)
        # Bloom tier: likely losers shape (unless the filter is saturated
        # past discriminating — then probes are all-hit noise and the
        # saturation SIGNAL carries the load shedding instead).
        risk = 0.0
        if keys:
            sat = self.filter.saturation()
            if sat >= self.SAT_BLIND:
                self.counters["saturation_blind"] += 1
            else:
                hits = self.filter.probe_keys(keys, read_version)
                risk = float(hits.sum()) / len(keys)
                if risk >= self.shape_risk:
                    self.counters["shaped"] += 1
                    self._note_intervention()
                    return AdmissionDecision("shape", risk)
        if wide and self.hot_ranges is not None:
            score = max(
                (self.hot_ranges.score(bytes(r.begin), bytes(r.end))
                 for r in wide), default=0.0)
            if score >= self.SKETCH_SHAPE_SCORE:
                self.counters["shaped"] += 1
                self.counters["wide_range_shaped"] += 1
                self._note_intervention()
                return AdmissionDecision("shape", risk, wide=True)
        self.counters["admitted"] += 1
        self._note_clean()
        return AdmissionDecision("admit", risk)

    def reclassify_no_shape(self, decision: AdmissionDecision) -> None:
        """A shape decision the client's admission_no_shape option turned
        into a rejection: the txn never rode the lane, so the shape
        counters (including the wide-range detail) are reversed and the
        reject counted instead — "shaped" stays exactly the population
        the false-positive rate and campaign gates are computed over."""
        self.counters["shaped"] -= 1
        if decision.wide:
            self.counters["wide_range_shaped"] -= 1
        self.counters["no_shape_rejects"] += 1

    def recheck_preabort(self, read_ranges, read_version: int) -> int | None:
        """Exact-tier-only recheck for a SHAPED txn at its flush ride: a
        loss that became provable while it parked (a contender committed
        into its read set) pre-aborts now instead of burning the
        dispatch. Returns the confirming write version or None. Never
        consults the Bloom tier — a recheck must not re-shape (park
        forever) or act on unconfirmed evidence."""
        if not (self.enabled and self.preabort_enabled):
            return None
        for r in read_ranges:
            if r.empty:
                continue
            k = self._point_key(r)
            if k is None:
                continue
            v = self.filter.probe_exact(k, read_version)
            if v is not None:
                self.counters["preaborted"] += 1
                if len(self.preabort_log) < self.PREABORT_LOG_CAP:
                    self.preabort_log.append((k, v, read_version))
                self._note_intervention()
                return v
        return None

    # -- outcome accounting ---------------------------------------------------

    def note_shaped_outcome(self, verdict) -> None:
        """Called by the commit proxy when a SHAPED txn's verdict lands:
        a shaped txn that committed is a measured false positive (it
        would have committed without shaping too — shaping never changes
        verdicts, only scheduling), judged against the resolve engine.
        TOO_OLD is tallied apart: an expired snapshot proves nothing
        about the filter's call, so folding it into shaped_conflicted
        would inflate the quoted true-positive count."""
        from foundationdb_tpu.core.types import Verdict

        if verdict == Verdict.COMMITTED:
            self.counters["shaped_committed"] += 1
        elif verdict == Verdict.TOO_OLD:
            self.counters["shaped_too_old"] += 1
        else:
            self.counters["shaped_conflicted"] += 1

    def note_system_shaped(self) -> None:  # pragma: no cover - must not fire
        self.counters["system_shaped"] += 1

    # -- feeding --------------------------------------------------------------

    def feed_accepted(self, write_ranges, version: int) -> None:
        """Record an accepted txn's write set (begin keys; wide ranges
        degrade to their begin key — under-detection only, see filter)."""
        keys = [bytes(w.begin) for w in write_ranges if not w.empty]
        # log_delta=False: this is the CONSUMER side (a proxy's probe
        # filter) — only resolver filters serve admission_delta, so
        # logging here would be pure hot-path churn. Empty sets still
        # age the banks.
        self.filter.record(keys, version, log_delta=False)

    # -- signals --------------------------------------------------------------

    def saturation(self) -> float:
        return self.filter.saturation() if self.enabled else 0.0

    def metrics(self) -> dict:
        return {
            "enabled": self.enabled,
            **self.counters,
            "engaged": self.engaged,
            "saturation": round(self.saturation(), 4),
            "filter": self.filter.metrics(),
        }
