"""fdbbackup analogue: snapshot / restore / describe a deployed cluster.

Reference: the fdbbackup binary (fdbbackup/backup.actor.cpp) — the
operator tool around FileBackupAgent. This tool speaks to any cluster the
cli can reach (a spec JSON from scripts/start_cluster.sh) and uses the
same BackupContainer file form as the sim's continuous backup:

    python -m foundationdb_tpu.backup_tool snapshot \\
        --cluster /tmp/fdb_tpu_cluster/cluster.json --out /tmp/b.fdbk
    python -m foundationdb_tpu.backup_tool describe --in /tmp/b.fdbk
    python -m foundationdb_tpu.backup_tool restore \\
        --cluster ... --in /tmp/b.fdbk

`snapshot` is a CONSISTENT cut: every chunk is read at one read version
(reference: backup snapshots are consistent because the mutation log
covers the scan window — with no continuous log, pinning one version is
the equivalent guarantee). Chunked to stay under per-txn read budgets;
TransactionTooOld from outliving the MVCC window fails the run cleanly.
Continuous (mutation-log) backup is the sim BackupAgent's job
(runtime/backup.py) — operator-driven file backup is what this tool adds.
"""

from __future__ import annotations

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")  # tool never needs a TPU

from foundationdb_tpu.runtime.backup import (
    BackupContainer,
    RangeChunk,
    RestoreError,
    restore,
)


def _open(cluster_path: str):
    from foundationdb_tpu.cli import open_cluster

    return open_cluster(cluster_path)


def cmd_snapshot(args) -> int:
    loop, t, db = _open(args.cluster)
    begin = args.begin.encode() if args.begin else b""
    end = args.end.encode() if args.end else b"\xff"
    container = BackupContainer()

    async def run():
        tr = db.transaction()
        version = await tr.get_read_version()
        cursor = begin
        while cursor < end:
            tr = db.transaction()
            tr.set_read_version(version)  # one consistent cut
            rows = await tr.get_range(cursor, end, limit=args.chunk)
            nxt = (rows[-1][0] + b"\x00"
                   if rows and len(rows) == args.chunk else end)
            container.chunks.append(
                RangeChunk(cursor, nxt, version, list(rows))
            )
            cursor = nxt
        container.snapshot_complete = True
        container.log_covered = version
        return version

    try:
        version = loop.run(run(), timeout=args.timeout)
    finally:
        t.close()
    container.save(args.out)
    rows = sum(len(c.kvs) for c in container.chunks)
    print(f"snapshot complete: version={version} chunks={len(container.chunks)} "
          f"rows={rows} -> {args.out}")
    return 0


def cmd_describe(args) -> int:
    c = BackupContainer.load(args.infile)
    rows = sum(len(ch.kvs) for ch in c.chunks)
    print(f"chunks={len(c.chunks)} rows={rows} "
          f"log_entries={len(c.log)} log_covered={c.log_covered} "
          f"snapshot_complete={c.snapshot_complete} "
          f"restorable_version={c.restorable_version()}")
    return 0


def cmd_restore(args) -> int:
    container = BackupContainer.load(args.infile)
    if container.restorable_version() is None:
        print("container is not restorable", file=sys.stderr)
        return 1
    target = args.version  # None = latest restorable
    loop, t, db = _open(args.cluster)
    try:
        restored = loop.run(restore(db, container, target_version=target),
                            timeout=args.timeout)
    except RestoreError as e:
        print(f"restore failed: {e}", file=sys.stderr)
        return 1
    finally:
        t.close()
    print(f"restored to version {restored}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m foundationdb_tpu.backup_tool",
        description="Backup/restore a deployed cluster (fdbbackup analogue).",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("snapshot", help="consistent range snapshot to a file")
    s.add_argument("--cluster", required=True)
    s.add_argument("--out", required=True)
    s.add_argument("--begin", default="")
    s.add_argument("--end", default="")
    def positive(v):
        n = int(v)
        if n < 1:
            raise argparse.ArgumentTypeError("chunk must be >= 1")
        return n

    s.add_argument("--chunk", type=positive, default=1000,
                   help="rows per chunk transaction")
    s.add_argument("--timeout", type=float, default=600.0)
    s.set_defaults(fn=cmd_snapshot)

    s = sub.add_parser("describe", help="print a backup file's contents")
    s.add_argument("--in", dest="infile", required=True)
    s.set_defaults(fn=cmd_describe)

    s = sub.add_parser("restore", help="restore a backup file into a cluster")
    s.add_argument("--cluster", required=True)
    s.add_argument("--in", dest="infile", required=True)
    s.add_argument("--version", type=int, default=None,
                   help="point-in-time target (reference: fdbrestore "
                        "--version); default = latest restorable")
    s.add_argument("--timeout", type=float, default=600.0)
    s.set_defaults(fn=cmd_restore)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
