"""SocketCluster: spawn and SUPERVISE a real multi-process cluster over TCP.

One helper shared by the open-loop bench, the chaos harness
(loadgen/chaos.py), the fast-battery smoke tests and scripts. Builds a
cluster spec (N proxy processes — the horizontal scale-out axis — plus
sequencer/resolver/tlog/storage/ratekeeper, optionally a controller for
managed recruitment), boots one OS process per role instance
(`python -m foundationdb_tpu.server`), waits for every readiness line,
and tears down gracefully (admin shutdown RPC, SIGKILL only as a last
resort) with an explicit leak check: every process reaped, every
listening port released, no orphaned children.

Beyond boot/teardown, this is the chaos harness's ROLE-LEVEL SUPERVISOR
(the fdbmonitor analogue the nemesis catalog maps onto):

- per-role persistent data dirs (``data_dirs=True``): each process gets
  ``--data-dir <workdir>/data/<role><i>`` so a SIGKILLed role restarts
  from its on-disk state (tlog disk queue, storage sqlite) through the
  existing ``from_disk``/``begin_epoch``/``tlog_adopt`` handshake;
- ``kill_role`` (SIGKILL — real process death, no goodbye),
  ``pause_role``/``resume_role`` (SIGSTOP/SIGCONT — an alive-but-frozen
  process, the failure detector's hardest case), ``restart_role``
  (reboot the same role+index+data-dir, what fdbmonitor does);
- an interposing TCP relay per instance of ``relay_roles``
  (runtime/net.TcpRelay): the spec advertises the relay's port while the
  role binds a private one (server.py --bind), so ``partition_role`` can
  black-hole/cut/delay EVERY connection to the role — both directions,
  regardless of the victim's state — and ``heal_role`` undoes it.

Process stdout/stderr go to per-process log files in the work dir (never a
pipe: a chatty supervisor under overload would fill a 64 KiB pipe buffer
and deadlock the role behind its own logging). Every process starts in its
OWN session/process group, so the leak check can see (and the teardown can
reap) children a crashed role left behind — a port check alone is
vacuously green for a crashed process whose forked child kept running.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time

from dataclasses import dataclass, field

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _group_has_running(pgid: int) -> bool:
    """Does process group `pgid` contain any non-zombie member? (/proc
    scan; if /proc is unavailable, the killpg(0) answer the caller
    already has stands — i.e. report alive.)"""
    try:
        pids = [p for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        return True
    for pid in pids:
        try:
            with open(f"/proc/{pid}/stat", "rb") as f:
                stat = f.read()
        except OSError:
            continue
        # pid (comm) state ppid pgrp ... — comm may embed spaces/parens;
        # fields are unambiguous after the LAST ')'.
        fields = stat.rsplit(b")", 1)[-1].split()
        if len(fields) >= 3 and fields[0] != b"Z" \
                and int(fields[2]) == pgid:
            return True
    return False


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def build_spec(proxies: int = 2, tlogs: int = 1, storages: int = 1,
               resolvers: int = 1, ratekeeper: bool = True,
               engine: str = "cpu", extra: "dict | None" = None,
               managed: bool = False,
               ports: "list[int] | None" = None) -> dict:
    """A cluster spec dict with fresh localhost ports (server.py shape).
    ``managed=True`` adds a controller process — chain-role failures then
    heal with a generation change instead of needing a full bounce.
    ``ports``: pre-allocated port list (callers that need MORE ports —
    relay binds — must draw them all from one free_ports batch, or the
    kernel can hand a just-released spec port back as a bind port)."""
    n = (1 + resolvers + tlogs + storages + proxies
         + (1 if ratekeeper else 0) + (1 if managed else 0))
    ports = iter(ports if ports is not None else free_ports(n))
    spec = {
        "sequencer": [f"127.0.0.1:{next(ports)}"],
        "resolver": [f"127.0.0.1:{next(ports)}" for _ in range(resolvers)],
        "tlog": [f"127.0.0.1:{next(ports)}" for _ in range(tlogs)],
        "storage": [f"127.0.0.1:{next(ports)}" for _ in range(storages)],
        "proxy": [f"127.0.0.1:{next(ports)}" for _ in range(proxies)],
        "ratekeeper": ([f"127.0.0.1:{next(ports)}"] if ratekeeper else []),
        "engine": engine,
    }
    if managed:
        spec["controller"] = [f"127.0.0.1:{next(ports)}"]
    if extra:
        spec.update(extra)
    return spec


@dataclass
class _Proc:
    """One supervised role process."""

    name: str  # e.g. "tlog0"
    role: str
    index: int
    addr: tuple  # advertised (spec) address — the relay's, when relayed
    bind: "tuple | None"  # private bind address behind a relay, else None
    log_path: str
    data_dir: "str | None"
    popen: "subprocess.Popen | None" = None
    log_offset: int = 0  # readiness scan starts here (restart support)
    restarts: int = 0
    paused: bool = False
    # Process-group ids of RETIRED generations of this role (a restart
    # replaces popen; the killed generation's orphaned children live in
    # the OLD group — leak checks and teardown must keep chasing it).
    dead_pgids: list = field(default_factory=list)

    def alive(self) -> bool:
        return self.popen is not None and self.popen.poll() is None


class SocketCluster:
    """Context manager around one deployed cluster's OS processes."""

    BOOT_DEADLINE_S = 180.0
    READY_DEADLINE_S = 60.0  # per-process restart readiness

    def __init__(self, workdir: str, proxies: int = 2, tlogs: int = 1,
                 storages: int = 1, resolvers: int = 1,
                 ratekeeper: bool = True, engine: str = "cpu",
                 spec_extra: "dict | None" = None,
                 env: "dict | None" = None,
                 managed: bool = False,
                 data_dirs: bool = False,
                 relay_roles: tuple = ()):
        os.makedirs(workdir, exist_ok=True)
        self.workdir = workdir
        self.managed = managed
        self.data_dirs = data_dirs
        # ONE free_ports batch covers the spec AND the relayed roles'
        # private bind ports: separate allocations release the spec
        # ports before the bind ports are drawn, and the kernel may
        # hand one straight back (flaky EADDRINUSE at boot).
        counts = {"sequencer": 1, "resolver": resolvers, "tlog": tlogs,
                  "storage": storages, "proxy": proxies,
                  "ratekeeper": 1 if ratekeeper else 0,
                  "controller": 1 if managed else 0}
        n_spec = sum(counts.values())
        n_bind = sum(counts.get(r, 0) for r in relay_roles)
        ports = free_ports(n_spec + n_bind)
        self._bind_ports = iter(ports[n_spec:])
        self.spec = build_spec(proxies, tlogs, storages, resolvers,
                               ratekeeper, engine, spec_extra, managed,
                               ports=ports[:n_spec])
        self.spec_path = os.path.join(workdir, "cluster.json")
        with open(self.spec_path, "w") as f:
            json.dump(self.spec, f)
        self.env = dict(os.environ, JAX_PLATFORMS="cpu", **(env or {}))
        self.procs: list[_Proc] = []
        self.relays: dict[str, "object"] = {}  # name -> TcpRelay
        self._relay_roles = tuple(relay_roles)
        self._build_proc_table()

    def _build_proc_table(self) -> None:
        from foundationdb_tpu.server import ROLES, parse_addr
        from foundationdb_tpu.runtime.net import TcpRelay

        for role in ROLES:
            for i, addr_s in enumerate(self.spec.get(role) or []):
                name = f"{role}{i}"
                addr = parse_addr(addr_s)
                bind = None
                if role in self._relay_roles:
                    # The spec's (advertised) port belongs to the RELAY;
                    # the role binds a private port the relay forwards to
                    # (allocated in __init__'s single free_ports batch).
                    bind = ("127.0.0.1", next(self._bind_ports))
                    self.relays[name] = TcpRelay(bind, host=addr[0],
                                                 port=addr[1])
                data_dir = None
                if self.data_dirs:
                    data_dir = os.path.join(self.workdir, "data", name)
                    os.makedirs(data_dir, exist_ok=True)
                self.procs.append(_Proc(
                    name=name, role=role, index=i, addr=addr, bind=bind,
                    log_path=os.path.join(self.workdir, f"{name}.log"),
                    data_dir=data_dir,
                ))

    def _by_name(self, name: str) -> _Proc:
        for p in self.procs:
            if p.name == name:
                return p
        raise KeyError(f"no role process {name!r} in this cluster")

    def _argv(self, p: _Proc) -> list[str]:
        argv = [sys.executable, "-m", "foundationdb_tpu.server",
                "--cluster", self.spec_path, "--role", p.role,
                "--index", str(p.index)]
        if p.data_dir:
            argv += ["--data-dir", p.data_dir]
        if p.bind:
            argv += ["--bind", f"{p.bind[0]}:{p.bind[1]}"]
        return argv

    # -- lifecycle --------------------------------------------------------

    def _launch(self, p: _Proc) -> None:
        if p.popen is not None:
            # The replaced generation's process group may still hold
            # orphaned children — keep its pgid on the chase list.
            p.dead_pgids.append(p.popen.pid)
        # Append mode: restarts keep one log per role instance, and the
        # readiness scan (log_offset) never re-reads an old generation's
        # "ready" line as the new process's.
        p.log_offset = (os.path.getsize(p.log_path)
                        if os.path.exists(p.log_path) else 0)
        log_f = open(p.log_path, "ab")
        p.popen = subprocess.Popen(
            self._argv(p), cwd=REPO, env=self.env,
            stdout=log_f, stderr=subprocess.STDOUT,
            # Own session = own process group: the leak check can see a
            # crashed role's surviving children, teardown can reap them.
            start_new_session=True,
        )
        log_f.close()  # the child holds the fd
        p.paused = False

    def role_ready(self, name: str) -> bool:
        """Has this process printed its readiness line since (re)launch?"""
        p = self._by_name(name)
        if not p.alive():
            return False
        try:
            with open(p.log_path, "rb") as f:
                f.seek(p.log_offset)
                return b"ready" in f.read()
        except OSError:
            return False

    def wait_ready(self, name: str,
                   timeout_s: "float | None" = None) -> None:
        p = self._by_name(name)
        deadline = time.monotonic() + (timeout_s or self.READY_DEADLINE_S)
        while True:
            if self.role_ready(name):
                return
            if p.popen is not None and p.popen.poll() is not None:
                raise RuntimeError(
                    f"{name} exited rc={p.popen.returncode} during boot "
                    f"(see {p.log_path})")
            if time.monotonic() > deadline:
                raise RuntimeError(f"timed out waiting for {name} ready")
            time.sleep(0.05)

    def start(self) -> "SocketCluster":
        try:
            for p in self.procs:
                self._launch(p)
            t0 = time.monotonic()
            for p in self.procs:
                remaining = self.BOOT_DEADLINE_S - (time.monotonic() - t0)
                self.wait_ready(p.name, timeout_s=max(1.0, remaining))
        except BaseException:
            # A role that exits or stalls during boot must not leak the
            # already-launched rest of the cluster (or the relays'
            # listener threads): a `with SocketCluster(...)` caller
            # never reaches __exit__ when __enter__ raises.
            self.kill()
            raise
        return self

    # -- chaos supervisor surface (loadgen/chaos.py) ----------------------

    def kill_role(self, name: str, sig: int = signal.SIGKILL) -> float:
        """Real process death: send `sig` (default SIGKILL — no shutdown
        RPC, no flush, exactly what the OOM killer or a kernel panic
        delivers) to the ROLE process only — a real crash does not take
        the role's forked children with it, which is precisely what the
        crashed-process leak check exists to catch (teardown's group
        kill is the mop-up, not the fault model). Returns the wall stamp
        of the kill (chaos MTTR anchors detection latency on it)."""
        p = self._by_name(name)
        stamp = time.time()
        if p.alive():
            p.popen.send_signal(sig)
            if p.paused and sig != signal.SIGKILL:
                # A SIGSTOPped process queues SIGTERM and never acts on
                # it: without the SIGCONT the wait below blocks forever
                # (SIGKILL needs no help — the kernel reaps stopped
                # processes on it directly).
                p.popen.send_signal(signal.SIGCONT)
            if sig in (signal.SIGKILL, signal.SIGTERM):
                p.popen.wait()
                p.paused = False
        return stamp

    def pause_role(self, name: str) -> float:
        """SIGSTOP: the process stays alive but answers nothing — the
        failure detector's hardest case (no connection death, RPCs just
        hang; the controller's probe timeout is what notices)."""
        p = self._by_name(name)
        if p.alive():
            p.popen.send_signal(signal.SIGSTOP)
            p.paused = True
        return time.time()

    def resume_role(self, name: str) -> None:
        p = self._by_name(name)
        if p.alive() and p.paused:
            p.popen.send_signal(signal.SIGCONT)
        p.paused = False

    def restart_role(self, name: str, wait: bool = True,
                     timeout_s: "float | None" = None) -> None:
        """Reboot a (dead) role from its on-disk state — fdbmonitor's
        restart-on-exit. The new process recovers its disk queue
        (TLog.from_disk) and the controller folds it into the next
        generation via the begin_epoch/tlog_adopt handshake."""
        p = self._by_name(name)
        if p.alive():
            self.kill_role(name)
        p.restarts += 1
        self._launch(p)
        if wait:
            self.wait_ready(name, timeout_s)

    def partition_role(self, name: str, mode: str = "drop",
                       delay_s: float = 0.05) -> float:
        """Socket-level partition of one role via its interposing relay:
        `drop` black-holes (connections hang), `cut` resets them,
        `delay` clogs. Requires the role in `relay_roles`."""
        relay = self.relays.get(name)
        if relay is None:
            raise KeyError(
                f"{name} has no relay — boot the cluster with "
                f"relay_roles=({self._by_name(name).role!r},)")
        relay.set_mode(mode, delay_s=delay_s)
        return time.time()

    def heal_role(self, name: str) -> None:
        relay = self.relays.get(name)
        if relay is not None:
            relay.heal()

    def heal_all(self) -> None:
        for relay in self.relays.values():
            relay.heal()

    # -- leak checking ----------------------------------------------------

    def _port_open(self, addr: tuple) -> bool:
        s = socket.socket()
        s.settimeout(0.2)
        try:
            s.connect(addr)
            return True
        except OSError:
            return False
        finally:
            s.close()

    @staticmethod
    def _pgid_running(pgid: int) -> bool:
        """Any RUNNING process left in process group `pgid`? Catches
        orphaned children of a CRASHED role (e.g. a background prober
        the role forked) that a port check alone can never see. The
        killpg(0) probe comes FIRST — on hosts without /proc the
        fallback in _group_has_running assumes the group exists.
        Zombies don't count: in a container without a reaping init, a
        killed orphan lingers as a defunct table entry forever — it
        holds no ports, no CPU, and cannot be killed again, so flagging
        it would make every teardown red with nothing actionable."""
        try:
            os.killpg(pgid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            return True  # exists but not ours — still alive
        return _group_has_running(pgid)

    def _group_alive(self, p: _Proc) -> bool:
        return p.popen is not None and self._pgid_running(p.popen.pid)

    def leak_report(self, dead_only: bool = True) -> dict:
        """What a crashed or stopped cluster left behind: for every role
        process that is DEAD (or all, with dead_only=False), is its REAL
        port still accepting (an orphan holds it — for relayed roles the
        private bind port is checked, never the harness-owned relay,
        which would be vacuously 'bound'), and does its process group
        still have live members? The old check only ran inside a clean
        shutdown() and only connect-probed spec addresses, so a role
        that died before stop() — or died leaving children — passed
        vacuously (ISSUE 14 satellite)."""
        ports, orphans, checked = [], [], []
        for p in self.procs:
            # Retired generations' groups are chased regardless of the
            # CURRENT process's liveness: a killed-then-restarted role
            # is alive, its dead predecessor's orphans are not less
            # leaked for it. Groups observed fully dead are PRUNED — an
            # exited group can never regain members, and keeping the
            # pgid risks a later pid-wraparound collision (an unrelated
            # group misreported, or worse, group-killed at teardown).
            p.dead_pgids = [g for g in p.dead_pgids
                            if self._pgid_running(g)]
            if p.dead_pgids:
                orphans.append(p.name)
            if dead_only and p.alive():
                continue
            checked.append(p.name)
            real = p.bind or p.addr
            if self._port_open(real):
                ports.append({"name": p.name, "port": real[1]})
            if not p.alive() and self._group_alive(p) \
                    and p.name not in orphans:
                orphans.append(p.name)
        return {"checked": checked, "ports_still_bound": ports,
                "orphan_groups": orphans}

    # -- teardown ---------------------------------------------------------

    def shutdown(self, timeout_s: float = 15.0) -> dict:
        """Graceful stop: admin shutdown RPC to every live process, reap,
        then verify nothing leaked — all processes (and their process
        groups) exited, all REAL ports released, crashed roles included.
        Returns {"exit_codes": {...}, "killed": [...]}."""
        from foundationdb_tpu.runtime.net import NetTransport, RealLoop

        killed: list[str] = []
        live = [p for p in self.procs if p.alive()]
        if live:
            self.heal_all()  # partitioned roles must still hear shutdown
            for p in live:
                if p.paused:
                    self.resume_role(p.name)  # a stopped process can't exit
            loop = RealLoop()
            t = NetTransport(loop)
            for p in live:
                try:
                    loop.run_until(
                        t.endpoint(p.bind or p.addr, "admin").shutdown(),
                        timeout=5.0)
                except Exception:
                    pass  # dead/wedged: the SIGKILL pass below reaps it
            t.close()
        deadline = time.monotonic() + timeout_s
        for p in self.procs:
            if p.popen is None:
                continue
            try:
                p.popen.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                killed.append(p.name)
                try:
                    os.killpg(p.popen.pid, signal.SIGKILL)
                except ProcessLookupError:
                    p.popen.kill()
                p.popen.wait()
        codes = {p.name: p.popen.returncode for p in self.procs
                 if p.popen is not None}
        report = self.leak_report(dead_only=False)
        leaks = report["ports_still_bound"] + report["orphan_groups"]
        if leaks:
            # Keep the proc table: clearing it here would leave the
            # caller's mop-up kill() with nothing to reap — the exact
            # vacuous-teardown hole this check exists to close.
            raise RuntimeError(f"cluster leaked after shutdown: {report}")
        self._close_relays()
        self.procs = []
        return {"exit_codes": codes, "killed": killed}

    def kill(self) -> None:
        """Hard teardown (exception path): SIGKILL every process GROUP —
        orphaned children of crashed AND restarted-over roles included —
        and reap."""
        for p in self.procs:
            if p.popen is None:
                continue
            if p.paused:
                self.resume_role(p.name)
            # Dead-generation groups are re-probed before the kill so a
            # recycled pgid (pid wraparound) can't take out an
            # unrelated process group.
            chase = [g for g in p.dead_pgids if self._pgid_running(g)]
            for pgid in [p.popen.pid] + chase:
                try:
                    os.killpg(pgid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
            p.dead_pgids = []
            if p.popen.poll() is None:
                p.popen.kill()
        for p in self.procs:
            if p.popen is not None:
                p.popen.wait()
        self._close_relays()
        self.procs = []

    def _close_relays(self) -> None:
        for relay in self.relays.values():
            relay.close()
        self.relays = {}

    def __enter__(self) -> "SocketCluster":
        return self.start()

    def __exit__(self, exc_type, _exc, _tb) -> None:
        if exc_type is None:
            try:
                self.shutdown()
            except RuntimeError:
                # Leak detected (crashed role / orphan group): mop up —
                # shutdown kept the proc table for exactly this — then
                # still surface the leak to the caller.
                self.kill()
                raise
        else:
            self.kill()

    # -- client surfaces --------------------------------------------------

    def open_client(self):
        """(loop, transport, db) against this cluster — the Python client
        stack over real sockets (cli.open_cluster)."""
        from foundationdb_tpu.cli import open_cluster

        return open_cluster(self.spec_path)

    def ratekeeper_ep(self, t):
        """Ratekeeper endpoint on transport `t` (None when not deployed)."""
        from foundationdb_tpu.server import parse_addr

        rk = self.spec.get("ratekeeper") or []
        return t.endpoint(parse_addr(rk[0]), "ratekeeper") if rk else None

    def controller_ep(self, t):
        """Controller endpoint on transport `t` (None when unmanaged)."""
        from foundationdb_tpu.server import parse_addr

        cc = self.spec.get("controller") or []
        return t.endpoint(parse_addr(cc[0]), "controller") if cc else None

    def admin_ep(self, t, name: str):
        """Admin endpoint of one role process (inject_fault/clear_faults/
        obs_snapshot), via its REAL address — reachable even when the
        role's relay is partitioned."""
        p = self._by_name(name)
        return t.endpoint(p.bind or p.addr, "admin")
