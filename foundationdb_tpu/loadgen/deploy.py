"""SocketCluster: spawn a real multi-process cluster over TCP.

One helper shared by the open-loop bench, the fast-battery smoke test and
scripts: builds a cluster spec (N proxy processes — the horizontal
scale-out axis — plus sequencer/resolver/tlog/storage/ratekeeper), boots
one OS process per role instance (`python -m foundationdb_tpu.server`),
waits for every readiness line, and tears down gracefully (admin shutdown
RPC, SIGKILL only as a last resort) with an explicit leak check: every
process reaped, every listening port released.

Process stdout/stderr go to per-process log files in the work dir (never a
pipe: a chatty supervisor under overload would fill a 64 KiB pipe buffer
and deadlock the role behind its own logging).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def free_ports(n: int) -> list[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def build_spec(proxies: int = 2, tlogs: int = 1, storages: int = 1,
               resolvers: int = 1, ratekeeper: bool = True,
               engine: str = "cpu", extra: "dict | None" = None) -> dict:
    """A cluster spec dict with fresh localhost ports (server.py shape)."""
    n = 1 + resolvers + tlogs + storages + proxies + (1 if ratekeeper else 0)
    ports = iter(free_ports(n))
    spec = {
        "sequencer": [f"127.0.0.1:{next(ports)}"],
        "resolver": [f"127.0.0.1:{next(ports)}" for _ in range(resolvers)],
        "tlog": [f"127.0.0.1:{next(ports)}" for _ in range(tlogs)],
        "storage": [f"127.0.0.1:{next(ports)}" for _ in range(storages)],
        "proxy": [f"127.0.0.1:{next(ports)}" for _ in range(proxies)],
        "ratekeeper": ([f"127.0.0.1:{next(ports)}"] if ratekeeper else []),
        "engine": engine,
    }
    if extra:
        spec.update(extra)
    return spec


class SocketCluster:
    """Context manager around one deployed cluster's OS processes."""

    BOOT_DEADLINE_S = 180.0

    def __init__(self, workdir: str, proxies: int = 2, tlogs: int = 1,
                 storages: int = 1, resolvers: int = 1,
                 ratekeeper: bool = True, engine: str = "cpu",
                 spec_extra: "dict | None" = None,
                 env: "dict | None" = None):
        os.makedirs(workdir, exist_ok=True)
        self.workdir = workdir
        self.spec = build_spec(proxies, tlogs, storages, resolvers,
                               ratekeeper, engine, spec_extra)
        self.spec_path = os.path.join(workdir, "cluster.json")
        with open(self.spec_path, "w") as f:
            json.dump(self.spec, f)
        self.env = dict(os.environ, JAX_PLATFORMS="cpu", **(env or {}))
        self.procs: list[tuple[str, tuple[str, int], subprocess.Popen]] = []
        self.logs: list[str] = []

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "SocketCluster":
        from foundationdb_tpu.server import ROLES, parse_addr

        for role in ROLES:
            for i, addr in enumerate(self.spec.get(role) or []):
                log_path = os.path.join(self.workdir, f"{role}{i}.log")
                self.logs.append(log_path)
                log_f = open(log_path, "w")
                p = subprocess.Popen(
                    [sys.executable, "-m", "foundationdb_tpu.server",
                     "--cluster", self.spec_path, "--role", role,
                     "--index", str(i)],
                    cwd=REPO, env=self.env,
                    stdout=log_f, stderr=subprocess.STDOUT,
                )
                log_f.close()  # the child holds the fd
                self.procs.append((f"{role}{i}", parse_addr(addr), p))
        deadline = time.monotonic() + self.BOOT_DEADLINE_S
        for (name, _addr, p), log_path in zip(self.procs, self.logs):
            while True:
                try:
                    with open(log_path) as f:
                        if "ready" in f.read():
                            break
                except OSError:
                    pass
                if p.poll() is not None:
                    raise RuntimeError(
                        f"{name} exited rc={p.returncode} during boot "
                        f"(see {log_path})")
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"cluster boot timed out waiting for {name}")
                time.sleep(0.05)
        return self

    def shutdown(self, timeout_s: float = 15.0) -> dict:
        """Graceful stop: admin shutdown RPC to every process, reap, then
        verify nothing leaked (all processes exited, all ports released).
        Returns {"exit_codes": {...}, "killed": [...]}."""
        from foundationdb_tpu.runtime.net import NetTransport, RealLoop

        killed: list[str] = []
        if self.procs:
            loop = RealLoop()
            t = NetTransport(loop)
            for name, addr, p in self.procs:
                if p.poll() is not None:
                    continue
                try:
                    loop.run_until(
                        t.endpoint(addr, "admin").shutdown(), timeout=5.0)
                except Exception:
                    pass  # dead/wedged: the SIGKILL pass below reaps it
            t.close()
        deadline = time.monotonic() + timeout_s
        for name, _addr, p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                killed.append(name)
                p.kill()
                p.wait()
        codes = {name: p.returncode for name, _a, p in self.procs}
        leaked = self._listening_ports()
        self.procs = []
        if leaked:
            raise RuntimeError(f"cluster ports still listening: {leaked}")
        return {"exit_codes": codes, "killed": killed}

    def _listening_ports(self) -> list[int]:
        out = []
        for _name, (host, port), _p in self.procs:
            s = socket.socket()
            s.settimeout(0.2)
            try:
                s.connect((host, port))
                out.append(port)
            except OSError:
                pass
            finally:
                s.close()
        return out

    def kill(self) -> None:
        for _name, _addr, p in self.procs:
            if p.poll() is None:
                p.kill()
        for _name, _addr, p in self.procs:
            p.wait()
        self.procs = []

    def __enter__(self) -> "SocketCluster":
        return self.start()

    def __exit__(self, exc_type, _exc, _tb) -> None:
        if exc_type is None:
            self.shutdown()
        else:
            self.kill()

    # -- client surfaces --------------------------------------------------

    def open_client(self):
        """(loop, transport, db) against this cluster — the Python client
        stack over real sockets (cli.open_cluster)."""
        from foundationdb_tpu.cli import open_cluster

        return open_cluster(self.spec_path)

    def ratekeeper_ep(self, t):
        """Ratekeeper endpoint on transport `t` (None when not deployed)."""
        from foundationdb_tpu.server import parse_addr

        rk = self.spec.get("ratekeeper") or []
        return t.endpoint(parse_addr(rk[0]), "ratekeeper") if rk else None
