"""One open-loop generator process: drive a deployed cluster, print JSON.

    python -m foundationdb_tpu.loadgen --cluster cluster.json \
        --rate 800 --duration 10 --clients 512 --seed 7

Several of these run side by side against the same cluster (each is one
OS process with its own RealLoop and sockets — the generator scales
horizontally exactly like the clients it simulates); bench.py --open-loop
merges their JSON lines (OpenLoopResult.merge_dicts). `--start-at` is an
epoch timestamp every generator sleeps until, so schedules across
processes share one t0; a generator that boots late fast-forwards through
its missed arrivals (the CO-correct accounting charges the delay to those
arrivals' latencies rather than quietly re-anchoring the schedule).

The default transaction is a single-key blind write into a seed-disjoint
keyspace (`--keys` distinct keys); `--reads N` prepends N point reads of
the same keyspace, making each txn a read-write conflict candidate.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from foundationdb_tpu.loadgen.arrivals import (
    parse_profile,
    poisson_schedule,
    trace_schedule,
)
from foundationdb_tpu.loadgen.harness import run_open_loop


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m foundationdb_tpu.loadgen")
    ap.add_argument("--cluster", required=True, help="cluster spec JSON")
    ap.add_argument("--rate", type=float, default=200.0,
                    help="offered load, txns/sec (Poisson)")
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--profile", default=None,
                    help="trace-shaped load 'dur:rate,dur:rate,...' "
                         "(overrides --rate/--duration)")
    ap.add_argument("--points", default=None,
                    help="rate LADDER 'dur:rate,dur:rate,...': run each "
                         "point as a SEPARATE Poisson run (own keyspace, "
                         "own JSON line with per-point accounting), "
                         "--point-gap-s apart. Cross-process sync: every "
                         "generator derives each point's start from "
                         "--start-at + the shared durations. This is how "
                         "bench.py sweeps offered load without paying a "
                         "process boot per point.")
    ap.add_argument("--point-gap-s", type=float, default=4.0,
                    help="settle/drain gap between ladder points")
    ap.add_argument("--clients", type=int, default=256,
                    help="virtual client slots (per-client concurrency 1)")
    ap.add_argument("--client-queue-cap", type=int, default=64)
    ap.add_argument("--max-inflight", type=int, default=4096)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--keys", type=int, default=4096,
                    help="distinct keys per generator (seed-disjoint)")
    ap.add_argument("--reads", type=int, default=0,
                    help="point reads per txn before the write")
    ap.add_argument("--value-bytes", type=int, default=16)
    ap.add_argument("--timeout-ms", type=int, default=5000)
    ap.add_argument("--retry-limit", type=int, default=8)
    ap.add_argument("--drain-s", type=float, default=15.0)
    ap.add_argument("--start-at", type=float, default=None,
                    help="epoch seconds to anchor t0 (cross-process sync)")
    args = ap.parse_args(argv)

    from foundationdb_tpu.cli import open_cluster

    loop, t, db = open_cluster(args.cluster)
    from foundationdb_tpu.client.transaction import Transaction

    db.transaction_class = Transaction  # raw txns: RYW adds no load here

    from foundationdb_tpu.obs.span import SpanSink, obs_env_default

    if obs_env_default():
        # Commit-path tracing (FDB_TPU_OBS=1): sampled txns' per-stage
        # breakdown rides each run's JSON line as `obs` (mergeable
        # histograms; bench.py --open-loop merges across generators).
        SpanSink(loop)

    value = b"v" * max(1, args.value_bytes)
    n_keys, n_reads = args.keys, args.reads

    def make_txn_fn(prefix: bytes):
        async def txn_fn(tr, k: int) -> None:
            key = prefix + b"%d" % (k % n_keys)
            for r in range(n_reads):
                await tr.get(prefix + b"%d" % ((k + r + 1) % n_keys))
            tr.set(key, value)

        return txn_fn

    def wait_until(wall: "float | None") -> float:
        if wall is None:
            return 0.0
        lag = max(0.0, time.time() - wall)
        while time.time() < wall:
            time.sleep(min(0.05, wall - time.time()))
        return lag

    def one_run(schedule, txn_fn, drain_s: float):
        async def main_coro():
            return await run_open_loop(
                loop, db, schedule, txn_fn,
                n_clients=args.clients,
                client_queue_cap=args.client_queue_cap,
                max_inflight=args.max_inflight,
                timeout_ms=args.timeout_ms,
                retry_limit=args.retry_limit,
                drain_s=drain_s,
            )

        span = float(schedule[-1]) if schedule.size else 0.0
        return loop.run(main_coro(), timeout=span + drain_s + 120.0)

    if args.points:
        points = parse_profile(args.points)
        at = args.start_at if args.start_at is not None else time.time()
        for i, (dur, rate) in enumerate(points):
            start_lag = wait_until(at)
            at += dur + args.point_gap_s
            schedule = poisson_schedule(rate, dur,
                                        seed=args.seed + 7919 * i)
            res = one_run(schedule,
                          make_txn_fn(b"ol/%d/%d/" % (args.seed, i)),
                          drain_s=max(1.0, args.point_gap_s - 1.0))
            rec = res.to_dict()
            rec.update(point=i, offered_tps=rate, duration_s=dur,
                       start_lag_s=round(start_lag, 3), seed=args.seed)
            print(json.dumps(rec), flush=True)
        t.close()
        return 0

    if args.profile:
        schedule = trace_schedule(parse_profile(args.profile),
                                  seed=args.seed)
    else:
        schedule = poisson_schedule(args.rate, args.duration,
                                    seed=args.seed)
    start_lag = wait_until(args.start_at)
    res = one_run(schedule, make_txn_fn(b"ol/%d/" % args.seed),
                  drain_s=args.drain_s)
    t.close()
    rec = res.to_dict()
    rec["start_lag_s"] = round(start_lag, 3)
    rec["seed"] = args.seed
    print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
