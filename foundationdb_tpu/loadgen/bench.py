"""bench.py --open-loop: the published open-loop scale-out record.

Produces ONE JSON record (metric ``open_loop_scaleout``) holding the two
curves the ROADMAP item names, measured against a REAL multi-process
cluster over TCP sockets (loadgen.deploy.SocketCluster), driven by
out-of-process open-loop generators (python -m foundationdb_tpu.loadgen)
whose latencies are coordinated-omission correct (measured from scheduled
arrival — harness.py):

1. ``scaling_curve`` — sustainable txns/s vs proxy-process count: for each
   count, a past-saturation capacity probe then a rate ladder; the
   sustainable point is the highest offered load the cluster completes
   (>= SUSTAIN_FRAC) at bounded CO-corrected p99.
2. ``latency_curve`` — CO-corrected p99 commit latency vs offered load on
   the largest proxy count, through and PAST saturation (the region
   closed-loop harnesses structurally cannot see).

Plus the ``overload`` run: offered load far past capacity on a cluster
whose resolver models real dispatch cost, while the ratekeeper is polled
from the side — the record shows its clamps engaging
(``resolver_queue``/``admission_filter`` limiting reasons, the signals
built for exactly this), shed/timed-out load counted explicitly, and the
cluster recovering (limiting reason back to ``none``, bounded p99) once
offered load drops.

Honesty flags ride along as established: ``valid`` gates on the full
acceptance (both curves, scaling at bounded p99, overload engage+recover),
``cpu_fallback`` is false because no TPU run is attempted or claimed (the
resolve engine is the C++ skiplist — this record is about the network
stack and the control plane, and says so in ``engine``), ``p99_quotable``
carries the sample-count rule, and every latency record is marked
``co_corrected``. A single-core host is recorded (``host.cores``) and
fails ``valid`` with its own reason: N proxy processes on one core cannot
add CPU, so a flat curve there is the host's fault, not evidence about
the architecture — exactly the cpu_fallback precedent.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

from foundationdb_tpu.loadgen.deploy import REPO, SocketCluster
from foundationdb_tpu.loadgen.harness import OpenLoopResult

#: a point "sustains" its offered load when this fraction completes...
SUSTAIN_FRAC = 0.92
#: ...at a CO-corrected p99 at or under this bound (ms).
P99_BOUND_MS = 750.0
MIN_SCALING = 1.15  # sustainable-tps ratio best-proxy-count / 1-proxy
#: Fallback quotability rule for library callers; `bench.py --open-loop`
#: injects the authoritative bench.annotate_latency instead (run_
#: open_loop_bench's `annotate`), so the 32-sample rule is not forked.
MIN_LATENCY_SAMPLES = 32


def _stamp_latency(rec: dict, n_samples: int, annotate) -> dict:
    if annotate is not None:
        return annotate(rec, n_samples, co_corrected=True)
    rec["latency_samples"] = int(n_samples)
    rec["co_corrected"] = True
    rec["p99_quotable"] = n_samples >= MIN_LATENCY_SAMPLES
    return rec

#: overload-cluster resolver knobs: model 50ms of engine time per batch —
#: a ~20 batches/s service ceiling, far below the batch-formation rate
#: the commit proxies reach under load (they pipeline a batch per 2ms
#: tick; even CPU-starved they form well over 20/s), so offered load
#: past the ceiling parks batches in the resolver dispatch queue and the
#: ratekeeper's resolver_queue signal engages the way it was designed
#: to. The ceiling must sit BELOW what the host lets proxies form —
#: otherwise the pipeline self-clocks through CPU scheduling and the
#: queue never materializes (single-core find). The recovery rate is
#: chosen below the ceiling even in the sparse one-txn-per-batch
#: regime, so the clamp provably releases.
OVERLOAD_SPEC = {"resolver_budget_s": 0.05, "resolver_dispatch_cost_s": 0.05}


def _log(msg: str) -> None:
    print(f"[openloop] {msg}", file=sys.stderr, flush=True)


def _run_generators(spec_path: str, workdir: str, points, generators: int,
                    clients: int, seed: int, keys: int, gap_s: float,
                    timeout_ms: int, lead_s: float = 6.0,
                    rk_poll=None,
                    annotate=None,
                    env: "dict | None" = None) -> "tuple[list[dict], list[dict]]":
    """Run `generators` loadgen processes through the shared rate ladder
    `points` = [(dur_s, total_rate), ...]; returns (per-point merged
    records, ratekeeper samples). Each generator offers rate/generators
    on its own seed-disjoint keyspace; per-point records merge by
    histogram/count sum (OpenLoopResult.merge_dicts)."""
    start_at = time.time() + lead_s
    procs = []
    for g in range(generators):
        err_path = os.path.join(workdir, f"loadgen{seed}_{g}.err")
        with open(err_path, "w") as err_f:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "foundationdb_tpu.loadgen",
                 "--cluster", spec_path,
                 "--points",
                 ",".join(f"{d}:{r / generators}" for d, r in points),
                 "--point-gap-s", str(gap_s),
                 "--clients", str(clients),
                 "--seed", str(seed + g),
                 "--keys", str(keys),
                 "--timeout-ms", str(timeout_ms),
                 "--start-at", str(start_at)],
                cwd=REPO,
                env=dict(os.environ, JAX_PLATFORMS="cpu", **(env or {})),
                stdout=subprocess.PIPE, stderr=err_f, text=True,
            ))
    budget = (lead_s + sum(d for d, _r in points)
              + gap_s * len(points) + 180.0)
    rk_samples = rk_poll(procs, budget) if rk_poll is not None else []
    outs = []
    deadline = time.monotonic() + budget
    for g, p in enumerate(procs):
        try:
            out, _ = p.communicate(
                timeout=max(1.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
            raise RuntimeError(
                f"loadgen generator {g} exceeded its budget "
                f"(see {workdir}/loadgen{seed}_{g}.err)")
        if p.returncode != 0:
            raise RuntimeError(
                f"loadgen generator {g} rc={p.returncode} "
                f"(see {workdir}/loadgen{seed}_{g}.err)")
        outs.append(out)
    merged = []
    for i, (dur, rate) in enumerate(points):
        recs = []
        for out in outs:
            for line in out.splitlines():
                r = json.loads(line)
                if r.get("point") == i:
                    recs.append(r)
        m = OpenLoopResult.merge_dicts(recs)
        m.update(point=i, offered_tps=rate, duration_s=dur,
                 start_lag_s=max(r.get("start_lag_s", 0.0) for r in recs))
        dumps = [r.get("obs") for r in recs if r.get("obs")]
        if dumps:
            # Per-stage commit-path breakdown (obs subsystem), merged by
            # histogram sum across generators — the record's answer to
            # WHERE this point's latency went, residue reported as
            # `unattributed`.
            from foundationdb_tpu.obs.span import SpanSink

            m["latency_breakdown"] = SpanSink.merge_dumps(dumps)
        # Quotability is judged on the histogram the p99 is READ from:
        # the CO histogram holds every non-shed arrival (committed +
        # timed_out + failed + abandoned), not just commits.
        _stamp_latency(m, m["offered"] - m["shed"], annotate)
        merged.append(m)
    return merged, rk_samples


def _sustained(point: dict, p99_bound_ms: float) -> bool:
    return (point["offered"] > 0
            and point["committed"] / point["offered"] >= SUSTAIN_FRAC
            and point["co_p99_ms"] <= p99_bound_ms)


def _rk_poller(cluster: SocketCluster, interval_s: float = 0.5):
    """A rk_poll callable for _run_generators: samples the deployed
    ratekeeper's get_rates (no poller id — observation must not join the
    budget-share lease) until every generator exits."""

    def poll(procs, budget: float) -> list[dict]:
        from foundationdb_tpu.runtime.net import NetTransport, RealLoop

        loop = RealLoop()
        t = NetTransport(loop)
        ep = cluster.ratekeeper_ep(t)
        samples: list[dict] = []
        t0 = time.monotonic()

        async def poller():
            while (any(p.poll() is None for p in procs)
                   and time.monotonic() - t0 < budget):
                try:
                    r = await ep.get_rates()
                    samples.append({
                        "t_s": round(time.monotonic() - t0, 2),
                        "limiting_reason": r["limiting_reason"],
                        "resolver_queue": r["worst_resolver_queue"],
                        "admission_saturation": round(
                            r.get("admission_saturation", 0.0), 3),
                        "tps_limit": round(r["tps_limit"], 1),
                        "grv_pollers": r.get("grv_pollers"),
                    })
                except Exception:
                    pass
                await loop.sleep(interval_s)

        try:
            loop.run(poller(), timeout=budget + 60.0)
        finally:
            t.close()
        return samples

    return poll


def _ladder_on_cluster(workdir: str, proxies: int, duration_s: float,
                       gap_s: float, generators: int, clients: int,
                       keys: int, seed: int, calib_rate: float,
                       p99_bound_ms: float, timeout_ms: int,
                       annotate=None, env: "dict | None" = None) -> dict:
    """Boot a cluster with `proxies` proxy processes, probe capacity at a
    past-saturation rate, then run a rate ladder around it. Returns the
    per-proxy-count record: every ladder point + the sustainable pick."""
    _log(f"cluster proxies={proxies}: booting")
    with SocketCluster(os.path.join(workdir, f"p{proxies}"),
                       proxies=proxies, env=env) as cluster:
        _log(f"cluster proxies={proxies}: capacity probe @ "
             f"{calib_rate:.0f} tps")
        calib, _ = _run_generators(
            cluster.spec_path, workdir, [(duration_s, calib_rate)],
            generators, clients, seed, keys, gap_s, timeout_ms,
            annotate=annotate, env=env)
        capacity = max(calib[0]["throughput_txns_per_sec"], 1.0)
        _log(f"cluster proxies={proxies}: probe completed "
             f"{capacity:.0f} tps (offered {calib_rate:.0f})")
        fracs = (0.5, 0.75, 0.95, 1.2, 1.6)
        ladder = [(duration_s, round(capacity * f, 1)) for f in fracs]
        points, _ = _run_generators(
            cluster.spec_path, workdir, ladder, generators, clients,
            seed + 100, keys, gap_s, timeout_ms, annotate=annotate,
            env=env)
    sustained = [p for p in points if _sustained(p, p99_bound_ms)]
    best = max(sustained, key=lambda p: p["offered_tps"], default=None)
    return {
        "proxies": proxies,
        "capacity_probe_tps": capacity,
        "capacity_probe_offered_tps": calib_rate,
        "sustainable_tps": best["offered_tps"] if best else 0.0,
        "sustainable_completed_tps": (
            best["throughput_txns_per_sec"] if best else 0.0),
        "p99_ms_at_sustainable": best["co_p99_ms"] if best else None,
        "p99_quotable": bool(best and best["p99_quotable"]),
        "points": points,
    }


def run_open_loop_bench(
    proxy_counts=(1, 2),
    duration_s: float = 4.0,
    gap_s: float = 4.0,
    generators: int = 1,
    clients: int = 512,
    keys: int = 4096,
    seed: int = 20260804,
    calib_rate: float = 2500.0,
    p99_bound_ms: float = P99_BOUND_MS,
    min_scaling: float = MIN_SCALING,
    timeout_ms: int = 5000,
    overload: bool = True,
    workdir: "str | None" = None,
    annotate=None,
) -> dict:
    proxy_counts = sorted(set(int(p) for p in proxy_counts))
    workdir = workdir or tempfile.mkdtemp(prefix="openloop_")
    # Arm commit-path tracing in the generator/cluster SUBPROCESSES at
    # the default 1-in-64 sampling (never by mutating this process's
    # environment): every ladder point's record then embeds the
    # per-stage latency breakdown (obs subsystem; the sampling-overhead
    # gate for this is OBS_AB.json). FDB_TPU_OBS=0 in the caller's env
    # still disables it end to end.
    obs_env = {"FDB_TPU_OBS": os.environ.get("FDB_TPU_OBS", "1")}
    cores = len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity") \
        else (os.cpu_count() or 1)
    rec: dict = {
        "metric": "open_loop_scaleout",
        "engine": "cpu-skiplist resolve over real TCP (no TPU claimed)",
        "arrivals": "poisson (open loop)",
        "co_corrected": True,
        "cpu_fallback": False,
        "host": {"cores": cores, "loadavg_1m": round(os.getloadavg()[0], 2)},
        "generators": generators,
        "clients_per_generator": clients,
        "duration_s_per_point": duration_s,
        "p99_bound_ms": p99_bound_ms,
        "sustain_frac": SUSTAIN_FRAC,
        "workdir": workdir,
    }
    # -- curve 1: sustainable txns/s vs proxy-process count ---------------
    scaling = []
    for i, p in enumerate(proxy_counts):
        scaling.append(_ladder_on_cluster(
            workdir, p, duration_s, gap_s, generators, clients, keys,
            seed + 1000 * i, calib_rate, p99_bound_ms, timeout_ms,
            annotate=annotate, env=obs_env))
    rec["scaling_curve"] = scaling
    base = next((s for s in scaling if s["proxies"] == proxy_counts[0]),
                None)
    best = max(scaling, key=lambda s: s["sustainable_tps"])
    ratio = (best["sustainable_tps"] / base["sustainable_tps"]
             if base and base["sustainable_tps"] else None)
    rec["throughput_scaling"] = {
        "from_proxies": proxy_counts[0],
        "to_proxies": best["proxies"],
        "ratio": round(ratio, 3) if ratio else None,
    }
    # -- curve 2: CO-corrected p99 vs offered load, through saturation ----
    maxp = next(s for s in scaling if s["proxies"] == max(proxy_counts))
    rec["latency_curve"] = [
        {k: p[k] for k in ("offered_tps", "throughput_txns_per_sec",
                           "co_p50_ms", "co_p99_ms", "service_p99_ms",
                           "shed", "timed_out", "failed", "committed",
                           "offered", "p99_quotable", "co_corrected",
                           "latency_samples", "max_dispatch_lag_s")
         if k in p}
        for p in maxp["points"]
    ]
    past_saturation = any(not _sustained(p, p99_bound_ms)
                          for p in maxp["points"])
    # Headline per-stage breakdown: the max-proxy cluster's best
    # sustained point (fallback: its first point) — the record-level
    # answer to where a sustained txn's time went.
    for p in sorted(maxp["points"],
                    key=lambda p: (not _sustained(p, p99_bound_ms),
                                   -p["offered_tps"])):
        if p.get("latency_breakdown"):
            rec["latency_breakdown"] = p["latency_breakdown"]
            break

    # -- overload: ratekeeper engagement + recovery -----------------------
    overload_rec = None
    if overload:
        s_tps = (maxp["sustainable_tps"]
                 or maxp["capacity_probe_tps"])
        overload_rec = _overload_run(
            workdir, max(proxy_counts), s_tps, duration_s, gap_s,
            generators, clients, keys, seed + 9000, p99_bound_ms,
            timeout_ms, annotate=annotate, env=obs_env)
        rec["overload"] = overload_rec

    scaling_ok = bool(
        len(proxy_counts) >= 2 and ratio is not None
        and ratio >= min_scaling
        and all(s["sustainable_tps"] > 0 for s in scaling))
    reasons = []
    if not scaling_ok:
        reasons.append(
            f"no throughput scaling >= {min_scaling} across proxy counts"
            + (" (single-core host: N proxy processes cannot add CPU)"
               if cores <= 1 else ""))
    if not past_saturation:
        reasons.append("latency curve never crossed saturation")
    if overload and not (overload_rec and overload_rec["engaged"]
                         and overload_rec["recovered"]):
        reasons.append("overload run missing engagement or recovery")
    rec["p99_quotable"] = all(s["p99_quotable"] for s in scaling)
    rec["past_saturation_observed"] = past_saturation
    rec["valid"] = not reasons
    if reasons:
        rec["invalid_reasons"] = reasons
    return rec


def _overload_run(workdir: str, proxies: int, sustainable_tps: float,
                  duration_s: float, gap_s: float, generators: int,
                  clients: int, keys: int, seed: int,
                  p99_bound_ms: float, timeout_ms: int,
                  annotate=None, env: "dict | None" = None) -> dict:
    """Drive far past capacity against a cluster whose resolver models
    dispatch cost (OVERLOAD_SPEC) with the admission subsystem armed,
    polling the ratekeeper from the side; then drop to well under
    capacity and require the clamps to release."""
    batch_ceiling = 1.0 / OVERLOAD_SPEC["resolver_dispatch_cost_s"]
    hi = round(max(sustainable_tps * 2.2, batch_ceiling * 6), 1)
    # Recovery offered load sits under the resolver's batch-rate ceiling
    # even in the sparse one-txn-per-batch regime, so the dispatch queue
    # drains and the clamp release is observable.
    lo = round(min(max(sustainable_tps * 0.25, 20.0),
                   0.4 * batch_ceiling), 1)
    hi_dur = max(duration_s * 2, 8.0)
    lo_dur = max(duration_s * 2.5, 10.0)
    _log(f"overload: booting {proxies}-proxy cluster with resolver "
         f"dispatch-cost knobs {OVERLOAD_SPEC}")
    with SocketCluster(os.path.join(workdir, "overload"), proxies=proxies,
                       spec_extra=dict(OVERLOAD_SPEC),
                       env={"FDB_TPU_ADMISSION": "1",
                            **(env or {})}) as cluster:
        _log(f"overload: offering {hi} tps for {hi_dur}s, then {lo} tps "
             "(transition + steady recovery windows)")
        # Three windows: overload, the recovery TRANSITION (absorbs the
        # backlog the overload left behind), and the steady recovered
        # state the recovery claim is judged on — separate accounting
        # each, so backlog drain cannot blur the recovered p99.
        points, rk = _run_generators(
            cluster.spec_path, workdir,
            [(hi_dur, hi), (lo_dur, lo), (lo_dur, lo)],
            generators, clients, seed, keys, gap_s, timeout_ms,
            rk_poll=_rk_poller(cluster), annotate=annotate, env=env)
    over, transition, rest = points[0], points[1], points[2]
    # "Engaged" means the ratekeeper itself REPORTED one of the two
    # admission signals as its limiting reason — raw queue depth alone
    # is reported next to it but must not satisfy the claim.
    engaged_signals = sorted({
        s["limiting_reason"] for s in rk
        if s["limiting_reason"] in ("resolver_queue", "admission_filter")
    })
    engaged = bool(engaged_signals)
    max_rq = max((s["resolver_queue"] for s in rk), default=0)
    tail = [s for s in rk if s["t_s"] >= rk[-1]["t_s"] - max(lo_dur / 2, 2.0)] \
        if rk else []
    released = bool(tail) and all(
        s["limiting_reason"] == "none" for s in tail)
    recovered = (released and _sustained(rest, p99_bound_ms))
    shed_total = over["shed"] + over["timed_out"] + over["failed"]
    return {
        "offered_tps_overload": hi,
        "offered_tps_recovery": lo,
        "resolver_knobs": dict(OVERLOAD_SPEC),
        "overload_point": over,
        "recovery_transition_point": transition,
        "recovery_point": rest,
        "shed_plus_timed_out_plus_failed": shed_total,
        "shed_frac_of_offered": (
            round(shed_total / over["offered"], 4) if over["offered"] else 0.0),
        "signals_observed": engaged_signals,
        "max_resolver_queue": max_rq,
        "engaged": engaged,
        "clamps_released": released,
        "recovered": recovered,
        "rk_timeline": rk,
    }
