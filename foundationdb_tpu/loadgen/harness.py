"""Open-loop runner: scheduled dispatch, CO-correct latency, shed counting.

Coordinated omission, and why latency is measured from the SCHEDULED
arrival: a generator that timestamps from the moment it actually sent a
request silently excludes the time the request spent waiting for the
generator itself to get around to it — precisely the time that explodes
when the system saturates. Every latency this harness records for an
open-loop run is (completion − scheduled arrival), so queueing anywhere
(harness client slot, GRV proxy queue, commit batch, resolver dispatch
queue) lands in the histogram instead of vanishing. Records produced here
carry ``co_corrected: true``; the closed-loop bench records keep
``co_corrected: false`` so the two latency regimes can never be confused
(bench.annotate_latency).

Load is never silently dropped either: an arrival that cannot even be
queued (global in-flight cap, per-client queue cap) increments ``shed``;
a transaction that exhausts its timeout or retry budget increments
``timed_out``/``failed``; in-flight work the drain deadline abandons
increments ``abandoned``. offered == committed + shed + timed_out +
failed + abandoned, always.
"""

from __future__ import annotations

from bisect import bisect_left
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from foundationdb_tpu.core.errors import (
    FdbError,
    NotCommitted,
    TransactionTimedOut,
)


class LatencyHistogram:
    """Log-binned latency histogram (ms), mergeable across processes.

    ~4.9% bin width (48 bins/decade) from 10µs to 600s: accurate enough
    to quote a p99, small enough to ship as one JSON line per generator
    process and SUM across generators (the only aggregation percentile
    sketches allow honestly)."""

    LO_MS = 1e-2
    HI_MS = 6e5
    BINS_PER_DECADE = 48
    _EDGES = np.logspace(
        np.log10(LO_MS), np.log10(HI_MS),
        int(np.log10(HI_MS / LO_MS) * BINS_PER_DECADE) + 1,
    )
    # Plain-python edge list for the record() hot path: bisect on a list
    # is several times cheaper than a numpy scalar searchsorted, and the
    # obs subsystem's stage stamps sit on the commit path.
    _EDGE_LIST = _EDGES.tolist()

    def __init__(self) -> None:
        # counts[i] = samples in (_EDGES[i-1], _EDGES[i]]; [0] underflow,
        # [-1] overflow.
        self.counts = np.zeros(len(self._EDGES) + 1, np.int64)
        self.max_ms = 0.0
        self.sum_ms = 0.0

    @property
    def count(self) -> int:
        return int(self.counts.sum())

    def record(self, ms: float) -> None:
        self.counts[bisect_left(self._EDGE_LIST, ms)] += 1
        if ms > self.max_ms:
            self.max_ms = float(ms)
        self.sum_ms += float(ms)

    def record_n(self, ms: float, n: int) -> None:
        """`n` samples at one value — batch-level stage stamps (obs
        subsystem) weight a per-batch duration by the batch's txn count
        without paying a record() per txn."""
        self.counts[bisect_left(self._EDGE_LIST, ms)] += n
        if ms > self.max_ms:
            self.max_ms = float(ms)
        self.sum_ms += float(ms) * n

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        self.counts += other.counts
        self.max_ms = max(self.max_ms, other.max_ms)
        self.sum_ms += other.sum_ms
        return self

    def percentile(self, q: float) -> float:
        """Upper edge of the bin holding the q-th percentile sample —
        CONSERVATIVE (never under-reports a latency). 0.0 when empty."""
        total = self.count
        if total == 0:
            return 0.0
        target = int(np.ceil(total * q / 100.0))
        cum = np.cumsum(self.counts)
        i = int(np.searchsorted(cum, target))
        if i >= len(self._EDGES):
            return float(self.max_ms)  # overflow bin: the max is exact
        return round(float(self._EDGES[i]), 4)

    def mean(self) -> float:
        return self.sum_ms / self.count if self.count else 0.0

    def to_dict(self) -> dict:
        nz = np.nonzero(self.counts)[0]
        return {
            "bins": [[int(i), int(self.counts[i])] for i in nz],
            "max_ms": round(self.max_ms, 3),
            "sum_ms": round(self.sum_ms, 3),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LatencyHistogram":
        h = cls()
        for i, n in d.get("bins", []):
            h.counts[int(i)] = int(n)
        h.max_ms = float(d.get("max_ms", 0.0))
        h.sum_ms = float(d.get("sum_ms", 0.0))
        return h


@dataclass
class OpenLoopResult:
    """One generator's accounting. offered == committed + shed +
    timed_out + failed + abandoned (asserted by the runner)."""

    offered: int = 0
    committed: int = 0
    shed: int = 0  # never even queued (in-flight / queue caps)
    timed_out: int = 0  # exhausted the transaction timeout
    failed: int = 0  # non-retryable error or retry limit
    abandoned: int = 0  # still in flight at the drain deadline
    conflict_retries: int = 0  # NotCommitted retries absorbed en route
    schedule_span_s: float = 0.0
    run_span_s: float = 0.0
    # Worst dispatcher lateness (s): how far behind its own schedule the
    # GENERATOR fell. Large values mean the generator, not the cluster,
    # bounded the offered load — the co-latency tail then includes
    # generator-side queueing and says so (single-core honesty).
    max_dispatch_lag_s: float = 0.0
    co_hist: LatencyHistogram = field(default_factory=LatencyHistogram)
    service_hist: LatencyHistogram = field(default_factory=LatencyHistogram)
    # Commit-path stage attribution (obs subsystem): the generator
    # loop's span-sink dump for this run's window, when tracing is
    # armed (FDB_TPU_OBS=1). Raw mergeable histograms — bench merges
    # across generators into the record's `latency_breakdown`.
    obs_dump: "dict | None" = None

    @property
    def throughput(self) -> float:
        return self.committed / self.run_span_s if self.run_span_s else 0.0

    def to_dict(self) -> dict:
        return {
            "offered": self.offered,
            "committed": self.committed,
            "shed": self.shed,
            "timed_out": self.timed_out,
            "failed": self.failed,
            "abandoned": self.abandoned,
            "conflict_retries": self.conflict_retries,
            "schedule_span_s": round(self.schedule_span_s, 3),
            "run_span_s": round(self.run_span_s, 3),
            "max_dispatch_lag_s": round(self.max_dispatch_lag_s, 3),
            "throughput_txns_per_sec": round(self.throughput, 1),
            # Latency from SCHEDULED arrival (coordinated-omission
            # correct) vs from actual send — shipping both keeps the gap
            # between them visible (it IS the omission a naive harness
            # hides). co_latency covers EVERY non-shed arrival:
            # timed-out/failed at their elapsed time, abandoned at their
            # censored lower bound — no survivorship; service_latency is
            # committed txns only.
            "co_latency": self.co_hist.to_dict(),
            "service_latency": self.service_hist.to_dict(),
            "co_p50_ms": self.co_hist.percentile(50),
            "co_p99_ms": self.co_hist.percentile(99),
            "service_p99_ms": self.service_hist.percentile(99),
            **({"obs": self.obs_dump} if self.obs_dump else {}),
        }

    @classmethod
    def merge_dicts(cls, dicts: "list[dict]") -> dict:
        """Aggregate several generators' to_dict() lines into one record
        (counts sum, histograms sum, spans max)."""
        out = cls()
        for d in dicts:
            out.offered += d["offered"]
            out.committed += d["committed"]
            out.shed += d["shed"]
            out.timed_out += d["timed_out"]
            out.failed += d["failed"]
            out.abandoned += d["abandoned"]
            out.conflict_retries += d["conflict_retries"]
            out.schedule_span_s = max(out.schedule_span_s,
                                      d["schedule_span_s"])
            out.run_span_s = max(out.run_span_s, d["run_span_s"])
            out.max_dispatch_lag_s = max(out.max_dispatch_lag_s,
                                         d.get("max_dispatch_lag_s", 0.0))
            out.co_hist.merge(LatencyHistogram.from_dict(d["co_latency"]))
            out.service_hist.merge(
                LatencyHistogram.from_dict(d["service_latency"]))
        merged = out.to_dict()
        # Throughput sums across generators (each measured its own span
        # against the same wall clock; max-span division under-reports
        # when spans differ — sum the per-process rates instead).
        merged["throughput_txns_per_sec"] = round(
            sum(d["throughput_txns_per_sec"] for d in dicts), 1)
        return merged


async def run_open_loop(
    loop,
    db,
    schedule,
    txn_fn,
    n_clients: int = 256,
    client_queue_cap: int = 64,
    max_inflight: int = 4096,
    timeout_ms: "int | None" = 5000,
    retry_limit: "int | None" = 8,
    drain_s: float = 15.0,
) -> OpenLoopResult:
    """Drive `db` with transactions at the scheduled offsets (seconds from
    now). Works on any flow Loop — the RealLoop against a socket cluster
    (the honest configuration) or the sim loop for deterministic tests of
    the harness itself.

    `txn_fn(tr, k)` is an async callable that stages arrival k's
    reads/writes on `tr`; the runner commits, retries through the
    standard on_error contract (bounded by `retry_limit`), and does the
    accounting. Arrivals round-robin onto `n_clients` virtual client
    slots with concurrency 1 each — the bounded-per-client-concurrency
    model of a large independent population; a busy slot QUEUES the
    arrival and the wait is measured, not skipped."""
    res = OpenLoopResult()
    schedule = np.asarray(schedule, np.float64)
    res.offered = int(schedule.size)
    res.schedule_span_s = float(schedule[-1]) if schedule.size else 0.0
    if hasattr(loop, "resync"):
        loop.resync()  # wall-clock loops: t0 must be NOW, not the last
        # pump iteration (a stale clock fakes schedule-wide lateness)
    t0 = loop.now
    # Flight-recorder load-phase annotation (obs subsystem): when this
    # loop carries a recorder, the open-loop phase boundaries land on
    # the cluster timeline so the doctor can tell "load started/ended
    # here" from an organic goodput change.
    _recorder = getattr(loop, "flight_recorder", None)
    if _recorder is not None:
        _recorder.annotate(
            "OpenLoopPhaseStart", cls="load_phase",
            offered=res.offered, span_s=round(res.schedule_span_s, 3),
            clients=n_clients)
    slots: list[deque] = [deque() for _ in range(n_clients)]
    state = {"outstanding": 0, "done_at": t0}

    async def one_txn(k: int, sched_abs: float) -> None:
        tr = db.transaction()
        if timeout_ms is not None:
            tr.set_option("timeout", timeout_ms)
        if retry_limit is not None:
            tr.set_option("retry_limit", retry_limit)
        start = loop.now
        try:
            while True:
                try:
                    await txn_fn(tr, k)
                    await tr.commit()
                    break
                except FdbError as e:
                    if isinstance(e, NotCommitted):
                        res.conflict_retries += 1
                    await tr.on_error(e)  # raises when out of budget
        except TransactionTimedOut:
            res.timed_out += 1
            # Unsuccessful arrivals still took this long: censoring them
            # out of the CO histogram would re-introduce the exact
            # survivorship omission this harness exists to kill — the
            # past-saturation p99 must include the arrivals that never
            # made it.
            res.co_hist.record((loop.now - sched_abs) * 1000.0)
            return
        except FdbError:
            res.failed += 1
            res.co_hist.record((loop.now - sched_abs) * 1000.0)
            return
        end = loop.now
        res.committed += 1
        res.co_hist.record((end - sched_abs) * 1000.0)
        res.service_hist.record((end - start) * 1000.0)

    busy = [False] * n_clients
    running: dict[int, float] = {}  # k -> scheduled time, while in flight
    workers: set = set()

    async def worker(c: int) -> None:
        try:
            while slots[c]:
                k, sched_abs = slots[c].popleft()
                running[k] = sched_abs
                try:
                    await one_txn(k, sched_abs)
                finally:
                    running.pop(k, None)
                    state["outstanding"] -= 1
                    state["done_at"] = loop.now
        finally:
            busy[c] = False

    behind = 0
    for k in range(res.offered):
        target = t0 + float(schedule[k])
        dt = target - loop.now
        if dt > 0:
            await loop.sleep(dt)
            behind = 0
        else:
            res.max_dispatch_lag_s = max(res.max_dispatch_lag_s, -dt)
            # Catching up after falling behind: yield every few dispatches
            # so workers drain while the burst floods in (otherwise the
            # dispatcher monopolizes the loop and sheds work the cluster
            # could have absorbed).
            behind += 1
            if behind % 64 == 0:
                await loop.sleep(0)
        c = k % n_clients
        if (state["outstanding"] >= max_inflight
                or len(slots[c]) >= client_queue_cap):
            res.shed += 1
            continue
        slots[c].append((k, target))
        state["outstanding"] += 1
        if not busy[c]:
            busy[c] = True
            task = loop.spawn(worker(c), name=f"loadgen.client{c}")
            workers.add(task)
            task.add_done_callback(lambda _f, t=task: workers.discard(t))

    deadline = loop.now + drain_s
    while state["outstanding"] > 0 and loop.now < deadline:
        await loop.sleep(0.05)
    if state["outstanding"] > 0:
        res.abandoned = state["outstanding"]
        # Abandoned arrivals are censored observations: record each at
        # its elapsed-so-far latency (a LOWER bound on its truth) so the
        # CO histogram never quietly drops the slowest tail.
        now = loop.now
        for s in slots:
            for _k, sched_abs in s:
                res.co_hist.record((now - sched_abs) * 1000.0)
            s.clear()
        for sched_abs in running.values():
            res.co_hist.record((now - sched_abs) * 1000.0)
        # Cancel the workers outright: on a reused loop (ladder points),
        # parked coroutines would otherwise resume DURING the next
        # point's run — consuming cluster capacity inside its window and
        # mutating this already-finalized result.
        for t in list(workers):
            t.cancel()
    res.run_span_s = max(res.schedule_span_s,
                         state["done_at"] - t0)
    assert (res.committed + res.shed + res.timed_out + res.failed
            + res.abandoned == res.offered)
    sink = getattr(loop, "span_sink", None)
    if sink is not None and sink.enabled:
        # Per-stage commit-path attribution for THIS run's window; the
        # sink resets so ladder points on a reused loop never bleed
        # samples into each other's records.
        res.obs_dump = sink.dump()
        sink.reset()
    if _recorder is not None:
        _recorder.annotate(
            "OpenLoopPhaseEnd", cls="load_phase",
            committed=res.committed, shed=res.shed,
            timed_out=res.timed_out, abandoned=res.abandoned)
    return res
