"""Open-loop traffic generation against real multi-process deployments.

Every headline number before this subsystem came from closed-loop drivers:
the next request waits for the previous response, so when the cluster
slows, the offered load politely slows with it — queueing collapse is
structurally invisible and sustainable throughput is overstated (FAFO,
arxiv 2507.10757, demonstrates exactly this failure of single-node TPS
claims). The paper's own target metric — resolved txns/sec at 1M in-flight
clients at equal p99 commit latency — is an OPEN-LOOP statement: arrivals
come from independent clients on their own schedule, whether or not the
cluster is keeping up.

This package makes that measurable honestly:

- arrivals.py  — Poisson and trace-shaped interarrival schedules modelling
  millions of independent clients with bounded per-client concurrency.
- harness.py   — the open-loop runner: dispatches transactions at their
  SCHEDULED times, measures latency from the scheduled arrival (coordinated-
  omission correct), counts shed load explicitly, aggregates into mergeable
  log-binned histograms.
- deploy.py    — SocketCluster: spawn/teardown AND role-level supervision
  of a real multi-process cluster (python -m foundationdb_tpu.server per
  role) over TCP: per-role persistent data dirs, kill/pause/restart of
  individual roles, interposing TCP relays for socket-level partitions,
  crash-aware leak checking (the fdbmonitor analogue).
- chaos.py     — the deployed chaos battery: seeded real-process fault
  scripts (SIGKILL each role class, partition-then-heal, SIGSTOP) against
  a live open-loop workload, gated on an exact acked-commit ledger,
  exactly-once markers, post-heal consistency, and per-stage recovery
  MTTR (scripts/chaos_run.sh -> CHAOS.json).
- __main__.py  — one generator process (several are aggregated by bench).
- bench.py     — the published curves: txns/s vs proxy-process count and
  p99 commit latency vs offered load through and past saturation, plus the
  overload/recovery run that shows ratekeeper clamps engaging and
  releasing (bench.py --open-loop).
"""

from foundationdb_tpu.loadgen.arrivals import (  # noqa: F401
    poisson_schedule,
    trace_schedule,
)
from foundationdb_tpu.loadgen.harness import (  # noqa: F401
    LatencyHistogram,
    OpenLoopResult,
    run_open_loop,
)
