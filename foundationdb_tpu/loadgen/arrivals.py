"""Open-loop arrival schedules: Poisson and trace-shaped interarrivals.

An open-loop schedule is decided BEFORE the run: arrival k happens at
schedule[k] seconds after t0 no matter how the cluster is doing. The
generator never waits for a response before the next arrival — that
dependency is exactly what makes closed-loop numbers lie past saturation.

Schedules model a large population of independent clients: the aggregate
of N independent sparse arrival processes converges on a Poisson process
(Palm–Khintchine), so a single exponential-gap stream stands in for
"millions of clients" faithfully as long as no single virtual client is
asked to pipeline against itself — the harness enforces that with bounded
per-client concurrency (each arrival is assigned to a virtual client slot;
a busy slot queues the arrival, and the queue wait is PART of the measured
latency, never silently skipped).
"""

from __future__ import annotations

import numpy as np


def poisson_schedule(rate: float, duration_s: float,
                     seed: int = 0) -> np.ndarray:
    """Arrival offsets (seconds, ascending, float64) for a homogeneous
    Poisson process of `rate` arrivals/sec over `duration_s`."""
    if rate <= 0 or duration_s <= 0:
        return np.zeros(0, np.float64)
    rng = np.random.default_rng(seed)
    # Draw with 3-sigma headroom, then trim to the window: one allocation,
    # no incremental growth, exact Poisson gaps.
    n = int(rate * duration_s + 4 * np.sqrt(rate * duration_s) + 16)
    t = np.cumsum(rng.exponential(1.0 / rate, size=n))
    out = t[t < duration_s]
    while n and out.size == n:  # headroom was not enough (tiny rates)
        n *= 2
        t = np.cumsum(rng.exponential(1.0 / rate, size=n))
        out = t[t < duration_s]
    return out


def trace_schedule(profile: "list[tuple[float, float]]",
                   seed: int = 0) -> np.ndarray:
    """Trace-shaped arrivals: `profile` is a list of (duration_s, rate)
    segments played back to back — a piecewise-constant rate function
    (diurnal curves, bursts, the overload→recovery shape the bench's
    ratekeeper run uses). Each segment is Poisson at its own rate."""
    out: list[np.ndarray] = []
    t0 = 0.0
    for i, (dur, rate) in enumerate(profile):
        seg = poisson_schedule(rate, dur, seed=seed + 1000003 * i)
        out.append(seg + t0)
        t0 += dur
    if not out:
        return np.zeros(0, np.float64)
    return np.concatenate(out)


def parse_profile(spec: str) -> "list[tuple[float, float]]":
    """Parse "dur:rate,dur:rate,..." (seconds:txns-per-sec) into a
    trace_schedule profile — the CLI surface of trace-shaped load."""
    profile = []
    for part in spec.split(","):
        dur, rate = part.split(":")
        profile.append((float(dur), float(rate)))
    return profile
