"""Deployed-cluster chaos: real-process crash/restart/partition injection
with acked-durability and recovery-time gates (ISSUE 14 tentpole).

Everything the sim's nemesis catalog does to virtual processes, done to
REAL OS processes over REAL TCP: a seeded fault script drives the
SocketCluster supervisor (loadgen/deploy.py) — SIGKILL a tlog mid-fsync,
kill the resolver with batches in flight, kill a commit proxy under its
clients, kill the sequencer to force a real epoch bump over sockets,
black-hole a role's connections through its interposing relay
(runtime/net.TcpRelay) and heal on schedule — while a live open-loop
workload commits against the cluster the whole time.

Verification is EXACT, never liveness-only:

- **Acked-commit ledger.** The workload client records key → value for
  every commit it got an ACK for; commits whose outcome it cannot know
  (CommitUnknownResult, or a commit RPC still in flight when its bound
  expired) are tracked separately as may-be-committed. After heal +
  quiesce the harness reads everything back at one snapshot: an acked
  key missing or mismatched is ACKED-COMMIT LOSS (hard failure); every
  may-be-committed entry must resolve to exactly-committed or cleanly
  absent.
- **Exactly-once oracle.** Every transaction atomically increments one
  of a small set of counters AND writes a per-arrival marker key in the
  same transaction, so `sum(counters) == #markers-present` holds iff no
  transaction committed twice or half; every ACKED transaction's marker
  must be present.
- **Consistency check.** The cluster-wide byte-parity audit
  (consistency/run_deployed_check) must come back green post-heal.
- **MTTR breakdown.** Each injected fault is wall-stamped; the deployed
  controller's recovery log (server.py: detection → lock → salvage →
  accepting-commits stage durations) is matched against those stamps,
  yielding per-fault detection latency + per-stage recovery time, plus
  the client-observed blackout (first post-fault commit ack).

`python -m foundationdb_tpu.loadgen.chaos [--fast] [--seed N]` prints the
one-JSON-line CHAOS record (scripts/chaos_run.sh → CHAOS.json; tpuwatch
stage `chaos` runs --fast: one kill-restart cycle per role class). The
seed reproduces the fault schedule and workload shape exactly; real-world
interleaving is of course not deterministic — which is the point.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

from dataclasses import dataclass

from foundationdb_tpu.core.errors import (
    CommitUnknownResult,
    FdbError,
    NotCommitted,
    ProcessKilled,
)
from foundationdb_tpu.loadgen.deploy import SocketCluster

#: bound on any single client operation (read/commit await): a commit
#: still in flight past this is classified may-be-committed — a
#: black-holed proxy never delivers the BrokenPromise a dead one would.
OP_TIMEOUT_S = 10.0
#: per-arrival total retry budget before the arrival is abandoned.
TXN_BUDGET_S = 45.0


class _OpTimeout(Exception):
    """A bounded client operation outran OP_TIMEOUT_S (hung link)."""


async def _bounded(loop, coro, timeout_s: float, name: str):
    """Await `coro` for at most `timeout_s` (server.bounded_rpc is the
    one deadline-race implementation; the abandoned task keeps running —
    its eventual result is discarded; for a commit that is exactly
    'outcome unknown', which the caller records as such)."""
    from foundationdb_tpu.server import bounded_rpc

    try:
        return await bounded_rpc(loop, loop.spawn(coro, name=name),
                                 timeout_s)
    except TimeoutError as e:
        raise _OpTimeout(name) from e


# -- fault script -------------------------------------------------------------


@dataclass
class ChaosEvent:
    at_s: float  # offset from workload start
    action: str  # kill | restart | pause | resume | partition | heal
    target: str  # role process name, e.g. "tlog0"
    mode: str = "drop"  # partition mode (drop | cut | delay)
    stamp: "float | None" = None  # wall clock when executed
    error: "str | None" = None


def default_script(fast: bool = False) -> "tuple[list[ChaosEvent], float]":
    """(events, workload duration). The core battery — one SIGKILL +
    restart cycle per role CLASS (tlog, resolver, commit proxy,
    sequencer), each under live load; the full script adds a
    partition-then-heal through the tlog relay and a SIGSTOP/SIGCONT
    freeze of a proxy (alive-but-silent: the probe-timeout case)."""
    ev = [
        ChaosEvent(2.0, "kill", "tlog0"),        # mid-fsync under load
        ChaosEvent(5.0, "restart", "tlog0"),     # from_disk -> tlog_adopt
        ChaosEvent(9.0, "kill", "resolver0"),    # in-flight batches die
        ChaosEvent(11.5, "restart", "resolver0"),
        ChaosEvent(15.5, "kill", "proxy0"),      # clients lose their proxy
        ChaosEvent(18.0, "restart", "proxy0"),
        ChaosEvent(22.0, "kill", "sequencer0"),  # real epoch bump
        ChaosEvent(24.5, "restart", "sequencer0"),
    ]
    duration = 30.0
    if not fast:
        ev += [
            ChaosEvent(30.0, "partition", "tlog1", mode="drop"),
            ChaosEvent(35.0, "heal", "tlog1"),
            ChaosEvent(38.0, "pause", "proxy1"),
            ChaosEvent(42.0, "resume", "proxy1"),
        ]
        duration = 48.0
    return ev, duration


# -- acked-commit ledger ------------------------------------------------------


class AckedLedger:
    """What the client KNOWS: values it holds commit acks for, values
    whose commit outcome it could not learn, and the exact accounting of
    every arrival — offered == acked + unknown + shed + abandoned +
    nonretryable, asserted at the end of the open-loop writer."""

    def __init__(self) -> None:
        self.acked: dict[bytes, bytes] = {}  # unique key -> acked value
        self.acked_markers: list[bytes] = []
        self.unknown: dict[bytes, bytes] = {}  # may-be-committed
        self.unknown_markers: list[bytes] = []
        self.ack_walls: list[float] = []
        self.offered = 0
        self.shed = 0
        self.abandoned = 0  # retry budget exhausted (known non-commits only)
        self.conflict_retries = 0
        self.op_timeouts = 0
        self.nonretryable: list[str] = []

    def ack(self, ukey: bytes, val: bytes, marker: bytes) -> None:
        self.acked[ukey] = val
        self.acked_markers.append(marker)
        self.ack_walls.append(time.time())

    def note_unknown(self, ukey: bytes, val: bytes, marker: bytes) -> None:
        self.unknown[ukey] = val
        self.unknown_markers.append(marker)

    def first_ack_after(self, wall: float) -> "float | None":
        later = [w for w in self.ack_walls if w >= wall]
        return (min(later) - wall) if later else None


# -- the chaos run ------------------------------------------------------------


def _log(msg: str) -> None:
    print(f"[chaos] {msg}", file=sys.stderr, flush=True)


async def _one_txn(loop, db, ledger: AckedLedger, pref: bytes, k: int,
                   n_ctrs: int) -> None:
    ctr_key = pref + b"ctr/%02d" % (k % n_ctrs)
    marker = pref + b"m/%06d" % k
    ukey = pref + b"u/%06d" % k
    val = b"v%06d" % k
    deadline = loop.now + TXN_BUDGET_S
    backoff = 0.02
    while True:
        tr = db.transaction()
        commit_in_flight = False
        try:
            cur = await _bounded(loop, tr.get(ctr_key), OP_TIMEOUT_S,
                                 f"chaos.get{k}")
            tr.set(ctr_key, b"%d" % (int(cur or b"0") + 1))
            tr.set(marker, b"1")
            tr.set(ukey, val)
            commit_in_flight = True
            await _bounded(loop, tr.commit(), OP_TIMEOUT_S, f"chaos.commit{k}")
            ledger.ack(ukey, val, marker)
            return
        except _OpTimeout:
            ledger.op_timeouts += 1
            if commit_in_flight:
                # The commit RPC was launched and never answered in
                # bound: the batch may be durable — may-be-committed.
                ledger.note_unknown(ukey, val, marker)
                return
            # A read/GRV hung: provably nothing was committed — retry.
        except CommitUnknownResult:
            ledger.note_unknown(ukey, val, marker)
            return
        except NotCommitted:
            ledger.conflict_retries += 1  # known non-commit: safe retry
        except FdbError as e:
            if not e.retryable:
                # The reconnect-hardening gate (ISSUE 14 satellite): a
                # connection death must NEVER surface non-retryably.
                ledger.nonretryable.append(
                    f"{type(e).__name__}({e.code}): {e}")
                return
            if isinstance(e, ProcessKilled):
                try:  # re-discover live proxies (ClientDBInfo path)
                    await db.refresh_client_info()
                except Exception:
                    pass
        if loop.now > deadline:
            ledger.abandoned += 1
            return
        backoff = min(0.5, backoff * 1.6)
        await loop.sleep(backoff * (0.5 + loop.rng.random()))


async def _open_loop_writer(loop, db, ledger: AckedLedger, pref: bytes,
                            schedule, n_ctrs: int, max_inflight: int,
                            drain_s: float) -> None:
    t0 = loop.now
    live: set = set()  # in-flight txn tasks (len == concurrency in use)
    for k, off in enumerate(schedule):
        dt = t0 + float(off) - loop.now
        if dt > 0:
            await loop.sleep(dt)
        ledger.offered += 1
        if len(live) >= max_inflight:
            ledger.shed += 1
            continue
        task = loop.spawn(_one_txn(loop, db, ledger, pref, k, n_ctrs),
                          name=f"chaos.txn{k}")
        live.add(task)
        task.add_done_callback(lambda f, t=task: live.discard(t))
    deadline = loop.now + drain_s
    while live and loop.now < deadline:
        await loop.sleep(0.1)
    # Residue at the drain deadline is CANCELLED, not left running: a
    # straggler acking after the read-back snapshot would make its own
    # (correct) commit read as acked-commit loss. A cancelled in-flight
    # commit may still land server-side — it is simply ungated (the
    # exactly-once identity is computed purely from read-back state and
    # holds either way). A task whose completion was ALREADY queued when
    # the cancel landed still runs to completion and records its own
    # outcome (cancel() is a no-op on a done task) — so abandoned counts
    # only the tasks that actually died cancelled, judged after the
    # unwind settles, never by the snapshot alone.
    leftovers = list(live)
    for task in leftovers:
        task.cancel()
    settle = loop.now + 5.0
    while any(not t.done() for t in leftovers) and loop.now < settle:
        await loop.sleep(0.05)
    ledger.abandoned += sum(1 for t in leftovers if t.is_error())
    assert (len(ledger.acked) + len(ledger.unknown) + ledger.shed
            + ledger.abandoned + len(ledger.nonretryable)
            == ledger.offered), "chaos ledger accounting broke"


async def _run_events(loop, cluster: SocketCluster, events, t0: float,
                      counters: dict) -> None:
    # Flight recorder (obs subsystem), when this run armed one: every
    # injected fault / scripted repair is stamped as a first-class
    # annotation on the SAME timeline the metric snapshots ride — the
    # doctor's fault-window attribution keys off exactly these.
    recorder = getattr(loop, "flight_recorder", None)
    for ev in events:
        dt = t0 + ev.at_s - loop.now
        if dt > 0:
            await loop.sleep(dt)
        try:
            if ev.action == "kill":
                ev.stamp = cluster.kill_role(ev.target)
                counters["chaos_kills"] += 1
            elif ev.action == "restart":
                ev.stamp = time.time()
                cluster.restart_role(ev.target, wait=False)
                counters["chaos_restarts"] += 1
                ready_deadline = loop.now + 20.0
                while (not cluster.role_ready(ev.target)
                       and loop.now < ready_deadline):
                    await loop.sleep(0.1)
            elif ev.action == "pause":
                ev.stamp = cluster.pause_role(ev.target)
                counters["chaos_pauses"] += 1
            elif ev.action == "resume":
                ev.stamp = time.time()
                cluster.resume_role(ev.target)
            elif ev.action == "partition":
                ev.stamp = cluster.partition_role(ev.target, ev.mode)
                counters["chaos_partitions"] += 1
            elif ev.action == "heal":
                ev.stamp = time.time()
                cluster.heal_role(ev.target)
                counters["chaos_heals"] += 1
            else:
                raise ValueError(f"unknown chaos action {ev.action!r}")
            if ev.action in ("kill", "pause", "partition"):
                # Faults only: restart/resume/heal are the REPAIRS —
                # counting them would double the published fault count.
                counters["chaos_faults_injected"] += 1
            if recorder is not None:
                recorder.annotate(
                    f"Chaos{ev.action.capitalize()}",
                    cls=("chaos_fault"
                         if ev.action in ("kill", "pause", "partition")
                         else "chaos_heal"),
                    severity=("warn"
                              if ev.action in ("kill", "pause", "partition")
                              else "info"),
                    action=ev.action, target=ev.target,
                    at_s=ev.at_s, wall=ev.stamp)
            _log(f"t+{ev.at_s:.1f}s {ev.action} {ev.target}")
        except Exception as e:  # noqa: BLE001 — record, keep the script going
            ev.error = f"{type(e).__name__}: {e}"
            _log(f"t+{ev.at_s:.1f}s {ev.action} {ev.target} FAILED: {ev.error}")


async def _controller_stable(loop, ctrl, spec: dict, timeout_s: float) -> dict:
    """Wait until the controller reports a full, quiet generation for a
    few consecutive probes; returns the final status."""
    expect = {r: list(range(len(spec[r])))
              for r in ("tlog", "resolver", "proxy")}
    stable, st = 0, {}
    deadline = loop.now + timeout_s
    while stable < 3:
        if loop.now > deadline:
            raise TimeoutError(
                f"cluster never quiesced: last status {st}")
        try:
            st = await _bounded(loop, ctrl.get_status(), 5.0, "chaos.status")
            ok = (not st.get("recovering")
                  and all(st.get("generation", {}).get(r) == idx
                          for r, idx in expect.items()))
        except Exception:
            ok = False
        stable = stable + 1 if ok else 0
        await loop.sleep(1.0)
    return st


def _mttr_report(events, recovery_log, ledger: AckedLedger) -> list[dict]:
    """Per-fault MTTR: match each injected fault to the first recovery
    the controller DETECTED at/after its wall stamp (several faults can
    fold into one generation change — they then share the entry). A
    match detected only after the NEXT scripted event's stamp is marked
    `attribution: "shared"` and claims no detection latency: a fault
    that triggered no recovery at all (a pause shorter than the probe
    timeout, a partition needing no generation change) must not steal
    the following fault's recovery as its own MTTR."""
    out = []
    for i, ev in enumerate(events):
        if ev.action not in ("kill", "partition", "pause"):
            continue
        rep = {"action": ev.action, "target": ev.target,
               "at_s": ev.at_s, "error": ev.error}
        entry = next((e for e in recovery_log
                      if ev.stamp is not None
                      and e["detected_wall"] >= ev.stamp), None)
        # The demotion threshold is the next FAULT only: this fault's
        # own scripted repair (restart/resume/heal) cannot be a
        # competing fault, and on a loaded host detection can honestly
        # land after it.
        next_stamp = next((e2.stamp for e2 in events[i + 1:]
                           if e2.stamp is not None
                           and e2.action in ("kill", "partition", "pause")),
                          None)
        if entry is not None:
            shared = (next_stamp is not None
                      and entry["detected_wall"] >= next_stamp)
            rep.update({
                "recovered_epoch": entry["epoch"],
                "detection_s": (None if shared else round(
                    entry["detected_wall"] - ev.stamp, 3)),
                "lock_s": entry["lock_s"],
                "salvage_s": entry["salvage_s"],
                "recruit_s": entry["recruit_s"],
                "mttr_total_s": (None if shared else round(
                    entry["completed_wall"] - ev.stamp, 3)),
            })
            if shared:
                rep["attribution"] = "shared"
        if ev.stamp is not None:
            blackout = ledger.first_ack_after(ev.stamp)
            rep["first_ack_after_s"] = (round(blackout, 3)
                                        if blackout is not None else None)
        out.append(rep)
    return out


def run_chaos(seed: int = 20260804, fast: bool = False,
              rate: float = 80.0, workdir: "str | None" = None,
              script: "list[ChaosEvent] | None" = None,
              duration_s: "float | None" = None,
              n_ctrs: int = 16, max_inflight: int = 256,
              drain_s: float = 20.0,
              recorder_path: "str | None" = None) -> dict:
    """One seeded chaos run → the CHAOS record (see module docstring).

    ``recorder_path``: arm the obs flight recorder for this run — server
    processes start with FDB_TPU_OBS=1 (stage spans ride commit replies),
    the harness loop gets a SpanSink + FlightRecorder scraping the
    cluster each second, every fault/heal is annotated on the timeline,
    and the client-side ledger counters join the scrape as the `client`
    role (the SLO tracker's unknown-result SLI). The ring at that path
    is the doctor's input (obs/doctor.py, `cli doctor`)."""
    from foundationdb_tpu.loadgen.arrivals import poisson_schedule

    workdir = workdir or tempfile.mkdtemp(prefix="chaos_")
    events, default_dur = default_script(fast)
    if script is not None:
        events = script
    dur = duration_s if duration_s is not None else default_dur
    cores = (len(os.sched_getaffinity(0)) if hasattr(os, "sched_getaffinity")
             else (os.cpu_count() or 1))
    counters = {k: 0 for k in ("chaos_faults_injected", "chaos_kills",
                               "chaos_restarts", "chaos_partitions",
                               "chaos_heals", "chaos_pauses")}
    ledger = AckedLedger()
    pref = b"chaos/%d/" % seed
    # ONE topology literal: the published record and the actual boot call
    # both read it, so they cannot drift apart.
    topo = {"proxies": 2, "tlogs": 2, "storages": 1, "resolvers": 1,
            "managed": True, "relay_roles": ("tlog",)}
    rec: dict = {
        "metric": "deployed_chaos",
        "seed": seed,
        "fast": fast,
        "engine": "cpu-skiplist resolve over real TCP (no TPU claimed)",
        "cpu_fallback": False,
        "cluster": {**topo, "relay_roles": list(topo["relay_roles"])},
        "host": {"cores": cores,
                 "loadavg_1m": round(os.getloadavg()[0], 2)},
        "rate_tps": rate,
        "duration_s": dur,
        "workdir": workdir,
        # The full workload shape rides the replay line: a non-default
        # rate changes the poisson schedule, so omitting it would make
        # the record claim a reproduction it doesn't perform
        # (chaos_run.sh forwards unrecognized args to the module).
        # A recorder-armed run traces the servers (FDB_TPU_OBS=1), which
        # is a different workload than an untraced one — the replay line
        # must say so.
        "replay": f"bash scripts/chaos_run.sh --seed {seed}"
                  + (" --fast" if fast else "")
                  + (f" --rate {rate:g}" if rate != 80.0 else "")
                  + (" --recorder flight_ring.jsonl" if recorder_path
                     else ""),
    }
    if recorder_path:
        rec["recorder_path"] = recorder_path
    problems: list[str] = []
    cluster: "SocketCluster | None" = None
    client_t = None  # the open_client NetTransport: closed on EVERY path
    try:
        # Boot INSIDE the guarded region: a role that dies during boot
        # must still yield an ok:false record and a reaped cluster (the
        # relays' listener threads start at construction).
        _log(f"seed={seed} fast={fast}: booting managed cluster in {workdir}")
        cluster = SocketCluster(
            workdir, ratekeeper=True, data_dirs=True,
            env=({"FDB_TPU_OBS": "1"} if recorder_path else None), **topo)
        cluster.start()
        rec["cluster"]["processes"] = len(cluster.procs)
        loop, t, db = cluster.open_client()
        client_t = t
        from foundationdb_tpu.client.transaction import Transaction

        db.transaction_class = Transaction
        ctrl = cluster.controller_ep(t)
        schedule = poisson_schedule(rate, dur, seed=seed)
        recorder = None
        if recorder_path:
            from foundationdb_tpu.obs.recorder import FlightRecorder
            from foundationdb_tpu.obs.registry import (
                add_span_sink,
                scrape_deployed_async,
            )
            from foundationdb_tpu.obs.span import SpanSink
            from foundationdb_tpu.server import load_spec as _spec_load

            # Client-side sink: servers run FDB_TPU_OBS=1 (env above), so
            # commit replies carry proxy stage spans and the harness
            # assembles full trees — dense enough at 1-in-8 for per-window
            # stage shares without distorting the workload.
            sink = SpanSink(loop, sample_every=8)
            chaos_spec = _spec_load(cluster.spec_path)

            async def recorder_scrape():
                reg = await scrape_deployed_async(loop, t, chaos_spec)
                reg.add("chaos", "", dict(counters))
                # The client's own ledger is the only honest source of
                # the unknown-result SLI — servers cannot know which
                # acks were lost in flight.
                reg.add("client", "", {
                    "commits_acked": len(ledger.acked),
                    "commit_unknowns": len(ledger.unknown),
                    "offered": ledger.offered,
                    "op_timeouts": ledger.op_timeouts,
                    "conflict_retries": ledger.conflict_retries,
                })
                add_span_sink(reg, sink)
                return reg

            recorder = FlightRecorder(loop, recorder_scrape, recorder_path,
                                      interval_s=1.0)

        async def main():
            t0 = loop.now
            recorder_task = (
                loop.spawn(recorder.run(), name="chaos.recorder")
                if recorder is not None else None)
            ev_task = loop.spawn(
                _run_events(loop, cluster, events, t0, counters),
                name="chaos.events")
            await _open_loop_writer(loop, db, ledger, pref, schedule,
                                    n_ctrs, max_inflight, drain_s)
            await ev_task
            # -- heal + quiesce ------------------------------------------
            _log("heal + quiesce")
            cluster.heal_all()
            for p in cluster.procs:
                if p.paused:
                    cluster.resume_role(p.name)
            for p in cluster.procs:
                if not p.alive():
                    _log(f"restarting dead {p.name} for quiesce")
                    cluster.restart_role(p.name, wait=False)
            for p in cluster.procs:
                ready_deadline = loop.now + 30.0
                while (not cluster.role_ready(p.name)
                       and loop.now < ready_deadline):
                    await loop.sleep(0.1)
            st = await _controller_stable(loop, ctrl, cluster.spec, 120.0)
            # Prove the healed cluster ACCEPTS commits before judging it.
            settle_deadline = loop.now + 60.0
            while True:
                tr = db.transaction()
                try:
                    tr.set(pref + b"settle", b"1")
                    await _bounded(loop, tr.commit(), OP_TIMEOUT_S,
                                   "chaos.settle")
                    break
                except (FdbError, _OpTimeout):
                    if loop.now > settle_deadline:
                        raise
                    await loop.sleep(0.5)
            # -- exact read-back -----------------------------------------
            _log("ledger read-back")
            got: dict[bytes, bytes] = {}
            readback_deadline = loop.now + 60.0
            while True:
                tr = db.transaction()
                try:
                    rows = await _bounded(
                        loop,
                        tr.get_range(pref, pref + b"\xff", snapshot=True),
                        30.0, "chaos.readback")
                    got = dict(rows)
                    break
                except (FdbError, _OpTimeout):
                    if loop.now > readback_deadline:
                        raise
                    await loop.sleep(0.5)
            # -- consistency check ---------------------------------------
            _log("consistency check")
            from foundationdb_tpu.consistency import run_deployed_check
            from foundationdb_tpu.server import load_spec

            consistency = await run_deployed_check(
                loop, t, load_spec(cluster.spec_path), db)
            log = await _bounded(loop, ctrl.get_recovery_log(), 5.0,
                                 "chaos.recovery_log")
            if recorder_task is not None:
                # One final scrape so the post-heal state is on the ring
                # (recovery counters, healed metrics), then stop.
                try:
                    recorder.observe_registry(await recorder_scrape())
                except Exception:
                    pass
                recorder_task.cancel()
            return st, got, consistency, log

        st, got, consistency, recovery_log = loop.run(
            main(), timeout=dur + drain_s + 600.0)

        # -- verification ----------------------------------------------------
        lost = sorted(
            k.decode() for k, v in ledger.acked.items() if got.get(k) != v)
        unknown_committed = sum(
            1 for k, v in ledger.unknown.items() if got.get(k) == v)
        unknown_absent = sum(
            1 for k in ledger.unknown if k not in got)
        unknown_mangled = (len(ledger.unknown) - unknown_committed
                           - unknown_absent)
        markers_present = sum(
            1 for k in got if k.startswith(pref + b"m/"))
        ctr_sum = sum(int(v) for k, v in got.items()
                      if k.startswith(pref + b"ctr/"))
        acked_marker_missing = [
            m.decode() for m in ledger.acked_markers if m not in got]
        exactly_once_ok = (ctr_sum == markers_present
                           and not acked_marker_missing
                           and unknown_mangled == 0)
        rec["ledger"] = {
            "offered": ledger.offered,
            "acked": len(ledger.acked),
            "unknown": len(ledger.unknown),
            "unknown_committed": unknown_committed,
            "unknown_absent": unknown_absent,
            "unknown_mangled": unknown_mangled,
            "shed": ledger.shed,
            "abandoned": ledger.abandoned,
            "conflict_retries": ledger.conflict_retries,
            "op_timeouts": ledger.op_timeouts,
            "acked_lost": lost[:20],
            "acked_lost_count": len(lost),
            "counter_sum": ctr_sum,
            "markers_present": markers_present,
            "acked_marker_missing": acked_marker_missing[:20],
            "exactly_once_ok": exactly_once_ok,
            "nonretryable_errors": ledger.nonretryable[:20],
        }
        rec["faults"] = _mttr_report(events, recovery_log, ledger)
        rec["recovery_log"] = recovery_log
        rec["recoveries_completed"] = st.get("recoveries_completed")
        rec["final_epoch"] = st.get("epoch")
        rec["consistency"] = {
            "status": consistency.get("status"),
            "divergences": len(consistency.get("divergences") or []),
            "shards_checked": consistency.get("shards_checked"),
            "rows_compared": consistency.get("rows_compared"),
        }
        # -- metrics scrape (registry + chaos counters, audited) -------------
        from foundationdb_tpu.obs.registry import (
            CHAOS_DOCUMENTED_COUNTERS,
            scrape_deployed,
        )
        from foundationdb_tpu.server import load_spec as _load

        reg = scrape_deployed(loop, t, _load(cluster.spec_path))
        reg.add("chaos", "", dict(counters))
        extra_documented = CHAOS_DOCUMENTED_COUNTERS
        if recorder is not None:
            from foundationdb_tpu.obs.registry import (
                RECORDER_DOCUMENTED_COUNTERS,
            )

            reg.add("recorder", "", recorder.metrics())
            reg.add("slo", "", recorder.slo.metrics())
            extra_documented = (CHAOS_DOCUMENTED_COUNTERS
                                + RECORDER_DOCUMENTED_COUNTERS)
            rec["recorder"] = {
                "path": recorder_path,
                **recorder.metrics(),
                "slo": recorder.slo.status(),
            }
            recorder.close()
        audit = reg.audit()
        missing = reg.missing_documented(extra=extra_documented)
        rec["scrape"] = {"metrics": len(reg.values),
                         "audit_problems": audit[:10],
                         "missing_documented": missing}
        agg = reg.aggregated()
        rec["recovery_counters"] = {
            k: agg[k] for k in agg if k.startswith("controller.recovery")}
        t.close()

        # -- gates -----------------------------------------------------------
        if lost:
            problems.append(f"ACKED-COMMIT LOSS: {len(lost)} keys")
        if not exactly_once_ok:
            problems.append(
                f"exactly-once violated: counters={ctr_sum} "
                f"markers={markers_present} "
                f"acked_marker_missing={len(acked_marker_missing)} "
                f"mangled={unknown_mangled}")
        if consistency.get("status") != "consistent":
            problems.append(
                f"consistency check {consistency.get('status')!r}")
        if ledger.nonretryable:
            problems.append(
                f"{len(ledger.nonretryable)} non-retryable client errors "
                f"(first: {ledger.nonretryable[0]})")
        if not ledger.acked:
            problems.append("no commit was ever acked (harness starved)")
        kill_unmatched = [
            f["target"] for f in rec["faults"]
            if f["action"] == "kill" and "recovered_epoch" not in f]
        if kill_unmatched:
            problems.append(
                f"kills with no matched recovery: {kill_unmatched}")
        inject_failures = [
            f"{ev.action} {ev.target}: {ev.error}"
            for ev in events if ev.error]
        if inject_failures:
            # A fault that failed to INJECT proves nothing about the
            # cluster — a partition that never happened must not let the
            # battery claim the partition was survived.
            problems.append(f"fault injection failed: {inject_failures}")
        if audit:
            problems.append(f"scrape audit problems: {audit[:3]}")
        if missing:
            problems.append(f"documented counters missing: {missing}")
    except Exception as e:  # noqa: BLE001 — the record must say WHY
        problems.append(f"harness error: {type(e).__name__}: {e}")
        if client_t is not None:
            try:  # a failed run must not leak the client's sockets
                client_t.close()
            except Exception:
                pass
        if cluster is not None:
            cluster.kill()
        rec["ok"] = rec["valid"] = False
        rec["problems"] = problems
        return rec
    try:
        cluster.shutdown()
    except RuntimeError as e:
        problems.append(str(e))  # the crashed-process leak check (deploy.py)
        cluster.kill()  # shutdown kept the proc table for exactly this
        # mop-up: reap orphan groups, close the relays' listeners
    rec["chaos_counters"] = counters
    rec["ok"] = rec["valid"] = not problems
    rec["problems"] = problems
    if cores <= 1:
        rec["mttr_caveat"] = (
            "single-core host: MTTR stage durations include CPU "
            "contention with the workload and every other role process — "
            "treat absolute times as upper bounds (correctness gates are "
            "unaffected)")
    return rec


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m foundationdb_tpu.loadgen.chaos",
        description="Deployed-cluster chaos battery -> one JSON line")
    ap.add_argument("--seed", type=int, default=20260804)
    ap.add_argument("--fast", action="store_true",
                    help="one kill-restart cycle per role class only "
                         "(tpuwatch chaos stage); default adds "
                         "partition-then-heal + SIGSTOP freeze")
    ap.add_argument("--rate", type=float, default=80.0,
                    help="open-loop offered load, txns/sec")
    ap.add_argument("--workdir", default=None)
    ap.add_argument("--recorder", default=None, metavar="RING_PATH",
                    help="arm the obs flight recorder: servers traced "
                         "(FDB_TPU_OBS=1), 1s metric snapshots + fault/"
                         "heal annotations ringed to RING_PATH — feed it "
                         "to `cli doctor` / --doctor for the root-cause "
                         "report")
    args = ap.parse_args(argv)
    rec = run_chaos(seed=args.seed, fast=args.fast, rate=args.rate,
                    workdir=args.workdir, recorder_path=args.recorder)
    print(json.dumps(rec), flush=True)
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
