// Redwood-class storage engine: a copy-on-write page B+tree.
//
// Reference: fdbserver/VersionedBTree.actor.cpp (Redwood) — the
// reference's current-generation ssd engine is a paged, checksummed,
// copy-on-write B+tree with a two-generation freelist and atomic root
// flips. This is the same architecture at sim scale, NOT a translation:
// one C++ file, a batch-apply recursive COW rebuild instead of actor
// pipelines, and the MVCC window stays in the storage server's memory
// (runtime/storage.py) exactly as with the sqlite engine — this engine
// persists the consistent prefix (runtime/kvstore.py contract: flush /
// durable_version / load).
//
// Crash model (what the design guarantees):
// - All NEW pages of a flush are written and fsync'd BEFORE the meta
//   page that references them; the meta (with checksum + seq) is then
//   written to the ALTERNATE slot and fsync'd. A crash at any point
//   leaves at least one valid meta whose every reachable page was
//   durable when that meta committed — torn in-flight pages are simply
//   unreachable. Open picks the valid meta with the higher seq.
// - Pages freed by commit N (replaced COW paths, deleted overflow
//   chains) are PENDING until commit N+1: while meta(N-1) is still the
//   fallback, its pages must not be overwritten. At commit N+1 the
//   pending set joins the free list. (Redwood's lazy-delete queue has
//   the same one-generation delay for the same reason.)
//
// Layout: 16 KiB pages. Page 0/1 = meta slots. Data pages start at 2.
//   meta:     {magic, seq, root, page_count, durable_version,
//              free_head, pending_head, checksum}
//   leaf:     {type=1, n} then n cells
//             cell: klen u32 | flags u8 | vlen u32 | key | (value |
//                   overflow_head u64)
//   internal: {type=2, n} then n entries: klen u32 | child u64 | key
//             entry i's key is the SMALLEST key of child i; entry 0's
//             key is empty.
//   freelist: {type=3, n, next} then n u64 page ids
//   overflow: {type=4, used, next} then `used` value bytes
//
// Values larger than INLINE_MAX spill to an overflow chain; keys (<=
// 10 KB by the client limit) always fit a 16 KiB page inline.

#include <algorithm>
#include <cstddef>
#include <cstdint>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t MAGIC = 0x52574254504642ULL;  // "RWBTPFB"
constexpr uint32_t PAGE = 16384;
constexpr uint32_t INLINE_MAX = 4096;  // larger values go to overflow pages
constexpr uint8_t LEAF = 1, INTERNAL = 2, FREEPAGE = 3, OVERFLOW_PAGE = 4;
constexpr uint8_t F_OVERFLOW = 1;

struct Meta {
  uint64_t magic;
  uint64_t seq;
  uint64_t root;        // 0 = empty tree
  uint64_t page_count;  // next fresh page id
  int64_t durable_version;
  uint64_t free_head;     // SPILL chain for free ids beyond the inline cap
  uint64_t pending_head;  // SPILL chain for pending ids beyond the cap
  uint32_t free_inline;     // ids stored inline in the meta page
  uint32_t pending_inline;  //   (free first, then pending)
  uint64_t checksum;  // fnv1a over the whole used meta region, field 0
};

// Inline freelist capacity: the meta page itself carries the free and
// pending ids in the common case, so steady-state commits write ZERO
// extra freelist pages (a naive chain-page-per-commit design grew the
// file 2 pages per commit forever — measured). Spill chains only appear
// under huge churn (a giant clear_range), and their pages recycle too.
constexpr size_t META_IDS_CAP = (PAGE - sizeof(Meta)) / 8;

uint64_t fnv1a(const uint8_t* p, size_t n) {
  uint64_t h = 1469598103934665603ULL;
  for (size_t i = 0; i < n; i++) {
    h ^= p[i];
    h *= 1099511628211ULL;
  }
  return h;
}

struct Store {
  int fd = -1;
  Meta meta{};
  std::vector<uint64_t> meta_ids;  // inline free+pending ids of `meta`
  // Commit-scoped state:
  std::vector<uint64_t> free_now;   // allocatable this commit
  std::vector<uint64_t> freed;      // freed this commit -> pending
  uint64_t next_page = 2;
  // Sticky IO/corruption flag for the current operation: every writer
  // and the apply-path readers set it on failure, and rw_flush refuses
  // to flip the meta when it is set (review finding: a short pwrite —
  // ENOSPC — previously still committed a root referencing the missing
  // page, silently corrupting the durable snapshot).
  mutable bool io_error = false;

  bool read_page(uint64_t id, uint8_t* buf) const {
    if (::pread(fd, buf, PAGE, off_t(id) * PAGE) == ssize_t(PAGE))
      return true;
    io_error = true;
    return false;
  }
  bool write_page(uint64_t id, const uint8_t* buf) const {
    if (::pwrite(fd, buf, PAGE, off_t(id) * PAGE) == ssize_t(PAGE))
      return true;
    io_error = true;
    return false;
  }
  uint64_t alloc() {
    if (!free_now.empty()) {
      uint64_t id = free_now.back();
      free_now.pop_back();
      return id;
    }
    return next_page++;
  }
  void free_page(uint64_t id) { freed.push_back(id); }
};

// -- little struct readers/writers on page buffers ---------------------------

struct W {
  uint8_t* p;
  size_t pos = 0;
  void u8(uint8_t v) { p[pos++] = v; }
  void u32(uint32_t v) { memcpy(p + pos, &v, 4); pos += 4; }
  void u64(uint64_t v) { memcpy(p + pos, &v, 8); pos += 8; }
  void bytes(const uint8_t* b, size_t n) { memcpy(p + pos, b, n); pos += n; }
};

struct R {
  const uint8_t* p;
  size_t pos = 0;
  uint8_t u8() { return p[pos++]; }
  uint32_t u32() { uint32_t v; memcpy(&v, p + pos, 4); pos += 4; return v; }
  uint64_t u64() { uint64_t v; memcpy(&v, p + pos, 8); pos += 8; return v; }
};

using Key = std::string;

struct LeafCell {
  Key key;
  std::string value;      // inline value, or empty when overflow
  uint64_t overflow = 0;  // overflow chain head (flags & F_OVERFLOW)
  uint64_t vlen = 0;      // total value length (overflow case)
};

struct Entry {  // internal-node entry
  Key min_key;
  uint64_t child;
};

size_t leaf_cell_size(const LeafCell& c) {
  size_t inline_v = c.overflow ? 8 : c.value.size();
  return 4 + 1 + 4 + c.key.size() + inline_v;
}

size_t entry_size(const Entry& e) { return 4 + 8 + e.min_key.size(); }

constexpr size_t HDR = 1 + 4;  // type + count

// -- page codecs -------------------------------------------------------------

void write_leaf(Store& s, uint64_t id, const std::vector<LeafCell>& cells) {
  std::vector<uint8_t> buf(PAGE, 0);
  W w{buf.data()};
  w.u8(LEAF);
  w.u32(uint32_t(cells.size()));
  for (const auto& c : cells) {
    w.u32(uint32_t(c.key.size()));
    w.u8(c.overflow ? F_OVERFLOW : 0);
    w.u32(uint32_t(c.overflow ? c.vlen : c.value.size()));
    w.bytes(reinterpret_cast<const uint8_t*>(c.key.data()), c.key.size());
    if (c.overflow) {
      w.u64(c.overflow);
    } else {
      w.bytes(reinterpret_cast<const uint8_t*>(c.value.data()),
              c.value.size());
    }
  }
  s.write_page(id, buf.data());
}

bool read_leaf(const Store& s, uint64_t id, std::vector<LeafCell>& out) {
  std::vector<uint8_t> buf(PAGE);
  if (!s.read_page(id, buf.data())) return false;
  R r{buf.data()};
  if (r.u8() != LEAF) return false;
  uint32_t n = r.u32();
  out.clear();
  out.reserve(n);
  for (uint32_t i = 0; i < n; i++) {
    LeafCell c;
    uint32_t klen = r.u32();
    uint8_t flags = r.u8();
    uint32_t vlen = r.u32();
    c.key.assign(reinterpret_cast<const char*>(buf.data() + r.pos), klen);
    r.pos += klen;
    if (flags & F_OVERFLOW) {
      c.overflow = r.u64();
      c.vlen = vlen;
    } else {
      c.value.assign(reinterpret_cast<const char*>(buf.data() + r.pos), vlen);
      r.pos += vlen;
    }
    out.push_back(std::move(c));
  }
  return true;
}

void write_internal(Store& s, uint64_t id, const std::vector<Entry>& es) {
  std::vector<uint8_t> buf(PAGE, 0);
  W w{buf.data()};
  w.u8(INTERNAL);
  w.u32(uint32_t(es.size()));
  for (const auto& e : es) {
    w.u32(uint32_t(e.min_key.size()));
    w.u64(e.child);
    w.bytes(reinterpret_cast<const uint8_t*>(e.min_key.data()),
            e.min_key.size());
  }
  s.write_page(id, buf.data());
}

uint8_t page_type(const Store& s, uint64_t id) {
  uint8_t b;
  if (::pread(s.fd, &b, 1, off_t(id) * PAGE) != 1) {
    s.io_error = true;  // unknown subtree must fail the op, not vanish
    return 0;
  }
  return b;
}

bool read_internal(const Store& s, uint64_t id, std::vector<Entry>& out) {
  std::vector<uint8_t> buf(PAGE);
  if (!s.read_page(id, buf.data())) return false;
  R r{buf.data()};
  if (r.u8() != INTERNAL) return false;
  uint32_t n = r.u32();
  out.clear();
  out.reserve(n);
  for (uint32_t i = 0; i < n; i++) {
    Entry e;
    uint32_t klen = r.u32();
    e.child = r.u64();
    e.min_key.assign(reinterpret_cast<const char*>(buf.data() + r.pos), klen);
    r.pos += klen;
    out.push_back(std::move(e));
  }
  return true;
}

// -- overflow chains ---------------------------------------------------------

uint64_t write_overflow(Store& s, const std::string& v) {
  constexpr size_t CAP = PAGE - (1 + 4 + 8);
  uint64_t head = 0, prev = 0;
  std::vector<uint8_t> buf;
  for (size_t off = 0; off < v.size() || off == 0; off += CAP) {
    size_t n = std::min(CAP, v.size() - off);
    uint64_t id = s.alloc();
    buf.assign(PAGE, 0);
    W w{buf.data()};
    w.u8(OVERFLOW_PAGE);
    w.u32(uint32_t(n));
    w.u64(0);  // next — patched below
    w.bytes(reinterpret_cast<const uint8_t*>(v.data()) + off, n);
    s.write_page(id, buf.data());
    if (prev) {  // patch prev.next
      std::vector<uint8_t> pb(PAGE);
      s.read_page(prev, pb.data());
      memcpy(pb.data() + 1 + 4, &id, 8);
      s.write_page(prev, pb.data());
    } else {
      head = id;
    }
    prev = id;
    if (v.size() == 0) break;
  }
  return head;
}

bool read_overflow(const Store& s, uint64_t head, uint64_t vlen,
                   std::string& out) {
  out.clear();
  out.reserve(vlen);
  std::vector<uint8_t> buf(PAGE);
  for (uint64_t id = head; id;) {
    if (!s.read_page(id, buf.data())) return false;
    R r{buf.data()};
    if (r.u8() != OVERFLOW_PAGE) return false;
    uint32_t n = r.u32();
    uint64_t next = r.u64();
    out.append(reinterpret_cast<const char*>(buf.data() + r.pos), n);
    id = next;
  }
  return out.size() == vlen;
}

void free_overflow(Store& s, uint64_t head) {
  std::vector<uint8_t> buf(PAGE);
  for (uint64_t id = head; id;) {
    if (!s.read_page(id, buf.data())) return;
    uint64_t next;
    memcpy(&next, buf.data() + 1 + 4, 8);
    s.free_page(id);
    id = next;
  }
}

// -- freelist chains ---------------------------------------------------------

uint64_t write_free_chain(Store& s, const std::vector<uint64_t>& ids) {
  // Chain pages are allocated FRESH (never from the pages being freed —
  // those may still be referenced by the fallback meta).
  if (ids.empty()) return 0;
  constexpr size_t CAP = (PAGE - (1 + 4 + 8)) / 8;
  uint64_t head = 0;
  std::vector<uint8_t> buf;
  for (size_t off = 0; off < ids.size(); off += CAP) {
    size_t n = std::min(CAP, ids.size() - off);
    uint64_t id = s.next_page++;  // always fresh
    buf.assign(PAGE, 0);
    W w{buf.data()};
    w.u8(FREEPAGE);
    w.u32(uint32_t(n));
    w.u64(head);  // prepend
    for (size_t i = 0; i < n; i++) w.u64(ids[off + i]);
    s.write_page(id, buf.data());
    head = id;
  }
  return head;
}

bool read_free_chain(const Store& s, uint64_t head,
                     std::vector<uint64_t>& out_ids,
                     std::vector<uint64_t>& out_chain_pages) {
  // The ids INSIDE a chain are allocatable by the caller's rules; the
  // chain PAGES themselves were freshly written by the commit that
  // created the chain and stay reachable from that commit's meta — they
  // are only reusable one commit LATER (callers route them to pending).
  std::vector<uint8_t> buf(PAGE);
  for (uint64_t id = head; id;) {
    if (!s.read_page(id, buf.data())) return false;
    R r{buf.data()};
    if (r.u8() != FREEPAGE) return false;
    uint32_t n = r.u32();
    uint64_t next = r.u64();
    for (uint32_t i = 0; i < n; i++) out_ids.push_back(r.u64());
    out_chain_pages.push_back(id);
    id = next;
  }
  return true;
}

// -- batch ops ---------------------------------------------------------------

struct Op {          // one mutation in a flush batch
  Key key;           // point write (set or tombstone)
  std::string value;
  bool tombstone;
};

struct FlushBatch {
  std::vector<Op> ops;                    // sorted by key
  std::vector<std::pair<Key, Key>> purges;  // sorted [begin, end)
};

void coalesce_purges(std::vector<std::pair<Key, Key>>& purges) {
  // Overlapping/adjacent purges merge so the binary-search membership
  // test below (which only inspects the last range with begin <= k) is
  // exact. The storage server legitimately batches overlapping purges
  // (a moved-away range plus single-key residue purges inside it —
  // review finding: testing only the nearest begin let keys inside a
  // WIDER earlier range survive a clear).
  std::sort(purges.begin(), purges.end());
  std::vector<std::pair<Key, Key>> out;
  for (auto& p : purges) {
    if (p.first >= p.second) continue;  // empty
    if (!out.empty() && p.first <= out.back().second) {
      if (p.second > out.back().second) out.back().second = p.second;
    } else {
      out.push_back(std::move(p));
    }
  }
  purges = std::move(out);
}

bool in_purge(const FlushBatch& b, const Key& k) {
  // purges sorted, coalesced, disjoint: the last with begin <= k decides.
  auto it = std::upper_bound(
      b.purges.begin(), b.purges.end(), k,
      [](const Key& key, const std::pair<Key, Key>& p) {
        return key < p.first;
      });
  if (it == b.purges.begin()) return false;
  --it;
  return k >= it->first && k < it->second;
}

void build_leaves(Store& s, std::vector<LeafCell>& cells,
                  std::vector<Entry>& out) {
  // Pack cells into as few leaves as fit; split points keep every page
  // under PAGE bytes.
  size_t i = 0;
  while (i < cells.size()) {
    size_t used = HDR, j = i;
    std::vector<LeafCell> page;
    while (j < cells.size() && used + leaf_cell_size(cells[j]) <= PAGE) {
      used += leaf_cell_size(cells[j]);
      page.push_back(std::move(cells[j]));
      j++;
    }
    if (page.empty()) {  // oversized cell (guarded at rw_flush; backstop)
      s.io_error = true;
      return;
    }
    uint64_t id = s.alloc();
    Entry e;
    e.min_key = page.front().key;
    e.child = id;
    write_leaf(s, id, page);
    out.push_back(std::move(e));
    i = j;
  }
}

void build_internals(Store& s, std::vector<Entry>& level,
                     std::vector<Entry>& out) {
  size_t i = 0;
  while (i < level.size()) {
    size_t used = HDR, j = i;
    std::vector<Entry> page;
    while (j < level.size() && used + entry_size(level[j]) <= PAGE) {
      used += entry_size(level[j]);
      page.push_back(std::move(level[j]));
      j++;
    }
    uint64_t id = s.alloc();
    Entry e;
    e.min_key = page.front().min_key;
    e.child = id;
    write_internal(s, id, page);
    out.push_back(std::move(e));
    i = j;
  }
}

void free_subtree(Store& s, uint64_t id) {
  uint8_t t = page_type(s, id);
  if (t == INTERNAL) {
    std::vector<Entry> es;
    if (read_internal(s, id, es))
      for (const auto& e : es) free_subtree(s, e.child);
  } else if (t == LEAF) {
    std::vector<LeafCell> cells;
    if (read_leaf(s, id, cells))
      for (const auto& c : cells)
        if (c.overflow) free_overflow(s, c.overflow);
  }
  s.free_page(id);
}

LeafCell make_cell(Store& s, const Key& k, const std::string& v) {
  LeafCell c;
  c.key = k;
  if (v.size() > INLINE_MAX) {
    c.vlen = v.size();
    c.overflow = write_overflow(s, v);
  } else {
    c.value = v;
  }
  return c;
}

// Recursive COW rebuild: apply ops/purges falling in [lo, hi) (hi empty
// = +inf) to the subtree at `id`; emit replacement entries. The old page
// is always freed (its replacement is freshly written).
void apply_rec(Store& s, uint64_t id, const FlushBatch& b,
               size_t op_lo, size_t op_hi, std::vector<Entry>& out) {
  uint8_t t = page_type(s, id);
  if (t == LEAF) {
    std::vector<LeafCell> cells;
    read_leaf(s, id, cells);
    std::vector<LeafCell> merged;
    merged.reserve(cells.size() + (op_hi - op_lo));
    size_t oi = op_lo;
    auto emit_op = [&](size_t k) {
      // Same-flush semantics match the sqlite engine: purges apply
      // FIRST, point writes second — a write inside a purged range
      // survives (kvstore.flush applies them in that order in one txn).
      const Op& op = b.ops[k];
      if (!op.tombstone) merged.push_back(make_cell(s, op.key, op.value));
    };
    for (auto& c : cells) {
      while (oi < op_hi && b.ops[oi].key < c.key) emit_op(oi++);
      bool replaced = oi < op_hi && b.ops[oi].key == c.key;
      if (replaced || in_purge(b, c.key)) {
        if (c.overflow) free_overflow(s, c.overflow);
        if (replaced) emit_op(oi++);
      } else {
        merged.push_back(std::move(c));
      }
    }
    while (oi < op_hi) emit_op(oi++);
    s.free_page(id);
    if (!merged.empty()) build_leaves(s, merged, out);
    return;
  }
  if (t != INTERNAL) return;  // corrupt/unexpected: drop (unreachable)
  std::vector<Entry> es;
  read_internal(s, id, es);
  s.free_page(id);
  std::vector<Entry> children;
  for (size_t ci = 0; ci < es.size(); ci++) {
    const Key& lo = es[ci].min_key;  // child's smallest CONTENT key
    const Key* hi = (ci + 1 < es.size()) ? &es[ci + 1].min_key : nullptr;
    // Ops for this child: everything up to the NEXT child's separator.
    // The leftmost child absorbs ops below its own min_key too — keys
    // smaller than any existing content still belong to its range
    // (skipping them would silently drop writes).
    size_t a = op_lo, z = op_hi;
    size_t e2 = a;
    while (e2 < z && (hi == nullptr || b.ops[e2].key < *hi)) e2++;
    // Whole child inside one purge and no point ops -> free the subtree.
    bool covered = false;
    if (a == e2 && hi != nullptr) {
      for (const auto& pr : b.purges)
        if (pr.first <= lo && *hi <= pr.second) { covered = true; break; }
    }
    if (covered) {
      free_subtree(s, es[ci].child);
    } else if (a == e2 && b.purges.empty()) {
      children.push_back(std::move(es[ci]));  // untouched subtree
    } else if (a == e2) {
      // No point ops, but purges may intersect: check overlap cheaply.
      bool overlap = false;
      for (const auto& pr : b.purges) {
        if (hi != nullptr && pr.first >= *hi) continue;
        if (pr.second <= lo) continue;
        overlap = true;
        break;
      }
      if (overlap) {
        apply_rec(s, es[ci].child, b, a, e2, children);
      } else {
        children.push_back(std::move(es[ci]));
      }
    } else {
      apply_rec(s, es[ci].child, b, a, e2, children);
    }
    op_lo = e2;
  }
  if (!children.empty()) {
    // Repack the children into internal pages.
    build_internals(s, children, out);
  }
}

void scan_rec(const Store& s, uint64_t id,
              void (*cb)(const uint8_t*, uint64_t, const uint8_t*, uint64_t,
                         void*),
              void* ctx) {
  // Any unreadable/corrupt page marks io_error (a silent skip would
  // hand the storage server an INCOMPLETE snapshot at full
  // durable_version — permanent, invisible data loss; review finding).
  uint8_t t = page_type(s, id);
  if (t == LEAF) {
    std::vector<LeafCell> cells;
    if (!read_leaf(s, id, cells)) {
      s.io_error = true;
      return;
    }
    std::string big;
    for (const auto& c : cells) {
      const std::string* v = &c.value;
      if (c.overflow) {
        if (!read_overflow(s, c.overflow, c.vlen, big)) {
          s.io_error = true;
          continue;  // never emit a partial value
        }
        v = &big;
      }
      cb(reinterpret_cast<const uint8_t*>(c.key.data()), c.key.size(),
         reinterpret_cast<const uint8_t*>(v->data()), v->size(), ctx);
    }
  } else if (t == INTERNAL) {
    std::vector<Entry> es;
    if (!read_internal(s, id, es)) {
      s.io_error = true;
      return;
    }
    for (const auto& e : es) scan_rec(s, e.child, cb, ctx);
  } else {
    s.io_error = true;  // tree pointer at a non-tree page
  }
}

bool parse_meta_page(const uint8_t* buf, Meta& m,
                     std::vector<uint64_t>& ids) {
  memcpy(&m, buf, sizeof(Meta));
  if (m.magic != MAGIC) return false;
  size_t n = size_t(m.free_inline) + size_t(m.pending_inline);
  if (n > META_IDS_CAP) return false;
  size_t used = sizeof(Meta) + n * 8;
  std::vector<uint8_t> copy(buf, buf + used);
  memset(copy.data() + offsetof(Meta, checksum), 0, 8);
  if (fnv1a(copy.data(), used) != m.checksum) return false;
  ids.assign(n, 0);
  memcpy(ids.data(), buf + sizeof(Meta), n * 8);
  return true;
}

bool load_meta(Store& s) {
  Meta a{}, b{};
  std::vector<uint64_t> ia, ib;
  bool va = false, vb = false;
  std::vector<uint8_t> buf(PAGE);
  if (s.read_page(0, buf.data())) va = parse_meta_page(buf.data(), a, ia);
  if (s.read_page(1, buf.data())) vb = parse_meta_page(buf.data(), b, ib);
  if (!va && !vb) return false;
  if (!vb || (va && a.seq >= b.seq)) {
    s.meta = a;
    s.meta_ids = std::move(ia);
  } else {
    s.meta = b;
    s.meta_ids = std::move(ib);
  }
  return true;
}

void write_meta(Store& s) {
  size_t n = size_t(s.meta.free_inline) + size_t(s.meta.pending_inline);
  size_t used = sizeof(Meta) + n * 8;
  std::vector<uint8_t> buf(PAGE, 0);
  s.meta.checksum = 0;
  memcpy(buf.data(), &s.meta, sizeof(Meta));
  memcpy(buf.data() + sizeof(Meta), s.meta_ids.data(), n * 8);
  uint64_t ck = fnv1a(buf.data(), used);
  s.meta.checksum = ck;
  memcpy(buf.data() + offsetof(Meta, checksum), &ck, 8);
  s.write_page(s.meta.seq % 2, buf.data());  // alternate slots by seq
}

}  // namespace

extern "C" {

void* rw_open(const char* path) {
  int fd = ::open(path, O_RDWR | O_CREAT, 0644);
  if (fd < 0) return nullptr;
  Store* s = new Store();
  s->fd = fd;
  struct stat st{};
  fstat(fd, &st);
  if (st.st_size >= off_t(2 * PAGE) && load_meta(*s)) {
    s->next_page = s->meta.page_count;
  } else if (st.st_size > off_t(2 * PAGE)) {
    // A file with DATA pages but no valid meta is corruption: refuse
    // rather than silently reinitialize over someone's data (review
    // finding). A file at/below 2 pages never held data (data starts at
    // page 2) — a torn fresh init — and is safely re-initialized below.
    ::close(fd);
    delete s;
    return nullptr;
  } else {
    // Fresh file: seq 0 so the first commit writes slot 1... write both
    // slots so torn half-created files never parse.
    s->meta = Meta{MAGIC, 0, 0, 2, 0, 0, 0, 0};
    s->next_page = 2;
    write_meta(*s);
    s->meta.seq = 1;
    write_meta(*s);
    s->meta.seq = 0;
    ::fsync(fd);
  }
  return s;
}

int64_t rw_durable_version(void* h) {
  return static_cast<Store*>(h)->meta.durable_version;
}

// One atomic flush. Arrays: n point writes (key blob + offsets, value
// blob + offsets; vlen<0 via tomb[i]!=0 = tombstone), m purges (begin/
// end blob + offsets). Returns 0 on success.
int64_t rw_flush(void* h, int64_t n, const uint8_t* kblob,
                 const int64_t* koff, const uint8_t* vblob,
                 const int64_t* voff, const uint8_t* tomb, int64_t m,
                 const uint8_t* pbblob, const int64_t* pboff,
                 const uint8_t* peblob, const int64_t* peoff,
                 int64_t version) {
  Store& s = *static_cast<Store*>(h);
  s.io_error = false;
  // Largest key whose leaf cell (overflow form) still fits a page: a
  // bigger one would make build_leaves spin forever (review finding) —
  // refuse it up front. (Client limit is 10 KB; this is the backstop.)
  const size_t MAX_KEY_BYTES = PAGE - HDR - (4 + 1 + 4 + 8);
  FlushBatch b;
  b.ops.reserve(n);
  for (int64_t i = 0; i < n; i++) {
    Op op;
    op.key.assign(reinterpret_cast<const char*>(kblob + koff[i]),
                  size_t(koff[i + 1] - koff[i]));
    if (op.key.size() > MAX_KEY_BYTES) return -3;
    op.tombstone = tomb[i] != 0;
    if (!op.tombstone)
      op.value.assign(reinterpret_cast<const char*>(vblob + voff[i]),
                      size_t(voff[i + 1] - voff[i]));
    b.ops.push_back(std::move(op));
  }
  std::sort(b.ops.begin(), b.ops.end(),
            [](const Op& a, const Op& c) { return a.key < c.key; });
  for (int64_t i = 0; i < m; i++) {
    b.purges.emplace_back(
        Key(reinterpret_cast<const char*>(pbblob + pboff[i]),
            size_t(pboff[i + 1] - pboff[i])),
        Key(reinterpret_cast<const char*>(peblob + peoff[i]),
            size_t(peoff[i + 1] - peoff[i])));
  }
  coalesce_purges(b.purges);

  if (b.ops.empty() && b.purges.empty()) {
    // Durability-marker-only flush (the storage server's periodic
    // flusher with a clean dirty set): bump the version without
    // COW-rewriting the root (review finding). The freelist carries
    // over unchanged — rotation resumes with the next real commit.
    s.meta.seq += 1;
    s.meta.durable_version = version;
    write_meta(s);
    if (s.io_error || ::fsync(s.fd) != 0) return -1;
    return 0;
  }

  // The pages freed by the LAST commit (pending) become allocatable now
  // (both meta slots are at-or-past that commit); this commit's frees
  // go to pending. Ids live inline in the meta page (free first, then
  // pending); overflow SPILL chain pages are reachable from the
  // fallback meta, so they join pending, never free_now (overwriting
  // one and crashing would corrupt the fallback's freelist, whose
  // stale ids could point at live pages).
  s.free_now.clear();
  s.freed.clear();
  s.free_now.assign(s.meta_ids.begin(), s.meta_ids.end());
  std::vector<uint64_t> chain_pages;
  if (!read_free_chain(s, s.meta.free_head, s.free_now, chain_pages) ||
      !read_free_chain(s, s.meta.pending_head, s.free_now, chain_pages)) {
    return -2;  // corrupt freelist: refuse to guess (fail the flush)
  }
  for (uint64_t id : chain_pages) s.freed.push_back(id);

  std::vector<Entry> roots;
  if (s.meta.root != 0) {
    apply_rec(s, s.meta.root, b, 0, b.ops.size(), roots);
  } else {
    std::vector<LeafCell> cells;
    for (const auto& op : b.ops)
      if (!op.tombstone) cells.push_back(make_cell(s, op.key, op.value));
    if (!cells.empty()) build_leaves(s, cells, roots);
  }
  while (roots.size() > 1) {
    std::vector<Entry> up;
    build_internals(s, roots, up);
    roots = std::move(up);
  }
  uint64_t new_root = roots.empty() ? 0 : roots[0].child;

  // Freelist persistence: inline as much as fits in the meta page
  // (free ids first, pending after); spill only the excess to chains.
  size_t cap = META_IDS_CAP;
  size_t fi = std::min(s.free_now.size(), cap);
  size_t pi = std::min(s.freed.size(), cap - fi);
  std::vector<uint64_t> spill_free(s.free_now.begin() + fi,
                                   s.free_now.end());
  std::vector<uint64_t> spill_pend(s.freed.begin() + pi, s.freed.end());
  uint64_t free_head = write_free_chain(s, spill_free);
  uint64_t pending = write_free_chain(s, spill_pend);
  s.meta_ids.assign(s.free_now.begin(), s.free_now.begin() + fi);
  s.meta_ids.insert(s.meta_ids.end(), s.freed.begin(), s.freed.begin() + pi);

  // Gate: NO meta flip when anything failed to read or write — the old
  // meta (complete snapshot) stays authoritative and the caller sees
  // the error instead of silent corruption.
  if (s.io_error || ::fsync(s.fd) != 0) return -1;
  s.meta.seq += 1;
  s.meta.root = new_root;
  s.meta.page_count = s.next_page;
  s.meta.durable_version = version;
  s.meta.free_head = free_head;
  s.meta.pending_head = pending;
  s.meta.free_inline = uint32_t(fi);
  s.meta.pending_inline = uint32_t(pi);
  write_meta(s);
  if (s.io_error || ::fsync(s.fd) != 0) return -1;
  return 0;
}

// Full ordered scan via callback (load path). Returns 0, or -1 when any
// page failed to read/parse — the snapshot handed back is incomplete
// and the caller must treat the store as corrupt, not as small.
int64_t rw_scan(void* h,
                void (*cb)(const uint8_t*, uint64_t, const uint8_t*,
                           uint64_t, void*),
                void* ctx) {
  Store& s = *static_cast<Store*>(h);
  s.io_error = false;
  if (s.meta.root) scan_rec(s, s.meta.root, cb, ctx);
  return s.io_error ? -1 : 0;
}

int64_t rw_page_count(void* h) {
  return int64_t(static_cast<Store*>(h)->meta.page_count);
}

void rw_close(void* h) {
  Store* s = static_cast<Store*>(h);
  if (s->fd >= 0) ::close(s->fd);
  delete s;
}

}  // extern "C"
