// Production batch packer: resolver wire format -> padded device tensors.
//
// The reference resolver receives ResolveTransactionBatchRequest as flat
// serialized bytes and walks them in C++ (fdbserver/Resolver.actor.cpp +
// ConflictSet.h ConflictBatch::addTransaction). This is the TPU-native
// equivalent: one C pass over the batch blob emits the padded int32 key
// tensors models/conflict_kernel.py consumes, so the Python runtime never
// touches per-transaction objects on the hot path.
//
// Wire format (little-endian, packed tight):
//   per txn:
//     int64  read_version (absolute)
//     int32  n_reads
//     int32  n_writes
//     then n_reads + n_writes ranges (reads first):
//       int32 begin_len, int32 end_len, begin bytes, end bytes
//
// Key packing must match core/keypack.py KeyCodec bit-for-bit: big-endian
// bytes into int32 words, XOR 0x80000000 bias, trailing length column;
// overlong begins truncate down, overlong ends round up to the prefix
// successor (all-0xff prefix -> +inf sentinel). Range-count overflow
// coalesces exactly like models/conflict_set.py _coalesce: stable-sort by
// begin, cover ceil(n/limit)-sized groups.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int32_t INT32_MAX_V = 0x7fffffff;
constexpr int MAX_KEY_BYTES = 256;  // packer scratch bound (codec max)

struct RangeView {
  const uint8_t* b;
  int32_t bl;
  const uint8_t* e;
  int32_t el;
};

int bytecmp(const uint8_t* a, int la, const uint8_t* b, int lb) {
  int n = la < lb ? la : lb;
  int c = std::memcmp(a, b, n);
  if (c) return c;
  return la - lb;
}

// Pack one key into out[0..n_words]: words + length column.
void pack_key(const uint8_t* k, int len, int n_words, bool end_mode,
              int32_t* out) {
  uint8_t tmp[MAX_KEY_BYTES];
  const int maxb = 4 * n_words;
  if (len > maxb) {
    if (end_mode) {
      // Successor of the truncated prefix: drop trailing 0xff, bump last.
      std::memcpy(tmp, k, maxb);
      int i = maxb - 1;
      while (i >= 0 && tmp[i] == 0xff) --i;
      if (i < 0) {  // all-0xff prefix: no successor -> +inf sentinel
        for (int w = 0; w <= n_words; ++w) out[w] = INT32_MAX_V;
        return;
      }
      ++tmp[i];
      len = i + 1;
      k = tmp;
    } else {
      len = maxb;  // begins truncate down
    }
  }
  for (int w = 0; w < n_words; ++w) {
    uint32_t word = 0;
    for (int b = 0; b < 4; ++b) {
      const int idx = 4 * w + b;
      word = (word << 8) | (idx < len ? k[idx] : 0u);
    }
    out[w] = static_cast<int32_t>(word ^ 0x80000000u);
  }
  out[n_words] = len;
}

// Emit up to `limit` slots for `ranges` into row-major [limit, W] tensors,
// mirroring _coalesce: empties dropped; if still over limit, stable-sort by
// begin and cover even groups (group begin, max group end).
void emit_ranges(std::vector<RangeView>& live, int limit, int n_words,
                 int32_t* begin_out, int32_t* end_out, uint8_t* mask_out) {
  const int w = n_words + 1;
  if (static_cast<int>(live.size()) <= limit) {
    for (size_t c = 0; c < live.size(); ++c) {
      pack_key(live[c].b, live[c].bl, n_words, false, begin_out + c * w);
      pack_key(live[c].e, live[c].el, n_words, true, end_out + c * w);
      mask_out[c] = 1;
    }
    return;
  }
  std::stable_sort(live.begin(), live.end(),
                   [](const RangeView& x, const RangeView& y) {
                     return bytecmp(x.b, x.bl, y.b, y.bl) < 0;
                   });
  const int n = static_cast<int>(live.size());
  const int step = (n + limit - 1) / limit;
  int c = 0;
  for (int i = 0; i < n; i += step, ++c) {
    const int hi = std::min(i + step, n);
    const RangeView* best = &live[i];
    for (int j = i + 1; j < hi; ++j)
      if (bytecmp(live[j].e, live[j].el, best->e, best->el) > 0)
        best = &live[j];
    pack_key(live[i].b, live[i].bl, n_words, false, begin_out + c * w);
    pack_key(best->e, best->el, n_words, true, end_out + c * w);
    mask_out[c] = 1;
  }
}

}  // namespace

extern "C" {

// Walks `count` transactions starting at byte `offset`; fills the padded
// batch tensors (callers pass zero/INT32_MAX-prefilled arrays of shape
// B x R x W / B x Q x W / B x R / B x Q / B). Returns the wire offset just
// past the last consumed transaction, or -1 on malformed input / overrun.
int64_t kp_pack_batch(
    const uint8_t* wire, int64_t wire_len, int64_t offset, int count,
    int b_cap, int r_cap, int q_cap, int n_words, int64_t base_version,
    int32_t* read_begin, int32_t* read_end, uint8_t* read_mask,
    int32_t* write_begin, int32_t* write_end, uint8_t* write_mask,
    int32_t* read_version, uint8_t* txn_mask) {
  const int w = n_words + 1;
  if (count > b_cap) return -1;
  // pack_key's truncation scratch is MAX_KEY_BYTES — a wider codec would
  // smash the stack on overlong wire keys. Reject the config, not the key.
  if (n_words <= 0 || 4 * n_words > MAX_KEY_BYTES) return -1;
  std::vector<RangeView> reads, writes;
  for (int t = 0; t < count; ++t) {
    if (offset + 16 > wire_len) return -1;
    int64_t rv;
    int32_t n_reads, n_writes;
    std::memcpy(&rv, wire + offset, 8);
    std::memcpy(&n_reads, wire + offset + 8, 4);
    std::memcpy(&n_writes, wire + offset + 12, 4);
    offset += 16;
    if (n_reads < 0 || n_writes < 0) return -1;
    // All arithmetic below in int64: hostile 32-bit counts/lengths must
    // not overflow int before the bounds checks run (this parser is the
    // RPC trust boundary).
    const int64_t n_ranges = static_cast<int64_t>(n_reads) + n_writes;

    reads.clear();
    writes.clear();
    for (int64_t i = 0; i < n_ranges; ++i) {
      if (offset + 8 > wire_len) return -1;
      int32_t bl, el;
      std::memcpy(&bl, wire + offset, 4);
      std::memcpy(&el, wire + offset + 4, 4);
      offset += 8;
      if (bl < 0 || el < 0 ||
          static_cast<int64_t>(bl) + el > wire_len - offset)
        return -1;
      RangeView v{wire + offset, bl, wire + offset + bl, el};
      offset += static_cast<int64_t>(bl) + el;
      if (bytecmp(v.b, v.bl, v.e, v.el) < 0)  // drop empty ranges
        (i < n_reads ? reads : writes).push_back(v);
    }

    // Relative read version, clamped like _rel_read (ancient readers -> -1,
    // strictly below every window floor -> TOO_OLD). A version beyond int32
    // is rejected: the Python object path raises on the same input, and a
    // silent wrap would turn a far-future reader into a recent one.
    const int64_t rel = rv - base_version;
    if (rel > 0x7fffffffLL) return -1;
    txn_mask[t] = 1;
    read_version[t] = static_cast<int32_t>(rel < -1 ? -1 : rel);
    emit_ranges(reads, r_cap, n_words, read_begin + t * r_cap * w,
                read_end + t * r_cap * w, read_mask + t * r_cap);
    emit_ranges(writes, q_cap, n_words, write_begin + t * q_cap * w,
                write_end + t * q_cap * w, write_mask + t * q_cap);
  }
  return offset;
}

// Fused window pack: k consecutive batches of `count` txns each, plus each
// batch's sorted-unique endpoint-key dictionary and per-slot ranks — the
// full host half of the packed window path (models/conflict_set.py
// pack_wire_window + _pack_dict) in one C pass, so packing window N+2 on
// the packer thread never stalls the device on window N+1 (the speculative
// pipeline's host half). Tensor arguments carry a [k] leading axis; callers
// prefill them exactly like kp_pack_batch's (masked slots all-INT32_MAX, so
// they dedup into the +inf dictionary row by construction). dict_keys is
// [k, n+1, W] for n = 2*B*(R+Q) input rows — the unique count can never
// reach n+1, so the +inf padding row the kernel parks masked slots on
// always survives. Rank order must match models/conflict_set.py
// pack_rank_dictionary bit-for-bit: rows compare lexicographically by
// SIGNED int32 words (the packing bias makes that equal to key byte order;
// the trailing length column is a small non-negative int in both).
// Returns the wire offset past the last batch, or -1 on malformed input.
int64_t kp_pack_window(
    const uint8_t* wire, int64_t wire_len, int64_t offset, int k, int count,
    int b_cap, int r_cap, int q_cap, int n_words, int64_t base_version,
    int32_t* read_begin, int32_t* read_end, uint8_t* read_mask,
    int32_t* write_begin, int32_t* write_end, uint8_t* write_mask,
    int32_t* read_version, uint8_t* txn_mask,
    int32_t* dict_keys, int32_t* rb_rank, int32_t* re_rank,
    int32_t* wb_rank, int32_t* we_rank) {
  const int w = n_words + 1;
  const int64_t nr = static_cast<int64_t>(b_cap) * r_cap;
  const int64_t nq = static_cast<int64_t>(b_cap) * q_cap;
  const int64_t n = 2 * (nr + nq);
  const int64_t pad_rows = n + 1;
  std::vector<int32_t> idx(n);
  std::vector<int32_t> rank_of(n);
  for (int i = 0; i < k; ++i) {
    int32_t* rb = read_begin + i * nr * w;
    int32_t* re = read_end + i * nr * w;
    int32_t* wb = write_begin + i * nq * w;
    int32_t* we = write_end + i * nq * w;
    offset = kp_pack_batch(wire, wire_len, offset, count, b_cap, r_cap,
                           q_cap, n_words, base_version, rb, re,
                           read_mask + i * nr, wb, we, write_mask + i * nq,
                           read_version + static_cast<int64_t>(i) * b_cap,
                           txn_mask + static_cast<int64_t>(i) * b_cap);
    if (offset < 0) return -1;
    // Flat dictionary-input row j, section order rb/re/wb/we (the order
    // _pack_dict concatenates — ranks scatter back by the same layout).
    auto row = [&](int64_t j) -> const int32_t* {
      if (j < nr) return rb + j * w;
      j -= nr;
      if (j < nr) return re + j * w;
      j -= nr;
      if (j < nq) return wb + j * w;
      return we + (j - nq) * w;
    };
    for (int64_t j = 0; j < n; ++j) idx[j] = static_cast<int32_t>(j);
    std::sort(idx.begin(), idx.end(), [&](int32_t a, int32_t b) {
      const int32_t* ra = row(a);
      const int32_t* rb2 = row(b);
      for (int c = 0; c < w; ++c)
        if (ra[c] != rb2[c]) return ra[c] < rb2[c];
      return false;
    });
    int32_t* dict = dict_keys + static_cast<int64_t>(i) * pad_rows * w;
    int32_t u = -1;
    const int32_t* prev = nullptr;
    for (int64_t s = 0; s < n; ++s) {
      const int32_t* r = row(idx[s]);
      if (!prev || std::memcmp(prev, r, w * 4) != 0) {
        ++u;
        std::memcpy(dict + static_cast<int64_t>(u) * w, r, w * 4);
        prev = dict + static_cast<int64_t>(u) * w;
      }
      rank_of[idx[s]] = u;
    }
    for (int64_t j = 0; j < nr; ++j) rb_rank[i * nr + j] = rank_of[j];
    for (int64_t j = 0; j < nr; ++j) re_rank[i * nr + j] = rank_of[nr + j];
    for (int64_t j = 0; j < nq; ++j) wb_rank[i * nq + j] = rank_of[2 * nr + j];
    for (int64_t j = 0; j < nq; ++j)
      we_rank[i * nq + j] = rank_of[2 * nr + nq + j];
  }
  return offset;
}

// Count (and structurally validate) the transactions in [offset, wire_len).
int64_t kp_count_txns(const uint8_t* wire, int64_t wire_len, int64_t offset) {
  int64_t n = 0;
  while (offset < wire_len) {
    if (offset + 16 > wire_len) return -1;
    int32_t n_reads, n_writes;
    std::memcpy(&n_reads, wire + offset + 8, 4);
    std::memcpy(&n_writes, wire + offset + 12, 4);
    offset += 16;
    if (n_reads < 0 || n_writes < 0) return -1;
    const int64_t n_ranges = static_cast<int64_t>(n_reads) + n_writes;
    for (int64_t i = 0; i < n_ranges; ++i) {
      if (offset + 8 > wire_len) return -1;
      int32_t bl, el;
      std::memcpy(&bl, wire + offset, 4);
      std::memcpy(&el, wire + offset + 4, 4);
      offset += 8;
      if (bl < 0 || el < 0 ||
          static_cast<int64_t>(bl) + el > wire_len - offset)
        return -1;
      offset += static_cast<int64_t>(bl) + el;
    }
    ++n;
  }
  return n;
}

}  // extern "C"
