// C client API: an fdb_c-shaped surface over a native embedded MVCC engine.
//
// Reference: bindings/c/fdb_c.cpp — the C ABI every reference binding
// (Python/Java/Go/Ruby) builds on. This is the framework's equivalent
// surface: database/transaction handles, gets/sets/clears/atomic ops,
// snapshot reads, conflict ranges, optimistic commit with the same error
// codes (1020 not_committed, 1007 transaction_too_old, 2011 used_during_
// commit), and fdb_error_predicate-style retryability — implemented over an
// in-process MVCC store with a step-function write history, the same
// conflict-checking design as the device kernel (models/conflict_kernel.py)
// and the skiplist baseline (native/skiplist.cpp).
//
// Transaction semantics mirror the reference client:
// - reads are snapshot-at-read-version with read-your-writes overlay
// - non-snapshot reads add read conflict ranges
// - commit conflict-checks reads against writes committed after the
//   transaction's read version, then paints its writes at the new version
// - atomic ops fold little-endian per fdbclient/Atomic.h (see
//   core/mutations.py apply_atomic for the shared semantics)
//
// Built by native/__init__.py (g++ → lib, dlopen'd via ctypes); the Python
// wrapper is client/embedded.py.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <optional>
#include <string>
#include <vector>

namespace {

using Key = std::string;
using Val = std::string;

// Error codes (flow/error_definitions.h values).
constexpr int ERR_OK = 0;
constexpr int ERR_TOO_OLD = 1007;
constexpr int ERR_NOT_COMMITTED = 1020;
constexpr int ERR_COMMIT_UNKNOWN = 1021;
constexpr int ERR_USED_DURING_COMMIT = 2017;
constexpr int ERR_KEY_TOO_LARGE = 2102;
constexpr int ERR_VALUE_TOO_LARGE = 2103;
constexpr int ERR_INVERTED_RANGE = 2005;
constexpr int ERR_CLIENT_INVALID_OP = 2000;

constexpr size_t MAX_KEY_SIZE = 10000;
constexpr size_t MAX_VALUE_SIZE = 100000;
// Conflict history window in commits (the reference's ~5s MVCC window is
// versions-per-second based; an embedded engine counts commits).
constexpr int64_t MVCC_WINDOW = 5'000'000;

// Mutation type codes matching fdbclient/CommitTransaction.h (and
// core/mutations.py MutationType).
enum MutType : int {
  M_SET = 0, M_CLEAR_RANGE = 1, M_ADD = 2, M_AND = 6, M_OR = 7, M_XOR = 8,
  M_APPEND_IF_FITS = 9, M_MAX = 12, M_MIN = 13, M_BYTE_MIN = 16,
  M_BYTE_MAX = 17, M_MIN_V2 = 18, M_AND_V2 = 19, M_COMPARE_AND_CLEAR = 20,
};

// -- little-endian arithmetic on byte strings (fdbclient/Atomic.h) ----------
// Byte-wise over the FULL operand width (no 8-byte cap) so results match
// core/mutations.py apply_atomic, which uses arbitrary-precision ints.

std::string fit(const std::string& s, size_t n) {
  std::string out = s.substr(0, std::min(n, s.size()));
  out.resize(n, '\0');
  return out;
}

std::string le_add(const std::string& a, const std::string& b, size_t n) {
  std::string out(n, '\0');
  unsigned carry = 0;
  for (size_t i = 0; i < n; ++i) {
    unsigned s = static_cast<unsigned char>(i < a.size() ? a[i] : 0) +
                 static_cast<unsigned char>(i < b.size() ? b[i] : 0) + carry;
    out[i] = static_cast<char>(s & 0xff);
    carry = s >> 8;
  }
  return out;  // overflow past n bytes drops, as in the Python model
}

template <typename F>
std::string bytewise(const std::string& a, const std::string& b, size_t n, F op) {
  std::string out(n, '\0');
  for (size_t i = 0; i < n; ++i)
    out[i] = static_cast<char>(op(
        static_cast<unsigned char>(i < a.size() ? a[i] : 0),
        static_cast<unsigned char>(i < b.size() ? b[i] : 0)));
  return out;
}

// Compare two n-byte little-endian magnitudes: <0, 0, >0.
int le_cmp(const std::string& a, const std::string& b, size_t n) {
  for (size_t i = n; i-- > 0;) {
    unsigned char ca = i < a.size() ? a[i] : 0, cb = i < b.size() ? b[i] : 0;
    if (ca != cb) return ca < cb ? -1 : 1;
  }
  return 0;
}

std::optional<Val> apply_atomic(int op, const std::optional<Val>& existing,
                                const std::string& p) {
  const size_t n = p.size();
  switch (op) {
    case M_ADD:
      return le_add(fit(existing.value_or(""), n), p, n);
    case M_AND: case M_AND_V2:
      if (!existing) return p;
      return bytewise(fit(*existing, n), p, n,
                      [](unsigned char a, unsigned char b) { return a & b; });
    case M_OR:
      return bytewise(fit(existing.value_or(""), n), p, n,
                      [](unsigned char a, unsigned char b) { return a | b; });
    case M_XOR:
      return bytewise(fit(existing.value_or(""), n), p, n,
                      [](unsigned char a, unsigned char b) { return a ^ b; });
    case M_APPEND_IF_FITS: {
      std::string cur = existing.value_or("");
      return (cur.size() + p.size() <= MAX_VALUE_SIZE) ? cur + p : cur;
    }
    case M_MAX: {
      if (!existing) return p;
      std::string cur = fit(*existing, n);
      return le_cmp(cur, p, n) > 0 ? cur : p;
    }
    case M_MIN: case M_MIN_V2: {
      if (!existing) return p;
      std::string cur = fit(*existing, n);
      return le_cmp(cur, p, n) < 0 ? cur : p;
    }
    case M_BYTE_MIN:
      if (!existing) return p;
      return std::min(*existing, p);
    case M_BYTE_MAX:
      if (!existing) return p;
      return std::max(*existing, p);
    case M_COMPARE_AND_CLEAR:
      if (existing && *existing == p) return std::nullopt;  // clear
      return existing;
    default:
      return existing;
  }
}

// -- the embedded database ---------------------------------------------------

struct Database {
  std::mutex mu;
  int64_t version = 0;  // last committed version
  // MVCC store: per-key version chains (version, value-or-tombstone).
  std::map<Key, std::vector<std::pair<int64_t, std::optional<Val>>>> chains;
  // Write-history step function over the keyspace: boundary -> last write
  // version of the segment [boundary, next boundary). The "" boundary
  // covers the start of keyspace (same design as the device kernel state).
  std::map<Key, int64_t> history{{"", -1}};

  int64_t window = MVCC_WINDOW;  // adjustable (tests shrink it to hit GC)

  int64_t oldest() const { return std::max<int64_t>(0, version - window); }

  std::optional<Val> read(const Key& k, int64_t at) const {
    auto it = chains.find(k);
    if (it == chains.end()) return std::nullopt;
    const auto& chain = it->second;
    // Last entry with version <= at.
    auto pos = std::upper_bound(
        chain.begin(), chain.end(), at,
        [](int64_t v, const auto& e) { return v < e.first; });
    if (pos == chain.begin()) return std::nullopt;
    return std::prev(pos)->second;
  }

  // Max write version over [b, e) per the step function; an empty interval
  // has no writes (the reference treats empty conflict ranges as no-ops).
  int64_t range_max_version(const Key& b, const Key& e) const {
    if (b >= e) return -1;
    auto it = history.upper_bound(b);
    --it;  // segment containing b ("" sentinel guarantees validity)
    int64_t best = it->second;
    for (++it; it != history.end() && it->first < e; ++it)
      best = std::max(best, it->second);
    return best;
  }

  // Sweep abandoned chains: per-key GC in write_at only fires on the NEXT
  // write to that key, so a key cleared and never touched again keeps a
  // one-entry tombstone chain forever. Periodically drop chains that are
  // entirely below the floor and end in a tombstone (unreadable at every
  // admissible version), and prune the expired prefix of the rest.
  void sweep_chains() {
    const int64_t floor = oldest();
    for (auto it = chains.begin(); it != chains.end();) {
      auto& chain = it->second;
      auto pos = std::upper_bound(
          chain.begin(), chain.end(), floor,
          [](int64_t f, const auto& e) { return f < e.first; });
      if (pos != chain.begin()) {
        auto keep = std::prev(pos);
        chain.erase(chain.begin(), keep->second ? keep : pos);
      }
      if (chain.empty())
        it = chains.erase(it);
      else
        ++it;
    }
  }

  // Merge adjacent expired history segments: any version below the MVCC
  // floor is unreachable (commit rejects read_version < oldest first), so
  // expired segments are interchangeable — clamp them to -1 and coalesce
  // runs, bounding the boundary map under sustained painting.
  void coalesce_history() {
    const int64_t floor = oldest();
    bool prev_expired = false;
    for (auto it = history.begin(); it != history.end();) {
      const bool expired = it->second < floor;
      if (expired) it->second = -1;
      if (expired && prev_expired && it != history.begin())
        it = history.erase(it);
      else {
        prev_expired = expired;
        ++it;
      }
    }
  }

  // Paint [b, e) with `ver` (split segments at both ends).
  void paint(const Key& b, const Key& e, int64_t ver) {
    if (b >= e) return;
    // Preserve the pre-paint value from e rightward: if no boundary sits at
    // e, split the segment containing e (prev(upper_bound(e)) is its start;
    // the "" sentinel guarantees it exists).
    if (!history.count(e)) {
      int64_t at_e = std::prev(history.upper_bound(e))->second;
      history[e] = at_e;
    }
    // Replace all boundaries in [b, e) with one segment [b, e) -> ver.
    history.erase(history.lower_bound(b), history.lower_bound(e));
    history[b] = ver;
  }
};

struct RangeResult {
  std::vector<std::pair<Key, Val>> kvs;
  bool more = false;
};

struct Transaction {
  Database* db;
  int64_t read_version = -1;  // lazily acquired
  int64_t committed_version = -1;
  bool committed = false;
  int last_error = ERR_OK;

  // RYW overlay: program-order per-key outcome, either a known value
  // ("value" entry; nullopt = cleared) or a pending atomic-op fold.
  struct Overlay {
    bool is_ops = false;
    std::optional<Val> value;
    std::vector<std::pair<int, std::string>> ops;
  };
  std::map<Key, Overlay> overlay;
  std::vector<std::pair<Key, Key>> clears;  // cleared ranges
  std::vector<std::pair<Key, Key>> read_ranges;
  std::vector<std::pair<Key, Key>> write_ranges;
  // Mutation log in program order for commit: (type, key/begin, val/end).
  std::vector<std::tuple<int, std::string, std::string>> mutations;
  // Arena for values handed out to C callers (valid until reset/destroy).
  // deque, not vector: element addresses must be stable across push_back
  // (vector reallocation would move SSO string buffers and dangle earlier
  // returned pointers).
  std::deque<std::string> arena;
  std::vector<RangeResult*> ranges;

  ~Transaction() { reset(); }

  void reset() {
    read_version = -1;
    committed_version = -1;
    committed = false;
    last_error = ERR_OK;
    overlay.clear();
    clears.clear();
    read_ranges.clear();
    write_ranges.clear();
    mutations.clear();
    arena.clear();
    for (auto* r : ranges) delete r;
    ranges.clear();
  }

  int64_t grv() {
    if (read_version < 0) {
      std::lock_guard<std::mutex> g(db->mu);
      read_version = db->version;
    }
    return read_version;
  }

  bool covered_by_clear(const Key& k) const {
    for (const auto& [b, e] : clears)
      if (b <= k && k < e) return true;
    return false;
  }

  // Snapshot + overlay read (the RYW contract).
  int get(const Key& k, bool snapshot, std::optional<Val>* out) {
    if (k.size() > MAX_KEY_SIZE) return ERR_KEY_TOO_LARGE;
    grv();
    {
      std::lock_guard<std::mutex> g(db->mu);
      if (read_version < db->oldest()) return ERR_TOO_OLD;
      auto ov = overlay.find(k);
      if (ov != overlay.end() && !ov->second.is_ops) {
        *out = ov->second.value;
        return ERR_OK;  // known locally: no conflict range (reference RYW)
      }
      std::optional<Val> base =
          covered_by_clear(k) ? std::nullopt : db->read(k, read_version);
      if (ov != overlay.end()) {
        for (const auto& [op, p] : ov->second.ops) base = apply_atomic(op, base, p);
      }
      *out = base;
    }
    if (!snapshot) {
      Key end = k;
      end.push_back('\0');
      read_ranges.emplace_back(k, end);
    }
    return ERR_OK;
  }

  int get_range(const Key& b, const Key& e, int limit, bool reverse,
                bool snapshot, RangeResult* out) {
    if (b > e) return ERR_INVERTED_RANGE;
    grv();
    std::vector<Key> keys;
    {
      std::lock_guard<std::mutex> g(db->mu);
      if (read_version < db->oldest()) return ERR_TOO_OLD;
      for (auto it = db->chains.lower_bound(b);
           it != db->chains.end() && it->first < e; ++it)
        keys.push_back(it->first);
      const size_t n_store = keys.size();  // sorted prefix (map order)
      for (const auto& [k, ov] : overlay) {
        (void)ov;
        if (b <= k && k < e &&
            !std::binary_search(keys.begin(), keys.begin() + n_store, k))
          keys.push_back(k);
      }
      std::sort(keys.begin(), keys.end());
      if (reverse) std::reverse(keys.begin(), keys.end());
      for (const auto& k : keys) {
        std::optional<Val> v;
        auto ov = overlay.find(k);
        if (ov != overlay.end() && !ov->second.is_ops) {
          v = ov->second.value;
        } else {
          v = covered_by_clear(k) ? std::nullopt : db->read(k, read_version);
          if (ov != overlay.end())
            for (const auto& [op, p] : ov->second.ops) v = apply_atomic(op, v, p);
        }
        if (v) {
          out->kvs.emplace_back(k, *v);
          if (limit > 0 && static_cast<int>(out->kvs.size()) >= limit) {
            out->more = true;
            break;
          }
        }
      }
    }
    if (!snapshot) {
      // Trim the conflict range to what was actually scanned when a limit
      // truncated the read (reference RYW does the same) — otherwise a
      // paginated scan conflicts with writes beyond the page it saw.
      if (!out->more || out->kvs.empty()) {
        read_ranges.emplace_back(b, e);
      } else if (!reverse) {
        read_ranges.emplace_back(b, out->kvs.back().first + std::string(1, '\0'));
      } else {
        read_ranges.emplace_back(out->kvs.back().first, e);
      }
    }
    return ERR_OK;
  }

  void set(const Key& k, const Val& v) {
    overlay[k] = Overlay{false, v, {}};
    mutations.emplace_back(M_SET, k, v);
    Key end = k;
    end.push_back('\0');
    write_ranges.emplace_back(k, end);
  }

  void clear(const Key& k) {
    overlay[k] = Overlay{false, std::nullopt, {}};
    mutations.emplace_back(M_CLEAR_RANGE, k, k + std::string(1, '\0'));
    Key end = k;
    end.push_back('\0');
    write_ranges.emplace_back(k, end);
  }

  void clear_range(const Key& b, const Key& e) {
    for (auto it = overlay.lower_bound(b);
         it != overlay.end() && it->first < e;)
      it = overlay.erase(it);
    clears.emplace_back(b, e);
    mutations.emplace_back(M_CLEAR_RANGE, b, e);
    write_ranges.emplace_back(b, e);
  }

  int atomic_op(int op, const Key& k, const std::string& p) {
    switch (op) {
      case M_ADD: case M_AND: case M_OR: case M_XOR: case M_APPEND_IF_FITS:
      case M_MAX: case M_MIN: case M_BYTE_MIN: case M_BYTE_MAX:
      case M_MIN_V2: case M_AND_V2: case M_COMPARE_AND_CLEAR:
        break;
      default:
        return ERR_CLIENT_INVALID_OP;
    }
    auto ov = overlay.find(k);
    if (ov != overlay.end() && !ov->second.is_ops) {
      ov->second.value = apply_atomic(op, ov->second.value, p);  // known base
    } else if (ov != overlay.end()) {
      ov->second.ops.emplace_back(op, p);
    } else {
      Overlay o;
      o.is_ops = true;
      o.ops.emplace_back(op, p);
      overlay[k] = std::move(o);
    }
    mutations.emplace_back(op, k, p);
    Key end = k;
    end.push_back('\0');
    write_ranges.emplace_back(k, end);
    return ERR_OK;
  }

  int commit() {
    if (committed) return ERR_USED_DURING_COMMIT;
    grv();
    std::lock_guard<std::mutex> g(db->mu);
    if (read_version < db->oldest()) return ERR_TOO_OLD;
    // Conflict check: any write committed after our read version that
    // overlaps a read range aborts us (reference resolver semantics).
    for (const auto& [b, e] : read_ranges)
      if (db->range_max_version(b, e) > read_version) return ERR_NOT_COMMITTED;
    // Read-only means no mutations AND no (manual) write conflict ranges —
    // an add_write_conflict_range-only transaction must still paint, or it
    // could never abort anybody (its entire purpose).
    if (mutations.empty() && write_ranges.empty()) {
      committed = true;
      committed_version = read_version;
      return ERR_OK;
    }
    const int64_t ver = ++db->version;
    for (const auto& [op, k, v] : mutations) {
      if (op == M_SET) {
        write_at(k, ver, v);
      } else if (op == M_CLEAR_RANGE) {
        for (auto it = db->chains.lower_bound(k);
             it != db->chains.end() && it->first < v; ++it) {
          if (db->read(it->first, ver)) write_at(it->first, ver, std::nullopt);
        }
      } else {
        write_at(k, ver, apply_atomic(op, db->read(k, ver), v));
      }
    }
    for (const auto& [b, e] : write_ranges) db->paint(b, e, ver);
    if ((ver & 0xFF) == 0) {  // amortised GC
      db->coalesce_history();
      db->sweep_chains();
    }
    committed = true;
    committed_version = ver;
    return ERR_OK;
  }

  void write_at(const Key& k, int64_t ver, const std::optional<Val>& v) {
    auto& chain = db->chains[k];
    // MVCC GC, amortised onto the write path: readers hold versions in
    // [oldest, version], so only the newest entry at-or-below the floor is
    // reachable — drop everything older (and that entry too if it is a
    // tombstone, which reads identically to "no entry"). Chains touched by
    // sustained writes therefore stay O(window) instead of growing forever.
    const int64_t floor = db->oldest();
    auto pos = std::upper_bound(
        chain.begin(), chain.end(), floor,
        [](int64_t f, const auto& e) { return f < e.first; });
    if (pos != chain.begin()) {
      auto keep = std::prev(pos);
      chain.erase(chain.begin(), keep->second ? keep : pos);
    }
    if (!chain.empty() && chain.back().first == ver)
      chain.back().second = v;
    else
      chain.emplace_back(ver, v);
  }
};

}  // namespace

// -- C ABI -------------------------------------------------------------------

extern "C" {

void* fdb_tpu_create_database() { return new Database(); }
void fdb_tpu_destroy_database(void* db) { delete static_cast<Database*>(db); }

int64_t fdb_tpu_database_get_version(void* db) {
  Database* d = static_cast<Database*>(db);
  std::lock_guard<std::mutex> g(d->mu);
  return d->version;
}

void fdb_tpu_database_set_window(void* db, int64_t w) {
  Database* d = static_cast<Database*>(db);
  std::lock_guard<std::mutex> g(d->mu);
  d->window = w;
}

// Diagnostic: total MVCC chain entries + history boundaries. Lets tests
// assert the amortised GC bounds memory under sustained writes.
int64_t fdb_tpu_database_debug_entries(void* db) {
  Database* d = static_cast<Database*>(db);
  std::lock_guard<std::mutex> g(d->mu);
  int64_t n = static_cast<int64_t>(d->history.size());
  for (const auto& [k, chain] : d->chains) n += chain.size();
  return n;
}

void* fdb_tpu_database_create_transaction(void* db) {
  Transaction* t = new Transaction();
  t->db = static_cast<Database*>(db);
  return t;
}

void fdb_tpu_transaction_destroy(void* tr) { delete static_cast<Transaction*>(tr); }
void fdb_tpu_transaction_reset(void* tr) { static_cast<Transaction*>(tr)->reset(); }

int64_t fdb_tpu_transaction_get_read_version(void* tr) {
  return static_cast<Transaction*>(tr)->grv();
}

void fdb_tpu_transaction_set_read_version(void* tr, int64_t v) {
  static_cast<Transaction*>(tr)->read_version = v;
}

int fdb_tpu_transaction_get(void* tr, const uint8_t* key, int klen, int snapshot,
                            const uint8_t** out_val, int* out_len,
                            int* out_present) {
  Transaction* t = static_cast<Transaction*>(tr);
  std::optional<Val> v;
  int err = t->get(Key(reinterpret_cast<const char*>(key), klen), snapshot, &v);
  if (err) return err;
  *out_present = v.has_value() ? 1 : 0;
  if (v) {
    t->arena.push_back(std::move(*v));
    *out_val = reinterpret_cast<const uint8_t*>(t->arena.back().data());
    *out_len = static_cast<int>(t->arena.back().size());
  } else {
    *out_val = nullptr;
    *out_len = 0;
  }
  return ERR_OK;
}

// Range reads: returns a handle; iterate with the accessors below. The
// handle (and all returned pointers) live until transaction reset/destroy.
int fdb_tpu_transaction_get_range(void* tr, const uint8_t* b, int blen,
                                  const uint8_t* e, int elen, int limit,
                                  int reverse, int snapshot, void** out_handle,
                                  int* out_count, int* out_more) {
  Transaction* t = static_cast<Transaction*>(tr);
  RangeResult* r = new RangeResult();
  int err = t->get_range(Key(reinterpret_cast<const char*>(b), blen),
                         Key(reinterpret_cast<const char*>(e), elen), limit,
                         reverse != 0, snapshot != 0, r);
  if (err) {
    delete r;
    return err;
  }
  t->ranges.push_back(r);
  *out_handle = r;
  *out_count = static_cast<int>(r->kvs.size());
  *out_more = r->more ? 1 : 0;
  return ERR_OK;
}

void fdb_tpu_range_kv(void* handle, int i, const uint8_t** k, int* klen,
                      const uint8_t** v, int* vlen) {
  RangeResult* r = static_cast<RangeResult*>(handle);
  const auto& [key, val] = r->kvs[i];
  *k = reinterpret_cast<const uint8_t*>(key.data());
  *klen = static_cast<int>(key.size());
  *v = reinterpret_cast<const uint8_t*>(val.data());
  *vlen = static_cast<int>(val.size());
}

int fdb_tpu_transaction_set(void* tr, const uint8_t* k, int klen,
                            const uint8_t* v, int vlen) {
  if (static_cast<size_t>(klen) > MAX_KEY_SIZE) return ERR_KEY_TOO_LARGE;
  if (static_cast<size_t>(vlen) > MAX_VALUE_SIZE) return ERR_VALUE_TOO_LARGE;
  static_cast<Transaction*>(tr)->set(Key(reinterpret_cast<const char*>(k), klen),
                                     Val(reinterpret_cast<const char*>(v), vlen));
  return ERR_OK;
}

int fdb_tpu_transaction_clear(void* tr, const uint8_t* k, int klen) {
  if (static_cast<size_t>(klen) > MAX_KEY_SIZE) return ERR_KEY_TOO_LARGE;
  static_cast<Transaction*>(tr)->clear(Key(reinterpret_cast<const char*>(k), klen));
  return ERR_OK;
}

int fdb_tpu_transaction_clear_range(void* tr, const uint8_t* b, int blen,
                                    const uint8_t* e, int elen) {
  Key kb(reinterpret_cast<const char*>(b), blen), ke(reinterpret_cast<const char*>(e), elen);
  if (kb > ke) return ERR_INVERTED_RANGE;
  static_cast<Transaction*>(tr)->clear_range(kb, ke);
  return ERR_OK;
}

int fdb_tpu_transaction_atomic_op(void* tr, const uint8_t* k, int klen,
                                  const uint8_t* p, int plen, int op) {
  if (static_cast<size_t>(klen) > MAX_KEY_SIZE) return ERR_KEY_TOO_LARGE;
  return static_cast<Transaction*>(tr)->atomic_op(
      op, Key(reinterpret_cast<const char*>(k), klen),
      std::string(reinterpret_cast<const char*>(p), plen));
}

int fdb_tpu_transaction_add_conflict_range(void* tr, const uint8_t* b, int blen,
                                           const uint8_t* e, int elen,
                                           int write) {
  Transaction* t = static_cast<Transaction*>(tr);
  Key kb(reinterpret_cast<const char*>(b), blen), ke(reinterpret_cast<const char*>(e), elen);
  if (kb > ke) return ERR_INVERTED_RANGE;
  (write ? t->write_ranges : t->read_ranges).emplace_back(kb, ke);
  return ERR_OK;
}

int fdb_tpu_transaction_commit(void* tr, int64_t* out_version) {
  Transaction* t = static_cast<Transaction*>(tr);
  int err = t->commit();
  if (!err) *out_version = t->committed_version;
  return err;
}

int64_t fdb_tpu_transaction_get_committed_version(void* tr) {
  return static_cast<Transaction*>(tr)->committed_version;
}

const char* fdb_tpu_get_error(int code) {
  switch (code) {
    case ERR_OK: return "success";
    case ERR_TOO_OLD: return "transaction_too_old";
    case ERR_NOT_COMMITTED: return "not_committed";
    case ERR_COMMIT_UNKNOWN: return "commit_unknown_result";
    case ERR_USED_DURING_COMMIT: return "used_during_commit";
    case ERR_KEY_TOO_LARGE: return "key_too_large";
    case ERR_VALUE_TOO_LARGE: return "value_too_large";
    case ERR_INVERTED_RANGE: return "inverted_range";
    case ERR_CLIENT_INVALID_OP: return "client_invalid_operation";
    default: return "unknown_error";
  }
}

// predicate 50000 = fdb_error_predicate RETRYABLE (reference fdb_c.h).
int fdb_tpu_error_predicate(int predicate, int code) {
  if (predicate == 50000)
    return code == ERR_NOT_COMMITTED || code == ERR_TOO_OLD ||
           code == ERR_COMMIT_UNKNOWN;
  return 0;
}

}  // extern "C"
