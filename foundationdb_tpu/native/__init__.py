"""Native (C++) components, loaded via ctypes with build-on-first-use.

The reference keeps its hot CPU paths in hand-tuned C++ (fdbserver/SkipList.cpp,
flow's Arena); here the C++ side is the CPU-baseline conflict engine and the
batch key packer. Libraries are compiled once into native/_build/ with g++
(no pip deps), then dlopened.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "_build")
_LOCK = threading.Lock()
_LIBS: dict[str, ctypes.CDLL] = {}


def load_library(name: str) -> ctypes.CDLL:
    """Compile (if stale) and load native/<name>.cpp as lib<name>.so."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        src = os.path.join(_DIR, f"{name}.cpp")
        out = os.path.join(_BUILD, f"lib{name}.so")
        if not os.path.exists(out) or os.path.getmtime(out) < os.path.getmtime(src):
            os.makedirs(_BUILD, exist_ok=True)
            cmd = [
                "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
                "-march=native", src, "-o", out,
            ]
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        lib = ctypes.CDLL(out)
        _LIBS[name] = lib
        return lib
