// CPU baseline ConflictSet: a randomized skiplist over keyspace boundaries.
//
// This plays the role of the reference's fdbserver/SkipList.cpp (the
// SSE-tuned skiplist behind newConflictSet()): an ordered step function
// boundary-key -> last-write-version, with MVCC conflict checks and
// range paints. It is written fresh for this repo (no code taken from the
// reference); semantics match foundationdb_tpu/sim/oracle.py exactly, and
// it serves as the "CPU SkipList" side of bench.py's vs_baseline ratio.
//
// Batch semantics note: painting each accepted txn's writes at the batch
// commit version immediately makes the intra-batch read-vs-earlier-write
// rule fall out of the ordinary history check (cv > rv for every txn in the
// batch), so resolve is one sequential pass — exactly how the reference's
// ConflictBatch behaves observably.
//
// Build: g++ -O3 -shared -fPIC skiplist.cpp -o libskiplist.so

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <vector>

namespace {

constexpr int kMaxLevel = 24;
constexpr int64_t kNegVersion = INT64_MIN;

struct Node {
  Node* next[kMaxLevel];  // only [0, level) valid
  int64_t version;        // version of segment [this->key, succ->key)
  int level;
  uint32_t keylen;
  // key bytes follow the struct
  const uint8_t* key() const {
    return reinterpret_cast<const uint8_t*>(this) + sizeof(Node);
  }
};

int cmp_keys(const uint8_t* a, uint32_t alen, const uint8_t* b, uint32_t blen) {
  uint32_t n = alen < blen ? alen : blen;
  int c = n ? std::memcmp(a, b, n) : 0;
  if (c) return c;
  return (alen > blen) - (alen < blen);
}

struct SkipListCS {
  Node* head;  // sentinel: the b"" boundary (version starts at kNegVersion)
  int level = 1;
  uint64_t rng = 0x9e3779b97f4a7c15ull;
  int64_t oldest = 0;
  size_t node_count = 1;
  size_t sweep_watermark = 64;
  std::vector<void*> arena_blocks;
  std::vector<Node*> free_lists[kMaxLevel + 1];

  SkipListCS() {
    head = alloc_node(kMaxLevel, nullptr, 0);
    head->version = kNegVersion;
    for (int i = 0; i < kMaxLevel; i++) head->next[i] = nullptr;
  }
  ~SkipListCS() {
    for (void* b : arena_blocks) std::free(b);
  }

  Node* alloc_node(int lvl, const uint8_t* key, uint32_t keylen) {
    // Reuse freed nodes of sufficient level and key capacity is fiddly;
    // keep it simple: free lists keyed by level, nodes sized for their key.
    // (Freed nodes are only reused when the key fits; otherwise leak until
    // destroy — bounded in practice by the sweep keeping node count low.)
    for (size_t i = 0; i < free_lists[lvl].size(); i++) {
      Node* n = free_lists[lvl][i];
      if (n->keylen >= keylen) {
        free_lists[lvl][i] = free_lists[lvl].back();
        free_lists[lvl].pop_back();
        n->level = lvl;
        n->keylen = keylen;
        if (keylen) std::memcpy(const_cast<uint8_t*>(n->key()), key, keylen);
        return n;
      }
    }
    void* mem = std::malloc(sizeof(Node) + keylen);
    arena_blocks.push_back(mem);
    Node* n = reinterpret_cast<Node*>(mem);
    n->level = lvl;
    n->keylen = keylen;
    if (keylen) std::memcpy(const_cast<uint8_t*>(n->key()), key, keylen);
    return n;
  }

  int random_level() {
    rng ^= rng << 13;
    rng ^= rng >> 7;
    rng ^= rng << 17;
    int lvl = 1;
    uint64_t x = rng;
    while ((x & 1) && lvl < kMaxLevel) {
      lvl++;
      x >>= 1;
    }
    return lvl;
  }

  // Fill update[] with the last node < key at each level; returns update[0].
  Node* find_pred(const uint8_t* key, uint32_t keylen, Node** update) {
    Node* x = head;
    for (int i = level - 1; i >= 0; i--) {
      while (x->next[i] &&
             cmp_keys(x->next[i]->key(), x->next[i]->keylen, key, keylen) < 0)
        x = x->next[i];
      update[i] = x;
    }
    return x;
  }

  // Version in effect at `key` (the floor segment's version).
  int64_t version_at(const uint8_t* key, uint32_t keylen) {
    Node* update[kMaxLevel];
    Node* pred = find_pred(key, keylen, update);
    Node* nxt = pred->next[0];
    if (nxt && cmp_keys(nxt->key(), nxt->keylen, key, keylen) == 0)
      return nxt->version;
    return pred->version;
  }

  // Any segment intersecting [b, e) with version > rv?
  bool check(const uint8_t* b, uint32_t blen, const uint8_t* e, uint32_t elen,
             int64_t rv) {
    Node* update[kMaxLevel];
    Node* pred = find_pred(b, blen, update);
    // Floor segment: pred unless a node sits exactly at b.
    Node* x = pred->next[0];
    if (!(x && cmp_keys(x->key(), x->keylen, b, blen) == 0)) {
      if (pred->version > rv) return true;
    }
    while (x && cmp_keys(x->key(), x->keylen, e, elen) < 0) {
      if (x->version > rv) return true;
      x = x->next[0];
    }
    return false;
  }

  void insert_at(Node** update, const uint8_t* key, uint32_t keylen,
                 int64_t version) {
    int lvl = random_level();
    if (lvl > level) {
      for (int i = level; i < lvl; i++) update[i] = head;
      level = lvl;
    }
    Node* n = alloc_node(lvl, key, keylen);
    n->version = version;
    for (int i = 0; i < lvl; i++) {
      n->next[i] = update[i]->next[i];
      update[i]->next[i] = n;
    }
    node_count++;
  }

  // Paint [b, e) at version cv: boundary at b (version cv), erase interior
  // boundaries, boundary at e restoring the prior version.
  void paint(const uint8_t* b, uint32_t blen, const uint8_t* e, uint32_t elen,
             int64_t cv) {
    if (cmp_keys(b, blen, e, elen) >= 0) return;
    int64_t resume = version_at(e, elen);

    Node* update[kMaxLevel];
    find_pred(b, blen, update);
    Node* x = update[0]->next[0];
    // Node exactly at b? repaint it. Otherwise insert one.
    if (x && cmp_keys(x->key(), x->keylen, b, blen) == 0) {
      x->version = cv;
      for (int i = 0; i < x->level; i++) update[i] = x;
      x = x->next[0];
    } else {
      insert_at(update, b, blen, cv);
      // update[] now stale at low levels; refresh via the inserted node.
      Node* nb = update[0]->next[0];
      for (int i = 0; i < nb->level; i++) update[i] = nb;
      x = nb->next[0];
    }
    // Erase interior nodes in (b, e).
    while (x && cmp_keys(x->key(), x->keylen, e, elen) < 0) {
      Node* victim = x;
      // update[i] is the last surviving node < victim at each level.
      for (int i = 0; i < victim->level; i++)
        update[i]->next[i] = victim->next[i];
      x = victim->next[0];
      free_lists[victim->level].push_back(victim);
      node_count--;
    }
    // Boundary at e (unless one already exists).
    if (!(x && cmp_keys(x->key(), x->keylen, e, elen) == 0)) {
      if (resume != cv) insert_at(update, e, elen, resume);
    }
  }

  // Remove expired + redundant boundaries (segment version == predecessor's).
  void sweep() {
    Node* update[kMaxLevel];
    for (int i = 0; i < level; i++) update[i] = head;
    int64_t prev_version = kNegVersion;
    if (head->version < oldest) head->version = kNegVersion;
    prev_version = head->version;
    Node* x = head->next[0];
    while (x) {
      if (x->version < oldest) x->version = kNegVersion;
      if (x->version == prev_version) {
        for (int i = 0; i < x->level; i++) update[i]->next[i] = x->next[i];
        Node* victim = x;
        x = x->next[0];
        free_lists[victim->level].push_back(victim);
        node_count--;
      } else {
        prev_version = x->version;
        for (int i = 0; i < x->level; i++) update[i] = x;
        x = x->next[0];
      }
    }
    sweep_watermark = node_count < 32 ? 64 : node_count * 2;
  }
};

struct Range {
  const uint8_t* b;
  uint32_t blen;
  const uint8_t* e;
  uint32_t elen;
};

}  // namespace

extern "C" {

void* cs_create() { return new SkipListCS(); }

void cs_destroy(void* p) { delete static_cast<SkipListCS*>(p); }

int64_t cs_node_count(void* p) {
  return static_cast<int64_t>(static_cast<SkipListCS*>(p)->node_count);
}

// Resolve one batch.
//   blob: all key bytes, ranges reference (offset, len) pairs into it.
//   ranges: 4 int64 per range [boff, blen, eoff, elen]; for txn i its read
//     ranges come first, then its write ranges (prefix-summed via counts).
//   verdicts_out: int8 per txn, 0=committed 1=conflict 2=too_old.
void cs_resolve(void* p, const uint8_t* blob, const int64_t* ranges,
                const int32_t* read_counts, const int32_t* write_counts,
                const int64_t* read_versions, int32_t n_txns,
                int64_t commit_version, int64_t oldest_version,
                int8_t* verdicts_out) {
  SkipListCS* cs = static_cast<SkipListCS*>(p);
  if (oldest_version > cs->oldest) cs->oldest = oldest_version;

  size_t ri = 0;  // running range index
  for (int32_t t = 0; t < n_txns; t++) {
    int32_t nr = read_counts[t], nw = write_counts[t];
    const int64_t* rr = ranges + 4 * ri;
    const int64_t* wr = ranges + 4 * (ri + nr);
    ri += nr + nw;

    bool has_reads = false;
    for (int32_t k = 0; k < nr; k++) {
      const int64_t* q = rr + 4 * k;
      if (cmp_keys(blob + q[0], (uint32_t)q[1], blob + q[2], (uint32_t)q[3]) < 0)
        has_reads = true;
    }
    if (has_reads && read_versions[t] < cs->oldest) {
      verdicts_out[t] = 2;
      continue;
    }
    bool conflict = false;
    for (int32_t k = 0; k < nr && !conflict; k++) {
      const int64_t* q = rr + 4 * k;
      if (cmp_keys(blob + q[0], (uint32_t)q[1], blob + q[2], (uint32_t)q[3]) >= 0)
        continue;
      conflict = cs->check(blob + q[0], (uint32_t)q[1], blob + q[2],
                           (uint32_t)q[3], read_versions[t]);
    }
    if (conflict) {
      verdicts_out[t] = 1;
      continue;
    }
    verdicts_out[t] = 0;
    for (int32_t k = 0; k < nw; k++) {
      const int64_t* q = wr + 4 * k;
      cs->paint(blob + q[0], (uint32_t)q[1], blob + q[2], (uint32_t)q[3],
                commit_version);
    }
  }
  if (cs->node_count > cs->sweep_watermark) cs->sweep();
}

}  // extern "C"
