// Networked C client: speaks the runtime's RPC wire protocol over TCP.
//
// The reference's C client (bindings/c/fdb_c.cpp) connects to the cluster
// over the network and drives the full GRV/commit/read path; this is the
// TPU-framework equivalent against runtime/net.py's transport. The frame
// and tag formats mirror runtime/wire.py exactly (length-prefixed frames,
// tagged values, registered message structs); FdbError crosses back as its
// numeric code so C callers see the same retryable error space as Python
// clients.
//
// Blocking, one-outstanding-request-per-connection by design: the C client
// is a foreign-runtime guest without the flow loop; callers wanting
// pipelining open more connections (exactly how fdb_c's network thread is
// the concurrency boundary there).

#include <cstdint>
#include <cstring>
#include <map>
#include <cstdlib>
#include <string>
#include <vector>

#include <arpa/inet.h>
#include <dlfcn.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

namespace {

// -- optional mutual TLS (matching runtime/net.py's transport) ---------------
//
// A TLS-enabled cluster (spec `tls` section) requires every peer —
// including this C client — to complete a mutual handshake (reference:
// the fdb_c client speaks the same TLS as the server via network
// options, flow/TLSConfig.actor.cpp). OpenSSL 3 ships in the image as a
// RUNTIME library only (no headers), so the handful of stable C-ABI
// entry points a blocking client needs is declared here and resolved
// with dlopen on first use.

constexpr int SSL_FILETYPE_PEM_ = 1;
constexpr int SSL_VERIFY_PEER_ = 1;

struct TlsApi {
  void* (*TLS_client_method)() = nullptr;
  void* (*SSL_CTX_new)(void*) = nullptr;
  void (*SSL_CTX_free)(void*) = nullptr;
  int (*SSL_CTX_use_certificate_chain_file)(void*, const char*) = nullptr;
  int (*SSL_CTX_use_PrivateKey_file)(void*, const char*, int) = nullptr;
  int (*SSL_CTX_load_verify_locations)(void*, const char*,
                                       const char*) = nullptr;
  void (*SSL_CTX_set_verify)(void*, int, void*) = nullptr;
  void* (*SSL_new)(void*) = nullptr;
  int (*SSL_set_fd)(void*, int) = nullptr;
  int (*SSL_connect)(void*) = nullptr;
  int (*SSL_read)(void*, void*, int) = nullptr;
  int (*SSL_write)(void*, const void*, int) = nullptr;
  int (*SSL_shutdown)(void*) = nullptr;
  void (*SSL_free)(void*) = nullptr;
  bool ok = false;
};

TlsApi* tls_api() {
  static TlsApi api;
  static bool tried = false;
  if (!tried) {
    tried = true;
    void* h = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (!h) h = dlopen("libssl.so", RTLD_NOW | RTLD_GLOBAL);
    if (h) {
      auto sym = [h](const char* n) { return dlsym(h, n); };
      api.TLS_client_method =
          reinterpret_cast<void* (*)()>(sym("TLS_client_method"));
      api.SSL_CTX_new = reinterpret_cast<void* (*)(void*)>(sym("SSL_CTX_new"));
      api.SSL_CTX_free = reinterpret_cast<void (*)(void*)>(sym("SSL_CTX_free"));
      api.SSL_CTX_use_certificate_chain_file =
          reinterpret_cast<int (*)(void*, const char*)>(
              sym("SSL_CTX_use_certificate_chain_file"));
      api.SSL_CTX_use_PrivateKey_file =
          reinterpret_cast<int (*)(void*, const char*, int)>(
              sym("SSL_CTX_use_PrivateKey_file"));
      api.SSL_CTX_load_verify_locations =
          reinterpret_cast<int (*)(void*, const char*, const char*)>(
              sym("SSL_CTX_load_verify_locations"));
      api.SSL_CTX_set_verify = reinterpret_cast<void (*)(void*, int, void*)>(
          sym("SSL_CTX_set_verify"));
      api.SSL_new = reinterpret_cast<void* (*)(void*)>(sym("SSL_new"));
      api.SSL_set_fd = reinterpret_cast<int (*)(void*, int)>(sym("SSL_set_fd"));
      api.SSL_connect = reinterpret_cast<int (*)(void*)>(sym("SSL_connect"));
      api.SSL_read =
          reinterpret_cast<int (*)(void*, void*, int)>(sym("SSL_read"));
      api.SSL_write =
          reinterpret_cast<int (*)(void*, const void*, int)>(sym("SSL_write"));
      api.SSL_shutdown = reinterpret_cast<int (*)(void*)>(sym("SSL_shutdown"));
      api.SSL_free = reinterpret_cast<void (*)(void*)>(sym("SSL_free"));
      api.ok = api.TLS_client_method && api.SSL_CTX_new && api.SSL_CTX_free &&
               api.SSL_CTX_use_certificate_chain_file &&
               api.SSL_CTX_use_PrivateKey_file &&
               api.SSL_CTX_load_verify_locations && api.SSL_CTX_set_verify &&
               api.SSL_new && api.SSL_set_fd && api.SSL_connect &&
               api.SSL_read && api.SSL_write && api.SSL_shutdown &&
               api.SSL_free;
    }
  }
  return &api;
}

// wire.py tags
constexpr uint8_t T_NONE = 0x00, T_TRUE = 0x01, T_FALSE = 0x02, T_INT = 0x03,
                  T_BIGINT = 0x04, T_FLOAT = 0x05, T_BYTES = 0x06,
                  T_STR = 0x07, T_LIST = 0x08, T_TUPLE = 0x09, T_DICT = 0x0A,
                  T_STRUCT = 0x0B, T_ERROR = 0x0C, T_ERROREX = 0x0D;
// wire.py struct registry ids
constexpr uint16_t S_MUTATION = 1, S_KEYRANGE = 2, S_COMMIT_REQ = 5;

constexpr int64_t ERR_INTERNAL = 1500;   // internal_error
constexpr int64_t ERR_BROKEN = 1100;     // broken_promise (connection lost)

struct Conn {
  int fd = -1;
  void* ssl = nullptr;      // OpenSSL SSL* when the cluster runs TLS
  void* ssl_ctx = nullptr;  // its SSL_CTX*
  uint64_t next_id = 1;
  // Replies that arrived while waiting for a different request id —
  // the pipelining stash (multiple requests in flight on one conn).
  std::map<uint64_t, std::vector<uint8_t>> stash;
};

struct Buf {
  std::vector<uint8_t> d;
  void u8(uint8_t v) { d.push_back(v); }
  void u16(uint16_t v) { put(&v, 2); }
  void u32(uint32_t v) { put(&v, 4); }
  void i64(int64_t v) { put(&v, 8); }
  void put(const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    d.insert(d.end(), b, b + n);
  }
  void tag_int(int64_t v) { u8(T_INT); i64(v); }
  void tag_bool(bool v) { u8(v ? T_TRUE : T_FALSE); }
  void tag_bytes(const uint8_t* p, int64_t n) {
    u8(T_BYTES); u32(static_cast<uint32_t>(n)); put(p, n);
  }
  void tag_str(const char* s) {
    size_t n = strlen(s);
    u8(T_STR); u32(static_cast<uint32_t>(n)); put(s, n);
  }
  void seq_header(uint8_t tag, uint32_t count) { u8(tag); u32(count); }
  void struct_header(uint16_t sid) { u8(T_STRUCT); u16(sid); }
};

// -- reply parsing -----------------------------------------------------------

struct Cur {
  const uint8_t* p;
  size_t n, pos = 0;
  bool ok = true;
  bool need(size_t k) {
    if (pos + k > n) { ok = false; return false; }
    return true;
  }
  uint8_t u8() { if (!need(1)) return 0; return p[pos++]; }
  uint16_t u16() { if (!need(2)) return 0; uint16_t v; memcpy(&v, p + pos, 2); pos += 2; return v; }
  uint32_t u32() { if (!need(4)) return 0; uint32_t v; memcpy(&v, p + pos, 4); pos += 4; return v; }
  int64_t i64() { if (!need(8)) return 0; int64_t v; memcpy(&v, p + pos, 8); pos += 8; return v; }
};

// Generic skip of one tagged value.
bool skip_value(Cur& c) {
  uint8_t t = c.u8();
  if (!c.ok) return false;
  switch (t) {
    case T_NONE: case T_TRUE: case T_FALSE: return true;
    case T_INT: case T_FLOAT: c.i64(); return c.ok;
    case T_BIGINT: {
      uint32_t n = c.u32();
      if (!c.need(1 + n)) return false;
      c.pos += 1 + n;
      return true;
    }
    case T_BYTES: case T_STR: {
      uint32_t n = c.u32();
      if (!c.need(n)) return false;
      c.pos += n;
      return true;
    }
    case T_LIST: case T_TUPLE: {
      uint32_t n = c.u32();
      for (uint32_t i = 0; i < n && c.ok; i++) if (!skip_value(c)) return false;
      return c.ok;
    }
    case T_DICT: {
      uint32_t n = c.u32();
      for (uint32_t i = 0; i < n && c.ok; i++) {
        if (!skip_value(c) || !skip_value(c)) return false;
      }
      return c.ok;
    }
    case T_STRUCT: c.u16(); return skip_value(c);
    case T_ERROR: case T_ERROREX: {
      c.u16();
      uint32_t n = c.u32();
      if (!c.need(n)) return false;
      c.pos += n;
      // T_ERROREX carries a trailing structured payload (e.g. conflicting
      // key ranges); the C surface reports only the code, so skip it.
      if (t == T_ERROREX) return skip_value(c);
      return true;
    }
    default: return false;
  }
}

// -- socket IO ---------------------------------------------------------------

bool conn_write(Conn* c, const uint8_t* p, size_t n) {
  if (c->ssl) {
    TlsApi* t = tls_api();
    while (n) {
      int k = t->SSL_write(c->ssl, p, static_cast<int>(n));
      if (k <= 0) return false;
      p += k;
      n -= static_cast<size_t>(k);
    }
    return true;
  }
  while (n) {
    ssize_t k = ::send(c->fd, p, n, MSG_NOSIGNAL);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

bool conn_read(Conn* c, uint8_t* p, size_t n) {
  if (c->ssl) {
    TlsApi* t = tls_api();
    while (n) {
      int k = t->SSL_read(c->ssl, p, static_cast<int>(n));
      if (k <= 0) return false;
      p += k;
      n -= static_cast<size_t>(k);
    }
    return true;
  }
  while (n) {
    ssize_t k = ::recv(c->fd, p, n, 0);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

// Send one request frame (no wait). Returns false on IO failure.
bool send_frame(Conn* c, const Buf& req) {
  if (c->fd < 0) return false;
  uint32_t len = static_cast<uint32_t>(req.d.size());
  uint8_t hdr[4];
  memcpy(hdr, &len, 4);
  return conn_write(c, hdr, 4) && conn_write(c, req.d.data(), len);
}

// Parse a reply frame (RSP=1, msg_id, ok, value). Fills msg_id; on ok
// positions value_cur at the value and returns 0; on ok=false returns
// the FdbError code negated.
int64_t parse_reply(std::vector<uint8_t>& frame, Cur& value_cur,
                    uint64_t* msg_id) {
  Cur cur{frame.data(), frame.size()};
  if (cur.u8() != T_TUPLE || cur.u32() != 4) return -ERR_INTERNAL;
  if (cur.u8() != T_INT || cur.i64() != 1) return -ERR_INTERNAL;  // kind
  if (cur.u8() != T_INT) return -ERR_INTERNAL;  // msg_id (our ids are ints)
  *msg_id = static_cast<uint64_t>(cur.i64());
  uint8_t okt = cur.u8();
  if (okt == T_FALSE) {
    // value is an FdbError (or anything): extract the code if possible.
    uint8_t et = cur.u8();
    if (et == T_ERROR || et == T_ERROREX) {
      uint16_t code = cur.u16();
      return -static_cast<int64_t>(code ? code : ERR_INTERNAL);
    }
    return -ERR_INTERNAL;
  }
  if (okt != T_TRUE) return -ERR_INTERNAL;
  value_cur = cur;  // positioned at the value
  return 0;
}

// Wait for the reply to `want`: replies for OTHER in-flight requests are
// stashed (pipelining — fdb_c keeps many requests outstanding the same
// way; here ordering is cooperative rather than threaded).
int64_t recv_reply_for(Conn* c, uint64_t want, std::vector<uint8_t>& out,
                       Cur& value_cur) {
  auto it = c->stash.find(want);
  if (it != c->stash.end()) {
    out = std::move(it->second);
    c->stash.erase(it);
    uint64_t id;
    return parse_reply(out, value_cur, &id);
  }
  while (true) {
    if (c->fd < 0) return -ERR_BROKEN;
    uint8_t hdr[4];
    if (!conn_read(c, hdr, 4)) return -ERR_BROKEN;
    uint32_t rlen;
    memcpy(&rlen, hdr, 4);
    if (rlen > (64u << 20)) {
      // Cannot resync without draining the oversized frame: break the
      // conn so later calls fail cleanly instead of parsing stale bytes.
      ::close(c->fd);
      c->fd = -1;
      return -ERR_BROKEN;
    }
    std::vector<uint8_t> frame(rlen);
    if (!conn_read(c, frame.data(), rlen)) return -ERR_BROKEN;
    // Peek the msg_id without consuming the frame.
    Cur cur{frame.data(), frame.size()};
    if (cur.u8() != T_TUPLE || cur.u32() != 4) return -ERR_INTERNAL;
    if (cur.u8() != T_INT || cur.i64() != 1) return -ERR_INTERNAL;
    if (cur.u8() != T_INT) return -ERR_INTERNAL;
    uint64_t id = static_cast<uint64_t>(cur.i64());
    if (id == want) {
      out = std::move(frame);
      uint64_t got;
      return parse_reply(out, value_cur, &got);
    }
    c->stash[id] = std::move(frame);
  }
}

// One round trip: frame out, matching frame in. Returns the reply payload
// (the value inside (RSP, msg_id, ok, value)) via `out`; on ok=false
// returns the FdbError code as a negative number; 0 on success.
int64_t round_trip(Conn* c, const Buf& req, uint64_t id,
                   std::vector<uint8_t>& out, Cur& value_cur) {
  if (!send_frame(c, req)) return -ERR_BROKEN;
  return recv_reply_for(c, id, out, value_cur);
}

uint64_t req_header(Buf& b, Conn* c, const char* service, const char* method,
                    uint32_t n_args) {
  uint64_t id = c->next_id++;
  b.seq_header(T_TUPLE, 5);       // (REQ, msg_id, service, method, args)
  b.tag_int(0);                   // kind = request
  b.tag_int(static_cast<int64_t>(id));
  b.tag_str(service);
  b.tag_str(method);
  b.seq_header(T_LIST, n_args);
  return id;
}

void pack_range(Buf& b, const uint8_t* begin, int64_t blen,
                const uint8_t* end, int64_t elen) {
  b.struct_header(S_KEYRANGE);
  b.seq_header(T_TUPLE, 2);
  b.tag_bytes(begin, blen);
  b.tag_bytes(end, elen);
}

}  // namespace

extern "C" {

void fnet_close(void* h);  // fwd: fnet_connect_tls unwinds through it

void* fnet_connect(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return nullptr;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  Conn* c = new Conn();
  c->fd = fd;
  return c;
}

// TLS variant: mutual TLS with the cluster's CA + this client's cert/key
// (PEM paths — the same material the spec's `tls` section names). The
// server requires a client certificate (CERT_REQUIRED both ways in
// runtime/net.py); we verify the server against `ca` (chain, not
// hostname — processes move, matching the Python transport). Returns
// nullptr on any failure (no OpenSSL runtime, bad key material, refused
// handshake).
void* fnet_connect_tls(const char* host, int port, const char* cert,
                       const char* key, const char* ca) {
  TlsApi* t = tls_api();
  if (!t->ok) return nullptr;
  void* raw = fnet_connect(host, port);
  if (!raw) return nullptr;
  Conn* c = static_cast<Conn*>(raw);
  void* ctx = t->SSL_CTX_new(t->TLS_client_method());
  if (!ctx ||
      t->SSL_CTX_use_certificate_chain_file(ctx, cert) != 1 ||
      t->SSL_CTX_use_PrivateKey_file(ctx, key, SSL_FILETYPE_PEM_) != 1 ||
      t->SSL_CTX_load_verify_locations(ctx, ca, nullptr) != 1) {
    if (ctx) t->SSL_CTX_free(ctx);
    fnet_close(raw);
    return nullptr;
  }
  t->SSL_CTX_set_verify(ctx, SSL_VERIFY_PEER_, nullptr);
  void* ssl = t->SSL_new(ctx);
  if (!ssl || t->SSL_set_fd(ssl, c->fd) != 1 || t->SSL_connect(ssl) != 1) {
    if (ssl) t->SSL_free(ssl);
    t->SSL_CTX_free(ctx);
    fnet_close(raw);
    return nullptr;
  }
  c->ssl = ssl;
  c->ssl_ctx = ctx;
  return c;
}

void fnet_close(void* h) {
  Conn* c = static_cast<Conn*>(h);
  if (!c) return;
  if (c->ssl) {
    TlsApi* t = tls_api();
    t->SSL_shutdown(c->ssl);
    t->SSL_free(c->ssl);
    t->SSL_CTX_free(c->ssl_ctx);
  }
  if (c->fd >= 0) ::close(c->fd);
  delete c;
}

// >= 0: read version; < 0: -fdb_error_code
int64_t fnet_get_read_version(void* h, const char* grv_service) {
  Conn* c = static_cast<Conn*>(h);
  Buf b;
  uint64_t id = req_header(b, c, grv_service, "get_read_version", 0);
  std::vector<uint8_t> reply;
  Cur v{nullptr, 0};
  int64_t rc = round_trip(c, b, id, reply, v);
  if (rc < 0) return rc;
  if (v.u8() != T_INT) return -ERR_INTERNAL;
  return v.i64();
}

// Commit a transaction. Mutations/ranges are flat arrays with offset
// tables (offsets have n+1 entries; item i is bytes [off[i], off[i+1])).
// >= 0: commit version; < 0: -fdb_error_code (e.g. -1020 not_committed).
static uint64_t build_commit_req(
    Buf& b, Conn* c, const char* proxy_service, int64_t read_version,
    int32_t n_mutations, const int32_t* mtypes,
    const uint8_t* p1, const int64_t* p1_off,
    const uint8_t* p2, const int64_t* p2_off,
    int32_t n_reads, const uint8_t* rb, const int64_t* rb_off,
    const uint8_t* re, const int64_t* re_off,
    int32_t n_writes, const uint8_t* wb, const int64_t* wb_off,
    const uint8_t* we, const int64_t* we_off) {
  uint64_t id = req_header(b, c, proxy_service, "commit", 1);
  b.struct_header(S_COMMIT_REQ);
  b.seq_header(T_TUPLE, 5);
  b.tag_int(read_version);
  b.seq_header(T_LIST, static_cast<uint32_t>(n_mutations));
  for (int32_t i = 0; i < n_mutations; i++) {
    b.struct_header(S_MUTATION);
    b.seq_header(T_TUPLE, 3);
    b.tag_int(mtypes[i]);
    b.tag_bytes(p1 + p1_off[i], p1_off[i + 1] - p1_off[i]);
    b.tag_bytes(p2 + p2_off[i], p2_off[i + 1] - p2_off[i]);
  }
  b.seq_header(T_LIST, static_cast<uint32_t>(n_reads));
  for (int32_t i = 0; i < n_reads; i++)
    pack_range(b, rb + rb_off[i], rb_off[i + 1] - rb_off[i],
               re + re_off[i], re_off[i + 1] - re_off[i]);
  b.seq_header(T_LIST, static_cast<uint32_t>(n_writes));
  for (int32_t i = 0; i < n_writes; i++)
    pack_range(b, wb + wb_off[i], wb_off[i + 1] - wb_off[i],
               we + we_off[i], we_off[i + 1] - we_off[i]);
  b.tag_bool(false);  // report_conflicting_keys
  return id;
}

// CommitResult struct: (version, batch_order) -> commit version.
static int64_t parse_commit_value(Cur& v) {
  if (v.u8() != T_STRUCT) return -ERR_INTERNAL;
  v.u16();
  if (v.u8() != T_TUPLE || v.u32() < 1) return -ERR_INTERNAL;
  if (v.u8() != T_INT) return -ERR_INTERNAL;
  return v.i64();
}

int64_t fnet_commit(
    void* h, const char* proxy_service, int64_t read_version,
    int32_t n_mutations, const int32_t* mtypes,
    const uint8_t* p1, const int64_t* p1_off,
    const uint8_t* p2, const int64_t* p2_off,
    int32_t n_reads, const uint8_t* rb, const int64_t* rb_off,
    const uint8_t* re, const int64_t* re_off,
    int32_t n_writes, const uint8_t* wb, const int64_t* wb_off,
    const uint8_t* we, const int64_t* we_off) {
  Conn* c = static_cast<Conn*>(h);
  Buf b;
  uint64_t id = build_commit_req(
      b, c, proxy_service, read_version, n_mutations, mtypes, p1, p1_off,
      p2, p2_off, n_reads, rb, rb_off, re, re_off, n_writes, wb, wb_off,
      we, we_off);
  std::vector<uint8_t> reply;
  Cur v{nullptr, 0};
  int64_t rc = round_trip(c, b, id, reply, v);
  if (rc < 0) return rc;
  return parse_commit_value(v);
}

// Pipelined commit: send without waiting. Returns the request id (> 0)
// or 0 on send failure; pass the id to fnet_commit_wait. Any number of
// sends may be outstanding on one connection; waits may happen in any
// order (replies for other ids are stashed).
uint64_t fnet_commit_send(
    void* h, const char* proxy_service, int64_t read_version,
    int32_t n_mutations, const int32_t* mtypes,
    const uint8_t* p1, const int64_t* p1_off,
    const uint8_t* p2, const int64_t* p2_off,
    int32_t n_reads, const uint8_t* rb, const int64_t* rb_off,
    const uint8_t* re, const int64_t* re_off,
    int32_t n_writes, const uint8_t* wb, const int64_t* wb_off,
    const uint8_t* we, const int64_t* we_off) {
  Conn* c = static_cast<Conn*>(h);
  Buf b;
  uint64_t id = build_commit_req(
      b, c, proxy_service, read_version, n_mutations, mtypes, p1, p1_off,
      p2, p2_off, n_reads, rb, rb_off, re, re_off, n_writes, wb, wb_off,
      we, we_off);
  if (!send_frame(c, b)) return 0;
  return id;
}

// >= 0: commit version; < 0: -fdb_error_code.
int64_t fnet_commit_wait(void* h, uint64_t req_id) {
  Conn* c = static_cast<Conn*>(h);
  std::vector<uint8_t> reply;
  Cur v{nullptr, 0};
  int64_t rc = recv_reply_for(c, req_id, reply, v);
  if (rc < 0) return rc;
  return parse_commit_value(v);
}

// Point read at a version. Returns 0 (found, *out_len set), 1 (no value),
// or < 0: -fdb_error_code. out_cap too small -> -ERR_INTERNAL with
// *out_len set to the required size.
int32_t fnet_get(void* h, const char* storage_service, const uint8_t* key,
                 int64_t key_len, int64_t version, uint8_t* out,
                 int64_t out_cap, int64_t* out_len) {
  Conn* c = static_cast<Conn*>(h);
  Buf b;
  uint64_t id = req_header(b, c, storage_service, "get", 2);
  b.tag_bytes(key, key_len);
  b.tag_int(version);
  std::vector<uint8_t> reply;
  Cur v{nullptr, 0};
  int64_t rc = round_trip(c, b, id, reply, v);
  if (rc < 0) return static_cast<int32_t>(rc);
  uint8_t t = v.u8();
  if (t == T_NONE) return 1;
  if (t != T_BYTES) return static_cast<int32_t>(-ERR_INTERNAL);
  uint32_t n = v.u32();
  *out_len = n;
  if (!v.need(n) || static_cast<int64_t>(n) > out_cap)
    return static_cast<int32_t>(-ERR_INTERNAL);
  memcpy(out, v.p + v.pos, n);
  return 0;
}

// Range read at a version (reference: fdb_transaction_get_range through
// fdb_c). Rows land in one packed output buffer:
//   per row: u32 key_len, key bytes, u32 value_len, value bytes.
// Returns the row count (>= 0), or < 0: -fdb_error_code; if the buffer
// is too small, returns -ERR_INTERNAL with *out_used set to the
// required size.
int32_t fnet_get_range(void* h, const char* storage_service,
                       const uint8_t* begin, int64_t blen,
                       const uint8_t* end, int64_t elen,
                       int64_t version, int32_t limit, int32_t reverse,
                       uint8_t* out, int64_t out_cap, int64_t* out_used) {
  *out_used = 0;  // malformed-reply errors must not leave resize-signal garbage
  Conn* c = static_cast<Conn*>(h);
  Buf b;
  uint64_t id = req_header(b, c, storage_service, "get_range", 5);
  b.tag_bytes(begin, blen);
  b.tag_bytes(end, elen);
  b.tag_int(version);
  b.tag_int(limit);
  b.tag_bool(reverse != 0);
  std::vector<uint8_t> reply;
  Cur v{nullptr, 0};
  int64_t rc = round_trip(c, b, id, reply, v);
  if (rc < 0) return static_cast<int32_t>(rc);
  uint8_t t = v.u8();
  if (t != T_LIST && t != T_TUPLE) return static_cast<int32_t>(-ERR_INTERNAL);
  uint32_t rows = v.u32();
  int64_t used = 0;
  for (uint32_t i = 0; i < rows; i++) {
    uint8_t rt = v.u8();
    if ((rt != T_TUPLE && rt != T_LIST) || v.u32() != 2)
      return static_cast<int32_t>(-ERR_INTERNAL);
    for (int part = 0; part < 2; part++) {
      if (v.u8() != T_BYTES) return static_cast<int32_t>(-ERR_INTERNAL);
      uint32_t n = v.u32();
      if (!v.need(n)) return static_cast<int32_t>(-ERR_INTERNAL);
      if (used + 4 + n <= out_cap) {
        memcpy(out + used, &n, 4);
        memcpy(out + used + 4, v.p + v.pos, n);
      }
      used += 4 + n;
      v.pos += n;
    }
  }
  *out_used = used;
  if (used > out_cap) return static_cast<int32_t>(-ERR_INTERNAL);
  return static_cast<int32_t>(rows);
}

}  // extern "C"
