"""Storage-side deadline coalescer for the read plane.

The same brain that batches resolver dispatches (sched/coalescer.py —
latency-budget deadline coalescing + an online dispatch cost model) gathers
concurrent get / multi-get / get_range requests queued against one storage
server into a single ``TPUReadSet`` probe. Requests at DIFFERENT read
versions merge into the same dispatch: the packed search is
version-independent, only the host-side value gather consults each
request's version.

Observability: each dispatch ticks the read-plane sub-stages
(``read_coalesce`` — oldest queue wait, ``read_pack`` — host pack time,
``read_dispatch`` — probe + gather) through the loop's span sink, the same
sampled batch-level attribution the commit path uses, so ``cli latency``
and the flight recorder see the read plane next to the txn stages.
"""

from __future__ import annotations

import os

from time import perf_counter

from foundationdb_tpu.runtime.flow import Promise
from foundationdb_tpu.sched.coalescer import AdaptiveCoalescer


class ReadBrain(AdaptiveCoalescer):
    """Deadline-only window policy for the read plane.

    The resolver brain's fill-abort branch (ship NOW when the window
    cannot fill before the deadline) minimizes verdict latency, but on
    the read plane it degenerates: the cost model only ever observes
    depth-1 dispatches, so it never learns amortization, concludes
    batching is worthless, and ships every request as a singleton — the
    exact per-key actor pattern this subsystem replaces. Reads are cheap
    and plentiful; the win IS the amortized probe. So: hold until the
    oldest request's budget is spent (or the window fills), then ship
    everything queued. The inherited cost model still prices the
    dispatch into the deadline so a slow probe ships early."""

    def decide(self, queued: int, oldest_age_ms: float) -> int:
        if queued <= 0:
            return 0
        if self.budget_ms <= 0 or queued >= self.max_window:
            return min(queued, self.max_window)
        if oldest_age_ms + self.cost.predict(queued) >= self.budget_ms:
            return min(queued, self.max_window)
        return 0


def read_budget_ms_default() -> float:
    """FDB_TPU_READ_BUDGET_MS: coalescer latency budget in virtual ms
    (default 0.25; 0 = immediate mode — dispatch whatever is queued)."""
    raw = os.environ.get("FDB_TPU_READ_BUDGET_MS", "0.25")
    try:
        v = float(raw)
        if v < 0:
            raise ValueError
    except ValueError:
        raise ValueError(
            f"FDB_TPU_READ_BUDGET_MS={raw!r} invalid: want a float >= 0"
        ) from None
    return v


class _Req:
    __slots__ = ("kind", "args", "version", "p", "t_in")

    def __init__(self, kind, args, version, p, t_in):
        self.kind = kind  # "points" | "range"
        self.args = args
        self.version = version
        self.p = p
        self.t_in = t_in


class ReadCoalescer:
    """Queue + pump: submit_* parks the caller on a promise; the pump
    task dispatches windows per the adaptive brain's decision."""

    MIN_TICK_S = 0.0001  # pump re-decide floor (virtual s)

    def __init__(self, loop, read_set, budget_ms: float | None = None,
                 max_window: int = 64):
        self.loop = loop
        self.read_set = read_set
        self.brain = ReadBrain(
            budget_ms=(read_budget_ms_default() if budget_ms is None
                       else budget_ms),
            max_window=max_window,
        )
        self._q: list[_Req] = []
        self._wake: Promise | None = None
        self._pump_task = None
        self.stats = {
            "dispatches": 0, "requests": 0, "point_reads": 0,
            "range_reads": 0, "busy_s": 0.0, "errors": 0,
        }
        self._t_first = None  # perf_counter at first dispatch (occupancy)
        self._last_pack_s = 0.0

    # -- client surface -------------------------------------------------------

    async def submit_points(self, keys, version: int):
        return await self._submit("points", list(keys), version)

    async def submit_range(self, begin, end, limit, reverse, version: int):
        return await self._submit("range", (begin, end, limit, reverse),
                                  version)

    async def _submit(self, kind, args, version):
        req = _Req(kind, args, version, Promise(), self.loop.now)
        self._q.append(req)
        self.brain.note_arrival(self.loop.now * 1000.0)
        if self._pump_task is None:
            self._pump_task = self.loop.spawn(self._pump(), name="read_pump")
        if self._wake is not None:
            w, self._wake = self._wake, None
            w.send(None)
        return await req.p.future

    # -- pump -----------------------------------------------------------------

    async def _pump(self):
        while True:
            if not self._q:
                self._wake = Promise()
                await self._wake.future
                continue
            now_ms = self.loop.now * 1000.0
            oldest_ms = now_ms - self._q[0].t_in * 1000.0
            depth = self.brain.decide(len(self._q), oldest_ms)
            if depth <= 0:
                hint = self.brain.wait_hint_ms(len(self._q), oldest_ms)
                await self.loop.sleep(max(hint / 1000.0, self.MIN_TICK_S))
                continue
            batch, self._q = self._q[:depth], self._q[depth:]
            self._dispatch(batch, oldest_ms)

    def _dispatch(self, batch: list[_Req], oldest_ms: float) -> None:
        from foundationdb_tpu.obs.span import span_sink

        sink = span_sink(self.loop)
        t0 = perf_counter()
        if self._t_first is None:
            self._t_first = t0
        point_reqs = [r for r in batch if r.kind == "points"]
        range_reqs = [r for r in batch if r.kind == "range"]
        try:
            flat_keys: list[bytes] = []
            flat_versions: list[int] = []
            for r in point_reqs:
                flat_keys.extend(r.args)
                flat_versions.extend([r.version] * len(r.args))
            pack_before = self.read_set.stats["pack_s"]
            values = (self.read_set.get_points(flat_keys, flat_versions)
                      if flat_keys else [])
            ranges = (self.read_set.get_ranges(
                [(*r.args, r.version) for r in range_reqs])
                if range_reqs else [])
        except BaseException as e:  # engine bug: fail the batch, not the pump
            self.stats["errors"] += 1
            for r in batch:
                r.p.fail(e)
            return
        pos = 0
        for r in point_reqs:
            k = len(r.args)
            r.p.send(values[pos:pos + k])
            pos += k
        for r, rows in zip(range_reqs, ranges):
            r.p.send(rows)
        dt = perf_counter() - t0
        self.stats["dispatches"] += 1
        self.stats["requests"] += len(batch)
        self.stats["point_reads"] += len(flat_keys)
        self.stats["range_reads"] += len(range_reqs)
        self.stats["busy_s"] += dt
        self.brain.observe_dispatch(len(batch), dt * 1000.0)
        if sink is not None:
            sink.stage_tick("read_coalesce", oldest_ms / 1000.0, len(batch))
            pack_s = self.read_set.stats["pack_s"] - pack_before
            sink.stage_tick("read_pack", pack_s, 1)
            sink.stage_tick("read_dispatch", dt, len(batch))

    # -- metrics --------------------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return len(self._q)

    @property
    def occupancy(self) -> float:
        """Fraction of real time since the first dispatch spent inside
        dispatches (host-cost gauge; 0 before any dispatch)."""
        if self._t_first is None:
            return 0.0
        elapsed = perf_counter() - self._t_first
        return min(1.0, self.stats["busy_s"] / elapsed) if elapsed > 0 else 0.0

    @property
    def reads_per_dispatch(self) -> float:
        d = self.stats["dispatches"]
        if not d:
            return 0.0
        return (self.stats["point_reads"] + self.stats["range_reads"]) / d
