"""TPUReadSet: resident key-universe mirror + one probe per dispatch.

The read-plane analogue of ``TPUConflictSet``'s resident dictionary
(models/conflict_set.py, FDB_TPU_RESIDENT): the versioned map's sorted key
universe is packed ONCE into ``[n, W]`` int32 rows (core/keypack.py) and
stays resident — in HBM on the device arm, as the u64-column host mirror
otherwise — across dispatches. A dispatch packs only its queries and runs
one two-sided search (``ops/lex.searchsorted_words_2sided_fp`` jitted on
device; the same column-cascade in numpy on host) that answers every point
lookup and range boundary of the batch at once. Values then gather
host-side from the per-key version chains, which keeps every arm
byte-identical to the scalar ``VersionedMap.at`` oracle:

- point hit: the equal-packed-row run from the two-sided search is
  confirmed by exact bytes (packed rows truncate at ``max_key_bytes``),
  then the chain resolves at the read version exactly as ``at()`` does;
- range: the conservative packed bounds are tightened by an advance-only
  byte compare at the run edges (truncation rounds down, so packed bounds
  can only be LOW), then keys in [lo, hi) resolve per chain.

The mirror invalidates on key-universe changes only (``struct_seq`` on the
map — inserts, purges, rollback/GC removals); value updates mutate the
referenced chains in place and cost the mirror nothing. That is the same
economics as the resident conflict dictionary: rebuilds are the cold path,
steady-state reads ride the resident tensors.
"""

from __future__ import annotations

import bisect

import numpy as np

from foundationdb_tpu.core.keypack import INT32_MAX, KeyCodec, row_sort_keys


def reads_device_default() -> bool:
    """FDB_TPU_READS_DEVICE: probe on the jax device (default 0 = host)."""
    from foundationdb_tpu.core.types import env_choice

    return env_choice("FDB_TPU_READS_DEVICE", "0", ("0", "1")) == "1"


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


class TPUReadSet:
    """Batched point/range reads over a versioned map.

    `vmap` duck-types ``runtime.storage.VersionedMap``: sorted ``_keys``,
    ``_chains`` (key → ascending ``(version, value)`` chain), and a
    ``struct_seq`` counter bumped whenever the KEY SET changes."""

    MIN_QUERY_SLOTS = 8  # device query pad floor (bounds compile count)

    def __init__(self, vmap, codec: KeyCodec | None = None,
                 device: bool | None = None):
        self.vmap = vmap
        self.codec = codec or KeyCodec()
        self.device = reads_device_default() if device is None else bool(device)
        self._seq = None  # mirror generation (vmap.struct_seq at build)
        self._keys: list[bytes] = []
        self._chains: list[list[tuple[int, bytes | None]]] = []
        self._void = row_sort_keys(
            np.zeros((0, self.codec.width), np.int32))
        self._dev_rows = None
        self._probe = None  # jitted two-sided search (device arm)
        self.stats = {
            "rebuilds": 0, "uploads": 0, "probes": 0,
            "point_reads": 0, "range_reads": 0, "pack_s": 0.0,
        }

    # -- mirror maintenance ---------------------------------------------------

    def _sync(self) -> None:
        seq = getattr(self.vmap, "struct_seq", 0)
        if self._seq == seq:
            return
        self._keys = list(self.vmap._keys)
        self._chains = [self.vmap._chains[k] for k in self._keys]
        rows = (self.codec.pack(self._keys, mode="begin") if self._keys
                else np.zeros((0, self.codec.width), np.int32))
        # memcmp-order void view: one native np.searchsorted call answers
        # a whole dispatch on the host arm (C-speed, no per-column pass).
        self._void = row_sort_keys(rows)
        self._seq = seq
        self.stats["rebuilds"] += 1
        if self.device:
            import jax.numpy as jnp

            cap = max(1, _next_pow2(len(self._keys)))
            padded = np.full((cap, self.codec.width), INT32_MAX, np.int32)
            padded[: len(self._keys)] = rows
            self._dev_rows = jnp.asarray(padded)
            self.stats["uploads"] += 1
            if self._probe is None:
                import jax

                from foundationdb_tpu.ops.lex import searchsorted_words_2sided_fp

                self._probe = jax.jit(searchsorted_words_2sided_fp)

    def _search2(self, q_rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(left, right) bounds of each query row in the resident mirror —
        the one vectorized search a dispatch pays."""
        self.stats["probes"] += 1
        if self.device and self._dev_rows is not None:
            k = q_rows.shape[0]
            slots = max(self.MIN_QUERY_SLOTS, _next_pow2(k))
            qpad = np.full((slots, q_rows.shape[1]), INT32_MAX, np.int32)
            qpad[:k] = q_rows
            lo, hi = self._probe(self._dev_rows, qpad)
            n = len(self._keys)
            return (np.minimum(np.asarray(lo)[:k], n),
                    np.minimum(np.asarray(hi)[:k], n))
        qv = row_sort_keys(np.ascontiguousarray(q_rows))
        return (np.searchsorted(self._void, qv, side="left"),
                np.searchsorted(self._void, qv, side="right"))

    # -- value resolution (host gather; identical to VersionedMap.at) --------

    def _value_at(self, idx: int, version: int) -> bytes | None:
        chain = self._chains[idx]
        last_v, last_val = chain[-1]
        if last_v <= version:
            return last_val
        i = bisect.bisect_right(chain, version, key=lambda e: e[0]) - 1
        return None if i < 0 else chain[i][1]

    # -- batched reads --------------------------------------------------------

    def get_points(self, keys: list[bytes], versions) -> list[bytes | None]:
        """One batched lookup: values of `keys` at `versions` (an int, or a
        per-key sequence — the coalescer merges requests at different read
        versions into one probe; the search is version-independent)."""
        self._sync()
        out: list[bytes | None] = [None] * len(keys)
        self.stats["point_reads"] += len(keys)
        if not keys or not self._keys:
            return out
        if isinstance(versions, int):
            versions = [versions] * len(keys)
        from time import perf_counter

        t0 = perf_counter()
        q = self.codec.pack(keys, mode="begin")
        self.stats["pack_s"] += perf_counter() - t0
        lo, hi = self._search2(q)
        for j, key in enumerate(keys):
            for i in range(int(lo[j]), int(hi[j])):
                if self._keys[i] == key:
                    out[j] = self._value_at(i, versions[j])
                    break
        return out

    def get_ranges(self, reqs) -> list[list[tuple[bytes, bytes]]]:
        """Batched range reads. `reqs` is a list of
        ``(begin, end, limit, reverse, version)``; all boundary probes ride
        one search."""
        self._sync()
        self.stats["range_reads"] += len(reqs)
        if not reqs or not self._keys:
            return [[] for _ in reqs]
        from time import perf_counter

        t0 = perf_counter()
        bounds = [r[0] for r in reqs] + [r[1] for r in reqs]
        q = self.codec.pack(bounds, mode="begin")
        self.stats["pack_s"] += perf_counter() - t0
        lo, _hi = self._search2(q)
        n, m = len(self._keys), len(reqs)
        out = []
        for j, (begin, end, limit, reverse, version) in enumerate(reqs):
            a, b = int(lo[j]), int(lo[m + j])
            # Truncated packed bounds are conservative-LOW: advance by
            # exact bytes (bounded by the shared-prefix collision run).
            while a < n and self._keys[a] < begin:
                a += 1
            while b < n and self._keys[b] < end:
                b += 1
            idxs = range(b - 1, a - 1, -1) if reverse else range(a, b)
            rows: list[tuple[bytes, bytes]] = []
            for i in idxs:
                v = self._value_at(i, version)
                if v is not None:
                    rows.append((self._keys[i], v))
                    if len(rows) >= limit:
                        break
            out.append(rows)
        return out

    # -- the sequential oracle ------------------------------------------------

    def oracle_get(self, key: bytes, version: int) -> bytes | None:
        """Scalar reference read (VersionedMap.at semantics, no mirror):
        the parity baseline every batched arm must match byte-for-byte."""
        chain = self.vmap._chains.get(key)
        if not chain:
            return None
        i = bisect.bisect_right(chain, version, key=lambda e: e[0]) - 1
        return None if i < 0 else chain[i][1]

    def oracle_range(self, begin: bytes, end: bytes, limit: int,
                     reverse: bool, version: int) -> list[tuple[bytes, bytes]]:
        keys = self.vmap._keys
        a = bisect.bisect_left(keys, begin)
        b = bisect.bisect_left(keys, end)
        idxs = range(b - 1, a - 1, -1) if reverse else range(a, b)
        rows: list[tuple[bytes, bytes]] = []
        for i in idxs:
            v = self.oracle_get(keys[i], version)
            if v is not None:
                rows.append((keys[i], v))
                if len(rows) >= limit:
                    break
        return rows
