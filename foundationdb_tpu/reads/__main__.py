"""CI entry point: one-JSON-line read-plane self-check / READS_AB bench.

    python -m foundationdb_tpu.reads          # selfcheck, rc 0/1
    python -m foundationdb_tpu.reads --ab     # full READS_AB record

The selfcheck is a fast all-parity pass — batched point/range reads vs
the sequential oracle on host AND device arms, watch fire-set parity
across arms 0/1/device, plus a small end-to-end get_multi through a
storage server — wired as the `reads` stage of scripts/tpuwatch_r05.sh.
The A/B (scripts/reads_ab.sh -> READS_AB.json) additionally measures the
batched-vs-per-key-actor throughput gates and watch-sweep scaling; see
reads/bench.py.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def selfcheck(seed: int = 0) -> dict:
    import random

    from foundationdb_tpu.core.mutations import Mutation, MutationType as M
    from foundationdb_tpu.reads.bench import (
        bench_watch_parity,
        _oracle_results,
        _oracle_shaped_engine,
    )
    from foundationdb_tpu.reads.read_set import TPUReadSet
    from foundationdb_tpu.runtime.flow import Loop
    from foundationdb_tpu.runtime.storage import StorageServer

    rng = random.Random(seed)
    loop = Loop(seed=seed)
    ss = StorageServer(loop, tag=0, tlog_ep=None)
    keys = sorted({bytes(rng.randrange(256) for _ in range(rng.randrange(1, 24)))
                   for _ in range(800)})
    ss._apply(1, [Mutation(M.SET_VALUE, k, b"v0%s" % k[:4]) for k in keys])
    for v in (2, 3, 4):
        ss._apply(v, [Mutation(M.SET_VALUE, rng.choice(keys), b"v%d" % v)
                      for _ in range(60)])

    stream = []
    for _ in range(150):
        ver = rng.randrange(1, 5)
        if rng.random() < 0.3:
            a, b = sorted([rng.choice(keys), rng.choice(keys)])
            stream.append(("range", a, b + b"\x00", rng.randrange(0, 20), ver))
        else:
            stream.append(("points",
                           [rng.choice(keys) for _ in range(rng.randrange(1, 9))]
                           + [bytes([rng.randrange(256)])],  # misses too
                           ver))
    oracle = _oracle_results(ss.read_set, stream)
    host_ok = _oracle_shaped_engine(ss.read_set, stream) == oracle
    dev_ok = _oracle_shaped_engine(TPUReadSet(ss.map, device=True),
                                   stream) == oracle

    async def multi():
        ks = [rng.choice(keys) for _ in range(20)]
        got = await ss.get_multi(ks, 4)
        want = [await ss.get(k, 4) for k in ks]
        return got == want

    rpc_ok = loop.run(multi(), timeout=60_000)
    watch_ok = bench_watch_parity(n_keys=120, versions=25, seed=seed)
    ok = bool(host_ok and dev_ok and rpc_ok and watch_ok)
    return {
        "metric": "reads_selfcheck",
        "ok": ok,
        "host_parity": host_ok,
        "device_parity": dev_ok,
        "get_multi_rpc_parity": rpc_ok,
        "watch_fire_parity": watch_ok,
        "ops": len(stream),
        "read_stats": dict(ss.read_set.stats, pack_s=round(
            ss.read_set.stats["pack_s"], 5)),
    }


def main(argv: "list[str] | None" = None) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")  # pure sim: no TPU touch
    ap = argparse.ArgumentParser(prog="python -m foundationdb_tpu.reads")
    ap.add_argument("--ab", action="store_true",
                    help="full READS_AB bench instead of the selfcheck")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ops", type=int, default=2000)
    ap.add_argument("--keys", type=int, default=4096)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--clients", type=int, default=24)
    ap.add_argument("--watch-sizes", type=str, default="1000,100000,1000000")
    args = ap.parse_args(argv)
    if args.ab:
        from foundationdb_tpu.reads.bench import run_ab

        sizes = tuple(int(s) for s in args.watch_sizes.split(",") if s)
        rec = run_ab(n_keys=args.keys, n_ops=args.ops, batch=args.batch,
                     n_clients=args.clients, seed=args.seed,
                     watch_sizes=sizes)
        print(json.dumps(rec))
        return 0
    rec = selfcheck(seed=args.seed)
    print(json.dumps(rec))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
