"""Device-vectorized read plane: batched reads + packed watch fan-out.

The commit path got its speed from one discipline — key sets become packed
tensors, one kernel answers the whole batch (models/conflict_set.py). This
package applies the same discipline to the OTHER half of the storage
server's job, which the seed still ran as scalar actors:

- :mod:`~foundationdb_tpu.reads.read_set` — ``TPUReadSet``: a resident
  sorted mirror of the versioned map's key universe (the read-plane
  analogue of ``TPUConflictSet``'s resident dictionary). One probe —
  ``ops/lex.searchsorted_words_2sided_fp`` on device, the u64-column
  binary search on host — resolves every point lookup and range boundary
  of a dispatch at once; values gather host-side from the per-key version
  chains, byte-identical to the scalar ``VersionedMap.at`` oracle.
- :mod:`~foundationdb_tpu.reads.coalescer` — ``ReadCoalescer``: the
  storage-side deadline coalescer (the ``sched/`` brain, reused verbatim)
  that gathers concurrent get / multi-get / get_range requests into one
  probe dispatch.
- :mod:`~foundationdb_tpu.reads.watches` — ``WatchIndex``: watch
  registrations as a resident packed key set, matched once per committed
  version against that version's written keys; fired indices gather back
  to promises host-side. A million idle watches cost one probe per
  version instead of a million dict pops, and shard-move cancellation is
  O(log n + hits) instead of the seed's O(all watches) scan.

Env knobs (every arm is byte-identical; knobs trade host/device work only):

- ``FDB_TPU_READS_DEVICE=0|1`` — probe on the jax device (default 0: the
  vectorized host path; the sim and tier-1 tests run host).
- ``FDB_TPU_PACKED_WATCHES=0|1|device`` — watch sweep arm (default 1:
  packed numpy probe; ``0`` is the dict-lookup host oracle, ``device``
  probes via the jitted kernel).
- ``FDB_TPU_READ_BATCH=0|1`` — route scalar ``get``/``get_range`` RPCs
  through the coalescer too (default 0; ``get_multi`` always batches).
- ``FDB_TPU_READ_BUDGET_MS`` — coalescer latency budget (virtual ms,
  default 0.25; ``0`` = immediate dispatch of whatever is queued).
"""

from foundationdb_tpu.reads.coalescer import ReadCoalescer  # noqa: F401
from foundationdb_tpu.reads.read_set import TPUReadSet  # noqa: F401
from foundationdb_tpu.reads.watches import WatchIndex  # noqa: F401
