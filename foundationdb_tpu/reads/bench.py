"""READS_AB: the batched read plane vs the per-key actor baseline, plus
packed watch-sweep scaling — one honesty-flagged JSON record.

Two claims, measured on the SAME storage server and op streams:

1. **Batched multi-get/range throughput**: YCSB-B/C read streams (Zipf
   point batches + short scans) driven by concurrent closed-loop
   clients. Baseline arm = one `ss.get` actor round-trip per key (the
   per-key actor path every fdb client pays today); batched arm = one
   `ss.get_multi` per op, which the deadline coalescer merges across
   clients into single packed interval-probe dispatches. Gate:
   throughput >= 3x at batched p99 no worse than baseline p99. Every
   arm's bytes are compared against the sequential oracle
   (`TPUReadSet.oracle_get/oracle_range`) — parity is a validity gate,
   not a footnote.

2. **Watch-sweep sublinearity**: per-committed-version sweep time of the
   packed registry at n_watches in {1e3, 1e5, 1e6} with a fixed write
   batch per version. The packed sweep probes the sorted set per
   WRITTEN key (O(w log n)), so the gate is sweep(1e5..1e6) <= 2x
   sweep(1e3). Fire-set parity across arms 0/1/device vs the
   final-value oracle rides along.

Honesty flags: `valid` (every gate AND every parity check), `cpu_fallback`
(no TPU backend — the device arm ran on jax-cpu), `p99_quotable` (enough
samples per arm), `co_corrected` (False: closed-loop clients, latencies
are service times and subject to coordinated omission; throughput is
wall-clock and unaffected).
"""

from __future__ import annotations

import json
from time import perf_counter

from foundationdb_tpu.core.mutations import Mutation, MutationType as M
from foundationdb_tpu.runtime.flow import Loop, Promise, all_of
from foundationdb_tpu.runtime.storage import StorageServer
from foundationdb_tpu.sim.network import SimNetwork
from foundationdb_tpu.reads.read_set import TPUReadSet
from foundationdb_tpu.reads.watches import WatchIndex


def _backend() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:  # noqa: BLE001 — no jax is a legal host-only config
        return "none"


def _pctl(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


# -- op-stream generation ------------------------------------------------------


def _key(i: int) -> bytes:
    return b"ycsb/%08d" % i


def _build_store(loop: Loop, n_keys: int, update_versions: int,
                 rng) -> StorageServer:
    """Load n_keys rows, then apply `update_versions` committed versions
    of Zipf-skewed value updates (the YCSB-B write mix as version
    history: chains get DEEP on hot keys, the key set never changes, so
    the read mirror packs exactly once)."""
    ss = StorageServer(loop, tag=0, tlog_ep=None)
    ss._apply(1, [Mutation(M.SET_VALUE, _key(i), b"init%08d" % i)
                  for i in range(n_keys)])
    for v in range(2, 2 + update_versions):
        hot = sorted({min(int(rng.paretovariate(1.5)) - 1, n_keys - 1)
                      for _ in range(32)})
        ss._apply(v, [Mutation(M.SET_VALUE, _key(i), b"u%08d.%08d" % (v, i))
                      for i in hot])
    return ss


def _build_stream(rng, n_ops: int, n_keys: int, batch: int,
                  scan_fraction: float, version: int) -> list[tuple]:
    """Pre-generated versioned read ops, identical for both arms (MVCC
    reads at a pinned version are deterministic regardless of client
    interleaving — byte parity across arms is therefore exact)."""
    ops: list[tuple] = []
    for _ in range(n_ops):
        if rng.random() < scan_fraction:
            lo = min(int(rng.paretovariate(1.5)) - 1, n_keys - 1)
            span = 1 + rng.randrange(16)
            ops.append(("range", _key(lo), _key(lo + span), span, version))
        else:
            # Log-uniform hot head (YCSB zipfian shape) WITHOUT collapsing
            # every draw onto key 0 — multi-get batches keep real width.
            picks = sorted({int(n_keys ** rng.random()) - 1
                            for _ in range(batch)})
            ops.append(("points", [_key(i) for i in picks], version))
    return ops


async def _run_arm(loop: Loop, ss: StorageServer, ep, stream: list[tuple],
                   n_clients: int, batched: bool):
    """Drive the shared op stream with n_clients concurrent closed-loop
    clients THROUGH the RPC endpoint — the baseline pays one actor
    round-trip per key (what every per-key client pays today), the
    batched arm one per op. Returns (results, sorted ms, elapsed_s)."""
    results: list = [None] * len(stream)
    lats: list[float] = []
    nxt = [0]
    ss._batch_scalar_reads = batched  # route scans through the coalescer
    t0 = perf_counter()

    async def client(cid: int):
        while True:
            i = nxt[0]
            if i >= len(stream):
                return
            nxt[0] += 1
            op = stream[i]
            s = perf_counter()
            if op[0] == "points":
                _, keys, ver = op
                if batched:
                    rows = await ep.get_multi(keys, ver)
                else:
                    rows = [await ep.get(k, ver) for k in keys]
            else:
                _, b, e, lim, ver = op
                rows = await ep.get_range(b, e, ver, limit=lim)
            lats.append(perf_counter() - s)
            results[i] = rows

    await all_of([loop.spawn(client(i), name=f"reads_ab.c{i}")
                  for i in range(n_clients)])
    elapsed = perf_counter() - t0
    return results, sorted(l * 1000.0 for l in lats), elapsed


def _oracle_results(read_set: TPUReadSet, stream: list[tuple]) -> list:
    out = []
    for op in stream:
        if op[0] == "points":
            _, keys, ver = op
            out.append([read_set.oracle_get(k, ver) for k in keys])
        else:
            _, b, e, lim, ver = op
            out.append(read_set.oracle_range(b, e, lim, False, ver))
    return out


def _stream_reads(stream: list[tuple]) -> int:
    return sum(len(op[1]) if op[0] == "points" else 1 for op in stream)


def bench_reads(mode: str = "ycsb_b", n_keys: int = 4096, n_ops: int = 2000,
                batch: int = 16, n_clients: int = 24, seed: int = 0,
                device_parity: bool = True, reps: int = 3) -> dict:
    """One YCSB mode through both arms + oracle + (optionally) the
    device read engine for parity/timing. Arms alternate for `reps`
    rounds and each quotes its best round (obs_ab precedent: wall-clock
    on a shared host is noisy; best-of-N is the stable estimator, and
    BOTH arms get the same treatment). Parity is checked on EVERY
    round."""
    loop = Loop(seed=seed)
    rng = loop.rng
    update_versions = 64 if mode == "ycsb_b" else 0
    ss = _build_store(loop, n_keys, update_versions, rng)
    # Storage-side window budget sized to the sim RPC latency (default
    # 0.25 virtual ms is tuned for intra-process reads; here arrivals
    # spread across the 0.2-2ms virtual network hop).
    ss._reads.brain.budget_ms = 2.0
    net = SimNetwork(loop)
    ep = net.host("ss0", "ss", ss)
    version = ss._version
    scan_fraction = 0.2
    stream = _build_stream(rng, n_ops, n_keys, batch, scan_fraction, version)
    total_reads = _stream_reads(stream)

    oracle = _oracle_results(ss.read_set, stream)
    base = batchd = None
    parity = True
    for _ in range(max(1, reps)):
        b = loop.run(_run_arm(loop, ss, ep, stream, n_clients, batched=False),
                     timeout=3_600_000)
        m = loop.run(_run_arm(loop, ss, ep, stream, n_clients, batched=True),
                     timeout=3_600_000)
        parity = parity and (b[0] == m[0] == oracle)
        if base is None or b[2] < base[2]:
            base = b
        if batchd is None or m[2] < batchd[2]:
            batchd = m

    dev = None
    if device_parity:
        t = perf_counter()
        dset = TPUReadSet(ss.map, device=True)
        dres = _oracle_shaped_engine(dset, stream)
        dev = {
            "parity": dres == oracle,
            "elapsed_s": round(perf_counter() - t, 4),
            "uploads": dset.stats["uploads"],
        }

    def arm_rec(results, lats_ms, elapsed):
        return {
            "reads_per_sec": round(total_reads / elapsed, 1) if elapsed else 0,
            "ops": len(results),
            "reads": total_reads,
            "elapsed_s": round(elapsed, 4),
            "p50_ms": round(_pctl(lats_ms, 0.50), 4),
            "p99_ms": round(_pctl(lats_ms, 0.99), 4),
        }

    b_rec = arm_rec(*base)
    m_rec = arm_rec(*batchd)
    b_rec["best_of"] = m_rec["best_of"] = max(1, reps)
    m_rec["dispatches"] = ss._reads.stats["dispatches"]
    m_rec["reads_per_dispatch"] = round(ss._reads.reads_per_dispatch, 2)
    ratio = (m_rec["reads_per_sec"] / b_rec["reads_per_sec"]
             if b_rec["reads_per_sec"] else 0.0)
    return {
        "mode": mode,
        "keys": n_keys,
        "ops": n_ops,
        "batch": batch,
        "clients": n_clients,
        "update_versions": update_versions,
        "per_key": b_rec,
        "batched": m_rec,
        "throughput_ratio": round(ratio, 2),
        "p99_equal_or_better": m_rec["p99_ms"] <= b_rec["p99_ms"],
        "read_parity": parity,
        "device": dev,
    }


def _oracle_shaped_engine(read_set: TPUReadSet, stream: list[tuple]) -> list:
    """The same stream through a TPUReadSet engine directly (one probe
    per op) — used for the device-arm parity check."""
    out = []
    for op in stream:
        if op[0] == "points":
            _, keys, ver = op
            out.append(read_set.get_points(keys, ver))
        else:
            _, b, e, lim, ver = op
            out.append(read_set.get_ranges([(b, e, lim, False, ver)])[0])
    return out


# -- watch sweep scaling -------------------------------------------------------


def _wkey(i: int) -> bytes:
    return b"w/%08d" % i


def bench_watch_sweep(sizes=(1_000, 100_000, 1_000_000), writes_per_version=64,
                      rounds=21, arm: str = "1") -> dict:
    """Per-version sweep time vs registry size, fixed write batch. The
    written keys EXIST in the set but carry the expected value, so no
    watch fires and the resident set stays intact across rounds (the
    steady state a watch-heavy cluster lives in)."""
    out: dict[str, float] = {}
    reg: dict[str, int] = {}
    for n in sizes:
        idx = WatchIndex(arm=arm)
        t = perf_counter()
        for i in range(n):
            idx.add(_wkey(i), b"expect", Promise())
        reg[str(n)] = round(perf_counter() - t, 4)
        written = [(_wkey(i * (n // writes_per_version or 1)), b"expect")
                   for i in range(writes_per_version)]
        idx.sweep(1, written)  # warm-up: consolidation + pack land here
        times = []
        for r in range(rounds):
            t = perf_counter()
            idx.sweep(2 + r, written)
            times.append(perf_counter() - t)
        times.sort()
        out[str(n)] = round(times[len(times) // 2] * 1000.0, 4)
    lo, hi = str(sizes[0]), str(sizes[-1])
    return {
        "arm": arm,
        "writes_per_version": writes_per_version,
        "sweep_ms": out,
        "register_s": reg,
        "sublinear": bool(out[hi] <= 2.0 * max(out[lo], 1e-3)),
    }


def bench_watch_parity(n_keys: int = 300, versions: int = 40,
                       seed: int = 7) -> bool:
    """Randomized fire-set parity: identical write streams through arms
    0 / 1 / device must fire the identical (key, version) sets, equal to
    the final-value sequential oracle."""
    import random

    rng = random.Random(seed)
    keys = [_wkey(i) for i in range(n_keys)]
    stream = []
    for v in range(1, versions + 1):
        stream.append((v, [(rng.choice(keys),
                            b"new%d" % rng.randrange(4)
                            if rng.random() < 0.8 else None)
                           for _ in range(rng.randrange(1, 12))]))

    def run(arm: str):
        idx = WatchIndex(arm=arm)
        fired: list[tuple[bytes, int]] = []

        def hook(k):
            p = Promise()
            p.future.add_done_callback(lambda f, k=k: fired.append((k, f._value)))
            return p

        for k in keys:
            idx.add(k, b"expect", hook(k))
        for v, written in stream:
            idx.sweep(v, written)
        return sorted(fired)

    # Oracle: first version whose FINAL value for the key != expect.
    want = []
    alive = {k: b"expect" for k in keys}
    for v, written in stream:
        final = {}
        for k, val in written:
            final[k] = val
        for k, val in final.items():
            if k in alive and val != alive[k]:
                want.append((k, v))
                del alive[k]
    want.sort()
    return run("0") == run("1") == run("device") == want


# -- the record ----------------------------------------------------------------


def run_ab(n_keys: int = 4096, n_ops: int = 2000, batch: int = 16,
           n_clients: int = 24, seed: int = 0,
           watch_sizes=(1_000, 100_000, 1_000_000)) -> dict:
    backend = _backend()
    modes = {m: bench_reads(m, n_keys=n_keys, n_ops=n_ops, batch=batch,
                            n_clients=n_clients, seed=seed)
             for m in ("ycsb_b", "ycsb_c")}
    sweep = bench_watch_sweep(sizes=watch_sizes)
    watch_parity = bench_watch_parity()
    ratios = [m["throughput_ratio"] for m in modes.values()]
    parity_all = (all(m["read_parity"] for m in modes.values())
                  and all((m["device"] or {}).get("parity", True)
                          for m in modes.values())
                  and watch_parity)
    p99_quotable = all(m["per_key"]["ops"] >= 1000 for m in modes.values())
    gates = {
        "throughput_3x": min(ratios) >= 3.0,
        "p99_equal_or_better": all(m["p99_equal_or_better"]
                                   for m in modes.values()),
        "watch_sublinear": sweep["sublinear"],
        "parity": parity_all,
    }
    return {
        "metric": "reads_ab",
        "backend": backend,
        "cpu_fallback": backend != "tpu",
        "co_corrected": False,  # closed-loop clients; see module docstring
        "p99_quotable": p99_quotable,
        "modes": modes,
        "throughput_ratio_min": min(ratios),
        "watch_sweep": sweep,
        "watch_parity": watch_parity,
        "gates": gates,
        "valid": all(gates.values()),
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="foundationdb_tpu.reads.bench")
    ap.add_argument("--ops", type=int, default=2000)
    ap.add_argument("--keys", type=int, default=4096)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--clients", type=int, default=24)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--watch-sizes", type=str, default="1000,100000,1000000")
    args = ap.parse_args(argv)
    sizes = tuple(int(s) for s in args.watch_sizes.split(",") if s)
    rec = run_ab(n_keys=args.keys, n_ops=args.ops, batch=args.batch,
                 n_clients=args.clients, seed=args.seed, watch_sizes=sizes)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
