"""Packed watch registry: fan-out as one probe per committed version.

The seed kept watches in ``dict[key] -> [(expect, promise)]`` and popped
the dict inside every ``_write`` — per-mutation actor bookkeeping, plus an
O(all-watches) linear scan to cancel a moved shard's watches. Here the
registry is a sorted resident key set (packed rows viewed memcmp-order,
the resident-dictionary economics of models/conflict_set.py): each committed version's written keys
are packed once and probed against the watch keys in one vectorized
search, and fired indices gather back to promises host-side.

Semantics (the reference watch contract, storageserver.actor.cpp):

- a watch armed with ``expect`` fires with the triggering version once the
  key's value is observed ``!= expect``;
- fires may be SPURIOUS (e.g. on an applied-but-unacked write that
  recovery later rolls back — the client must re-read); NOT firing while
  the value still equals ``expect`` is always correct. The per-version
  sweep compares the version's FINAL value per key, so a same-version
  A→B→A rewrite does not fire — allowed under the contract, and identical
  across every arm (host / packed / device), which is what the parity
  tests pin.

Cancellation on shard moves (``cancel_range``) is a bisect over the sorted
key index plus a scan of the hits and the small unconsolidated tail:
O(log n + hits) where the seed scanned every armed watch.
"""

from __future__ import annotations

import bisect

import numpy as np

from foundationdb_tpu.core.keypack import KeyCodec, row_sort_keys

_ARMS = ("0", "1", "device")


def watch_arm_default() -> str:
    """FDB_TPU_PACKED_WATCHES: 0 = dict-lookup host oracle, 1 = packed
    numpy probe (default), device = jitted kernel probe."""
    from foundationdb_tpu.core.types import env_choice

    return env_choice("FDB_TPU_PACKED_WATCHES", "1", _ARMS)


class WatchIndex:
    """Armed watches: promise book-keeping plus a lazily-consolidated
    sorted key index for packed sweeps and O(log n + hits) range cancel.

    The consolidated index may lag the dict (adds append to a pending
    tail, fires/cancels leave tombstoned rows); every lookup therefore
    checks membership back through ``_by_key``, the single source of
    truth. Consolidation merges the sorted pending tail in O(n + p) and
    is amortized over the adds that created it."""

    def __init__(self, arm: str | None = None, codec: KeyCodec | None = None):
        self.arm = watch_arm_default() if arm is None else str(arm)
        if self.arm not in _ARMS:
            raise ValueError(f"watch arm {self.arm!r}: want one of {_ARMS}")
        self.codec = codec or KeyCodec()
        self._by_key: dict[bytes, list[tuple[bytes | None, object]]] = {}
        self._count = 0
        # Consolidated sorted index + pending tail (packed/device arms;
        # the host arm still maintains it for cancel_range).
        self._sorted: list[bytes] = []
        self._void = row_sort_keys(
            np.zeros((0, self.codec.width), np.int32))
        self._pending: list[bytes] = []
        self._dead = 0  # tombstoned rows in _sorted (keys no longer armed)
        self._dev_rows = None  # device-resident [n, W] rows (arm="device")
        self.stats = {
            "registered": 0, "fired": 0, "cancelled": 0, "sweeps": 0,
            "swept_writes": 0, "probed": 0, "cancel_scanned": 0,
            "consolidations": 0, "uploads": 0,
        }

    @property
    def count(self) -> int:
        return self._count

    # -- registration --------------------------------------------------------

    def add(self, key: bytes, expect: bytes | None, promise) -> None:
        """Arm one watch (the caller enforces MAX_WATCHES on `count`)."""
        entries = self._by_key.get(key)
        if entries is None:
            self._by_key[key] = [(expect, promise)]
            self._pending.append(key)
        else:
            entries.append((expect, promise))
        self._count += 1
        self.stats["registered"] += 1

    # -- index maintenance ---------------------------------------------------

    def _consolidate(self) -> None:
        if self._pending:
            news = sorted(set(self._pending))
            self._pending = []
            if news:
                merged: list[bytes] = []
                i = j = 0
                a, b = self._sorted, news
                while i < len(a) and j < len(b):
                    if a[i] <= b[j]:
                        if a[i] == b[j]:
                            j += 1
                        merged.append(a[i])
                        i += 1
                    else:
                        merged.append(b[j])
                        j += 1
                merged.extend(a[i:])
                merged.extend(b[j:])
                self._sorted = merged
                self._rebuild_packed()
        if self._dead > max(64, len(self._sorted) // 2):
            # Tombstone-heavy index: drop dead rows so probes stay tight.
            self._sorted = [k for k in self._sorted if k in self._by_key]
            self._dead = 0
            self._rebuild_packed()

    def _rebuild_packed(self) -> None:
        self.stats["consolidations"] += 1
        if self.arm == "0":
            return  # host arm: the sorted byte list alone serves cancels
        rows = (self.codec.pack(self._sorted, mode="begin") if self._sorted
                else np.zeros((0, self.codec.width), np.int32))
        # memcmp-order void view: one native searchsorted per sweep side.
        self._void = row_sort_keys(rows)
        if self.arm == "device":
            import jax.numpy as jnp

            self._dev_rows = jnp.asarray(rows)
            self.stats["uploads"] += 1

    def _candidate_keys(self, written_keys: list[bytes]) -> list[bytes]:
        """Armed keys among `written_keys` — the probe under A/B test.
        Every arm must return the same set (parity-pinned)."""
        # Every arm consolidates: the host arm skips packing
        # (_rebuild_packed early-returns) but must still fold the pending
        # tail into _sorted, or cancel_range's "bounded pending tail"
        # scan degrades to O(all adds ever).
        self._consolidate()
        if self.arm == "0":
            return [k for k in written_keys if k in self._by_key]
        out: list[bytes] = []
        n = len(self._sorted)
        if n:
            q = self.codec.pack(written_keys, mode="begin")
            self.stats["probed"] += len(written_keys)
            if self.arm == "device":
                from foundationdb_tpu.ops.lex import searchsorted_words_2sided_fp

                lo, hi = searchsorted_words_2sided_fp(self._dev_rows, q)
                lo, hi = np.asarray(lo), np.asarray(hi)
            else:
                qv = row_sort_keys(np.ascontiguousarray(q))
                lo = np.searchsorted(self._void, qv, side="left")
                hi = np.searchsorted(self._void, qv, side="right")
            for j, k in enumerate(written_keys):
                for i in range(int(lo[j]), int(hi[j])):
                    # Packed rows truncate at max_key_bytes: confirm the
                    # candidate run by exact bytes (runs are length 1
                    # outside pathological shared-prefix keyspaces).
                    if self._sorted[i] == k and k in self._by_key:
                        out.append(k)
                        break
        return out

    # -- the per-version sweep ----------------------------------------------

    def sweep(self, version: int, written: list[tuple[bytes, bytes | None]]) -> int:
        """Match one committed version's written keys (key → FINAL value at
        that version) against the armed set; fire promises whose expected
        value differs. Returns the number fired."""
        if not written or not self._count:
            return 0
        self.stats["sweeps"] += 1
        self.stats["swept_writes"] += len(written)
        final: dict[bytes, bytes | None] = {}
        for k, v in written:
            final[k] = v  # last write in the version wins
        fired = 0
        for key in self._candidate_keys(list(final)):
            entries = self._by_key.get(key)
            if not entries:
                continue
            value = final[key]
            keep = [(e, p) for e, p in entries if value == e]
            for _e, p in entries:
                if value != _e:
                    p.send(version)
                    fired += 1
            if keep:
                self._by_key[key] = keep
            else:
                del self._by_key[key]
                self._dead += 1
        self._count -= fired
        self.stats["fired"] += fired
        return fired

    # -- shard-move cancellation ---------------------------------------------

    def cancel_range(self, begin: bytes, end: bytes):
        """Disarm every watch in [begin, end): bisect the sorted index,
        scan only the hit run plus the pending tail. Returns the
        disarmed ``(key, expect, promise)`` entries (the storage server
        fails them with wrong_shard_server)."""
        # NOT _consolidate(): a cancel must stay O(log n + hits) even
        # right after a burst of adds — the pending tail is scanned
        # linearly instead (it is bounded by adds since the last sweep).
        hits: list[bytes] = []
        lo = bisect.bisect_left(self._sorted, begin)
        hi = bisect.bisect_left(self._sorted, end)
        self.stats["cancel_scanned"] += (hi - lo) + len(self._pending)
        seen = set()
        dead_rows = 0
        for k in self._sorted[lo:hi]:
            if k in self._by_key and k not in seen:
                hits.append(k)
                seen.add(k)
                dead_rows += 1  # this row in _sorted becomes a tombstone
        pend_in_range = False
        for k in self._pending:
            if begin <= k < end:
                # Pending-tail hits have no row in _sorted — they are NOT
                # tombstones, so they must not inflate _dead.
                pend_in_range = True
                if k in self._by_key and k not in seen:
                    hits.append(k)
                    seen.add(k)
        if pend_in_range:
            # Drop cancelled keys from the tail: left behind, a later
            # _consolidate would merge them into _sorted as tombstones
            # _dead never counted, drifting the prune heuristic.
            self._pending = [
                k for k in self._pending if not (begin <= k < end)
            ]
        out = []
        for k in hits:
            for expect, p in self._by_key.pop(k):
                out.append((k, expect, p))
        self._count -= len(out)
        self._dead += dead_rows
        self.stats["cancelled"] += len(out)
        return out
