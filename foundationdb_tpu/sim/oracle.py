"""Brute-force MVCC conflict oracle.

The O(n²) reference model the simulation workloads compare the real engine
against — the same role ConflictRange.actor.cpp's in-memory model plays for
the reference's simulation tests. Semantics mirror
fdbserver/ConflictSet.h exactly:

- a txn with reads and read_version < oldestVersion is TOO_OLD
  (write-only txns are never too old);
- a txn conflicts if any non-empty read range overlaps a historical write
  with version > read_version, or overlaps a write range of an EARLIER
  ACCEPTED txn in the same batch;
- accepted txns' write ranges enter the history at the batch commit version.

``wave_commit=True`` replaces the third rule with the reorder-don't-abort
schedule (conflict_kernel phase 2b): the intra-batch constraint
"i must serialize before j" exists exactly when reads(i) ∩ writes(j) ≠ ∅,
the constraint digraph is leveled into commit WAVES, and only txns on true
cycles abort — one deterministic min-index victim per stall, replaying the
kernel's ``_cycle_victim`` walk byte-for-byte so engine/oracle parity holds
on verdicts AND schedules (``last_wave``).
"""

from __future__ import annotations

from foundationdb_tpu.core.types import (
    WAVE_LEVEL_CYCLE as LEVEL_CYCLE,
    WAVE_LEVEL_NONE as LEVEL_NONE,
    KeyRange,
    TxnConflictInfo,
    Verdict,
)
from foundationdb_tpu.core.wavemesh import (
    WaveEdges,
    level_wave_graph,
    pack_pred_rows,
    schedule_graph,
    unpack_pred_rows,
    verdicts_from_schedule,
)


class OracleConflictSet:
    def __init__(self, wave_commit: bool = False) -> None:
        self.history: list[tuple[KeyRange, int]] = []
        self.oldest_version = 0
        self.wave_commit = wave_commit
        # Exact conflicting read ranges of the LAST resolve call, by txn
        # index — only recorded for txns that asked (report_conflicting_keys;
        # reference: conflictingKRIndices in ResolveTransactionBatchReply).
        self.last_conflicting: dict[int, list[KeyRange]] = {}
        # Wave levels of the LAST resolve call (wave_commit engines only):
        # >= 0 committed at that wave, LEVEL_CYCLE aborted on a true
        # cycle, LEVEL_NONE every other non-commit. last_reordered counts
        # the commits past wave 0 (same contract as TPUConflictSet).
        self.last_wave: list[int] | None = None
        self.last_reordered: int | None = None
        # Role-level global wave protocol (core/wavemesh): resolve_edges
        # stashes the window here until resolve_apply paints it.
        self._wave_pending: "tuple | None" = None

    @property
    def wave_global_capable(self) -> bool:
        """Wave-commit oracles implement the two-phase global protocol
        (resolve_edges/resolve_apply), so sharded multi-resolver wave
        deployments are legal with this engine."""
        return self.wave_commit

    def resolve(
        self,
        txns: list[TxnConflictInfo],
        commit_version: int,
        oldest_version: int | None = None,
    ) -> list[Verdict]:
        if oldest_version is not None:
            self.oldest_version = max(self.oldest_version, oldest_version)
        if self.wave_commit:
            return self._resolve_wave(txns, commit_version)
        verdicts: list[Verdict] = []
        accepted_writes: list[KeyRange] = []
        self.last_conflicting = {}
        for i, t in enumerate(txns):
            reads = [r for r in t.read_ranges if not r.empty]
            if reads and t.read_version < self.oldest_version:
                verdicts.append(Verdict.TOO_OLD)
                continue

            def bad(r: KeyRange, t=t, accepted=accepted_writes) -> bool:
                return any(
                    r.overlaps(w) and v > t.read_version
                    for (w, v) in self.history
                ) or any(r.overlaps(w) for w in accepted)

            conflicting = [r for r in reads if bad(r)]
            if conflicting:
                verdicts.append(Verdict.CONFLICT)
                if t.report_conflicting_keys:
                    self.last_conflicting[i] = conflicting
                continue
            verdicts.append(Verdict.COMMITTED)
            accepted_writes.extend(w for w in t.write_ranges if not w.empty)
        self.history.extend((w, commit_version) for w in accepted_writes)
        # GC below the window floor (matches the kernel's clamp-to-sentinel).
        self.history = [
            (w, v) for (w, v) in self.history if v > self.oldest_version
        ]
        return verdicts

    # -- wave commit (reorder-don't-abort) ----------------------------------

    def _resolve_wave(
        self, txns: list[TxnConflictInfo], commit_version: int
    ) -> list[Verdict]:
        n = len(txns)
        self.last_conflicting = {}
        verdicts: list[Verdict | None] = [None] * n
        reads = [[r for r in t.read_ranges if not r.empty] for t in txns]
        writes = [[w for w in t.write_ranges if not w.empty] for t in txns]

        # History gate first (unchanged from sequential acceptance): the
        # wave schedule only reorders txns whose reads are clean against
        # every PRIOR batch.
        cand: list[int] = []
        for i, t in enumerate(txns):
            if reads[i] and t.read_version < self.oldest_version:
                verdicts[i] = Verdict.TOO_OLD
                continue
            hist = [
                r for r in reads[i]
                if any(r.overlaps(w) and v > t.read_version
                       for (w, v) in self.history)
            ]
            if hist:
                verdicts[i] = Verdict.CONFLICT
                if t.report_conflicting_keys:
                    self.last_conflicting[i] = hist
                continue
            cand.append(i)

        # pred[j] = {i : reads(i) ∩ writes(j) ≠ ∅} — i must serialize
        # BEFORE j (i must not observe j's write). Candidates only,
        # diagonal excluded — exactly _pred_matrix_packed's bitset.
        pred: dict[int, set[int]] = {
            j: {
                i for i in cand
                if i != j and any(
                    r.overlaps(w) for r in reads[i] for w in writes[j]
                )
            }
            for j in cand
        }

        # Deterministic leveling — ONE implementation (core/wavemesh,
        # replaying the kernel's _wave_level_packed rule byte-for-byte)
        # shared with the role-level global-graph apply path below.
        level = level_wave_graph(n, cand, pred)

        committed_writes = [w for j in cand if level[j] >= 0 for w in writes[j]]
        for i in cand:
            if level[i] >= 0:
                verdicts[i] = Verdict.COMMITTED
                continue
            verdicts[i] = Verdict.CONFLICT
            if txns[i].report_conflicting_keys:
                # A cycle victim's losers: its reads overlapping same-batch
                # WINNERS' writes (those land at commit_version; a repair
                # replay at commit_version-1 re-validates over a window
                # that includes them — see repair/engine.py's soundness
                # argument). Degrades to the full read set if the cycle
                # was broken before its peers committed.
                lost = [
                    r for r in reads[i]
                    if any(r.overlaps(w) for w in committed_writes)
                ]
                self.last_conflicting[i] = lost or list(reads[i])
        self.history.extend(
            (w, commit_version) for w in committed_writes
        )
        self.history = [
            (w, v) for (w, v) in self.history if v > self.oldest_version
        ]
        self.last_wave = level
        self.last_reordered = sum(1 for lv in level if lv > 0)
        return verdicts  # type: ignore[return-value]

    # -- role-level global wave protocol (core/wavemesh) ---------------------

    def _gate_and_pred(self, txns: list[TxnConflictInfo]):
        """(too_old, hist_conflict, hist_losers, pred): this shard's
        clipped gate + predecessor sets over LOCAL candidates — phase 1's
        raw material, shared with nothing else so the single-shard
        _resolve_wave stays byte-identical to its history."""
        n = len(txns)
        reads = [[r for r in t.read_ranges if not r.empty] for t in txns]
        writes = [[w for w in t.write_ranges if not w.empty] for t in txns]
        too_old = [
            bool(reads[i] and t.read_version < self.oldest_version)
            for i, t in enumerate(txns)
        ]
        hist_losers = [
            [] if too_old[i] else [
                r for r in reads[i]
                if any(r.overlaps(w) and v > txns[i].read_version
                       for (w, v) in self.history)
            ]
            for i in range(n)
        ]
        hist_conflict = [bool(h) for h in hist_losers]
        cand = [
            i for i in range(n) if not too_old[i] and not hist_conflict[i]
        ]
        pred = {
            j: {
                i for i in cand
                if i != j and any(
                    r.overlaps(w) for r in reads[i] for w in writes[j]
                )
            }
            for j in cand
        }
        return too_old, hist_conflict, hist_losers, pred

    def resolve_edges(
        self,
        txns: list[TxnConflictInfo],
        commit_version: int,
        oldest_version: int | None = None,
    ) -> WaveEdges:
        """Phase 1: gate this shard's clipped view and pack its clipped
        predecessor bitsets; nothing is painted until resolve_apply."""
        if not self.wave_global_capable:
            raise ValueError("resolve_edges requires a wave-commit oracle")
        if self._wave_pending is not None:
            raise ValueError(
                "resolve_edges with an apply outstanding (version chain)"
            )
        import numpy as np

        if oldest_version is not None:
            self.oldest_version = max(self.oldest_version, oldest_version)
        too_old, hist_conflict, hist_losers, pred = self._gate_and_pred(txns)
        n = len(txns)
        self._wave_pending = (txns, commit_version, hist_losers)
        return WaveEdges(
            count=n,
            too_old=np.asarray(too_old, bool),
            hist_conflict=np.asarray(hist_conflict, bool),
            chunks=[(n, pack_pred_rows(pred, n))] if n else [],
        )

    def resolve_abandon(self) -> None:
        """Drop a pending resolve_edges without painting (fail-safe
        elsewhere in the deployment rejected the window)."""
        self._wave_pending = None

    def resolve_apply(self, graph) -> list[Verdict]:
        """Phase 2: level the combined GLOBAL graph with the shared
        deterministic rule, paint this shard's clipped accepted writes,
        publish last_wave/last_reordered. Every shard receives the same
        graph, so every shard reports the identical schedule."""
        if self._wave_pending is None:
            raise ValueError("resolve_apply without a pending resolve_edges")
        txns, commit_version, hist_losers = self._wave_pending
        self._wave_pending = None
        levels, reordered = schedule_graph(graph)
        verdicts = verdicts_from_schedule(graph, levels)
        reads = [[r for r in t.read_ranges if not r.empty] for t in txns]
        writes = [[w for w in t.write_ranges if not w.empty] for t in txns]
        committed_writes = [
            w for i in range(len(txns)) if levels[i] >= 0 for w in writes[i]
        ]
        self.last_conflicting = {}
        for i, t in enumerate(txns):
            if verdicts[i] != Verdict.CONFLICT or not t.report_conflicting_keys:
                continue
            if hist_losers[i]:
                self.last_conflicting[i] = list(hist_losers[i])
                continue
            lost = [
                r for r in reads[i]
                if any(r.overlaps(w) for w in committed_writes)
            ]
            # Only losers THIS shard can witness are reported (the proxy
            # unions the shards); a txn gated purely elsewhere reports
            # nothing here.
            if lost:
                self.last_conflicting[i] = lost
        self.history.extend((w, commit_version) for w in committed_writes)
        self.history = [
            (w, v) for (w, v) in self.history if v > self.oldest_version
        ]
        self.last_wave = levels
        self.last_reordered = reordered
        return verdicts


class ReplayCheckedOracle(OracleConflictSet):
    """OracleConflictSet that PROVES each wave verdict by sequential
    replay, inline, on every resolve call.

    With ``wave_commit=True`` every resolve snapshots the pre-batch
    history and runs ``replay_wave_schedule`` over the verdicts it is
    about to return — a sequential executor replaying the realized
    (wave, index) order must agree byte-for-byte, or the resolve raises
    instead of answering. This is the engine behind the wave-commit A/B's
    "oracle-verified serializability" claim (repair/bench.py) and the
    nemesis campaigns' exactness rule. With ``wave_commit=False`` the
    sequential oracle's acceptance rule IS sequential replay (each txn is
    validated against the already-replayed prefix), so the subclass adds
    nothing beyond the shared entry point for A/B harnesses.
    """

    def resolve(
        self,
        txns: list[TxnConflictInfo],
        commit_version: int,
        oldest_version: int | None = None,
    ) -> list[Verdict]:
        if not self.wave_commit:
            return super().resolve(txns, commit_version, oldest_version)
        if oldest_version is not None:
            # Mirror the base class's floor advance BEFORE snapshotting:
            # replay must judge TOO_OLD against the same floor.
            self.oldest_version = max(self.oldest_version, oldest_version)
        history_before = list(self.history)
        floor_before = self.oldest_version
        verdicts = super().resolve(txns, commit_version, None)
        replay_wave_schedule(
            txns, verdicts, self.last_wave, history_before, floor_before
        )
        return verdicts

    def resolve_apply(self, graph) -> list[Verdict]:
        """Global-wave phase 2 with inline verification of everything
        THIS SHARD can witness, plus the graph-global cycle rule:

        - serial-order replay over the shard's CLIPPED ranges — a
          committed txn's reads must overlap no pre-batch write past its
          read version and no earlier-ordered committed write. Every
          overlap in the keyspace is clipped into the shard(s) owning
          those keys, so all shards passing their local replays IS a
          proof of global serializability;
        - edge-respect on local edges (level(i) < level(j));
        - cycle-only aborts on the GLOBAL graph (the shard holds the
          OR-reduced matrix — a cross-shard cycle is checkable here even
          though no single shard's ranges witness all its edges)."""
        history_before = list(self.history)
        floor_before = self.oldest_version
        txns = self._wave_pending[0] if self._wave_pending else []
        verdicts = super().resolve_apply(graph)
        replay_wave_schedule(
            txns, verdicts, self.last_wave, history_before, floor_before,
            global_graph=graph,
        )
        return verdicts


def replay_wave_schedule(
    txns: list[TxnConflictInfo],
    verdicts: list[Verdict],
    levels: list[int],
    history: list[tuple[KeyRange, int]],
    oldest_version: int = 0,
    global_graph=None,
) -> None:
    """Sequentially replay a wave schedule and raise AssertionError on any
    serializability violation — the acceptance check behind the wave-commit
    A/B (ISSUE 7): a sequential executor visiting committed txns in
    realized order (wave level, then batch index) must reproduce the
    engine's verdicts byte-for-byte.

    Checks, against ``history`` as it stood BEFORE the batch:
    - every committed txn's reads overlap no historical write past its
      read version and no write of a txn EARLIER in the realized order;
    - every committed txn with reads is within the MVCC window;
    - every CONFLICT either fails the history gate or sits on a true
      cycle of the candidate constraint graph (cycle-only aborts);
    - levels respect the constraint digraph: reads(i) ∩ writes(j) ≠ ∅
      for committed i, j implies level(i) < level(j).
    """
    reads = [[r for r in t.read_ranges if not r.empty] for t in txns]
    writes = [[w for w in t.write_ranges if not w.empty] for t in txns]
    order = sorted(
        (i for i, v in enumerate(verdicts) if v == Verdict.COMMITTED),
        key=lambda i: (levels[i], i),
    )
    replayed: list[KeyRange] = []
    for i in order:
        assert levels[i] >= 0, f"txn {i}: committed without a wave level"
        t = txns[i]
        if reads[i]:
            assert t.read_version >= oldest_version, (
                f"txn {i}: committed outside the MVCC window"
            )
        for r in reads[i]:
            assert not any(
                r.overlaps(w) and v > t.read_version for (w, v) in history
            ), f"txn {i}: committed over a history conflict"
            assert not any(r.overlaps(w) for w in replayed), (
                f"txn {i}: read overlaps an earlier-ordered write — the "
                f"realized order is not serial"
            )
        replayed.extend(writes[i])
    # Ordering respects every constraint edge among committed txns.
    for i in order:
        for j in order:
            if i != j and any(
                r.overlaps(w) for r in reads[i] for w in writes[j]
            ):
                assert levels[i] < levels[j], (
                    f"edge {i}->{j} violated: level {levels[i]} !< {levels[j]}"
                )
    # Cycle-only aborts: every intra-batch CONFLICT must lie on a cycle of
    # the candidate graph (candidates = txns passing the history gate).
    # Under the role-level global protocol the candidate set and the edge
    # set are GLOBAL (``global_graph``: a txn gated on another shard is
    # no candidate here, and a cycle may thread edges through several
    # shards' keyspace slices) — the local recomputation below would
    # misjudge both.
    if global_graph is not None:
        cand = [i for i in range(global_graph.count) if global_graph.cand[i]]
        cset = set(cand)
        pred = {}
        start = 0
        for nc, m in global_graph.chunks:
            for j, preds in unpack_pred_rows(m, nc).items():
                if start + j in cset:
                    pred[start + j] = {
                        start + i for i in preds if start + i in cset
                    }
            start += nc
    else:
        cand = [
            i for i, v in enumerate(verdicts)
            if v != Verdict.TOO_OLD and not (
                reads[i] and any(
                    r.overlaps(w) and v2 > txns[i].read_version
                    for r in reads[i] for (w, v2) in history
                )
            )
        ]
        cset = set(cand)
        pred = {
            j: {
                i for i in cand
                if i != j and any(
                    r.overlaps(w) for r in reads[i] for w in writes[j]
                )
            }
            for j in cand
        }
    for i in cand:
        if verdicts[i] != Verdict.CONFLICT:
            continue
        assert levels[i] == LEVEL_CYCLE, (
            f"txn {i}: intra-batch abort without the cycle level"
        )
        assert _on_cycle(i, pred, cset), (
            f"txn {i}: aborted but lies on no cycle of the constraint graph"
        )


def _on_cycle(i: int, pred: dict[int, set[int]], nodes: set[int]) -> bool:
    """Is node i on a directed cycle of the predecessor graph restricted
    to ``nodes``? (DFS from i through predecessors back to i.)"""
    stack, seen = [i], set()
    while stack:
        j = stack.pop()
        # frozenset default: the global-graph path hands a SPARSE pred
        # (entries only for txns with at least one predecessor bit).
        for k in pred.get(j, frozenset()) & nodes:
            if k == i:
                return True
            if k not in seen:
                seen.add(k)
                stack.append(k)
    return False
