"""Brute-force MVCC conflict oracle.

The O(n²) reference model the simulation workloads compare the real engine
against — the same role ConflictRange.actor.cpp's in-memory model plays for
the reference's simulation tests. Semantics mirror
fdbserver/ConflictSet.h exactly:

- a txn with reads and read_version < oldestVersion is TOO_OLD
  (write-only txns are never too old);
- a txn conflicts if any non-empty read range overlaps a historical write
  with version > read_version, or overlaps a write range of an EARLIER
  ACCEPTED txn in the same batch;
- accepted txns' write ranges enter the history at the batch commit version.
"""

from __future__ import annotations

from foundationdb_tpu.core.types import KeyRange, TxnConflictInfo, Verdict


class OracleConflictSet:
    def __init__(self) -> None:
        self.history: list[tuple[KeyRange, int]] = []
        self.oldest_version = 0
        # Exact conflicting read ranges of the LAST resolve call, by txn
        # index — only recorded for txns that asked (report_conflicting_keys;
        # reference: conflictingKRIndices in ResolveTransactionBatchReply).
        self.last_conflicting: dict[int, list[KeyRange]] = {}

    def resolve(
        self,
        txns: list[TxnConflictInfo],
        commit_version: int,
        oldest_version: int | None = None,
    ) -> list[Verdict]:
        if oldest_version is not None:
            self.oldest_version = max(self.oldest_version, oldest_version)
        verdicts: list[Verdict] = []
        accepted_writes: list[KeyRange] = []
        self.last_conflicting = {}
        for i, t in enumerate(txns):
            reads = [r for r in t.read_ranges if not r.empty]
            if reads and t.read_version < self.oldest_version:
                verdicts.append(Verdict.TOO_OLD)
                continue

            def bad(r: KeyRange, t=t, accepted=accepted_writes) -> bool:
                return any(
                    r.overlaps(w) and v > t.read_version
                    for (w, v) in self.history
                ) or any(r.overlaps(w) for w in accepted)

            conflicting = [r for r in reads if bad(r)]
            if conflicting:
                verdicts.append(Verdict.CONFLICT)
                if t.report_conflicting_keys:
                    self.last_conflicting[i] = conflicting
                continue
            verdicts.append(Verdict.COMMITTED)
            accepted_writes.extend(w for w in t.write_ranges if not w.empty)
        self.history.extend((w, commit_version) for w in accepted_writes)
        # GC below the window floor (matches the kernel's clamp-to-sentinel).
        self.history = [
            (w, v) for (w, v) in self.history if v > self.oldest_version
        ]
        return verdicts
