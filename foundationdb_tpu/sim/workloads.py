"""Simulation workloads: randomized transactional load with correctness
oracles, run against a SimCluster under seeded fault injection.

Reference: fdbserver/workloads/ (~200 actors driven by TOML specs in
tests/). The ones re-built here are the load-bearing correctness suite:

- CycleWorkload        — Cycle.actor.cpp: the canonical serializability
  check. Keys form a permutation ring; txns swap successor pointers; any
  lost/torn/reordered update breaks the single-cycle invariant.
- AtomicOpsWorkload    — AtomicOps.actor.cpp: concurrent atomic ADD/MAX/
  MIN/XOR streams vs an exactly-computable expected state.
- RandomReadWriteWorkload — mako/YCSB-style mixed load (Zipf hot keys);
  throughput/liveness under contention, with read-your-committed checks.
- ConflictRangeWorkload — ConflictRange.actor.cpp: randomized range
  read/write sets through the real commit path; verdict parity is covered
  kernel-side (tests/test_conflict_oracle.py), here we assert observable
  serializability of the committed history.
- FaultInjector        — the machine-kill/clogging half of the reference's
  simulation: a seeded actor that kills generation processes, injects
  partitions, and heals them, on a schedule drawn from the loop's RNG.

Every workload exposes  setup(db) / run(db) / check(db)  like the
reference's TestWorkload interface; `run_workload` wires one (plus
optional faults) onto a cluster and returns its metrics.
"""

from __future__ import annotations

import bisect
import struct
from dataclasses import dataclass, field

from foundationdb_tpu.core.errors import FdbError
from foundationdb_tpu.core.mutations import MutationType
from foundationdb_tpu.core.types import strinc
from foundationdb_tpu.runtime.flow import Promise, all_of


class WorkloadFailed(FdbError):
    """An invariant check failed — the simulation found a bug."""

    code = 1500


@dataclass
class WorkloadMetrics:
    txns_committed: int = 0
    txns_retried: int = 0
    txns_failed: int = 0
    ops: int = 0
    extra: dict = field(default_factory=dict)


class Workload:
    """Reference: TestWorkload — setup once, run concurrent clients, then
    check invariants on the quiesced database."""

    name = "workload"

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.metrics = WorkloadMetrics()

    async def setup(self, db) -> None:  # pragma: no cover - interface
        pass

    async def run(self, db, cluster) -> None:  # pragma: no cover - interface
        pass

    async def check(self, db) -> None:  # pragma: no cover - interface
        pass

    # -- helpers -------------------------------------------------------------

    async def _run_txn(self, db, fn, max_retries: int = 100):
        """Delegates to the ONE canonical retry loop (Database.run), adding
        only attempt accounting; tolerates cluster recoveries."""
        attempts = [0]

        async def counted(tr):
            attempts[0] += 1
            return await fn(tr)

        try:
            result = await db.run(counted, max_retries=max_retries)
        except FdbError:
            self.metrics.txns_failed += 1
            raise
        self.metrics.txns_committed += 1
        self.metrics.txns_retried += attempts[0] - 1
        return result

    @staticmethod
    def _split(n_txns: int, n_clients: int) -> list[int]:
        """Per-client txn counts summing exactly to n_txns (no silent
        remainder drop when n_txns % n_clients != 0)."""
        base, rem = divmod(n_txns, n_clients)
        return [base + (1 if i < rem else 0) for i in range(n_clients)]


class CycleWorkload(Workload):
    """Keys 0..N-1 hold a permutation forming one cycle; each transaction
    picks a random node A and rotates A's successor: A→B→C becomes A→C→B...
    preserving the permutation-single-cycle invariant IF AND ONLY IF every
    transaction is atomic and serializable (reference: Cycle.actor.cpp)."""

    name = "cycle"

    def __init__(self, seed: int = 0, n_nodes: int = 16, n_txns: int = 60,
                 n_clients: int = 4):
        super().__init__(seed)
        self.n_nodes = n_nodes
        self.n_txns = n_txns
        self.n_clients = n_clients

    def _key(self, i: int) -> bytes:
        return b"cycle/%06d" % i

    async def setup(self, db) -> None:
        async def body(tr):
            for i in range(self.n_nodes):
                tr.set(self._key(i), struct.pack("<q", (i + 1) % self.n_nodes))

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        rng = cluster.loop.rng

        counts = self._split(self.n_txns, self.n_clients)

        async def client(cid: int):
            for _ in range(counts[cid]):
                a = rng.randrange(self.n_nodes)

                async def body(tr, a=a):
                    b = struct.unpack("<q", await tr.get(self._key(a)))[0]
                    c = struct.unpack("<q", await tr.get(self._key(b)))[0]
                    d = struct.unpack("<q", await tr.get(self._key(c)))[0]
                    # Rotate: a -> c -> b -> d
                    tr.set(self._key(a), struct.pack("<q", c))
                    tr.set(self._key(c), struct.pack("<q", b))
                    tr.set(self._key(b), struct.pack("<q", d))

                await self._run_txn(db, body)
                self.metrics.ops += 3

        await all_of(
            [
                cluster.loop.spawn(client(i), name=f"cycle.client{i}")
                for i in range(self.n_clients)
            ]
        )

    async def check(self, db) -> None:
        async def body(tr):
            succ = []
            for i in range(self.n_nodes):
                v = await tr.get(self._key(i))
                if v is None:
                    raise WorkloadFailed(f"cycle: node {i} missing")
                succ.append(struct.unpack("<q", v)[0])
            return succ

        succ = await self._run_txn(db, body)
        seen, node = set(), 0
        for _ in range(self.n_nodes):
            if node in seen:
                raise WorkloadFailed(f"cycle: not a single cycle (revisit {node})")
            seen.add(node)
            node = succ[node]
        if node != 0 or len(seen) != self.n_nodes:
            raise WorkloadFailed("cycle: broken ring — lost or torn update")


class AtomicOpsWorkload(Workload):
    """Concurrent atomic-op streams whose final state is exactly computable:
    ADD totals, MAX/MIN extremes, XOR parity (reference: AtomicOps.actor.cpp
    compares a log-derived expectation against the db)."""

    name = "atomic_ops"

    def __init__(self, seed: int = 0, n_keys: int = 4, n_txns: int = 48,
                 n_clients: int = 4):
        super().__init__(seed)
        self.n_keys = n_keys
        self.n_txns = n_txns
        self.n_clients = n_clients
        self._expected_add = [0] * n_keys
        self._expected_max = [0] * n_keys
        self._expected_xor = [0] * n_keys

    async def run(self, db, cluster) -> None:
        rng = cluster.loop.rng
        # Pre-draw the op log so the expectation is independent of commit
        # interleaving (atomic ops commute — that is the point of the test).
        plan = []
        for count in self._split(self.n_txns, self.n_clients):
            ops = []
            for _ in range(count):
                k = rng.randrange(self.n_keys)
                val = rng.randrange(1, 1000)
                ops.append((k, val))
                self._expected_add[k] += val
                self._expected_max[k] = max(self._expected_max[k], val)
                self._expected_xor[k] ^= val
            plan.append(ops)

        async def client(cid, ops):
            for n, (k, val) in enumerate(ops):
                # Idempotency marker: a CommitUnknownResult retry of a txn
                # that DID commit must not re-apply its ADD/XOR (the
                # expectation counts each op exactly once).
                marker = b"aop/done/%d/%d" % (cid, n)

                async def body(tr, k=k, val=val, marker=marker):
                    if await tr.get(marker) is not None:
                        return  # earlier attempt committed
                    tr.set(marker, b"")
                    p = struct.pack("<q", val)
                    tr.atomic_op(MutationType.ADD, b"aop/add/%d" % k, p)
                    tr.atomic_op(MutationType.MAX, b"aop/max/%d" % k, p)
                    tr.atomic_op(MutationType.XOR, b"aop/xor/%d" % k, p)

                await self._run_txn(db, body)
                self.metrics.ops += 3

        await all_of(
            [
                cluster.loop.spawn(client(i, ops), name=f"aop.client{i}")
                for i, ops in enumerate(plan)
            ]
        )

    async def check(self, db) -> None:
        async def body(tr):
            for k in range(self.n_keys):
                for kind, expected in (
                    ("add", self._expected_add[k]),
                    ("max", self._expected_max[k]),
                    ("xor", self._expected_xor[k]),
                ):
                    raw = await tr.get(b"aop/%s/%d" % (kind.encode(), k))
                    got = struct.unpack("<q", raw)[0] if raw else 0
                    if got != expected:
                        raise WorkloadFailed(
                            f"atomic {kind}[{k}]: got {got}, want {expected}"
                        )

        await self._run_txn(db, body)


class RandomReadWriteWorkload(Workload):
    """mako/YCSB-style mixed point load on a hot-key distribution; checks
    that every acked write is durably readable (read-your-committed)."""

    name = "random_rw"

    def __init__(self, seed: int = 0, n_keys: int = 32, n_txns: int = 80,
                 n_clients: int = 4, write_fraction: float = 0.5):
        super().__init__(seed)
        self.n_keys = n_keys
        self.n_txns = n_txns
        self.n_clients = n_clients
        self.write_fraction = write_fraction
        self._acked: dict[bytes, bytes] = {}  # key -> last acked write

    def _key(self, i: int) -> bytes:
        return b"rw/%06d" % i

    async def run(self, db, cluster) -> None:
        rng = cluster.loop.rng
        counter = [0]
        counts = self._split(self.n_txns, self.n_clients)

        async def client(cid: int):
            for _ in range(counts[cid]):
                k = self._key(min(int(rng.paretovariate(1.5)) - 1, self.n_keys - 1))
                if rng.random() < self.write_fraction:
                    counter[0] += 1
                    val = b"v%08d" % counter[0]

                    async def body(tr, k=k, val=val):
                        await tr.get(k)
                        tr.set(k, val)

                    await self._run_txn(db, body)
                    # Acked: later sequential writes may overwrite, so track
                    # program order per client stream (last committed wins
                    # within this client; cross-client order is by commit).
                    self._acked[k] = val
                else:
                    async def body(tr, k=k):
                        return await tr.get(k)

                    await self._run_txn(db, body)
                self.metrics.ops += 1

        await all_of(
            [
                cluster.loop.spawn(client(i), name=f"rw.client{i}")
                for i in range(self.n_clients)
            ]
        )

    async def check(self, db) -> None:
        async def body(tr):
            for k in self._acked:
                if await tr.get(k) is None:
                    raise WorkloadFailed(f"rw: acked write to {k!r} lost")

        await self._run_txn(db, body)


class MakoWorkload(Workload):
    """mako-style fixed op mix (reference: bindings/c/test/mako): each
    transaction runs `reads_per_txn` GETs and `writes_per_txn` UPDATEs on a
    preloaded row set (the classic 90/10 mix is 9 reads + 1 write). The
    check is read-your-committed from the database itself: every surviving
    value must be one some client actually committed (values are tagged
    with client id + sequence, so torn/partial writes are detectable)."""

    name = "mako"

    def __init__(self, seed: int = 0, rows: int = 64, n_txns: int = 60,
                 n_clients: int = 4, reads_per_txn: int = 9,
                 writes_per_txn: int = 1):
        super().__init__(seed)
        self.rows = rows
        self.n_txns = n_txns
        self.n_clients = n_clients
        self.reads_per_txn = reads_per_txn
        self.writes_per_txn = writes_per_txn
        self._committed: dict[bytes, set[bytes]] = {}

    def _key(self, i: int) -> bytes:
        return b"mako%08d" % i

    async def setup(self, db) -> None:
        async def body(tr):
            for i in range(self.rows):
                k = self._key(i)
                tr.set(k, b"init")
                self._committed.setdefault(k, set()).add(b"init")

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        rng = cluster.loop.rng
        counts = self._split(self.n_txns, self.n_clients)

        async def client(cid: int):
            for seq in range(counts[cid]):
                picks_r = [rng.randrange(self.rows)
                           for _ in range(self.reads_per_txn)]
                picks_w = [rng.randrange(self.rows)
                           for _ in range(self.writes_per_txn)]
                vals = {self._key(i): b"c%d.%d.%d" % (cid, seq, i)
                        for i in picks_w}

                async def body(tr, picks_r=picks_r, vals=vals):
                    for i in picks_r:
                        await tr.get(self._key(i))
                    for k, v in vals.items():
                        tr.set(k, v)

                await self._run_txn(db, body)
                for k, v in vals.items():
                    self._committed.setdefault(k, set()).add(v)
                self.metrics.ops += self.reads_per_txn + self.writes_per_txn

        await all_of([
            cluster.loop.spawn(client(i), name=f"mako.client{i}")
            for i in range(self.n_clients)
        ])

    async def check(self, db) -> None:
        async def body(tr):
            rows = await tr.get_range(self._key(0), self._key(self.rows))
            if len(rows) != self.rows:
                raise WorkloadFailed(
                    f"mako: {len(rows)} rows survive, expected {self.rows}"
                )
            for k, v in rows:
                if v not in self._committed.get(k, ()):
                    raise WorkloadFailed(
                        f"mako: {k!r} holds {v!r}, never committed"
                    )

        await self._run_txn(db, body)


class TPCCNewOrderWorkload(Workload):
    """Simplified TPC-C new-order mix (reference: mako's tpcc-flavored
    configs; the §5 baseline's 'TPC-C new-order, 1M txns/s sustained').

    Schema (tuple-layer keys): per (warehouse, district) a next_order_id
    counter; per item a stock level; orders + order lines inserted by each
    new-order transaction. Invariants checked from the database alone:

    - order ids are dense: next_order_id - 1 == #orders for the district
      (a lost or double-committed order breaks it);
    - stock conservation: initial_stock == stock + sum(order-line qty)
      - 100 * restocks (restocks ride an atomic ADD counter).
    """

    name = "tpcc_new_order"

    def __init__(self, seed: int = 0, warehouses: int = 2, districts: int = 2,
                 items: int = 20, n_txns: int = 40, n_clients: int = 4,
                 initial_stock: int = 100):
        super().__init__(seed)
        self.warehouses = warehouses
        self.districts = districts
        self.items = items
        self.n_txns = n_txns
        self.n_clients = n_clients
        self.initial_stock = initial_stock

    # -- keys (tuple layer) ---------------------------------------------------

    @staticmethod
    def _pack(*parts) -> bytes:
        from foundationdb_tpu.layers.tuple_layer import pack

        return pack(parts)

    def k_district(self, w, d) -> bytes:
        return self._pack("tpcc", "district", w, d)

    def k_stock(self, i) -> bytes:
        return self._pack("tpcc", "stock", i)

    def k_order(self, w, d, oid) -> bytes:
        return self._pack("tpcc", "order", w, d, oid)

    def k_restocks(self) -> bytes:
        return self._pack("tpcc", "restocks")

    async def setup(self, db) -> None:
        async def body(tr):
            for w in range(self.warehouses):
                for d in range(self.districts):
                    tr.set(self.k_district(w, d), struct.pack("<q", 1))
            for i in range(self.items):
                tr.set(self.k_stock(i), struct.pack("<q", self.initial_stock))
            tr.set(self.k_restocks(), struct.pack("<q", 0))

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        rng = cluster.loop.rng
        counts = self._split(self.n_txns, self.n_clients)

        async def new_order(cid: int):
            for _ in range(counts[cid]):
                w = rng.randrange(self.warehouses)
                d = rng.randrange(self.districts)
                n_lines = rng.randrange(3, 8)
                lines = [(rng.randrange(self.items), rng.randrange(1, 5))
                         for _ in range(n_lines)]

                async def body(tr, w=w, d=d, lines=lines):
                    (oid,) = struct.unpack("<q", await tr.get(self.k_district(w, d)))
                    tr.set(self.k_district(w, d), struct.pack("<q", oid + 1))
                    tr.set(
                        self.k_order(w, d, oid),
                        self._pack(*[x for ln in lines for x in ln]),
                    )
                    for item, qty in lines:
                        (stock,) = struct.unpack(
                            "<q", await tr.get(self.k_stock(item))
                        )
                        stock -= qty
                        if stock < 10:  # TPC-C's restock rule
                            stock += 100
                            tr.atomic_op(
                                MutationType.ADD, self.k_restocks(),
                                struct.pack("<q", 1),
                            )
                        tr.set(self.k_stock(item), struct.pack("<q", stock))

                await self._run_txn(db, body)
                self.metrics.ops += 1 + len(lines)

        await all_of([
            cluster.loop.spawn(new_order(i), name=f"tpcc.client{i}")
            for i in range(self.n_clients)
        ])

    async def check(self, db) -> None:
        from foundationdb_tpu.layers.tuple_layer import unpack

        async def body(tr):
            total_lines_qty = 0
            n_orders = 0
            for w in range(self.warehouses):
                for d in range(self.districts):
                    (next_oid,) = struct.unpack(
                        "<q", await tr.get(self.k_district(w, d))
                    )
                    lo = self.k_order(w, d, 0)
                    hi = self.k_order(w, d, 1 << 60)
                    orders = await tr.get_range(lo, hi)
                    if len(orders) != next_oid - 1:
                        raise WorkloadFailed(
                            f"tpcc: district ({w},{d}) has {len(orders)} "
                            f"orders but next_oid={next_oid}"
                        )
                    n_orders += len(orders)
                    for _k, v in orders:
                        flat = unpack(v)
                        total_lines_qty += sum(flat[1::2])
            total_stock = 0
            for i in range(self.items):
                (s,) = struct.unpack("<q", await tr.get(self.k_stock(i)))
                total_stock += s
            (restocks,) = struct.unpack("<q", await tr.get(self.k_restocks()))
            expect = self.items * self.initial_stock
            got = total_stock + total_lines_qty - 100 * restocks
            if got != expect:
                raise WorkloadFailed(
                    f"tpcc: stock not conserved: {got} != {expect} "
                    f"(stock={total_stock} lines={total_lines_qty} "
                    f"restocks={restocks}, orders={n_orders})"
                )

        await self._run_txn(db, body)


class ConflictRangeWorkload(Workload):
    """Randomized range reads + writes through the real commit path; the
    observable check is bank-style conservation: txns move value between
    accounts under range-read guards, so the total is invariant IF conflict
    detection is sound (reference: ConflictRange.actor.cpp randomized sets;
    kernel-level verdict parity lives in tests/test_conflict_oracle.py)."""

    name = "conflict_range"

    TOTAL = 1000

    def __init__(self, seed: int = 0, n_accounts: int = 8, n_txns: int = 40,
                 n_clients: int = 4):
        super().__init__(seed)
        self.n_accounts = n_accounts
        self.n_txns = n_txns
        self.n_clients = n_clients

    def _key(self, i: int) -> bytes:
        return b"bank/%04d" % i

    async def setup(self, db) -> None:
        async def body(tr):
            each = self.TOTAL // self.n_accounts
            rem = self.TOTAL - each * self.n_accounts
            for i in range(self.n_accounts):
                tr.set(self._key(i), struct.pack("<q", each + (rem if i == 0 else 0)))

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        rng = cluster.loop.rng

        counts = self._split(self.n_txns, self.n_clients)

        async def client(cid: int):
            for _ in range(counts[cid]):
                src = rng.randrange(self.n_accounts)
                dst = rng.randrange(self.n_accounts)
                amt = rng.randrange(1, 50)

                async def body(tr, src=src, dst=dst, amt=amt):
                    # Range read over the whole bank: a wide read conflict
                    # range, the thing the resolver must get right.
                    rows = await tr.get_range(b"bank/", b"bank0")
                    balances = {k: struct.unpack("<q", v)[0] for k, v in rows}
                    s, d = self._key(src), self._key(dst)
                    if balances.get(s, 0) < amt or src == dst:
                        return
                    tr.set(s, struct.pack("<q", balances[s] - amt))
                    tr.set(d, struct.pack("<q", balances[d] + amt))

                await self._run_txn(db, body)
                self.metrics.ops += 1

        await all_of(
            [
                cluster.loop.spawn(client(i), name=f"bank.client{i}")
                for i in range(self.n_clients)
            ]
        )

    async def check(self, db) -> None:
        async def body(tr):
            rows = await tr.get_range(b"bank/", b"bank0")
            total = sum(struct.unpack("<q", v)[0] for _k, v in rows)
            if total != self.TOTAL:
                raise WorkloadFailed(
                    f"bank conservation broken: total {total} != {self.TOTAL}"
                )
            negative = [k for k, v in rows if struct.unpack("<q", v)[0] < 0]
            if negative:
                raise WorkloadFailed(f"bank: negative balances {negative}")

        await self._run_txn(db, body)


class FaultInjector:
    """Seeded chaos actor (reference: the machine-kill + clogging machinery
    of SimulatedCluster): kills random generation processes and injects
    transient partitions while a workload runs. All choices come from the
    loop RNG — a seed replays the exact fault schedule."""

    def __init__(self, cluster, kill_interval: float = 2.0,
                 partition_interval: float = 1.3, partition_length: float = 0.8,
                 max_kills: int = 2, include_controller: bool = False,
                 clog_interval: float = 0.0, clog_length: float = 0.8,
                 clog_factor: float = 100.0):
        self.cluster = cluster
        self.kill_interval = kill_interval
        self.partition_interval = partition_interval
        self.partition_length = partition_length
        self.max_kills = max_kills
        # With a coordinator quorum the controller itself is fair game: a
        # rival candidate must win election and recover (the hardest
        # failure mode of the reference — CC loss).
        self.include_controller = include_controller
        # Clogging (slow-but-alive links): 0 = off.
        self.clog_interval = clog_interval
        self.clog_length = clog_length
        self.clog_factor = clog_factor
        self.kills: list[str] = []
        self.partitions = 0
        self.clogs = 0
        self._stop = False

    def stop(self) -> None:
        self._stop = True

    def _storage_procs(self) -> list[str]:
        """Actual storage process names (see SimCluster.storage_procs —
        bare "storage{i}" would silently no-op on multi-region)."""
        return self.cluster.storage_procs()

    async def run(self) -> None:
        loop = self.cluster.loop
        rng = loop.rng
        loop.spawn(self._partitioner(), name="faults.partitioner")
        if self.clog_interval > 0:
            loop.spawn(self._clogger(), name="faults.clogger")
        while not self._stop and len(self.kills) < self.max_kills:
            await loop.sleep(self.kill_interval * (0.5 + rng.random()))
            if self._stop:
                return
            gen = self.cluster.controller.generation
            victims = sorted(gen.heartbeat_eps)
            if self.include_controller and self.cluster.cc_heartbeats:
                victims.append(self.cluster.controller.identity)
            victim = victims[rng.randrange(len(victims))]
            if not self._safe_to_kill(gen, victim):
                continue  # would destroy the last durable log copy
            self.kills.append(victim)
            self.cluster.net.kill(victim)

    def _safe_to_kill(self, gen, victim: str) -> bool:
        """Never kill the LAST reachable tlog of the generation: with every
        log copy gone the durable suffix is unknowable and recovery stalls
        forever (the reference's kill machinery keeps a replica alive the
        same way — kills are permanent here, nothing reboots). Likewise a
        controller kill needs a surviving candidate to take over."""
        dead = self.cluster.loop.dead_processes
        if victim in getattr(self.cluster, "cc_heartbeats", {}):
            others = [
                p for p in self.cluster.cc_heartbeats
                if p != victim and p not in dead
            ]
            return bool(others)
        tlog_procs = [ep.process for ep in gen.tlog_eps]
        if victim not in tlog_procs:
            return True
        alive = [p for p in tlog_procs if p not in dead]
        return len(alive) > 1 or victim not in alive

    async def _partitioner(self) -> None:
        loop = self.cluster.loop
        rng = loop.rng
        while not self._stop:
            await loop.sleep(self.partition_interval * (0.5 + rng.random()))
            if self._stop:
                return
            gen = self.cluster.controller.generation
            procs = sorted(gen.heartbeat_eps) + self._storage_procs()
            a = procs[rng.randrange(len(procs))]
            b = procs[rng.randrange(len(procs))]
            if a == b:
                continue
            self.cluster.net.partition(a, b)
            self.partitions += 1
            await loop.sleep(self.partition_length)
            self.cluster.net.heal(a, b)

    async def _clogger(self) -> None:
        """Slow-but-alive links: RPCs between a random pair take ~clog_factor
        longer for clog_length — no failure detector fires, every timeout
        and ordering assumption in between is on trial (reference: sim2's
        clogging, the bug-richest fault mode)."""
        loop = self.cluster.loop
        rng = loop.rng
        while not self._stop:
            await loop.sleep(self.clog_interval * (0.5 + rng.random()))
            if self._stop:
                return
            gen = self.cluster.controller.generation
            procs = sorted(gen.heartbeat_eps) + self._storage_procs() + ["<main>"]  # client-side links clog too
            a = procs[rng.randrange(len(procs))]
            b = procs[rng.randrange(len(procs))]
            if a == b:
                continue
            self.cluster.net.clog(
                a, b, factor=self.clog_factor,
                duration=self.clog_length * (0.5 + rng.random()),
            )
            self.clogs += 1


async def run_workload(cluster, db, workload: Workload,
                       faults: FaultInjector | None = None) -> WorkloadMetrics:
    """setup → (run ∥ faults) → quiesce → check. Returns the metrics."""
    await workload.setup(db)
    fault_task = (
        cluster.loop.spawn(faults.run(), name="faults.run") if faults else None
    )
    await workload.run(db, cluster)
    if faults:
        faults.stop()
        await fault_task
        cluster.net.heal_all()
        # Quiesce: let any in-flight recovery finish before checking.
        while cluster.controller._recovering:
            await cluster.loop.sleep(0.25)
    await workload.check(db)
    return workload.metrics


class WatchesWorkload(Workload):
    """Watch semantics under concurrent mutation (reference:
    Watches.actor.cpp): watcher clients arm a watch on a key, mutator
    clients change it, and every armed watch must FIRE (spurious fires are
    legal; a hung watch is the bug). After each fire the watcher re-reads
    and re-arms. Checks: every round completed, and the final value equals
    the mutators' last write."""

    name = "watches"

    def __init__(self, seed: int = 0, n_keys: int = 4, n_rounds: int = 12):
        super().__init__(seed)
        self.n_keys = n_keys
        self.n_rounds = n_rounds

    def _key(self, i: int) -> bytes:
        return b"watch/%04d" % i

    async def setup(self, db) -> None:
        async def body(tr):
            for i in range(self.n_keys):
                tr.set(self._key(i), b"init")

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        fired = [0] * self.n_keys
        done = [False] * self.n_keys

        MAX_REARMS = 200  # bounded: a wedged cluster must FAIL, not hang

        async def watcher(i: int):
            try:
                for _ in range(self.n_rounds):
                    for attempt in range(MAX_REARMS):
                        try:
                            async def arm(tr):
                                return await tr.watch(self._key(i))

                            slot = await self._run_txn(db, arm)
                            await slot
                            break
                        except FdbError as e:
                            if not e.retryable:
                                raise
                            await cluster.loop.sleep(0.05)  # re-arm
                    else:
                        raise WorkloadFailed(
                            f"watch {i}: {MAX_REARMS} re-arms exhausted"
                        )
                    fired[i] += 1
                    self.metrics.ops += 1
            finally:
                done[i] = True  # success OR failure: release the mutator

        async def mutator(i: int):
            # Keep mutating until the watcher is satisfied: a watch armed
            # just after our final write would otherwise hang forever.
            r = 0
            while not done[i]:
                async def body(tr, r=r):
                    tr.set(self._key(i), b"round/%05d" % r)

                await self._run_txn(db, body)
                r += 1
                await cluster.loop.sleep(0.02)

        await all_of(
            [cluster.loop.spawn(watcher(i), name=f"watch.w{i}")
             for i in range(self.n_keys)]
            + [cluster.loop.spawn(mutator(i), name=f"watch.m{i}")
               for i in range(self.n_keys)]
        )
        self.metrics.extra["fired"] = list(fired)
        if any(f < self.n_rounds for f in fired):
            raise WorkloadFailed(f"watches hung: fired={fired}")

    async def check(self, db) -> None:
        async def body(tr):
            for i in range(self.n_keys):
                v = await tr.get(self._key(i))
                if v is None or not v.startswith(b"round/"):
                    raise WorkloadFailed(f"watch key {i} lost: {v!r}")

        await self._run_txn(db, body)


class VersionStampWorkload(Workload):
    """Versionstamped-key ordering (reference: VersionStamp.actor.cpp):
    every txn appends via SET_VERSIONSTAMPED_KEY and records the stamp
    get_versionstamp() reports. Check: the database holds exactly the
    committed rows, under exactly the reported keys, and their key order
    equals commit order (stamps are monotone in commit version)."""

    name = "versionstamp"

    def __init__(self, seed: int = 0, n_txns: int = 40, n_clients: int = 4):
        super().__init__(seed)
        self.n_txns = n_txns
        self.n_clients = n_clients
        self._committed: list[tuple[bytes, bytes]] = []  # (stamp, payload)
        # Payloads whose txn saw CommitUnknownResult on some attempt: a
        # versionstamped append is inherently non-idempotent (each attempt
        # writes a DIFFERENT key), so a landed-but-unacked attempt plus
        # its retry legitimately leaves two rows (campaign find, seed
        # 5056; the reference's VersionStamp workload tolerates unknown
        # results the same way). Any OTHER duplicate is real corruption.
        self._maybe_dup: set[bytes] = set()

    async def run(self, db, cluster) -> None:
        from foundationdb_tpu.core.errors import CommitUnknownResult

        counts = self._split(self.n_txns, self.n_clients)

        async def client(cid: int):
            for j in range(counts[cid]):
                payload = b"c%02d-%04d" % (cid, j)
                # Own retry loop instead of _run_txn: the workload must
                # OBSERVE unknown results to know which payloads may
                # duplicate; db.run hides them.
                tr = db.transaction()
                for attempt in range(100):
                    try:
                        key = b"vs/" + b"\x00" * 10 + struct.pack("<I", 3)
                        tr.atomic_op(
                            MutationType.SET_VERSIONSTAMPED_KEY, key, payload
                        )
                        await tr.commit()
                        break
                    except FdbError as e:
                        if isinstance(e, CommitUnknownResult):
                            self._maybe_dup.add(payload)
                        self.metrics.txns_retried += 1
                        await tr.on_error(e)  # raises if not retryable
                else:
                    raise FdbError("retry limit reached", code=1021)
                self.metrics.txns_committed += 1
                self._committed.append((tr.get_versionstamp(), payload))
                self.metrics.ops += 1

        await all_of(
            [cluster.loop.spawn(client(i), name=f"vs.client{i}")
             for i in range(self.n_clients)]
        )

    async def check(self, db) -> None:
        async def body(tr):
            return await tr.get_range(b"vs/", b"vs0", limit=100_000)

        rows = await self._run_txn(db, body)
        recorded = {
            b"vs/" + stamp: payload for stamp, payload in self._committed
        }
        rows_by_key = dict(rows)
        if len(rows_by_key) != len(rows):
            raise WorkloadFailed("duplicate versionstamp keys in range")
        missing = [k for k, p in recorded.items() if rows_by_key.get(k) != p]
        if missing:
            raise WorkloadFailed(
                f"versionstamp rows lost: {missing[:3]!r} "
                f"({len(rows)} rows vs {len(recorded)} committed)"
            )
        for key, payload in rows:
            if key in recorded:
                continue
            if payload not in self._maybe_dup:
                raise WorkloadFailed(
                    f"unexplained versionstamp row {key!r}={payload!r}: "
                    "not the recorded stamp and its txn never saw "
                    "commit_unknown_result"
                )
        # Stamps must be strictly monotone in commit order per client chain.
        by_payload = {p: s for s, p in self._committed}
        for cid in range(self.n_clients):
            chain = [s for p, s in sorted(by_payload.items())
                     if p.startswith(b"c%02d-" % cid)]
            if chain != sorted(chain) or len(set(chain)) != len(chain):
                raise WorkloadFailed("stamps not monotone within a client")


class ChangeFeedWorkload(Workload):
    """Change-feed correctness (reference: the change-feed variants of
    fdbserver/workloads/): register a feed over the workload's range,
    run concurrent writes, then REPLAY the feed in version order into a
    model and require the model to equal the database's final state —
    every committed mutation must appear exactly once, ordered."""

    name = "changefeed"

    def __init__(self, seed: int = 0, n_keys: int = 8, n_txns: int = 40,
                 n_clients: int = 4):
        super().__init__(seed)
        self.n_keys = n_keys
        self.n_txns = n_txns
        self.n_clients = n_clients

    def _key(self, i: int) -> bytes:
        return b"cf/%04d" % i

    async def setup(self, db) -> None:
        # Register on every storage server: each captures its shard's
        # slice of the range (clears are clipped server-side).
        for i, ss in enumerate(db.cluster.storages):
            ss.register_change_feed(b"wl-feed", b"cf/", b"cf0")

    async def run(self, db, cluster) -> None:
        rng = cluster.loop.rng
        counts = self._split(self.n_txns, self.n_clients)

        async def client(cid: int):
            for j in range(counts[cid]):
                op = rng.random()
                k = self._key(rng.randrange(self.n_keys))

                async def body(tr, op=op, k=k, cid=cid, j=j):
                    if op < 0.6:
                        tr.set(k, b"v%02d-%04d" % (cid, j))
                    elif op < 0.8:
                        tr.atomic_op(
                            MutationType.ADD, k, struct.pack("<q", 1)
                        )
                    else:
                        tr.clear(k)

                await self._run_txn(db, body)
                self.metrics.ops += 1

        await all_of(
            [cluster.loop.spawn(client(i), name=f"cf.client{i}")
             for i in range(self.n_clients)]
        )

    async def check(self, db) -> None:
        from foundationdb_tpu.core.mutations import Mutation

        # Deterministic quiesce (campaign-found at seed 1052: a fixed
        # 0.5s drain lost the race against clogged/buggified pull loops
        # — the feed was read BEFORE the final mutation applied, while
        # the later range read waited for it): take a read version and
        # wait until EVERY storage has applied through it; every commit
        # is then both readable and feed-captured.
        async def rv_body(tr):
            return await tr.get_read_version()

        rv = await self._run_txn(db, rv_body)
        for ss in db.cluster.storages:
            while ss._version < rv:
                await db.cluster.loop.sleep(0.05)
        entries: list[tuple[int, Mutation]] = []
        for ss in db.cluster.storages:
            entries.extend(ss.read_change_feed(b"wl-feed", 0))
        entries.sort(key=lambda e: e[0])
        model: dict[bytes, bytes] = {}
        for _v, m in entries:
            if m.type == MutationType.SET_VALUE:
                model[m.param1] = m.param2
            elif m.type == MutationType.CLEAR_RANGE:
                for k in [k for k in model if m.param1 <= k < m.param2]:
                    del model[k]
            else:
                raise WorkloadFailed(f"feed leaked raw atomic op: {m!r}")

        async def body(tr):
            return await tr.get_range(b"cf/", b"cf0", limit=100_000)

        rows = dict(await self._run_txn(db, body))
        if model != rows:
            raise WorkloadFailed(
                f"feed replay diverged: model {len(model)} keys vs "
                f"db {len(rows)} keys"
            )


class IncrementWorkload(Workload):
    """Atomic-increment conservation (reference: Increment.actor.cpp):
    clients ADD 1 to random counters; quiesced, the counters must sum to
    the committed-op count — except that an applied-but-unknown commit
    retried by the loop legitimately double-applies (as in the
    reference, which tracks min/max expected): the sum must land in
    [ops, ops + 2*retried_txns]. Clean runs have zero retries, making
    the bound exact; lost or torn atomic ops still fail it from below."""

    name = "increment"

    def __init__(self, seed: int = 0, n_counters: int = 8, n_txns: int = 40,
                 n_clients: int = 4):
        super().__init__(seed)
        self.n_counters = n_counters
        self.n_txns = n_txns
        self.n_clients = n_clients

    def _key(self, i: int) -> bytes:
        return b"incr/%04d" % i

    async def setup(self, db) -> None:
        async def body(tr):
            tr.clear_range(b"incr/", b"incr0")  # own the prefix

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        rng = cluster.loop.rng
        counts = self._split(self.n_txns, self.n_clients)

        async def client(cid: int):
            for _ in range(counts[cid]):
                i = rng.randrange(self.n_counters)
                j = rng.randrange(self.n_counters)

                async def body(tr, i=i, j=j):
                    one = struct.pack("<q", 1)
                    tr.atomic_op(MutationType.ADD, self._key(i), one)
                    tr.atomic_op(MutationType.ADD, self._key(j), one)

                await self._run_txn(db, body)
                self.metrics.ops += 2

        await all_of(
            [cluster.loop.spawn(client(i), name=f"incr.client{i}")
             for i in range(self.n_clients)]
        )

    async def check(self, db) -> None:
        async def body(tr):
            total = 0
            for i in range(self.n_counters):
                v = await tr.get(self._key(i))
                total += struct.unpack("<q", v)[0] if v is not None else 0
            return total

        # Snapshot BEFORE the read-only check txn runs: only run-phase
        # ADD transactions can double-apply, so their retries alone set
        # the tolerance (a retried check read must not widen it).
        run_retries = self.metrics.txns_retried
        total = await self._run_txn(db, body)
        slack = 2 * run_retries  # 2 ADDs per txn attempt
        if not self.metrics.ops <= total <= self.metrics.ops + slack:
            raise WorkloadFailed(
                f"increment sum {total} outside [{self.metrics.ops}, "
                f"{self.metrics.ops + slack}] (run retried {run_retries})"
            )


class SelectorCorrectnessWorkload(Workload):
    """Key-selector + limited/reverse range reads vs a sorted in-memory
    model (reference: SelectorCorrectness.actor.cpp): populate a known key
    set, then fire random firstGreaterOrEqual/lastLessThan selectors with
    random offsets and random limited scans; every answer must equal the
    model's."""

    name = "selectors"

    def __init__(self, seed: int = 0, n_keys: int = 24, n_queries: int = 60,
                 n_clients: int = 3):
        super().__init__(seed)
        self.n_keys = n_keys
        self.n_queries = n_queries
        self.n_clients = n_clients
        self.keys: list[bytes] = []

    async def setup(self, db) -> None:
        self.keys = [b"sel/%04d" % (3 * i) for i in range(self.n_keys)]

        async def body(tr):
            # Own the prefix: a previous test in the same spec file may
            # have left keys here (tests share the cluster, as in the
            # reference's multi-test TOML runs).
            tr.clear_range(b"sel/", b"sel0")
            for k in self.keys:
                tr.set(k, b"v" + k[-4:])

        await self._run_txn(db, body)

    def _model_resolve(self, anchor: bytes, or_equal: bool, offset: int) -> bytes:
        """The reference selector semantics over the sorted model."""
        import bisect

        from foundationdb_tpu.runtime.shardmap import MAX_KEY

        ks = self.keys
        if offset >= 1:
            start = anchor + b"\x00" if or_equal else anchor
            i = bisect.bisect_left(ks, start) + (offset - 1)
            return ks[i] if i < len(ks) else MAX_KEY
        back = 1 - offset
        end = anchor + b"\x00" if or_equal else anchor
        i = bisect.bisect_left(ks, end) - back
        return ks[i] if i >= 0 else b""

    async def run(self, db, cluster) -> None:
        from foundationdb_tpu.client.transaction import KeySelector

        rng = cluster.loop.rng
        counts = self._split(self.n_queries, self.n_clients)

        async def client(cid: int):
            for _ in range(counts[cid]):
                anchor = b"sel/%04d" % rng.randrange(3 * self.n_keys + 2)
                or_equal = rng.random() < 0.5
                offset = rng.randrange(-3, 4)
                kind = rng.random()

                async def body(tr, anchor=anchor, or_equal=or_equal,
                               offset=offset, kind=kind):
                    if kind < 0.5:
                        from foundationdb_tpu.runtime.shardmap import MAX_KEY

                        got = await tr.get_key(
                            KeySelector(anchor, or_equal, offset)
                        )
                        want = self._model_resolve(anchor, or_equal, offset)
                        # A resolution escaping our prefix lands on some
                        # OTHER workload's key (the db resolves selectors
                        # over the whole keyspace); the model only knows
                        # the direction then.
                        ok = (
                            got == want
                            or (want == b"" and got < b"sel/")
                            or (want == MAX_KEY and got >= b"sel0")
                        )
                        if not ok:
                            raise WorkloadFailed(
                                f"selector({anchor!r},{or_equal},{offset}) "
                                f"= {got!r}, model says {want!r}"
                            )
                    else:
                        limit = 1 + int(kind * 10)
                        reverse = kind > 0.8
                        rows = await tr.get_range(
                            b"sel/", anchor, limit=limit, reverse=reverse
                        )
                        model = [k for k in self.keys if k < anchor]
                        if reverse:
                            model.reverse()
                        model = model[:limit]
                        if [k for k, _ in rows] != model:
                            raise WorkloadFailed(
                                f"range(sel/..{anchor!r} lim={limit} "
                                f"rev={reverse}) mismatch"
                            )

                await self._run_txn(db, body)
                self.metrics.ops += 1

        await all_of(
            [cluster.loop.spawn(client(i), name=f"sel.client{i}")
             for i in range(self.n_clients)]
        )


class BackupRestoreWorkload(Workload):
    """Backup under live writes, restore elsewhere, compare keyspaces
    (reference: BackupToDBCorrectness.actor.cpp): a continuous backup and
    a rolling snapshot run WHILE writer clients mutate; after stop, the
    container restores into a fresh cluster on the same sim loop and the
    two keyspaces must match exactly at the restorable version."""

    name = "backup_restore"

    def __init__(self, seed: int = 0, n_keys: int = 20, n_txns: int = 30,
                 n_clients: int = 3):
        super().__init__(seed)
        self.n_keys = n_keys
        self.n_txns = n_txns
        self.n_clients = n_clients
        self._container = None

    def _key(self, i: int) -> bytes:
        return b"bk/%04d" % i

    async def run(self, db, cluster) -> None:
        from foundationdb_tpu.runtime.backup import BackupAgent

        async def seed(tr):
            for i in range(self.n_keys):
                tr.set(self._key(i), b"seed")

        await self._run_txn(db, seed)
        agent = BackupAgent(cluster, db)
        await agent.start()

        rng = cluster.loop.rng
        counts = self._split(self.n_txns, self.n_clients)

        async def client(cid: int):
            for j in range(counts[cid]):
                i = rng.randrange(self.n_keys)

                async def body(tr, i=i, cid=cid, j=j):
                    tr.set(self._key(i), b"w%02d-%04d" % (cid, j))
                    if rng.random() < 0.2:
                        tr.clear(self._key(rng.randrange(self.n_keys)))

                await self._run_txn(db, body)
                self.metrics.ops += 1

        writers = [
            cluster.loop.spawn(client(i), name=f"bk.client{i}")
            for i in range(self.n_clients)
        ]
        await agent.snapshot(b"bk/", b"bk0")  # rolls while writers run
        await all_of(writers)
        await agent.stop()
        self._container = agent.container

    async def check(self, db) -> None:
        from foundationdb_tpu.client.ryw import open_database
        from foundationdb_tpu.runtime.backup import restore
        from foundationdb_tpu.sim.cluster import SimCluster

        if self._container is None or \
                self._container.restorable_version() is None:
            raise WorkloadFailed("backup produced no restorable version")
        # Fresh destination cluster on the SAME loop (the sim stays one
        # deterministic world).
        # process_prefix: two clusters on one Loop must NOT share process
        # names — loop-global kills/retirement would cross clusters (a
        # buggify-triggered recovery on the source retired "tlog0" and
        # black-holed the destination's identically named tlog forever;
        # campaign-found at BackupRestoreBuggify seed 1032).
        dst_c = SimCluster(loop=db.loop, seed=self.seed + 9999,
                           process_prefix="bkdst.")
        dst = open_database(dst_c)
        await restore(dst, self._container)

        async def dump(tr):
            return await tr.get_range(b"bk/", b"bk0", limit=100_000)

        src_rows = await self._run_txn(db, dump)
        dst_rows = await dst.run(dump)
        if src_rows != dst_rows:
            raise WorkloadFailed(
                f"restore mismatch: src {len(src_rows)} rows vs dst "
                f"{len(dst_rows)} rows"
            )


class WriteDuringReadWorkload(Workload):
    """RYW semantics fuzz (reference: WriteDuringRead.actor.cpp): inside
    one transaction, interleave random sets / clears / clear_ranges /
    atomic ops with random gets and range reads; every read must see the
    transaction's own uncommitted mutations applied over the database
    snapshot. On commit the model becomes the expected database state."""

    name = "write_during_read"

    def __init__(self, seed: int = 0, n_keys: int = 24, n_txns: int = 20,
                 ops_per_txn: int = 12):
        super().__init__(seed)
        self.n_keys = n_keys
        self.n_txns = n_txns
        self.ops_per_txn = ops_per_txn
        self.model: dict[bytes, bytes] = {}  # committed state

    def _key(self, rng) -> bytes:
        return b"wdr/%03d" % rng.randrange(self.n_keys)

    async def setup(self, db) -> None:
        async def body(tr):
            tr.clear_range(b"wdr/", b"wdr0")

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        rng = cluster.loop.rng
        for _ in range(self.n_txns):
            plan = []  # decided OUTSIDE the retry loop → deterministic replay
            for _o in range(self.ops_per_txn):
                r = rng.random()
                if r < 0.25:
                    plan.append(("set", self._key(rng),
                                 b"v%06d" % rng.randrange(1 << 20)))
                elif r < 0.35:
                    plan.append(("clear", self._key(rng), None))
                elif r < 0.45:
                    a, b = sorted((self._key(rng), self._key(rng)))
                    plan.append(("clear_range", a, b))
                elif r < 0.55:
                    plan.append(("add", self._key(rng),
                                 struct.pack("<q", rng.randrange(100))))
                elif r < 0.8:
                    plan.append(("get", self._key(rng), None))
                else:
                    a, b = sorted((self._key(rng), self._key(rng)))
                    plan.append(("get_range", a, b))

            async def body(tr, plan=plan):
                # Txn-visible model is rebuilt from a snapshot range read
                # each attempt, NOT carried across txns: an applied-but-
                # unknown commit (fault injection) double-applies ADDs on
                # retry, and a carried model would diverge from the
                # database while both are individually correct. Reading
                # the prefix keeps every in-txn RYW assertion exact.
                local = dict(await tr.get_range(b"wdr/", b"wdr0"))
                for op, a, b in plan:
                    if op == "set":
                        tr.set(a, b)
                        local[a] = b
                    elif op == "clear":
                        tr.clear(a)
                        local.pop(a, None)
                    elif op == "clear_range":
                        tr.clear_range(a, b)
                        for k in [k for k in local if a <= k < b]:
                            del local[k]
                    elif op == "add":
                        tr.atomic_op(MutationType.ADD, a, b)
                        base = (local.get(a, b"") + b"\x00" * 8)[:8]
                        total = (struct.unpack("<q", base)[0]
                                 + struct.unpack("<q", b)[0])
                        local[a] = struct.pack("<q", total)
                    elif op == "get":
                        got = await tr.get(a)
                        want = local.get(a)
                        if got != want:
                            raise WorkloadFailed(
                                f"RYW get({a!r}) = {got!r}, want {want!r}")
                    elif op == "get_range":
                        got = await tr.get_range(a, b)
                        want = sorted(
                            (k, v) for k, v in local.items() if a <= k < b)
                        if got != want:
                            raise WorkloadFailed(
                                f"RYW range [{a!r},{b!r}) = {got!r}, "
                                f"want {want!r}")
                return local

            self.model = await self._run_txn(db, body)
            self.metrics.ops += len(plan)

    async def check(self, db) -> None:
        async def body(tr):
            return await tr.get_range(b"wdr/", b"wdr0")

        rows = await self._run_txn(db, body)
        want = sorted(self.model.items())
        if rows != want:
            raise WorkloadFailed(
                f"final state {len(rows)} rows != model {len(want)} rows")


class FuzzApiWorkload(Workload):
    """Randomized API-surface fuzz vs a sequential model (reference:
    FuzzApiCorrectness.actor.cpp, narrowed to the implemented surface):
    single-client random transactions mixing mutations, snapshot and
    conflict reads, limited/reverse ranges, and key selectors; each txn's
    reads are checked against the model, and committed txns fold into it."""

    name = "fuzz_api"

    def __init__(self, seed: int = 0, n_keys: int = 40, n_txns: int = 30,
                 ops_per_txn: int = 8):
        super().__init__(seed)
        self.n_keys = n_keys
        self.n_txns = n_txns
        self.ops_per_txn = ops_per_txn
        self.model: dict[bytes, bytes] = {}

    def _key(self, rng) -> bytes:
        return b"fuzz/%03d" % rng.randrange(self.n_keys)

    async def setup(self, db) -> None:
        async def body(tr):
            tr.clear_range(b"fuzz/", b"fuzz0")

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        from foundationdb_tpu.client.transaction import KeySelector
        from foundationdb_tpu.runtime.shardmap import MAX_KEY

        rng = cluster.loop.rng
        for _ in range(self.n_txns):
            plan = []
            for _o in range(self.ops_per_txn):
                r = rng.random()
                if r < 0.3:
                    plan.append(("set", self._key(rng),
                                 b"x%05d" % rng.randrange(99999)))
                elif r < 0.4:
                    plan.append(("clear", self._key(rng), None))
                elif r < 0.6:
                    plan.append(("get", self._key(rng),
                                 rng.random() < 0.5))  # snapshot?
                elif r < 0.8:
                    a, b = sorted((self._key(rng), self._key(rng)))
                    plan.append(("range", (a, b, rng.randrange(0, 6),
                                           rng.random() < 0.5), None))
                else:
                    plan.append(("get_key", self._key(rng),
                                 (rng.random() < 0.5, rng.randrange(-2, 3))))

            async def body(tr, plan=plan):
                # Snapshot-rebuilt per attempt (same hazard WriteDuringRead
                # documents: an applied-but-unknown commit retried by
                # db.run would diverge from a carried model).
                local = dict(await tr.get_range(b"fuzz/", b"fuzz0"))
                for op, a, b in plan:
                    if op == "set":
                        tr.set(a, b)
                        local[a] = b
                    elif op == "clear":
                        tr.clear(a)
                        local.pop(a, None)
                    elif op == "get":
                        got = await tr.get(a, snapshot=b)
                        if got != local.get(a):
                            raise WorkloadFailed(
                                f"fuzz get({a!r}) = {got!r}, "
                                f"want {local.get(a)!r}")
                    elif op == "range":
                        ra, rb, limit, reverse = a
                        got = await tr.get_range(ra, rb, limit=limit,
                                                 reverse=reverse)
                        rows = sorted(
                            (k, v) for k, v in local.items() if ra <= k < rb)
                        if reverse:
                            rows.reverse()
                        if limit > 0:
                            rows = rows[:limit]
                        if got != rows:
                            raise WorkloadFailed(
                                f"fuzz range {a} = {len(got)} rows, "
                                f"want {len(rows)}")
                    elif op == "get_key":
                        or_equal, offset = b
                        sel = KeySelector(a, or_equal, offset)
                        got = await tr.get_key(sel)
                        ks = sorted(local)
                        anchor = a + (b"\x00" if or_equal else b"")
                        if offset >= 1:
                            i = bisect.bisect_left(ks, anchor) + (offset - 1)
                            want = ks[i] if i < len(ks) else MAX_KEY
                        else:
                            i = bisect.bisect_left(ks, anchor) - (1 - offset)
                            want = ks[i] if i >= 0 else b""
                        # Clamp like the runtime: selectors resolving
                        # outside the fuzz prefix see OTHER tests' keys —
                        # only verify in-prefix answers.
                        in_prefix = (want.startswith(b"fuzz/")
                                     and got.startswith(b"fuzz/"))
                        if in_prefix and got != want:
                            raise WorkloadFailed(
                                f"fuzz get_key({a!r},{or_equal},{offset}) "
                                f"= {got!r}, want {want!r}")
                return local

            self.model = await self._run_txn(db, body)
            self.metrics.ops += len(plan)

    async def check(self, db) -> None:
        async def body(tr):
            return await tr.get_range(b"fuzz/", b"fuzz0")

        rows = await self._run_txn(db, body)
        if rows != sorted(self.model.items()):
            raise WorkloadFailed("fuzz final state diverged from model")


class DDBalanceWorkload(Workload):
    """Reads and writes racing shard moves (reference: DDBalance.actor.cpp):
    clients hammer a key prefix while the DataDistributor is told to move
    the hot shard between storage teams; every committed write must stay
    readable throughout and afterwards. Requires data_distribution=True."""

    name = "dd_balance"

    def __init__(self, seed: int = 0, n_keys: int = 16, n_txns: int = 30,
                 n_moves: int = 4):
        super().__init__(seed)
        self.n_keys = n_keys
        self.n_txns = n_txns
        self.n_moves = n_moves
        self.written: dict[bytes, bytes] = {}

    async def setup(self, db) -> None:
        async def body(tr):
            tr.clear_range(b"ddb/", b"ddb0")

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        dd = getattr(cluster, "data_distributor", None)
        if dd is None:
            raise WorkloadFailed("DDBalance needs data_distribution=True")
        rng = cluster.loop.rng
        done = [False]

        async def mover():
            n_storages = len(cluster.storage_eps)
            k = cluster.n_replicas
            for m in range(self.n_moves):
                dst = tuple((m + j) % n_storages for j in range(k))
                try:
                    await dd.move_shard(b"ddb/", b"ddb0", dst)
                except Exception:
                    pass  # racing recoveries may abort a move; keep going
                await cluster.loop.sleep(0.5)
            done[0] = True

        async def writer():
            i = 0
            while not done[0] or i < self.n_txns:
                k = b"ddb/%03d" % rng.randrange(self.n_keys)
                v = b"m%06d" % i

                async def body(tr, k=k, v=v):
                    got_prev = await tr.get(k)
                    # An applied-but-unknown commit retried by db.run may
                    # legitimately observe ITS OWN value on the second
                    # attempt — accept either.
                    if got_prev not in (self.written.get(k), v):
                        raise WorkloadFailed(
                            f"dd_balance read {k!r} = {got_prev!r} "
                            f"mid-move, want {self.written.get(k)!r}")
                    tr.set(k, v)

                await self._run_txn(db, body)
                self.written[k] = v
                self.metrics.ops += 1
                i += 1
                await cluster.loop.sleep(0.05)

        await all_of([
            cluster.loop.spawn(mover(), name="ddb.mover"),
            cluster.loop.spawn(writer(), name="ddb.writer"),
        ])

    async def check(self, db) -> None:
        async def body(tr):
            return await tr.get_range(b"ddb/", b"ddb0")

        rows = await self._run_txn(db, body)
        if rows != sorted(self.written.items()):
            raise WorkloadFailed(
                f"dd_balance final {len(rows)} rows != "
                f"{len(self.written)} written")


class TenantWorkload(Workload):
    """Tenant lifecycle + isolation under concurrency (reference:
    TenantManagementWorkload.actor.cpp, narrowed): clients create/use/
    delete random tenants; every tenant's data must stay isolated and
    the final tenant list must match the model."""

    name = "tenants"

    def __init__(self, seed: int = 0, n_tenants: int = 4, n_txns: int = 24,
                 n_clients: int = 3):
        super().__init__(seed)
        self.n_tenants = n_tenants
        self.n_txns = n_txns
        self.n_clients = n_clients
        self.model: dict[bytes, dict[bytes, bytes]] = {}  # name -> kv

    async def setup(self, db) -> None:
        from foundationdb_tpu.client.tenant import (
            Tenant,
            TenantExists,
            create_tenant,
        )

        for i in range(self.n_tenants):
            name = b"wl%02d" % i
            try:
                await create_tenant(db, name)
            except TenantExists:
                # A previous test in the same spec file owns this name:
                # reuse it, clearing its data (tests share the cluster,
                # as in the reference's multi-test TOML runs).
                t = Tenant(db, name)

                async def wipe(tr):
                    tr.clear_range(b"", b"\xff")

                await t.run(wipe)
            self.model[name] = {}

    async def run(self, db, cluster) -> None:
        from foundationdb_tpu.client.tenant import Tenant

        rng = cluster.loop.rng
        counts = self._split(self.n_txns, self.n_clients)
        # One cached handle per tenant (the module's documented client
        # pattern) — a per-txn Tenant would re-read the map every time.
        handles = {name: Tenant(db, name) for name in self.model}

        async def client(cid: int):
            for _ in range(counts[cid]):
                name = b"wl%02d" % rng.randrange(self.n_tenants)
                # Per-client key partition: the model records commit-REPLY
                # order, which for a shared key can differ from commit-
                # version order under delayed replies — distinct keys per
                # client make the model exact (same pattern as ChangeFeed).
                k = b"c%02d/k%02d" % (cid, rng.randrange(6))
                v = name + b"/%05d" % rng.randrange(99999)

                async def body(tr, k=k, v=v):
                    tr.set(k, v)

                # Tenant.run duck-types as db.run: the base helper's
                # retry/failure accounting applies unchanged.
                await self._run_txn(handles[name], body)
                self.model[name][k] = v
                self.metrics.ops += 1

        await all_of(
            [cluster.loop.spawn(client(i), name=f"tenant.client{i}")
             for i in range(self.n_clients)]
        )

    async def check(self, db) -> None:
        from foundationdb_tpu.client.tenant import Tenant, list_tenants

        names = await list_tenants(db)
        for name, kv in self.model.items():
            if name not in names:
                raise WorkloadFailed(f"tenant {name!r} missing")

            async def dump(tr):
                return dict(await tr.get_range(b"", b"\xff"))

            rows = await self._run_txn(Tenant(db, name), dump)
            if rows != kv:
                raise WorkloadFailed(
                    f"tenant {name!r}: {len(rows)} rows != model {len(kv)}"
                )


class IndexStressWorkload(Workload):
    """Transactional secondary index (reference: Storefront/IndexStress
    shapes): every txn writes item `data/<k> = v` AND maintains the
    index entry `idx/<v>/<k>` (clearing the previous index entry) in ONE
    transaction. Quiesced, the index and the data must be exact mirrors:
    a dangling or missing index entry means a torn multi-key txn."""

    name = "index_stress"

    def __init__(self, seed: int = 0, n_items: int = 10, n_txns: int = 30,
                 n_clients: int = 3):
        super().__init__(seed)
        self.n_items = n_items
        self.n_txns = n_txns
        self.n_clients = n_clients

    async def setup(self, db) -> None:
        async def body(tr):
            tr.clear_range(b"data/", b"data0")
            tr.clear_range(b"idx/", b"idx0")

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        rng = cluster.loop.rng
        counts = self._split(self.n_txns, self.n_clients)

        async def client(cid: int):
            for _ in range(counts[cid]):
                k = b"%03d" % rng.randrange(self.n_items)
                v = b"v%05d" % rng.randrange(99999)

                async def body(tr, k=k, v=v):
                    old = await tr.get(b"data/" + k)
                    if old is not None:
                        tr.clear(b"idx/" + old + b"/" + k)
                    tr.set(b"data/" + k, v)
                    tr.set(b"idx/" + v + b"/" + k, b"")

                await self._run_txn(db, body)
                self.metrics.ops += 1

        await all_of(
            [cluster.loop.spawn(client(i), name=f"idx.client{i}")
             for i in range(self.n_clients)]
        )

    async def check(self, db) -> None:
        async def dump(tr):
            data = await tr.get_range(b"data/", b"data0")
            idx = await tr.get_range(b"idx/", b"idx0")
            return data, idx

        data, idx = await self._run_txn(db, dump)
        want_idx = sorted(
            b"idx/" + v + b"/" + k[len(b"data/"):] for k, v in data
        )
        got_idx = sorted(k for k, _ in idx)
        if got_idx != want_idx:
            dangling = set(got_idx) - set(want_idx)
            missing = set(want_idx) - set(got_idx)
            raise WorkloadFailed(
                f"index diverged: {len(dangling)} dangling, "
                f"{len(missing)} missing"
            )


class RegionFailoverWorkload(Workload):
    """Multi-region failover under live writes (reference: the
    multi-region correctness the reference covers with region-config
    simulation tests + ClusterController dc failover): clients write a
    monotone journal; mid-run the ENTIRE primary region is failed; the
    chain must re-form in the remote region from the satellite tlogs.
    Check: every ACKED write reads back (zero acked-commit loss), the
    active region flipped, and writes continued post-failover. Requires
    a cluster built with multi_region."""

    name = "region_failover"

    def __init__(self, seed: int = 0, n_txns: int = 40, n_clients: int = 2,
                 fail_after: int = 10, heal: bool = False,
                 mode: str = "fail"):
        super().__init__(seed)
        self.n_txns = n_txns
        self.n_clients = n_clients
        self.fail_after = fail_after  # acked txns before the region dies
        self.heal = heal  # heal the failed region mid-run (failback test)
        # "fail" = blackout (processes die); "partition" = the HARD mode:
        # the region stays alive-but-severed, its chain running on as a
        # zombie generation — what the known-committed/epoch fences and
        # GRV epoch confirmation exist for (see
        # tests/test_multi_region.py::test_region_partition_fences_zombie_generation).
        assert mode in ("fail", "partition"), mode
        self.mode = mode
        self._acked: list[bytes] = []
        self._failed_region = None
        self._token: str | None = None  # minted lazily on authz clusters

    def _key(self, cid: int, i: int) -> bytes:
        return b"rf/%02d/%04d" % (cid, i)

    def _tokenize(self, db, tr) -> None:
        """On an authz-armed cluster this workload plays a tenant scoped
        to its own rf/ prefix (untokened writes would be denied) — the
        AuthzAcrossRegionFailover spec composes it with the Authz
        workload's isolation probes."""
        if self._token is None:
            cluster = getattr(db, "cluster", None)
            priv = getattr(cluster, "authz_private_pem", None)
            if priv is None:
                return
            from foundationdb_tpu.runtime.authz import mint_token

            self._token = mint_token(priv, [b"rf/"], expires_at=1e12)
        tr.set_option("authorization_token", self._token)

    async def setup(self, db) -> None:
        async def body(tr):
            self._tokenize(db, tr)
            tr.clear_range(b"rf/", b"rf0")

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        assert cluster.multi_region, "RegionFailover needs multi_region"
        counts = self._split(self.n_txns, self.n_clients)
        total_acked = [0]

        async def client(cid: int):
            for i in range(counts[cid]):
                key = self._key(cid, i)

                async def body(tr, key=key):
                    self._tokenize(db, tr)
                    tr.set(key, b"v")

                await self._run_txn(db, body)
                self._acked.append(key)
                self.metrics.ops += 1
                total_acked[0] += 1

        async def regicide():
            while total_acked[0] < self.fail_after:
                await cluster.loop.sleep(0.05)
            self._failed_region = cluster.active_region
            if self.mode == "partition":
                cluster.net.partition_region(self._failed_region + "/")
                if self.heal:
                    await cluster.loop.sleep(5.0)
                    # Partition heal: nothing died — the severed links
                    # return and the fenced replicas catch up in place.
                    cluster.net.heal_region_partition(
                        self._failed_region + "/")
            else:
                cluster.net.fail_region(self._failed_region + "/")
                if self.heal:
                    await cluster.loop.sleep(5.0)
                    cluster.heal_region(self._failed_region)

        await all_of(
            [cluster.loop.spawn(client(i), name=f"rf.client{i}")
             for i in range(self.n_clients)]
            + [cluster.loop.spawn(regicide(), name="rf.regicide")]
        )
        self._cluster = cluster

    async def check(self, db) -> None:
        c = self._cluster
        assert self._failed_region is not None, "region never failed"
        assert c.active_region != self._failed_region or self.heal, (
            "active region never flipped")

        async def body(tr):
            self._tokenize(db, tr)
            return await tr.get_range(b"rf/", b"rf0")

        rows = dict(await self._run_txn(db, body))
        missing = [k for k in self._acked if k not in rows]
        assert not missing, (
            f"{len(missing)} ACKED writes lost in region failover: "
            f"{missing[:5]}")


class AuthzWorkload(Workload):
    """Tenant authorization under faults (reference: the authz simulation
    coverage around TokenSign/TenantAuthorizer): on an authz-enabled
    cluster, clients carrying tenant-bound tokens write and read their
    own tenant through kills/recoveries, while out-of-scope and
    dead-tenant operations are ALWAYS denied — across every generation.
    Requires [test.cluster] authz = true."""

    name = "authz"

    def __init__(self, seed: int = 0, n_txns: int = 30, n_clients: int = 2):
        super().__init__(seed)
        self.n_txns = n_txns
        self.n_clients = n_clients
        self._acked: list[bytes] = []

    async def setup(self, db) -> None:
        pass  # needs the cluster (private key): everything happens in run

    async def run(self, db, cluster) -> None:
        from foundationdb_tpu.client.tenant import (
            Tenant,
            TenantExists,
            TenantNotFound,
            create_tenant,
            delete_tenant,
        )
        from foundationdb_tpu.core.errors import PermissionDenied
        from foundationdb_tpu.runtime.authz import mint_token

        priv = cluster.authz_private_pem
        assert priv is not None, "AuthzWorkload needs [test.cluster] authz"
        loop = cluster.loop
        admin = cluster.authz_system_token
        exp = loop.now + 1e9

        async def create_idempotent(name: bytes) -> bytes:
            # A CommitUnknownResult retry can observe our OWN landed
            # create (campaign-found twice: delete at seed 1032-era,
            # create at aggressive seed 2005) — resolve the prefix
            # instead of failing; these names belong to this workload
            # alone, so TenantExists here can only mean "we made it".
            try:
                return await create_tenant(db, name, token=admin)
            except TenantExists:
                return await Tenant(db, name, token=admin)._resolve()

        prefix = await create_idempotent(b"authz-w")
        token = mint_token(priv, [prefix], expires_at=exp, tenant=b"authz-w")
        # A doomed tenant whose bound token must die with it.
        doomed_prefix = await create_idempotent(b"authz-doomed")
        doomed = mint_token(priv, [doomed_prefix], expires_at=exp,
                            tenant=b"authz-doomed")
        try:
            await delete_tenant(db, b"authz-doomed", token=admin)
        except TenantNotFound:
            # A CommitUnknownResult retry observed our own landed delete
            # (reference deleteTenant throws the same way; campaign-found).
            pass
        # Fence on the mirror's VIEW VERSION passing the delete, not on
        # the tenant's absence from the view: a lagging map replica can
        # leave the view so stale it never saw the doomed CREATE — the
        # absence check passes vacuously, then the view advances INTO
        # the [create, delete) window and the probe is legitimately
        # admitted (campaign find, aggressive seed 5336). A GRV taken
        # after the delete upper-bounds its commit version; the mirror
        # is monotone, so view_version >= fence makes denial permanent.
        fence = await self._run_txn(
            db, lambda tr: tr.get_read_version())
        deadline = loop.now + 60
        while loop.now < deadline:
            m = cluster.tenant_mirror
            if (m is not None and m.view is not None
                    and b"authz-w" in m.view
                    and m._view_version >= fence):
                break
            await loop.sleep(0.1)
        else:
            raise WorkloadFailed(
                "tenant-map mirror never caught up to the delete fence "
                f"(view_version={getattr(cluster.tenant_mirror, '_view_version', None)} "
                f"fence={fence})")

        counts = self._split(self.n_txns, self.n_clients)

        async def client(cid: int):
            for i in range(counts[cid]):
                key = prefix + b"k/%02d/%04d" % (cid, i)

                async def body(tr, key=key):
                    tr.set_option("authorization_token", token)
                    tr.set(key, b"v")

                await self._run_txn(db, body)
                self._acked.append(key)
                self.metrics.ops += 1

                # Negative probes must ride recoveries like any client
                # (retryable errors — killed proxy, commit-unknown — are
                # NOT verdicts) and end in a DEFINITIVE PermissionDenied;
                # the one outcome that fails the workload is admission.
                async def expect_denied(body, what):
                    try:
                        await db.run(body)
                    except PermissionDenied:
                        return
                    raise AssertionError(f"{what} admitted!")

                # Out-of-scope write: denied by whatever generation serves.
                async def outside(tr):
                    tr.set_option("authorization_token", token)
                    tr.set(b"other-tenant/x", b"v")

                await expect_denied(outside, "out-of-scope write")

                # Dead-tenant token: denied at commit AND at read.
                async def dead_write(tr):
                    tr.set_option("authorization_token", doomed)
                    tr.set(doomed_prefix + b"x", b"v")

                await expect_denied(dead_write, "dead-tenant write")

        await all_of(
            [cluster.loop.spawn(client(i), name=f"authz.client{i}")
             for i in range(self.n_clients)]
        )
        self._token, self._prefix = token, prefix

    async def check(self, db) -> None:
        async def body(tr):
            tr.set_option("authorization_token", self._token)
            return await tr.get_range(self._prefix, strinc(self._prefix))

        rows = dict(await self._run_txn(db, body))
        missing = [k for k in self._acked if k not in rows]
        assert not missing, f"{len(missing)} acked tenant writes lost"


class ZipfRepairWorkload(Workload):
    """Zipf-0.99 hot-key read-modify-write contention — the goodput
    workload of the transaction-repair subsystem (repair/engine.py).

    Every transaction reads `reads_per_txn` keys drawn from a bounded
    Zipf(theta) distribution and rewrites the hottest pick to
    read-value + 1 — a true read-modify-write, NOT an atomic ADD, so any
    unsound repair (a stale cached read surviving into a commit) loses an
    increment and breaks the invariant. With ``repair=True`` transactions
    run through ``run_repairable`` (partial re-execution at the failed
    batch's snapshot + hot-range backoff); with ``repair=False`` they take
    the canonical full-restart loop (Database.run) — same stream, so the
    goodput ratio is the repair subsystem's measured win.

    Checks (the oracle-verified serializability side of the bench):
    - sum(keys) == committed increment count (lost/duplicated update ⇔
      broken), on a cluster whose resolver is the brute-force oracle;
    - with repair on, the repair loop converged within its attempt bound
      for every commit (run_repairable raises otherwise).
    """

    name = "zipf_repair"

    def __init__(self, seed: int = 0, n_keys: int = 16, n_txns: int = 80,
                 n_clients: int = 8, theta: float = 0.99,
                 reads_per_txn: int = 3, repair: bool = True,
                 repair_config=None, target_pick: str = "hottest"):
        super().__init__(seed)
        self.n_keys = n_keys
        self.n_txns = n_txns
        self.n_clients = n_clients
        self.theta = theta
        self.reads_per_txn = reads_per_txn
        self.repair = repair
        self.repair_config = repair_config
        # Which pick gets rewritten. "hottest" (default, the original
        # harness): every txn RMWs the hottest key it read — concurrent
        # readers of a hot key are also its writers, so contention is
        # mutual (true dependency cycles; the wave-commit scheduler's
        # WORST case — reordering can't untangle two txns that each read
        # the other's write target). "coldest": read hot, write cold —
        # contention is read-hot-key-vs-its-writer, which forms
        # reader-before-writer CHAINS a wave schedule serializes without
        # aborting (the FAFO sweet spot). The wave-commit A/B records
        # both shapes to make the gains attributable.
        if target_pick not in ("hottest", "coldest"):
            # Hard error, not assert: under python -O a typo'd value would
            # silently bench the coldest (wave-friendly) arm while the
            # record claims the hottest — the silent-wrong-arm A/B hazard.
            raise ValueError(
                f"target_pick={target_pick!r} is not a valid setting; "
                f"accepted values: hottest, coldest"
            )
        self.target_pick = target_pick
        self.repair_stats = None  # populated by run() when repair=True

    def _key(self, i: int) -> bytes:
        return b"zipf/%04d" % i

    def _cdf(self) -> list[float]:
        w = [(r + 1) ** -self.theta for r in range(self.n_keys)]
        total = sum(w)
        acc, cdf = 0.0, []
        for x in w:
            acc += x
            cdf.append(acc / total)
        return cdf

    async def setup(self, db) -> None:
        async def body(tr):
            tr.clear_range(b"zipf/", b"zipf0")
            for i in range(self.n_keys):
                tr.set(self._key(i), struct.pack("<q", 0))

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        from foundationdb_tpu.repair.engine import RepairStats, run_repairable

        rng = cluster.loop.rng
        cdf = self._cdf()

        def pick() -> int:
            return min(bisect.bisect_left(cdf, rng.random()), self.n_keys - 1)

        counts = self._split(self.n_txns, self.n_clients)
        stats = RepairStats() if self.repair else None
        self.repair_stats = stats
        t0 = cluster.loop.now

        async def client(cid: int):
            for _ in range(counts[cid]):
                picks = [pick() for _ in range(self.reads_per_txn)]
                # rank 0 = hottest key (see target_pick in __init__)
                target = (min(picks) if self.target_pick == "hottest"
                          else max(picks))

                async def body(tr, picks=picks, target=target):
                    vals = {}
                    for i in picks:
                        raw = await tr.get(self._key(i))
                        vals[i] = struct.unpack("<q", raw)[0]
                    tr.set(self._key(target),
                           struct.pack("<q", vals[target] + 1))

                if self.repair:
                    await run_repairable(db, body, config=self.repair_config,
                                         stats=stats)
                    self.metrics.txns_committed += 1
                else:
                    await self._run_txn(db, body)
                self.metrics.ops += 1
                if ctx is not None:
                    # Campaign traffic anchor (shared with WriteStorm /
                    # FailoverZipfRepair): actions with afterAcked land
                    # provably mid-stream of THIS workload too.
                    ctx.bump("acked")

        ctx = getattr(cluster, "nemesis_ctx", None)
        await all_of([
            cluster.loop.spawn(client(i), name=f"zipf.client{i}")
            for i in range(self.n_clients)
        ])
        self.metrics.extra["elapsed"] = cluster.loop.now - t0
        if self.metrics.extra["elapsed"] > 0:
            self.metrics.extra["goodput"] = round(
                self.metrics.ops / self.metrics.extra["elapsed"], 2
            )
        if stats is not None:
            self.metrics.extra["repair"] = {
                "commits": stats.commits,
                "repaired_commits": stats.repaired_commits,
                "repair_rounds": stats.repair_rounds,
                "full_restarts": stats.full_restarts,
                "declined": stats.declined,
                "hot_backoffs": stats.hot_backoffs,
                "cache_hits": stats.cache_hits,
            }

    async def check(self, db) -> None:
        async def body(tr):
            rows = await tr.get_range(b"zipf/", b"zipf0")
            return sum(struct.unpack("<q", v)[0] for _k, v in rows)

        total = await self._run_txn(db, body)
        if total != self.metrics.ops:
            raise WorkloadFailed(
                f"zipf_repair: sum {total} != {self.metrics.ops} committed "
                f"increments — a repair admitted a stale read"
            )


class ConsistencyCheckWorkload(Workload):
    """The consistency subsystem as a sim workload (reference:
    fdbserver/workloads/ConsistencyCheck.actor.cpp): run() commits a
    randomized write load like any client; check() walks the quiesced
    cluster's shard map and byte-compares every replica of every team
    through each member's own serve path (foundationdb_tpu/consistency/).
    Any divergence — torn replica, missed tag stream, bad shard move —
    fails the test with the exact shard and first divergent key."""

    name = "consistency_check"

    def __init__(self, seed: int = 0, n_keys: int = 48, n_txns: int = 24,
                 n_clients: int = 2):
        super().__init__(seed)
        self.n_keys = n_keys
        self.n_txns = n_txns
        self.n_clients = n_clients

    def _key(self, i: int) -> bytes:
        return b"ccheck/%05d" % i

    async def run(self, db, cluster) -> None:
        rng = cluster.loop.rng
        counts = self._split(self.n_txns, self.n_clients)

        async def client(cid: int):
            for _ in range(counts[cid]):
                async def body(tr):
                    for _ in range(4):
                        k = self._key(rng.randrange(self.n_keys))
                        tr.set(k, b"v%08d" % rng.randrange(1 << 30))

                await self._run_txn(db, body)
                self.metrics.ops += 4

        await all_of([
            cluster.loop.spawn(client(i), name=f"ccheck.client{i}")
            for i in range(self.n_clients)
        ])

    async def check(self, db) -> None:
        from foundationdb_tpu.consistency.checker import ConsistencyChecker

        report = await ConsistencyChecker(db.cluster, db).run()
        self.metrics.extra["consistency"] = {
            k: report[k] for k in
            ("status", "shards_checked", "chunks", "bytes_compared",
             "moved_rescans")
        }
        if report["status"] != "consistent":
            raise WorkloadFailed(
                f"consistency check {report['status']}: "
                f"{report['divergences'][:3]!r} "
                f"unreachable={report['unreachable'][:3]!r}"
            )


class FailoverZipfRepairWorkload(Workload):
    """Zipf hot-key RMW contention through the repair engine, surviving a
    DR failover mid-run — the campaign composition "DR failover
    mid-repair" (nemesis.DRSwitchover + repair/engine.py).

    Differences from ZipfRepairWorkload, both load-bearing for the
    exactly-once gate:

    - every transaction carries a unique idempotency marker read in the
      same transaction as its increment, so a commit_unknown_result retry
      (or a post-failover retry of a txn that LANDED on the primary and
      drained to the secondary) can never double-apply: sum(keys) ==
      acked commits EXACTLY, under any fault schedule;
    - clients fail over: when the switchover locks the primary
      (DatabaseLocked is definitive, not retryable) they park until the
      nemesis raises ctx.flags['failover'], then resume on the secondary
      — the repaired transaction replays there against the drained
      stream, and the marker decides landed-vs-lost exactly.

    check() audits the SURVIVING side.
    """

    name = "failover_zipf_repair"

    # Longest a locked-out client waits (virtual s) for the switchover
    # to raise the failover flag before re-raising DatabaseLocked — a
    # switchover that locks the primary then dies must fail the run
    # crisply, not eat the whole campaign budget.
    PARK_TIMEOUT_S = 60.0

    def __init__(self, seed: int = 0, n_keys: int = 8, n_txns: int = 60,
                 n_clients: int = 6, theta: float = 0.99,
                 reads_per_txn: int = 3):
        super().__init__(seed)
        self.n_keys = n_keys
        self.n_txns = n_txns
        self.n_clients = n_clients
        self.theta = theta
        self.reads_per_txn = reads_per_txn
        self.repair_stats = None
        self._ctx_cache = None  # NemesisContext, remembered by run()

    def _key(self, i: int) -> bytes:
        return b"zipf/%04d" % i

    def _cdf(self) -> list[float]:
        w = [(r + 1) ** -self.theta for r in range(self.n_keys)]
        total = sum(w)
        acc, cdf = 0.0, []
        for x in w:
            acc += x
            cdf.append(acc / total)
        return cdf

    @staticmethod
    def _ctx(cluster):
        return getattr(cluster, "nemesis_ctx", None)

    def _surviving_db(self, db):
        ctx = self._ctx(getattr(db, "cluster", None)) or self._ctx_cache
        if ctx is not None and ctx.flags.get("failover"):
            return ctx.extra["dst_db"]
        return db

    async def setup(self, db) -> None:
        async def body(tr):
            tr.clear_range(b"zipf/", b"zipf0")
            tr.clear_range(b"zmk/", b"zmk0")
            for i in range(self.n_keys):
                tr.set(self._key(i), struct.pack("<q", 0))

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        from foundationdb_tpu.core.errors import DatabaseLocked
        from foundationdb_tpu.repair.engine import RepairStats, run_repairable

        ctx = self._ctx(cluster)
        self._ctx_cache = ctx
        rng = cluster.loop.rng
        cdf = self._cdf()

        def pick() -> int:
            return min(bisect.bisect_left(cdf, rng.random()), self.n_keys - 1)

        counts = self._split(self.n_txns, self.n_clients)
        stats = RepairStats()
        self.repair_stats = stats

        async def client(cid: int):
            cur_db = db
            for seq in range(counts[cid]):
                picks = [pick() for _ in range(self.reads_per_txn)]
                target = min(picks)
                marker = b"zmk/%02d/%04d" % (cid, seq)

                async def body(tr, picks=picks, target=target, marker=marker):
                    if await tr.get(marker) is not None:
                        return  # an earlier attempt landed: exactly-once
                    vals = {}
                    for i in picks:
                        raw = await tr.get(self._key(i))
                        vals[i] = struct.unpack("<q", raw)[0]
                    tr.set(marker, b"")
                    tr.set(self._key(target),
                           struct.pack("<q", vals[target] + 1))

                while True:
                    try:
                        await run_repairable(cur_db, body, stats=stats)
                        break
                    except DatabaseLocked:
                        # Switchover locked the primary under us: park for
                        # the nemesis to finish draining + parity, then
                        # replay on the secondary (the marker read decides
                        # whether the locked-out attempt already landed).
                        # Bounded park: if there is no nemesis context
                        # (plain [[test]] usage) or the switchover action
                        # died after locking but before raising the flag,
                        # re-raise so the real failure surfaces instead
                        # of spinning out the campaign budget.
                        if ctx is None:
                            raise
                        deadline = cluster.loop.now + self.PARK_TIMEOUT_S
                        while not ctx.flags.get("failover"):
                            if cluster.loop.now >= deadline:
                                raise
                            await cluster.loop.sleep(0.05)
                        cur_db = ctx.extra["dst_db"]
                self.metrics.txns_committed += 1
                self.metrics.ops += 1
                if ctx is not None:
                    ctx.bump("acked")

        await all_of([
            cluster.loop.spawn(client(i), name=f"fzr.client{i}")
            for i in range(self.n_clients)
        ])
        self.metrics.extra["repair"] = {
            "commits": stats.commits,
            "repaired_commits": stats.repaired_commits,
            "repair_rounds": stats.repair_rounds,
            "full_restarts": stats.full_restarts,
        }

    async def check(self, db) -> None:
        db = self._surviving_db(db)

        async def body(tr):
            rows = await tr.get_range(b"zipf/", b"zipf0")
            markers = await tr.get_range(b"zmk/", b"zmk0", limit=100_000)
            return sum(struct.unpack("<q", v)[0] for _k, v in rows), \
                len(markers)

        total, markers = await self._run_txn(db, body)
        if total != self.metrics.ops:
            raise WorkloadFailed(
                f"failover_zipf_repair: sum {total} != {self.metrics.ops} "
                f"acked increments on the surviving side — a repaired txn "
                f"was lost or applied twice across the failover")
        if markers != self.metrics.ops:
            raise WorkloadFailed(
                f"failover_zipf_repair: {markers} idempotency markers != "
                f"{self.metrics.ops} acked txns on the surviving side")


class TaskBucketWorkload(Workload):
    """TaskBucket work-queue drain under faults (layers/taskbucket.py):
    setup enqueues ``n_tasks`` tasks; ``n_executors`` concurrent executors
    claim → execute → finish with short leases, so a claim that stalls
    across a recovery expires and another executor legally re-runs the
    task (the bucket's idempotency contract). The work transaction is an
    idempotent marker + counter ADD, making the final accounting exact:

    - counter == n_tasks (every task executed EXACTLY once in effect —
      a lease double-run is absorbed by the marker, a lost task breaks it
      from below, a double-apply from above);
    - the bucket fully drains (no task stranded in avail/ or leased/).

    On an authz-armed cluster the bucket carries the cluster system token.
    """

    name = "taskbucket"

    def __init__(self, seed: int = 0, n_tasks: int = 12, n_executors: int = 3,
                 lease: float = 0.8):
        super().__init__(seed)
        self.n_tasks = n_tasks
        self.n_executors = n_executors
        self.lease = lease
        self._tb = None

    COUNTER = b"tbwl-count"
    MARKERS = b"tbwl-mk/"

    def _bucket(self, db):
        from foundationdb_tpu.layers.taskbucket import TaskBucket
        from foundationdb_tpu.layers.tuple_layer import Subspace

        if self._tb is None:
            token = getattr(db.cluster, "authz_system_token", None)
            self._tb = TaskBucket(Subspace(("tbwl",)), token=token)
        return self._tb

    async def setup(self, db) -> None:
        tb = self._bucket(db)

        async def body(tr):
            if tb.token:
                tr.set_option("authorization_token", tb.token)
            tr.clear_range(b"tbwl", b"tbwm")  # counter + markers
            tr.clear_range(tb.ss.key(), strinc(tb.ss.key()))  # the bucket
            tr.set(self.COUNTER, struct.pack("<q", 0))

        await self._run_txn(db, body)
        for i in range(self.n_tasks):
            await tb.add(db, {b"n": i})
            self.metrics.txns_committed += 1

    async def run(self, db, cluster) -> None:
        tb = self._bucket(db)

        async def executor(eid: int):
            while True:
                task = await tb.claim(db, lease=self.lease)
                if task is None:
                    avail, leased = await tb.counts(db)
                    if avail == 0 and leased == 0:
                        return  # drained
                    await cluster.loop.sleep(self.lease / 4)
                    continue

                async def work(tr, task=task):
                    if tb.token:
                        tr.set_option("authorization_token", tb.token)
                    marker = self.MARKERS + task.stamp
                    if await tr.get(marker) is None:
                        tr.set(marker, b"")
                        tr.atomic_op(MutationType.ADD, self.COUNTER,
                                     struct.pack("<q", 1))

                await self._run_txn(db, work)
                await tb.finish(db, task)  # False = lease lost: tolerated
                self.metrics.ops += 1

        await all_of([
            cluster.loop.spawn(executor(i), name=f"tbwl.exec{i}")
            for i in range(self.n_executors)
        ])

    async def check(self, db) -> None:
        tb = self._bucket(db)
        avail, leased = await tb.counts(db)
        if avail or leased:
            raise WorkloadFailed(
                f"taskbucket not drained: {avail} available, {leased} leased")

        async def body(tr):
            if tb.token:
                tr.set_option("authorization_token", tb.token)
            raw = await tr.get(self.COUNTER)
            markers = await tr.get_range(self.MARKERS, b"tbwl-mk0",
                                         limit=100_000)
            return (struct.unpack("<q", raw)[0] if raw else 0), len(markers)

        count, markers = await self._run_txn(db, body)
        if count != self.n_tasks or markers != self.n_tasks:
            raise WorkloadFailed(
                f"taskbucket accounting broken: counter {count}, "
                f"{markers} markers != {self.n_tasks} tasks — a task was "
                f"lost or double-applied")


class YCSBWorkload(Workload):
    """YCSB core workloads B (95/5 read/update) and C (read-only) on a
    preloaded Zipf-skewed row set, driving the BATCHED read plane: each
    read op is one multi-key `tr.get_multi` (a single get_multi RPC per
    storage team), and a fraction are short range scans. Updates mutate
    EXISTING rows only — no inserts — so the storage read mirror's key
    set stays stable (value updates don't force a repack; see
    foundationdb_tpu/reads/). Checks: get_multi parity against
    sequential per-key gets on the final state, plus read-your-committed
    for every acked update."""

    name = "ycsb"

    def __init__(self, seed: int = 0, variant: str = "B", n_keys: int = 64,
                 n_txns: int = 40, n_clients: int = 4, batch: int = 8,
                 scan_fraction: float = 0.2):
        super().__init__(seed)
        if variant not in ("B", "C"):
            raise ValueError(f"YCSB variant {variant!r}: only B/C modeled")
        self.variant = variant
        self.n_keys = n_keys
        self.n_txns = n_txns
        self.n_clients = n_clients
        self.batch = batch
        self.scan_fraction = scan_fraction
        self.update_fraction = 0.05 if variant == "B" else 0.0
        self._acked: dict[bytes, bytes] = {}

    def _key(self, i: int) -> bytes:
        return b"ycsb/%06d" % i

    def _pick(self, rng) -> int:
        # Zipf-ish hot set, same shape as RandomReadWriteWorkload.
        return min(int(rng.paretovariate(1.5)) - 1, self.n_keys - 1)

    async def setup(self, db) -> None:
        async def body(tr):
            for i in range(self.n_keys):
                tr.set(self._key(i), b"init%06d" % i)

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        rng = cluster.loop.rng
        counter = [0]
        counts = self._split(self.n_txns, self.n_clients)

        async def client(cid: int):
            for _ in range(counts[cid]):
                roll = rng.random()
                if roll < self.update_fraction:
                    k = self._key(self._pick(rng))
                    counter[0] += 1
                    val = b"u%08d" % counter[0]

                    async def body(tr, k=k, val=val):
                        await tr.get(k)
                        tr.set(k, val)

                    await self._run_txn(db, body)
                    self._acked[k] = val
                elif roll < self.update_fraction + self.scan_fraction:
                    lo = self._pick(rng)
                    span = 1 + rng.randrange(8)

                    async def body(tr, lo=lo, span=span):
                        return await tr.get_range(
                            self._key(lo), self._key(lo + span), limit=span)

                    await self._run_txn(db, body)
                else:
                    picks = sorted({self._pick(rng)
                                    for _ in range(self.batch)})

                    async def body(tr, picks=picks):
                        rows = await tr.get_multi(
                            [self._key(i) for i in picks])
                        if any(r is None for r in rows):
                            raise WorkloadFailed("ycsb: preloaded row gone")
                        return rows

                    await self._run_txn(db, body)
                self.metrics.ops += 1

        await all_of([
            cluster.loop.spawn(client(i), name=f"ycsb.client{i}")
            for i in range(self.n_clients)
        ])

    async def check(self, db) -> None:
        async def body(tr):
            keys = [self._key(i) for i in range(self.n_keys)]
            batched = await tr.get_multi(keys, snapshot=True)
            for k, got in zip(keys, batched):
                single = await tr.get(k, snapshot=True)
                if got != single:
                    raise WorkloadFailed(
                        f"ycsb: get_multi({k!r})={got!r} != get={single!r}")
            # Read-your-committed: C never writes; B's acked updates must
            # survive (a later acked update to the same key supersedes).
            for k, val in self._acked.items():
                cur = batched[keys.index(k)]
                if cur is None:
                    raise WorkloadFailed(f"ycsb: acked update to {k!r} lost")

        await self._run_txn(db, body)


class WatchFanOutWorkload(Workload):
    """Many watches, few writes: `watchers_per_key` clients arm a watch
    on each of `n_keys` keys (fan-out = product), then one mutation wave
    touches every watched key. Every armed watch must fire (the packed
    registry must not LOSE a fire under fan-out; spurious fires remain
    legal per the reference contract). Exercises the packed watch
    registry's one-sweep-per-version match against a large resident
    set — the cost the reads/ subsystem makes sublinear."""

    name = "watch_fanout"

    def __init__(self, seed: int = 0, n_keys: int = 8,
                 watchers_per_key: int = 4):
        super().__init__(seed)
        self.n_keys = n_keys
        self.watchers_per_key = watchers_per_key

    def _key(self, i: int) -> bytes:
        return b"wfan/%05d" % i

    async def setup(self, db) -> None:
        async def body(tr):
            for i in range(self.n_keys):
                tr.set(self._key(i), b"v0")

        await self._run_txn(db, body)

    async def run(self, db, cluster) -> None:
        MAX_REARMS = 200  # a wedged watch must fail, not hang the sim
        armed = [0]
        fired = [0]
        all_armed = Promise()

        async def watcher(i: int, w: int):
            for attempt in range(MAX_REARMS):
                try:
                    async def arm(tr):
                        return await tr.watch(self._key(i))

                    slot = await self._run_txn(db, arm)
                    armed[0] += 1
                    if armed[0] == self.n_keys * self.watchers_per_key:
                        all_armed.send(None)
                    await slot
                    fired[0] += 1
                    self.metrics.ops += 1
                    return
                except FdbError as e:
                    if not e.retryable:
                        raise
                    # Value may already differ from the armed snapshot —
                    # that immediate fire path raises nothing; only
                    # retryable transport errors land here.
                    await cluster.loop.sleep(0.05)
            raise WorkloadFailed(f"watch fan-out {i}/{w}: re-arms exhausted")

        async def mutator():
            await all_armed.future
            async def body(tr):
                for i in range(self.n_keys):
                    tr.set(self._key(i), b"v1")

            await self._run_txn(db, body)

        tasks = [
            cluster.loop.spawn(watcher(i, w), name=f"wfan.w{i}.{w}")
            for i in range(self.n_keys)
            for w in range(self.watchers_per_key)
        ]
        tasks.append(cluster.loop.spawn(mutator(), name="wfan.mutator"))
        await all_of(tasks)
        want = self.n_keys * self.watchers_per_key
        if fired[0] != want:
            raise WorkloadFailed(
                f"watch fan-out: {fired[0]}/{want} watches fired")
        self.metrics.extra["fan_out"] = want

    async def check(self, db) -> None:
        async def body(tr):
            for i in range(self.n_keys):
                if await tr.get(self._key(i)) != b"v1":
                    raise WorkloadFailed("watch fan-out: wave write lost")

        await self._run_txn(db, body)
