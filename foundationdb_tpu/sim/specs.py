"""TOML test specs driving simulation workloads.

Reference: tests/fast/*.toml — each file holds one or more ``[[test]]``
blocks; a test has a title and one or more ``[[test.workload]]`` entries
run CONCURRENTLY against the same cluster, with optional fault-injection
knobs. The reference's fdbserver -r simulation consumes these; here
``run_spec`` does, against a SimCluster.

Example (the shape the reference uses, reference: tests/fast/Cycle.toml):

    [[test]]
    testTitle = 'CycleWithFaults'
    killInterval = 0.4
    maxKills = 2

    [[test.workload]]
    testName = 'Cycle'
    nodeCount = 10
    transactionCount = 40
"""

from __future__ import annotations

try:
    import tomllib
except ModuleNotFoundError:  # python 3.10: API-compatible backport
    import tomli as tomllib

from dataclasses import dataclass, field

from foundationdb_tpu.runtime.flow import all_of
from foundationdb_tpu.sim.workloads import (
    AtomicOpsWorkload,
    AuthzWorkload,
    BackupRestoreWorkload,
    ChangeFeedWorkload,
    ConflictRangeWorkload,
    ConsistencyCheckWorkload,
    CycleWorkload,
    FailoverZipfRepairWorkload,
    FaultInjector,
    IncrementWorkload,
    MakoWorkload,
    RandomReadWriteWorkload,
    SelectorCorrectnessWorkload,
    TPCCNewOrderWorkload,
    DDBalanceWorkload,
    FuzzApiWorkload,
    IndexStressWorkload,
    RegionFailoverWorkload,
    TaskBucketWorkload,
    TenantWorkload,
    VersionStampWorkload,
    YCSBWorkload,
    WatchesWorkload,
    WatchFanOutWorkload,
    WorkloadMetrics,
    WriteDuringReadWorkload,
    ZipfRepairWorkload,
)

# testName -> (workload class, TOML key -> constructor kwarg). Unknown TOML
# keys are ignored, like the reference tolerates unconsumed knobs.
WORKLOAD_REGISTRY: dict[str, tuple[type, dict[str, str]]] = {
    "Cycle": (CycleWorkload, {
        "nodeCount": "n_nodes",
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
    }),
    "AtomicOps": (AtomicOpsWorkload, {
        "transactionCount": "n_txns",
    }),
    "RandomReadWrite": (RandomReadWriteWorkload, {
        "keyCount": "n_keys",
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
        "writeFraction": "write_fraction",
    }),
    "Mako": (MakoWorkload, {
        "rows": "rows",
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
        "readsPerTransaction": "reads_per_txn",
        "writesPerTransaction": "writes_per_txn",
    }),
    "TpccNewOrder": (TPCCNewOrderWorkload, {
        "warehouses": "warehouses",
        "districts": "districts",
        "items": "items",
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
    }),
    "ConflictRange": (ConflictRangeWorkload, {
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
    }),
    "Watches": (WatchesWorkload, {
        "keyCount": "n_keys",
        "rounds": "n_rounds",
    }),
    "WatchFanOut": (WatchFanOutWorkload, {
        "keyCount": "n_keys",
        "watchersPerKey": "watchers_per_key",
    }),
    "YCSB": (YCSBWorkload, {
        "variant": "variant",
        "keyCount": "n_keys",
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
        "batchSize": "batch",
        "scanFraction": "scan_fraction",
    }),
    "VersionStamp": (VersionStampWorkload, {
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
    }),
    "ChangeFeed": (ChangeFeedWorkload, {
        "keyCount": "n_keys",
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
    }),
    "Increment": (IncrementWorkload, {
        "counterCount": "n_counters",
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
    }),
    "SelectorCorrectness": (SelectorCorrectnessWorkload, {
        "keyCount": "n_keys",
        "queryCount": "n_queries",
        "clientCount": "n_clients",
    }),
    "BackupRestore": (BackupRestoreWorkload, {
        "keyCount": "n_keys",
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
    }),
    "FailoverZipfRepair": (FailoverZipfRepairWorkload, {
        "keyCount": "n_keys",
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
        "theta": "theta",
        "readsPerTransaction": "reads_per_txn",
    }),
    "TaskBucket": (TaskBucketWorkload, {
        "taskCount": "n_tasks",
        "executorCount": "n_executors",
        "lease": "lease",
    }),
    "ZipfRepair": (ZipfRepairWorkload, {
        "keyCount": "n_keys",
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
        "theta": "theta",
        "readsPerTransaction": "reads_per_txn",
        "repair": "repair",
        # hottest = mutual hot-key RMW (cycle-heavy); coldest =
        # read-hot-write-cold chains (the wave-reorderable shape).
        "targetPick": "target_pick",
    }),
    "WriteDuringRead": (WriteDuringReadWorkload, {
        "keyCount": "n_keys",
        "transactionCount": "n_txns",
        "opsPerTransaction": "ops_per_txn",
    }),
    "FuzzApiCorrectness": (FuzzApiWorkload, {
        "keyCount": "n_keys",
        "transactionCount": "n_txns",
        "opsPerTransaction": "ops_per_txn",
    }),
    "IndexStress": (IndexStressWorkload, {
        "itemCount": "n_items",
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
    }),
    "Tenants": (TenantWorkload, {
        "tenantCount": "n_tenants",
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
    }),
    "DDBalance": (DDBalanceWorkload, {
        "keyCount": "n_keys",
        "transactionCount": "n_txns",
        "moveCount": "n_moves",
    }),
    "Authz": (AuthzWorkload, {
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
    }),
    "ConsistencyCheck": (ConsistencyCheckWorkload, {
        "keyCount": "n_keys",
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
    }),
    "RegionFailover": (RegionFailoverWorkload, {
        "transactionCount": "n_txns",
        "clientCount": "n_clients",
        "failAfter": "fail_after",
        "heal": "heal",
        "mode": "mode",
    }),
}


# Base topology every spec runner starts from; [test.cluster] entries
# override it. One definition so the pytest path and the campaign runner
# exercise identical clusters for the same spec.
BASE_CLUSTER = {"n_tlogs": 2, "n_storages": 2}


def cluster_kwargs(spec: "TestSpec") -> dict:
    return {**BASE_CLUSTER, **spec.cluster_opts}


# [test.cluster] / [campaign.cluster] key -> SimCluster kwarg.
CLUSTER_KEY_MAP = {
    "storages": "n_storages",
    "tlogs": "n_tlogs",
    "replicas": "n_replicas",
    "proxies": "n_proxies",
    "resolvers": "n_resolvers",
    "coordinators": "n_coordinators",
    "dataDistribution": "data_distribution",
    "storageEngine": "storage_engine",
    # Resolve-dispatch scheduler (sched subsystem): a coalescing budget
    # and a modeled per-batch device-execution cost — nonzero cost makes
    # dispatch take virtual time, so queue depth (and the ratekeeper's
    # resolver_queue backpressure) is exercisable in simulation.
    "resolverBudget": "resolver_budget_s",
    "resolverDispatchCost": "resolver_dispatch_cost_s",
    # Admission-time early conflict detection (admission subsystem):
    # `admission = true` arms the recent-writes filter + policy on every
    # generation's proxies/resolvers.
    "admission": "admission",
    # Commit-path tracing (obs subsystem): `obs = true` attaches a span
    # sink to the cluster loop; `obsSampleEvery = N` samples 1-in-N
    # (campaigns gate span-tree completeness under faults with it).
    "obs": "obs",
    "obsSampleEvery": "obs_sample_every",
    # Wave commit (reorder-don't-abort resolve; with resolvers > 1 the
    # role-level global edge-exchange protocol) and the engine behind it
    # — campaigns gating wave counters pin engine = 'oracle-replay' so
    # every schedule is sequentially replay-verified inline.
    "waveCommit": "wave_commit",
    "engine": "engine",
}


def cluster_kwargs_from_table(tbl: dict) -> dict:
    """Translate a TOML cluster table into SimCluster kwargs — shared by
    [[test]] specs and [[campaign]] specs so both drive identical
    clusters for the same table."""
    opts = {CLUSTER_KEY_MAP[k]: v for k, v in tbl.items()
            if k in CLUSTER_KEY_MAP}
    # Admission knobs (admission subsystem): threshold/feature overrides
    # collected into SimCluster's admission_opts.
    adm_opts = {}
    if "admissionShapeRisk" in tbl:
        adm_opts["shape_risk"] = float(tbl["admissionShapeRisk"])
    if "admissionPreabort" in tbl:
        adm_opts["preabort"] = bool(tbl["admissionPreabort"])
    if adm_opts:
        opts["admission_opts"] = adm_opts
    # Region config (reference: DatabaseConfiguration regions):
    # `satelliteTlogs = k` turns on the pri/sat/rem multi-region topology.
    if "satelliteTlogs" in tbl:
        opts["multi_region"] = {"satellite_tlogs": tbl["satelliteTlogs"]}
    # `authz = true`: generate an operator keypair for this test cluster —
    # processes verify with the public key; the private key stays
    # harness-side (cluster.authz_private_pem) so workloads can mint
    # tokens, playing the operator.
    if tbl.get("authz"):
        from foundationdb_tpu.runtime.authz import generate_keypair, mint_token

        priv, pub = generate_keypair()
        opts["authz_public_key"] = pub
        opts["authz_private_pem"] = priv
        opts["authz_system_token"] = mint_token(
            priv, [b""], expires_at=1e12, system=True)
    return opts


@dataclass
class TestSpec:
    title: str
    workloads: list  # instantiated Workload objects
    kill_interval: float | None = None
    partition_interval: float | None = None
    max_kills: int = 0
    include_controller: bool = False
    clog_interval: float | None = None  # slow-but-alive link injection
    buggify: bool = False  # enable in-role BUGGIFY sites for this test
    buggify_aggressive: bool = False  # every site active, fire >= 50%
    # [test.cluster] table: tests needing a non-default cluster (e.g. the
    # DataDistributor for DDBalance) declare it; the runner builds a fresh
    # SimCluster with these kwargs for that test only.
    cluster_opts: dict = field(default_factory=dict)


@dataclass
class SpecResult:
    title: str
    metrics: dict[str, WorkloadMetrics] = field(default_factory=dict)
    kills: list[str] = field(default_factory=list)


def load_spec(source: str | bytes) -> list[TestSpec]:
    """Parse TOML text (or a path ending in .toml) into TestSpecs."""
    if isinstance(source, str) and source.endswith(".toml"):
        with open(source, "rb") as f:
            doc = tomllib.load(f)
    else:
        text = source.decode() if isinstance(source, bytes) else source
        doc = tomllib.loads(text)
    specs: list[TestSpec] = []
    for test in doc.get("test", []):
        workloads = []
        for i, w in enumerate(test.get("workload", [])):
            name = w["testName"]
            if name not in WORKLOAD_REGISTRY:
                raise ValueError(f"unknown workload testName {name!r}")
            cls, mapping = WORKLOAD_REGISTRY[name]
            kwargs = {
                mapping[k]: v for k, v in w.items() if k in mapping
            }
            kwargs["seed"] = w.get("seed", test.get("seed", i))
            workloads.append(cls(**kwargs))
        cluster_opts = cluster_kwargs_from_table(test.get("cluster", {}))
        specs.append(TestSpec(
            title=test.get("testTitle", "untitled"),
            workloads=workloads,
            kill_interval=test.get("killInterval"),
            partition_interval=test.get("partitionInterval"),
            max_kills=test.get("maxKills", 0),
            include_controller=test.get("killController", False),
            clog_interval=test.get("clogInterval"),
            buggify=test.get("buggify", False),
            buggify_aggressive=test.get("buggifyAggressive", False),
            cluster_opts=cluster_opts,
        ))
    return specs


async def run_spec_test(spec: TestSpec, cluster, db) -> SpecResult:
    """setup all → run all CONCURRENTLY (± faults) → quiesce → check all —
    the reference's multi-workload test execution order."""
    result = SpecResult(spec.title)
    if spec.buggify or spec.buggify_aggressive:
        cluster.loop.buggify_enabled = True
        cluster.loop.buggify_aggressive = spec.buggify_aggressive
    for w in spec.workloads:
        await w.setup(db)
    faults = None
    if spec.max_kills > 0 or spec.partition_interval or spec.clog_interval:
        faults = FaultInjector(
            cluster,
            kill_interval=spec.kill_interval or 2.0,
            partition_interval=spec.partition_interval or 1.3,
            max_kills=spec.max_kills,
            include_controller=spec.include_controller,
            clog_interval=spec.clog_interval or 0.0,
        )
        fault_task = cluster.loop.spawn(faults.run(), name="spec.faults")
    await all_of([
        cluster.loop.spawn(w.run(db, cluster), name=f"spec.{w.name}")
        for w in spec.workloads
    ])
    if faults:
        faults.stop()
        await fault_task
        cluster.net.heal_all()
        while cluster.controller._recovering:
            await cluster.loop.sleep(0.25)
        result.kills = list(faults.kills)
    for w in spec.workloads:
        await w.check(db)
        result.metrics[w.name] = w.metrics
    return result


def run_spec(source: str | bytes, cluster, db) -> list[SpecResult]:
    """Run every [[test]] in the spec against the given cluster (tests
    with [test.cluster] requirements get their own fresh cluster)."""
    out = []
    for spec in load_spec(source):
        c, d = cluster, db
        if spec.cluster_opts:
            from foundationdb_tpu.client.ryw import open_database
            from foundationdb_tpu.sim.cluster import SimCluster

            c = SimCluster(seed=cluster.loop.rng.randint(0, 1 << 30),
                           **cluster_kwargs(spec))
            d = open_database(c)
        out.append(c.loop.run(run_spec_test(spec, c, d), timeout=3000))
    return out
