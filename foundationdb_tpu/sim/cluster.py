"""Whole-cluster simulation harness: wire every role on a SimNetwork.

The analogue of the reference's simulated cluster setup
(fdbserver/SimulatedCluster.actor.cpp): one deterministic loop, each role
hosted on its own named process so kills/partitions hit realistic blast
radii. The conflict engine is pluggable via the ``newConflictSet()`` seam:
"oracle" (pure-python model), "cpp" (native skiplist), or "tpu" (the jitted
device kernel) — simulation tests default to the oracle so they run
anywhere; the TPU engine is exercised by the kernel/bench suites.
"""

from __future__ import annotations

from foundationdb_tpu.runtime.commit_proxy import CommitProxy
from foundationdb_tpu.runtime.flow import Loop
from foundationdb_tpu.runtime.grv_proxy import GrvProxy
from foundationdb_tpu.runtime.ratekeeper import Ratekeeper
from foundationdb_tpu.runtime.resolver import Resolver
from foundationdb_tpu.runtime.sequencer import Sequencer
from foundationdb_tpu.runtime.shardmap import KeyShardMap
from foundationdb_tpu.runtime.storage import StorageServer
from foundationdb_tpu.runtime.tlog import TLog
from foundationdb_tpu.sim.network import SimNetwork


def new_conflict_set(engine: str):
    if engine == "oracle":
        from foundationdb_tpu.sim.oracle import OracleConflictSet

        return OracleConflictSet()
    if engine == "cpp":
        from foundationdb_tpu.models.cpu_conflict_set import CPUSkipListConflictSet

        return CPUSkipListConflictSet()
    if engine == "tpu":
        from foundationdb_tpu.models.conflict_set import TPUConflictSet

        return TPUConflictSet(capacity=1 << 14, batch_size=256)
    raise ValueError(f"unknown conflict engine {engine!r}")


class SimCluster:
    """A running simulated cluster; role endpoints as attributes."""

    def __init__(
        self,
        loop: Loop | None = None,
        seed: int = 0,
        n_proxies: int = 1,
        n_resolvers: int = 1,
        n_tlogs: int = 1,
        n_storages: int = 2,
        engine: str = "oracle",
        ratekeeper: bool = True,
    ):
        self.loop = loop or Loop(seed=seed)
        self.net = SimNetwork(self.loop)
        self.engine = engine
        self.resolver_map = KeyShardMap.uniform(n_resolvers)
        self.storage_map = KeyShardMap.uniform(n_storages)

        self.sequencer = Sequencer(self.loop)
        self.sequencer_ep = self.net.host("master", "sequencer", self.sequencer)

        self.resolvers = [Resolver(self.loop, new_conflict_set(engine)) for _ in range(n_resolvers)]
        self.resolver_eps = [
            self.net.host(f"resolver{i}", f"resolver{i}", r)
            for i, r in enumerate(self.resolvers)
        ]

        self.tlogs = [TLog(self.loop) for _ in range(n_tlogs)]
        self.tlog_eps = [
            self.net.host(f"tlog{i}", f"tlog{i}", t) for i, t in enumerate(self.tlogs)
        ]

        # Storage servers pull from the first tlog (replicas hold identical
        # content; the reference picks a preferred tlog per tag similarly).
        self.storages = [
            StorageServer(self.loop, tag=i, tlog_ep=self.tlog_eps[0])
            for i in range(n_storages)
        ]
        self.storage_eps = [
            self.net.host(f"storage{i}", f"storage{i}", s)
            for i, s in enumerate(self.storages)
        ]

        self.ratekeeper = Ratekeeper(self.loop, self.storage_eps) if ratekeeper else None
        self.ratekeeper_ep = (
            self.net.host("ratekeeper", "ratekeeper", self.ratekeeper)
            if self.ratekeeper
            else None
        )

        self.grv_proxies = [
            GrvProxy(self.loop, self.sequencer_ep, self.ratekeeper_ep)
            for _ in range(n_proxies)
        ]
        self.grv_proxy_eps = [
            self.net.host(f"grv_proxy{i}", f"grv_proxy{i}", g)
            for i, g in enumerate(self.grv_proxies)
        ]

        self.commit_proxies = [
            CommitProxy(
                self.loop,
                self.sequencer_ep,
                self.resolver_eps,
                self.resolver_map,
                self.tlog_eps,
                self.storage_map,
            )
            for _ in range(n_proxies)
        ]
        self.commit_proxy_eps = [
            self.net.host(f"commit_proxy{i}", f"commit_proxy{i}", c)
            for i, c in enumerate(self.commit_proxies)
        ]

        self._start()

    def _start(self) -> None:
        for i, s in enumerate(self.storages):
            self.loop.spawn(s.run(), process=f"storage{i}", name=f"storage{i}.run")
        for i, g in enumerate(self.grv_proxies):
            self.loop.spawn(g.run(), process=f"grv_proxy{i}", name=f"grv_proxy{i}.run")
        for i, c in enumerate(self.commit_proxies):
            self.loop.spawn(c.run(), process=f"commit_proxy{i}", name=f"commit_proxy{i}.run")
        if self.ratekeeper:
            self.loop.spawn(self.ratekeeper.run(), process="ratekeeper", name="ratekeeper.run")

    # -- client-side routing helpers -----------------------------------------

    def storage_ep_for_key(self, key: bytes):
        return self.storage_eps[self.storage_map.tag_for_key(key)]

    def storage_eps_for_range(self, begin: bytes, end: bytes):
        from foundationdb_tpu.core.types import KeyRange

        return [
            (r, self.storage_eps[tag])
            for r, tag in self.storage_map.split_range(KeyRange(begin, end))
        ]
