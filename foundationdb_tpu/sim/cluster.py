"""Whole-cluster simulation harness: wire every role on a SimNetwork.

The analogue of the reference's simulated cluster setup
(fdbserver/SimulatedCluster.actor.cpp): one deterministic loop, each role
hosted on its own named process so kills/partitions hit realistic blast
radii. The conflict engine is pluggable via the ``newConflictSet()`` seam:
"oracle" (pure-python model), "cpp" (native skiplist), or "tpu" (the jitted
device kernel) — simulation tests default to the oracle so they run
anywhere; the TPU engine is exercised by the kernel/bench suites.

The transaction subsystem (sequencer, resolvers, tlogs, proxies,
ratekeeper) is owned by a ClusterController and recruited per recovery
*generation*: SimCluster is the controller's recruiter — it knows how to
place role objects on `.e{epoch}`-suffixed processes, seed new tlogs with
salvaged entries, re-point the (persistent) storage servers, and retire
the previous generation. Kill any generation process and the controller's
heartbeat sweep drives recovery to a fresh epoch.
"""

from __future__ import annotations

from foundationdb_tpu.runtime.cluster import ClusterController, Generation, Heartbeat
from foundationdb_tpu.runtime.commit_proxy import CommitProxy
from foundationdb_tpu.runtime.flow import Loop
from foundationdb_tpu.runtime.grv_proxy import GrvProxy
from foundationdb_tpu.runtime.ratekeeper import Ratekeeper
from foundationdb_tpu.runtime.resolver import Resolver
from foundationdb_tpu.runtime.sequencer import EPOCH_VERSION_JUMP, Sequencer
from foundationdb_tpu.runtime.shardmap import KeyShardMap
from foundationdb_tpu.runtime.storage import StorageServer
from foundationdb_tpu.runtime.tlog import TLog
from foundationdb_tpu.sim.network import SimNetwork


def new_conflict_set(engine: str):
    if engine == "oracle":
        from foundationdb_tpu.sim.oracle import OracleConflictSet

        return OracleConflictSet()
    if engine == "cpp":
        from foundationdb_tpu.models.cpu_conflict_set import CPUSkipListConflictSet

        return CPUSkipListConflictSet()
    if engine == "tpu":
        from foundationdb_tpu.models.conflict_set import TPUConflictSet

        return TPUConflictSet(capacity=1 << 14, batch_size=256)
    raise ValueError(f"unknown conflict engine {engine!r}")


class SimCluster:
    """A running simulated cluster; role endpoints as attributes (always
    reflecting the CURRENT generation — refreshed on recovery)."""

    def __init__(
        self,
        loop: Loop | None = None,
        seed: int = 0,
        n_proxies: int = 1,
        n_resolvers: int = 1,
        n_tlogs: int = 1,
        n_storages: int = 2,
        n_replicas: int = 1,
        engine: str = "oracle",
        ratekeeper: bool = True,
        data_distribution: bool = False,
        n_coordinators: int = 0,
        n_cc_candidates: int = 3,
    ):
        assert 1 <= n_replicas <= n_storages
        self.loop = loop or Loop(seed=seed)
        self.net = SimNetwork(self.loop)
        self.engine = engine
        self.n_proxies = n_proxies
        self.n_resolvers = n_resolvers
        self.n_tlogs = n_tlogs
        self.n_replicas = n_replicas
        self.with_ratekeeper = ratekeeper
        self.resolver_map = KeyShardMap.uniform(n_resolvers)
        # k-way teams: shard i is owned by storages {i, i+1, ..., i+k-1}
        # (reference: DDTeamCollection builds overlapping teams so load
        # spreads without k*n servers).
        teams = [
            tuple((i + j) % n_storages for j in range(n_replicas))
            for i in range(n_storages)
        ]
        self.storage_map = KeyShardMap.uniform(n_storages, teams=teams)
        self._gen_processes: list[str] = []  # previous generation, for retirement
        self.backup_active = False  # BackupAgent sets; survives recoveries
        self.backup_worker = None  # live BackupWorker (its cursor bounds salvage)
        self.retired_tags: set[int] = set()  # stopped-backup tags, per tlog

        # Storage servers persist across generations (they ARE the data);
        # their tlog endpoint is re-pointed by each recruitment.
        self.storages = [
            StorageServer(self.loop, tag=i, tlog_ep=None) for i in range(n_storages)
        ]
        self.storage_eps = [
            self.net.host(f"storage{i}", f"storage{i}", s)
            for i, s in enumerate(self.storages)
        ]
        # Serve-set guards are active whenever shards can move or replicate
        # (single-replica static clusters skip them entirely).
        if data_distribution or n_replicas > 1:
            for i, s in enumerate(self.storages):
                s.init_served([
                    (sh.range.begin, sh.range.end)
                    for sh in self.storage_map.shards
                    if i in sh.team
                ])

        if n_coordinators:
            self._bootstrap_coordinated(n_coordinators, n_cc_candidates)
        else:
            # Legacy singleton controller (no election, never killed).
            self.coordinators = []
            self.coordinator_eps = []
            self.cc_heartbeats = {}
            self.controller = ClusterController(self.loop, recruiter=self)
            self.controller_ep = self.net.host(
                "cluster_controller", "cluster_controller", self.controller
            )
            self.controller.bootstrap()
            self.loop.spawn(
                self.controller.run(), process="cluster_controller", name="cc.run"
            )

        for i, s in enumerate(self.storages):
            self.loop.spawn(s.run(), process=f"storage{i}", name=f"storage{i}.run")

        self.data_distributor = None
        self.data_distributor_ep = None
        if data_distribution:
            from foundationdb_tpu.runtime.data_distribution import DataDistributor

            self.data_distributor = DataDistributor(
                self.loop, self, replication=n_replicas
            )
            self.data_distributor_ep = self.net.host(
                "data_distributor", "data_distributor", self.data_distributor
            )
            self.loop.spawn(
                self.data_distributor.run(),
                process="data_distributor",
                name="dd.run",
            )

    # -- coordinated-controller mode ------------------------------------------

    def install_controller(self, cc, process: str):
        """Host an elected controller's RPC surface and make it the cluster's
        current controller (called at bootstrap and by takeover winners)."""
        ep = self.net.host(process, "cluster_controller", cc)
        self.controller = cc
        self.controller_ep = ep
        return ep

    def _bootstrap_coordinated(self, n_coordinators: int, n_cc: int) -> None:
        """Coordinator quorum + controller candidates. Initial election is
        seeded synchronously (candidate 0 wins reign 1) so the first
        generation exists before the loop runs — the same shortcut the
        reference takes by writing the cluster file's initial coordinated
        state at database creation."""
        from foundationdb_tpu.runtime.cluster import Heartbeat
        from foundationdb_tpu.runtime.coordination import (
            ControllerCandidate,
            CoordinatedState,
            Coordinator,
        )

        self.coordinators = [Coordinator() for _ in range(n_coordinators)]
        self.coordinator_eps = [
            self.net.host(f"coord{i}", "coordinator", c)
            for i, c in enumerate(self.coordinators)
        ]
        # Every candidate process carries a liveness probe so rivals can
        # tell a dead incumbent from a live one before racing a takeover.
        self.cc_heartbeats = {
            f"cc{i}": self.net.host(f"cc{i}", "heartbeat", Heartbeat())
            for i in range(n_cc)
        }

        cc0 = ClusterController(
            self.loop, recruiter=self, identity="cc0",
            coord=CoordinatedState(self.loop, self.coordinator_eps, 0),
            reign=1,
        )
        self.install_controller(cc0, "cc0")
        cc0.bootstrap()
        seed = {
            "reign": 1,
            "leader": "cc0",
            "controller_ep": self.controller_ep,
            "epoch": 1,
            "recovery_version": 0,
            "tlog_eps": list(self.tlog_eps),
        }
        for c in self.coordinators:
            c.accepted_ballot = (1, 0)
            c.promised = (1, 0)
            c.accepted_value = dict(seed)
        self.loop.spawn(cc0.run(), process="cc0", name="cc0.run")

        self.cc_candidates = [
            ControllerCandidate(self.loop, self, i, self.coordinator_eps)
            for i in range(n_cc)
        ]
        for cand in self.cc_candidates:
            self.loop.spawn(
                cand.run(), process=cand.my_id, name=f"{cand.my_id}.candidate"
            )

    # -- recruiter interface (called by ClusterController / recovery) ---------

    def recruit_generation(
        self, epoch: int, recovery_version: int, seed_entries: list
    ) -> Generation:
        sfx = "" if epoch == 1 else f".e{epoch}"
        start_version = 0 if epoch == 1 else recovery_version + EPOCH_VERSION_JUMP
        # Seed only what some puller may still need: salvage can come from a
        # replica whose log was never trimmed (pullers pop one tlog), and
        # re-seeding its full history would compound across recoveries. The
        # floor is the min over every pull cursor: storage applied versions
        # AND the backup worker's log cursor when a backup is running.
        floor = min(
            (min(s._version, recovery_version) for s in self.storages),
            default=0,
        )
        if self.backup_active and self.backup_worker is not None:
            floor = min(floor, self.backup_worker._version)
        seed_entries = [(v, t) for v, t in seed_entries if v > floor]
        heartbeat_eps: dict = {}

        def host(process: str, name: str, obj, run: bool = False):
            ep = self.net.host(process, name, obj)
            heartbeat_eps[process] = self.net.host(process, "heartbeat", Heartbeat())
            if run:
                self.loop.spawn(obj.run(), process=process, name=f"{name}.run")
            return ep

        self.sequencer = Sequencer(self.loop, epoch, recovery_version)
        assert self.sequencer.last_handed_out == start_version
        self.sequencer_ep = host("master" + sfx, "sequencer", self.sequencer)

        self.resolvers = [
            Resolver(self.loop, new_conflict_set(self.engine), init_version=start_version)
            for _ in range(self.n_resolvers)
        ]
        self.resolver_eps = [
            host(f"resolver{i}{sfx}", f"resolver{i}", r)
            for i, r in enumerate(self.resolvers)
        ]

        self.tlogs = [
            TLog(self.loop, init_version=start_version, seed=list(seed_entries),
                 retired_tags=set(self.retired_tags))
            for _ in range(self.n_tlogs)
        ]
        self.tlog_eps = [
            host(f"tlog{i}{sfx}", f"tlog{i}", t) for i, t in enumerate(self.tlogs)
        ]

        self.ratekeeper = (
            Ratekeeper(self.loop, self.storage_eps) if self.with_ratekeeper else None
        )
        self.ratekeeper_ep = (
            host("ratekeeper" + sfx, "ratekeeper", self.ratekeeper, run=True)
            if self.ratekeeper
            else None
        )

        self.grv_proxies = [
            GrvProxy(self.loop, self.sequencer_ep, self.ratekeeper_ep)
            for _ in range(self.n_proxies)
        ]
        self.grv_proxy_eps = [
            host(f"grv_proxy{i}{sfx}", f"grv_proxy{i}", g, run=True)
            for i, g in enumerate(self.grv_proxies)
        ]

        self.commit_proxies = [
            CommitProxy(
                self.loop,
                self.sequencer_ep,
                self.resolver_eps,
                self.resolver_map,
                self.tlog_eps,
                self.storage_map,
                controller_ep=getattr(self, "controller_ep", None),
                epoch=epoch,
            )
            for _ in range(self.n_proxies)
        ]
        for c in self.commit_proxies:
            c.backup_enabled = self.backup_active  # backup spans recoveries
        self.commit_proxy_eps = [
            host(f"commit_proxy{i}{sfx}", f"commit_proxy{i}", c, run=True)
            for i, c in enumerate(self.commit_proxies)
        ]

        # Hand storage servers to the new generation: roll back anything
        # applied above the recovery version (their old tlog's lost suffix)
        # and re-point their pull loops at the new tlog.
        for s in self.storages:
            s.recover_to(recovery_version, self.tlog_eps[0], self.tlog_eps)

        # Retire the previous generation: locked/stale roles must not keep
        # serving (reference: old-epoch roles die on seeing the new epoch),
        # and their objects must be unhosted or every recovery leaks them.
        for proc in self._gen_processes:
            self.loop.kill_process(proc)
            self.net.unhost_process(proc)
        self._gen_processes = list(heartbeat_eps)

        return Generation(
            epoch=epoch,
            recovery_version=recovery_version,
            sequencer_ep=self.sequencer_ep,
            resolver_eps=self.resolver_eps,
            tlog_eps=self.tlog_eps,
            grv_proxy_eps=self.grv_proxy_eps,
            commit_proxy_eps=self.commit_proxy_eps,
            ratekeeper_ep=self.ratekeeper_ep,
            heartbeat_eps=heartbeat_eps,
        )

    # -- client-side routing helpers -----------------------------------------

    def storage_ep_for_key(self, key: bytes):
        return self.storage_eps[self.storage_map.tag_for_key(key)]

    def storage_eps_for_range(self, begin: bytes, end: bytes):
        from foundationdb_tpu.core.types import KeyRange

        return [
            (r, self.storage_eps[tag])
            for r, tag in self.storage_map.split_range(KeyRange(begin, end))
        ]
