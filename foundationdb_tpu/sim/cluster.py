"""Whole-cluster simulation harness: wire every role on a SimNetwork.

The analogue of the reference's simulated cluster setup
(fdbserver/SimulatedCluster.actor.cpp): one deterministic loop, each role
hosted on its own named process so kills/partitions hit realistic blast
radii. The conflict engine is pluggable via the ``newConflictSet()`` seam:
"oracle" (pure-python model), "cpp" (native skiplist), or "tpu" (the jitted
device kernel) — simulation tests default to the oracle so they run
anywhere; the TPU engine is exercised by the kernel/bench suites.

The transaction subsystem (sequencer, resolvers, tlogs, proxies,
ratekeeper) is owned by a ClusterController and recruited per recovery
*generation*: SimCluster is the controller's recruiter — it knows how to
place role objects on `.e{epoch}`-suffixed processes, seed new tlogs with
salvaged entries, re-point the (persistent) storage servers, and retire
the previous generation. Kill any generation process and the controller's
heartbeat sweep drives recovery to a fresh epoch.
"""

from __future__ import annotations

import json
import os

from foundationdb_tpu.runtime.cluster import ClusterController, Generation, Heartbeat
from foundationdb_tpu.runtime.commit_proxy import CommitProxy
from foundationdb_tpu.runtime.flow import Loop
from foundationdb_tpu.runtime.grv_proxy import GrvProxy
from foundationdb_tpu.runtime.ratekeeper import Ratekeeper
from foundationdb_tpu.runtime.resolver import Resolver
from foundationdb_tpu.runtime.sequencer import EPOCH_VERSION_JUMP, Sequencer
from foundationdb_tpu.runtime.shardmap import KeyShardMap
from foundationdb_tpu.runtime.storage import StorageServer
from foundationdb_tpu.runtime.tlog import TLog
from foundationdb_tpu.core.types import (
    validate_wave_commit as _validate_wave_commit,
    wave_commit_env_default as _wave_commit_default,
)
from foundationdb_tpu.sim.network import SimNetwork


#: Engines implementing the role-level global wave protocol
#: (resolve_edges/resolve_apply — core/wavemesh): legal under
#: wave_commit at ANY resolver count. The cpp skiplist never
#: materializes the conflict graph and refuses wave commit outright.
WAVE_GLOBAL_CAPABLE_ENGINES = frozenset({"oracle", "oracle-replay", "tpu"})


def new_conflict_set(engine: str, wave_commit: bool | None = None):
    """Conflict-engine factory (the ``newConflictSet()`` seam).

    ``wave_commit`` selects the reorder-don't-abort resolve mode
    (conflict-graph wave scheduling; only true cycles abort). None reads
    the FDB_TPU_WAVE_COMMIT env flag so A/B harnesses can flip whole sim
    clusters per-subprocess without code changes."""
    if wave_commit is None:
        wave_commit = _wave_commit_default()
    if engine == "oracle":
        from foundationdb_tpu.sim.oracle import OracleConflictSet

        return OracleConflictSet(wave_commit=wave_commit)
    if engine == "oracle-replay":
        # Oracle that PROVES each wave schedule by sequential replay inline
        # (raises on any serializability violation) — the wave-commit A/B's
        # verification engine; identical to "oracle" when wave_commit off.
        from foundationdb_tpu.sim.oracle import ReplayCheckedOracle

        return ReplayCheckedOracle(wave_commit=wave_commit)
    if engine == "cpp":
        from foundationdb_tpu.models.cpu_conflict_set import CPUSkipListConflictSet

        if wave_commit:
            _validate_wave_commit(skiplist_engine="cpp")
        return CPUSkipListConflictSet()
    if engine == "tpu":
        from foundationdb_tpu.models.conflict_set import TPUConflictSet

        return TPUConflictSet(capacity=1 << 14, batch_size=256,
                              wave_commit=wave_commit)
    raise ValueError(f"unknown conflict engine {engine!r}")


class SimCluster:
    """A running simulated cluster; role endpoints as attributes (always
    reflecting the CURRENT generation — refreshed on recovery)."""

    def __init__(
        self,
        loop: Loop | None = None,
        seed: int = 0,
        n_proxies: int = 1,
        n_resolvers: int = 1,
        n_tlogs: int = 1,
        n_storages: int = 2,
        n_replicas: int = 1,
        engine: str = "oracle",
        ratekeeper: bool = True,
        data_distribution: bool = False,
        n_coordinators: int = 0,
        n_cc_candidates: int = 3,
        data_dir: str | None = None,
        timekeeper: bool = True,
        process_prefix: str = "",
        authz_public_key: bytes | None = None,
        authz_system_token: str | None = None,
        authz_private_pem: bytes | None = None,
        multi_region: dict | None = None,
        storage_engine: str = "sqlite",
        resolver_budget_s: float = 0.0,
        resolver_dispatch_cost_s: float = 0.0,
        wave_commit: bool | None = None,
        admission: bool | None = None,
        admission_opts: dict | None = None,
        obs: bool | None = None,
        obs_sample_every: int | None = None,
        recorder_path: str | None = None,
        recorder_interval_s: float | None = None,
    ):
        """``multi_region`` (reference: DatabaseConfiguration regions —
        fdbclient/DatabaseConfiguration.cpp — and DataDistribution region
        teams): a three-region topology
        ``{"satellite_tlogs": k}`` with

        - **pri/**: the active region — the whole transaction subsystem
          (sequencer, resolvers, tlogs, proxies) plus one storage replica
          per shard;
        - **sat/**: satellite TLogs — IN the synchronous commit path
          (every proxy push awaits them, the reference's satellite
          redundancy), holding the full mutation stream but no storage;
        - **rem/**: the standby region — the other storage replica of
          every shard (pulling asynchronously, the reference's remote
          region), plus capacity to host the next transaction subsystem.

        Automatic inter-region failover: when recovery runs while the
        active region is dead (``net.fail_region("pri/")``), recruitment
        flips the active region to the standby and re-forms the chain
        there, salvaging from the surviving satellite tlogs — which hold
        every ACKED commit by construction, so failover loses nothing.
        """
        assert 1 <= n_replicas <= n_storages
        self.multi_region = multi_region or None
        if self.multi_region:
            assert n_replicas == 1 and not data_distribution, (
                "multi_region replicates across regions (one replica per "
                "region); in-region replication/DD on top is not modeled"
            )
            self.active_region = "pri"
            self.standby_region = "rem"
            self.n_satellite_tlogs = int(self.multi_region.get(
                "satellite_tlogs", 1))
        self.loop = loop or Loop(seed=seed)
        # Real durability (reference: tlog DiskQueue + KeyValueStoreSQLite):
        # tlogs fsync pushes to append-only queues, storages flush a
        # consistent prefix to sqlite. A SimCluster re-created on the same
        # data_dir restarts from disk: epoch advances, the last generation's
        # disk queues seed the new tlogs, storage reloads its snapshot.
        self.data_dir = data_dir
        self._restore = self._read_cluster_meta() if data_dir else None
        # Ring-buffer tracer on every sim cluster: role trace events are
        # queryable in tests/status with zero config (reference: TraceEvent
        # always logs; sim asserts on trace lines).
        from foundationdb_tpu.runtime.trace import Tracer

        if not hasattr(self.loop, "tracer"):
            Tracer(self.loop)
        # Commit-path tracing (obs subsystem; None = the FDB_TPU_OBS env
        # default, off by default): one SpanSink per loop — every role and
        # client on this cluster's loop stamps spans into it, so a sim run
        # yields complete, seed-deterministic span trees.
        from foundationdb_tpu.obs.span import SpanSink, obs_env_default

        self.obs = obs_env_default() if obs is None else bool(obs)
        if self.obs and not hasattr(self.loop, "span_sink"):
            SpanSink(self.loop, sample_every=obs_sample_every)
        # Flight recorder (obs subsystem): event-annotated metric
        # time-series ring on disk + SLO tracking, armed per cluster via
        # recorder_path. Spawned on its own sim process so kills /
        # partitions of cluster roles never take the recorder with them
        # (it is the thing that must survive the incident).
        self.flight_recorder = None
        if recorder_path is not None:
            from foundationdb_tpu.obs.recorder import FlightRecorder
            from foundationdb_tpu.obs.registry import scrape_sim

            self.flight_recorder = FlightRecorder(
                self.loop, lambda: scrape_sim(self), recorder_path,
                interval_s=recorder_interval_s,
            )
            self.loop.spawn(
                self.flight_recorder.run(),
                process=process_prefix + "flight_recorder",
                name="flight_recorder.run",
            )
        # Namespace for loop-global process names: two clusters on one
        # Loop (a DR pair) must not both own a "tlog0" (kills would
        # cross clusters). Applied by SimNetwork at host()/kill() and
        # here at every loop.spawn(process=...).
        self.process_prefix = process_prefix
        self.net = SimNetwork(self.loop, process_prefix=process_prefix)
        self.engine = engine
        self.n_proxies = n_proxies
        self.n_resolvers = n_resolvers
        self.n_tlogs = n_tlogs
        self.n_replicas = n_replicas
        self.with_ratekeeper = ratekeeper
        # Resolve-dispatch scheduler knobs (sched subsystem): coalescing
        # budget + modeled per-batch device-execution cost (virtual time),
        # applied to every generation's resolvers — nonzero cost is what
        # makes resolver queue depth (and the ratekeeper's resolver_queue
        # backpressure loop) observable under simulation.
        self.resolver_budget_s = resolver_budget_s
        self.resolver_dispatch_cost_s = resolver_dispatch_cost_s
        # Wave-commit resolve mode (reorder-don't-abort; None = the
        # FDB_TPU_WAVE_COMMIT env default). Multi-resolver wave commit is
        # a CAPABILITY check, not a blanket refusal: engines implementing
        # the global edge-exchange protocol (resolve_edges/resolve_apply
        # — oracle, oracle-replay, tpu) reorder against the OR-reduced
        # GLOBAL graph at any resolver count; the cpp skiplist never
        # materializes the graph and still refuses.
        self.wave_commit = (_wave_commit_default() if wave_commit is None
                            else bool(wave_commit))
        if self.wave_commit:
            _validate_wave_commit(
                n_resolvers=n_resolvers,
                skiplist_engine="cpp" if engine == "cpp" else None,
                wave_global_capable=engine in WAVE_GLOBAL_CAPABLE_ENGINES,
            )
        # Admission-time early conflict detection (admission subsystem;
        # None = the FDB_TPU_ADMISSION env default, off by default): each
        # generation's resolvers get a recent-writes filter (the
        # authoritative feed), each commit proxy an AdmissionPolicy over
        # its own probe filter (self-fed from its batches + resolver
        # deltas), and the GRV proxies defer on the saturation signal the
        # ratekeeper aggregates.
        from foundationdb_tpu.admission import admission_env_default

        self.admission = (admission_env_default() if admission is None
                          else bool(admission))
        self.admission_opts = dict(admission_opts or {})
        # Operator tag quotas survive recoveries: the dict is SHARED with
        # each generation's Ratekeeper (set_tag_quota mutates it in
        # place), so a newly recruited ratekeeper inherits every quota —
        # campaign-found defect: a kill-triggered recovery silently
        # unthrottled an abusive tag (QuotaAbuseUnderKills seed 3).
        self.tag_quotas: dict[str, float] = {}
        self.resolver_map = KeyShardMap.uniform(n_resolvers)
        # k-way ring teams (shared with the deployed storage_shard_map —
        # runtime/shardmap.ring_teams; reference: DDTeamCollection builds
        # overlapping teams so load spreads without k*n servers).
        # Multi-region: REGION teams — each shard's replicas are
        # (primary storage i, remote storage n+i), the reference's
        # cross-region team pairing.
        from foundationdb_tpu.runtime.shardmap import ring_teams

        if self.multi_region:
            teams = [(i, n_storages + i) for i in range(n_storages)]
        else:
            teams = ring_teams(n_storages, n_replicas) or [
                (i,) for i in range(n_storages)
            ]
        self.storage_map = KeyShardMap.uniform(n_storages, teams=teams)
        self._gen_processes: list[str] = []  # previous generation, for retirement
        self.backup_active = False  # BackupAgent sets; survives recoveries
        self.backup_worker = None  # live BackupWorker (its cursor bounds salvage)
        self.db_locked = False  # DR switchover / operator lock; survives recoveries
        # Tenant authorization (runtime/authz): proxies of every generation
        # verify commit tokens against this public key when set.
        self.authz = None
        if authz_public_key is not None:
            from foundationdb_tpu.runtime.authz import TokenAuthority

            self.authz = TokenAuthority(authz_public_key)
        # Operator-minted system-scope token for in-process system actors
        # (TimeKeeper): with authz on, \xff writes require it.
        self.authz_system_token = authz_system_token
        # HARNESS-side operator private key (cluster processes never hold
        # it — they verify with the public key only): lets spec-driven
        # workloads mint tokens mid-run, playing the operator (the
        # reference's simulation signs tokens the same way).
        self.authz_private_pem = authz_private_pem
        self.retired_tags: set[int] = set()  # stopped-backup tags, per tlog

        # Storage servers persist across generations (they ARE the data);
        # their tlog endpoint is re-pointed by each recruitment.
        def make_kvstore(i: int):
            if data_dir is None:
                return None
            from foundationdb_tpu.runtime.kvstore import make_kvstore as mk

            return mk(os.path.join(data_dir, f"storage{i}.db"),
                      storage_engine)

        n_storage_total = n_storages * (2 if self.multi_region else 1)
        self.storages = [
            StorageServer(self.loop, tag=i, tlog_ep=None,
                          kvstore=make_kvstore(i), authz=self.authz)
            for i in range(n_storage_total)
        ]
        self.storage_eps = [
            self.net.host(self._region_proc(self._storage_region(i),
                                            f"storage{i}"),
                          f"storage{i}", s)
            for i, s in enumerate(self.storages)
        ]
        # ONE tenant-map mirror per cluster (authz.TenantMapMirror):
        # proxies check tenant-bound tokens at commit, storages at read;
        # all against the same live view refreshed from the owning
        # storage team at its latest applied version.
        self.tenant_mirror = None
        if self.authz is not None:
            from foundationdb_tpu.runtime.authz import TenantMapMirror

            self.tenant_mirror = TenantMapMirror(
                self.loop, self.storage_eps, self.storage_map,
                token=self.authz_system_token,
            )
            self.loop.spawn(
                self.tenant_mirror.run(),
                process=process_prefix + "tenant_mirror",
                name="tenant_mirror.run",
            )
            for s in self.storages:
                s.tenant_mirror = self.tenant_mirror
                # Peer-facing credential for shard-move snapshots (mint
                # the cluster token as [b""] + system: moves copy user
                # keyspace).
                s.system_token = self.authz_system_token
        # Serve-set guards are active whenever shards can move or replicate
        # (single-replica static clusters skip them entirely).
        if data_distribution or n_replicas > 1 or self.multi_region:
            for i, s in enumerate(self.storages):
                s.init_served([
                    (sh.range.begin, sh.range.end)
                    for sh in self.storage_map.shards
                    if i in sh.team
                ])

        if n_coordinators:
            self._bootstrap_coordinated(n_coordinators, n_cc_candidates)
        else:
            # Legacy singleton controller (no election, never killed).
            self.coordinators = []
            self.coordinator_eps = []
            self.cc_heartbeats = {}
            self.controller = ClusterController(self.loop, recruiter=self)
            self.controller_ep = self.net.host(
                "cluster_controller", "cluster_controller", self.controller
            )
            self.controller.bootstrap(**self._bootstrap_args())
            self.loop.spawn(
                self.controller.run(), process=process_prefix + "cluster_controller", name="cc.run"
            )

        for i, s in enumerate(self.storages):
            self.loop.spawn(
                s.run(),
                process=process_prefix + self._region_proc(
                    self._storage_region(i), f"storage{i}"),
                name=f"storage{i}.run")

        self.data_distributor = None
        self.data_distributor_ep = None
        if data_distribution:
            from foundationdb_tpu.runtime.data_distribution import DataDistributor

            self.data_distributor = DataDistributor(
                self.loop, self, replication=n_replicas
            )
            self.data_distributor_ep = self.net.host(
                "data_distributor", "data_distributor", self.data_distributor
            )
            self.loop.spawn(
                self.data_distributor.run(),
                process=process_prefix + "data_distributor",
                name="dd.run",
            )

        # TimeKeeper (reference: the actor inside ClusterController):
        # version ↔ clock samples through the normal commit path. Spawned
        # once — it survives recoveries via the client retry loop.
        self.timekeeper = None
        if timekeeper:
            from foundationdb_tpu.client.ryw import open_database
            from foundationdb_tpu.runtime.timekeeper import TimeKeeper

            self.timekeeper = TimeKeeper(self.loop, open_database(self),
                                         token=authz_system_token)
            self.loop.spawn(
                self.timekeeper.run(), process=process_prefix + "timekeeper",
                name="timekeeper.run",
            )

    # -- durable restart (reference: tlog DiskQueue + sqlite engine) ----------

    def _meta_path(self) -> str:
        return os.path.join(self.data_dir, "cluster.json")

    def _read_cluster_meta(self) -> dict | None:
        path = self._meta_path()
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return json.load(f)

    def _persist_cluster_meta(self, epoch: int, recovery_version: int,
                              tlog_files: list[str]) -> None:
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "epoch": epoch,
                "recovery_version": recovery_version,
                "tlog_files": tlog_files,
            }, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._meta_path())  # atomic swap

    def _bootstrap_args(self) -> dict:
        """Fresh cluster → epoch 1; restart → persisted epoch + 1 with the
        last generation's disk queues salvaged as seed entries (the whole-
        cluster-crash analogue of recovery's lock-and-salvage)."""
        if not self._restore:
            return {}
        from foundationdb_tpu.runtime.diskqueue import DiskQueue

        best: list = []
        for path in self._restore["tlog_files"]:
            entries = DiskQueue.recover(path)
            if len(entries) > len(best):
                best = entries  # replicas are identical chains: longest wins
        recovery_version = max(
            [v for v, _t in best] + [self._restore["recovery_version"]]
        )
        return {
            "epoch": self._restore["epoch"] + 1,
            "recovery_version": recovery_version,
            "seed_entries": best,
        }

    # -- coordinated-controller mode ------------------------------------------

    def install_controller(self, cc, process: str):
        """Host an elected controller's RPC surface and make it the cluster's
        current controller (called at bootstrap and by takeover winners)."""
        ep = self.net.host(process, "cluster_controller", cc)
        self.controller = cc
        self.controller_ep = ep
        return ep

    def _bootstrap_coordinated(self, n_coordinators: int, n_cc: int) -> None:
        """Coordinator quorum + controller candidates. Initial election is
        seeded synchronously (candidate 0 wins reign 1) so the first
        generation exists before the loop runs — the same shortcut the
        reference takes by writing the cluster file's initial coordinated
        state at database creation."""
        from foundationdb_tpu.runtime.cluster import Heartbeat
        from foundationdb_tpu.runtime.coordination import (
            ControllerCandidate,
            CoordinatedState,
            Coordinator,
        )

        self.coordinators = [Coordinator() for _ in range(n_coordinators)]
        self.coordinator_eps = [
            self.net.host(f"coord{i}", "coordinator", c)
            for i, c in enumerate(self.coordinators)
        ]
        # Every candidate process carries a liveness probe so rivals can
        # tell a dead incumbent from a live one before racing a takeover.
        self.cc_heartbeats = {
            f"cc{i}": self.net.host(f"cc{i}", "heartbeat", Heartbeat())
            for i in range(n_cc)
        }

        cc0 = ClusterController(
            self.loop, recruiter=self, identity="cc0",
            coord=CoordinatedState(self.loop, self.coordinator_eps, 0),
            reign=1,
        )
        self.install_controller(cc0, "cc0")
        cc0.bootstrap(**self._bootstrap_args())
        g = cc0.generation
        seed = {
            "reign": 1,
            "leader": "cc0",
            "controller_ep": self.controller_ep,
            "epoch": g.epoch,
            "recovery_version": g.recovery_version,
            "tlog_eps": list(self.tlog_eps),
        }
        for c in self.coordinators:
            c.accepted_ballot = (1, 0)
            c.promised = (1, 0)
            c.accepted_value = dict(seed)
        self.loop.spawn(cc0.run(), process=self.process_prefix + "cc0", name="cc0.run")

        self.cc_candidates = [
            ControllerCandidate(self.loop, self, i, self.coordinator_eps)
            for i in range(n_cc)
        ]
        for cand in self.cc_candidates:
            self.loop.spawn(
                cand.run(), process=self.process_prefix + cand.my_id,
                name=f"{cand.my_id}.candidate"
            )

    def retire_previous(self) -> None:
        """Kill + unhost the superseded generation's roles (reference:
        old-epoch roles die on seeing the new epoch). Called by the
        controller once the new generation is PUBLISHED.

        Names still in the CURRENT generation are skipped: a deposed
        rival that recruited at the same epoch used the same process
        names (sfx is epoch-derived), and killing by that shared name
        would take down the winner's live roles — the rival's orphaned
        actors are left to fail harmlessly against the locked old
        tlogs."""
        current = set(self._gen_processes)
        for proc in set(getattr(self, "_pending_retirement", [])):
            if proc in current:
                continue
            self.loop.kill_process(self.process_prefix + proc)
            self.net.unhost_process(proc)
        self._pending_retirement = []

    # -- region placement -----------------------------------------------------

    def _storage_region(self, i: int) -> str | None:
        if not self.multi_region:
            return None
        return "pri" if i < len(self.storage_map.shards) else "rem"

    def _region_proc(self, region: str | None, name: str) -> str:
        """Region-prefixed process name ("pri/storage0"); plain name in
        single-region clusters (zero behavior change there)."""
        return f"{region}/{name}" if region else name

    def storage_procs(self) -> list[str]:
        """Actual storage process names, region-prefixed on multi-region
        clusters — the ONE place the scheme lives. A bare "storage0"
        names nothing there, so any consumer building its own (fault
        injection, worker_interfaces discovery) silently no-ops."""
        return [
            self._region_proc(self._storage_region(i), f"storage{i}")
            for i in range(len(self.storages))
        ]

    def _pick_active_region(self) -> str | None:
        """Recruitment-time region choice (the automatic failover seam):
        if the active region is dead and the standby is not, flip — the
        new transaction subsystem forms in the standby region, salvaging
        from the satellite tlogs. Reference: ClusterController's
        datacenter preference + region failover
        (fdbserver/ClusterController.actor.cpp bestDC logic)."""
        if not self.multi_region:
            return None

        def dark(region: str) -> bool:
            # Dead (blackout) and partitioned-alive both read as dark
            # from the controller's side — the deployed controller makes
            # the same call from failed probes, unable to distinguish.
            return (self.net.region_dead(region + "/")
                    or self.net.region_partitioned(region + "/"))

        if dark(self.active_region) and not dark(self.standby_region):
            from foundationdb_tpu.runtime.trace import Severity, trace

            trace(self.loop).event(
                "RegionFailover", Severity.WARN_ALWAYS,
                failed=self.active_region, to=self.standby_region,
            )
            self.active_region, self.standby_region = (
                self.standby_region, self.active_region)
        return self.active_region

    def heal_region(self, region: str) -> None:
        """Harness-side region heal: clear the network fault and restart
        the region's storage pull loops (sim kills cancel actor tasks;
        the storage OBJECTS survive with their data — a rebooted machine
        reattaching its disk). Chain roles of the dead region are NOT
        restarted: they belong to a retired generation; the region serves
        as standby until a failover recruits into it again. Catch-up is
        guaranteed by the pop-floor machinery: these storages never
        popped their tags from the new generation's tlogs, so the suffix
        they missed is still held for them."""
        self.net.heal_region(region + "/")
        for i, s in enumerate(self.storages):
            if self._storage_region(i) == region:
                self.loop.spawn(
                    s.run(),
                    process=self.process_prefix + self._region_proc(
                        region, f"storage{i}"),
                    name=f"storage{i}.run")

    # -- recruiter interface (called by ClusterController / recovery) ---------

    def _derive_resolver_map(self) -> KeyShardMap:
        """Density-driven resolver splits (reference: CommitProxyServer
        resolver ranges kept balanced from DD metrics): split the
        keyspace at the byte-weighted quantiles of DataDistribution's
        last shard-stats pass, so each resolver owns ~equal observed
        load instead of equal key prefixes. Safe ONLY at recruitment —
        resolver histories reset with the generation, so moving the
        split cannot separate a read from the history of the writes it
        must be checked against."""
        from foundationdb_tpu.runtime.shardmap import MAX_KEY

        n = self.n_resolvers
        stats = getattr(self, "dd_shard_bytes", None)  # [(begin, end, bytes)]
        total = sum(b for _, _, b in stats) if stats else 0
        if n <= 1 or not total:
            return KeyShardMap.uniform(n)
        picks: list[bytes] = []
        acc, d = 0, 1
        for _begin, end, nbytes in stats:  # shards in key order
            acc += nbytes
            while d < n and acc * n >= d * total:
                if end != MAX_KEY and (not picks or end > picks[-1]):
                    picks.append(end)  # split at this shard's end boundary
                d += 1
        if len(picks) != n - 1:
            return KeyShardMap.uniform(n)  # too few distinct boundaries
        return KeyShardMap(picks, tags=list(range(n)))

    def recruit_generation(
        self, epoch: int, recovery_version: int, seed_entries: list
    ) -> Generation:
        sfx = "" if epoch == 1 else f".e{epoch}"
        start_version = 0 if epoch == 1 else recovery_version + EPOCH_VERSION_JUMP
        # Seed only what some puller may still need: salvage can come from a
        # replica whose log was never trimmed (pullers pop one tlog), and
        # re-seeding its full history would compound across recoveries. The
        # floor is the min over every pull cursor: storage applied versions
        # (DURABLE versions when a persistent engine runs — everything above
        # sqlite's snapshot must survive into the new epoch's disk queues or
        # a later whole-cluster crash loses acked commits) AND the backup
        # worker's log cursor when a backup is running.
        def pull_floor(s) -> int:
            return s._version if s.kvstore is None else s._durable_version

        floor = min(
            (min(pull_floor(s), recovery_version) for s in self.storages),
            default=0,
        )
        if self.backup_active and self.backup_worker is not None:
            floor = min(floor, self.backup_worker._version)
        seed_entries = [(v, t) for v, t in seed_entries if v > floor]
        heartbeat_eps: dict = {}
        region = self._pick_active_region()

        def host(process: str, name: str, obj, run: bool = False,
                 region_name: str | None = region):
            process = self._region_proc(region_name, process)
            ep = self.net.host(process, name, obj)
            heartbeat_eps[process] = self.net.host(process, "heartbeat", Heartbeat())
            if run:
                self.loop.spawn(obj.run(),
                                process=self.process_prefix + process,
                                name=f"{name}.run")
            return ep

        if epoch > 1:
            # Re-split resolver ranges from observed density at recovery
            # (fresh resolver histories make the move safe).
            self.resolver_map = self._derive_resolver_map()

        self.sequencer = Sequencer(self.loop, epoch, recovery_version)
        assert self.sequencer.last_handed_out == start_version
        self.sequencer_ep = host("master" + sfx, "sequencer", self.sequencer)

        def new_admission_filter():
            if not self.admission:
                return None
            from foundationdb_tpu.admission import RecentWritesFilter

            return RecentWritesFilter(
                **{k: v for k, v in self.admission_opts.items()
                   if k in ("bits_log2", "banks", "window_versions")})

        self.resolvers = [
            Resolver(self.loop,
                     new_conflict_set(self.engine,
                                      wave_commit=self.wave_commit),
                     init_version=start_version,
                     budget_s=self.resolver_budget_s,
                     dispatch_cost_s=self.resolver_dispatch_cost_s,
                     admission_filter=new_admission_filter())
            for _ in range(self.n_resolvers)
        ]
        self.resolver_eps = [
            host(f"resolver{i}{sfx}", f"resolver{i}", r)
            for i, r in enumerate(self.resolvers)
        ]

        def tlog_disk(i: int) -> str | None:
            if self.data_dir is None:
                return None
            return os.path.join(self.data_dir, f"tlog{i}.e{epoch}.q")

        self.tlogs = [
            TLog(self.loop, init_version=start_version, seed=list(seed_entries),
                 retired_tags=set(self.retired_tags), disk_path=tlog_disk(i),
                 epoch=epoch)
            for i in range(self.n_tlogs)
        ]
        self.tlog_eps = [
            host(f"tlog{i}{sfx}", f"tlog{i}", t) for i, t in enumerate(self.tlogs)
        ]
        # Region tlog set: chain tlogs serve storage pulls; satellite
        # tlogs (hosted in the satellite region, full replicas of the
        # mutation stream) are in the proxies' synchronous push set AND
        # recovery's lock/salvage set — that is what makes region
        # failover lossless (reference: satellite TLogs,
        # TLogServer.actor.cpp + DatabaseConfiguration satellite policy).
        chain_tlog_eps = list(self.tlog_eps)
        if self.multi_region and self.n_satellite_tlogs:
            self.satellite_tlogs = [
                TLog(self.loop, init_version=start_version,
                     seed=list(seed_entries),
                     retired_tags=set(self.retired_tags), epoch=epoch)
                for _ in range(self.n_satellite_tlogs)
            ]
            sat_eps = [
                host(f"tlog_s{i}{sfx}", f"tlog_s{i}", t, region_name="sat")
                for i, t in enumerate(self.satellite_tlogs)
            ]
            self.tlogs = self.tlogs + self.satellite_tlogs
            self.tlog_eps = chain_tlog_eps + sat_eps
        if self.data_dir is not None:
            self._persist_cluster_meta(
                epoch, recovery_version,
                [tlog_disk(i) for i in range(self.n_tlogs)],
            )

        self.ratekeeper = (
            # resolver_eps: the sched subsystem's backpressure loop —
            # resolver dispatch-queue depth throttles admission.
            # tag_quotas: the cluster's shared dict, so operator quotas
            # survive the generation change (see __init__).
            Ratekeeper(self.loop, self.storage_eps, self.tlog_eps,
                       resolver_eps=self.resolver_eps,
                       tag_quotas=self.tag_quotas)
            if self.with_ratekeeper
            else None
        )
        self.ratekeeper_ep = (
            host("ratekeeper" + sfx, "ratekeeper", self.ratekeeper, run=True)
            if self.ratekeeper
            else None
        )

        self.grv_proxies = [
            # tlog_eps includes the satellites — the full push set is the
            # confirmEpochLive set (see runtime/grv_proxy.py).
            GrvProxy(self.loop, self.sequencer_ep, self.ratekeeper_ep,
                     tlog_eps=self.tlog_eps, epoch=epoch)
            for _ in range(self.n_proxies)
        ]
        self.grv_proxy_eps = [
            host(f"grv_proxy{i}{sfx}", f"grv_proxy{i}", g, run=True)
            for i, g in enumerate(self.grv_proxies)
        ]

        def new_admission_policy():
            if not self.admission:
                return None
            from foundationdb_tpu.admission import AdmissionPolicy

            return AdmissionPolicy(
                filter=new_admission_filter(), enabled=True,
                shape_risk=self.admission_opts.get("shape_risk"),
                preabort=self.admission_opts.get("preabort"),
            )

        self.commit_proxies = [
            CommitProxy(
                self.loop,
                self.sequencer_ep,
                self.resolver_eps,
                self.resolver_map,
                self.tlog_eps,
                self.storage_map,
                controller_ep=getattr(self, "controller_ep", None),
                epoch=epoch,
                authz=self.authz,
                tenant_mirror=self.tenant_mirror,
                admission=new_admission_policy(),
                wave_commit=self.wave_commit,
                # One exchange = one schedule domain: cap wave batches at
                # the recruited engines' OWN chunk (derived, not
                # re-stated — a drifted constant would hit resolve_edges'
                # loud per-window refusal under load); oracle engines are
                # unchunked (None).
                wave_batch_limit=getattr(
                    self.resolvers[0].cs, "batch_size", None
                ),
            )
            for _ in range(self.n_proxies)
        ]
        for c in self.commit_proxies:
            c.backup_enabled = self.backup_active  # backup spans recoveries
            c.locked = self.db_locked  # the lock spans recoveries too
        self.commit_proxy_eps = [
            host(f"commit_proxy{i}{sfx}", f"commit_proxy{i}", c, run=True)
            for i, c in enumerate(self.commit_proxies)
        ]
        if self.ratekeeper is not None:
            # Proxies recruit after the ratekeeper; hand it their endpoints
            # so it can measure committed-txn throughput (calibration).
            self.ratekeeper.proxies = list(self.commit_proxy_eps)

        # Hand storage servers to the new generation: roll back anything
        # applied above the recovery version (their old tlog's lost suffix)
        # and re-point their pull loops at the new CHAIN tlogs (satellite
        # tlogs hold the same stream but serve recovery, not pulls).
        for s in self.storages:
            s.recover_to(recovery_version, chain_tlog_eps[0], chain_tlog_eps)

        # Retirement of the previous generation is DEFERRED: the
        # controller calls retire_previous() only after the registry
        # accepts the new generation. A deposed controller that already
        # recruited must leave the old roles alive — its rival's recovery
        # still needs to lock the old tlogs (killing them here was the
        # Chaos-campaign stall: an unpublished generation orphaned the
        # only locked log copies).
        # ACCUMULATE (not overwrite): after a deposed rival's unpublished
        # recruit, the winner's retire sweeps both the superseded
        # generation AND the rival's orphaned roles.
        self._pending_retirement = (
            getattr(self, "_pending_retirement", []) + list(self._gen_processes)
        )
        self._gen_processes = list(heartbeat_eps)

        return Generation(
            epoch=epoch,
            recovery_version=recovery_version,
            sequencer_ep=self.sequencer_ep,
            resolver_eps=self.resolver_eps,
            tlog_eps=self.tlog_eps,
            grv_proxy_eps=self.grv_proxy_eps,
            commit_proxy_eps=self.commit_proxy_eps,
            ratekeeper_ep=self.ratekeeper_ep,
            heartbeat_eps=heartbeat_eps,
        )

    # -- client-side routing helpers -----------------------------------------

    def storage_ep_for_key(self, key: bytes):
        return self.storage_eps[self.storage_map.tag_for_key(key)]

    def storage_eps_for_range(self, begin: bytes, end: bytes):
        from foundationdb_tpu.core.types import KeyRange

        return [
            (r, self.storage_eps[tag])
            for r, tag in self.storage_map.split_range(KeyRange(begin, end))
        ]
