"""Nemesis campaigns: TOML-declared cross-subsystem fault schedules with
exact-oracle acceptance gates.

A campaign composes live workloads (the same registry TOML test specs
use) with scheduled nemesis actions (sim/nemesis.py) on one seeded
deterministic loop, then gates the run on EXACT checks — workload
invariants (cycle permutation, conservation sums), byte parity
(consistency checker, DR switchover parity), admission bounds (tag
quotas), and bounded lane latency — never on "it didn't crash". A
failing (campaign, seed) pair replays bit-identically.

Spec shape (tests/specs/campaigns/*.toml):

    [[campaign]]
    title = 'ConsistencyVsMovement'
    budget = 600.0            # virtual-seconds cap (deterministic)

    [campaign.cluster]        # same keys as [test.cluster]
    storages = 3
    replicas = 2
    dataDistribution = true

    [[campaign.workload]]     # same registry as [[test.workload]]
    testName = 'Cycle'
    transactionCount = 30

    [[campaign.action]]       # nemesis.NEMESIS_REGISTRY
    name = 'DataMovementKick'
    at = 0.3
    every = 0.4
    count = 6
    begin = 'cycle/'
    end = 'cycle0'

    [campaign.checks]         # cross-cutting exact gates
    consistency = true
    movedRescansMin = 1

Run one: ``python -m foundationdb_tpu.sim.run <file> --seeds 1
--seed-base SEED``; the fast battery: ``python -m foundationdb_tpu.sim.run
--campaigns fast``.
"""

from __future__ import annotations

import traceback
from dataclasses import dataclass, field

try:
    import tomllib
except ModuleNotFoundError:  # python 3.10: API-compatible backport
    import tomli as tomllib

from foundationdb_tpu.runtime.flow import all_of
from foundationdb_tpu.sim.nemesis import (
    NEMESIS_REGISTRY,
    CampaignCheckFailed,
    NemesisContext,
)
from foundationdb_tpu.sim.specs import (
    WORKLOAD_REGISTRY,
    cluster_kwargs_from_table,
)

DEFAULT_BUDGET_S = 600.0  # virtual seconds — deterministic per-spec cap


@dataclass
class CampaignSpec:
    title: str
    workloads: list
    actions: list  # instantiated Nemesis objects
    cluster_opts: dict = field(default_factory=dict)
    checks: dict = field(default_factory=dict)
    dr: bool = False
    dr_opts: dict = field(default_factory=dict)
    buggify: bool = False
    budget_s: float = DEFAULT_BUDGET_S


def load_campaigns(source: str | bytes) -> list[CampaignSpec]:
    """Parse TOML text (or a path ending in .toml) into CampaignSpecs."""
    if isinstance(source, str) and source.endswith(".toml"):
        with open(source, "rb") as f:
            doc = tomllib.load(f)
    else:
        text = source.decode() if isinstance(source, bytes) else source
        doc = tomllib.loads(text)
    specs: list[CampaignSpec] = []
    for camp in doc.get("campaign", []):
        workloads = []
        for i, w in enumerate(camp.get("workload", [])):
            name = w["testName"]
            if name not in WORKLOAD_REGISTRY:
                raise ValueError(f"unknown workload testName {name!r}")
            cls, mapping = WORKLOAD_REGISTRY[name]
            # Strict keys (matching run_checks): a typo'd schedule knob
            # silently dropped would let the campaign pass while not
            # testing the composition it exists for.
            unknown = set(w) - set(mapping) - {"testName", "seed"}
            if unknown:
                raise ValueError(
                    f"unknown keys {sorted(unknown)} in workload {name!r} "
                    f"(known: {sorted(mapping)})")
            kwargs = {mapping[k]: v for k, v in w.items() if k in mapping}
            kwargs["seed"] = w.get("seed", camp.get("seed", i))
            workloads.append(cls(**kwargs))
        actions = []
        for a in camp.get("action", []):
            name = a["name"]
            if name not in NEMESIS_REGISTRY:
                raise ValueError(f"unknown nemesis action {name!r}")
            cls, mapping = NEMESIS_REGISTRY[name]
            unknown = set(a) - set(mapping) - {"name"}
            if unknown:
                raise ValueError(
                    f"unknown keys {sorted(unknown)} in action {name!r} "
                    f"(known: {sorted(mapping)})")
            kwargs = {mapping[k]: v for k, v in a.items() if k in mapping}
            actions.append(cls(**kwargs))
        specs.append(CampaignSpec(
            title=camp.get("title", "untitled"),
            workloads=workloads,
            actions=actions,
            cluster_opts=cluster_kwargs_from_table(camp.get("cluster", {})),
            checks=camp.get("checks", {}),
            dr=camp.get("dr", False),
            dr_opts=cluster_kwargs_from_table(camp.get("drCluster", {})),
            buggify=camp.get("buggify", False),
            budget_s=camp.get("budget", DEFAULT_BUDGET_S),
        ))
    if not specs:
        raise ValueError("no [[campaign]] blocks in spec")
    return specs


# -- cross-cutting checks -----------------------------------------------------


def _p99(samples: list[float]) -> float:
    if not samples:
        return float("inf")
    s = sorted(samples)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


async def _final_consistency(ctx: NemesisContext) -> dict:
    from foundationdb_tpu.consistency.checker import ConsistencyChecker

    report = await ConsistencyChecker(ctx.cluster, ctx.db).run()
    ctx.reports.append(report)
    detail = {k: report[k] for k in
              ("status", "shards_checked", "bytes_compared", "moved_rescans",
               "resnapshots")}
    if report["status"] != "consistent":
        raise CampaignCheckFailed(
            f"final audit {report['status']}: "
            f"divergences={report['divergences'][:2]!r} "
            f"unreachable={report['unreachable'][:2]!r}")
    return detail


async def run_checks(spec: CampaignSpec, ctx: NemesisContext) -> dict:
    """Evaluate [campaign.checks]; returns {check: detail}, raising
    CampaignCheckFailed on the first violated gate."""
    out: dict = {}
    checks = dict(spec.checks)
    if checks.pop("consistency", False):
        out["consistency"] = await _final_consistency(ctx)
    moved = sum(r["moved_rescans"] for r in ctx.reports)
    n = checks.pop("movedRescansMin", None)
    if n is not None:
        out["moved_rescans"] = moved
        if moved < n:
            raise CampaignCheckFailed(
                f"audits reported {moved} moved_rescans < required {n} — "
                "the movement race never happened")
    n = checks.pop("movesMin", None)
    if n is not None:
        dd = getattr(ctx.cluster, "data_distributor", None)
        moves = dd.moves if dd else 0
        out["moves"] = moves
        if moves < n:
            raise CampaignCheckFailed(f"{moves} shard moves < required {n}")
    for key, lane in (("systemP99Ms", "system"), ("defaultP99Ms", "default")):
        bound = checks.pop(key, None)
        if bound is None:
            continue
        lat = ctx.latencies.get(lane, [])
        p99_ms = _p99(lat) * 1e3
        out[key] = {"p99_ms": round(p99_ms, 1), "samples": len(lat)}
        if p99_ms > bound:
            raise CampaignCheckFailed(
                f"{lane}-lane p99 {p99_ms:.0f}ms > bound {bound}ms "
                f"({len(lat)} probes)")
    for key, counter in (("ackedMin", "acked"), ("probesMin", "probes"),
                         ("killsMin", "kills"), ("clogsMin", "clogs"),
                         ("auditsMin", "audits")):
        n = checks.pop(key, None)
        if n is None:
            continue
        got = ctx.counters.get(counter, 0)
        out[counter] = got
        if got < n:
            raise CampaignCheckFailed(
                f"counter {counter}={got} < required {n} — the composition "
                "this campaign exists for never happened")
    # Admission-subsystem exact gates (admission subsystem): counters read
    # off the CURRENT generation's commit-proxy policies — campaigns using
    # them must not kill proxies (per-generation counters, like every
    # other role counter).
    def _adm_totals() -> dict:
        totals: dict = {}
        for p in getattr(ctx.cluster, "commit_proxies", []):
            pol = getattr(p, "admission", None)
            if pol is None:
                continue
            for k, v in pol.counters.items():
                totals[k] = totals.get(k, 0) + v
        return totals

    for key, counter in (("admissionShapedMin", "shaped"),
                         ("admissionPreabortedMin", "preaborted"),
                         ("admissionProbesMin", "probes")):
        n = checks.pop(key, None)
        if n is None:
            continue
        got = _adm_totals().get(counter, 0)
        out[f"admission_{counter}"] = got
        if got < n:
            raise CampaignCheckFailed(
                f"admission counter {counter}={got} < required {n} — the "
                "admission composition this campaign exists for never "
                "happened")
    if checks.pop("admissionSystemZeroShaped", False):
        t = _adm_totals()
        out["admission_system"] = {
            "bypass": t.get("system_bypass", 0),
            "shaped": t.get("system_shaped", 0),
        }
        if t.get("system_bypass", 0) <= 0:
            raise CampaignCheckFailed(
                "no system-priority txn ever reached admission — the "
                "zero-shaping gate is vacuous")
        if t.get("system_shaped", 0):
            raise CampaignCheckFailed(
                f"system-priority txns were shaped: {t}")
    # Commit-path tracing gates (obs subsystem): under this campaign's
    # faults, every sampled COMMITTED txn must still yield a complete
    # span tree satisfying e2e == sum(stages) + unattributed — kills,
    # clogs and recoveries must degrade tracing to "txn not sampled",
    # never to a half-stamped tree that misattributes latency.
    if checks.pop("obsSpanTreesComplete", False):
        from foundationdb_tpu.obs.span import check_txn_tree

        sink = getattr(ctx.loop, "span_sink", None)
        if sink is None:
            raise CampaignCheckFailed(
                "obsSpanTreesComplete needs [campaign.cluster] obs = true")
        trees = bad = 0
        for tid in sink.sampled_tids(complete_only=True):
            spans = sink.spans_for(tid)
            if not any(s["name"] == "e2e" for s in spans):
                continue  # sampled but never committed (aborted/killed)
            trees += 1
            problems = check_txn_tree(spans)
            if problems:
                bad += 1
                if bad == 1:
                    first = f"tid {tid:#x}: {problems[:2]}"
        out["obs_span_trees"] = {"complete": trees - bad, "broken": bad,
                                 "sampled": sink.txns_sampled}
        if bad:
            raise CampaignCheckFailed(
                f"{bad}/{trees} sampled span trees broken under faults — "
                f"first: {first}")
    n = checks.pop("obsSampledMin", None)
    if n is not None:
        sink = getattr(ctx.loop, "span_sink", None)
        got = sink.txns_sampled if sink is not None else 0
        out["obs_sampled"] = got
        if got < n:
            raise CampaignCheckFailed(
                f"only {got} txns sampled < required {n} — the tracing "
                "composition this campaign gates never happened")
    # Wave-commit gates (ISSUE 13): counters read off the CURRENT
    # generation's resolvers — after a ResolverKill-driven recovery these
    # are the POST-RECOVERY shards, so crossing the minimums proves the
    # re-formed chain kept exchanging and reordering. Under the global
    # protocol every shard's schedule-derived counters must also AGREE
    # (byte-identical schedules), gated unconditionally whenever a wave
    # minimum is requested on a multi-resolver cluster.
    wave_keys = (("waveReorderedMin", "txns_reordered"),
                 ("waveCycleAbortedMin", "txns_cycle_aborted"),
                 ("waveBatchesMin", "wave_batches"))
    if any(k in checks for k, _ in wave_keys):
        resolvers = list(getattr(ctx.cluster, "resolvers", []))
        shard_counts = [
            {attr: getattr(r, attr) for _k, attr in wave_keys}
            for r in resolvers
        ]
        out["wave_per_shard"] = shard_counts
        # Counter identity only holds on fail-safe-free runs: a shard-
        # local capacity fail-safe during apply skips that shard's
        # counters for the (wholesale-rejected) window by design.
        fail_safed = any(
            getattr(r, "txns_rejected_fail_safe", 0) for r in resolvers
        )
        if fail_safed:
            out["wave_counter_identity"] = "skipped: fail-safe engaged"
        elif len(shard_counts) > 1 and any(
            s != shard_counts[0] for s in shard_counts[1:]
        ):
            raise CampaignCheckFailed(
                f"per-shard wave counters diverge (schedules were not "
                f"byte-identical): {shard_counts}"
            )
        for key, attr in wave_keys:
            n = checks.pop(key, None)
            if n is None:
                continue
            got = shard_counts[0][attr] if shard_counts else 0
            out[attr] = got
            if got < n:
                raise CampaignCheckFailed(
                    f"{attr}={got} < required {n} — the wave composition "
                    "this campaign gates never happened (post-recovery)"
                )
    n = checks.pop("repairRoundsMin", None)
    if n is not None:
        rounds = sum(
            (w.metrics.extra.get("repair") or {}).get("repair_rounds", 0)
            for w in spec.workloads
        )
        out["repair_rounds"] = rounds
        if rounds < n:
            raise CampaignCheckFailed(
                f"{rounds} repair rounds < required {n} — the faults never "
                "raced an in-flight repair")
    if checks:
        raise ValueError(f"unknown campaign checks: {sorted(checks)}")
    return out


# -- the runner ---------------------------------------------------------------


async def _quiesce(ctx: NemesisContext) -> None:
    """Heal every injected fault, let recovery settle, and wait for live
    storages to apply through the last committed version so the final
    byte-parity audit sees the true end state."""
    for cluster in (ctx.cluster, ctx.extra.get("dst_cluster")):
        if cluster is None:
            continue
        cluster.net.reset_faults()
        while cluster.controller._recovering:
            await ctx.loop.sleep(0.25)
        target = await cluster.sequencer.get_live_committed_version()
        deadline = ctx.loop.now + 60
        dead = cluster.loop.dead_processes
        live = [
            s for i, s in enumerate(cluster.storages)
            if (cluster.process_prefix + cluster.storage_procs()[i])
            not in dead
        ]
        while (any(s._version < target for s in live)
               and ctx.loop.now < deadline):
            await ctx.loop.sleep(0.05)


async def run_campaign_test(spec: CampaignSpec, cluster, db) -> dict:
    """setup workloads → (workloads ∥ scheduled nemeses) → heal+quiesce →
    exact gates. Returns a JSON-able result; ``ok`` is the verdict."""
    loop = cluster.loop
    t0 = loop.now
    ctx = NemesisContext(cluster=cluster, db=db)
    cluster.nemesis_ctx = ctx
    result: dict = {"title": spec.title, "failures": [], "checks": {}}
    if spec.buggify:
        loop.buggify_enabled = True
    if spec.dr:
        from foundationdb_tpu.client.ryw import open_database
        from foundationdb_tpu.runtime.dr import DRAgent
        from foundationdb_tpu.sim.cluster import SimCluster

        dst_opts = {"n_tlogs": 1, "n_storages": 2, **spec.dr_opts}
        dst_cluster = SimCluster(loop=loop, seed=loop.rng.randrange(1 << 30),
                                 process_prefix="dr.", **dst_opts)
        dst_db = open_database(dst_cluster)
        agent = DRAgent(cluster, db, dst_db)
        await agent.start()
        ctx.extra.update(dr_agent=agent, dst_db=dst_db,
                         dst_cluster=dst_cluster)

    for w in spec.workloads:
        await w.setup(db)
    action_tasks = [
        loop.spawn(a.run(ctx), name=f"nemesis.{a.name}") for a in spec.actions
    ]
    try:
        await all_of([
            loop.spawn(w.run(db, cluster), name=f"campaign.{w.name}")
            for w in spec.workloads
        ])
    finally:
        ctx.stopped = True
    for a, t in zip(spec.actions, action_tasks):
        try:
            await t
        except Exception:
            result["failures"].append({
                "check": f"action:{a.name}",
                "error": traceback.format_exc(limit=6),
            })
    await _quiesce(ctx)

    async def gate(name, coro):
        try:
            detail = await coro
            if detail is not None:
                result["checks"][name] = detail
        except Exception:
            result["failures"].append({
                "check": name, "error": traceback.format_exc(limit=6),
            })

    for w in spec.workloads:
        await gate(f"workload:{w.name}", w.check(db))
        result.setdefault("workloads", {})[w.name] = {
            "txns_committed": w.metrics.txns_committed,
            "txns_retried": w.metrics.txns_retried,
            "ops": w.metrics.ops,
            **({"extra": w.metrics.extra} if w.metrics.extra else {}),
        }
    for a in spec.actions:
        await gate(f"verify:{a.name}", a.verify(ctx, db))

    checks_detail = {}
    try:
        checks_detail = await run_checks(spec, ctx)
    except Exception:
        result["failures"].append({
            "check": "campaign.checks", "error": traceback.format_exc(limit=6),
        })
    result["checks"].update(checks_detail)
    if ctx.defects:
        result["failures"].append({"check": "live_defects",
                                   "error": "\n".join(ctx.defects)})
    result["counters"] = dict(ctx.counters)
    if ctx.reports:
        # Audit telemetry is always reported (the ROADMAP item's
        # moved_rescans contract), gated or not.
        result["audits"] = {
            "runs": len(ctx.reports),
            "moved_rescans": sum(r["moved_rescans"] for r in ctx.reports),
            "resnapshots": sum(r["resnapshots"] for r in ctx.reports),
            "statuses": [r["status"] for r in ctx.reports],
        }
    result["events"] = len(ctx.events)
    result["elapsed_virtual_s"] = round(loop.now - t0, 2)
    result["ok"] = not result["failures"]
    return result


def run_campaign(source: str | bytes, seed: int = 0) -> list[dict]:
    """Run every [[campaign]] in the spec, each on a fresh seeded cluster.
    The budget is a VIRTUAL-time cap — deterministic, so a budget blowout
    fails identically on replay."""
    from foundationdb_tpu.client.ryw import open_database
    from foundationdb_tpu.runtime.flow import Loop
    from foundationdb_tpu.sim.cluster import SimCluster

    out = []
    for i, spec in enumerate(load_campaigns(source)):
        loop = Loop(seed=seed)
        cluster = SimCluster(loop=loop, seed=seed,
                             **{"n_tlogs": 2, "n_storages": 2,
                                **spec.cluster_opts})
        db = open_database(cluster)
        result = loop.run(run_campaign_test(spec, cluster, db),
                          timeout=spec.budget_s)
        result["seed"] = seed
        out.append(result)
    return out
