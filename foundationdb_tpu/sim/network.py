"""Simulated RPC network with fault injection.

The reference's fdbrpc/FlowTransport + flow/sim2 pair: endpoints route
requests between named processes, every hop takes seeded-random virtual
latency, and the harness can kill processes or partition pairs at any point.
A request whose destination is dead or unreachable fails the caller with
BrokenPromise after the failure-detection delay — the same observable
behavior as the reference's broken_promise on connection failure
(fdbrpc/FlowTransport.actor.cpp), which is what drives client retry loops
and recovery.

All randomness comes from the loop's seeded RNG: identical seeds replay
identical histories, including message interleavings and failures.
"""

from __future__ import annotations

from typing import Any

from foundationdb_tpu.runtime.flow import BrokenPromise, Future, Loop, Promise


class Endpoint:
    """Callable proxy to a role hosted on some process.

    ``await ep.method(args)`` issues an RPC through the simulated network;
    attribute access returns a stub, so role interfaces read like the
    reference's RequestStream fields."""

    def __init__(self, net: "SimNetwork", process: str, name: str):
        self._net = net
        self.process = process
        self.name = name

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        return lambda *a, **kw: self._net.call(self, method, a, kw)

    def __repr__(self) -> str:
        return f"<Endpoint {self.name}@{self.process}>"


class SimNetwork:
    FAILURE_DETECTION_DELAY = 1.0  # virtual seconds until a lost RPC breaks

    def __init__(
        self,
        loop: Loop,
        min_latency: float = 0.0002,
        max_latency: float = 0.002,
        process_prefix: str = "",
    ):
        self.loop = loop
        self.min_latency = min_latency
        self.max_latency = max_latency
        # Process-name namespace: kills/partitions act on loop-global
        # process names, so two clusters sharing one Loop (DR pairs) must
        # not both own a "tlog0". The prefix is applied at host()/kill()
        # so per-cluster call sites keep using bare names.
        self.process_prefix = process_prefix
        self._objects: dict[str, Any] = {}  # endpoint name -> role object
        self._partitions: set[frozenset] = set()
        # Dead REGIONS (reference: multi-region FDB models datacenter
        # loss, fdbserver/DataDistribution.actor.cpp region teams). A
        # region here is a process-name prefix ("pri/", "sat/", "rem/");
        # failing one kills every process under it AND isolates the
        # prefix: later-hosted processes there are unreachable too, so a
        # recovery that recruited into a dead region simply stalls and
        # retries elsewhere.
        self._dead_regions: set[str] = set()
        # Partitioned regions: alive but severed at the boundary (the
        # zombie-generation mode — see partition_region()).
        self._partitioned_regions: set[str] = set()
        # Clogs: slow-but-alive links (reference: sim2's clogging — the
        # failure mode BETWEEN healthy and partitioned that shakes out
        # timeout/ordering assumptions). pair -> (latency multiplier,
        # virtual-time expiry).
        self._clogs: dict[frozenset, tuple[float, float]] = {}

    # -- topology -------------------------------------------------------------

    def host(self, process: str, name: str, obj: Any) -> Endpoint:
        """Register a role object as `name` on `process`; returns its endpoint."""
        process = self.process_prefix + process
        self._objects[(process, name)] = obj
        return Endpoint(self, process, name)

    def kill(self, process: str) -> None:
        self.loop.kill_process(self.process_prefix + process)

    def unhost_process(self, process: str) -> None:
        """Drop every role object hosted on `process` (generation retirement
        — without this, each recovery would leak the full old generation,
        including never-trimmed replica tlogs holding an epoch's history)."""
        process = self.process_prefix + process
        self._objects = {k: v for k, v in self._objects.items() if k[0] != process}

    def reboot(self, process: str) -> None:
        """Clears the dead flag; the harness re-hosts/restarts role actors."""
        self.loop.revive_process(self.process_prefix + process)

    def fail_region(self, prefix: str) -> None:
        """Datacenter loss: kill every live process under `prefix` and
        black-hole the prefix for anything hosted there later."""
        p = self.process_prefix + prefix
        self._dead_regions.add(p)
        for proc in {k[0] for k in self._objects}:
            if proc.startswith(p):
                self.loop.kill_process(proc)

    def heal_region(self, prefix: str) -> None:
        self._dead_regions.discard(self.process_prefix + prefix)
        for proc in {k[0] for k in self._objects}:
            if proc.startswith(self.process_prefix + prefix):
                self.loop.revive_process(proc)

    def region_dead(self, prefix: str) -> bool:
        return (self.process_prefix + prefix) in self._dead_regions

    def _in_dead_region(self, process: str) -> bool:
        return any(process.startswith(r) for r in self._dead_regions)

    def partition_region(self, prefix: str) -> None:
        """The HARD region-failure mode (vs fail_region's blackout):
        every process under `prefix` stays ALIVE with its intra-region
        links intact, but nothing crosses the region boundary in either
        direction. The region's chain keeps running as a ZOMBIE
        generation — proxies keep pushing to in-region tlogs while the
        out-of-region satellite fences every ack — which is exactly the
        scenario the known-committed/epoch fences exist for
        (tests/test_deployed_multiregion.py TestRegionPartition; sim
        twin in tests/test_multi_region.py)."""
        self._partitioned_regions.add(self.process_prefix + prefix)

    def heal_region_partition(self, prefix: str) -> None:
        self._partitioned_regions.discard(self.process_prefix + prefix)

    def region_partitioned(self, prefix: str) -> bool:
        return (self.process_prefix + prefix) in self._partitioned_regions

    def _crosses_partitioned_region(self, src: str, dst: str) -> bool:
        for r in self._partitioned_regions:
            if src.startswith(r) != dst.startswith(r):
                return True
        return False

    def partition(self, a: str, b: str) -> None:
        self._partitions.add(frozenset(
            (self.process_prefix + a, self.process_prefix + b)))

    def heal(self, a: str, b: str) -> None:
        self._partitions.discard(frozenset(
            (self.process_prefix + a, self.process_prefix + b)))

    def heal_all(self) -> None:
        """Clear every link-level fault: pair partitions, clogs, AND
        region partitions (campaign-found: the quiesce path called this
        expecting a clean network, but a region partition injected by a
        nemesis survived it and the post-storm checks ran against a
        still-severed region). Dead regions are NOT cleared — their
        processes are dead and need the heal_region reboot path."""
        self._partitions.clear()
        self._clogs.clear()
        self._partitioned_regions.clear()

    def reset_faults(self) -> None:
        """Explicit full network-fault reset (alias of heal_all, the
        campaign runner's quiesce contract)."""
        self.heal_all()

    def clog(self, a: str, b: str, factor: float = 50.0,
             duration: float = 1.0) -> None:
        """Inflate latency on the a↔b link by `factor` for `duration`
        virtual seconds. The link stays ALIVE: RPCs arrive late rather
        than failing, so no failure detector trips — the hard case."""
        self._clogs[frozenset(
            (self.process_prefix + a, self.process_prefix + b)
        )] = (factor, self.loop.now + duration)

    def unclog(self, a: str, b: str) -> None:
        self._clogs.pop(frozenset(
            (self.process_prefix + a, self.process_prefix + b)), None)

    def _unreachable(self, src: str, dst: str) -> bool:
        return (
            dst in self.loop.dead_processes
            or (src != dst and frozenset((src, dst)) in self._partitions)
            or (self._dead_regions
                and (self._in_dead_region(dst) or self._in_dead_region(src)))
            or (self._partitioned_regions
                and self._crosses_partitioned_region(src, dst))
        )

    def _latency(self, src: str | None = None, dst: str | None = None) -> float:
        base = self.loop.rng.uniform(self.min_latency, self.max_latency)
        if src is None or not self._clogs:
            return base
        entry = self._clogs.get(frozenset((src, dst)))
        if entry is None:
            return base
        factor, until = entry
        if self.loop.now >= until:
            del self._clogs[frozenset((src, dst))]
            return base
        return base * factor

    # -- RPC ------------------------------------------------------------------

    def call(self, ep: Endpoint, method: str, args: tuple, kwargs: dict) -> Future:
        loop = self.loop
        src = loop._current.process if loop._current else "<main>"
        reply = Promise()

        def fail_later(_f=None) -> None:
            loop.sleep(self.FAILURE_DETECTION_DELAY).add_done_callback(
                lambda _: reply.fail(
                    BrokenPromise(f"{ep.name}.{method} unreachable from {src}")
                )
            )

        def deliver(_f) -> None:
            if self._unreachable(src, ep.process):
                fail_later()
                return
            obj = self._objects.get((ep.process, ep.name))
            if obj is None:
                fail_later()
                return
            try:
                coro = getattr(obj, method)(*args, **kwargs)
            except Exception as e:  # bad method/signature fails this RPC only
                reply.fail(e)
                return
            task = loop.spawn(coro, process=ep.process, name=f"{ep.name}.{method}")
            task.add_done_callback(send_reply)

        def send_reply(task) -> None:
            err = task.exception()

            def finish(_f) -> None:
                # The requesting side may itself be dead/partitioned by now;
                # a reply into a partition is simply lost.
                if self._unreachable(ep.process, src):
                    fail_later()
                elif err is not None:
                    reply.fail(err)
                else:
                    reply.send(task.result())

            loop.sleep(self._latency(ep.process, src)).add_done_callback(finish)

        loop.sleep(self._latency(src, ep.process)).add_done_callback(deliver)
        return reply.future
