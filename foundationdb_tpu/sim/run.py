"""Simulation campaign runner: every TOML spec × N seeds, one command.

Reference: the TestHarness/Joshua loop around `fdbserver -r simulation` —
run a spec under many seeds, report the failing (spec, seed) pairs with
an exact replay command (same seed → same trace, including the fault,
clog, and nemesis schedules).

Two spec kinds share the loop:

- ``[[test]]`` specs (tests/specs/*.toml): workloads + optional fault
  injector, run via sim/specs.py.
- ``[[campaign]]`` specs (tests/specs/campaigns/*.toml): workloads ∥
  scheduled nemesis actions with exact-oracle gates, run via
  sim/campaigns.py. Campaign runs additionally write a per-(spec, seed)
  JSON result artifact under --artifacts (default CAMPAIGN_RESULTS/,
  gitignored) — the full gate/counter/audit record for forensics.

    python -m foundationdb_tpu.sim.run tests/specs --seeds 50
    python -m foundationdb_tpu.sim.run tests/specs/campaigns --seeds 20
    python -m foundationdb_tpu.sim.run tests/specs/Cycle.toml \
        --seeds 1 --seed-base 1234 --buggify --clog 0.7   # replay one
    python -m foundationdb_tpu.sim.run --campaigns fast   # CI stage:
        # fast campaign battery, ONE summary JSON line last on stdout,
        # exit 0 iff all green (tpuwatch/heal-window contract)

Each (spec-file, seed) runs in a fresh process (seeds fan out over
--jobs workers); --buggify arms the in-role BUGGIFY sites, --clog adds
slow-but-alive link injection on top of whatever the spec asks for, and
--fail-fast stops the fleet at the first failure (CI).
"""

from __future__ import annotations

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")  # campaign never needs a TPU

import argparse
import json
import sys
import traceback
from concurrent.futures import FIRST_COMPLETED, ProcessPoolExecutor, wait

try:
    import tomllib
except ModuleNotFoundError:  # python 3.10: API-compatible backport
    import tomli as tomllib

CAMPAIGN_SPEC_DIR = os.path.join("tests", "specs", "campaigns")
DEFAULT_ARTIFACT_DIR = "CAMPAIGN_RESULTS"  # gitignored (CAMPAIGN_*)
FAST_SEEDS = 3  # --campaigns fast: seeds per spec in the CI battery


def is_campaign_spec(path: str) -> bool:
    """True iff the TOML holds [[campaign]] blocks (vs [[test]])."""
    with open(path, "rb") as f:
        return bool(tomllib.load(f).get("campaign"))


def run_one(spec_path: str, seed: int, buggify: bool,
            clog: float | None,
            aggressive: bool = False,
            ) -> tuple[str, int, list[tuple[str, bool, str, dict | None]],
                       bool]:
    """Run every [[test]] / [[campaign]] of one spec file at one seed in
    THIS process. Returns (spec_path, seed, [(title, ok, detail,
    result_json_or_None), ...], is_campaign) — the dict is the campaign
    result record the parent writes as the per-seed artifact; the flag
    rides along so the parent never has to re-parse (a malformed spec
    must fail in the worker, not crash the reporting loop)."""
    if is_campaign_spec(spec_path):
        return _run_one_campaign(spec_path, seed)

    from foundationdb_tpu.client.ryw import open_database
    from foundationdb_tpu.sim.cluster import SimCluster
    from foundationdb_tpu.sim.specs import (
        cluster_kwargs, load_spec, run_spec_test,
    )

    out: list[tuple[str, bool, str, dict | None]] = []
    for spec in load_spec(spec_path):
        if buggify:
            spec.buggify = True
        if aggressive:
            spec.buggify = True
            spec.buggify_aggressive = True
        if clog is not None and spec.clog_interval is None:
            spec.clog_interval = clog
        c = SimCluster(seed=seed, **cluster_kwargs(spec))
        db = open_database(c)
        try:
            r = c.loop.run(run_spec_test(spec, c, db), timeout=3000)
            detail = ", ".join(
                f"{name}={m.txns_committed}tx" for name, m in r.metrics.items()
            )
            if r.kills:
                detail += f" kills={r.kills}"
            out.append((spec.title, True, detail, None))
        except Exception:
            out.append((spec.title, False, traceback.format_exc(limit=8), None))
    return spec_path, seed, out, False


def _run_one_campaign(spec_path: str, seed: int,
                      ) -> tuple[str, int, list[tuple[str, bool, str, dict]],
                                 bool]:
    from foundationdb_tpu.sim.campaigns import run_campaign

    out: list[tuple[str, bool, str, dict]] = []
    try:
        results = run_campaign(spec_path, seed=seed)
    except Exception:
        # Spec-level blowup (parse error, budget timeout escaping the
        # runner): every campaign of the file is charged.
        err = traceback.format_exc(limit=8)
        return spec_path, seed, [("<campaign>", False, err,
                                  {"ok": False, "seed": seed, "error": err})
                                 ], True
    for r in results:
        if r["ok"]:
            counters = r.get("counters", {})
            detail = (f"acked={counters.get('acked', 0)} "
                      f"checks={sorted(r.get('checks', {}))} "
                      f"t={r.get('elapsed_virtual_s')}s")
            out.append((r["title"], True, detail, r))
        else:
            detail = "\n".join(
                f"[{f['check']}] {f['error'].strip().splitlines()[-1]}"
                for f in r["failures"])
            out.append((r["title"], False, detail, r))
    return spec_path, seed, out, True


def write_artifact(art_dir: str, spec_path: str, seed: int,
                   results: list[tuple[str, bool, str, dict | None]]) -> str:
    """One JSON file per (campaign spec, seed): the full result records."""
    os.makedirs(art_dir, exist_ok=True)
    stem = os.path.splitext(os.path.basename(spec_path))[0]
    path = os.path.join(art_dir, f"{stem}.seed{seed}.json")
    with open(path, "w") as f:
        json.dump({
            "spec": spec_path,
            "seed": seed,
            "ok": all(ok for _t, ok, _d, _r in results),
            "campaigns": [r for _t, _ok, _d, r in results if r is not None],
            "replay": replay_line(spec_path, seed),
        }, f, indent=1, default=str)
    return path


def replay_line(spec_path: str, seed: int, buggify: bool = False,
                aggressive: bool = False, clog: float | None = None) -> str:
    """The fully-reproducing one-liner: the seed IS the entire schedule
    (workload interleaving, fault timing, nemesis draws), so spec+seed+
    flags replay the failure bit-identically."""
    flags = ""
    if buggify:
        flags += " --buggify"
    if aggressive:
        flags += " --buggify-aggressive"
    if clog is not None:
        flags += f" --clog {clog}"
    return (f"python -m foundationdb_tpu.sim.run {spec_path} "
            f"--seeds 1 --seed-base {seed}{flags}")


def collect_specs(paths: list[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files += sorted(
                os.path.join(p, f) for f in os.listdir(p) if f.endswith(".toml")
            )
        else:
            files.append(p)
    if not files:
        raise SystemExit(f"no .toml specs under {paths}")
    return files


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m foundationdb_tpu.sim.run",
        description="Run every TOML spec × N seeds (TestHarness analogue).",
    )
    ap.add_argument("specs", nargs="*",
                    help="spec .toml files or directories ([[test]] or "
                         "[[campaign]] kind; may be mixed)")
    ap.add_argument("--campaigns", choices=("fast",), default=None,
                    help="CI battery preset: run tests/specs/campaigns at "
                         f"{FAST_SEEDS} seeds, print one summary JSON line "
                         "last (exit 0 iff all green)")
    ap.add_argument("--seeds", type=int, default=None,
                    help="seeds per spec (default 10; "
                         f"{FAST_SEEDS} under --campaigns fast)")
    ap.add_argument("--seed-base", type=int, default=0,
                    help="first seed (failing seeds replay with "
                         "--seeds 1 --seed-base SEED)")
    ap.add_argument("--buggify", action="store_true",
                    help="arm in-role BUGGIFY sites in every test")
    ap.add_argument("--buggify-aggressive", action="store_true",
                    help="every BUGGIFY site active, firing >= 50% "
                         "(maximum perturbation; implies --buggify)")
    ap.add_argument("--clog", type=float, default=None, metavar="INTERVAL",
                    help="add slow-link clogging at this mean interval (s)")
    ap.add_argument("--fail-fast", action="store_true",
                    help="stop the fleet at the first failing (spec, seed)")
    ap.add_argument("--artifacts", default=DEFAULT_ARTIFACT_DIR,
                    metavar="DIR",
                    help="per-(campaign, seed) JSON result directory "
                         f"(default {DEFAULT_ARTIFACT_DIR}/; '' disables)")
    ap.add_argument("--jobs", type=int, default=min(8, os.cpu_count() or 1))
    args = ap.parse_args(argv)

    if args.campaigns:
        if not args.specs:
            args.specs = [CAMPAIGN_SPEC_DIR]
        if args.seeds is None:
            args.seeds = FAST_SEEDS
    elif not args.specs:
        ap.error("specs required (or use --campaigns fast)")
    if args.seeds is None:
        args.seeds = 10

    files = collect_specs(args.specs)
    jobs = [(f, args.seed_base + s) for f in files for s in range(args.seeds)]
    print(f"campaign: {len(files)} specs x {args.seeds} seeds = "
          f"{len(jobs)} runs on {args.jobs} workers", flush=True)

    failures: list[tuple[str, int, str, str]] = []
    done = 0
    stopped_early = False
    with ProcessPoolExecutor(max_workers=args.jobs) as pool:
        futs = {
            pool.submit(run_one, f, seed, args.buggify, args.clog,
                        args.buggify_aggressive): (f, seed)
            for f, seed in jobs
        }
        pending = set(futs)
        while pending:
            finished, pending = wait(pending, return_when=FIRST_COMPLETED)
            for fut in finished:
                f, seed = futs[fut]
                done += 1
                try:
                    _, _, results, campaign = fut.result()
                except Exception as e:  # worker crash counts as failure
                    results = [("<worker>", False,
                                f"{type(e).__name__}: {e}", None)]
                    campaign = False  # kind unknowable: no artifact
                if args.artifacts and campaign:
                    write_artifact(args.artifacts, f, seed, results)
                for title, ok, detail, _r in results:
                    if ok:
                        print(f"[{done}/{len(jobs)}] ok   {f}:{title} "
                              f"seed={seed} {detail}", flush=True)
                    else:
                        failures.append((f, seed, title, detail))
                        print(f"[{done}/{len(jobs)}] FAIL {f}:{title} "
                              f"seed={seed}", flush=True)
            if failures and args.fail_fast and pending:
                stopped_early = True
                for fut in pending:
                    fut.cancel()
                pending = set()

    if failures:
        print(f"\n{len(failures)} FAILURES"
              + (" (--fail-fast: fleet stopped early)" if stopped_early
                 else "") + ":", flush=True)
        for f, seed, title, detail in failures:
            print(f"--- {f}:{title} seed={seed}\n{detail}\n"
                  f"replay: "
                  + replay_line(f, seed, args.buggify,
                                args.buggify_aggressive, args.clog),
                  flush=True)
    else:
        print("all green", flush=True)
    if args.campaigns:
        # ONE summary line, LAST on stdout — the tpuwatch `have` helper
        # judges the artifact by its final JSON line.
        print(json.dumps({
            "metric": "nemesis_campaigns",
            "mode": args.campaigns,
            "specs": len(files),
            "seeds": args.seeds,
            "runs": len(jobs),
            "completed": done,
            "ok": not failures,
            "failures": [
                {"spec": f, "seed": seed, "title": title,
                 "replay": replay_line(f, seed, args.buggify,
                                       args.buggify_aggressive, args.clog)}
                for f, seed, title, _detail in failures[:10]
            ],
        }), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
