"""Simulation campaign runner: every TOML spec × N seeds, one command.

Reference: the TestHarness/Joshua loop around `fdbserver -r simulation` —
run a spec under many seeds, report the failing (spec, seed) pairs with
an exact replay command (same seed → same trace, including the fault and
clog schedules).

    python -m foundationdb_tpu.sim.run tests/specs --seeds 50
    python -m foundationdb_tpu.sim.run tests/specs/Cycle.toml \
        --seeds 1 --seed-base 1234 --buggify --clog 0.7   # replay one

Each (spec-file, seed) runs in a fresh process (seeds fan out over
--jobs workers); --buggify arms the in-role BUGGIFY sites and --clog
adds slow-but-alive link injection on top of whatever the spec asks for.
"""

from __future__ import annotations

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")  # campaign never needs a TPU

import argparse
import sys
import traceback
from concurrent.futures import ProcessPoolExecutor, as_completed


def run_one(spec_path: str, seed: int, buggify: bool,
            clog: float | None,
            aggressive: bool = False,
            ) -> tuple[str, int, list[tuple[str, bool, str]]]:
    """Run every [[test]] of one spec file at one seed in THIS process.
    Returns (spec_path, seed, [(title, ok, detail), ...])."""
    from foundationdb_tpu.client.ryw import open_database
    from foundationdb_tpu.sim.cluster import SimCluster
    from foundationdb_tpu.sim.specs import (
        cluster_kwargs, load_spec, run_spec_test,
    )

    out: list[tuple[str, bool, str]] = []
    for spec in load_spec(spec_path):
        if buggify:
            spec.buggify = True
        if aggressive:
            spec.buggify = True
            spec.buggify_aggressive = True
        if clog is not None and spec.clog_interval is None:
            spec.clog_interval = clog
        c = SimCluster(seed=seed, **cluster_kwargs(spec))
        db = open_database(c)
        try:
            r = c.loop.run(run_spec_test(spec, c, db), timeout=3000)
            detail = ", ".join(
                f"{name}={m.txns_committed}tx" for name, m in r.metrics.items()
            )
            if r.kills:
                detail += f" kills={r.kills}"
            out.append((spec.title, True, detail))
        except Exception:
            out.append((spec.title, False, traceback.format_exc(limit=8)))
    return spec_path, seed, out


def collect_specs(paths: list[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files += sorted(
                os.path.join(p, f) for f in os.listdir(p) if f.endswith(".toml")
            )
        else:
            files.append(p)
    if not files:
        raise SystemExit(f"no .toml specs under {paths}")
    return files


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m foundationdb_tpu.sim.run",
        description="Run every TOML spec × N seeds (TestHarness analogue).",
    )
    ap.add_argument("specs", nargs="+", help="spec .toml files or directories")
    ap.add_argument("--seeds", type=int, default=10)
    ap.add_argument("--seed-base", type=int, default=0,
                    help="first seed (failing seeds replay with "
                         "--seeds 1 --seed-base SEED)")
    ap.add_argument("--buggify", action="store_true",
                    help="arm in-role BUGGIFY sites in every test")
    ap.add_argument("--buggify-aggressive", action="store_true",
                    help="every BUGGIFY site active, firing >= 50% "
                         "(maximum perturbation; implies --buggify)")
    ap.add_argument("--clog", type=float, default=None, metavar="INTERVAL",
                    help="add slow-link clogging at this mean interval (s)")
    ap.add_argument("--jobs", type=int, default=min(8, os.cpu_count() or 1))
    args = ap.parse_args(argv)

    files = collect_specs(args.specs)
    jobs = [(f, args.seed_base + s) for f in files for s in range(args.seeds)]
    print(f"campaign: {len(files)} specs x {args.seeds} seeds = "
          f"{len(jobs)} runs on {args.jobs} workers", flush=True)

    failures: list[tuple[str, int, str, str]] = []
    done = 0
    with ProcessPoolExecutor(max_workers=args.jobs) as pool:
        futs = {
            pool.submit(run_one, f, seed, args.buggify, args.clog,
                        args.buggify_aggressive): (f, seed)
            for f, seed in jobs
        }
        for fut in as_completed(futs):
            f, seed = futs[fut]
            done += 1
            try:
                _, _, results = fut.result()
            except Exception as e:  # worker crash counts as failure
                results = [("<worker>", False, f"{type(e).__name__}: {e}")]
            for title, ok, detail in results:
                if ok:
                    print(f"[{done}/{len(jobs)}] ok   {f}:{title} "
                          f"seed={seed} {detail}", flush=True)
                else:
                    failures.append((f, seed, title, detail))
                    print(f"[{done}/{len(jobs)}] FAIL {f}:{title} seed={seed}",
                          flush=True)

    if failures:
        print(f"\n{len(failures)} FAILURES:", flush=True)
        for f, seed, title, detail in failures:
            flags = " --buggify" if args.buggify else ""
            if args.buggify_aggressive:
                flags += " --buggify-aggressive"
            if args.clog is not None:
                flags += f" --clog {args.clog}"
            print(f"--- {f}:{title} seed={seed}\n{detail}\n"
                  f"replay: python -m foundationdb_tpu.sim.run {f} "
                  f"--seeds 1 --seed-base {seed}{flags}", flush=True)
        return 1
    print("all green", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
